"""Local SGD / periodic parameter averaging: H=1 plain-SGD equivalence with
exact DDP, divergence-then-sync mechanics, byte-exact wire accounting, and
end-to-end training at H=4."""

import jax
import jax.numpy as jnp
import numpy as np

from network_distributed_pytorch_tpu.parallel import (
    ExactReducer,
    make_local_sgd_train_fn,
    make_mesh,
)
from network_distributed_pytorch_tpu.parallel.trainer import (
    LOSS_SYNC_BITS,
    make_train_step,
    stateless_loss,
)

W = 8


def _problem():
    rng = np.random.RandomState(0)
    w_true = rng.randn(16, 4).astype(np.float32)
    x = rng.randn(64, 16).astype(np.float32)
    y = x @ w_true
    params = {"w": jnp.zeros((16, 4)), "b": jnp.zeros((4,))}

    def loss(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] + p["b"] - yb) ** 2)

    return params, stateless_loss(loss), (jnp.asarray(x), jnp.asarray(y))


def _stack(batch, h):
    return tuple(jnp.broadcast_to(b[None], (h,) + b.shape) for b in batch)


def test_h1_plain_sgd_equals_exact_ddp(devices):
    """sync_every=1 + plain SGD == exact-DDP plain SGD step-for-step
    (averaging post-step params == stepping with the averaged gradient)."""
    params, loss_fn, batch = _problem()
    mesh = make_mesh()
    local = make_local_sgd_train_fn(
        loss_fn, params, 0.05, sync_every=1, algorithm="sgd_plain",
        mesh=mesh, donate_state=False,
    )
    ddp = make_train_step(
        loss_fn, ExactReducer(), params, 0.05, algorithm="sgd_plain",
        mesh=mesh, donate_state=False,
    )
    lstate, dstate = local.init_state(params), ddp.init_state(params)
    for _ in range(10):
        lstate, llosses = local(lstate, _stack(batch, 1))
        dstate, dloss = ddp(dstate, batch)
        np.testing.assert_allclose(
            float(llosses[0]), float(dloss), rtol=1e-6
        )
    np.testing.assert_allclose(
        np.asarray(local.eval_params(lstate)["w"]),
        np.asarray(dstate.params["w"]),
        rtol=1e-5, atol=1e-7,
    )


def test_wire_accounting_hlo_exact(devices):
    """bits_per_round (one param allreduce + H loss pmeans) must equal the
    compiled round's collective payloads byte-exactly — and be ~H-fold less
    per step than exact DDP's gradient allreduce."""
    from network_distributed_pytorch_tpu.utils.hlo_audit import (
        collective_summary,
        compiled_hlo_text,
    )

    params, loss_fn, batch = _problem()
    mesh = make_mesh()
    h = 4
    local = make_local_sgd_train_fn(
        loss_fn, params, 0.05, sync_every=h, mesh=mesh, donate_state=False
    )
    state = local.init_state(params)
    s = collective_summary(compiled_hlo_text(local.fn, state, _stack(batch, h)))
    param_bits = 32 * sum(
        l.size for l in jax.tree_util.tree_leaves(params)
    )
    # the loss pmean lives in the lax.scan BODY: it appears once in the HLO
    # text but executes sync_every times, so the text-level audit sees
    # param_bits + ONE loss payload while the true per-round cost carries
    # sync_every of them (bits_per_round)
    assert 8 * s["total_payload_bytes"] == param_bits + LOSS_SYNC_BITS
    assert local.bits_per_round == param_bits + h * LOSS_SYNC_BITS
    assert local.bits_per_step < param_bits / (h - 1)


def test_local_sgd_trains_h4(devices):
    params, loss_fn, batch = _problem()
    mesh = make_mesh()
    local = make_local_sgd_train_fn(
        loss_fn, params, 0.05, sync_every=4, mesh=mesh, donate_state=False
    )
    state = local.init_state(params)
    losses = []
    for _ in range(10):  # 40 local steps, 10 syncs
        state, l = local(state, _stack(batch, 4))
        losses.extend(np.asarray(l).tolist())
    assert losses[-1] < 0.05 * losses[0], (losses[0], losses[-1])
