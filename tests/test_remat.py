"""Rematerialization (gradient checkpointing): remat=True recomputes block
activations in the backward pass — same parameter tree, same loss, same
gradients (bit-close), composing with the distributed EF-PowerSGD step."""

import jax
import jax.numpy as jnp
import numpy as np

from network_distributed_pytorch_tpu.models.distilbert import (
    DistilBertConfig,
    DistilBertEncoder,
)
from network_distributed_pytorch_tpu.models.gpt import GPTConfig, GPTLM
from network_distributed_pytorch_tpu.utils import cross_entropy_loss

_TINY = dict(
    vocab_size=64, max_position_embeddings=16, dim=16, n_layers=2,
    n_heads=2, hidden_dim=32, dropout=0.0,
)


def _gpt_loss(model):
    def loss(params, ids):
        logits = model.apply({"params": params}, ids)
        return cross_entropy_loss(
            logits[:, :-1].reshape(-1, logits.shape[-1]), ids[:, 1:].reshape(-1)
        )

    return loss


def test_gpt_remat_same_params_loss_grads():
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)))
    plain = GPTLM(GPTConfig(**_TINY))
    remat = GPTLM(GPTConfig(**_TINY, remat=True))
    params = plain.init(jax.random.PRNGKey(0), ids)["params"]
    assert jax.tree_util.tree_structure(
        remat.init(jax.random.PRNGKey(0), ids)["params"]
    ) == jax.tree_util.tree_structure(params)
    l0, g0 = jax.value_and_grad(_gpt_loss(plain))(params, ids)
    l1, g1 = jax.value_and_grad(_gpt_loss(remat))(params, ids)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)


def test_distilbert_remat_same_forward_grads():
    cfg = dict(
        vocab_size=64, max_position_embeddings=16, dim=16, n_layers=2,
        n_heads=2, hidden_dim=32, dropout=0.0, attention_dropout=0.0,
    )
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 64, (2, 16)))
    amask = jnp.ones_like(ids)
    plain = DistilBertEncoder(DistilBertConfig(**cfg))
    remat = DistilBertEncoder(DistilBertConfig(**cfg, remat=True))
    params = plain.init(jax.random.PRNGKey(0), ids, amask)["params"]

    def loss(m):
        return lambda p: jnp.mean(
            m.apply({"params": p}, ids, amask, deterministic=True) ** 2
        )

    l0, g0 = jax.value_and_grad(loss(plain))(params)
    l1, g1 = jax.value_and_grad(loss(remat))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)


def test_gpt_remat_trains_under_powersgd_dp(devices):
    """remat composes with the distributed EF step: identical training
    trajectory to the unrematted model on 8 devices."""
    from network_distributed_pytorch_tpu.parallel import (
        PowerSGDReducer,
        make_mesh,
    )
    from network_distributed_pytorch_tpu.parallel.trainer import (
        make_train_step,
        stateless_loss,
    )

    ids = jnp.asarray(np.random.RandomState(2).randint(0, 64, (16, 16)))
    mesh = make_mesh()
    states = {}
    for key, rm in (("plain", False), ("remat", True)):
        model = GPTLM(GPTConfig(**_TINY, remat=rm))
        params = model.init(jax.random.PRNGKey(0), ids[:1])["params"]
        step = make_train_step(
            stateless_loss(lambda p, b, m=model: _gpt_loss(m)(p, b)),
            PowerSGDReducer(random_seed=3, compression_rank=2, matricize="last"),
            params, 0.1, algorithm="ef_momentum", mesh=mesh, donate_state=False,
        )
        state = step.init_state(params)
        losses = []
        for _ in range(3):
            state, loss = step(state, ids)
            losses.append(float(loss))
        states[key] = (losses, state)
    np.testing.assert_allclose(states["plain"][0], states["remat"][0], rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(states["plain"][1].params["wte"]["embedding"]),
        np.asarray(states["remat"][1].params["wte"]["embedding"]),
        rtol=1e-5, atol=1e-7,
    )
