"""Gram-Schmidt kernel: orthonormality + exact recurrence parity with the
reference (``reducer.py:180-191``) via the NumPy oracle."""

import jax
import jax.numpy as jnp
import numpy as np

from network_distributed_pytorch_tpu.ops import orthogonalize
from oracle_powersgd import orthogonalize_np


def test_orthonormal_columns():
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 8))
    p = orthogonalize(x)
    gram = np.asarray(p.T @ p)
    np.testing.assert_allclose(gram, np.eye(8), atol=1e-5)


def test_matches_reference_recurrence():
    for shape in [(16, 4), (100, 1), (7, 7), (3, 2)]:
        x = np.asarray(
            jax.random.normal(jax.random.PRNGKey(shape[0]), shape), dtype=np.float32
        )
        ours = np.asarray(orthogonalize(jnp.asarray(x)))
        oracle = orthogonalize_np(x)
        np.testing.assert_allclose(ours, oracle, rtol=1e-5, atol=1e-6)


def test_under_jit():
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 4))
    np.testing.assert_allclose(
        np.asarray(jax.jit(orthogonalize)(x)), np.asarray(orthogonalize(x)), rtol=1e-6
    )


def test_near_zero_column_stable():
    # eps in the denominator keeps a zero column finite (reducer.py:186)
    x = jnp.zeros((10, 3)).at[:, 0].set(1.0)
    p = orthogonalize(x)
    assert bool(jnp.all(jnp.isfinite(p)))
