"""Top-k / sign / int8 compressors: NumPy oracles on the single-process path,
real 8-device gather path, EF-chain training, and bits accounting."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from network_distributed_pytorch_tpu.parallel import DATA_AXIS, make_mesh
from network_distributed_pytorch_tpu.parallel.compression import (
    QSGDReducer,
    SignSGDReducer,
    TopKReducer,
)

W = 8


def _leaves(seed):
    rng = np.random.RandomState(seed)
    return [
        jnp.asarray(rng.randn(4, 3, 2), jnp.float32),
        jnp.asarray(rng.randn(5, 4), jnp.float32),
        jnp.asarray(rng.randn(7), jnp.float32),
    ]


def _np(leaves):
    return [np.asarray(l) for l in leaves]


def _run_multiworker(reducer, sends_per_worker, n_leaves):
    """Run reducer.reduce inside shard_map on the 8-device mesh; returns
    per-device (out, mem) stacked on axis 0."""
    mesh = make_mesh()
    state = reducer.init(sends_per_worker[0])
    stacked = [
        jnp.stack([w[i] for w in sends_per_worker]) for i in range(n_leaves)
    ]

    def f(*send):
        send = [s[0] for s in send]
        _, out, mem, _ = reducer.reduce(state, send, DATA_AXIS)
        return [o[None] for o in out], [m[None] for m in mem]

    out, mem = jax.jit(
        jax.shard_map(
            f,
            mesh=mesh,
            in_specs=(P(DATA_AXIS),) * n_leaves,
            out_specs=([P(DATA_AXIS)] * n_leaves, [P(DATA_AXIS)] * n_leaves),
        )
    )(*stacked)
    return out, mem


# ---------------------------------------------------------------- top-k


def test_topk_full_k_is_identity():
    reducer = TopKReducer(k_fraction=1.0)
    send = _leaves(0)
    _, out, mem, bits = reducer.reduce({}, send, None)
    total = sum(l.size for l in send)
    assert bits == total * 64
    for s, o, m in zip(send, out, mem):
        np.testing.assert_allclose(np.asarray(o), np.asarray(s), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(m), 0.0)


def _topk_oracle(sends_np, k):
    """Per-worker top-k scatter on the flat concat, then mean."""
    flats = [np.concatenate([l.ravel() for l in s]) for s in sends_np]
    n = flats[0].size
    locals_ = []
    for f in flats:
        idx = np.argsort(-np.abs(f), kind="stable")[:k]
        loc = np.zeros(n, np.float32)
        loc[idx] = f[idx]
        locals_.append(loc)
    mean = np.mean(locals_, axis=0)
    return locals_, mean


def _unflatten(flat, template):
    out, off = [], 0
    for l in template:
        out.append(flat[off : off + l.size].reshape(l.shape))
        off += l.size
    return out


def test_topk_single_worker_oracle():
    send = _leaves(3)
    n = sum(l.size for l in send)
    reducer = TopKReducer(k_fraction=0.25)
    k = reducer._k(n)
    locals_, mean = _topk_oracle([_np(send)], k)
    _, out, mem, bits = reducer.reduce({}, send, None)
    assert bits == k * 64 == reducer.bits_per_step(send)
    for o, e in zip(out, _unflatten(mean, _np(send))):
        np.testing.assert_allclose(np.asarray(o), e, rtol=1e-5, atol=1e-6)
    # EF residual: send - own selection
    for m, s, e in zip(mem, _np(send), _unflatten(locals_[0], _np(send))):
        np.testing.assert_allclose(np.asarray(m), s - e, rtol=1e-5, atol=1e-6)


def test_topk_multiworker_mean(devices):
    sends = [_leaves(100 + w) for w in range(W)]
    n = sum(l.size for l in sends[0])
    reducer = TopKReducer(k_fraction=0.2)
    k = reducer._k(n)
    locals_, mean = _topk_oracle([_np(s) for s in sends], k)
    out, mem = _run_multiworker(reducer, sends, 3)
    expected = _unflatten(mean, _np(sends[0]))
    for i in range(3):
        for d in range(W):
            np.testing.assert_allclose(
                np.asarray(out[i])[d], expected[i], rtol=1e-5, atol=1e-6
            )


# ---------------------------------------------------------------- sign


def test_sign_bitpack_roundtrip():
    rng = np.random.RandomState(0)
    for n in (1, 7, 8, 9, 64, 100):
        bools = jnp.asarray(rng.rand(n) > 0.5)
        bitmap = SignSGDReducer._pack_bits(bools)
        assert bitmap.dtype == jnp.uint8 and bitmap.shape == (-(-n // 8),)
        signs = SignSGDReducer._unpack_signs(bitmap, n)
        np.testing.assert_array_equal(
            np.asarray(signs), np.where(np.asarray(bools), 1, -1)
        )


def _sign_oracle(sends_np):
    contribs = []
    for s in sends_np:
        contribs.append(
            [np.mean(np.abs(l)) * np.where(l >= 0, 1.0, -1.0) for l in s]
        )
    mean = [np.mean([c[i] for c in contribs], axis=0) for i in range(len(sends_np[0]))]
    return contribs, mean


def test_sign_single_worker_oracle():
    send = _leaves(5)
    reducer = SignSGDReducer()
    contribs, mean = _sign_oracle([_np(send)])
    _, out, mem, bits = reducer.reduce({}, send, None)
    n = sum(l.size for l in send)
    assert bits == 8 * (-(-n // 8)) + 32 * 3 == reducer.bits_per_step(send)
    for o, e in zip(out, mean):
        np.testing.assert_allclose(np.asarray(o), e, rtol=1e-5, atol=1e-6)
    for m, s, c in zip(mem, _np(send), contribs[0]):
        np.testing.assert_allclose(np.asarray(m), s - c, rtol=1e-5, atol=1e-6)


def test_sign_multiworker_mean(devices):
    sends = [_leaves(200 + w) for w in range(W)]
    _, mean = _sign_oracle([_np(s) for s in sends])
    out, _ = _run_multiworker(SignSGDReducer(), sends, 3)
    for i in range(3):
        for d in range(W):
            np.testing.assert_allclose(
                np.asarray(out[i])[d], mean[i], rtol=1e-5, atol=1e-6
            )


# ---------------------------------------------------------------- qsgd


def _qsgd_oracle(sends_np):
    contribs = []
    for s in sends_np:
        per = []
        for l in s:
            scale = np.max(np.abs(l)) / 127.0 if np.max(np.abs(l)) > 0 else 1.0
            q = np.clip(np.round(l / scale), -127, 127)
            per.append((scale * q).astype(np.float32))
        contribs.append(per)
    mean = [np.mean([c[i] for c in contribs], axis=0) for i in range(len(sends_np[0]))]
    return contribs, mean


def test_qsgd_deterministic_oracle():
    send = _leaves(9)
    reducer = QSGDReducer(stochastic=False)
    state = reducer.init(send)
    contribs, mean = _qsgd_oracle([_np(send)])
    _, out, mem, bits = reducer.reduce(state, send, None)
    n = sum(l.size for l in send)
    assert bits == 8 * n + 32 * 3 == reducer.bits_per_step(send)
    for o, e in zip(out, mean):
        np.testing.assert_allclose(np.asarray(o), e, rtol=1e-5, atol=1e-6)
    for m, s, c in zip(mem, _np(send), contribs[0]):
        np.testing.assert_allclose(np.asarray(m), s - c, rtol=1e-5, atol=1e-6)


def test_qsgd_multiworker_mean(devices):
    sends = [_leaves(300 + w) for w in range(W)]
    _, mean = _qsgd_oracle([_np(s) for s in sends])
    out, _ = _run_multiworker(QSGDReducer(stochastic=False), sends, 3)
    for i in range(3):
        for d in range(W):
            np.testing.assert_allclose(
                np.asarray(out[i])[d], mean[i], rtol=1e-5, atol=2e-6
            )


def test_qsgd_stochastic_is_unbiased():
    # E[dequant] == send: average many independent stochastic roundings
    send = [jnp.asarray(np.random.RandomState(1).randn(64), np.float32)]
    outs = []
    for seed in range(200):
        reducer = QSGDReducer(random_seed=seed, stochastic=True)
        _, out, _, _ = reducer.reduce(reducer.init(send), send, None)
        outs.append(np.asarray(out[0]))
    scale = np.max(np.abs(np.asarray(send[0]))) / 127.0
    np.testing.assert_allclose(
        np.mean(outs, axis=0), np.asarray(send[0]), atol=3 * scale / np.sqrt(200)
    )


# ------------------------------------------------------- bits + training


def test_compression_bits_ladder():
    template = [jnp.zeros((256, 64)), jnp.zeros((64,))]
    exact = 32 * (256 * 64 + 64)
    sign = SignSGDReducer().bits_per_step(template)
    qsgd = QSGDReducer().bits_per_step(template)
    topk = TopKReducer(k_fraction=0.01).bits_per_step(template)
    assert topk < sign < qsgd < exact  # 1% top-k at 64 bits/kept < 1 bit/elem
    assert sign < exact / 30  # ~32x compression (per contribution, W=1)
    assert qsgd < exact / 3.9  # ~4x
    # gathered-result convention: the gather family's wire cost scales with
    # W (each worker receives all contributions) — at W=8 sign is only ~4x
    # under exact, while PowerSGD's allreduce payload is W-invariant
    assert SignSGDReducer().bits_per_step(template, n_workers=8) == 8 * sign
    assert exact / 5 < 8 * sign < exact / 3.9


@pytest.mark.parametrize(
    "reducer",
    [TopKReducer(k_fraction=0.1), SignSGDReducer(), QSGDReducer(random_seed=1)],
    ids=["topk", "sign", "qsgd"],
)
def test_compressors_train_ef_momentum(devices, reducer):
    """Each compressor plugged into the Algorithm-2 trainer on the 8-device
    mesh: loss on a toy regression decreases."""
    from network_distributed_pytorch_tpu.parallel.trainer import (
        make_train_step,
        stateless_loss,
    )

    mesh = make_mesh()
    rng = np.random.RandomState(0)
    w_true = rng.randn(16, 4).astype(np.float32)
    x = rng.randn(64, 16).astype(np.float32)
    y = x @ w_true
    params = {"w": jnp.zeros((16, 4)), "b": jnp.zeros((4,))}

    def loss(params, batch):
        xb, yb = batch
        pred = xb @ params["w"] + params["b"]
        return jnp.mean((pred - yb) ** 2)

    step = make_train_step(
        stateless_loss(loss), reducer, params, learning_rate=0.05,
        momentum=0.9, algorithm="ef_momentum", mesh=mesh, donate_state=False,
    )
    state = step.init_state(params)
    batch = (jnp.asarray(x), jnp.asarray(y))
    losses = []
    for _ in range(30):
        state, l = step(state, batch)
        losses.append(float(l))
    assert losses[-1] < 0.2 * losses[0], losses
    from network_distributed_pytorch_tpu.parallel.trainer import LOSS_SYNC_BITS

    assert step.bits_per_step == reducer.bits_per_step(params, n_workers=8) + LOSS_SYNC_BITS
