"""Pipeline parallelism on a real model: the GPT decoder split into 8 block
stages over a 'pipe' mesh — forward exact vs the plain GPTLM forward, 1F1B
training grads exact vs single-device autodiff."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from network_distributed_pytorch_tpu.models.gpt import (
    gpt_embed_apply,
    gpt_head_apply,
    gpt_tiny,
    make_gpt_pipeline_train_fn,
    make_gpt_stage_fn,
    next_token_loss,
    split_gpt_params,
)
from network_distributed_pytorch_tpu.parallel import make_mesh
from network_distributed_pytorch_tpu.parallel.pipeline import (
    make_pipeline_train_fn,
    pipeline_apply,
    stacked_stage_params,
)

N = 8
B, T = 8, 16


def _setup():
    model = gpt_tiny(n_layers=N, max_position_embeddings=T)
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (B, T)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    return model, params, ids


@pytest.mark.slow
def test_gpt_pipeline_forward_matches_direct(devices):
    model, params, ids = _setup()
    cfg = model.config
    ref = model.apply({"params": params}, ids)

    embed, stages, final = split_gpt_params(params, N)
    stacked = stacked_stage_params(stages)
    stage_fn = make_gpt_stage_fn(cfg, layers_per_stage=1)
    mesh = make_mesh(axis_sizes=(N,), axis_names=("pipe",))

    def fwd(stacked, embed, final, ids):
        x = gpt_embed_apply(cfg, embed, ids)
        local = jax.tree_util.tree_map(lambda p: p[0], stacked)
        x = pipeline_apply(stage_fn, local, x, "pipe", num_microbatches=4)
        return gpt_head_apply(cfg, final, embed, x)

    out = jax.jit(
        jax.shard_map(
            fwd, mesh=mesh,
            in_specs=(P("pipe"), P(), P(), P()), out_specs=P(),
        )
    )(stacked, embed, final, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_gpt_pipeline_1f1b_grads_match_single_device(devices):
    model, params, ids = _setup()
    cfg = model.config
    labels = jnp.asarray(
        np.random.RandomState(1).randint(0, 128, (B, T)), jnp.int32
    )

    embed, stages, final = split_gpt_params(params, N)
    stacked = stacked_stage_params(stages)
    stage_fn = make_gpt_stage_fn(cfg, layers_per_stage=1)

    def mb_loss(act, lab):
        return next_token_loss(gpt_head_apply(cfg, final, embed, act), lab)

    # reference: plain autodiff wrt the per-layer block params
    def ref_loss(stages_list, ids, labels):
        x = gpt_embed_apply(cfg, embed, ids)
        for sp in stages_list:
            x = stage_fn(sp, x)
        return mb_loss(x, labels)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(stages, ids, labels)

    mesh = make_mesh(axis_sizes=(N,), axis_names=("pipe",))
    train = make_pipeline_train_fn(stage_fn, mb_loss, "pipe", num_microbatches=4)

    def fn(stacked, ids, labels):
        x = gpt_embed_apply(cfg, embed, ids)
        return train(stacked, x, labels)

    loss, grads = jax.jit(
        jax.shard_map(
            fn, mesh=mesh,
            in_specs=(P("pipe"), P(), P()), out_specs=(P(), P("pipe")),
        )
    )(stacked, ids, labels)

    np.testing.assert_allclose(float(loss), float(ref_l), rtol=2e-5)
    ref_stacked = stacked_stage_params(ref_g)
    for a, e in zip(
        jax.tree_util.tree_leaves(grads), jax.tree_util.tree_leaves(ref_stacked)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=5e-4, atol=1e-5
        )


@pytest.mark.slow
def test_gpt_pipeline_full_model_grads(devices):
    """make_gpt_pipeline_train_fn must produce gradients for EVERY param —
    embedding (wte/wpe), blocks, final LN, and the weight-tied head's
    contribution into wte — matching single-device autodiff (round-1 advisor
    finding: the hand-wired decomposition silently froze embed/head)."""
    model, params, ids = _setup()
    cfg = model.config
    labels = jnp.asarray(
        np.random.RandomState(1).randint(0, 128, (B, T)), jnp.int32
    )

    embed, stages, final = split_gpt_params(params, N)
    stacked = stacked_stage_params(stages)
    stage_fn = make_gpt_stage_fn(cfg, layers_per_stage=1)

    # reference: plain autodiff over ALL pieces at once
    def ref_loss(embed, stages_list, final, ids, labels):
        x = gpt_embed_apply(cfg, embed, ids)
        for sp in stages_list:
            x = stage_fn(sp, x)
        return next_token_loss(gpt_head_apply(cfg, final, embed, x), labels)

    ref_l, ref_g = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))(
        embed, stages, final, ids, labels
    )
    ref_embed_g, ref_stage_g, ref_final_g = ref_g

    mesh = make_mesh(axis_sizes=(N,), axis_names=("pipe",))
    train = make_gpt_pipeline_train_fn(
        cfg, layers_per_stage=1, num_microbatches=4
    )
    loss, (embed_g, stage_g, final_g) = jax.jit(
        jax.shard_map(
            train, mesh=mesh,
            in_specs=(P(), P("pipe"), P(), P(), P()),
            out_specs=(P(), (P(), P("pipe"), P())),
        )
    )(embed, stacked, final, ids, labels)

    np.testing.assert_allclose(float(loss), float(ref_l), rtol=2e-5)
    # embedding grads: nonzero and exact (includes the tied-head term on wte)
    assert np.any(np.asarray(embed_g["wte"]["embedding"]) != 0.0)
    assert np.any(np.asarray(embed_g["wpe"]["embedding"]) != 0.0)
    for got, want in (
        (embed_g, ref_embed_g),
        (stage_g, stacked_stage_params(ref_stage_g)),
        (final_g, ref_final_g),
    ):
        for a, e in zip(
            jax.tree_util.tree_leaves(got), jax.tree_util.tree_leaves(want)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), rtol=5e-4, atol=1e-5
            )


def test_gpt_pipeline_full_model_grads_with_data_axis(devices):
    """The documented pipe x data composition: params_varying_over=('data',)
    must trace (no double-pcast) and per-shard LOCAL grads must pmean to the
    full-batch gradient."""
    n_pipe, n_data = 4, 2
    model = gpt_tiny(n_layers=n_pipe, max_position_embeddings=T)
    cfg = model.config
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (B, T)), jnp.int32)
    labels = jnp.asarray(np.random.RandomState(1).randint(0, 128, (B, T)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    embed, stages, final = split_gpt_params(params, n_pipe)
    stacked = stacked_stage_params(stages)
    stage_fn = make_gpt_stage_fn(cfg, layers_per_stage=1)

    def ref_loss(embed, stages_list, final, ids, labels):
        x = gpt_embed_apply(cfg, embed, ids)
        for sp in stages_list:
            x = stage_fn(sp, x)
        return next_token_loss(gpt_head_apply(cfg, final, embed, x), labels)

    ref_l, ref_g = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))(
        embed, stages, final, ids, labels
    )

    mesh = make_mesh(
        axis_sizes=(n_data, n_pipe), axis_names=("data", "pipe")
    )
    train = make_gpt_pipeline_train_fn(
        cfg, layers_per_stage=1, num_microbatches=2,
        params_varying_over=("data",),
    )

    def step(embed, stacked, final, ids, labels):
        loss, grads = train(embed, stacked, final, ids, labels)
        # local grads -> data-parallel mean (the pluggable-reduction seam)
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, "data"), grads
        )
        return jax.lax.pmean(loss, "data"), grads

    loss, (embed_g, stage_g, final_g) = jax.jit(
        jax.shard_map(
            step, mesh=mesh,
            in_specs=(P(), P("pipe"), P(), P("data"), P("data")),
            out_specs=(P(), (P(), P("pipe"), P())),
        )
    )(embed, stacked, final, ids, labels)

    np.testing.assert_allclose(float(loss), float(ref_l), rtol=2e-5)
    ref_embed_g, ref_stage_g, ref_final_g = ref_g
    for got, want in (
        (embed_g, ref_embed_g),
        (stage_g, stacked_stage_params(ref_stage_g)),
        (final_g, ref_final_g),
    ):
        for a, e in zip(
            jax.tree_util.tree_leaves(got), jax.tree_util.tree_leaves(want)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), rtol=5e-4, atol=1e-5
            )
