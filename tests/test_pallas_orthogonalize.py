"""Pallas Gram-Schmidt kernel vs the XLA version and the NumPy oracle
(interpreter mode on CPU; the same kernel compiles for TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from network_distributed_pytorch_tpu.ops import orthogonalize
from network_distributed_pytorch_tpu.ops.pallas_orthogonalize import orthogonalize_pallas
from oracle_powersgd import orthogonalize_np


@pytest.mark.parametrize("shape", [(64, 4), (256, 8), (128, 1), (100, 3)])
def test_matches_oracle(shape):
    x = np.asarray(
        jax.random.normal(jax.random.PRNGKey(shape[0] + shape[1]), shape), np.float32
    )
    ours = np.asarray(orthogonalize_pallas(jnp.asarray(x), interpret=True))
    np.testing.assert_allclose(ours, orthogonalize_np(x), rtol=1e-4, atol=1e-5)


def test_matches_xla_version():
    x = jax.random.normal(jax.random.PRNGKey(7), (512, 8))
    a = np.asarray(orthogonalize(x))
    b = np.asarray(orthogonalize_pallas(x, interpret=True))
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_orthonormality():
    x = jax.random.normal(jax.random.PRNGKey(9), (300, 6))
    p = orthogonalize_pallas(x, interpret=True)
    np.testing.assert_allclose(np.asarray(p.T @ p), np.eye(6), atol=1e-4)
