"""Overlap analyzer: -start/-done window extraction from scheduled HLO."""

from network_distributed_pytorch_tpu.utils.overlap import overlap_report

_SCHEDULED_HLO = """\
HloModule jit_step, is_scheduled=true

ENTRY %main (p0: f32[64,32]) -> f32[64,32] {
  %p0 = f32[64,32]{1,0} parameter(0)
  %ar-start = f32[96]{0} all-reduce-start(%rank1buf), replica_groups={}, to_apply=%add
  %gs = f32[64,2]{1,0} fusion(%p0), kind=kLoop, calls=%gram_schmidt
  %qt = f32[32,2]{1,0} dot(%p0, %gs), lhs_contracting_dims={0}
  %ar-done = f32[96]{0} all-reduce-done(%ar-start)
  %ag-start = (f32[8],f32[64]) all-gather-start(%x), dimensions={0}
  %ag-done = f32[64]{0} all-gather-done(%ag-start)
  ROOT %out = f32[64,32]{1,0} fusion(%qt, %ar-done), kind=kOutput, calls=%f
}
"""


def test_overlap_report_synthetic():
    rep = overlap_report(_SCHEDULED_HLO)
    assert rep["scheduled"]
    assert rep["n_async_collectives"] == 2
    # the all-reduce window contains a fusion + a dot -> overlapped; the
    # all-gather window is empty -> not
    assert rep["n_overlapped"] == 1
    assert not rep["all_overlap"]
    ar = [c for c in rep["collectives"] if c["kind"] == "all-reduce"][0]
    assert ar["compute_ops_between"] == 2 and ar["ops_between"] == 2
    ag = [c for c in rep["collectives"] if c["kind"] == "all-gather"][0]
    assert ag["ops_between"] == 0


def test_overlap_report_on_real_cpu_hlo(devices):
    """CPU compiles synchronous collectives — the report must say so (zero
    async), never crash, on a real compiled PowerSGD step."""
    import jax.numpy as jnp

    from network_distributed_pytorch_tpu.parallel import PowerSGDReducer, make_mesh
    from network_distributed_pytorch_tpu.parallel.trainer import (
        make_train_step,
        stateless_loss,
    )
    from network_distributed_pytorch_tpu.utils.hlo_audit import compiled_hlo_text

    params = {"w": jnp.zeros((32, 16))}
    loss = stateless_loss(lambda p, b: jnp.mean((b[0] @ p["w"] - b[1]) ** 2))
    step = make_train_step(
        loss, PowerSGDReducer(compression_rank=2, matricize="last"), params,
        0.05, mesh=make_mesh(), donate_state=False,
    )
    state = step.init_state(params)
    batch = (jnp.zeros((16, 32)), jnp.zeros((16, 16)))
    rep = overlap_report(compiled_hlo_text(step.fn, state, batch))
    assert rep["scheduled"]
    assert rep["n_async_collectives"] == 0


def test_overlap_report_generic_async_wrapper():
    """XLA's generic `async-start`/`async-done` wrapper (what the TPU
    async-collective-fusion pass emits) is recognized and classified by the
    wrapped collective named on the line."""
    hlo = "\n".join([
        "HloModule m, is_scheduled=true",
        "ENTRY %main () -> f32[8] {",
        "  %p = f32[8]{0} parameter(0)",
        "  %ar = ((f32[8]), f32[8]) async-start(%p), calls=%wrapped_all-reduce.1",
        "  %f1 = f32[8]{0} fusion(%p), kind=kLoop",
        "  %d = f32[8]{0} dot(%f1, %f1)",
        "  %done = f32[8]{0} async-done(%ar)",
        "  ROOT %r = f32[8]{0} add(%done, %d)",
        "}",
    ])
    rep = overlap_report(hlo)
    assert rep["n_async_collectives"] == 1
    assert rep["collectives"][0]["kind"] == "all-reduce"
    assert rep["n_overlapped"] == 1  # the fusion + dot sit inside the window
    assert rep["collectives"][0]["compute_ops_between"] == 2


def test_overlap_report_start_done_pairing_by_name():
    """-done pairs with ITS -start by operand name, not by order: with two
    interleaved windows, each window's op count comes from its own span,
    and a -done naming an unknown op is ignored rather than crashing."""
    hlo = "\n".join([
        "HloModule m, is_scheduled=true",
        "ENTRY %main () -> f32[8] {",
        "  %a-start = f32[96]{0} all-reduce-start(%x), to_apply=%add",
        "  %b-start = (f32[8],f32[8]) all-gather-start(%y), dimensions={0}",
        "  %f1 = f32[8]{0} fusion(%y), kind=kLoop",
        "  %a-done = f32[96]{0} all-reduce-done(%a-start)",
        "  %orphan = f32[8]{0} all-gather-done(%never-started)",
        "  %d = f32[8]{0} dot(%f1, %f1)",
        "  %b-done = f32[8]{0} all-gather-done(%b-start)",
        "}",
    ])
    rep = overlap_report(hlo)
    assert rep["n_async_collectives"] == 2
    ar = [c for c in rep["collectives"] if c["kind"] == "all-reduce"][0]
    ag = [c for c in rep["collectives"] if c["kind"] == "all-gather"][0]
    # the all-reduce window holds only the all-gather-start + fusion; the
    # all-gather window additionally spans the -done/orphan/dot lines
    assert ar["compute_ops_between"] == 1
    assert ag["compute_ops_between"] == 2
    assert rep["n_overlapped"] == 2 and rep["all_overlap"]


def test_overlap_report_copy_windows_counted():
    """The TPU memory scheduler's copy-start/copy-done DMA prefetch windows
    are counted (with/without compute inside) but never listed as async
    collectives — on v5e they ARE the visible latency hiding."""
    hlo = "\n".join([
        "HloModule m, is_scheduled=true",
        "ENTRY %main () -> f32[8] {",
        "  %c1 = (f32[8],f32[8],u32[],u32[]) copy-start(%p)",
        "  %f = f32[8]{0} fusion(%p), kind=kLoop",
        "  %c1d = f32[8]{0} copy-done(%c1)",
        "  %c2 = (f32[8],f32[8],u32[],u32[]) copy-start(%q)",
        "  %c2d = f32[8]{0} copy-done(%c2)",
        "}",
    ])
    rep = overlap_report(hlo)
    assert rep["n_async_collectives"] == 0
    assert rep["collectives"] == []
    assert rep["n_async_copy_windows"] == 2
    assert rep["n_copy_windows_with_compute"] == 1
