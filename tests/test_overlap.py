"""Overlap analyzer: -start/-done window extraction from scheduled HLO."""

from network_distributed_pytorch_tpu.utils.overlap import overlap_report

_SCHEDULED_HLO = """\
HloModule jit_step, is_scheduled=true

ENTRY %main (p0: f32[64,32]) -> f32[64,32] {
  %p0 = f32[64,32]{1,0} parameter(0)
  %ar-start = f32[96]{0} all-reduce-start(%rank1buf), replica_groups={}, to_apply=%add
  %gs = f32[64,2]{1,0} fusion(%p0), kind=kLoop, calls=%gram_schmidt
  %qt = f32[32,2]{1,0} dot(%p0, %gs), lhs_contracting_dims={0}
  %ar-done = f32[96]{0} all-reduce-done(%ar-start)
  %ag-start = (f32[8],f32[64]) all-gather-start(%x), dimensions={0}
  %ag-done = f32[64]{0} all-gather-done(%ag-start)
  ROOT %out = f32[64,32]{1,0} fusion(%qt, %ar-done), kind=kOutput, calls=%f
}
"""


def test_overlap_report_synthetic():
    rep = overlap_report(_SCHEDULED_HLO)
    assert rep["scheduled"]
    assert rep["n_async_collectives"] == 2
    # the all-reduce window contains a fusion + a dot -> overlapped; the
    # all-gather window is empty -> not
    assert rep["n_overlapped"] == 1
    assert not rep["all_overlap"]
    ar = [c for c in rep["collectives"] if c["kind"] == "all-reduce"][0]
    assert ar["compute_ops_between"] == 2 and ar["ops_between"] == 2
    ag = [c for c in rep["collectives"] if c["kind"] == "all-gather"][0]
    assert ag["ops_between"] == 0


def test_overlap_report_on_real_cpu_hlo(devices):
    """CPU compiles synchronous collectives — the report must say so (zero
    async), never crash, on a real compiled PowerSGD step."""
    import jax.numpy as jnp

    from network_distributed_pytorch_tpu.parallel import PowerSGDReducer, make_mesh
    from network_distributed_pytorch_tpu.parallel.trainer import (
        make_train_step,
        stateless_loss,
    )
    from network_distributed_pytorch_tpu.utils.hlo_audit import compiled_hlo_text

    params = {"w": jnp.zeros((32, 16))}
    loss = stateless_loss(lambda p, b: jnp.mean((b[0] @ p["w"] - b[1]) ** 2))
    step = make_train_step(
        loss, PowerSGDReducer(compression_rank=2, matricize="last"), params,
        0.05, mesh=make_mesh(), donate_state=False,
    )
    state = step.init_state(params)
    batch = (jnp.zeros((16, 32)), jnp.zeros((16, 16)))
    rep = overlap_report(compiled_hlo_text(step.fn, state, batch))
    assert rep["scheduled"]
    assert rep["n_async_collectives"] == 0


def test_overlap_report_generic_async_wrapper():
    """XLA's generic `async-start`/`async-done` wrapper (what the TPU
    async-collective-fusion pass emits) is recognized and classified by the
    wrapped collective named on the line."""
    hlo = "\n".join([
        "HloModule m, is_scheduled=true",
        "ENTRY %main () -> f32[8] {",
        "  %p = f32[8]{0} parameter(0)",
        "  %ar = ((f32[8]), f32[8]) async-start(%p), calls=%wrapped_all-reduce.1",
        "  %f1 = f32[8]{0} fusion(%p), kind=kLoop",
        "  %d = f32[8]{0} dot(%f1, %f1)",
        "  %done = f32[8]{0} async-done(%ar)",
        "  ROOT %r = f32[8]{0} add(%done, %d)",
        "}",
    ])
    rep = overlap_report(hlo)
    assert rep["n_async_collectives"] == 1
    assert rep["collectives"][0]["kind"] == "all-reduce"
    assert rep["n_overlapped"] == 1  # the fusion + dot sit inside the window
    assert rep["collectives"][0]["compute_ops_between"] == 2


def test_overlap_report_start_done_pairing_by_name():
    """-done pairs with ITS -start by operand name, not by order: with two
    interleaved windows, each window's op count comes from its own span,
    and a -done naming an unknown op is ignored rather than crashing."""
    hlo = "\n".join([
        "HloModule m, is_scheduled=true",
        "ENTRY %main () -> f32[8] {",
        "  %a-start = f32[96]{0} all-reduce-start(%x), to_apply=%add",
        "  %b-start = (f32[8],f32[8]) all-gather-start(%y), dimensions={0}",
        "  %f1 = f32[8]{0} fusion(%y), kind=kLoop",
        "  %a-done = f32[96]{0} all-reduce-done(%a-start)",
        "  %orphan = f32[8]{0} all-gather-done(%never-started)",
        "  %d = f32[8]{0} dot(%f1, %f1)",
        "  %b-done = f32[8]{0} all-gather-done(%b-start)",
        "}",
    ])
    rep = overlap_report(hlo)
    assert rep["n_async_collectives"] == 2
    ar = [c for c in rep["collectives"] if c["kind"] == "all-reduce"][0]
    ag = [c for c in rep["collectives"] if c["kind"] == "all-gather"][0]
    # the all-reduce window holds only the all-gather-start + fusion; the
    # all-gather window additionally spans the -done/orphan/dot lines
    assert ar["compute_ops_between"] == 1
    assert ag["compute_ops_between"] == 2
    assert rep["n_overlapped"] == 2 and rep["all_overlap"]


def test_overlap_report_copy_windows_counted():
    """The TPU memory scheduler's copy-start/copy-done DMA prefetch windows
    are counted (with/without compute inside) but never listed as async
    collectives — on v5e they ARE the visible latency hiding."""
    hlo = "\n".join([
        "HloModule m, is_scheduled=true",
        "ENTRY %main () -> f32[8] {",
        "  %c1 = (f32[8],f32[8],u32[],u32[]) copy-start(%p)",
        "  %f = f32[8]{0} fusion(%p), kind=kLoop",
        "  %c1d = f32[8]{0} copy-done(%c1)",
        "  %c2 = (f32[8],f32[8],u32[],u32[]) copy-start(%q)",
        "  %c2d = f32[8]{0} copy-done(%c2)",
        "}",
    ])
    rep = overlap_report(hlo)
    assert rep["n_async_collectives"] == 0
    assert rep["collectives"] == []
    assert rep["n_async_copy_windows"] == 2
    assert rep["n_copy_windows_with_compute"] == 1


def test_overlap_report_async_compute_wrapper_skipped():
    """A generic async-start wrapping NON-collective work (no collective
    kind named on the line) must be dropped at its -done, not reported as
    an async collective — and must not shadow a real window around it."""
    hlo = "\n".join([
        "HloModule m, is_scheduled=true",
        "ENTRY %main () -> f32[8] {",
        "  %p = f32[8]{0} parameter(0)",
        "  %ac = ((f32[8]), f32[8]) async-start(%p), calls=%wrapped_fusion.3",
        "  %ar-start = f32[96]{0} all-reduce-start(%p), to_apply=%add",
        "  %f1 = f32[8]{0} fusion(%p), kind=kLoop",
        "  %acd = f32[8]{0} async-done(%ac)",
        "  %ar-done = f32[96]{0} all-reduce-done(%ar-start)",
        "}",
    ])
    rep = overlap_report(hlo)
    # only the real collective window is reported; the compute wrapper is
    # skipped silently (its window would otherwise double-count the fusion)
    assert rep["n_async_collectives"] == 1
    assert rep["collectives"][0]["kind"] == "all-reduce"
    assert rep["collectives"][0]["name"] == "ar-start"
    assert rep["n_overlapped"] == 1


_CHUNKED_SYNC_HLO = """\
HloModule jit_step, is_scheduled=true

%wrapped_ar (x: f32[8]) -> f32[8] {
  %x = f32[8]{0} parameter(0)
  ROOT %inner = f32[8]{0} all-reduce(%x), to_apply=%add
}

ENTRY %main (p0: f32[24]) -> f32[24] {
  %p0 = f32[24]{0} parameter(0)
  %ar.1 = f32[8]{0} all-reduce(%s0), replica_groups={}, to_apply=%add
  %retire.1 = f32[8]{0} fusion(%ar.1), kind=kLoop, calls=%unpack1
  %ar.2 = f32[8]{0} all-reduce(%s1), replica_groups={}, to_apply=%add
  %retire.2 = f32[8]{0} fusion(%ar.2), kind=kLoop, calls=%unpack2
  %ar.3 = f32[8]{0} all-reduce(%s2), replica_groups={}, to_apply=%add
  ROOT %out = f32[24]{0} fusion(%retire.1, %retire.2, %ar.3), kind=kOutput
}
"""


def test_overlap_report_sync_interleave_fields():
    """Synchronous chunk collectives (the CPU backend, Round-6 pipeline)
    are listed in schedule order with the compute between each and the
    next; only INTERIOR gaps count toward the interleave verdict, and the
    all-reduce inside the non-ENTRY wrapper computation is not counted."""
    rep = overlap_report(_CHUNKED_SYNC_HLO)
    assert rep["n_sync_collectives"] == 3
    names = [op["name"] for op in rep["sync_collectives"]]
    assert names == ["ar.1", "ar.2", "ar.3"]
    gaps = [op["compute_ops_after"] for op in rep["sync_collectives"]]
    # ar.1 -> retire.1; ar.2 -> retire.2; ar.3 -> the ROOT fusion (tail)
    assert gaps == [1, 1, 1]
    assert rep["n_sync_gaps_with_compute"] == 2  # interior gaps only
    assert rep["sync_interleaved"]


def test_overlap_report_sync_single_collective_not_interleaved():
    """One collective cannot interleave with itself: compute after the
    LAST collective proves nothing, so the verdict stays False."""
    hlo = "\n".join([
        "HloModule m, is_scheduled=true",
        "ENTRY %main () -> f32[8] {",
        "  %ar = f32[8]{0} all-reduce(%p), to_apply=%add",
        "  %f = f32[8]{0} fusion(%ar), kind=kLoop",
        "}",
    ])
    rep = overlap_report(hlo)
    assert rep["n_sync_collectives"] == 1
    assert rep["n_sync_gaps_with_compute"] == 0
    assert not rep["sync_interleaved"]


def test_overlap_report_sync_ignores_start_done_forms():
    """The sync matcher must not re-count async -start/-done pairs (the
    kind is followed by '-start('/'-done(' there, never '(')."""
    hlo = "\n".join([
        "HloModule m, is_scheduled=true",
        "ENTRY %main () -> f32[8] {",
        "  %ar-start = f32[96]{0} all-reduce-start(%x), to_apply=%add",
        "  %f1 = f32[8]{0} fusion(%x), kind=kLoop",
        "  %ar-done = f32[96]{0} all-reduce-done(%ar-start)",
        "}",
    ])
    rep = overlap_report(hlo)
    assert rep["n_async_collectives"] == 1
    assert rep["n_sync_collectives"] == 0
    assert not rep["sync_interleaved"]


def test_overlap_report_chunked_cpu_step_interleaves(devices):
    """End-to-end Round-6 evidence on a REAL compiled chunked step: the CPU
    backend keeps the K fenced chunk all-reduces separate, schedule order
    interleaves them with retire compute, and every window carries a name."""
    import jax.numpy as jnp

    from network_distributed_pytorch_tpu.parallel import ExactReducer, make_mesh
    from network_distributed_pytorch_tpu.parallel.trainer import (
        make_train_step,
        stateless_loss,
    )
    from network_distributed_pytorch_tpu.utils.hlo_audit import compiled_hlo_text

    params = {"w": jnp.zeros((32, 16)), "b": jnp.zeros((16,))}
    loss = stateless_loss(
        lambda p, b: jnp.mean((b[0] @ p["w"] + p["b"] - b[1]) ** 2)
    )
    step = make_train_step(
        loss, ExactReducer(comm_chunks=3), params, 0.05,
        mesh=make_mesh(), donate_state=False,
    )
    state = step.init_state(params)
    batch = (jnp.zeros((16, 32)), jnp.zeros((16, 16)))
    rep = overlap_report(compiled_hlo_text(step.fn, state, batch))
    # 3 grad chunks + the loss-sync pmean, all synchronous on CPU
    assert rep["n_sync_collectives"] == 4
    assert rep["n_async_collectives"] == 0
    assert rep["sync_interleaved"]
    assert rep["n_sync_gaps_with_compute"] >= 2
    assert all(op["name"] for op in rep["sync_collectives"])
