"""Checkpoint round-trip: save mid-training, restore, and verify the resumed
run continues the error-feedback chain exactly (same losses as the
uninterrupted run)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from network_distributed_pytorch_tpu.models import SmallCNN
from network_distributed_pytorch_tpu.parallel import PowerSGDReducer, make_mesh
from network_distributed_pytorch_tpu.parallel.trainer import make_train_step, stateless_loss
from network_distributed_pytorch_tpu.utils import cross_entropy_loss
from network_distributed_pytorch_tpu.utils.checkpoint import (
    latest_step_path,
    restore_checkpoint,
    save_checkpoint,
)

IMG = (8, 8, 3)


def _batch(i, n=32):
    ky, kx = jax.random.split(jax.random.PRNGKey(i))
    means = jax.random.normal(jax.random.PRNGKey(999), (10, *IMG))
    y = jax.random.randint(ky, (n,), 0, 10)
    return means[y] + 0.5 * jax.random.normal(kx, (n, *IMG)), y


@pytest.mark.slow
def test_save_restore_resume_bitexact(tmp_path, devices):
    model = SmallCNN(width=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *IMG)))["params"]

    def lf(p, b):
        x, y = b
        return cross_entropy_loss(model.apply({"params": p}, x), y)

    reducer = PowerSGDReducer(random_seed=3, compression_rank=2, matricize="last")
    step = make_train_step(
        stateless_loss(lf), reducer, params, 0.05, 0.9, "ef_momentum",
        mesh=make_mesh(), donate_state=False,
    )

    # uninterrupted: 6 steps
    state = step.init_state(params)
    losses_full = []
    for i in range(6):
        state, loss = step(state, _batch(i))
        losses_full.append(float(loss))

    # interrupted: 3 steps, save, restore, 3 more
    state = step.init_state(params)
    for i in range(3):
        state, _ = step(state, _batch(i))
    save_checkpoint(str(tmp_path / "ckpt"), state, step=3)
    path = latest_step_path(str(tmp_path / "ckpt"))
    assert path and path.endswith("step_3")

    restored = restore_checkpoint(path, jax.tree_util.tree_map(jnp.zeros_like, state))
    # error memories and Q warm-start survive the round trip
    for a, b in zip(jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    losses_resumed = []
    state2 = restored
    for i in range(3, 6):
        state2, loss = step(state2, _batch(i))
        losses_resumed.append(float(loss))
    np.testing.assert_allclose(losses_resumed, losses_full[3:], rtol=1e-6)


@pytest.mark.slow
def test_gpt_pp_checkpoint_resume_bitexact(devices, tmp_path):
    """audited_carry_loop checkpointing: a gpt_pp run interrupted at the
    epoch boundary and resumed must converge to the SAME final loss as an
    uninterrupted run (deterministic per-epoch batch streams)."""
    from network_distributed_pytorch_tpu.experiments import gpt_pp
    from network_distributed_pytorch_tpu.utils.config import ExperimentConfig

    cfg = lambda e: ExperimentConfig(
        training_epochs=e, learning_rate=0.15, global_batch_size=16,
        log_every=0,
    )
    kw = dict(preset="small", seq_len=32, steps_per_epoch=6)
    full = gpt_pp.run(cfg(3), **kw)

    ckpt = str(tmp_path / "pp_ckpt")
    gpt_pp.run(cfg(1), checkpoint_dir=ckpt, **kw)  # "crash" after epoch 0
    resumed = gpt_pp.run(cfg(3), checkpoint_dir=ckpt, **kw)  # resumes epoch 1

    np.testing.assert_allclose(
        resumed["final_loss"], full["final_loss"], rtol=1e-6
    )


def test_diloco_checkpoint_resume_bitexact(devices, tmp_path):
    """DiLoCo's full carry — replicated globals, outer momenta, per-worker
    inner momenta and EF memories, PowerSGD warm-start Q — survives
    save/restore: the resumed trajectory is bit-identical."""
    import jax.numpy as jnp

    from network_distributed_pytorch_tpu.parallel import (
        PowerSGDReducer,
        make_diloco_train_fn,
        make_mesh,
    )
    from network_distributed_pytorch_tpu.parallel.trainer import stateless_loss
    from network_distributed_pytorch_tpu.utils.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, 16).astype(np.float32))
    y = jnp.asarray(x @ rng.randn(16, 4).astype(np.float32))
    params = {"w": jnp.zeros((16, 4)), "b": jnp.zeros((4,))}
    loss_fn = stateless_loss(
        lambda p, b: jnp.mean((b[0] @ p["w"] + p["b"] - b[1]) ** 2)
    )
    h = 4
    stack = lambda b: tuple(jnp.broadcast_to(t[None], (h,) + t.shape) for t in b)
    mk = lambda: make_diloco_train_fn(
        loss_fn, params, inner_learning_rate=0.05, sync_every=h,
        mesh=make_mesh(), donate_state=False,
        reducer=PowerSGDReducer(random_seed=7, compression_rank=2, matricize="last"),
    )
    diloco = mk()
    state = diloco.init_state(params)
    for _ in range(2):
        state, _ = diloco(state, stack((x, y)))
    path = save_checkpoint(str(tmp_path / "diloco"), state, step=2)
    for _ in range(2):
        state, _ = diloco(state, stack((x, y)))

    fresh = mk()
    resumed = restore_checkpoint(path, fresh.init_state(params))
    assert type(resumed).__name__ == "DiLoCoState"
    for _ in range(2):
        resumed, _ = fresh(resumed, stack((x, y)))
    for a, b in zip(
        jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(resumed)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_sharded_preserves_shardings(tmp_path, devices):
    """``restore_checkpoint_sharded`` materializes each leaf ON the
    template's sharding (per-host memory = shard size, the pod-scale path —
    no full-state host replication) and the values round-trip exactly; the
    jitted step accepts the restored carry directly."""
    from network_distributed_pytorch_tpu.utils.checkpoint import (
        restore_checkpoint_sharded,
    )

    model = SmallCNN(width=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *IMG)))["params"]

    def lf(p, b):
        x, y = b
        return cross_entropy_loss(model.apply({"params": p}, x), y)

    step = make_train_step(
        stateless_loss(lf),
        PowerSGDReducer(random_seed=3, compression_rank=2, matricize="last"),
        params, 0.05, 0.9, "ef_momentum", mesh=make_mesh(), donate_state=False,
    )
    state, _ = step(step.init_state(params), _batch(0))  # a real mid-run state
    save_checkpoint(str(tmp_path / "ck"), state, step=1)
    restored = restore_checkpoint_sharded(
        latest_step_path(str(tmp_path / "ck")), state
    )
    assert type(restored) is type(state)
    for a, b in zip(
        jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)
    ):
        assert b.sharding.is_equivalent_to(a.sharding, a.ndim), (
            a.sharding, b.sharding,
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    state2, loss = step(restored, _batch(1))  # accepted without resharding
    assert np.isfinite(float(loss))


def test_restore_sharded_fsdp_state(tmp_path, devices):
    """The pod-scale case the sharded restore exists for: ZeRO-3 state whose
    leaves are genuinely SHARDED across devices round-trips onto its own
    shardings and training continues — per-host memory stays shard-sized."""
    from network_distributed_pytorch_tpu.parallel.fsdp import make_fsdp_train_step
    from network_distributed_pytorch_tpu.utils.checkpoint import (
        restore_checkpoint_sharded,
    )

    model = SmallCNN(width=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *IMG)))["params"]

    def lf(p, b):
        x, y = b
        return cross_entropy_loss(model.apply({"params": p}, x), y)

    fsdp = make_fsdp_train_step(
        stateless_loss(lf), params, 0.05, mesh=make_mesh(), donate_state=False
    )
    state, _ = fsdp(fsdp.init_state(params), _batch(0))
    save_checkpoint(str(tmp_path / "ck"), state, step=1)
    restored = restore_checkpoint_sharded(
        latest_step_path(str(tmp_path / "ck")), state
    )
    assert type(restored) is type(state)
    for a, b in zip(
        jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)
    ):
        assert b.sharding.is_equivalent_to(a.sharding, a.ndim)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    _state2, loss = fsdp(restored, _batch(1))
    assert np.isfinite(float(loss))
