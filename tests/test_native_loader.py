"""Native (C++) data runtime: exact agreement with the numpy semantics.

The native path must be a pure accelerant — byte-identical outputs to the
Python reference implementations in ``data/`` (SURVEY.md §4's golden-parity
test style, applied to our own native layer).
"""

import numpy as np
import pytest

from network_distributed_pytorch_tpu.data.loader import iterate_batches
from network_distributed_pytorch_tpu.native import (
    NativeBatchLoader,
    decode_cifar10_bin,
    gather_normalize_u8,
    native_available,
)

needs_native = pytest.mark.skipif(
    not native_available(), reason="native library unavailable"
)


def test_native_builds():
    # g++ is part of the image toolchain; the native runtime must come up.
    assert native_available()


@needs_native
def test_decode_cifar10_bin_matches_numpy():
    rng = np.random.RandomState(0)
    records = rng.randint(0, 256, size=(64, 3073), dtype=np.uint8)
    images, labels = decode_cifar10_bin(records)
    assert images.shape == (64, 32, 32, 3) and images.dtype == np.float32
    np.testing.assert_array_equal(labels, records[:, 0].astype(np.int32))
    chw = records[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    expect = ((chw.astype(np.float32) / 255.0) - 0.5) / 0.5
    np.testing.assert_array_equal(images, expect)


@needs_native
def test_gather_normalize_matches_numpy():
    rng = np.random.RandomState(1)
    src = rng.randint(0, 256, size=(100, 7, 3), dtype=np.uint8)
    idx = rng.randint(0, 100, size=33)
    out = gather_normalize_u8(src, idx, mean=0.4, std=0.25)
    expect = ((src[idx].astype(np.float32) / 255.0) - 0.4) / 0.25
    np.testing.assert_array_equal(out, expect)


@needs_native
def test_gather_bounds_checked_like_numpy():
    src = np.zeros((10, 3), np.uint8)
    with pytest.raises(IndexError):
        gather_normalize_u8(src, np.array([0, 10]))
    with pytest.raises(IndexError):
        gather_normalize_u8(src, np.array([-1]))


@pytest.mark.parametrize("dtype", [np.uint8, np.float32])
def test_prefetch_loader_matches_iterate_batches(dtype):
    rng = np.random.RandomState(2)
    n, batch = 70, 16
    if dtype == np.uint8:
        x_store = rng.randint(0, 256, size=(n, 4, 4, 3), dtype=np.uint8)
        x_ref = ((x_store.astype(np.float32) / 255.0) - 0.5) / 0.5
    else:
        x_store = rng.randn(n, 4, 4, 3).astype(np.float32)
        x_ref = x_store
    y = rng.randint(0, 10, size=n).astype(np.int32)

    loader = NativeBatchLoader(x_store, y, batch, seed=5)
    for epoch in range(2):
        got = list(loader.epoch(epoch))
        want = list(iterate_batches([x_ref, y], batch, seed=5, epoch=epoch))
        assert len(got) == len(want) == loader.steps_per_epoch()
        for (gx, gy), (wx, wy) in zip(got, want):
            np.testing.assert_allclose(gx, wx, rtol=0, atol=1e-6)
            np.testing.assert_array_equal(gy, wy)


def test_fallback_matches_native(monkeypatch):
    # With NDP_TPU_NO_NATIVE the loader must produce identical batches.
    rng = np.random.RandomState(3)
    x = rng.randint(0, 256, size=(40, 2, 2), dtype=np.uint8)
    y = rng.randint(0, 5, size=40).astype(np.int32)
    native = list(NativeBatchLoader(x, y, 8, seed=9).epoch(0))
    loader = NativeBatchLoader(x, y, 8, seed=9)
    loader._lib = None  # force the numpy path
    fallback = list(loader.epoch(0))
    assert len(native) == len(fallback) == 5  # 40 // 8
    for (nx, ny), (fx, fy) in zip(native, fallback):
        np.testing.assert_allclose(nx, fx, rtol=0, atol=1e-6)
        np.testing.assert_array_equal(ny, fy)


def test_tokenize_hash_native_matches_python():
    """The C++ tokenizer must be token-for-token equal to the Python
    HashTokenizer on realistic text: mixed case, punctuation glued to words,
    runs of ASCII whitespace (tabs/newlines), truncation, empty strings,
    and non-ASCII WORD bytes (lowercasing is done Python-side, so 'Café'
    hashes identically on both paths)."""
    from network_distributed_pytorch_tpu.data import HashTokenizer
    from network_distributed_pytorch_tpu.native.build import native_available
    from network_distributed_pytorch_tpu.native.loader import tokenize_hash

    if not native_available():
        import pytest

        pytest.skip("native toolchain unavailable")

    texts = [
        "This movie was GREAT, truly great!",
        "awful.\tJust awful...\n\nnever  again",
        "",
        "  leading and trailing   ",
        "Café au lait — très bon, naïve résumé",
        "good\u00a0movie\u2003with\u2000unicode\u0085whitespace",
        "x" * 5000,
        " ".join(f"word{i}" for i in range(500)),  # truncation past max_len
    ]
    tok = HashTokenizer(vocab_size=1000, max_len=32)
    native = tokenize_hash(texts, tok.vocab_size, tok.max_len)
    assert native is not None
    ref = tok.python_call(texts)
    np.testing.assert_array_equal(native["input_ids"], ref["input_ids"])
    np.testing.assert_array_equal(native["attention_mask"], ref["attention_mask"])
    # the tokenizer front door picked the native path and agrees too
    out = tok(texts)
    np.testing.assert_array_equal(out["input_ids"], ref["input_ids"])


def test_tokenize_hash_fallback(monkeypatch):
    """NDP_TPU_NO_NATIVE=1 → tokenize_hash returns None and HashTokenizer
    serves the Python loop."""
    import network_distributed_pytorch_tpu.native.build as build
    from network_distributed_pytorch_tpu.data import HashTokenizer
    from network_distributed_pytorch_tpu.native.loader import tokenize_hash

    monkeypatch.setattr(build, "_lib", None)
    monkeypatch.setattr(build, "_load_attempted", False)
    monkeypatch.setenv("NDP_TPU_NO_NATIVE", "1")
    assert tokenize_hash(["hello world"], 100, 8) is None
    out = HashTokenizer(vocab_size=100, max_len=8)(["hello world"])
    assert out["input_ids"][0, 0] == 1 and out["attention_mask"][0].sum() == 4
    monkeypatch.setattr(build, "_lib", None)
    monkeypatch.setattr(build, "_load_attempted", False)


def test_native_wordpiece_parity(tmp_path):
    """Native greedy matcher ≡ the Python WordPiece oracle token-for-token,
    across multi-piece words, greedy ties, [UNK] whole words, over-long
    words, unicode (the matcher is byte-level; probes only succeed on UTF-8
    boundaries), empties, and truncation."""
    from network_distributed_pytorch_tpu.data.wordpiece import WordPieceTokenizer
    from network_distributed_pytorch_tpu.native.build import native_available

    if not native_available():
        import pytest

        pytest.skip("native toolchain unavailable")

    vocab = [
        "[PAD]", "[UNK]", "[CLS]", "[SEP]", "the", "movie", "un", "##believ",
        "##able", "unbeliev", "watch", "##ed", "!", ",", "café", "ca",
        "##fé", "电", "影", "a", "##b", "##c", "abc",
    ]
    vf = tmp_path / "vocab.txt"
    vf.write_text("\n".join(vocab) + "\n", encoding="utf-8")
    tok = WordPieceTokenizer(str(vf), max_len=16)
    texts = [
        "the movie was unbelievable!",   # multi-piece + whole-word [UNK]
        "watched, watch abc ab",          # greedy longest-match (abc whole)
        "café 电影 cafe",                  # unicode pieces + CJK + [UNK]
        "",                               # empty row
        "x" * 500,                        # over the 100-char cap → [UNK]
        "the " * 50,                      # truncation past max_len
    ]
    words = [tok.basic_tokenize(t) for t in texts]
    ref = tok.python_encode(words)
    native = tok._native_matcher()
    assert native is not None
    out = native.encode(
        words, tok.unk_id, tok.cls_id, tok.sep_id, tok.pad_id, tok.max_len
    )
    np.testing.assert_array_equal(out["input_ids"], ref["input_ids"])
    np.testing.assert_array_equal(out["attention_mask"], ref["attention_mask"])
    # front door selects the native path and agrees too
    np.testing.assert_array_equal(
        tok(texts)["input_ids"], ref["input_ids"]
    )


def test_native_wordpiece_ascii_onepass_parity(tmp_path):
    """The one-pass ASCII normalize+match kernel ≡ Python normalize +
    oracle match, on control bytes, VT/FF, punct runs, casing, over-long
    words, whitespace-only and empty rows, and cap truncation."""
    from network_distributed_pytorch_tpu.data.wordpiece import WordPieceTokenizer
    from network_distributed_pytorch_tpu.native.build import native_available

    if not native_available():
        import pytest

        pytest.skip("native toolchain unavailable")

    vocab = [
        "[PAD]", "[UNK]", "[CLS]", "[SEP]", "the", "movie", "was", "great",
        "!", ",", ".", "-", "a", "##b", "ab", "x", "##x",
    ]
    vf = tmp_path / "vocab.txt"
    vf.write_text("\n".join(vocab) + "\n", encoding="utf-8")
    tok = WordPieceTokenizer(str(vf), max_len=12)
    texts = [
        "The MOVIE was GREAT!",
        "a\x00b\x01c",                      # NUL/control dropped mid-word
        "the\x0bmovie\x0cwas",              # VT/FF are control (joined), not spaces
        "--..!!,,",                         # punctuation run
        "",                                 # empty
        " \t\n\r ",                         # whitespace only
        "x" * 150,                          # over the 100-char cap → [UNK]
        "xxxx",                             # multi-piece x ##x ##x ##x
        ("the great movie ! " * 20),        # truncation past max_len
    ]
    assert all(t.isascii() for t in texts)
    ref = tok.python_encode([tok.basic_tokenize(t) for t in texts])
    out = tok._native_matcher().encode_ascii(
        texts, tok.unk_id, tok.cls_id, tok.sep_id, tok.pad_id, tok.max_len
    )
    np.testing.assert_array_equal(out["input_ids"], ref["input_ids"])
    np.testing.assert_array_equal(out["attention_mask"], ref["attention_mask"])


def test_native_wordpiece_mixed_batch_routing(tmp_path):
    """__call__ routes ASCII rows to the one-pass kernel and non-ASCII rows
    through the Python normalizer, reassembling rows in order."""
    from network_distributed_pytorch_tpu.data.wordpiece import WordPieceTokenizer
    from network_distributed_pytorch_tpu.native.build import native_available

    if not native_available():
        import pytest

        pytest.skip("native toolchain unavailable")

    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "the", "cafe", "movie", "电"]
    vf = tmp_path / "vocab.txt"
    vf.write_text("\n".join(vocab) + "\n", encoding="utf-8")
    tok = WordPieceTokenizer(str(vf), max_len=8)
    texts = ["the movie", "café 电", "the the", "CAFÉ"]
    out = tok(texts)
    ref = tok.python_encode([tok.basic_tokenize(t) for t in texts])
    np.testing.assert_array_equal(out["input_ids"], ref["input_ids"])
    np.testing.assert_array_equal(out["attention_mask"], ref["attention_mask"])


def test_decode_cifar10_bin_out_params(monkeypatch):
    """In-place decode into slices of a larger preallocated array — native
    and numpy-fallback paths produce identical results to the allocating
    form, and the returned arrays ARE the passed slices."""
    import network_distributed_pytorch_tpu.native.build as build

    rng = np.random.RandomState(4)
    records = rng.randint(0, 256, size=(12, 3073), dtype=np.uint8)
    want_x, want_y = decode_cifar10_bin(records)

    for force_fallback in (False, True):
        if force_fallback:
            monkeypatch.setattr(build, "_lib", None)
            monkeypatch.setattr(build, "_load_attempted", True)
        big_x = np.zeros((20, 32, 32, 3), np.float32)
        big_y = np.zeros((20,), np.int32)
        rx, ry = decode_cifar10_bin(
            records, out_images=big_x[5:17], out_labels=big_y[5:17]
        )
        assert rx.base is big_x and ry.base is big_y
        np.testing.assert_array_equal(big_x[5:17], want_x)
        np.testing.assert_array_equal(big_y[5:17], want_y)
        assert not big_x[:5].any() and not big_x[17:].any()  # no overwrite
    monkeypatch.setattr(build, "_lib", None)
    monkeypatch.setattr(build, "_load_attempted", False)


def test_wordpiece_sparse_vocab_falls_back_to_python(tmp_path):
    """Blank/duplicate vocab lines make line-number ids sparse;
    NativeWordPiece.build assigns ids by list position, so the native
    matcher must be REFUSED then (silent id compaction would feed wrong
    embedding rows) and the front door must still produce line-number ids
    via the Python matcher."""
    from network_distributed_pytorch_tpu.data.wordpiece import WordPieceTokenizer

    # line 4 blank (skipped -> gap), "the" duplicated (first id shadowed)
    vf = tmp_path / "vocab.txt"
    vf.write_text(
        "[PAD]\n[UNK]\n[CLS]\n[SEP]\n\nthe\nmovie\nthe\n", encoding="utf-8"
    )
    tok = WordPieceTokenizer(str(vf), max_len=8)
    assert sorted(tok.vocab.values()) != list(range(len(tok.vocab)))
    assert tok._native_matcher() is None  # sparse -> no native table
    out = tok(["the movie"])
    # line-number ids: "the" = 7 (duplicate shadows line 5), "movie" = 6
    np.testing.assert_array_equal(
        out["input_ids"][0][:4], [tok.cls_id, 7, 6, tok.sep_id]
    )


def test_wordpiece_dense_vocab_still_uses_native(tmp_path):
    """The dense-vocab gate must not disable the native matcher for a
    well-formed vocab.txt."""
    from network_distributed_pytorch_tpu.data.wordpiece import WordPieceTokenizer
    from network_distributed_pytorch_tpu.native.build import native_available

    if not native_available():
        import pytest

        pytest.skip("native toolchain unavailable")
    vf = tmp_path / "vocab.txt"
    vf.write_text("[PAD]\n[UNK]\n[CLS]\n[SEP]\nthe\nmovie\n", encoding="utf-8")
    tok = WordPieceTokenizer(str(vf), max_len=8)
    assert tok._native_matcher() is not None


def test_tokenizer_max_len_guards(tmp_path):
    """max_len < 2 cannot reach the native encoders: the C side computes
    cap = max_len - 2, and a negative cap cast to size_t would be a
    multi-exabyte resize plus OOB CLS/SEP writes."""
    import pytest

    from network_distributed_pytorch_tpu.data.imdb import HashTokenizer
    from network_distributed_pytorch_tpu.data.wordpiece import WordPieceTokenizer
    from network_distributed_pytorch_tpu.native.build import native_available
    from network_distributed_pytorch_tpu.native.loader import NativeWordPiece

    vf = tmp_path / "vocab.txt"
    vf.write_text("[PAD]\n[UNK]\n[CLS]\n[SEP]\n", encoding="utf-8")
    with pytest.raises(ValueError, match="max_len"):
        WordPieceTokenizer(str(vf), max_len=1)
    with pytest.raises(ValueError, match="max_len"):
        HashTokenizer(max_len=1)
    if native_available():
        native = NativeWordPiece.build(["[PAD]", "[UNK]", "[CLS]", "[SEP]"])
        with pytest.raises(ValueError, match="max_len"):
            native.encode([["x"]], 1, 2, 3, 0, max_len=0)
        with pytest.raises(ValueError, match="max_len"):
            native.encode_ascii(["x"], 1, 2, 3, 0, max_len=1)
