"""Reducer golden tests against the NumPy oracle of the reference math
(``reducer.py:43-170``), on both the single-process fallback path and the
real 8-device shard_map/psum path."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from network_distributed_pytorch_tpu.parallel import (
    DATA_AXIS,
    ExactReducer,
    PowerSGDReducer,
    make_mesh,
)
from oracle_powersgd import powersgd_reduce_np

W = 8


def _template_leaves(key):
    """A CNN-ish mix: conv-like 4D, linear-like 2D, and rank-1 bias/BN leaves."""
    ks = jax.random.split(key, 5)
    return [
        jax.random.normal(ks[0], (8, 3, 3, 3)),   # conv kernel (high-rank)
        jax.random.normal(ks[1], (16, 8)),        # linear (high-rank)
        jax.random.normal(ks[2], (16,)),          # bias (rank-1)
        jax.random.normal(ks[3], (10, 16)),       # linear (high-rank)
        jax.random.normal(ks[4], (10,)),          # bias (rank-1)
    ]


def _sends_per_worker(seed, n_workers=W):
    return [
        [np.asarray(l, dtype=np.float32) for l in _template_leaves(jax.random.PRNGKey(seed + w))]
        for w in range(n_workers)
    ]


def _qs_from_state(reducer, state, template):
    metas = reducer._metas(template)
    _, q_packer, _ = reducer._packers(template, metas)
    return [np.asarray(q) for q in q_packer.unpack(state.q_memory)]


def test_exact_reducer_is_pmean(devices):
    mesh = make_mesh()
    reducer = ExactReducer()
    sends = jnp.stack([jnp.arange(12.0).reshape(3, 4) + w for w in range(W)])

    def f(send):
        send = send[0]  # strip device-local leading axis
        _, out, mem, bits = reducer.reduce({}, send, DATA_AXIS)
        return out[None], mem[None]

    out, mem = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=(P(DATA_AXIS), P(DATA_AXIS)))
    )(sends)
    expected = np.asarray(sends).mean(axis=0)
    for d in range(W):
        np.testing.assert_allclose(np.asarray(out)[d], expected, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(mem)[d], 0.0)


def test_exact_reducer_bits():
    reducer = ExactReducer()
    send = [jnp.zeros((3, 4)), jnp.zeros((7,))]
    _, _, _, bits = reducer.reduce({}, send, None)
    assert bits == 32 * (12 + 7)


def test_powersgd_single_worker_matches_oracle():
    reducer = PowerSGDReducer(random_seed=3, compression_rank=2)
    template = [jnp.zeros_like(l) for l in _sends_per_worker(0, 1)[0]]
    state = reducer.init(template)
    sends = _sends_per_worker(42, 1)

    qs = _qs_from_state(reducer, state, template)
    exp_out, exp_mems, exp_qs, exp_bits = powersgd_reduce_np(sends, qs, 2)

    send_jax = [jnp.asarray(t) for t in sends[0]]
    state2, out, mem, bits = reducer.reduce(state, send_jax, None)

    assert bits == exp_bits
    for o, e in zip(out, exp_out):
        np.testing.assert_allclose(np.asarray(o), e, rtol=1e-4, atol=1e-5)
    for m, e in zip(mem, exp_mems[0]):
        np.testing.assert_allclose(np.asarray(m), e, rtol=1e-4, atol=1e-5)
    for q, e in zip(_qs_from_state(reducer, state2, template), exp_qs):
        np.testing.assert_allclose(q, e, rtol=1e-4, atol=1e-5)


def test_powersgd_error_feedback_identity():
    # EF telescoping: send = out + memory exactly, for every high-rank leaf
    reducer = PowerSGDReducer(random_seed=5, compression_rank=4)
    send = [jnp.asarray(t) for t in _sends_per_worker(7, 1)[0]]
    state = reducer.init(send)
    _, out, mem, _ = reducer.reduce(state, send, None)
    for s, o, m in zip(send, out, mem):
        if s.ndim > 1:
            np.testing.assert_allclose(np.asarray(o) + np.asarray(m), np.asarray(s), rtol=1e-5, atol=1e-6)


def test_powersgd_multiworker_golden_three_steps(devices):
    """The full warm-start chain over 3 steps on 8 real (virtual) devices
    vs the oracle — this pins allreduce placement, orthogonalization order,
    warm-start handoff, and bits accounting simultaneously."""
    mesh = make_mesh()
    reducer = PowerSGDReducer(random_seed=11, compression_rank=2)
    template = [jnp.zeros_like(l) for l in _sends_per_worker(0, 1)[0]]
    state = reducer.init(template)

    def f(q_memory, key, *send):
        from network_distributed_pytorch_tpu.parallel.reducers import PowerSGDState

        send = [s[0] for s in send]
        st, out, mem, _ = reducer.reduce(PowerSGDState(q_memory, key), send, DATA_AXIS)
        return st.q_memory, st.key, [o[None] for o in out], [m[None] for m in mem]

    shmap = jax.jit(
        jax.shard_map(
            f,
            mesh=mesh,
            in_specs=(P(), P()) + (P(DATA_AXIS),) * 5,
            out_specs=(P(), P(), [P(DATA_AXIS)] * 5, [P(DATA_AXIS)] * 5),
        )
    )

    qs = _qs_from_state(reducer, state, template)
    q_memory, key = state.q_memory, state.key
    for step in range(3):
        sends = _sends_per_worker(100 + 31 * step)
        stacked = [jnp.stack([jnp.asarray(w[i]) for w in sends]) for i in range(5)]

        exp_out, exp_mems, exp_qs, exp_bits = powersgd_reduce_np(sends, qs, 2)
        q_memory, key, out, mem = shmap(q_memory, key, *stacked)

        for i in range(5):
            for d in range(W):
                np.testing.assert_allclose(
                    np.asarray(out[i])[d], exp_out[i], rtol=2e-4, atol=1e-4
                )
                np.testing.assert_allclose(
                    np.asarray(mem[i])[d], exp_mems[d][i], rtol=2e-4, atol=1e-4
                )
        qs = exp_qs  # oracle warm-start for next step

    # our carried q_memory must equal the oracle's final Qs
    from network_distributed_pytorch_tpu.parallel.reducers import PowerSGDState

    final_qs = _qs_from_state(reducer, PowerSGDState(q_memory, key), template)
    for q, e in zip(final_qs, qs):
        np.testing.assert_allclose(q, e, rtol=2e-4, atol=1e-4)


def test_powersgd_bits_less_than_exact():
    template = [jnp.zeros((512, 512)), jnp.zeros((512,))]
    psgd = PowerSGDReducer(compression_rank=4)
    exact_bits = 32 * (512 * 512 + 512)
    psgd_bits = psgd.bits_per_step(template)
    assert psgd_bits == 32 * ((512 + 512) * 4 + 512)
    assert psgd_bits < exact_bits / 50


def test_powersgd_rank_clipping():
    # r = min(n, m, rank) (reducer.py:78)
    template = [jnp.zeros((2, 100))]
    psgd = PowerSGDReducer(compression_rank=8)
    assert psgd.bits_per_step(template) == 32 * (2 * 2 + 100 * 2)


def test_powersgd_no_reuse_rerandomizes():
    reducer = PowerSGDReducer(random_seed=1, reuse_query=False, compression_rank=2)
    send = [jnp.asarray(t) for t in _sends_per_worker(3, 1)[0]]
    state = reducer.init(send)
    state1, out1, _, _ = reducer.reduce(state, send, None)
    assert not np.array_equal(np.asarray(state1.key), np.asarray(state.key))
    # same state in -> deterministic out
    _, out1b, _, _ = reducer.reduce(state, send, None)
    for a, b in zip(out1, out1b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_powersgd_matricize_last():
    # flax-natural matricization: reshape(-1, shape[-1])
    reducer = PowerSGDReducer(random_seed=2, compression_rank=2, matricize="last")
    sends = _sends_per_worker(9, 1)
    send_jax = [jnp.asarray(t) for t in sends[0]]
    state = reducer.init(send_jax)
    qs = _qs_from_state(reducer, state, send_jax)
    exp_out, exp_mems, _, exp_bits = powersgd_reduce_np(sends, qs, 2, matricize_mode="last")
    _, out, mem, bits = reducer.reduce(state, send_jax, None)
    assert bits == exp_bits
    for o, e in zip(out, exp_out):
        np.testing.assert_allclose(np.asarray(o), e, rtol=1e-4, atol=1e-5)


def test_powersgd_all_rank1():
    # a model with only vector params skips the P/Q path entirely
    reducer = PowerSGDReducer(compression_rank=4)
    send = [jnp.arange(5.0), jnp.ones((3,))]
    state = reducer.init(send)
    state2, out, mem, bits = reducer.reduce(state, send, None)
    assert bits == 32 * 8
    for s, o in zip(send, out):
        np.testing.assert_array_equal(np.asarray(s), np.asarray(o))


def test_exact_unpacked_matches_packed(devices):
    mesh = make_mesh()
    packed = ExactReducer(packed=True)
    unpacked = ExactReducer(packed=False)
    send = [jnp.arange(12.0).reshape(3, 4), jnp.arange(5.0)]
    stacked = [jnp.stack([s + w for w in range(W)]) for s in send]

    def run(reducer):
        def f(*send):
            send = [s[0] for s in send]
            _, out, _, bits = reducer.reduce({}, send, DATA_AXIS)
            return [o[None] for o in out]

        return jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P(DATA_AXIS),) * 2, out_specs=[P(DATA_AXIS)] * 2
            )
        )(*stacked)

    a = run(packed)
    b = run(unpacked)
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)
    # same bytes on wire; collective structure differs (reference: per-param)
    _, _, _, bits_p = packed.reduce({}, send, None)
    _, _, _, bits_u = unpacked.reduce({}, send, None)
    assert bits_p == bits_u == 32 * 17
    assert packed.n_collectives(send) == 1
    assert unpacked.n_collectives(send) == 2


def test_powersgd_bf16_wire_halves_bits():
    template = [jnp.zeros((128, 64)), jnp.zeros((64,))]
    fp32 = PowerSGDReducer(compression_rank=4)
    bf16 = PowerSGDReducer(compression_rank=4, compression_dtype="bfloat16")
    assert bf16.bits_per_step(template) * 2 == fp32.bits_per_step(template)

    # math still works and error feedback telescopes in fp32
    send = [jnp.asarray(t) for t in _sends_per_worker(21, 1)[0]]
    state = bf16.init(send)
    state2, out, mem, bits = bf16.reduce(state, send, None)
    for s, o, m in zip(send, out, mem):
        assert o.dtype == s.dtype
        if s.ndim > 1:
            np.testing.assert_allclose(
                np.asarray(o) + np.asarray(m), np.asarray(s), rtol=1e-4, atol=1e-4
            )


def test_powersgd_extra_power_iterations_match_oracle():
    """Beyond parity: k extra subspace rounds (reference asserts k=0)."""
    reducer = PowerSGDReducer(random_seed=11, compression_rank=2, n_power_iterations=2)
    template = [jnp.zeros_like(l) for l in _sends_per_worker(0, 1)[0]]
    state = reducer.init(template)
    sends = _sends_per_worker(21, 1)

    qs = _qs_from_state(reducer, state, template)
    exp_out, exp_mems, exp_qs, exp_bits = powersgd_reduce_np(
        sends, qs, 2, n_power_iterations=2
    )

    state2, out, mem, bits = reducer.reduce(
        state, [jnp.asarray(t) for t in sends[0]], None
    )
    assert bits == exp_bits
    for o, e in zip(out, exp_out):
        np.testing.assert_allclose(np.asarray(o), e, rtol=1e-4, atol=1e-5)
    for m, e in zip(mem, exp_mems[0]):
        np.testing.assert_allclose(np.asarray(m), e, rtol=1e-4, atol=1e-5)
    for q, e in zip(_qs_from_state(reducer, state2, template), exp_qs):
        np.testing.assert_allclose(q, e, rtol=1e-4, atol=1e-5)


def test_powersgd_extra_iterations_improve_approximation():
    """More subspace rounds ⇒ the rank-r factorization tracks the dominant
    subspace better ⇒ smaller residual ‖M − PQᵀ‖ on a fixed matrix."""
    rng = np.random.RandomState(0)
    # strongly non-isotropic spectrum so subspace iteration has work to do
    u = np.linalg.qr(rng.randn(64, 64))[0]
    v = np.linalg.qr(rng.randn(48, 48))[0]
    s = np.diag(np.logspace(2, -2, 48))
    mat = (u[:, :48] @ s @ v.T).astype(np.float32)
    send = [jnp.asarray(mat)]

    errs = []
    for k in (0, 3):
        reducer = PowerSGDReducer(
            random_seed=2, compression_rank=2, n_power_iterations=k, reuse_query=False
        )
        state = reducer.init(send)
        _, out, _, _ = reducer.reduce(state, send, None)
        errs.append(float(jnp.linalg.norm(send[0] - out[0])))
    assert errs[1] < errs[0]


def test_powersgd_extra_iterations_bits_scale():
    send = [jnp.zeros((16, 8)), jnp.zeros((16,))]
    base = PowerSGDReducer(compression_rank=2).bits_per_step(send)
    more = PowerSGDReducer(compression_rank=2, n_power_iterations=2).bits_per_step(send)
    pq_bits = 32 * (16 * 2 + 8 * 2)
    assert base == pq_bits + 32 * 16
    assert more == 3 * pq_bits + 32 * 16


def test_wide_distilbert_r16_compression_is_algorithmic():
    """The accuracy study's wide tier (``distilbert_wide``, dim 256) exists
    so r=16 is a REAL compression: measured bytes ratio >= 8x. The tiny
    tier's dim-32 matrices meet r=16 at half their full rank (min(n,m,r)),
    making its 1.5x ratio definitional — the flaw this tier removes."""
    from network_distributed_pytorch_tpu.models import distilbert_wide

    model = distilbert_wide(num_labels=2)
    shapes = jax.eval_shape(
        lambda: model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, 32), jnp.int32),
            jnp.ones((1, 32), jnp.int32),
            deterministic=True,
        )
    )["params"]
    leaves = jax.tree_util.tree_leaves(shapes)
    exact_bits = 32 * sum(int(np.prod(l.shape)) for l in leaves)
    psgd_bits = PowerSGDReducer(compression_rank=16).bits_per_step(leaves)
    assert exact_bits / psgd_bits >= 8.0
