"""Test harness: an 8-device virtual CPU mesh.

The reference had no tests at all (SURVEY §4); its only harness was the
single-process no-op fallback in every collective. JAX makes real distributed
testing cheap: ``--xla_force_host_platform_device_count=8`` gives eight CPU
"devices" in one process, and the exact same ``shard_map``/``psum`` code path
that runs over TPU ICI runs over them.

This must run before jax initializes its backends, hence module-import time.
"""

import os

# Force CPU even when the environment pre-sets a TPU platform: tests exercise
# the distributed code path on 8 virtual devices, which needs the host
# platform. replace=False keeps a user-supplied device-count flag; the
# helper also covers the jax-already-imported case via jax.config.
from network_distributed_pytorch_tpu.hostenv import force_cpu_devices  # noqa: E402

# collective_timeout_s: XLA:CPU's default 40 s rendezvous-terminate
# deadline aborts the whole process when a heavy multi-device program's
# serialized per-device computes (8 devices, possibly 1 core) keep the
# last participant away too long — observed on the full suite at
# test_exact_cifar10_fsdp_strategy. 120 s sufficed for the suite alone
# but still aborted when ANOTHER jax process shared the single core
# (reproduced twice with a concurrent TPU-tunnel probe); 300 s/600 s
# absorbs that while a genuine deadlock still dies in ten minutes.
force_cpu_devices(8, replace=False, collective_timeout_s=300)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite is hundreds of small XLA compiles;
# caching serialized executables across runs cuts re-run wall time sharply
# (first run pays, repeats hit). XLA:CPU AOT entries bake in the compiling
# host's CPU features and can SIGILL if replayed on a lesser machine, so the
# cache directory is keyed by a fingerprint of this host's feature set — a
# different machine/image gets a fresh cache instead of stale executables.
# Safe to delete .xla_cache_tests/ anytime.
def _host_fingerprint() -> str:
    import hashlib
    import platform as _platform

    # machine + processor brand (NOT platform.platform(): that embeds the
    # kernel build string, which would invalidate the whole cache on every
    # routine kernel update); on hosts without /proc/cpuinfo (macOS) the
    # processor string still separates e.g. Rosetta from native
    feat = "|".join((_platform.machine(), _platform.processor()))
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feat += line
                    break
    except OSError:
        pass
    return hashlib.sha256(feat.encode()).hexdigest()[:12]


_cache = os.path.join(
    os.path.dirname(os.path.dirname(__file__)),
    ".xla_cache_tests",
    _host_fingerprint(),
)
try:
    os.makedirs(_cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
except Exception:  # noqa: BLE001 — cache is an optimization, never required
    pass

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
