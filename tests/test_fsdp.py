"""FSDP/ZeRO-3: sharded training ≡ replicated DDP, memory actually sharded."""

import jax
import jax.numpy as jnp
import numpy as np

from network_distributed_pytorch_tpu.models import SmallCNN
from network_distributed_pytorch_tpu.parallel import ExactReducer, make_mesh
from network_distributed_pytorch_tpu.parallel.fsdp import (
    make_fsdp_train_step,
    shard_params,
    unshard_params,
)
from network_distributed_pytorch_tpu.parallel.trainer import (
    make_train_step,
    stateless_loss,
)
from network_distributed_pytorch_tpu.utils import cross_entropy_loss

IMG = (8, 8, 3)


def _cnn_setup():
    model = SmallCNN(width=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *IMG)))["params"]

    def loss_fn(params, batch):
        x, y = batch
        return cross_entropy_loss(model.apply({"params": params}, x), y)

    return params, stateless_loss(loss_fn)


def _batch(key, n=64):
    ky, kx = jax.random.split(key)
    means = jax.random.normal(jax.random.PRNGKey(999), (10, *IMG))
    y = jax.random.randint(ky, (n,), 0, 10)
    x = means[y] + 0.5 * jax.random.normal(kx, (n, *IMG))
    return x, y


def test_shard_unshard_roundtrip(devices):
    params, _ = _cnn_setup()
    world = 8
    shards = shard_params(params, world)
    # every shard leaf carries the (world, chunk) layout
    for leaf, orig in zip(
        jax.tree_util.tree_leaves(shards), jax.tree_util.tree_leaves(params)
    ):
        assert leaf.shape[0] == world
        assert leaf.size >= orig.size
    back = unshard_params(shards, params)
    for a, b in zip(
        jax.tree_util.tree_leaves(back), jax.tree_util.tree_leaves(params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fsdp_matches_replicated_ddp(devices):
    """The ZeRO-3 step (gather params → grad → AD-transposed reduce-scatter →
    sharded SGD) must trace the same trajectory as replicated exact-DDP."""
    params, loss_fn = _cnn_setup()
    mesh = make_mesh()

    ddp = make_train_step(
        loss_fn, ExactReducer(), params, learning_rate=0.05, momentum=0.9,
        algorithm="sgd", mesh=mesh, donate_state=False,
    )
    fsdp = make_fsdp_train_step(
        loss_fn, params, learning_rate=0.05, momentum=0.9,
        algorithm="sgd", mesh=mesh, donate_state=False,
    )

    ds = ddp.init_state(params)
    fs = fsdp.init_state(params)
    for i in range(5):
        batch = _batch(jax.random.PRNGKey(i))
        ds, dloss = ddp(ds, batch)
        fs, floss = fsdp(fs, batch)
        np.testing.assert_allclose(float(dloss), float(floss), rtol=1e-5)

    full = fsdp.unshard(fs)
    for a, b in zip(
        jax.tree_util.tree_leaves(full), jax.tree_util.tree_leaves(ds.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_fsdp_memory_is_sharded(devices):
    """Each device holds ~1/world of every parameter + optimizer leaf."""
    params, loss_fn = _cnn_setup()
    mesh = make_mesh()
    fsdp = make_fsdp_train_step(
        loss_fn, params, learning_rate=0.05, mesh=mesh, donate_state=False
    )
    state = fsdp.init_state(params)
    for shard, orig in zip(
        jax.tree_util.tree_leaves(state.param_shards),
        jax.tree_util.tree_leaves(params),
    ):
        per_device = shard.size // 8
        assert per_device == -(-orig.size // 8)
        # genuinely distributed: one addressable shard per device
        assert len(shard.sharding.device_set) == 8


def test_fsdp_optax_adamw_trains(devices):
    import optax

    params, loss_fn = _cnn_setup()
    mesh = make_mesh()
    fsdp = make_fsdp_train_step(
        loss_fn, params, learning_rate=0.0, algorithm="optax",
        optimizer=optax.adamw(1e-2), mesh=mesh, donate_state=False,
    )
    state = fsdp.init_state(params)
    losses = []
    for i in range(8):
        state, loss = fsdp(state, _batch(jax.random.PRNGKey(i % 2)))
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_fsdp_bits_accounting(devices):
    params, loss_fn = _cnn_setup()
    mesh = make_mesh()
    fsdp = make_fsdp_train_step(
        loss_fn, params, learning_rate=0.05, mesh=mesh, donate_state=False
    )
    # gather + scatter of every (padded) leaf
    manual = 0
    for leaf in jax.tree_util.tree_leaves(params):
        padded = -(-leaf.size // 8) * 8
        manual += 2 * 8 * padded * leaf.dtype.itemsize
    from network_distributed_pytorch_tpu.parallel.trainer import LOSS_SYNC_BITS

    assert fsdp.bits_per_step == manual + LOSS_SYNC_BITS
