"""Tensor-parallel dense/MLP over an 8-device model mesh ≡ single-device."""

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from network_distributed_pytorch_tpu.parallel import make_mesh
from network_distributed_pytorch_tpu.parallel.tensor import tp_mlp

B, DIN, DH, DOUT = 4, 16, 64, 16  # hidden sharded 8 ways


def test_tp_mlp_matches_single_device(devices):
    ks = jax.random.split(jax.random.PRNGKey(0), 5)
    x = jax.random.normal(ks[0], (B, DIN))
    w_up = jax.random.normal(ks[1], (DIN, DH)) / np.sqrt(DIN)
    b_up = jax.random.normal(ks[2], (DH,))
    w_down = jax.random.normal(ks[3], (DH, DOUT)) / np.sqrt(DH)
    b_down = jax.random.normal(ks[4], (DOUT,))

    ref = jax.nn.relu(x @ w_up + b_up) @ w_down + b_down

    mesh = make_mesh(axis_sizes=(8,), axis_names=("model",))

    def body(x, w_up, b_up, w_down, b_down):
        return tp_mlp(x, w_up, b_up, w_down, b_down, axis_name="model")

    out = jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(
                P(),                 # x replicated
                P(None, "model"),    # up kernel: columns sharded
                P("model"),          # up bias sharded with the columns
                P("model", None),    # down kernel: rows sharded
                P(),                 # down bias replicated
            ),
            out_specs=P(),
        )
    )(x, w_up, b_up, w_down, b_down)

    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)
