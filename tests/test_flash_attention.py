"""Pallas flash attention (interpret mode on CPU) vs naive einsum attention:
plain, padding-masked, causal, and causal+masked; bf16 inputs; and the GPT
attn_impl="flash" path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from network_distributed_pytorch_tpu.ops.flash_attention import flash_attention

B, T, H, D = 2, 32, 4, 16


def _qkv(seed, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    mk = lambda k: jax.random.normal(k, (B, T, H, D), dtype)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


def _naive(q, k, v, mask=None, causal=False):
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s / jnp.sqrt(q.shape[-1])
    if mask is not None:
        s = s + mask[:, None, None, :]
    if causal:
        tril = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(tril[None, None], s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v.astype(jnp.float32))


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
@pytest.mark.parametrize("masked", [False, True], ids=["nomask", "mask"])
def test_flash_matches_naive(devices, causal, masked):
    q, k, v = _qkv(0)
    mask = None
    if masked:
        m = np.zeros((B, T), np.float32)
        m[0, 24:] = -1e30  # padded tail on row 0
        mask = jnp.asarray(m)
    ref = _naive(q, k, v, mask=mask, causal=causal)
    out = flash_attention(
        q, k, v, mask=mask, causal=causal, block_q=8, block_k=8, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_bf16(devices):
    q, k, v = _qkv(1, jnp.bfloat16)
    ref = _naive(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=8, interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=5e-2, atol=5e-2
    )


def test_flash_uneven_blocks(devices):
    """block_q != block_k and blocks that don't align with the causal
    diagonal still give exact results."""
    q, k, v = _qkv(2)
    ref = _naive(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=4, block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_gpt_flash_attention_path(devices):
    from network_distributed_pytorch_tpu.models.gpt import gpt_tiny

    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 32)), jnp.int32)
    base = gpt_tiny(max_position_embeddings=32)
    params = base.init(jax.random.PRNGKey(0), ids)["params"]
    ref = base.apply({"params": params}, ids)

    flash = gpt_tiny(max_position_embeddings=32, attn_impl="flash")
    out = flash.apply({"params": params}, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_distilbert_flash_attention_path(devices):
    from network_distributed_pytorch_tpu.models.distilbert import (
        DistilBertConfig,
        DistilBertEncoder,
    )

    cfg = dict(
        vocab_size=64, max_position_embeddings=32, dim=16, n_layers=2,
        n_heads=4, hidden_dim=32, dropout=0.0, attention_dropout=0.0,
    )
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 32)), jnp.int32)
    amask = jnp.ones_like(ids).at[0, 24:].set(0)  # padded tail
    base = DistilBertEncoder(DistilBertConfig(**cfg))
    params = base.init(jax.random.PRNGKey(0), ids, amask)["params"]
    ref = base.apply({"params": params}, ids, amask)

    flash = DistilBertEncoder(DistilBertConfig(**cfg, attn_impl="flash"))
    out = flash.apply({"params": params}, ids, amask)
    np.testing.assert_allclose(
        np.asarray(out[:, :24]), np.asarray(ref[:, :24]), rtol=2e-4, atol=2e-4
    )


@pytest.mark.parametrize("pad_value", [-1e30, -3.4e38], ids=["neg1e30", "f32min"])
def test_flash_fully_masked_rows(devices, pad_value):
    """An ALL-padded row must emit exactly zero output and leak NO gradient
    into the padded K/V — for both the package's -1e30 convention and the
    f32-min masks DistilBertEncoder emits (round-1 advisor finding: -1e30
    ties the running-max init, so exp doesn't underflow)."""
    q, k, v = _qkv(4)
    m = np.zeros((B, T), np.float32)
    m[0, :] = pad_value  # batch row 0: EVERY key padded
    mask = jnp.asarray(m)

    out = flash_attention(q, k, v, mask=mask, block_q=8, block_k=8, interpret=True)
    assert np.all(np.asarray(out[0]) == 0.0), "all-masked row output must be 0"
    assert np.all(np.isfinite(np.asarray(out)))

    def loss(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, mask=mask, block_q=8, block_k=8, interpret=True
            ) ** 2
        )

    dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert np.all(np.asarray(dq[0]) == 0.0)
    assert np.all(np.asarray(dk[0]) == 0.0), "grad leaked into padded K"
    assert np.all(np.asarray(dv[0]) == 0.0), "grad leaked into padded V"
    # the unpadded batch row still gets real gradients
    assert np.any(np.asarray(dv[1]) != 0.0)


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_flash_gradients_match_naive(devices, causal):
    """The custom-VJP chunked backward vs jax.grad through naive attention,
    including the mask cotangent path (mask rows partially padded)."""
    q, k, v = _qkv(3)
    m = np.zeros((B, T), np.float32)
    m[1, 28:] = -1e30
    mask = jnp.asarray(m)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, mask=mask, causal=causal, block_q=8, block_k=8,
                interpret=True,
            )
            ** 2
        )

    def loss_naive(q, k, v):
        return jnp.sum(_naive(q, k, v, mask=mask, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_naive = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, e in zip(g_flash, g_naive):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=5e-4, atol=5e-4
        )
