"""Failure detection: watchdog fires on hangs (and not on fast steps),
transient retry recovers, heartbeat staleness finds dead peers."""

import random
import time

import pytest

from network_distributed_pytorch_tpu.observe import MemorySink, Telemetry
from network_distributed_pytorch_tpu.utils.failure import (
    HeartbeatMonitor,
    StepWatchdog,
    retry_transient,
)


def test_watchdog_fires_on_slow_step():
    fired = []
    wd = StepWatchdog(timeout_seconds=0.1, on_timeout=fired.append)
    with wd.watch("slow"):
        time.sleep(0.3)
    assert fired == ["slow"]
    assert wd.fired == ["slow"]


def test_watchdog_quiet_on_fast_step():
    fired = []
    wd = StepWatchdog(timeout_seconds=0.5, on_timeout=fired.append)
    for i in range(3):
        with wd.watch(f"fast {i}"):
            time.sleep(0.01)
    time.sleep(0.1)
    assert fired == []


def test_retry_transient_recovers_and_exhausts():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("transient")
        return "ok"

    seen = []
    assert (
        retry_transient(
            flaky, retries=5, backoff_seconds=0.01,
            on_retry=lambda a, e: seen.append(a),
        )
        == "ok"
    )
    assert calls["n"] == 3 and seen == [1, 2]

    def always():
        raise RuntimeError("permanent")

    try:
        retry_transient(always, retries=2, backoff_seconds=0.01)
    except RuntimeError as e:
        assert str(e) == "permanent"
    else:
        raise AssertionError("should have re-raised")


def test_heartbeat_staleness(tmp_path):
    # grace 0: a never-beat peer counts as stale immediately (the default
    # grace would hold off while the world is still booting)
    a = HeartbeatMonitor(
        str(tmp_path), process_id=0, num_processes=3,
        startup_grace_seconds=0.0,
    )
    b = HeartbeatMonitor(str(tmp_path), process_id=1, num_processes=3)
    a.beat()
    b.beat(step=42)
    # process 2 never beat; 0 and 1 are fresh
    assert a.stale_peers(threshold_seconds=5.0) == [2]
    beats = a.last_beats()
    assert beats[0] is not None and beats[1] is not None and beats[2] is None
    # age out process 1
    time.sleep(0.15)
    a.beat()
    assert a.stale_peers(threshold_seconds=0.1) == [1, 2]


def test_watchdog_reset_rearms_compile_grace():
    """reset() clears fired history and re-applies compile_grace — a
    supervisor-restarted worker recompiles, so its first step is exempt
    again."""
    fired = []
    wd = StepWatchdog(
        timeout_seconds=0.1, on_timeout=fired.append, compile_grace=1
    )
    with wd.watch("compile"):  # grace: never armed
        time.sleep(0.25)
    with wd.watch("steady"):  # armed: fires
        time.sleep(0.25)
    assert fired == ["steady"]
    assert wd.fired == ["steady"]

    wd.reset()
    assert wd.fired == []
    with wd.watch("recompile"):  # grace applies AGAIN after reset
        time.sleep(0.25)
    with wd.watch("fast"):
        pass
    assert wd.fired == []


def test_retry_backoff_cap_and_jitter(monkeypatch):
    """Exponential growth is capped at max_backoff_seconds and jitter
    spreads each delay over [d, d*(1+jitter)] with a seedable rng."""
    slept = []
    monkeypatch.setattr(time, "sleep", slept.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 5:
            raise RuntimeError("blip")
        return "ok"

    assert retry_transient(
        flaky, retries=5, backoff_seconds=1.0, max_backoff_seconds=2.0,
        jitter=0.5, rng=random.Random(0),
    ) == "ok"
    # uncapped would be 1, 2, 4, 8; the cap clamps to 1, 2, 2, 2 before jitter
    assert len(slept) == 4
    for base, actual in zip([1.0, 2.0, 2.0, 2.0], slept):
        assert base <= actual <= base * 1.5

    # jitter is reproducible: the same seed gives the same schedule
    calls["n"], replay = 0, list(slept)
    slept.clear()
    retry_transient(
        flaky, retries=5, backoff_seconds=1.0, max_backoff_seconds=2.0,
        jitter=0.5, rng=random.Random(0),
    )
    assert slept == replay


def test_retry_emits_event_per_attempt(monkeypatch):
    """Every attempt — including the exhausted last one — lands in the
    structured log as FailureEvent(kind='retry')."""
    monkeypatch.setattr(time, "sleep", lambda _s: None)
    sink = MemorySink()
    telemetry = Telemetry([sink])

    def always():
        raise RuntimeError("permanent")

    with pytest.raises(RuntimeError, match="permanent"):
        retry_transient(
            always, retries=2, backoff_seconds=0.0,
            telemetry=telemetry, label="reducer",
        )
    retries = [
        r for r in sink.records
        if r.get("event") == "failure" and r.get("kind") == "retry"
    ]
    assert len(retries) == 3  # initial try + 2 retries, all recorded
    assert retries[0]["label"] == "reducer"
    assert "attempt 1/2" in retries[0]["message"]
    assert "attempt 3/2" in retries[-1]["message"]
    assert "permanent" in retries[-1]["message"]


def test_heartbeat_incarnation_and_grace(tmp_path):
    """Beats carry the incarnation field (how a reader tells a live
    restarted worker from its dead predecessor's file), and a fresh monitor
    gives never-beat peers a startup grace before calling them stale."""
    old = HeartbeatMonitor(str(tmp_path), process_id=0, num_processes=2)
    old.beat()
    new = HeartbeatMonitor(
        str(tmp_path), process_id=0, num_processes=2, incarnation=1,
        startup_grace_seconds=0.2,
    )
    new.beat(step=7)
    payloads = new.peer_payloads()
    assert payloads[0]["incarnation"] == 1  # the restart overwrote life 0
    assert payloads[0]["step"] == 7
    assert payloads[1] is None

    # within the grace window the silent peer 1 is not yet stale...
    assert new.stale_peers(threshold_seconds=60.0) == []
    time.sleep(0.25)
    # ...after it, "never beat" counts
    assert new.stale_peers(threshold_seconds=60.0) == [1]
