"""Failure detection: watchdog fires on hangs (and not on fast steps),
transient retry recovers, heartbeat staleness finds dead peers."""

import time

from network_distributed_pytorch_tpu.utils.failure import (
    HeartbeatMonitor,
    StepWatchdog,
    retry_transient,
)


def test_watchdog_fires_on_slow_step():
    fired = []
    wd = StepWatchdog(timeout_seconds=0.1, on_timeout=fired.append)
    with wd.watch("slow"):
        time.sleep(0.3)
    assert fired == ["slow"]
    assert wd.fired == ["slow"]


def test_watchdog_quiet_on_fast_step():
    fired = []
    wd = StepWatchdog(timeout_seconds=0.5, on_timeout=fired.append)
    for i in range(3):
        with wd.watch(f"fast {i}"):
            time.sleep(0.01)
    time.sleep(0.1)
    assert fired == []


def test_retry_transient_recovers_and_exhausts():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("transient")
        return "ok"

    seen = []
    assert (
        retry_transient(
            flaky, retries=5, backoff_seconds=0.01,
            on_retry=lambda a, e: seen.append(a),
        )
        == "ok"
    )
    assert calls["n"] == 3 and seen == [1, 2]

    def always():
        raise RuntimeError("permanent")

    try:
        retry_transient(always, retries=2, backoff_seconds=0.01)
    except RuntimeError as e:
        assert str(e) == "permanent"
    else:
        raise AssertionError("should have re-raised")


def test_heartbeat_staleness(tmp_path):
    a = HeartbeatMonitor(str(tmp_path), process_id=0, num_processes=3)
    b = HeartbeatMonitor(str(tmp_path), process_id=1, num_processes=3)
    a.beat()
    b.beat(step=42)
    # process 2 never beat; 0 and 1 are fresh
    assert a.stale_peers(threshold_seconds=5.0) == [2]
    beats = a.last_beats()
    assert beats[0] is not None and beats[1] is not None and beats[2] is None
    # age out process 1
    time.sleep(0.15)
    a.beat()
    assert a.stale_peers(threshold_seconds=0.1) == [1, 2]
