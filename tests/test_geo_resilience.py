"""Host-side geo-resilience plane: the partition policy / outer-sync driver
state machine, the chaos partition faults, the cost model's two-level
pricing, the report's hierarchy/partition sections, and the staleness
detector. Everything here is jax-free by construction — the control plane
must keep deciding while a worker's jax runtime is hung."""

import importlib.util
import os

import pytest

from network_distributed_pytorch_tpu.observe import costmodel
from network_distributed_pytorch_tpu.observe.health import (
    DetectorConfig,
    HealthMonitor,
)
from network_distributed_pytorch_tpu.resilience.chaos import (
    ChaosPlan,
    CommFaultInjector,
    FaultSpec,
)
from network_distributed_pytorch_tpu.resilience.guards import (
    CommEscalationError,
    OuterSyncDriver,
    PartitionPolicy,
    derive_outer_deadline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_report_module():
    spec = importlib.util.spec_from_file_location(
        "report", os.path.join(REPO, "scripts", "report.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# PartitionPolicy / OuterSyncDriver
# ---------------------------------------------------------------------------


def test_partition_policy_lifecycle_and_budget():
    policy = PartitionPolicy(max_local_steps=16)
    policy.note_partition(edge=(0, 1), step=5, reason="test fault")
    policy.note_partition(edge=(0, 1), step=6)  # idempotent while down
    assert policy.partitioned and policy.edge == (0, 1)
    assert [e.phase for e in policy.events] == ["partitioned"]

    policy.note_local_round(8, step=6)
    policy.note_local_round(8, step=7)  # == budget: charged, not exhausted
    assert policy.local_steps == 16 and policy.outer_staleness == 2
    assert policy.remaining_budget == 0
    with pytest.raises(CommEscalationError):
        policy.note_local_round(8, step=8)

    # a heal-and-sync is the rejoin: partition ends, staleness resets
    healed = PartitionPolicy(max_local_steps=16)
    healed.note_partition(edge=(0, 1), step=2)
    healed.note_local_round(8, step=3)
    healed.note_sync(step=4)
    assert not healed.partitioned and healed.outer_staleness == 0
    phases = [e.phase for e in healed.events]
    assert phases == ["partitioned", "local", "rejoin"]
    assert "EF catch-up" in healed.events[-1].reason


def test_outer_sync_driver_routes_on_probe():
    down = {"v": False}
    policy = PartitionPolicy(max_local_steps=32)
    driver = OuterSyncDriver(
        policy, probes=[lambda: down["v"]], edge_probe=lambda: (0, 1)
    )
    assert driver.should_sync(step=0)
    driver.note_sync(step=0)

    down["v"] = True
    assert not driver.should_sync(step=1)
    assert policy.partitioned and policy.edge == (0, 1)
    driver.note_local(8, step=1)
    assert policy.local_steps == 8

    down["v"] = False
    assert driver.should_sync(step=2)
    driver.note_sync(step=2)
    assert not policy.partitioned
    assert [e.phase for e in policy.events] == ["partitioned", "local", "rejoin"]


def test_derive_outer_deadline_floor_and_scaling():
    tiny = derive_outer_deadline(64, n_sites=2, fabric="1GbE")
    assert tiny >= 0.25  # the floor: scalars must not hair-trigger
    small = derive_outer_deadline(100 << 20, n_sites=2, fabric="1GbE")
    big = derive_outer_deadline(200 << 20, n_sites=2, fabric="1GbE")
    assert big > small > tiny  # past the floor, wire-time scaling wins


# ---------------------------------------------------------------------------
# chaos: comm_partition / comm_heal
# ---------------------------------------------------------------------------


def test_comm_partition_holds_until_heal():
    plan = ChaosPlan([
        FaultSpec(
            kind="comm_partition", step=2, rank=0,
            payload={"edge": [0, 1]},
        ),
        FaultSpec(kind="comm_heal", step=5, rank=0),
    ])
    inj = CommFaultInjector(plan, rank=0)
    for s in (0, 1):
        inj.advance(s)
        assert not inj.partitioned
    for s in (2, 3, 4):  # no duration: the edge stays down until the heal
        inj.advance(s)
        assert inj.partitioned and inj.partition_edge == (0, 1)
    inj.advance(5)
    assert not inj.partitioned and inj.partition_edge is None


def test_comm_partition_duration_self_clears():
    plan = ChaosPlan([
        FaultSpec(
            kind="comm_partition", step=1, rank=0,
            payload={"edge": [0, 1], "duration_steps": 2},
        ),
    ])
    inj = CommFaultInjector(plan, rank=0)
    inj.advance(1)
    inj.advance(2)
    assert inj.partitioned
    inj.advance(3)  # step >= until_step: retired without an explicit heal
    assert not inj.partitioned


# ---------------------------------------------------------------------------
# cost model: two-level pricing
# ---------------------------------------------------------------------------


def _calib(dense=1 << 20, workers=8):
    return costmodel.CostCalibration(
        step_time_s=0.02, compute_s=0.01, dense_bytes=float(dense),
        bytes_per_step=float(dense), n_workers=workers,
    )


def test_canonical_config_hierarchical_knobs():
    c = costmodel.canonical_config({
        "reducer": "HierarchicalReducer", "reducer_rank": 1,
        "sync_every": 8, "outer_async": 1, "sites": 2,
    })
    assert c["reducer"] == "hierarchical"
    assert c["outer_async"] == 1 and c["sites"] == 2
    key = costmodel.config_key(c)
    assert "sync=8" in key and "async=1" in key
    # the flat keys stay byte-stable: no two-level knobs leak into them
    flat_key = costmodel.config_key(
        costmodel.canonical_config({"reducer": "exact"})
    )
    assert "async" not in flat_key and "sites" not in flat_key


def test_predict_hierarchical_prices_both_levels():
    dense = 1 << 20
    sync = 8
    pred = costmodel.predict(
        _calib(dense),
        {"reducer": "hierarchical", "sync_every": sync,
         "outer_async": 1, "sites": 2},
        fabric="1GbE",
    )
    # exact outer (rank 0): the full dense delta crosses once per round
    assert pred["predicted_outer_bytes_per_step"] == pytest.approx(dense / sync)
    # inner: dense every step plus the amortized packed outer-delta reduce
    assert pred["predicted_inner_bytes_per_step"] == pytest.approx(
        dense * (1 + 1 / sync)
    )
    ranked = costmodel.predict(
        _calib(dense),
        {"reducer": "hierarchical", "reducer_rank": 1, "sync_every": sync,
         "outer_async": 1, "sites": 2},
        fabric="1GbE",
    )
    # a compressed outer shrinks the slow-fabric bytes, never the inner
    assert (
        ranked["predicted_outer_bytes_per_step"]
        < pred["predicted_outer_bytes_per_step"]
    )
    assert ranked["predicted_inner_bytes_per_step"] == pytest.approx(
        pred["predicted_inner_bytes_per_step"]
    )


def test_predict_hierarchical_async_hides_outer_time():
    cfg = {"reducer": "hierarchical", "reducer_rank": 1, "sync_every": 8,
           "sites": 2}
    slow = costmodel.predict(_calib(), cfg, fabric="1GbE")
    hidden = costmodel.predict(_calib(), {**cfg, "outer_async": 1},
                               fabric="1GbE")
    assert hidden["predicted_step_s"] <= slow["predicted_step_s"]
    # the bytes on the wire are identical — async hides time, not traffic
    assert hidden["predicted_outer_bytes_per_step"] == pytest.approx(
        slow["predicted_outer_bytes_per_step"]
    )


def test_hierarchical_configs_extend_the_grid():
    grid = costmodel.hierarchical_configs(_calib())
    keys = {costmodel.config_key(costmodel.canonical_config(c)) for c in grid}
    assert len(keys) == len(grid)  # no duplicate join keys
    assert any(c.get("outer_async") for c in grid)
    assert all(
        costmodel.canonical_config(c)["reducer"] == "hierarchical"
        for c in grid
    )


# ---------------------------------------------------------------------------
# report: hierarchy + partition sections
# ---------------------------------------------------------------------------


def test_hierarchy_summary_splits_levels():
    report = _load_report_module()
    bandwidth = {"by_tag": [
        {"tag": "inner.step_grads", "payload_bytes": 8000.0, "count": 8},
        {"tag": "inner.grads", "payload_bytes": 1000.0, "count": 1},
        {"tag": "outer.grads", "payload_bytes": 125.0, "count": 1},
        {"tag": "grads", "payload_bytes": 999.0, "count": 1},  # flat: ignored
    ]}
    h = report.hierarchy_summary(bandwidth)
    assert h["inner_bytes_per_step"] == 9000.0
    assert h["outer_bytes_per_step"] == 125.0
    assert h["cross_site_fraction"] == pytest.approx(125.0 / 9125.0)
    assert report.hierarchy_summary({"by_tag": [
        {"tag": "grads", "payload_bytes": 1.0, "count": 1},
    ]}) is None  # a flat run has no hierarchy section
    lines = report.render_hierarchy_section(h)
    assert any("cross-site share" in l for l in lines)


def test_partition_summary_counts_the_timeline():
    report = _load_report_module()
    policy = PartitionPolicy(max_local_steps=12, rank=0)
    policy.note_partition(edge=(0, 1), step=10, reason="gameday")
    policy.note_local_round(8, step=11)
    policy.note_sync(step=12)
    events = [e.record() for e in policy.events]
    p = report.partition_summary(events)
    assert p["n_partitions"] == 1 and p["n_rejoins"] == 1
    assert p["healed"] and p["budget"] == 12 and p["max_local_steps"] == 8
    assert report.partition_summary([{"event": "step"}]) is None
    assert report.render_partition_section(p)


# ---------------------------------------------------------------------------
# health: divergence-budget burn detector
# ---------------------------------------------------------------------------


def test_outer_staleness_detector_pages_at_budget_fractions():
    cfg = DetectorConfig()
    assert HealthMonitor(cfg).observe_outer_staleness(4, 16) == []
    warn = HealthMonitor(cfg).observe_outer_staleness(9, 16)
    assert [a.severity for a in warn] == ["warn"]
    crit = HealthMonitor(cfg).observe_outer_staleness(15, 16)
    assert [a.severity for a in crit] == ["critical"]
    assert "divergence budget" in crit[0].message
    # no positive budget → no escalation contract → silence, not a page
    assert HealthMonitor(cfg).observe_outer_staleness(5, 0) == []
