"""Sequence-parallel DistilBERT encoder: the same params run sharded over an
8-device seq mesh (ring attention + ring-offset positions) must reproduce the
single-device forward exactly."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from network_distributed_pytorch_tpu.models.distilbert import (
    DistilBertConfig,
    DistilBertEncoder,
)
from network_distributed_pytorch_tpu.parallel import make_mesh

CFG = dict(
    vocab_size=128,
    max_position_embeddings=64,
    dim=32,
    n_layers=2,
    n_heads=4,
    hidden_dim=64,
    dropout=0.0,
    attention_dropout=0.0,
)
B, T = 2, 32  # 4 tokens per device on the 8-way ring


def test_seq_parallel_encoder_matches_single_device(devices):
    base = DistilBertEncoder(DistilBertConfig(**CFG))
    ring = DistilBertEncoder(DistilBertConfig(**CFG, seq_axis="seq"))

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 128, (B, T)), jnp.int32)
    mask = jnp.ones((B, T), jnp.int32).at[1, 24:].set(0)  # pad tail of row 1

    params = base.init(jax.random.PRNGKey(0), ids, mask)["params"]
    ref = base.apply({"params": params}, ids, mask, deterministic=True)

    mesh = make_mesh(axis_sizes=(8,), axis_names=("seq",))

    def fwd(params, ids, mask):
        return ring.apply({"params": params}, ids, mask, deterministic=True)

    out = jax.jit(
        jax.shard_map(
            fwd,
            mesh=mesh,
            in_specs=(P(), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq"),
        )
    )(params, ids, mask)

    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)
