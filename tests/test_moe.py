"""Switch-MoE expert parallelism: routing exactness vs a per-token reference
(single-process and 8-device all-to-all paths), capacity drops, aux-loss
formula, and training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from network_distributed_pytorch_tpu.parallel import make_mesh
from network_distributed_pytorch_tpu.parallel.moe import (
    MoEOutput,
    stacked_expert_params,
    switch_moe,
)

E, D = 8, 6  # 8 experts over the 8-device mesh (1 per device)


def _expert_fn(params, tokens):
    return jnp.tanh(tokens @ params["w1"] + params["b1"]) @ params["w2"] + params["b2"]


def _experts(seed):
    rng = np.random.RandomState(seed)
    return [
        {
            "w1": jnp.asarray(rng.randn(D, 2 * D) * 0.3, jnp.float32),
            "b1": jnp.asarray(rng.randn(2 * D) * 0.1, jnp.float32),
            "w2": jnp.asarray(rng.randn(2 * D, D) * 0.3, jnp.float32),
            "b2": jnp.asarray(rng.randn(D) * 0.1, jnp.float32),
        }
        for _ in range(E)
    ]


def _reference(x, router_kernel, experts):
    """Per-token dense routing: out[t] = gate_t * expert_{argmax}(x_t)."""
    logits = np.asarray(x, np.float64) @ np.asarray(router_kernel, np.float64)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    idx = probs.argmax(-1)
    out = np.zeros_like(np.asarray(x))
    for t in range(x.shape[0]):
        y = _expert_fn(experts[idx[t]], x[t][None])[0]
        out[t] = probs[t, idx[t]] * np.asarray(y)
    return out, idx, probs


def test_moe_single_process_matches_reference():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(32, D), jnp.float32)
    router = jnp.asarray(rng.randn(D, E), jnp.float32)
    experts = _experts(1)
    stacked = stacked_expert_params(experts)

    ref, _, _ = _reference(x, router, experts)
    res = switch_moe(x, router, stacked, _expert_fn, None, capacity=32)
    assert isinstance(res, MoEOutput)
    np.testing.assert_allclose(np.asarray(res.out), ref, rtol=1e-4, atol=1e-5)
    assert float(res.dropped_fraction) == 0.0


def test_moe_multidevice_matches_reference(devices):
    rng = np.random.RandomState(2)
    t_total = 64  # 8 tokens per device
    x = jnp.asarray(rng.randn(t_total, D), jnp.float32)
    router = jnp.asarray(rng.randn(D, E) * 2.0, jnp.float32)
    experts = _experts(3)
    stacked = stacked_expert_params(experts)
    ref, _, _ = _reference(x, router, experts)

    mesh = make_mesh(axis_sizes=(8,), axis_names=("expert",))

    def body(x, router, stacked):
        res = switch_moe(x, router, stacked, _expert_fn, "expert", capacity=8)
        return res.out, res.dropped_fraction[None]

    out, dropped = jax.jit(
        jax.shard_map(
            body, mesh=mesh,
            in_specs=(P("expert"), P(), P("expert")),
            out_specs=(P("expert"), P("expert")),
        )
    )(x, router, stacked)
    assert float(np.asarray(dropped).max()) == 0.0  # capacity == local tokens
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_moe_capacity_drops_tokens():
    # all tokens route to expert 0 (router column 0 huge); capacity 2 keeps
    # exactly the first two, the rest get zero output
    x = jnp.ones((5, D), jnp.float32)
    router = jnp.zeros((D, E)).at[:, 0].set(10.0)
    experts = _experts(4)
    stacked = stacked_expert_params(experts)
    res = switch_moe(x, router, stacked, _expert_fn, None, capacity=2)
    out = np.asarray(res.out)
    assert np.abs(out[:2]).sum() > 0
    np.testing.assert_allclose(out[2:], 0.0)
    np.testing.assert_allclose(float(res.dropped_fraction), 3 / 5, rtol=1e-6)


def test_moe_aux_loss_formula():
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(16, D), jnp.float32)
    router = jnp.asarray(rng.randn(D, E), jnp.float32)
    stacked = stacked_expert_params(_experts(6))
    res = switch_moe(x, router, stacked, _expert_fn, None, capacity=16)

    logits = np.asarray(x) @ np.asarray(router)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    onehot = np.eye(E)[probs.argmax(-1)]
    expected = E * np.sum(onehot.mean(0) * probs.mean(0))
    np.testing.assert_allclose(float(res.aux_loss), expected, rtol=1e-5)


@pytest.mark.slow
def test_moe_trains(devices):
    """The routed layer learns a piecewise target on the 8-device mesh."""
    rng = np.random.RandomState(7)
    t_total = 64
    x = jnp.asarray(rng.randn(t_total, D), jnp.float32)
    w_true = jnp.asarray(rng.randn(D, D) * 0.7, jnp.float32)
    y = jnp.where(x[:, :1] > 0, x @ w_true, -(x @ w_true))

    experts = _experts(8)
    stacked = stacked_expert_params(experts)
    router = jnp.asarray(rng.randn(D, E) * 0.1, jnp.float32)
    mesh = make_mesh(axis_sizes=(8,), axis_names=("expert",))

    def loss_fn(params, x, y):
        res = switch_moe(
            x, params["router"], params["experts"], _expert_fn, "expert", capacity=16
        )
        mse = jnp.mean((res.out - y) ** 2)
        return jax.lax.pmean(mse + 0.01 * res.aux_loss, "expert")

    @jax.jit
    def step(params, x, y):
        def body(params, x, y):
            l, g = jax.value_and_grad(loss_fn)(params, x, y)
            # router grads are token-local partials: reduce over the mesh
            g = {
                "router": jax.lax.pmean(g["router"], "expert"),
                "experts": g["experts"],  # expert grads live with their shard
            }
            return jax.tree.map(lambda p, g_: p - 0.3 * g_, params, g), l

        specs = {"router": P(), "experts": P("expert")}
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(specs, P("expert"), P("expert")),
            out_specs=(specs, P()),
        )(params, x, y)

    params = {"router": router, "experts": stacked}
    losses = []
    for _ in range(200):
        params, l = step(params, x, y)
        losses.append(float(l))
    assert losses[-1] < 0.3 * losses[0], losses[::20]


def _reference_topk(x, router_kernel, experts, k):
    """Per-token dense top-k routing with GShard gate renormalization."""
    logits = np.asarray(x, np.float64) @ np.asarray(router_kernel, np.float64)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    out = np.zeros_like(np.asarray(x))
    for t in range(x.shape[0]):
        idx = np.argsort(-probs[t])[:k]
        gates = probs[t, idx] / probs[t, idx].sum()
        for g, e_i in zip(gates, idx):
            y = _expert_fn(experts[e_i], x[t][None])[0]
            out[t] += g * np.asarray(y)
    return out


def test_moe_top2_single_process_matches_reference():
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(24, D), jnp.float32)
    router = jnp.asarray(rng.randn(D, E) * 0.5, jnp.float32)
    experts = _experts(4)
    got = switch_moe(
        x, router, stacked_expert_params(experts), _expert_fn,
        axis_name=None, capacity=64, top_k=2,
    )
    ref = _reference_topk(x, router, experts, 2)
    np.testing.assert_allclose(np.asarray(got.out), ref, rtol=2e-4, atol=2e-5)
    assert float(got.dropped_fraction) == 0.0


def test_moe_top2_multidevice_matches_single_process(devices):
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(32, D), jnp.float32)
    router = jnp.asarray(rng.randn(D, E) * 0.5, jnp.float32)
    experts = stacked_expert_params(_experts(6))
    local = switch_moe(x, router, experts, _expert_fn, None, capacity=64, top_k=2)
    mesh = make_mesh(axis_sizes=(8,), axis_names=("expert",), devices=devices)
    dist = jax.jit(
        jax.shard_map(
            lambda x_, r_, e_: switch_moe(
                x_, r_, e_, _expert_fn, "expert", capacity=64, top_k=2
            ).out,
            mesh=mesh,
            in_specs=(P("expert"), P(), P("expert")),
            out_specs=P("expert"),
        )
    )(x, router, experts)
    np.testing.assert_allclose(
        np.asarray(dist), np.asarray(local.out), rtol=1e-5, atol=1e-6
    )


def test_moe_top2_priority_dispatch_drops_secondary_first():
    """With capacity 1 and colliding choices, the primary (top-1) assignment
    claims the slot and the secondary drops — not the other way around."""
    rng = np.random.RandomState(7)
    experts = _experts(8)
    # steer ALL tokens to the same top-1 expert 0 and top-2 expert 1
    router = np.zeros((D, E), np.float32)
    router[:, 0] = 1.0
    router[:, 1] = 0.5
    x = jnp.asarray(np.abs(rng.randn(4, D)), jnp.float32)
    got = switch_moe(
        x, jnp.asarray(router), stacked_expert_params(experts), _expert_fn,
        axis_name=None, capacity=1, top_k=2,
    )
    # token 0 keeps both assignments; tokens 1-3 drop both (slots taken):
    # 2 kept of 8 assignments
    np.testing.assert_allclose(float(got.dropped_fraction), 6 / 8, rtol=1e-6)
    # token 0's output mixes experts 0 and 1; later tokens fall through to 0
    assert float(jnp.max(jnp.abs(got.out[1:]))) == 0.0
    assert float(jnp.max(jnp.abs(got.out[0]))) > 0.0
