"""Jax worker for supervisor kill-and-resume tests (run as a subprocess).

One rank of a real training run: SmallCNN + PowerSGD ef_momentum through
``resilient_train_loop`` with committed checkpoints, a heartbeat file, a
JSONL event log, and an optional chaos plan. On completion writes a result
JSON holding sha256 digests of the final params and EF memories, so the
parent can assert a killed-and-resumed run is bit-identical to an
uninterrupted one.

Usage::

    python supervised_worker.py --rank R --world W --epochs N \
        --ckpt-dir D --result F [--heartbeat-dir D] [--chaos-plan F] \
        [--event-log F] [--step-retries K] [--guard-batches]
"""

import argparse
import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# must happen before jax import: CPU backend, no TPU plugin
from network_distributed_pytorch_tpu.hostenv import force_cpu_devices  # noqa: E402

force_cpu_devices(n=1, drop_tpu_tunnel=True)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from network_distributed_pytorch_tpu.experiments.common import (  # noqa: E402
    resilient_train_loop,
)
from network_distributed_pytorch_tpu.models import SmallCNN  # noqa: E402
from network_distributed_pytorch_tpu.observe import (  # noqa: E402
    telemetry_for_run,
)
from network_distributed_pytorch_tpu.parallel import (  # noqa: E402
    PowerSGDReducer,
    make_mesh,
)
from network_distributed_pytorch_tpu.parallel.trainer import (  # noqa: E402
    make_train_step,
    stateless_loss,
)
from network_distributed_pytorch_tpu.resilience import (  # noqa: E402
    ChaosPlan,
    incarnation_from_env,
)
from network_distributed_pytorch_tpu.utils import (  # noqa: E402
    cross_entropy_loss,
)
from network_distributed_pytorch_tpu.utils.failure import (  # noqa: E402
    HeartbeatMonitor,
)

IMG = (8, 8, 3)


def _setup():
    model = SmallCNN(width=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *IMG)))["params"]

    def lf(p, b):
        x, y = b
        return cross_entropy_loss(model.apply({"params": p}, x), y)

    mesh = make_mesh()
    step = make_train_step(
        stateless_loss(lf),
        PowerSGDReducer(random_seed=7, compression_rank=2, matricize="last"),
        params, learning_rate=0.05, momentum=0.9, algorithm="ef_momentum",
        mesh=mesh, donate_state=False,
    )
    return step, params


def _batches(epoch, steps=4):
    rng = np.random.RandomState(1000 + epoch)
    means = np.random.RandomState(999).randn(10, *IMG)
    for _ in range(steps):
        y = rng.randint(0, 10, 32)
        x = means[y] + 0.5 * rng.randn(32, *IMG)
        yield jnp.asarray(x, jnp.float32), jnp.asarray(y)


def _digest(tree) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(tree):
        h.update(np.ascontiguousarray(jax.device_get(leaf)).tobytes())
    return h.hexdigest()


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--rank", type=int, default=0)
    p.add_argument("--world", type=int, default=1)
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--result", required=True)
    p.add_argument("--heartbeat-dir", default=None)
    p.add_argument("--chaos-plan", default=None)
    p.add_argument("--event-log", default=None)
    p.add_argument("--step-retries", type=int, default=0)
    p.add_argument("--guard-batches", action="store_true")
    args = p.parse_args()

    incarnation = incarnation_from_env()
    plan = ChaosPlan.load(args.chaos_plan) if args.chaos_plan else None
    telemetry = telemetry_for_run(event_log=args.event_log)
    hb = (
        HeartbeatMonitor(
            args.heartbeat_dir, process_id=args.rank,
            num_processes=args.world, incarnation=incarnation,
        )
        if args.heartbeat_dir
        else None
    )

    step, params = _setup()
    state, _, start_epoch = resilient_train_loop(
        step, step.init_state(params), _batches, args.epochs,
        checkpoint_dir=args.ckpt_dir, rank=args.rank,
        heartbeat=hb, telemetry=telemetry, run_name="supervised",
        chaos_plan=plan, incarnation=incarnation,
        step_retries=args.step_retries, guard_batches=args.guard_batches,
        expected_batch=32 if args.guard_batches else None,
    )
    telemetry.close()

    with open(args.result, "w") as f:
        json.dump(
            {
                "rank": args.rank,
                "incarnation": incarnation,
                "start_epoch": start_epoch,
                "params_digest": _digest(state.params),
                "memories_digest": _digest(state.memories),
            },
            f,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
