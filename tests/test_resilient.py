"""Crash/resume: a run killed mid-training and restarted from its
checkpoints converges to the SAME final state as an uninterrupted run
(deterministic per-epoch data + full-TrainState checkpoints ⇒ the EF chain
continues exactly)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from network_distributed_pytorch_tpu.experiments.common import (
    resilient_train_loop,
)
from network_distributed_pytorch_tpu.models import SmallCNN
from network_distributed_pytorch_tpu.parallel import PowerSGDReducer, make_mesh
from network_distributed_pytorch_tpu.parallel.trainer import (
    make_train_step,
    stateless_loss,
)
from network_distributed_pytorch_tpu.utils import cross_entropy_loss
from network_distributed_pytorch_tpu.utils.failure import HeartbeatMonitor

IMG = (8, 8, 3)
EPOCHS = 4


def _setup():
    model = SmallCNN(width=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *IMG)))["params"]

    def lf(p, b):
        x, y = b
        return cross_entropy_loss(model.apply({"params": p}, x), y)

    mesh = make_mesh()
    step = make_train_step(
        stateless_loss(lf),
        PowerSGDReducer(random_seed=7, compression_rank=2, matricize="last"),
        params, learning_rate=0.05, momentum=0.9, algorithm="ef_momentum",
        mesh=mesh, donate_state=False,
    )
    return step, params


def _batches(epoch, steps=4):
    rng = np.random.RandomState(1000 + epoch)
    means = np.random.RandomState(999).randn(10, *IMG)
    for _ in range(steps):
        y = rng.randint(0, 10, 32)
        x = means[y] + 0.5 * rng.randn(32, *IMG)
        yield jnp.asarray(x, jnp.float32), jnp.asarray(y)


class _Crash(Exception):
    pass


def _crashing_batches(crash_at_epoch):
    def fn(epoch):
        if epoch == crash_at_epoch:
            raise _Crash()
        return _batches(epoch)

    return fn


@pytest.mark.slow
def test_crash_resume_matches_uninterrupted(devices, tmp_path):
    step, params = _setup()

    # uninterrupted reference run
    ref_state, _, se = resilient_train_loop(
        step, step.init_state(params), _batches, EPOCHS,
        checkpoint_dir=str(tmp_path / "ref"),
    )
    assert se == 0

    # crashing run: dies entering epoch 2 (epochs 0-1 checkpointed)
    try:
        resilient_train_loop(
            step, step.init_state(params), _crashing_batches(2), EPOCHS,
            checkpoint_dir=str(tmp_path / "crashy"),
        )
        raise AssertionError("should have crashed")
    except _Crash:
        pass

    # restart: resumes at epoch 2, finishes, matches the reference exactly
    hb = HeartbeatMonitor(str(tmp_path / "hb"), process_id=0, num_processes=1)
    state, _, start_epoch = resilient_train_loop(
        step, step.init_state(params), _batches, EPOCHS,
        checkpoint_dir=str(tmp_path / "crashy"),
        watchdog_timeout_s=600.0, heartbeat=hb,
    )
    assert start_epoch == 2
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(ref_state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the EF memories and momenta resumed exactly too
    for a, b in zip(
        jax.tree_util.tree_leaves(state.memories),
        jax.tree_util.tree_leaves(ref_state.memories),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert hb.last_beats()[0] is not None
