"""Jax-free toy worker for supervisor mechanics tests (run as subprocess).

Simulates a rank of a deterministic "training" run without importing jax
(so a restart costs milliseconds, not a backend init): each step adds
``world_size`` to an accumulator — the toy stand-in for the global-batch
contribution, so a degraded-world restart visibly changes the accounting —
checkpoints the accumulator atomically every step, beats a heartbeat file,
and obeys a ``resilience.chaos.ChaosPlan`` for process-level faults
(exit / SIGKILL / hang) and correlated faults (``zone_outage`` kills every
zone member, ``host_flap`` dies hard on its first incarnations). On
completion writes a result JSON per rank. A persistently unwritable state
path exits ``CKPT_UNWRITABLE_EXIT_CODE`` (fail-fast, no restart storm).

With ``--graceful-term`` the worker installs the PreemptionGuard-style
SIGTERM contract: persist state, then exit ``PREEMPT_EXIT_CODE`` so the
supervisor classifies the death as graceful (the ``proc_preempt`` chaos
fault self-delivers exactly that SIGTERM).

With ``--event-log`` (or a supervisor-exported run dir, resolved via
``observe.runlog.shard_event_log_from_env``) the worker also emits real
telemetry into its per-rank shard: the auto run-start marker, one
CollectiveEvent (the toy "wire ledger" — a fixed per-step payload), one
CompileEvent carrying the toy cost model (fixed FLOPs/step + a made-up
peak, for the report's MFU join), a timed StepEvent per step, and nested
SpanEvents (``step`` > ``step/compute`` / ``checkpoint/save``) — what the
run-level merger, straggler detector, bandwidth estimator, MFU
accounting, and trace export consume in tests.

With ``--sim-fabric`` the worker also sleeps the modeled allreduce wall
time of the active comm rung's payload (``--rung`` / ``--payload-mult``)
in a ``step/comm`` span each step — the measured step then responds to
comm configs, which is what lets run_probe exercise the offline what-if
planner (``observe.costmodel``) end-to-end against realized times.

Usage::

    python toy_supervised_worker.py --rank R --world W --steps N \
        --state-dir D --result-dir D [--heartbeat-dir D] [--chaos-plan F] \
        [--step-seconds S] [--graceful-term] [--event-log F]
"""

import argparse
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from network_distributed_pytorch_tpu.resilience.chaos import (  # noqa: E402
    CHAOS_EXIT_CODE,
    CKPT_UNWRITABLE_EXIT_CODE,
    CORRELATED_FAULTS,
    HEALTH_FAULTS,
    LOADER_FAULTS,
    MEMORY_FAULTS,
    PREEMPT_EXIT_CODE,
    PROCESS_FAULTS,
    ChaosPlan,
    CommFaultInjector,
)
from network_distributed_pytorch_tpu.observe import (  # noqa: E402
    CollectiveEvent,
    CompileEvent,
    FailureEvent,
    MemoryEvent,
    StepEvent,
    TrainHealthEvent,
    recording,
    span,
    telemetry_for_run,
)
from network_distributed_pytorch_tpu.observe.fidelity import (  # noqa: E402
    FidelityTracker,
)
from network_distributed_pytorch_tpu.observe.memory import (  # noqa: E402
    OOM_REPORT_NAME,
    build_oom_report,
    write_oom_report,
)
from network_distributed_pytorch_tpu.observe.live import AlertFeed  # noqa: E402
from network_distributed_pytorch_tpu.observe.runlog import (  # noqa: E402
    ENV_RUN_DIR,
    shard_event_log_from_env,
)
from network_distributed_pytorch_tpu.resilience.guards import (  # noqa: E402
    CommEscalationError,
    OuterSyncDriver,
    PartitionPolicy,
)
from network_distributed_pytorch_tpu.resilience.supervisor import (  # noqa: E402
    incarnation_from_env,
)

# the toy "wire ledger": a fixed per-step all-reduce payload, so the
# bandwidth estimator has real bytes to join with measured step times
TOY_PAYLOAD_BYTES = 1 << 20
# the toy "cost model": a fixed analytic FLOPs count and a made-up peak for
# the simulated device, so the report's MFU join and roofline verdict have
# real numbers to work from (the one collective is fully exposed -> the
# steady-state window classifies comm-exposed)
TOY_FLOPS_PER_STEP = 2.0e9
TOY_PEAK_FLOPS = 1e12
TOY_DEVICE_KIND = "toy-sim"
# --comm-flap: the simulated fabric flap lasts this many steps (each
# sleeping FLAP_SLOWDOWN x the nominal step), and the real
# FallbackController is fed one EpochHealth per EPOCH_LEN steps — small
# enough that a 16-step probe sees the full descend -> ascend cycle
FLAP_LEN = 4
FLAP_SLOWDOWN = 5.0
EPOCH_LEN = 4
# the toy compressed rung's ledger: rank-1 toy compression of the payload
TOY_COMPRESSED_BYTES = TOY_PAYLOAD_BYTES // 8
# --sim-fabric / --rung: the toy comm configs a planner replay can force.
# Each entry is (compression divisor of the payload, sync_every,
# n_collectives, the CompileEvent comm_config) — byte-compatible with the
# DEFAULT_LADDER rungs the offline cost model prices (compress is the toy
# rank-1 compression = the ladder's "compress-low-rank" knobs; localsgd
# widens the sync period like the ladder's "localsgd" rung). The simulated
# allreduce sleep is amortized (comm/sync_every every step) so each step is
# identical and the report's p50 equals the modeled mean.
TOY_RUNG_SPECS = {
    "baseline": (1, 1, 1, {"reducer": "exact"}),
    "compress": (8, 1, 2, {"reducer": "powersgd", "reducer_rank": 1}),
    "localsgd": (
        8, 8, 2,
        {"reducer": "powersgd", "reducer_rank": 1, "sync_every": 8},
    ),
    # the two-level geo rung (byte-compatible with the ladder's
    # "hierarchical-async" knobs): dense inner reduction every step on
    # the fast in-node fabric, rank-1-compressed outer reduction across
    # TOY_SITES every sync_every steps on --sim-fabric, outer sync
    # hidden behind the next round's compute (outer_async). The divisor
    # here compresses only the OUTER payload — the inner level stays
    # dense, which is the whole point of the hierarchy.
    "hierarchical": (
        8, 8, 2,
        {
            "reducer": "hierarchical", "reducer_rank": 1,
            "sync_every": 8, "outer_async": 1,
        },
    ),
}
# the toy geo topology: two sites, ring-split down the middle; the
# cross-site edge the partition game day cuts is (inner_world-1, inner_world)
TOY_SITES = 2
# the toy inner fabric: the fast in-node level the hierarchical rung's
# per-step dense reduction is priced on, regardless of --sim-fabric
TOY_INNER_FABRIC = "ICI(v5e)"
# --health-every: the synthetic grad norm baseline — near-constant, so the
# live plane's EWMA spike detector has an almost-zero-variance envelope and
# a chaos ``grad_spike`` (factor 1000 by default) is unambiguously critical
TOY_GRAD_NORM = 1.0
# --fidelity-groups: the toy fidelity plane's clean per-group baselines. A
# flat rel_error well UNDER the FidelityCollapseDetector's absolute floor
# (0.05), so the clean run never pages; a chaos ``fidelity_degrade``
# (factor 1000) lifts one group to 20 — unambiguously over both the floor
# and 3x the learned envelope. The EF norm is a flat nonzero baseline so
# the EfBlowupDetector has a real (non-dead-zero) envelope to learn.
TOY_FIDELITY_REL_ERROR = 0.02
TOY_FIDELITY_EF_NORM = 0.1
# the toy memory plane: a made-up HBM limit and a compile-time footprint
# split (the CompileEvent fields observe.memory would attach on a real
# backend), both scaled by --hbm-mult so a probe can "double the model" and
# watch the hbm_peak_bytes gate trip. Synthetic MemoryEvents ramp
# bytes_in_use from 50% of the limit toward 97% per health sample, so the
# supervisor-side HbmHeadroomDetector's EWMA crosses its warn threshold
# within ~7 samples — the OOM-precursor alert the memory game day asserts
# fires BEFORE the injected ``oom`` fault kills the rank
TOY_HBM_LIMIT = float(1 << 30)
TOY_FOOTPRINT = {
    "argument_bytes": 0.30 * TOY_HBM_LIMIT,
    "output_bytes": 0.05 * TOY_HBM_LIMIT,
    "temp_bytes": 0.25 * TOY_HBM_LIMIT,
    "generated_code_bytes": 0.02 * TOY_HBM_LIMIT,
}
# the OOM post-mortem's toy buffer-class attribution (fractions of the
# limit): params dominate, so the report's top_buffer names "params"
TOY_BUFFER_FRACS = {
    "params": 0.45,
    "ef_memory": 0.20,
    "activations_temp": 0.15,
    "serving_slots": 0.10,
}


def _load_state(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"step": 0, "value": 0}


def _save_state(path, state):
    # the toy fail-fast contract, mirroring experiments/common.py's
    # _commit_save: a persistently unwritable state path exits with the
    # CKPT_UNWRITABLE sentinel after a short retry budget, so the
    # supervisor fails the run fast instead of feeding a restart storm
    last = None
    for attempt in range(2):
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(state, f)
            os.replace(tmp, path)
            return
        except OSError as e:
            last = e
            time.sleep(0.02 * (attempt + 1))
    sys.stderr.write(f"toy worker: state unwritable after retries: {last}\n")
    os._exit(CKPT_UNWRITABLE_EXIT_CODE)


def _beat(directory, rank, incarnation, step):
    path = os.path.join(directory, f"heartbeat_{rank}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(
            {"process_id": rank, "incarnation": incarnation,
             "ts": time.time(), "step": step},
            f,
        )
    os.replace(tmp, path)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--rank", type=int, required=True)
    p.add_argument("--world", type=int, required=True)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--state-dir", required=True)
    p.add_argument("--result-dir", required=True)
    p.add_argument("--heartbeat-dir", default=None)
    p.add_argument("--chaos-plan", default=None)
    p.add_argument("--step-seconds", type=float, default=0.01)
    p.add_argument("--graceful-term", action="store_true")
    p.add_argument("--event-log", default=None)
    p.add_argument(
        "--comm-flap", type=int, default=None, metavar="STEP",
        help="simulate a transient fabric flap starting at this step"
             " (FLAP_LEN steps at FLAP_SLOWDOWN x step time) and drive a"
             " real FallbackController from measured pseudo-epoch health —"
             " the comm-layer PolicyEvent round-trip, jax-free",
    )
    p.add_argument(
        "--sim-fabric", default=None, metavar="FABRIC",
        choices=("1GbE", "10GbE", "100GbE", "ICI(v5e)"),
        help="sleep the modeled ring-allreduce wire time"
             " (utils.bandwidth.allreduce_time_s) of the active rung's"
             " payload on this fabric every step, in its own step/comm"
             " span — what makes the toy's measured step respond to comm"
             " configs so the offline planner's predictions are testable"
             " end-to-end, jax-free",
    )
    p.add_argument(
        "--payload-mult", type=int, default=1, metavar="K",
        help="scale the toy wire payload (and its compressed rung) by K —"
             " larger payloads separate simulated comm time from sleep"
             " jitter on slow fabrics",
    )
    p.add_argument(
        "--rung", default="baseline", choices=sorted(TOY_RUNG_SPECS),
        help="force the toy comm rung (payload compression + sync period +"
             " CompileEvent comm_config) — how a planner replay executes"
             " the predicted-best config; a --comm-flap controller"
             " overrides it per-step",
    )
    p.add_argument(
        "--max-local-steps", type=int, default=64, metavar="N",
        help="divergence budget of the hierarchical rung: site-local"
             " steps a cross-site partition may accrue before the toy"
             " escalates (CommEscalationError -> chaos exit), mirroring"
             " resilience.guards.PartitionPolicy's contract",
    )
    p.add_argument(
        "--hbm-mult", type=float, default=1.0, metavar="X",
        help="scale the toy HBM limit, compile-time footprint, and live"
             " memory ramp by X — the memory observatory's \"double the"
             " model\" knob: a 2.0 run against a 1.0 baseline must trip"
             " the hbm_peak_bytes gate",
    )
    p.add_argument(
        "--health-every", type=int, default=0, metavar="N",
        help="emit a synthetic TrainHealthEvent every N steps (0 = never);"
             " a chaos grad_spike fault multiplies the reading by its"
             " factor payload, and under a supervisor run dir the worker"
             " also tails alerts.jsonl each step and feeds every alert to"
             " a real FallbackController.nudge — the live plane's"
             " detector -> supervisor -> worker round-trip, jax-free."
             " The same cadence emits a synthetic MemoryEvent whose"
             " bytes_in_use ramps toward the toy HBM limit (the headroom"
             " detector's OOM-precursor feed)",
    )
    p.add_argument(
        "--fidelity-groups", type=int, default=0, metavar="K",
        help="emit K toy fidelity groups (toy.grads.b0..b{K-1}) per"
             " --health-every sample, with matching per-bucket"
             " CollectiveEvents so every FidelityEvent tag is byte-priced"
             " by the toy wire ledger (the ledger<->fidelity join). A"
             " chaos fidelity_degrade fault multiplies the NAMED group's"
             " rel_error by its factor payload from its step onward (a"
             " standing degradation, like a genuinely broken bucket) —"
             " the phase-13 game-day feed",
    )
    p.add_argument(
        "--controller-start", type=int, default=0, metavar="I",
        help="start the toy FallbackController at ladder index I instead"
             " of 0 — the phase-13 game day starts at the compress rung so"
             " a fidelity_collapse alert has a higher-fidelity rung to"
             " ascend TO",
    )
    args = p.parse_args()

    incarnation = incarnation_from_env()
    plan = ChaosPlan.load(args.chaos_plan) if args.chaos_plan else ChaosPlan()
    os.makedirs(args.state_dir, exist_ok=True)
    os.makedirs(args.result_dir, exist_ok=True)
    if args.heartbeat_dir:
        os.makedirs(args.heartbeat_dir, exist_ok=True)

    state_path = os.path.join(args.state_dir, f"rank{args.rank}.json")
    state = _load_state(state_path)

    # the toy wire payload, scaled by --payload-mult, and the forced rung's
    # compression/sync/comm_config (the --comm-flap controller below takes
    # over rung selection per-step when present)
    payload_bytes = TOY_PAYLOAD_BYTES * max(1, args.payload_mult)
    divisor, sync_every, n_coll, comm_config = TOY_RUNG_SPECS[args.rung]
    rung_bytes_now = payload_bytes // divisor

    # the two-level rung's per-step wire accounting: the dense inner
    # reduction runs every step AND once more inside each sync round; only
    # the compressed outer payload (rung_bytes_now) crosses the slow edge,
    # amortized over the sync period — the per-level split the report's
    # hierarchy section and the cost model's predicted_outer_bytes join on
    hier = args.rung == "hierarchical"
    outer_async = bool(comm_config.get("outer_async"))
    inner_world = max(1, args.world // TOY_SITES)
    if hier:
        inner_sync_bytes = payload_bytes // sync_every
        outer_step_bytes = rung_bytes_now // sync_every
        total_step_bytes = payload_bytes + inner_sync_bytes + outer_step_bytes
    else:
        total_step_bytes = rung_bytes_now

    # the toy memory plane, scaled as one unit: limit, footprint, and the
    # live ramp all follow --hbm-mult (occupancy FRACTIONS are invariant,
    # so the headroom detector behaves identically at any scale)
    hbm_mult = max(args.hbm_mult, 1e-9)
    hbm_limit = TOY_HBM_LIMIT * hbm_mult
    footprint = {k: v * hbm_mult for k, v in TOY_FOOTPRINT.items()}
    footprint["peak_hbm_bytes"] = sum(footprint.values())
    last_memory = None
    peak_in_use = 0.0

    # per-rank telemetry shard: explicit --event-log wins, else the
    # supervisor-exported run dir (run_start marker auto-emitted from env)
    event_log = args.event_log or shard_event_log_from_env()
    telemetry = (
        telemetry_for_run(event_log=event_log, stdout=False)
        if event_log else None
    )
    if telemetry is not None:
        if hier:
            # the per-level toy ledger, tags matching the real
            # HierarchicalReducer's tag_scope prefixes: the per-step
            # inner DDP reduction, the sync round's inner phase
            # (amortized), and the compressed cross-site outer payload
            # (amortized) — what hierarchy_summary splits per level
            for tag, axis, b in (
                ("inner.step_grads", "ici", payload_bytes),
                ("inner.grads", "ici", inner_sync_bytes),
                ("outer.grads", "dcn", outer_step_bytes),
            ):
                telemetry.emit(
                    CollectiveEvent(
                        label="toy", tag=tag, layer="reducer",
                        op="all-reduce", axis=axis, dtype="float32",
                        payload_bytes=b,
                    )
                )
        elif args.fidelity_groups > 0:
            # the bucketed toy wire: one CollectiveEvent per fidelity
            # group, so every FidelityEvent tag below is byte-priced by
            # the same ledger (the ledger<->fidelity join the phase-13
            # game day and test_fidelity assert on). Bytes split evenly
            # with the remainder on the last bucket, summing exactly to
            # the active rung's payload.
            n_g = args.fidelity_groups
            base_b = rung_bytes_now // n_g
            for k in range(n_g):
                b = base_b if k < n_g - 1 else rung_bytes_now - base_b * (
                    n_g - 1
                )
                telemetry.emit(
                    CollectiveEvent(
                        label="toy", tag=f"toy.grads.b{k}", layer="reducer",
                        op="all-reduce", axis="data", dtype="float32",
                        payload_bytes=b,
                    )
                )
        else:
            telemetry.emit(
                CollectiveEvent(
                    label="toy", tag="toy.grads", layer="reducer",
                    op="all-reduce", axis="data", dtype="float32",
                    payload_bytes=rung_bytes_now,
                )
            )
        # the toy compile verdict: byte-exact by fiat, one fully-exposed
        # collective, the cost fields observe.mfu joins at report time, and
        # the active rung's comm_config so the cost-model observatory can
        # identify WHICH config this run executed (join_realized)
        n_hlo_coll = 3 if hier else max(1, args.fidelity_groups)
        telemetry.emit(
            CompileEvent(
                label="toy",
                analytic_bytes=total_step_bytes,
                hlo_bytes=total_step_bytes,
                delta_bytes=0,
                exact=True,
                hlo_collective_count=n_hlo_coll,
                hlo_by_kind={"all-reduce": n_hlo_coll},
                overlap={
                    "scheduled": True,
                    "n_sync_collectives": n_hlo_coll,
                    "n_sync_gaps_with_compute": 0,
                },
                flops_per_step=TOY_FLOPS_PER_STEP,
                flops_source="analytic",
                device_kind=TOY_DEVICE_KIND,
                peak_flops_per_s=TOY_PEAK_FLOPS,
                # the toy compile-time HBM footprint: what
                # observe.memory.memory_footprint_fields attaches on a
                # real backend, byte-exact by fiat — the predicted side of
                # the report's memory join, jax-free
                **footprint,
                dense_grad_bytes=payload_bytes if hier else None,
                comm_config=dict(comm_config),
            )
        )

    # the comm-hook face of the chaos plan: pops COMM_FAULTS once per step
    # in advance(). The toy has no real fence hooks (jax-free), so the
    # simulated wire below adds the injector's modeled host-side sleep
    # inside the step/comm span — a comm_slow_edge on this rank's outgoing
    # ring link grows exactly the span the critical-path analyzer charges
    # to that edge (run_probe phase 8 asserts the blame end to end).
    comm_chaos = CommFaultInjector(
        plan, rank=args.rank, incarnation=incarnation, telemetry=telemetry
    )

    # the geo-resilient control plane of the hierarchical rung: the real
    # PartitionPolicy/OuterSyncDriver (not a toy copy) route each outer
    # round — a comm_partition fault degrades rounds to site-local, each
    # one charging the --max-local-steps divergence budget, and the heal
    # rejoins via note_sync. Budget exhaustion escalates exactly like the
    # jax loop: CommEscalationError -> chaos exit.
    outer_driver = None
    if hier:
        outer_driver = OuterSyncDriver(
            PartitionPolicy(
                max_local_steps=args.max_local_steps,
                telemetry=telemetry,
                rank=args.rank,
                incarnation=incarnation,
            ),
            probes=(lambda: comm_chaos.partitioned,),
            edge_probe=lambda: comm_chaos.partition_edge,
        )

    flap = args.comm_flap
    run_dir = os.environ.get(ENV_RUN_DIR)
    # the alert feed tails the supervisor's alerts.jsonl; only meaningful
    # under a supervised run dir and with the health sampler on
    alert_feed = (
        AlertFeed(run_dir) if args.health_every > 0 and run_dir else None
    )
    controller = None
    if flap is not None or alert_feed is not None:
        from network_distributed_pytorch_tpu.resilience.controller import (
            EpochHealth,
            FallbackController,
            Rung,
        )

        # two toy rungs are enough for the round-trip; recover_factor is
        # loose (0.6) so checkpoint-save jitter on a loaded CI box cannot
        # turn a genuinely healthy pseudo-epoch indeterminate
        controller = FallbackController(
            ladder=[
                Rung("baseline", {}),
                Rung("compress", {"reducer": "powersgd", "reducer_rank": 1}),
            ],
            descend_after=1, recover_factor=0.6,
            # when the phase-13 game day pins the start rung, ordinary
            # throughput recovery is disabled: the ONLY way back up the
            # ladder is a fidelity-alert nudge, which is exactly the
            # isolation the game day asserts on (otherwise a "recovered"
            # ascend at the first epoch boundary would vacate the rung
            # before the injected fault's alert could claim the credit)
            recover_after=(10 ** 6 if args.controller_start > 0 else 2),
            telemetry=telemetry, rank=args.rank,
            # the phase-13 game day starts on the compress rung so a
            # fidelity alert has somewhere to ascend TO
            start_index=max(0, min(args.controller_start, 1)),
        )
        epoch_times = []
        epoch_degraded = 0
        pseudo_epoch = 0

    def _rung_bytes(index):
        return payload_bytes if index == 0 else payload_bytes // 8

    # the toy fidelity plane: one group per --fidelity-groups bucket, each
    # group key identical to the toy.grads.b{k} ledger tag emitted above
    # (identity tag map — the toy wire is its own join). A fidelity_degrade
    # chaos fault LATCHES a multiplier onto its named group: a genuinely
    # broken bucket stays broken, so the supervisor's sustain-2 collapse
    # detector sees consecutive degraded samples from a single injection.
    fid_degrade = {}
    fid_tracker = None
    if args.fidelity_groups > 0 and telemetry is not None:
        groups = [f"toy.grads.b{k}" for k in range(args.fidelity_groups)]
        fid_tracker = FidelityTracker(
            {g: g for g in groups}, rank=args.rank, label="toy"
        )

    # simulated comm plane (--sim-fabric): the modeled allreduce wall time
    # of the active rung's payload, amortized over the rung's sync period.
    # Computed lazily per step because a --comm-flap controller can switch
    # rungs mid-run.
    def _comm_sleep_s():
        if args.sim_fabric is None:
            return 0.0
        from network_distributed_pytorch_tpu.utils.bandwidth import (
            allreduce_time_s,
        )

        if hier and controller is None:
            # two-level wire model, mirroring the cost model's pricing:
            # the dense inner reduction (per step + the sync round's
            # phase) on the fast in-node fabric; the compressed outer
            # payload on --sim-fabric across the site leaders, slowed by
            # any active cross-site throttle, skipped entirely while the
            # edge is partitioned (site-local round), and hidden behind
            # the round's compute window when the outer loop is async
            inner_s = allreduce_time_s(
                payload_bytes, inner_world, TOY_INNER_FABRIC
            ) * (1.0 + 1.0 / sync_every)
            if outer_driver is not None and comm_chaos.partitioned:
                return inner_s
            outer_s = allreduce_time_s(
                rung_bytes_now, TOY_SITES, args.sim_fabric,
                n_collectives=n_coll,
            )
            outer_s += comm_chaos.host_throttle_sleep_s(rung_bytes_now)
            if outer_async:
                window = sync_every * (args.step_seconds + inner_s)
                outer_s = max(0.0, outer_s - window)
            return inner_s + outer_s / sync_every
        if controller is not None:
            b, sync, nc = _rung_bytes(controller.index), 1, (
                1 if controller.index == 0 else 2
            )
        else:
            b, sync, nc = rung_bytes_now, sync_every, n_coll
        return allreduce_time_s(
            b, args.world, args.sim_fabric, n_collectives=nc
        ) / sync

    if args.graceful_term:
        # the PreemptionGuard contract, toy-sized: SIGTERM -> persist the
        # current state, exit with the sentinel the supervisor classifies
        # as a graceful death
        def _on_term(signum, frame):
            _save_state(state_path, state)
            os._exit(PREEMPT_EXIT_CODE)

        signal.signal(signal.SIGTERM, _on_term)

    # open loader timing-fault window (see the data_load injection below)
    loader_slow = {"left": 0, "total": 0, "delay_s": 0.0, "ramp": False}

    with recording(telemetry):
        while state["step"] < args.steps:
            i = state["step"]
            if args.heartbeat_dir:
                _beat(args.heartbeat_dir, args.rank, incarnation, i)
            comm_chaos.advance(i)
            spec = plan.pop(
                PROCESS_FAULTS + CORRELATED_FAULTS, i, args.rank, incarnation
            )
            if spec is not None:
                if spec.kind == "proc_exit":
                    os._exit(int(spec.payload.get("exit_code", 43)))
                if spec.kind in ("proc_kill", "zone_outage"):
                    # zone_outage: every rank in payload["ranks"] loads its
                    # own plan copy, so one spec kills the whole zone
                    os.kill(os.getpid(), signal.SIGKILL)
                if spec.kind == "host_flap":
                    # a flapping host dies hard on each of its first
                    # ``flaps`` incarnations, then stays up — the
                    # independent-death path that burns restart budget
                    if incarnation < int(spec.payload.get("flaps", 2)):
                        os.kill(os.getpid(), signal.SIGKILL)
                if spec.kind == "proc_hang":
                    time.sleep(float(spec.payload.get("hang_seconds", 3600.0)))
                if spec.kind == "proc_preempt":
                    os.kill(os.getpid(), signal.SIGTERM)
            spec = plan.pop(MEMORY_FAULTS, i, args.rank, incarnation)
            if spec is not None and spec.kind == "oom":
                # the toy allocator death, forensics-first like the real
                # GuardedStep trap: write the ranked post-mortem (into the
                # supervised run dir's artifacts/ when present), emit the
                # detection event, then die with the chaos sentinel — an
                # OOM is never retried in place
                want = int(spec.payload.get("bytes", hbm_limit))
                report = build_oom_report(
                    error=(
                        f"RESOURCE_EXHAUSTED: Out of memory while trying"
                        f" to allocate {want} bytes (injected at step {i},"
                        f" rank {args.rank})"
                    ),
                    label="toy",
                    rank=args.rank,
                    step=i,
                    last_memory=(
                        last_memory.record() if last_memory else None
                    ),
                    footprint=footprint,
                    buffers={
                        name: frac * hbm_limit
                        for name, frac in TOY_BUFFER_FRACS.items()
                    },
                )
                base_dir = run_dir or args.result_dir
                path = os.path.join(base_dir, "artifacts", OOM_REPORT_NAME)
                write_oom_report(report, path)
                if telemetry is not None:
                    telemetry.emit(
                        FailureEvent(
                            kind="oom", label="toy", rank=args.rank,
                            step=i, incarnation=incarnation,
                            message=(
                                f"device out of memory (top buffer:"
                                f" {report['top_buffer']}; forensics:"
                                f" {path})"
                            ),
                        )
                    )
                    telemetry.close()
                os._exit(CHAOS_EXIT_CODE)
            in_flap = flap is not None and flap <= i < flap + FLAP_LEN
            if flap is not None and telemetry is not None:
                if i == flap:
                    telemetry.emit(
                        FailureEvent(
                            kind="chaos_injected", label="comm_flap",
                            message=f"toy fabric flap: {FLAP_LEN} steps at"
                                    f" {FLAP_SLOWDOWN:g}x step time",
                            rank=args.rank, step=i, incarnation=incarnation,
                        )
                    )
                elif i == flap + FLAP_LEN:
                    telemetry.emit(
                        FailureEvent(
                            kind="comm_fault_cleared", label="comm_flap",
                            rank=args.rank, step=i, incarnation=incarnation,
                        )
                    )
            # loader timing faults (loader_slow_shard / loader_skewed_shard):
            # the toy data plane is a sleep, but the CONTRACT is the real
            # one — the delay lands inside the step's data_load span, the
            # step time absorbs it, and the merged report's straggler
            # detector must name this rank from p50s alone (run_probe
            # phase 6 asserts exactly that, jax-free)
            spec = plan.pop(LOADER_FAULTS, i, args.rank, incarnation)
            if spec is not None and spec.kind in (
                "loader_slow_shard", "loader_skewed_shard"
            ):
                loader_slow["left"] = max(1, int(spec.payload.get("batches", 8)))
                loader_slow["total"] = loader_slow["left"]
                loader_slow["delay_s"] = float(spec.payload.get("delay_s", 0.05))
                loader_slow["ramp"] = spec.kind == "loader_skewed_shard"
            t0 = time.monotonic()
            # nested spans, toy-sized like the real loop's: the trace export
            # e2e asserts this parent/child structure survives the merge
            with span("step", step=i, rank=args.rank):
                if loader_slow["left"] > 0:
                    k = loader_slow["total"] - loader_slow["left"]
                    delay = loader_slow["delay_s"]
                    if loader_slow["ramp"]:
                        delay *= (k + 1) / loader_slow["total"]
                    loader_slow["left"] -= 1
                    with span("data_load", step=i, rank=args.rank):
                        time.sleep(delay)
                with span("step/compute", step=i, rank=args.rank):
                    time.sleep(
                        args.step_seconds * (FLAP_SLOWDOWN if in_flap else 1.0)
                    )
                # the simulated wire time lives OUTSIDE step/compute so the
                # cost model's compute calibration (the step/compute span
                # mean) stays comm-free, exactly like a non-jitted loop
                comm_s = _comm_sleep_s()
                # active per-edge throttle: the modeled extra wire time the
                # fence hook would have injected, paid on the host here
                # (the hierarchical path already folds it into the outer
                # sync inside _comm_sleep_s, where async overlap and
                # partition skipping apply to it)
                if not hier:
                    comm_s += comm_chaos.host_throttle_sleep_s(rung_bytes_now)
                if comm_s > 0:
                    with span("step/comm", step=i, rank=args.rank):
                        time.sleep(comm_s)
                state = {"step": i + 1, "value": state["value"] + args.world}
                with span("checkpoint/save", step=i, rank=args.rank):
                    _save_state(state_path, state)
            step_time = time.monotonic() - t0
            if in_flap and telemetry is not None:
                # the detection the real loop's watchdog would emit —
                # BEFORE the StepEvent, so the step's window contains it
                # and the report's recovery-latency clock keeps running
                telemetry.emit(
                    FailureEvent(
                        kind="comm_degraded", label="comm_flap",
                        rank=args.rank, step=i, incarnation=incarnation,
                    )
                )
            if outer_driver is not None and (i + 1) % sync_every == 0:
                # end of an outer round: route the cross-site sync through
                # the real driver — partitioned rounds degrade to
                # site-local (typed "local" event, budget charged), the
                # first healthy round after the heal is the rejoin
                if outer_driver.should_sync(step=i):
                    outer_driver.note_sync(step=i)
                else:
                    try:
                        outer_driver.note_local(sync_every, step=i)
                    except CommEscalationError as e:
                        if telemetry is not None:
                            telemetry.emit(
                                FailureEvent(
                                    kind="comm_escalation", label="toy",
                                    rank=args.rank, step=i,
                                    incarnation=incarnation,
                                    message=str(e),
                                )
                            )
                            telemetry.close()
                        os._exit(CHAOS_EXIT_CODE)
            if telemetry is not None:
                telemetry.emit(
                    StepEvent(
                        step=i, epoch=i // EPOCH_LEN, loss=1.0 / (i + 1),
                        step_time_s=step_time,
                        bits_cumulative=8 * total_step_bytes * (i + 1),
                    )
                )
            if (
                args.health_every > 0
                and telemetry is not None
                and i % args.health_every == 0
            ):
                # synthetic health sample: a flat grad-norm baseline the
                # spike detector can learn in 3 observations; the chaos
                # grad_spike fault multiplies the reading at its step,
                # while fidelity_degrade latches a rel_error multiplier
                # onto its named group from this step onward
                grad_norm = TOY_GRAD_NORM
                spec = plan.pop(HEALTH_FAULTS, i, args.rank, incarnation)
                if spec is not None:
                    if spec.kind == "fidelity_degrade":
                        fid_degrade[
                            str(spec.payload.get("group", "toy.grads.b0"))
                        ] = float(spec.payload.get("factor", 1000.0))
                    else:
                        grad_norm *= float(
                            spec.payload.get("factor", 1000.0)
                        )
                telemetry.emit(
                    TrainHealthEvent(
                        step=i, epoch=i // EPOCH_LEN, grad_norm=grad_norm,
                        ef_memory_norm=0.0, powersgd_rel_error=0.0,
                        loss=1.0 / (i + 1), rank=args.rank, label="toy",
                    )
                )
                if fid_tracker is not None:
                    # flat clean baseline well under the detector's 0.05
                    # absolute floor; a degraded group jumps to 20 —
                    # unambiguous blame at a single group key
                    stats = {}
                    for g in groups:
                        rel = TOY_FIDELITY_REL_ERROR * fid_degrade.get(
                            g, 1.0
                        )
                        stats[g] = {
                            "rel_error": rel,
                            "cosine_sim": max(0.0, 1.0 - rel),
                            "ef_norm": TOY_FIDELITY_EF_NORM,
                            "quantized_share": 0.0,
                        }
                    for ev in fid_tracker.events(
                        i, stats, epoch=i // EPOCH_LEN
                    ):
                        telemetry.emit(ev)
                # the synthetic memory ramp: occupancy climbs 50% -> 97%
                # of the toy limit, one rung per health sample, so the
                # supervisor's HbmHeadroomDetector EWMA crosses warn
                # within ~7 samples — the OOM precursor
                k = i // args.health_every
                in_use = hbm_limit * min(0.97, 0.5 + 0.2 * k)
                peak_in_use = max(peak_in_use, in_use)
                last_memory = MemoryEvent(
                    step=i,
                    bytes_in_use=in_use,
                    peak_bytes_in_use=peak_in_use,
                    bytes_limit=hbm_limit,
                    device_kind=TOY_DEVICE_KIND,
                    rank=args.rank,
                    label="toy",
                )
                telemetry.emit(last_memory)
            if alert_feed is not None and controller is not None:
                # the return leg of the live plane: detector alerts the
                # supervisor appended to alerts.jsonl nudge the controller
                # mid-pseudo-epoch, exactly like adaptive_train_loop
                for rec in alert_feed.poll():
                    decision = controller.nudge(
                        rec.get("alert", ""), pseudo_epoch,
                        severity=rec.get("severity", "warn"),
                    )
                    if decision is not None:
                        controller.record(
                            decision,
                            predicted_bytes_per_step=_rung_bytes(
                                decision.rung_index_after
                            ),
                            realized_bytes_per_step=_rung_bytes(
                                decision.rung_index_before
                            ),
                        )
            if controller is not None:
                epoch_times.append(step_time)
                if in_flap:
                    epoch_degraded += 1
                if len(epoch_times) == EPOCH_LEN:
                    p50 = sorted(epoch_times)[len(epoch_times) // 2]
                    bytes_per_step = _rung_bytes(controller.index)
                    decision = controller.observe(
                        EpochHealth(
                            epoch=pseudo_epoch, step_p50_s=p50,
                            achieved_bytes_per_s=(
                                bytes_per_step / p50 if p50 > 0 else 0.0
                            ),
                            degraded_steps=epoch_degraded,
                        )
                    )
                    if decision is not None:
                        controller.record(
                            decision,
                            predicted_bytes_per_step=_rung_bytes(
                                decision.rung_index_after
                            ),
                            realized_bytes_per_step=_rung_bytes(
                                decision.rung_index_before
                            ),
                        )
                    epoch_times = []
                    epoch_degraded = 0
                    pseudo_epoch += 1

    if telemetry is not None:
        telemetry.close()
    with open(
        os.path.join(args.result_dir, f"rank{args.rank}.json"), "w"
    ) as f:
        json.dump(
            {"rank": args.rank, "world": args.world,
             "incarnation": incarnation, **state},
            f,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
