"""Jax-free toy SERVING worker for fail-over mechanics (run as subprocess).

Simulates one rank of a spool-serving fleet without importing jax (so a
supervised restart costs milliseconds): a :class:`ToyEngine` implements
the exact duck-typed engine protocol ``serving.frontend.serve_from_spool``
drives (``submit / step / take_finished / idle / n_slots / queue_len``)
with a deterministic token function in place of the GPT decoder — each
generated token depends only on the request itself, so a request that
dies mid-decode on one rank and is re-queued decodes the SAME tokens on
the survivor (what the probe's completion-record check relies on).

The spool protocol, the request lifecycle, the terminal
``observe.RequestEvent`` telemetry, and the orphan re-queue rules are all
the REAL ``serving/`` code — only the model is toy.

``--die-after-claims N`` makes the worker SIGKILL itself (incarnation 0
only) right after a decode tick once it has admitted >= N requests and
still holds some in flight — a mid-decode rank death with unreleased
spool claims, the scenario ``scripts/run_probe.py`` phase 3 supervises.

Usage::

    python toy_serving_worker.py --rank R --world W --spool-dir D \
        --result-dir D [--slots 2] [--step-seconds S] \
        [--die-after-claims N] [--max-wall-s S]
"""

import argparse
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from network_distributed_pytorch_tpu.observe import (  # noqa: E402
    telemetry_for_run,
)
from network_distributed_pytorch_tpu.observe.runlog import (  # noqa: E402
    shard_event_log_from_env,
)
from network_distributed_pytorch_tpu.resilience.supervisor import (  # noqa: E402
    incarnation_from_env,
)
from network_distributed_pytorch_tpu.serving import (  # noqa: E402
    FileSpool,
    Request,
    serve_from_spool,
)

TOY_VOCAB = 64


def toy_token(request: Request) -> int:
    """Deterministic next token: a pure function of the request's own
    prompt and progress, never of batch-mates or the serving rank — so
    fail-over to another rank reproduces identical completions."""
    return (sum(request.prompt) + 7 * len(request.tokens)) % TOY_VOCAB


class ToyEngine:
    """The SlotEngine's host-side scheduling, with :func:`toy_token` in
    place of the compiled decode step (same backfill-then-tick order, same
    lifecycle transitions, same terminal RequestEvents).

    With ``pool`` set (a real ``serving.blocks.BlockPool``), admission is
    gated by the PAGED allocator: a request only enters a slot when its
    whole decode horizon's KV blocks can be granted, admission stops at
    the first out-of-blocks request (strict FIFO backpressure, same rule
    as ``PagedEngine``), blocks are returned exactly once on finish, and
    the refcount-leak invariant is asserted after every tick — the paged
    bookkeeping under storm load, minus the model."""

    def __init__(self, n_slots, telemetry=None, rank=None,
                 step_seconds=0.0, label="toy_serving",
                 pool=None, block_len=4):
        self.n_slots = n_slots
        self.telemetry = telemetry
        self.rank = rank
        self.step_seconds = step_seconds
        self.label = label
        self.slots = [None] * n_slots
        self.chains = [None] * n_slots  # paged mode: per-slot block chain
        self.pool = pool
        self.block_len = block_len
        self.queue = []
        self._finished = []
        self.submits = 0
        self.decode_steps = 0
        self.prefills = 0
        self.admissions_deferred = 0

    def submit(self, request):
        request.mark_enqueued(time.monotonic())
        self.queue.append(request)
        self.submits += 1

    @property
    def n_active(self):
        return sum(1 for s in self.slots if s is not None)

    @property
    def queue_len(self):
        return len(self.queue)

    @property
    def idle(self):
        return not self.queue and self.n_active == 0

    def take_finished(self):
        out, self._finished = self._finished, []
        return out

    def _terminal(self, request):
        if self.telemetry is not None:
            self.telemetry.emit(
                request.event(label=self.label, rank=self.rank)
            )
        self._finished.append(request)

    def _admit_blocks(self, r):
        """Paged admission gate: all-or-nothing alloc for the request's
        whole horizon. Returns the chain, or None on out-of-blocks."""
        if self.pool is None:
            return []
        from network_distributed_pytorch_tpu.serving.blocks import (
            OutOfBlocks, blocks_needed,
        )

        need = blocks_needed(
            len(r.prompt) + r.max_new_tokens, self.block_len
        )
        try:
            return self.pool.alloc(need)
        except OutOfBlocks:
            return None

    def _release_blocks(self, s):
        if self.pool is not None and self.chains[s]:
            self.pool.release(self.chains[s])
        self.chains[s] = None

    def _check_leaks(self):
        if self.pool is not None:
            self.pool.check_owners([c for c in self.chains if c])

    def step(self):
        before = self.prefills
        now = time.monotonic()
        for s in range(self.n_slots):
            if not self.queue:
                break
            if self.slots[s] is None:
                chain = self._admit_blocks(self.queue[0])
                if chain is None:
                    # out of KV blocks: the request stays at the queue
                    # head (strict FIFO) until a finisher frees its chain
                    self.admissions_deferred += 1
                    break
                r = self.queue.pop(0)
                r.mark_prefilling(now)
                self.prefills += 1
                r.mark_decoding(time.monotonic())
                r.add_token(toy_token(r))
                if r.done:
                    if self.pool is not None and chain:
                        self.pool.release(chain)
                    r.finish(time.monotonic())
                    self._terminal(r)
                else:
                    self.slots[s] = r
                    self.chains[s] = chain
        occupied = [s for s in range(self.n_slots) if self.slots[s] is not None]
        if not occupied:
            self._check_leaks()
            return self.prefills != before
        if self.step_seconds:
            time.sleep(self.step_seconds)
        self.decode_steps += 1
        now = time.monotonic()
        for s in occupied:
            r = self.slots[s]
            r.add_token(toy_token(r))
            if r.done:
                self._release_blocks(s)
                r.finish(now)
                self._terminal(r)
                self.slots[s] = None
        self._check_leaks()
        return True


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--rank", type=int, required=True)
    p.add_argument("--world", type=int, required=True)
    p.add_argument("--spool-dir", required=True)
    p.add_argument("--result-dir", required=True)
    p.add_argument("--slots", type=int, default=2)
    p.add_argument("--step-seconds", type=float, default=0.005)
    p.add_argument("--max-wall-s", type=float, default=60.0)
    p.add_argument(
        "--paged", action="store_true",
        help="gate admission with a real serving.blocks.BlockPool"
             " (paged-allocator backpressure + leak checks)",
    )
    p.add_argument("--block-len", type=int, default=4)
    p.add_argument("--pool-blocks", type=int, default=None,
                   help="pool size; default sizes for slots*horizon")
    p.add_argument(
        "--die-after-claims", type=int, default=None, metavar="N",
        help="incarnation 0 only: SIGKILL self mid-decode once N requests"
             " have been admitted and some are still in flight",
    )
    args = p.parse_args()

    incarnation = incarnation_from_env()
    os.makedirs(args.result_dir, exist_ok=True)

    event_log = shard_event_log_from_env()
    telemetry = (
        telemetry_for_run(event_log=event_log, stdout=False)
        if event_log else None
    )

    spool = FileSpool(args.spool_dir, rank=args.rank, incarnation=incarnation)
    pool = None
    if args.paged:
        from network_distributed_pytorch_tpu.serving.blocks import (  # noqa: E501
            BlockPool,
        )

        # default: room for all slots at a 32-token horizon, + garbage
        n_blocks = args.pool_blocks or (
            args.slots * (32 // args.block_len + 1) + 1
        )
        pool = BlockPool(n_blocks, args.block_len)
    engine = ToyEngine(
        args.slots, telemetry=telemetry, rank=args.rank,
        step_seconds=args.step_seconds,
        pool=pool, block_len=args.block_len,
    )

    if args.die_after_claims is not None and incarnation == 0:
        # mid-decode death: strike AFTER a tick, with claims unreleased —
        # this step's finished-but-uncompleted requests are orphaned too
        # (re-queue must recover them, idempotently)
        plain_step = engine.step

        def step_then_maybe_die():
            worked = plain_step()
            if engine.submits >= args.die_after_claims and engine.n_active:
                os.kill(os.getpid(), signal.SIGKILL)
            return worked

        engine.step = step_then_maybe_die

    served = serve_from_spool(
        engine, spool, world=args.world, max_wall_s=args.max_wall_s
    )
    served.pop("requests", None)  # Request objects aren't JSON

    if telemetry is not None:
        telemetry.close()
    with open(
        os.path.join(args.result_dir, f"rank{args.rank}.json"), "w"
    ) as f:
        json.dump(
            {"rank": args.rank, "world": args.world,
             "incarnation": incarnation,
             "decode_steps": engine.decode_steps,
             "prefills": engine.prefills,
             "paged": bool(args.paged),
             "admissions_deferred": engine.admissions_deferred,
             **served},
            f,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
