"""The gradient-fidelity plane: per-group compression audit, the
ledger<->fidelity join, EF-growth tracking, the accuracy-per-byte
frontier, the streaming detectors that page on it, and the controller's
fidelity ascend.

Two invariants are pinned as EQUALITY, not closeness, because they are
correctness facts rather than estimates (DESIGN.md guarantee classes):
every exact reducer layout (flat / chunked / bucketed) reports
identically-zero relative error, and every fidelity group's wire tag is
byte-priced by the same reducer's ledger entries (an orphan group is a
broken join, not a tolerance question). Everything numeric about lossy
reducers stays in the sampled merge-tolerance class.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from network_distributed_pytorch_tpu.observe.events import FidelityEvent
from network_distributed_pytorch_tpu.observe.fidelity import (
    FidelityTracker,
    fidelity_summary,
    frontier_from_events,
)
from network_distributed_pytorch_tpu.observe.health import (
    DetectorConfig,
    EfBlowupDetector,
    FidelityCollapseDetector,
    HealthMonitor,
)
from network_distributed_pytorch_tpu.observe.ledger import (
    reducer_ledger_entries,
)
from network_distributed_pytorch_tpu.observe.live import (
    MetricRegistry,
    ingest_record,
)
from network_distributed_pytorch_tpu.parallel import (
    ExactReducer,
    HierarchicalReducer,
    PowerSGDReducer,
    make_mesh,
)
from network_distributed_pytorch_tpu.parallel.hierarchical import (
    replica_drift_stats,
)
from network_distributed_pytorch_tpu.resilience import (
    FallbackController,
    Rung,
)


def _template():
    """A CNN-ish mix (matches test_reducers): high-rank + rank-1 leaves."""
    ks = jax.random.split(jax.random.PRNGKey(0), 5)
    return [
        jax.random.normal(ks[0], (8, 3, 3, 3)),
        jax.random.normal(ks[1], (16, 8)),
        jax.random.normal(ks[2], (16,)),
        jax.random.normal(ks[3], (10, 16)),
        jax.random.normal(ks[4], (10,)),
    ]


def _get(stats):
    """device_get + plain floats, the host side of the health probe."""
    return {
        g: {k: float(v) for k, v in vals.items()}
        for g, vals in jax.device_get(stats).items()
    }


# ---------------------------------------------------------------------------
# satellite: exact reducers report identically zero, hierarchical reports
# the OUTER stage's error
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "reducer",
    [
        ExactReducer(),
        ExactReducer(comm_chunks=4),
        ExactReducer(bucket_bytes=512),
        ExactReducer(packed=False),
    ],
    ids=["flat", "chunked", "bucketed", "unpacked"],
)
def test_exact_compression_error_identically_zero(reducer):
    send = _template()
    err = float(reducer.compression_error({}, send, None))
    assert err == 0.0  # equality: exactness is a fact, not an estimate
    for vals in _get(reducer.fidelity_stats({}, send)).values():
        assert vals["rel_error"] == 0.0
        assert vals["cosine_sim"] == 1.0
        assert vals["quantized_share"] == 0.0


def test_powersgd_rel_error_positive_and_consistent():
    send = _template()
    reducer = PowerSGDReducer(random_seed=7, compression_rank=1)
    state = reducer.init(send)
    flat = float(reducer.compression_error(state, send, None))
    assert flat > 0.0  # rank-1 of real matrices must lose something
    stats = _get(reducer.fidelity_stats(state, send))
    grouped = [v["rel_error"] for g, v in stats.items() if g != "powersgd.rank1"]
    assert all(e > 0.0 for e in grouped)
    assert stats["powersgd.rank1"]["rel_error"] == 0.0  # exact fallthrough
    for vals in stats.values():
        assert -1.0 <= vals["cosine_sim"] <= 1.0 + 1e-6


def test_hierarchical_reports_outer_error_not_inner(devices):
    """The hierarchical probe must surface the slow-fabric compressor's own
    distortion — not the inner exact stage's zero."""
    mesh2d = make_mesh(axis_sizes=(2, 4), axis_names=("dcn", "ici"))
    outer = PowerSGDReducer(random_seed=3, compression_rank=1)
    hier = HierarchicalReducer(outer, mesh2d, "ici", "dcn")
    send = _template()
    state = hier.init(send)
    hier_err = float(hier.compression_error(state, send))
    outer_err = float(outer.compression_error(state, send, None))
    assert hier_err == outer_err > 0.0  # delegation, not re-derivation
    stats = _get(hier.fidelity_stats(state, send))
    inner = {g: v for g, v in stats.items() if g.startswith("inner.")}
    outer_groups = {g: v for g, v in stats.items() if g.startswith("outer.")}
    assert inner and outer_groups
    assert all(v["rel_error"] == 0.0 for v in inner.values())
    assert any(v["rel_error"] > 0.0 for v in outer_groups.values())


def test_exact_in_exact_hierarchy_all_groups_zero(devices):
    mesh2d = make_mesh(axis_sizes=(2, 4), axis_names=("dcn", "ici"))
    hier = HierarchicalReducer(ExactReducer(), mesh2d, "ici", "dcn")
    send = _template()
    assert float(hier.compression_error(hier.init(send), send)) == 0.0
    for vals in _get(hier.fidelity_stats(hier.init(send), send)).values():
        assert vals["rel_error"] == 0.0


def test_powersgd_bf16_wire_flags_quantized_share():
    send = _template()
    bf16 = PowerSGDReducer(compression_rank=2, compression_dtype="bfloat16")
    fp32 = PowerSGDReducer(compression_rank=2)
    s16 = _get(bf16.fidelity_stats(bf16.init(send), send))
    s32 = _get(fp32.fidelity_stats(fp32.init(send), send))
    assert all(v["quantized_share"] == 1.0 for v in s16.values())
    assert all(v["quantized_share"] == 0.0 for v in s32.values())


def test_fidelity_stats_jit_safe_static_keys():
    """The probe runs inside a separately-jitted health fn: group keys must
    be static (host strings), values traced scalars."""
    send = _template()
    reducer = PowerSGDReducer(random_seed=5, compression_rank=2)
    state = reducer.init(send)

    @jax.jit
    def probe(send):
        return reducer.fidelity_stats(state, send, None, None)

    stats = _get(probe(send))
    assert set(stats) == set(reducer.fidelity_group_tags(send))


def test_make_health_fn_nests_fidelity_with_legacy_flat_keys(devices):
    """The health probe adds the per-group ``fidelity`` sub-dict WITHOUT
    touching the flat legacy keys the event schema already promises."""
    from network_distributed_pytorch_tpu.parallel.trainer import (
        make_health_fn,
        make_train_step,
        stateless_loss,
    )

    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
    params = {"w": jax.random.normal(k1, (32, 16))}
    loss = stateless_loss(
        lambda p, b: jnp.mean((b[0] @ p["w"] - b[1]) ** 2)
    )
    reducer = PowerSGDReducer(compression_rank=2, matricize="last")
    step = make_train_step(
        loss, reducer, params, 0.05, mesh=None, donate_state=False
    )
    state = step.init_state(params)
    batch = (jax.random.normal(k2, (16, 32)), jax.random.normal(k3, (16, 16)))
    health = make_health_fn(loss, reducer)  # mesh=None: collective-free
    out = jax.device_get(health(state, batch))
    flat = {"grad_norm", "ef_memory_norm", "powersgd_rel_error", "loss"}
    assert flat <= set(out)
    fid = out["fidelity"]
    assert set(fid) == set(reducer.fidelity_group_tags(params))
    for vals in fid.values():
        assert {"rel_error", "cosine_sim", "ef_norm", "quantized_share"} <= set(
            vals
        )
    assert any(float(v["rel_error"]) > 0.0 for v in fid.values())


# ---------------------------------------------------------------------------
# satellite: the ledger<->fidelity join — every group's tag is byte-priced
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "make_reducer,axis",
    [
        (lambda: ExactReducer(), "data"),
        (lambda: ExactReducer(bucket_bytes=512), "data"),
        (lambda: PowerSGDReducer(compression_rank=2), "data"),
        (
            lambda: PowerSGDReducer(
                compression_rank=2, compression_dtype="bfloat16"
            ),
            "data",
        ),
    ],
    ids=["exact-flat", "exact-bucketed", "powersgd", "powersgd-bf16"],
)
def test_fidelity_groups_join_wire_ledger(make_reducer, axis):
    reducer = make_reducer()
    send = _template()
    tags = reducer.fidelity_group_tags(send)
    assert tags  # every reducer must declare its groups
    priced = {
        e.tag for e in reducer_ledger_entries(reducer, send, axis, n_workers=2)
    }
    orphans = {g: t for g, t in tags.items() if t not in priced}
    assert not orphans, f"fidelity tags not byte-priced: {orphans} vs {priced}"
    # the stats dict and the tag map must agree on the group universe
    state = reducer.init(send) if hasattr(reducer, "init") else {}
    assert set(_get(reducer.fidelity_stats(state, send))) == set(tags)


def test_hierarchical_fidelity_groups_join_ledger(devices):
    mesh2d = make_mesh(axis_sizes=(2, 4), axis_names=("dcn", "ici"))
    hier = HierarchicalReducer(
        PowerSGDReducer(compression_rank=2), mesh2d, "ici", "dcn"
    )
    send = _template()
    tags = hier.fidelity_group_tags(send)
    priced = {e.tag for e in hier.ledger_entries(send, n_workers=2)}
    orphans = {g: t for g, t in tags.items() if t not in priced}
    assert not orphans, f"hierarchical tags not priced: {orphans} vs {priced}"
    assert any(g.startswith("outer.") for g in tags)
    assert any(g.startswith("inner.") for g in tags)


def test_tracker_events_join_ledger_and_flag_orphans():
    """FidelityEvents carry the reducer's tag for known groups; an unknown
    group rides its own key so the join test sees it loudly."""
    reducer = PowerSGDReducer(compression_rank=2)
    send = _template()
    tags = reducer.fidelity_group_tags(send)
    tracker = FidelityTracker(tags, rank=0, label="t")
    stats = _get(reducer.fidelity_stats(reducer.init(send), send))
    events = tracker.events(4, stats, epoch=1)
    priced = {
        e.tag for e in reducer_ledger_entries(reducer, send, "data", n_workers=2)
    }
    assert events and all(ev.tag in priced for ev in events)
    assert all(ev.step == 4 and ev.epoch == 1 and ev.rank == 0 for ev in events)
    orphan = tracker.events(5, {"mystery.group": {"rel_error": 0.5}})
    assert orphan[0].tag == "mystery.group"  # not silently dropped


# ---------------------------------------------------------------------------
# the tracker: EF growth and drift attachment
# ---------------------------------------------------------------------------


def test_tracker_ef_growth_rate():
    tracker = FidelityTracker({"g": "g"})
    (first,) = tracker.events(0, {"g": {"ef_norm": 2.0}})
    assert first.ef_growth == 0.0  # no previous sample
    (second,) = tracker.events(1, {"g": {"ef_norm": 3.0}})
    assert second.ef_growth == pytest.approx(0.5)
    (third,) = tracker.events(2, {"g": {"ef_norm": 1.5}})
    assert third.ef_growth == pytest.approx(-0.5)
    # a dead-zero previous EF norm must not divide: growth clamps to 0
    tracker2 = FidelityTracker()
    tracker2.events(0, {"g": {"ef_norm": 0.0}})
    (ev,) = tracker2.events(1, {"g": {"ef_norm": 1.0}})
    assert ev.ef_growth == 0.0


def test_tracker_attaches_drift_scalars():
    tracker = FidelityTracker({"a": "a", "b": "b"})
    events = tracker.events(
        0,
        {"a": {"rel_error": 0.1}, "b": {"rel_error": 0.2}},
        drift={"replica_drift": 0.25, "anchor_drift": 0.5},
    )
    assert [e.group for e in events] == ["a", "b"]  # sorted, stable
    assert all(e.replica_drift == 0.25 for e in events)
    assert all(e.anchor_drift == 0.5 for e in events)


def test_replica_drift_stats_zero_for_agreeing_replicas():
    same = {"w": jnp.ones((4, 3, 2))}
    d = {k: float(v) for k, v in replica_drift_stats(same).items()}
    assert d["replica_drift"] == pytest.approx(0.0, abs=1e-6)
    assert d["anchor_drift"] == 0.0  # no anchors given
    walked = {"w": jnp.stack([jnp.ones((3, 2)), jnp.full((3, 2), 3.0)])}
    d2 = {k: float(v) for k, v in replica_drift_stats(walked).items()}
    assert d2["replica_drift"] > 0.0
    anchors = {"w": jnp.ones((3, 2))}
    d3 = replica_drift_stats(walked, anchors)
    assert float(d3["anchor_drift"]) > 0.0


# ---------------------------------------------------------------------------
# summary: per-group aggregation and worst-group blame
# ---------------------------------------------------------------------------


def _fid_rec(step, group, rel, tag=None, ef=0.0, **kw):
    return FidelityEvent(
        step=step, group=group, tag=tag or group, rel_error=rel,
        ef_norm=ef, **kw
    ).record()


def test_summary_blames_sustained_worst_group_by_mean():
    records = []
    for s in range(10):
        records.append(_fid_rec(s, "steady", 0.3))
        # one spectacular spike, otherwise clean: mean ~0.1 < 0.3
        records.append(_fid_rec(s, "spiky", 1.0 if s == 0 else 0.0))
    summary = fidelity_summary(records)
    assert summary["samples"] == 20
    assert summary["worst_group"] == "steady"  # sustained beats spike
    assert summary["rel_error"] == pytest.approx(0.3)
    assert summary["groups"]["spiky"]["max_rel_error"] == 1.0
    assert summary["groups"]["spiky"]["mean_rel_error"] == pytest.approx(0.1)


def test_summary_tracks_ef_and_drift_extremes():
    records = [
        _fid_rec(0, "g", 0.1, ef=1.0, ef_growth=0.0, replica_drift=0.1),
        _fid_rec(2, "g", 0.2, ef=5.0, ef_growth=4.0, replica_drift=0.4),
        _fid_rec(4, "g", 0.1, ef=2.0, ef_growth=-0.6, replica_drift=0.2),
    ]
    s = fidelity_summary(records)
    g = s["groups"]["g"]
    assert (g["first_step"], g["last_step"]) == (0, 4)
    assert g["max_ef_norm"] == 5.0 and g["last_ef_norm"] == 2.0
    assert g["max_ef_growth"] == 4.0
    assert s["replica_drift"]["max"] == pytest.approx(0.4)
    assert s["replica_drift"]["last"] == pytest.approx(0.2)


def test_summary_empty_and_non_fidelity_records():
    s = fidelity_summary([{"event": "step", "step": 1}])
    assert s["samples"] == 0 and s["worst_group"] is None
    assert s["rel_error"] == 0.0


# ---------------------------------------------------------------------------
# the accuracy-per-byte frontier
# ---------------------------------------------------------------------------


def _step_rec(step, epoch, loss, byts):
    return {
        "event": "step", "step": step, "epoch": epoch, "loss": loss,
        "bits_cumulative": byts * 8,
    }


def _policy_rec(epoch, action, before, after, idx):
    return {
        "event": "policy", "epoch": epoch, "action": action,
        "rung_before": before, "rung_after": after, "rung_index_after": idx,
    }


def test_frontier_segments_by_rung_and_prices_bytes():
    records = [
        _step_rec(s, s // 4, 1.0 / (s + 1), (s + 1) * 100) for s in range(12)
    ]
    records.append(_policy_rec(2, "ascend", "compress", "baseline", 0))
    f = frontier_from_events(records)
    assert f["steps"] == 12 and f["total_bytes"] == 1200
    assert [r["rung"] for r in f["rungs"]] == ["compress", "baseline"]
    first, second = f["rungs"]
    # boundary: first step whose epoch >= 2 -> step 8
    assert (first["start_step"], first["end_step"]) == (0, 7)
    assert (second["start_step"], second["end_step"]) == (8, 11)
    assert first["bytes"] + second["bytes"] == f["total_bytes"]
    assert second["bytes_cumulative_end"] == 1200
    # the toy loss 1/(s+1) is monotone decreasing: both drops positive
    assert first["loss_drop"] > 0 and second["loss_drop"] > 0
    assert second["loss_drop_per_gb"] == pytest.approx(
        second["loss_drop"] / (second["bytes"] / 1e9)
    )


def test_frontier_without_policies_is_one_run_segment():
    records = [_step_rec(s, 0, 1.0 - 0.1 * s, (s + 1) * 10) for s in range(5)]
    f = frontier_from_events(records)
    assert [r["rung"] for r in f["rungs"]] == ["run"]
    assert f["rungs"][0]["steps"] == 5


def test_frontier_dedups_multirank_merge():
    """A merged run-dir replays every rank's StepEvents and PolicyEvents;
    the frontier must count each step and transition once."""
    base = [_step_rec(s, s // 2, 1.0 / (s + 1), (s + 1) * 10) for s in range(6)]
    pol = [_policy_rec(1, "ascend", "compress", "baseline", 0)]
    doubled = base + pol + base + pol  # rank 0 + rank 1 shards interleaved
    f = frontier_from_events(doubled)
    assert f["steps"] == 6
    assert len(f["rungs"]) == 2
    assert f["total_bytes"] == 60


def test_frontier_empty():
    f = frontier_from_events([])
    assert f == {
        "rungs": [], "total_bytes": 0, "final_loss": None, "steps": 0
    }


# ---------------------------------------------------------------------------
# streaming detectors
# ---------------------------------------------------------------------------


def test_fidelity_collapse_floor_and_sustain():
    det = FidelityCollapseDetector(DetectorConfig())
    # clean samples under the absolute floor never fire
    for _ in range(10):
        assert det.observe(0.02) is None
    # one degraded sample: sustain=2 holds fire
    assert det.observe(0.2) is None
    alert = det.observe(0.2)
    assert alert is not None and alert.alert == "fidelity_collapse"
    assert alert.severity == "warn"  # 0.2 < the 0.5 critical absolute


def test_fidelity_collapse_critical_past_absolute():
    det = FidelityCollapseDetector(DetectorConfig())
    det.observe(0.02)
    det.observe(20.0)
    alert = det.observe(20.0)
    assert alert is not None and alert.severity == "critical"


def test_fidelity_collapse_baseline_frozen_while_firing():
    cfg = DetectorConfig()
    det = FidelityCollapseDetector(cfg)
    for _ in range(5):
        det.observe(0.01)
    base = det._ewma.mean
    det.observe(5.0)
    det.observe(5.0)  # fires; collapsed samples must not raise the envelope
    assert det._ewma.mean == base


def test_fidelity_collapse_fires_on_zero_baseline_group():
    """An exact group's baseline is identically zero — the absolute floor
    must still catch error materializing out of nowhere."""
    det = FidelityCollapseDetector(DetectorConfig())
    for _ in range(4):
        assert det.observe(0.0) is None
    det.observe(0.3)
    assert det.observe(0.3) is not None


def test_ef_blowup_needs_nonzero_baseline():
    det = EfBlowupDetector(DetectorConfig())
    for _ in range(10):
        assert det.observe(0.0) is None
    # even a jump from dead zero never fires (exact groups)
    assert det.observe(100.0) is None


def test_ef_blowup_warn_and_critical_bands():
    cfg = DetectorConfig()
    det = EfBlowupDetector(cfg)
    for _ in range(max(cfg.ef_min_obs, cfg.ef_sustain) + 1):
        assert det.observe(1.0) is None
    for _ in range(cfg.ef_sustain - 1):
        det.observe(cfg.ef_factor * 1.0 + 1.0)
    warn = det.observe(cfg.ef_factor * 1.0 + 1.0)
    assert warn is not None and warn.severity == "warn"
    det2 = EfBlowupDetector(cfg)
    for _ in range(cfg.ef_min_obs + 1):
        det2.observe(1.0)
    for _ in range(cfg.ef_sustain - 1):
        det2.observe(cfg.ef_critical_factor * 2.0)
    crit = det2.observe(cfg.ef_critical_factor * 2.0)
    assert crit is not None and crit.severity == "critical"


def test_monitor_keys_fidelity_detectors_per_group():
    mon = HealthMonitor(DetectorConfig())
    # group a collapses; group b stays clean — only a's detector may fire
    fired = []
    for step in range(8):
        fired += mon.observe_fidelity("a", 5.0 if step >= 2 else 0.01, step=step)
        fired += mon.observe_fidelity("b", 0.01, step=step)
    assert fired and all(a.message.startswith("group a:") for a in fired)
    assert mon.fired_by_kind().get("fidelity_collapse", 0) >= 1


# ---------------------------------------------------------------------------
# live plane gauges
# ---------------------------------------------------------------------------


def test_ingest_fidelity_record_sets_labeled_gauges():
    reg = MetricRegistry()
    rec = FidelityEvent(
        step=3, group="powersgd.g0:16x8r2", tag="powersgd.P",
        rel_error=0.25, cosine_sim=0.9, ef_norm=1.5, ef_growth=0.1,
        quantized_share=1.0, replica_drift=0.05, anchor_drift=0.01,
        rank=1,
    ).record()
    # the record's own rank wins over the shard-fallback argument
    ingest_record(reg, rec, rank=7)
    labels = {"rank": "1", "group": "powersgd.g0:16x8r2"}
    assert reg.get_gauge("live_fidelity_rel_error", **labels) == 0.25
    assert reg.get_gauge("live_ef_norm", **labels) == 1.5
    assert reg.get_gauge("live_ef_growth", **labels) == pytest.approx(0.1)
    assert reg.get_gauge("live_fidelity_cosine_sim", **labels) == 0.9
    # drift scalars are whole-state: rank-labeled, ungrouped
    assert reg.get_gauge("live_replica_drift", rank="1") == 0.05
    assert reg.get_gauge("live_anchor_drift", rank="1") == 0.01


# ---------------------------------------------------------------------------
# the controller's fidelity ascend
# ---------------------------------------------------------------------------


def _ladder():
    return [Rung("baseline", {}), Rung("compress", {"reducer": "powersgd"})]


def test_fidelity_alert_ascends_any_severity():
    c = FallbackController(ladder=_ladder(), start_index=1)
    d = c.nudge("fidelity_collapse", epoch=0, severity="warn")
    assert d is not None and d.action == "ascend"
    assert d.trigger == "alert:fidelity_collapse:warn"
    assert c.rung.name == "baseline"
    assert c.nudged_epoch == 0


def test_ef_blowup_alert_ascends_too():
    c = FallbackController(ladder=_ladder(), start_index=1)
    d = c.nudge("ef_blowup", epoch=2, severity="critical")
    assert d is not None and d.action == "ascend"


def test_fidelity_ascend_holds_at_top_rung():
    c = FallbackController(ladder=_ladder(), start_index=0)
    assert c.nudge("fidelity_collapse", epoch=0, severity="critical") is None
    assert c.rung.name == "baseline"
    # the no-op must NOT spend the epoch's nudge budget
    assert c.nudged_epoch is None


def test_one_fidelity_nudge_per_epoch():
    ladder = _ladder() + [Rung("compress-low", {})]
    c = FallbackController(ladder=ladder, start_index=2)
    assert c.nudge("fidelity_collapse", epoch=1, severity="warn") is not None
    assert c.nudge("fidelity_collapse", epoch=1, severity="warn") is None
    assert c.index == 1  # one rung, not two
    assert c.nudge("fidelity_collapse", epoch=2, severity="warn") is not None
    assert c.index == 0
