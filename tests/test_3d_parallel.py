"""Composed 3-D parallelism on a 2×2×2 mesh: data × pipeline × tensor.

The reference is data-parallel only (SURVEY §2.3); this exercises the
framework's axes composing in ONE training step — batch sharded over
``data``, stages of Megatron-style TP-MLP blocks sharded over ``pipe`` (1F1B
schedule) with kernels feature-sharded over ``model`` — and checks loss and
gradients exactly against plain single-device autodiff.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from network_distributed_pytorch_tpu.parallel import make_mesh
from network_distributed_pytorch_tpu.parallel.pipeline import (
    make_pipeline_train_fn,
    stacked_stage_params,
)
from network_distributed_pytorch_tpu.parallel.tensor import tp_mlp

N_DATA, N_PIPE, N_MODEL = 2, 2, 2
DIM, HID = 4, 6
B, MICRO = 8, 2  # global batch; microbatches of the per-data-shard batch


def _stage_params(seed):
    rng = np.random.RandomState(seed)
    return {
        "w_up": jnp.asarray(rng.randn(DIM, HID) * 0.5, jnp.float32),
        "b_up": jnp.asarray(rng.randn(HID) * 0.1, jnp.float32),
        "w_down": jnp.asarray(rng.randn(HID, DIM) * 0.5, jnp.float32),
        "b_down": jnp.asarray(rng.randn(DIM) * 0.1, jnp.float32),
    }


def _full_stage(p, a):
    return jax.nn.relu(a @ p["w_up"] + p["b_up"]) @ p["w_down"] + p["b_down"]


def _tp_stage(p, a):
    return tp_mlp(a, p["w_up"], p["b_up"], p["w_down"], p["b_down"], "model")


def _mb_loss(out, label):
    return jnp.mean((out - label) ** 2)


PARAM_SPECS = {
    "w_up": P("pipe", None, "model"),
    "b_up": P("pipe", "model"),
    "w_down": P("pipe", "model", None),
    "b_down": P("pipe", None),
}


def _make_3d_fit():
    """The composed training step: 1F1B pipeline of TP-MLP stages over
    ('data','pipe','model'), grads pmean'd over data."""
    mesh = make_mesh((N_DATA, N_PIPE, N_MODEL), ("data", "pipe", "model"))
    pipe_fn = make_pipeline_train_fn(
        _tp_stage, _mb_loss, "pipe", MICRO, params_varying_over=("data",)
    )

    def step(stacked, x, y):
        loss, grads = pipe_fn(stacked, x, y)
        grads = jax.tree_util.tree_map(lambda g: lax.pmean(g, "data"), grads)
        return lax.pmean(loss, "data"), grads

    return jax.jit(
        jax.shard_map(
            step,
            mesh=mesh,
            in_specs=(PARAM_SPECS, P("data"), P("data")),
            out_specs=(P(), PARAM_SPECS),
        )
    )


def test_dp_pp_tp_training_step_matches_single_device(devices):
    stages = [_stage_params(70 + s) for s in range(N_PIPE)]
    stacked = stacked_stage_params(stages)
    x = jnp.asarray(np.random.RandomState(1).randn(B, DIM), jnp.float32)
    y = jnp.asarray(np.random.RandomState(2).randn(B, DIM), jnp.float32)

    def ref_loss(stages, x, y):
        a = x
        for p in stages:
            a = _full_stage(p, a)
        return _mb_loss(a, y)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(stages, x, y)

    loss, grads = _make_3d_fit()(stacked, x, y)

    np.testing.assert_allclose(float(loss), float(ref_l), rtol=2e-5)
    # shard_map reassembles the sharded grads into full global arrays
    ref_stacked_g = stacked_stage_params([ref_g[s] for s in range(N_PIPE)])
    for name in ("w_up", "b_up", "w_down", "b_down"):
        np.testing.assert_allclose(
            np.asarray(grads[name]),
            np.asarray(ref_stacked_g[name]),
            rtol=2e-4,
            atol=1e-5,
        )


def test_dp_pp_tp_trains(devices):
    stages = [_stage_params(90 + s) for s in range(N_PIPE)]
    stacked = stacked_stage_params(stages)
    x = jnp.asarray(np.random.RandomState(5).randn(B, DIM), jnp.float32)
    y = jnp.asarray(np.random.RandomState(6).randn(B, DIM), jnp.float32)

    fit = _make_3d_fit()
    losses = []
    for _ in range(30):
        loss, grads = fit(stacked, x, y)
        stacked = jax.tree_util.tree_map(lambda p, g: p - 0.3 * g, stacked, grads)
        losses.append(float(loss))
    assert losses[-1] < 0.8 * losses[0]
