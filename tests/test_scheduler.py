"""resilience/scheduler.py: the fleet control plane's jax-free mechanics.

The fast twin of run_probe's phase-10 game day. The full multi-job storm
(SLO-burn preemption, bitwise resume oracle) lives in the probe; these
tests pin the pieces it rides on:

- **Manifests**: wire round-trip is lossless, argv placeholder tokens
  substitute per worker, and malformed submissions are rejected at
  construction.
- **Job spool**: a malformed queue doc is quarantined on claim (never
  crash-loops or wedges the control plane), and a parked job's mutable
  bookkeeping (preemptions, strikes, chip-seconds) survives the
  park/re-claim round-trip — a restarted scheduler sees history intact.
- **Admission math**: viable worlds honor plan_mesh's divisor
  discipline, and chips reserved for a burning pool are invisible to
  every OTHER job's admission.
- **End to end**: a real (subprocess-spawning) two-job fleet completes
  the good job, quarantines the crash-looper after max_strikes without
  blocking the queue, and reports a positive goodput.
"""

import json
import os
import sys

import pytest

from network_distributed_pytorch_tpu.resilience.scheduler import (
    FleetConfig,
    FleetScheduler,
    JobManifest,
    JobSpool,
)
from network_distributed_pytorch_tpu.resilience.supervisor import plan_mesh


def test_manifest_wire_roundtrip_and_argv_tokens():
    job = JobManifest(
        job_id="svc",
        argv=[
            "python", "-u", "w.py", "--rank", "{rank}", "--world",
            "{world}", "--dev", "{device_rank}", "--gen", "{incarnation}",
        ],
        kind="serve",
        priority=5,
        deadline_s=30.0,
        min_world=2,
        max_world=4,
        steps=10.0,
        env={"A": "1"},
        preemptions=1,
        strikes=1,
        chip_seconds=2.5,
    )
    clone = JobManifest.from_wire(json.loads(json.dumps(job.to_wire())))
    assert clone == job  # lossless, bookkeeping included
    argv = clone.worker_argv(rank=1, world=2, incarnation=3, device_rank=7)
    assert argv == [
        "python", "-u", "w.py", "--rank", "1", "--world", "2",
        "--dev", "7", "--gen", "3",
    ]
    with pytest.raises(ValueError):
        JobManifest(job_id="x", argv=["p"], kind="batch")
    with pytest.raises(ValueError):
        JobManifest(job_id="x", argv=["p"], min_world=3, max_world=2)
    with pytest.raises(ValueError):
        JobManifest(job_id="x", argv=[])


def test_jobspool_quarantines_malformed_and_keeps_queue_moving(tmp_path):
    spool = JobSpool(str(tmp_path / "jobs"))
    assert spool.submit([JobManifest(job_id="good", argv=["x"])]) == 1
    # a bad submission lands straight on the queue (sorts before "good",
    # so the claim loop hits it first)
    bad_path = os.path.join(spool._spool.queue_dir, "bad.json")
    with open(bad_path, "w") as f:
        json.dump({"job_id": "bad", "argv": ["x"], "kind": "gpu-hours"}, f)
    claimed = []
    while True:
        j = spool.claim()
        if j is None:
            break
        claimed.append(j.job_id)
    assert claimed == ["good"]  # bad never surfaced, never wedged
    assert spool.quarantined_ids() == ["bad"]
    # the forensics copy names why
    with open(os.path.join(spool.quarantine_dir, "bad.json")) as f:
        assert "malformed manifest" in json.load(f)["quarantine_reason"]


def test_jobspool_park_carries_bookkeeping(tmp_path):
    spool = JobSpool(str(tmp_path / "jobs"))
    spool.submit([JobManifest(job_id="j", argv=["x"], preemption_budget=2)])
    job = spool.claim()
    job.preemptions += 1
    job.strikes = 1
    job.chip_seconds = 12.5
    job.work_done = 3.0
    job.last_rc = 75
    spool.park(job)
    # re-submitting a parked id is a no-op (idempotent enqueue)
    assert spool.submit([JobManifest(job_id="j", argv=["x"])]) == 0
    again = spool.claim()
    assert (
        again.preemptions, again.strikes, again.chip_seconds,
        again.work_done, again.last_rc,
    ) == (1, 1, 12.5, 3.0, 75)


def test_viable_worlds_and_reservations(tmp_path):
    sched = FleetScheduler(
        JobSpool(str(tmp_path / "jobs")), FleetConfig(n_devices=8)
    )
    # pure DP: every world in [min_world, cap]
    dp = JobManifest(job_id="dp", argv=["x"], min_world=2, max_world=6)
    assert sched._viable_worlds(dp, cap=5) == [2, 3, 4, 5]
    # meshed: only worlds plan_mesh can realize under divisor discipline
    axes = {"data": 2, "fsdp": 2, "tensor": 2}
    meshy = JobManifest(
        job_id="m", argv=["x"], min_world=2, max_world=8, mesh_axes=axes
    )
    worlds = sched._viable_worlds(meshy, cap=8)
    assert worlds and 8 in worlds
    for w in worlds:
        mesh = plan_mesh(axes, w, 2)
        assert mesh is not None
        assert mesh["data"] * mesh["fsdp"] * mesh["tensor"] == w
    # chips reserved for another job are invisible to this job's
    # admission; the reservation holder still sees them
    sched._reserved["svc"] = [0, 1]
    assert sched._grantable(dp) == [2, 3, 4, 5, 6, 7]
    svc = JobManifest(job_id="svc", argv=["x"])
    assert sched._grantable(svc) == list(range(8))


def test_fleet_completes_and_quarantines_crash_looper(tmp_path):
    spool = JobSpool(str(tmp_path / "jobs"))
    spool.submit([
        JobManifest(
            job_id="ok",
            argv=[sys.executable, "-c", "pass"],
            steps=2.0,
        ),
        JobManifest(
            job_id="boom",
            argv=[sys.executable, "-c", "raise SystemExit(43)"],
            priority=1,  # outranks ok — still must not wedge the fleet
            max_restarts=0,
            max_strikes=2,
        ),
    ])
    sched = FleetScheduler(
        spool,
        FleetConfig(n_devices=2, max_wall_s=60.0, term_grace_s=2.0),
        run_dir=str(tmp_path / "fleet"),
    )
    summary = sched.run()
    assert summary["completed"] == ["ok"]
    assert summary["quarantined"] == ["boom"]
    assert summary["unfinished"] == []
    assert spool.quarantined_ids() == ["boom"]
    assert summary["jobs"]["boom"]["last_rc"] == 43
    assert summary["jobs"]["boom"]["strikes"] == 2
    # goodput counts ok's work against EVERY chip-second, boom's included
    assert summary["goodput"] > 0.0
    assert summary["total_chip_seconds"] > 0.0
    assert summary["weighted_work"] == 2.0
