"""hostenv.force_cpu_devices — pure env-var manipulation, no jax needed.
Covers the four caller profiles: conftest (keep user flag), dryrun
(replace), multiprocess worker (remove + drop tunnel), study (raise the
collective-rendezvous deadlines)."""

import importlib

from network_distributed_pytorch_tpu import hostenv


def _clean(monkeypatch):
    monkeypatch.delenv("XLA_FLAGS", raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")


def test_sets_platform_and_count(monkeypatch):
    _clean(monkeypatch)
    import os

    hostenv.force_cpu_devices(8)
    assert os.environ["JAX_PLATFORMS"] == "cpu"
    assert "--xla_force_host_platform_device_count=8" in os.environ["XLA_FLAGS"]
    assert os.environ["PALLAS_AXON_POOL_IPS"] == "127.0.0.1"  # kept by default


def test_replace_false_keeps_existing(monkeypatch):
    _clean(monkeypatch)
    import os

    monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
    hostenv.force_cpu_devices(8, replace=False)
    assert "count=4" in os.environ["XLA_FLAGS"]
    assert "count=8" not in os.environ["XLA_FLAGS"]
    hostenv.force_cpu_devices(8, replace=True)
    assert "count=8" in os.environ["XLA_FLAGS"]
    assert "count=4" not in os.environ["XLA_FLAGS"]


def test_none_removes_count_and_drops_tunnel(monkeypatch):
    _clean(monkeypatch)
    import os

    monkeypatch.setenv(
        "XLA_FLAGS", "--foo=1 --xla_force_host_platform_device_count=8 --bar=2"
    )
    hostenv.force_cpu_devices(n=None, drop_tpu_tunnel=True)
    assert "device_count" not in os.environ["XLA_FLAGS"]
    assert "--foo=1" in os.environ["XLA_FLAGS"]  # unrelated flags kept
    assert "--bar=2" in os.environ["XLA_FLAGS"]
    assert "PALLAS_AXON_POOL_IPS" not in os.environ


def test_collective_timeout_flags(monkeypatch):
    """The timeout flags are appended only when the installed jaxlib
    registers them — an unknown name in XLA_FLAGS aborts the process at
    backend init, so on older jaxlibs suppression IS the correct output."""
    _clean(monkeypatch)
    import os

    hostenv.force_cpu_devices(8, collective_timeout_s=600)
    flags = os.environ["XLA_FLAGS"]
    supported = hostenv._xla_flag_supported(
        "xla_cpu_collective_call_warn_stuck_timeout_seconds"
    )
    assert (
        "--xla_cpu_collective_call_warn_stuck_timeout_seconds=600" in flags
    ) is supported
    assert (
        "--xla_cpu_collective_call_terminate_timeout_seconds=1200" in flags
    ) is supported


def test_collective_timeout_flags_forced_supported(monkeypatch):
    """With the probe forced true, both deadlines are appended and
    de-duplicated on re-entry."""
    _clean(monkeypatch)
    import os

    monkeypatch.setattr(hostenv, "_xla_flag_supported", lambda name: True)
    hostenv.force_cpu_devices(8, collective_timeout_s=600)
    hostenv.force_cpu_devices(8, collective_timeout_s=600)
    flags = os.environ["XLA_FLAGS"]
    assert flags.count("warn_stuck_timeout_seconds=600") == 1
    assert flags.count("terminate_timeout_seconds=1200") == 1


def test_updates_config_when_jax_imported(monkeypatch):
    _clean(monkeypatch)
    import jax  # the test suite has jax imported already

    jax.config.update("jax_platforms", "cpu")  # conftest state
    hostenv.force_cpu_devices(8)
    assert jax.config.jax_platforms == "cpu"


def test_module_importable_without_jax_side_effects():
    """The module must not import jax at MODULE scope (it runs pre-init,
    at the very top of every entry script). Function-local imports are
    allowed in exactly one place — ``backend_preflight``'s probe thread,
    whose whole job is to touch backend init behind a deadline — so the
    check is structural (AST), not textual: no top-level jax/jaxlib
    import, and importing the module in a fresh process must not pull
    jax into sys.modules."""
    import ast
    import subprocess
    import sys

    src = importlib.util.find_spec(
        "network_distributed_pytorch_tpu.hostenv"
    ).origin
    with open(src) as f:
        tree = ast.parse(f.read(), filename=src)
    for node in tree.body:  # module scope only, by design
        if isinstance(node, ast.Import):
            assert not any(
                a.name.split(".")[0] in ("jax", "jaxlib")
                for a in node.names
            ), f"module-scope jax import at line {node.lineno}"
        elif isinstance(node, ast.ImportFrom):
            assert (node.module or "").split(".")[0] not in (
                "jax", "jaxlib",
            ), f"module-scope jax import at line {node.lineno}"
    proc = subprocess.run(
        [
            sys.executable, "-c",
            "import sys; from network_distributed_pytorch_tpu import "
            "hostenv; sys.exit(1 if any(m.split('.')[0] in ('jax', "
            "'jaxlib') for m in sys.modules) else 0)",
        ],
        capture_output=True,
    )
    assert proc.returncode == 0, proc.stderr.decode()
