"""Aux subsystems: multihost batch assembly (single-process path), profiling
context managers, bandwidth model arithmetic."""

import numpy as np

import jax
import jax.numpy as jnp

from network_distributed_pytorch_tpu.data.multihost import global_batch_from_local
from network_distributed_pytorch_tpu.parallel import make_mesh
from network_distributed_pytorch_tpu.utils.bandwidth import (
    allreduce_time_s,
    bandwidth_table,
)
from network_distributed_pytorch_tpu.utils.profiling import annotate


def test_global_batch_from_local(devices):
    mesh = make_mesh()
    batch = {"x": np.arange(32.0).reshape(16, 2), "y": np.arange(16)}
    g = global_batch_from_local(batch, mesh)
    assert g["x"].shape == (16, 2)
    # sharded over the data axis: each device holds 2 rows
    assert len(g["x"].sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(g["x"]), batch["x"])


def test_bandwidth_model():
    # 8 workers, 100 MB payload on 10GbE: ring 2*(7/8)*1e8/1.25e9 = 0.14 s
    t = allreduce_time_s(1e8, 8, "10GbE", n_collectives=1)
    assert abs(t - (2 * 7 / 8 * 1e8 / 1.25e9 + 30e-6)) < 1e-9
    table = bandwidth_table(bits_per_step=8 * 1e8, compute_time_s=0.05, n_workers=8)
    assert table["1GbE"].step_time_s > table["ICI(v5e)"].step_time_s
    assert 0 < table["ICI(v5e)"].comm_fraction < 1


def test_profiling_annotation_smoke():
    with annotate("test-region"):
        x = jnp.ones((4,)) + 1
    assert float(x.sum()) == 8.0


def test_profiler_trace_capture(tmp_path):
    """utils.profiling.trace captures a real profiler trace (the SURVEY §5
    'assert via profile' tooling): run a jitted computation under the
    context manager and assert the trace artifact exists on disk."""
    import glob

    import jax
    import jax.numpy as jnp

    from network_distributed_pytorch_tpu.utils.profiling import (
        step_annotation,
        trace,
    )

    f = jax.jit(lambda x: (x @ x.T).sum())
    x = jnp.ones((64, 64))
    jax.block_until_ready(f(x))  # compile outside the capture
    with trace(str(tmp_path)):
        for i in range(2):
            with step_annotation("train", i):
                jax.block_until_ready(f(x))
    files = glob.glob(str(tmp_path) + "/**/*.xplane.pb", recursive=True)
    assert files, f"no trace artifact written under {tmp_path}"
