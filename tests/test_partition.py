"""DataPartitioner unit tests (SURVEY §4: determinism / disjointness / coverage),
including an oracle check against the reference's exact shuffle semantics
(``partition_helper.py:20-32``: ``random.Random(1234).shuffle`` + fractional cuts)."""

import random

from network_distributed_pytorch_tpu.data import DataPartitioner, partition_dataset
from network_distributed_pytorch_tpu.data.partition import per_worker_batch_size


def test_determinism_across_ranks():
    # every rank constructs its own partitioner; permutations must agree
    data = list(range(1000))
    parts = [DataPartitioner(data, [0.25] * 4) for _ in range(4)]
    for rank in range(4):
        idx0 = parts[0].use(rank).index
        for p in parts[1:]:
            assert p.use(rank).index == idx0


def test_disjoint_and_coverage():
    data = list(range(1000))
    p = DataPartitioner(data, [0.25] * 4)
    all_idx = [i for r in range(4) for i in p.use(r).index]
    assert len(all_idx) == len(set(all_idx)) == 1000
    assert sorted(all_idx) == list(range(1000))


def test_fractional_truncation_drops_remainder():
    # int(frac * len) truncation: 10 items over 3 ranks -> 3+3+3, one dropped
    p = DataPartitioner(list(range(10)), [1 / 3] * 3)
    assert [len(p.use(r)) for r in range(3)] == [3, 3, 3]


def test_oracle_shuffle_semantics():
    # independently recompute the reference permutation
    data = list(range(100))
    rng = random.Random()
    rng.seed(1234)
    idx = list(range(100))
    rng.shuffle(idx)
    p = DataPartitioner(data, [0.5, 0.5])
    assert p.use(0).index == idx[:50]
    assert p.use(1).index == idx[50:]


def test_partition_view_remaps():
    data = [x * 10 for x in range(100)]
    part = partition_dataset(data, world_size=4, rank=2)
    for i in range(len(part)):
        assert part[i] == data[part.index[i]]


def test_per_worker_batch_size():
    assert per_worker_batch_size(256, 8) == 32  # ddp_guide_cifar10/ddp_init.py:49
    assert per_worker_batch_size(512, 4) == 128  # ddp_powersgd_guide_cifar10/ddp_init.py:52
