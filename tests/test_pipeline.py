"""GPipe-style pipeline over the 8-device 'pipe' mesh: forward ≡ sequential
stage application, gradients ≡ single-device autodiff, training converges,
and composition with a data axis on a 2×4 mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from network_distributed_pytorch_tpu.parallel import make_mesh
from network_distributed_pytorch_tpu.parallel.pipeline import (
    make_pipeline_fn,
    stacked_stage_params,
)

N = 8          # stages
B, DIM = 16, 6


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stage_params(seed):
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(rng.randn(DIM, DIM) * 0.5, jnp.float32),
        "b": jnp.asarray(rng.randn(DIM) * 0.1, jnp.float32),
    }


def _sequential(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


@pytest.mark.parametrize("microbatches", [2, 4, 8], ids=lambda m: f"mb{m}")
def test_pipeline_forward_matches_sequential(devices, microbatches):
    stages = [_stage_params(s) for s in range(N)]
    stacked = stacked_stage_params(stages)
    x = jnp.asarray(np.random.RandomState(99).randn(B, DIM), jnp.float32)
    ref = _sequential(stages, x)

    mesh = make_mesh(axis_sizes=(N,), axis_names=("pipe",))
    fn = make_pipeline_fn(_stage_fn, "pipe", microbatches)
    out = jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=(P("pipe"), P()), out_specs=P())
    )(stacked, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=1e-6)


def test_pipeline_gradients_match_single_device(devices):
    stages = [_stage_params(10 + s) for s in range(N)]
    stacked = stacked_stage_params(stages)
    x = jnp.asarray(np.random.RandomState(0).randn(B, DIM), jnp.float32)
    y = jnp.asarray(np.random.RandomState(1).randn(B, DIM), jnp.float32)

    def ref_loss(stacked_params):
        out = x
        for i in range(N):
            out = _stage_fn(jax.tree.map(lambda p: p[i], stacked_params), out)
        return jnp.mean((out - y) ** 2)

    ref_grads = jax.grad(ref_loss)(stacked)

    mesh = make_mesh(axis_sizes=(N,), axis_names=("pipe",))
    fn = make_pipeline_fn(_stage_fn, "pipe", 4, remat=True)

    def pipe_loss(stacked_params, x, y):
        out = fn(stacked_params, x)
        return jnp.mean((out - y) ** 2)

    grads = jax.jit(
        jax.shard_map(
            jax.grad(pipe_loss), mesh=mesh,
            in_specs=(P("pipe"), P(), P()), out_specs=P("pipe"),
        )
    )(stacked, x, y)
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[k]), np.asarray(ref_grads[k]), rtol=2e-4, atol=1e-6
        )


def test_pipeline_training_converges(devices):
    """PP-only training loop: per-stage SGD on the local stage params."""
    stages = [_stage_params(20 + s) for s in range(N)]
    stacked = stacked_stage_params(stages)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(B, DIM), jnp.float32)
    y = jnp.tanh(jnp.asarray(rng.randn(B, DIM), jnp.float32))

    mesh = make_mesh(axis_sizes=(N,), axis_names=("pipe",))
    fn = make_pipeline_fn(_stage_fn, "pipe", 4)

    def loss_fn(stacked_params, x, y):
        return jnp.mean((fn(stacked_params, x) - y) ** 2)

    @jax.jit
    def train_step(stacked_params, x, y):
        def body(p, x, y):
            l, g = jax.value_and_grad(loss_fn)(p, x, y)
            return jax.tree.map(lambda p_, g_: p_ - 0.2 * g_, p, g), l

        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P("pipe"), P(), P()), out_specs=(P("pipe"), P()),
        )(stacked_params, x, y)

    losses = []
    for _ in range(80):
        stacked, l = train_step(stacked, x, y)
        losses.append(float(l))
    # 8 stacked tanh stages train slowly; monotone-ish halving is the signal
    assert losses[-1] < 0.5 * losses[0], losses


def test_pipeline_composes_with_data_axis(devices):
    """2×4 mesh: batch sharded over 'data', stages over 'pipe'; forward equals
    sequential on the full batch."""
    n_pipe = 4
    stages = [_stage_params(30 + s) for s in range(n_pipe)]
    stacked = stacked_stage_params(stages)
    x = jnp.asarray(np.random.RandomState(3).randn(B, DIM), jnp.float32)
    ref = _sequential(stages, x)

    mesh = make_mesh(axis_sizes=(2, n_pipe), axis_names=("data", "pipe"))
    fn = make_pipeline_fn(_stage_fn, "pipe", 2)

    out = jax.jit(
        jax.shard_map(
            fn, mesh=mesh,
            in_specs=(P("pipe"), P("data")), out_specs=P("data"),
        )
    )(stacked, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=1e-6)


@pytest.mark.parametrize("microbatches", [2, 4, 16], ids=lambda m: f"mb{m}")
def test_1f1b_loss_and_grads_match_single_device(devices, microbatches):
    """Hand-scheduled 1F1B (make_pipeline_train_fn) ≡ plain autodiff of the
    sequential stage stack, for m below/equal/above the 2n-1 stash size."""
    from network_distributed_pytorch_tpu.parallel.pipeline import (
        make_pipeline_train_fn,
    )

    stages = [_stage_params(30 + s) for s in range(N)]
    stacked = stacked_stage_params(stages)
    BB = 32
    x = jnp.asarray(np.random.RandomState(3).randn(BB, DIM), jnp.float32)
    y = jnp.asarray(np.random.RandomState(4).randn(BB, DIM), jnp.float32)

    def mb_loss(out, label):
        return jnp.mean((out - label) ** 2)

    def ref_loss(stages, x, y):
        # mean over microbatches of the per-microbatch mean loss ≡ full-batch
        # mean loss (equal microbatch sizes)
        return mb_loss(_sequential(stages, x), y)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(stages, x, y)

    mesh = make_mesh(axis_sizes=(N,), axis_names=("pipe",))
    fn = make_pipeline_train_fn(_stage_fn, mb_loss, "pipe", microbatches)
    loss, grads = jax.jit(
        jax.shard_map(
            fn, mesh=mesh, in_specs=(P("pipe"), P(), P()),
            out_specs=(P(), P("pipe")),
        )
    )(stacked, x, y)

    np.testing.assert_allclose(float(loss), float(ref_l), rtol=2e-5)
    for s in range(N):
        got = jax.tree_util.tree_map(lambda g: np.asarray(g[s]), grads)
        exp = jax.tree_util.tree_map(np.asarray, ref_g[s])
        for a, b in zip(
            jax.tree_util.tree_leaves(got), jax.tree_util.tree_leaves(exp)
        ):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5)


def test_1f1b_trains(devices):
    """A few 1F1B SGD steps reduce the loss."""
    from network_distributed_pytorch_tpu.parallel.pipeline import (
        make_pipeline_train_fn,
    )

    stages = [_stage_params(50 + s) for s in range(N)]
    stacked = stacked_stage_params(stages)
    x = jnp.asarray(np.random.RandomState(5).randn(32, DIM), jnp.float32)
    y = jnp.asarray(np.random.RandomState(6).randn(32, DIM), jnp.float32)

    def mb_loss(out, label):
        return jnp.mean((out - label) ** 2)

    mesh = make_mesh(axis_sizes=(N,), axis_names=("pipe",))
    fn = make_pipeline_train_fn(_stage_fn, mb_loss, "pipe", 4)
    step = jax.jit(
        jax.shard_map(
            fn, mesh=mesh, in_specs=(P("pipe"), P(), P()),
            out_specs=(P(), P("pipe")),
        )
    )
    losses = []
    for _ in range(25):
        loss, grads = step(stacked, x, y)
        stacked = jax.tree_util.tree_map(lambda p, g: p - 0.3 * g, stacked, grads)
        losses.append(float(loss))
    assert losses[-1] < 0.8 * losses[0]
