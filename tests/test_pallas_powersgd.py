"""Fused Pallas PowerSGD kernels (``ops.pallas_powersgd``) vs NumPy and vs
the reference XLA pipeline (interpret mode on CPU; the same kernels compile
for TPU with Mosaic).

Three layers of pinning:

- kernel level: each fused op against plain NumPy fp32 math, including
  ragged (non-tile-multiple) matrix shapes;
- reducer level: ``PowerSGDReducer(compress_impl="pallas")`` against the
  default XLA pipeline for r ∈ {1, 4, 8}, uneven shape-bucket tails,
  rank-clipped matrices, and the bf16 wire dtype — same bits, same state,
  same out/mem up to fp32 accumulation order;
- step level: a full ef_momentum train step (grads flowing through the
  fused compress/decompress) lands on the same params as the XLA step.

Plus the bucketed-backward twin: ``ExactReducer(bucket_bytes=B)`` must stay
BITWISE identical to the monolithic reduction for K ∈ {1, 4} buckets (an
all-reduce is elementwise, so partitioning the payload commutes with it).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from network_distributed_pytorch_tpu.ops.pallas_powersgd import (
    fused_decompress_residual,
    fused_ef_compress,
    fused_orthogonalize_project,
)
from network_distributed_pytorch_tpu.parallel import (
    DATA_AXIS,
    ExactReducer,
    PowerSGDReducer,
    make_mesh,
)
from network_distributed_pytorch_tpu.parallel.reducers import PowerSGDState
from oracle_powersgd import orthogonalize_np

W = 8


def _bits(x):
    """uint bit-pattern view — equality here is BITWISE, not allclose."""
    x = np.asarray(x)
    return x.view({2: np.uint16, 4: np.uint32, 8: np.uint64}[x.dtype.itemsize])


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype=dtype)


# ---- kernel level: fused ops vs NumPy fp32 math ---------------------------


@pytest.mark.parametrize("g,n,m,r", [(1, 64, 32, 4), (3, 100, 37, 8), (2, 5, 3, 2)])
def test_fused_ef_compress_matches_numpy(g, n, m, r):
    """M = G + E and P = M·Q, ragged shapes included (interpret mode has no
    tile constraint; the BlockSpec carries whole matrices)."""
    grads = _rand(1, (g, n, m))
    resid = _rand(2, (g, n, m))
    q = _rand(3, (g, m, r))
    m_out, p_out = fused_ef_compress(grads, q, resid, interpret=True)
    exp_m = np.asarray(grads) + np.asarray(resid)
    exp_p = np.einsum("gnm,gmr->gnr", exp_m, np.asarray(q))
    np.testing.assert_allclose(np.asarray(m_out), exp_m, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(p_out), exp_p, rtol=1e-4, atol=1e-5)


def test_fused_compress_without_residual_is_plain_matmul():
    grads = _rand(4, (2, 48, 16))
    q = _rand(5, (2, 16, 4))
    m_out, p_out = fused_ef_compress(grads, q, interpret=True)
    # no EF add → the send matrix IS the gradient (modulo the jit boundary)
    np.testing.assert_array_equal(_bits(m_out), _bits(grads))
    np.testing.assert_allclose(
        np.asarray(p_out),
        np.einsum("gnm,gmr->gnr", np.asarray(grads), np.asarray(q)),
        rtol=1e-4, atol=1e-5,
    )


@pytest.mark.parametrize("g,n,m,r", [(1, 64, 32, 4), (2, 100, 37, 8), (2, 6, 9, 1)])
def test_fused_orthogonalize_project_matches_numpy(g, n, m, r):
    p = _rand(6, (g, n, r))
    mat = _rand(7, (g, n, m))
    phat, q = fused_orthogonalize_project(p, mat, interpret=True)
    for i in range(g):
        exp_phat = orthogonalize_np(np.asarray(p)[i])
        np.testing.assert_allclose(
            np.asarray(phat)[i], exp_phat, rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(q)[i], np.asarray(mat)[i].T @ exp_phat,
            rtol=1e-4, atol=1e-4,
        )


def test_fused_orthogonalize_output_is_orthonormal():
    phat, _ = fused_orthogonalize_project(
        _rand(8, (3, 200, 6)), _rand(9, (3, 200, 10)), interpret=True
    )
    for i in range(3):
        p = np.asarray(phat)[i]
        np.testing.assert_allclose(p.T @ p, np.eye(6), atol=1e-4)


@pytest.mark.parametrize("g,n,m,r", [(1, 64, 32, 4), (3, 100, 37, 8)])
def test_fused_decompress_residual_matches_numpy(g, n, m, r):
    p = _rand(10, (g, n, r))
    q = _rand(11, (g, m, r))
    mat = _rand(12, (g, n, m))
    out, mem = fused_decompress_residual(p, q, mat, interpret=True)
    exp_out = np.einsum("gnr,gmr->gnm", np.asarray(p), np.asarray(q))
    np.testing.assert_allclose(np.asarray(out), exp_out, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(mem), np.asarray(mat) - exp_out, rtol=1e-4, atol=1e-5
    )


def test_fused_decompress_bf16_accumulates_in_fp32():
    """The EF residual on a bf16 wire must be fp32 math cast ONCE at the
    end — bitwise equal to the fp32 NumPy computation, not to a bf16
    accumulation chain (r=8 inner products would diverge there)."""
    p = _rand(13, (2, 64, 8)).astype(jnp.bfloat16)
    q = _rand(14, (2, 32, 8)).astype(jnp.bfloat16)
    mat = _rand(15, (2, 64, 32)).astype(jnp.bfloat16)
    out, mem = fused_decompress_residual(p, q, mat, interpret=True)
    assert out.dtype == jnp.bfloat16 and mem.dtype == jnp.bfloat16
    exp_out = np.einsum(
        "gnr,gmr->gnm",
        np.asarray(p, np.float32), np.asarray(q, np.float32),
    )
    exp_mem = np.asarray(mat, np.float32) - exp_out
    np.testing.assert_array_equal(
        _bits(mem), _bits(jnp.asarray(exp_mem).astype(jnp.bfloat16))
    )
    np.testing.assert_array_equal(
        _bits(out), _bits(jnp.asarray(exp_out).astype(jnp.bfloat16))
    )


# ---- reducer level: fused pipeline vs the XLA reference -------------------


def _template_leaves(key):
    """A CNN-ish mix: conv-like 4D, linear-like 2D, and rank-1 bias leaves."""
    ks = jax.random.split(key, 5)
    return [
        jax.random.normal(ks[0], (8, 3, 3, 3)),
        jax.random.normal(ks[1], (16, 8)),
        jax.random.normal(ks[2], (16,)),
        jax.random.normal(ks[3], (10, 16)),
        jax.random.normal(ks[4], (10,)),
    ]


def _ragged_leaves(key):
    """Uneven shape buckets: three (16, 8) twins in ONE group (a ragged
    stack of 3 next to singleton groups), a (10, 16), and a (2, 3) whose
    rank clips to min(n, m) below every tested compression_rank."""
    ks = jax.random.split(key, 6)
    return [
        jax.random.normal(ks[0], (16, 8)),
        jax.random.normal(ks[1], (16, 8)),
        jax.random.normal(ks[2], (16, 8)),
        jax.random.normal(ks[3], (10, 16)),
        jax.random.normal(ks[4], (2, 3)),
        jax.random.normal(ks[5], (7,)),
    ]


def _compare_impls(template_fn, rank, seed, dtype_kw=None, rtol=2e-4, atol=1e-4):
    """reduce_ef (nonzero memories → the EF-fused kernel) on the fused and
    XLA paths: same bits, same state, same out/mem up to fp32 accumulation
    order. Single-process (axis_name=None) — the collectives are identity,
    so this isolates the compress pipeline itself."""
    kwargs = dict(random_seed=seed, compression_rank=rank, **(dtype_kw or {}))
    grads = [jnp.asarray(l) for l in template_fn(jax.random.PRNGKey(seed))]
    mems = [
        m if m.ndim <= 1 else m * 0.3
        for m in (jnp.zeros_like(l) if l.ndim <= 1 else l for l in
                  template_fn(jax.random.PRNGKey(seed + 1)))
    ]
    results = {}
    for impl in ("xla", "pallas"):
        reducer = PowerSGDReducer(compress_impl=impl, **kwargs)
        state = reducer.init(grads)
        results[impl] = reducer.reduce_ef(state, grads, mems, None)
    (st_x, out_x, mem_x, bits_x) = results["xla"]
    (st_p, out_p, mem_p, bits_p) = results["pallas"]
    assert bits_p == bits_x
    np.testing.assert_allclose(
        np.asarray(st_p.q_memory), np.asarray(st_x.q_memory),
        rtol=rtol, atol=atol,
    )
    for a, b in zip(out_p + mem_p, out_x + mem_x):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=rtol, atol=atol,
        )


@pytest.mark.parametrize("rank", [1, 4, 8])
def test_fused_reducer_matches_xla(rank):
    _compare_impls(_template_leaves, rank, seed=17 + rank)


@pytest.mark.parametrize("rank", [1, 4, 8])
def test_fused_reducer_matches_xla_ragged_buckets(rank):
    _compare_impls(_ragged_leaves, rank, seed=29 + rank)


def test_fused_reducer_matches_xla_bf16_wire():
    # bf16 on the wire, fp32 in the kernels: both impls quantize at the
    # same packer boundaries, so they still agree to bf16 resolution
    _compare_impls(
        _template_leaves, 4, seed=41,
        dtype_kw=dict(compression_dtype=jnp.bfloat16), rtol=2e-2, atol=2e-2,
    )


def test_fused_reducer_ef_identity():
    """send = out + memory exactly on the fused path too, per high-rank
    leaf — decompress subtracts against the VMEM-resident M = G + E."""
    reducer = PowerSGDReducer(
        random_seed=5, compression_rank=4, compress_impl="pallas"
    )
    grads = [jnp.asarray(l) for l in _template_leaves(jax.random.PRNGKey(7))]
    mems = [jnp.zeros_like(l) if l.ndim <= 1 else l * 0.5
            for l in _template_leaves(jax.random.PRNGKey(8))]
    _, out, mem, _ = reducer.reduce_ef(reducer.init(grads), grads, mems, None)
    for g, e, o, m in zip(grads, mems, out, mem):
        if g.ndim > 1:
            np.testing.assert_allclose(
                np.asarray(o) + np.asarray(m), np.asarray(g) + np.asarray(e),
                rtol=1e-5, atol=1e-5,
            )


def test_fused_reducer_matches_xla_multiworker(devices):
    """8-device shard_map: the fused pipeline slots between the SAME P/Q
    collectives (same placement, same bits) as the reference."""
    mesh = make_mesh()
    template = [jnp.zeros_like(l) for l in _template_leaves(jax.random.PRNGKey(0))]
    per_worker = [_template_leaves(jax.random.PRNGKey(100 + w)) for w in range(W)]
    stacked = [jnp.stack([pw[i] for pw in per_worker]) for i in range(5)]

    def run(impl):
        reducer = PowerSGDReducer(
            random_seed=11, compression_rank=2, compress_impl=impl
        )
        state = reducer.init(template)

        def f(q_memory, key, *send):
            send = [s[0] for s in send]
            st, out, mem, _ = reducer.reduce(
                PowerSGDState(q_memory, key), send, DATA_AXIS
            )
            return (
                st.q_memory,
                tuple(o[None] for o in out),
                tuple(m[None] for m in mem),
            )

        return jax.jit(
            jax.shard_map(
                f,
                mesh=mesh,
                in_specs=(P(), P()) + (P(DATA_AXIS),) * 5,
                out_specs=(P(), (P(DATA_AXIS),) * 5, (P(DATA_AXIS),) * 5),
            )
        )(state.q_memory, state.key, *stacked)

    q_x, out_x, mem_x = run("xla")
    q_p, out_p, mem_p = run("pallas")
    np.testing.assert_allclose(
        np.asarray(q_p), np.asarray(q_x), rtol=2e-4, atol=1e-4
    )
    for a, b in zip(out_p + mem_p, out_x + mem_x):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-4
        )


# ---- step level: grads through the fused path -----------------------------


def test_train_step_fused_matches_xla(devices):
    """Full ef_momentum steps (the trainer's reduce_ef → fused EF add →
    compress → decompress → SGD update) land on the same params."""
    from network_distributed_pytorch_tpu.models import SmallCNN
    from network_distributed_pytorch_tpu.parallel.trainer import (
        make_train_step,
        stateless_loss,
    )
    from network_distributed_pytorch_tpu.utils import cross_entropy_loss

    img = (8, 8, 3)
    model = SmallCNN(width=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *img)))["params"]

    def loss_fn(p, batch):
        x, y = batch
        return cross_entropy_loss(model.apply({"params": p}, x), y)

    loss_fn = stateless_loss(loss_fn)
    mesh = make_mesh()

    def run(impl):
        reducer = PowerSGDReducer(
            random_seed=3, compression_rank=2, compress_impl=impl
        )
        step = make_train_step(
            loss_fn, reducer, params, learning_rate=0.05, momentum=0.9,
            algorithm="ef_momentum", mesh=mesh, donate_state=False,
        )
        state = step.init_state(params)
        for i in range(3):
            ky, kx = jax.random.split(jax.random.PRNGKey(i))
            y = jax.random.randint(ky, (64,), 0, 10)
            x = jax.random.normal(kx, (64, *img))
            state, _ = step(state, (x, y))
        return state

    s_x = run("xla")
    s_p = run("pallas")
    for a, b in zip(
        jax.tree_util.tree_leaves(s_p.params),
        jax.tree_util.tree_leaves(s_x.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4
        )


# ---- bucketed backward overlap: bitwise identity --------------------------


def _run_exact(reducer, stacked):
    mesh = make_mesh()

    def f(*send):
        send = [s[0] for s in send]
        _, out, _, _ = reducer.reduce({}, send, DATA_AXIS)
        return tuple(o[None] for o in out)

    return jax.jit(
        jax.shard_map(
            f, mesh=mesh,
            in_specs=(P(DATA_AXIS),) * 5, out_specs=(P(DATA_AXIS),) * 5,
        )
    )(*stacked)


@pytest.mark.parametrize("bucket_bytes", [10**9, 60])
def test_bucketed_exact_bitwise_equals_monolithic(devices, bucket_bytes):
    """One giant bucket (K=1) and 4 small buckets (K=4): partitioning the packed
    payload commutes with the elementwise all-reduce, so the fenced bucket
    chain is BITWISE the monolithic reduction."""
    per_worker = [_template_leaves(jax.random.PRNGKey(50 + w)) for w in range(W)]
    stacked = [jnp.stack([pw[i] for pw in per_worker]) for i in range(5)]
    reducer = ExactReducer(bucket_bytes=bucket_bytes)
    n_buckets = len(reducer._buckets([pw for pw in per_worker[0]]))
    assert n_buckets == (1 if bucket_bytes == 10**9 else 4)
    mono = _run_exact(ExactReducer(), stacked)
    bucketed = _run_exact(reducer, stacked)
    for a, b in zip(bucketed, mono):
        np.testing.assert_array_equal(_bits(a), _bits(b))


@pytest.mark.parametrize("bucket_bytes", [10**9, 60])
def test_bucketed_ledger_bytes_invariant(bucket_bytes):
    """The buckets partition the leaves: ledger bytes are invariant and the
    entries itemize one backward-order bucket each."""
    template = _template_leaves(jax.random.PRNGKey(0))
    mono = ExactReducer()
    bucketed = ExactReducer(bucket_bytes=bucket_bytes)
    total = sum(e.payload_bytes * 1 for e in mono.ledger_entries(template))
    entries = bucketed.ledger_entries(template)
    assert sum(e.payload_bytes for e in entries) == total
    assert [e.tag for e in entries] == [
        f"grads.b{i}" for i in range(len(entries))
    ]
