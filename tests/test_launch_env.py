"""Launcher parser: mpirun-style env-var defaults (the reference documents
the OMPI_COMM_WORLD_* path, ddp_guide/run_script.py:8-22)."""

import os


def test_env_var_rank_defaults(monkeypatch):
    monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "3")
    monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "4")
    from network_distributed_pytorch_tpu.launch import build_parser

    args = build_parser().parse_args(["bare_init"])
    assert args.process_id == 3
    assert args.num_processes == 4
    # explicit flags still win
    args = build_parser().parse_args(["bare_init", "--process-id", "1"])
    assert args.process_id == 1


def test_config_from_args_overrides():
    from network_distributed_pytorch_tpu.launch import build_parser, config_from_args

    args = build_parser().parse_args(
        ["powersgd_cifar10", "--lr", "0.01", "--reducer-rank", "8", "--epochs", "2"]
    )
    cfg = config_from_args(args)
    assert cfg.learning_rate == 0.01
    assert cfg.reducer_rank == 8
    assert cfg.training_epochs == 2
