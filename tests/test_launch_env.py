"""Launcher parser: mpirun-style env-var defaults (the reference documents
the OMPI_COMM_WORLD_* path, ddp_guide/run_script.py:8-22) — plus a
slow-marked end-to-end CLI drive of an experiment subcommand."""

import os

import pytest


def test_env_var_rank_defaults(monkeypatch):
    monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "3")
    monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "4")
    from network_distributed_pytorch_tpu.launch import build_parser

    args = build_parser().parse_args(["bare_init"])
    assert args.process_id == 3
    assert args.num_processes == 4
    # explicit flags still win
    args = build_parser().parse_args(["bare_init", "--process-id", "1"])
    assert args.process_id == 1


def test_config_from_args_overrides():
    from network_distributed_pytorch_tpu.launch import build_parser, config_from_args

    args = build_parser().parse_args(
        ["powersgd_cifar10", "--lr", "0.01", "--reducer-rank", "8", "--epochs", "2"]
    )
    cfg = config_from_args(args)
    assert cfg.learning_rate == 0.01
    assert cfg.reducer_rank == 8
    assert cfg.training_epochs == 2


@pytest.mark.slow
def test_cli_drives_experiment_end_to_end():
    """The L5 surface the reference launches with run_script.py: ONE
    subprocess runs `python -m ...launch exact_cifar10 --preset small
    --epochs 1` on the 8-virtual-device CPU mesh (synthetic fallback data)
    and reports a finite mean loss plus the wire-byte accounting — the
    launcher -> config -> experiment -> trainer wiring end to end."""
    import re
    import subprocess
    import sys
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    # launch.py defaults rank/world-size from these (the mpirun path the
    # tests above pin); inherited values would make the child rendezvous
    env.pop("OMPI_COMM_WORLD_RANK", None)
    env.pop("OMPI_COMM_WORLD_SIZE", None)
    env["JAX_PLATFORMS"] = "cpu"
    # INHERIT the harness XLA_FLAGS (conftest's hostenv already put the
    # 8-device count AND the raised collective-rendezvous deadlines in
    # os.environ — overwriting would revert the child to the default 40 s
    # terminate deadline that aborts this workload class on a 1-core
    # host); only a standalone invocation without them needs a fallback
    if "xla_force_host_platform_device_count" not in env.get("XLA_FLAGS", ""):
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
            + " --xla_cpu_collective_call_warn_stuck_timeout_seconds=300"
            + " --xla_cpu_collective_call_terminate_timeout_seconds=600"
        ).strip()
    # share the suite's persistent compile cache: jax reads these env vars
    # at config init, so the child amortizes the 8-way shard_map compile
    # across runs like the in-process tests do
    import conftest

    cache = getattr(conftest, "_cache", None)
    if cache:
        env.setdefault("JAX_COMPILATION_CACHE_DIR", cache)
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    proc = subprocess.run(
        [sys.executable, "-u", "-m", "network_distributed_pytorch_tpu.launch",
         "exact_cifar10", "--preset", "small", "--epochs", "1",
         "--log-every", "0"],
        capture_output=True, text=True, timeout=540, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    m = re.search(
        r"epoch 0: mean loss ([\d.]+), ([\d.]+) MB communicated", proc.stdout
    )
    assert m, proc.stdout[-2000:]
    assert float(m.group(1)) < 10.0  # finite, sane cross-entropy
    assert float(m.group(2)) > 0.0  # bits accounting reported (SURVEY C9)
