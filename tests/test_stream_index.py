"""The streamed elastic index: bijectivity of the Feistel permutation,
residue-ownership tiling (the zero-drop/zero-dup argument), mid-shard
resume across a world reshape, a billion-index windowed property check
(nothing materialized), and a SIGKILL-mid-shard subprocess resume whose
committed sample multiset must equal an uninterrupted run's.

Deliberately jax-free: ``data/partition.py`` is loaded by path, so these
property tests (and the kill/resume subprocess) cost interpreter startup,
not a backend init."""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PARTITION = os.path.join(
    REPO, "network_distributed_pytorch_tpu", "data", "partition.py"
)


def _load_partition():
    spec = importlib.util.spec_from_file_location("_stream_pt", _PARTITION)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


pt = _load_partition()


# ---------------------------------------------------------------------------
# the permutation
# ---------------------------------------------------------------------------


def test_streamed_permutation_is_bijection():
    """apply over the full domain is a permutation of range(n) — including
    awkward sizes (1, powers of two, one past a power of two) — and
    invert is its exact inverse."""
    for n in (1, 2, 3, 7, 64, 65, 1000, 4097):
        perm = pt.StreamedPermutation(n, seed=5)
        offs = np.arange(n, dtype=np.int64)
        idx = perm.apply(offs)
        assert sorted(idx.tolist()) == list(range(n)), n
        np.testing.assert_array_equal(perm.invert(idx), offs)
    with pytest.raises(ValueError):
        pt.StreamedPermutation(0)
    with pytest.raises(ValueError):
        pt.StreamedPermutation(10).apply(np.array([10]))


def test_streamed_permutation_deterministic_and_keyed():
    """Same (seed, n) twice -> identical order across instances (the
    cross-incarnation contract); a different seed must actually re-key."""
    a = pt.StreamedPermutation(501, seed=9).apply(np.arange(501))
    b = pt.StreamedPermutation(501, seed=9).apply(np.arange(501))
    c = pt.StreamedPermutation(501, seed=10).apply(np.arange(501))
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_billion_index_windowed_property():
    """The acceptance property at scale: a 10^9-element stream, never
    materialized. Windows at the head, the middle, the tail, and across
    the epoch boundary must round-trip through invert, stay in range,
    and be duplicate-free within an epoch."""
    n = 1_000_000_000
    stream = pt.ElasticIndexStream(n, seed=3)
    perm = stream._perm(0)
    assert perm.domain <= 4 * n  # cycle-walk cost bound
    k = 100_000
    for start in (0, n // 2, n - k):
        offs = np.arange(start, start + k, dtype=np.int64)
        idx = perm.apply(offs)
        assert idx.min() >= 0 and idx.max() < n
        assert len(np.unique(idx)) == k  # injective on the window
        np.testing.assert_array_equal(perm.invert(idx), offs)
    # the epoch seam: positions straddling n re-key to epoch 1's
    # permutation and stay in range on both sides
    seam = np.arange(n - 50, n + 50, dtype=np.int64)
    idx = stream.indices_at(seam)
    assert idx.min() >= 0 and idx.max() < n
    assert len(np.unique(idx[:50])) == 50 and len(np.unique(idx[50:])) == 50
    assert not np.array_equal(
        stream.indices_at(np.arange(50)),
        stream.indices_at(n + np.arange(50)),
    )  # epochs reshuffle


# ---------------------------------------------------------------------------
# residue ownership: the zero-drop/zero-dup tiling
# ---------------------------------------------------------------------------


def _owned(cursor, group, world, rank):
    """Rank's share of the window [cursor, cursor+group) by residue."""
    want = [p for p in range(cursor, cursor + group) if p % world == rank]
    return np.asarray(want, dtype=np.int64)


def test_residue_windows_tile_exactly():
    """For ANY (cursor, window, W): the union of per-rank position sets is
    exactly [cursor, cursor+window), disjointly — the invariant that makes
    a reshape a no-op. shard_positions must agree with the residue spec."""
    for cursor in (0, 1, 7, 103):
        for world in (1, 2, 3, 5, 8):
            for group in (1, 4, 5, 17):
                stream = pt.ElasticIndexStream(997, seed=1)
                got = []
                for rank in range(world):
                    want = _owned(cursor, group, world, rank)
                    have = stream.shard_positions(
                        cursor, world, rank, len(want)
                    )
                    np.testing.assert_array_equal(have, want)
                    got.extend(have.tolist())
                assert sorted(got) == list(range(cursor, cursor + group))
    with pytest.raises(ValueError):
        pt.ElasticIndexStream(10).shard_positions(0, 2, 2, 1)


def test_streamed_elastic_assignments_non_divisible():
    """The elastic_assignments-shaped entry point on a non-divisible
    dataset: per-rank shares are disjoint, in range, and identical in
    SIZE across ranks (count = n // W, stream semantics — the remainder
    stays in the stream for the next window, it is never dropped)."""
    n, world = 103, 4
    shards = pt.streamed_elastic_assignments(n, world, seed=2)
    assert [len(s) for s in shards] == [n // world] * world
    flat = np.concatenate(shards)
    assert len(np.unique(flat)) == len(flat)
    assert flat.min() >= 0 and flat.max() < n
    # the remainder positions [100, 103) belong to the NEXT window: a
    # follow-up read at cursor=100 hands them out, no index lost
    stream = pt.ElasticIndexStream(n, seed=2)
    consumed = world * (n // world)
    rest = np.concatenate([
        stream.shard_indices(consumed, world, r, 1) for r in range(world)
    ])[: n - consumed]
    full = set(flat.tolist()) | set(rest.tolist())
    assert full == set(stream.indices_at(np.arange(n)).tolist())


def test_midshard_resume_after_2x2_to_2x1_reshape():
    """A 4-rank (2x2) world consumes to a cursor that divides NEITHER
    world size, reshapes to 2 ranks (2x1), and finishes the window. The
    combined multiset must equal the uninterrupted single-world read —
    zero drop, zero dup, no migration step in between."""
    n = 211
    stream = pt.ElasticIndexStream(n, seed=11)
    target = 2 * n + 17  # spans two epoch seams, ends mid-epoch
    cut = 93  # 93 % 4 == 1 and 93 % 2 == 1: genuinely mid-shard
    before = np.concatenate([
        stream.shard_indices(0, 4, r, len(_owned(0, cut, 4, r)))
        for r in range(4)
    ])
    after = np.concatenate([
        stream.shard_indices(
            cut, 2, r, len(_owned(cut, target - cut, 2, r))
        )
        for r in range(2)
    ])
    resharded = np.sort(np.concatenate([before, after]))
    straight = np.sort(stream.indices_at(np.arange(target)))
    np.testing.assert_array_equal(resharded, straight)


def test_state_roundtrip_and_schema_guard():
    stream = pt.ElasticIndexStream(4242, seed=6)
    doc = json.loads(json.dumps(stream.state(cursor=777)))
    back, cursor = pt.ElasticIndexStream.from_state(doc)
    assert cursor == 777
    assert (back.data_len, back.seed) == (4242, 6)
    np.testing.assert_array_equal(
        back.indices_at(np.arange(100)), stream.indices_at(np.arange(100))
    )
    with pytest.raises(ValueError):
        pt.ElasticIndexStream.from_state({**doc, "kind": "bogus"})
    with pytest.raises(ValueError):
        pt.ElasticIndexStream.from_state({**doc, "schema": 99})


# ---------------------------------------------------------------------------
# SIGKILL mid-shard, resume at a different world size
# ---------------------------------------------------------------------------

_WORKER = r"""
import importlib.util, json, os, sys, time

part_path, run_dir, world, group, target = sys.argv[1:6]
world, group, target = int(world), int(group), int(target)
spec = importlib.util.spec_from_file_location("p", part_path)
p = importlib.util.module_from_spec(spec)
spec.loader.exec_module(p)

state_path = os.path.join(run_dir, "loader_state.json")
log_path = os.path.join(run_dir, "consumed.jsonl")
if os.path.exists(state_path):
    with open(state_path) as f:
        stream, cursor = p.ElasticIndexStream.from_state(json.load(f))
else:
    stream, cursor = p.ElasticIndexStream(211, seed=11), 0

log = open(log_path, "a")
while cursor < target:
    group_now = min(group, target - cursor)
    indices = []
    for rank in range(world):
        count = len([
            q for q in range(cursor, cursor + group_now)
            if q % world == rank
        ])
        indices.extend(
            stream.shard_indices(cursor, world, rank, count).tolist()
        )
    # commit protocol: append the window's record, fsync, THEN advance the
    # durable cursor atomically — a kill between the two re-reads the same
    # window, and determinism makes the re-read byte-identical
    log.write(json.dumps(
        {"cursor": cursor, "world": world, "indices": sorted(indices)}
    ) + "\n")
    log.flush()
    os.fsync(log.fileno())
    tmp = state_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(stream.state(cursor + group_now), f)
    os.replace(tmp, state_path)
    cursor += group_now
    time.sleep(0.002)  # window for the parent's mid-run SIGKILL
"""


def test_sigkill_midshard_resume_zero_drop(tmp_path):
    """The acceptance test verbatim: a 4-rank consumer is SIGKILLed
    mid-stream (cursor persisted per committed window), the run resumes
    at world size 2 from the durable cursor, and the committed sample
    multiset equals the uninterrupted run's exactly. Windows replayed
    across the kill must be byte-identical (zero-dup by determinism)."""
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    group, target = 5, 2 * 211 + 12  # mid-shard windows, two epoch seams
    argv = [sys.executable, str(worker), _PARTITION, str(run_dir)]

    proc = subprocess.Popen(argv + ["4", str(group), str(target)])
    state_path = run_dir / "loader_state.json"
    deadline = time.monotonic() + 30.0
    cursor = 0
    while time.monotonic() < deadline:
        try:
            with open(state_path) as f:
                cursor = int(json.load(f)["cursor"])
        except (OSError, ValueError, KeyError):
            cursor = 0
        if cursor >= 60:
            break
        time.sleep(0.001)
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=30)
    assert proc.returncode == -signal.SIGKILL
    assert 0 < cursor < target, "kill must land mid-run"

    done = subprocess.run(
        argv + ["2", str(group), str(target)], timeout=120
    )
    assert done.returncode == 0

    by_cursor = {}
    with open(run_dir / "consumed.jsonl") as f:
        for line in f:
            rec = json.loads(line)
            prev = by_cursor.get(rec["cursor"])
            if prev is not None:  # the replayed window across the kill
                assert prev["indices"] == rec["indices"], rec["cursor"]
            by_cursor[rec["cursor"]] = rec
    assert sorted(by_cursor) == list(range(0, target, group))
    committed = np.sort(np.concatenate([
        by_cursor[c]["indices"] for c in sorted(by_cursor)
    ]))
    straight = np.sort(
        pt.ElasticIndexStream(211, seed=11).indices_at(np.arange(target))
    )
    np.testing.assert_array_equal(committed, straight)
    # both world sizes actually ran on the shared stream
    worlds = {rec["world"] for rec in by_cursor.values()}
    assert worlds == {4, 2}
