"""Numerical architecture parity with the reference's exact model classes
(torchvision ResNet, HF DistilBERT), on CPU with RANDOM weights: convert the
torch state_dict with ``models.import_weights`` and compare forward passes.
This proves both the architecture equivalence and the converter — so a real
pretrained checkpoint (the reference's starting point, SURVEY §5) imports
correctly when available on disk."""

import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")

from network_distributed_pytorch_tpu.models.distilbert import (
    DistilBertConfig,
    DistilBertForSequenceClassification,
)
from network_distributed_pytorch_tpu.models.import_weights import (
    distilbert_variables_from_torch,
    resnet_variables_from_torch,
)


# --- a minimal torch ResNet with torchvision's exact layout and state_dict
# naming (conv1/bn1/layerN.M.convK/downsample/fc), used as the numerical
# reference since torchvision itself is not installed in this image. This
# pins the semantics the converter targets: stride placement (v1.5: on the
# 3x3), pad-1 3x3 convs, pad-1 3x3/2 maxpool, eval-mode BN.

import torch.nn as tnn


class TorchBasicBlock(tnn.Module):
    def __init__(self, cin, cout, stride=1):
        super().__init__()
        self.conv1 = tnn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(cout)
        self.conv2 = tnn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = tnn.BatchNorm2d(cout)
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = tnn.Sequential(
                tnn.Conv2d(cin, cout, 1, stride, bias=False), tnn.BatchNorm2d(cout)
            )

    def forward(self, x):
        r = x if self.downsample is None else self.downsample(x)
        y = self.bn2(self.conv2(torch.relu(self.bn1(self.conv1(x)))))
        return torch.relu(r + y)


class TorchBottleneck(tnn.Module):
    def __init__(self, cin, planes, stride=1):
        super().__init__()
        cout = planes * 4
        self.conv1 = tnn.Conv2d(cin, planes, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(planes)
        self.conv2 = tnn.Conv2d(planes, planes, 3, stride, 1, bias=False)
        self.bn2 = tnn.BatchNorm2d(planes)
        self.conv3 = tnn.Conv2d(planes, cout, 1, bias=False)
        self.bn3 = tnn.BatchNorm2d(cout)
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = tnn.Sequential(
                tnn.Conv2d(cin, cout, 1, stride, bias=False), tnn.BatchNorm2d(cout)
            )

    def forward(self, x):
        r = x if self.downsample is None else self.downsample(x)
        y = torch.relu(self.bn1(self.conv1(x)))
        y = torch.relu(self.bn2(self.conv2(y)))
        y = self.bn3(self.conv3(y))
        return torch.relu(r + y)


class TorchResNet(tnn.Module):
    def __init__(self, stages, bottleneck, width=64, num_classes=10):
        super().__init__()
        self.conv1 = tnn.Conv2d(3, width, 7, 2, 3, bias=False)
        self.bn1 = tnn.BatchNorm2d(width)
        self.maxpool = tnn.MaxPool2d(3, 2, 1)
        expansion = 4 if bottleneck else 1
        block = TorchBottleneck if bottleneck else TorchBasicBlock
        cin = width
        for i, n in enumerate(stages):
            planes = width * 2**i
            blocks = []
            for j in range(n):
                stride = 2 if (i > 0 and j == 0) else 1
                blocks.append(block(cin, planes, stride))
                cin = planes * expansion
            setattr(self, f"layer{i + 1}", tnn.Sequential(*blocks))
        self.fc = tnn.Linear(cin, num_classes)
        self.stages = stages

    def forward(self, x):
        x = self.maxpool(torch.relu(self.bn1(self.conv1(x))))
        for i in range(len(self.stages)):
            x = getattr(self, f"layer{i + 1}")(x)
        x = x.mean(dim=(2, 3))
        return self.fc(x)


@pytest.mark.parametrize(
    "stages,bottleneck",
    [([2, 2, 2, 2], False), ([2, 2], True)],
)
def test_resnet_forward_parity(stages, bottleneck):
    torch.manual_seed(0)
    ref_model = TorchResNet(stages, bottleneck, width=16, num_classes=10).eval()
    # exercise non-trivial running stats (fresh BN has mean 0 / var 1)
    with torch.no_grad():
        for k, v in ref_model.state_dict().items():
            if "running_mean" in k:
                v.uniform_(-0.5, 0.5)
            if "running_var" in k:
                v.uniform_(0.5, 1.5)

    variables = resnet_variables_from_torch(ref_model.state_dict(), stages, bottleneck)
    from network_distributed_pytorch_tpu.models.resnet import (
        BasicBlock,
        BottleneckBlock,
        ResNet,
    )

    model = ResNet(
        stage_sizes=stages,
        block_cls=BottleneckBlock if bottleneck else BasicBlock,
        num_classes=10,
        width=16,
        norm="batch",
        stem="imagenet",
    )

    x = np.random.RandomState(0).randn(2, 64, 64, 3).astype(np.float32)
    with torch.no_grad():
        ref = ref_model(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    out = model.apply(
        {"params": variables["params"], "batch_stats": variables["batch_stats"]},
        jnp.asarray(x),
        train=False,
    )
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3, atol=1e-4)


@pytest.mark.slow
def test_distilbert_forward_parity():
    transformers = pytest.importorskip("transformers")
    hf_cfg = transformers.DistilBertConfig(
        vocab_size=200,
        max_position_embeddings=32,
        dim=48,
        n_layers=2,
        n_heads=4,
        hidden_dim=96,
        num_labels=2,
        dropout=0.0,
        attention_dropout=0.0,
    )
    torch.manual_seed(0)
    hf_model = transformers.DistilBertForSequenceClassification(hf_cfg).eval()

    cfg = DistilBertConfig(
        vocab_size=200,
        max_position_embeddings=32,
        dim=48,
        n_layers=2,
        n_heads=4,
        hidden_dim=96,
        num_labels=2,
    )
    model = DistilBertForSequenceClassification(cfg)
    variables = distilbert_variables_from_torch(hf_model.state_dict(), n_layers=2)

    rng = np.random.RandomState(1)
    ids = rng.randint(0, 200, (3, 16)).astype(np.int32)
    mask = np.ones((3, 16), np.int32)
    mask[1, 10:] = 0  # padded row exercises the attention mask path
    with torch.no_grad():
        ref = hf_model(
            input_ids=torch.from_numpy(ids).long(),
            attention_mask=torch.from_numpy(mask).long(),
        ).logits.numpy()
    out = model.apply(
        variables, jnp.asarray(ids), jnp.asarray(mask), deterministic=True
    )
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3, atol=1e-4)


def test_gpt2_forward_parity():
    transformers = pytest.importorskip("transformers")
    from network_distributed_pytorch_tpu.models import GPTConfig, GPTLM
    from network_distributed_pytorch_tpu.models.import_weights import (
        gpt2_variables_from_torch,
    )

    hf_cfg = transformers.GPT2Config(
        vocab_size=160, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        n_inner=64, resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        activation_function="gelu_new",
    )
    torch.manual_seed(0)
    hf_model = transformers.GPT2LMHeadModel(hf_cfg).eval()

    cfg = GPTConfig(
        vocab_size=160, max_position_embeddings=64, dim=32, n_layers=2,
        n_heads=4, hidden_dim=64, dropout=0.0,
    )
    model = GPTLM(cfg)
    variables = gpt2_variables_from_torch(hf_model.state_dict(), n_layers=2)

    rng = np.random.RandomState(1)
    ids = rng.randint(0, 160, (3, 20)).astype(np.int32)
    with torch.no_grad():
        ref = hf_model(input_ids=torch.from_numpy(ids).long()).logits.numpy()
    out = model.apply(variables, jnp.asarray(ids), deterministic=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3, atol=1e-4)
