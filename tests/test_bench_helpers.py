"""bench.py's jax-free logic: the peak-FLOPs device map, the artifact
pointers that ride the line, the phase-result merge, and the parent
orchestrator's resilience policy (hard per-phase timeouts, child respawn,
CPU fallback, cumulative emission) — driven by scripted fake children, no
backend and no subprocess needed. One exception: the slow-marked
``test_child_phases_real_jax_smoke`` at the bottom spawns the REAL
measurement child (subprocess + jax on one CPU device) to pin the phase
internals the fakes can't see."""

import importlib.util
import json
import os
import queue

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench(monkeypatch, **env):
    for k in list(os.environ):
        if k.startswith("BENCH_"):
            monkeypatch.delenv(k, raising=False)
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _FakeDevice:
    def __init__(self, platform, kind):
        self.platform = platform
        self.device_kind = kind


def test_peak_flops_device_map(monkeypatch):
    bench = _load_bench(monkeypatch)
    assert bench._peak_flops(_FakeDevice("tpu", "TPU v5 lite")) == 197e12
    assert bench._peak_flops(_FakeDevice("tpu", "TPU v5p")) == 459e12
    assert bench._peak_flops(_FakeDevice("tpu", "TPU v6e")) == 918e12
    # longest-match: "v5 lite" must not resolve via the bare "v5" entry
    assert bench._peak_flops(_FakeDevice("tpu", "tpu v5 litepod-8")) == 197e12
    assert bench._peak_flops(_FakeDevice("cpu", "cpu")) == 0.0  # smoke tier
    assert bench._peak_flops(_FakeDevice("tpu", "TPU v99")) == 0.0  # unknown


def test_artifact_pointers_ride_the_line(monkeypatch):
    """The committed evidence artifacts surface as compact pointers in the
    bench payload (device + phase list + freshness, study deltas)."""
    bench = _load_bench(monkeypatch)
    out = {}
    bench._artifact_pointers(out)
    # ACCURACY_STUDY.json is committed — pointers must decode it
    assert "accuracy_study" in out
    assert out["accuracy_study"]["cifar"]["gradient_bytes_ratio"] > 10
    assert "tpu_evidence" in out
    assert isinstance(out["tpu_evidence"]["phases_ok"], list)
    # the committed mid-round chip bench run rides the line too, so even a
    # CPU-fallback driver line names the round's real-TPU measurement
    assert out["midround_chip_bench"]["flagship_imgs_per_sec"] > 0
    assert out["midround_chip_bench"]["vs_baseline"] > 0
    json.dumps(out)  # the line must stay serializable


def test_merge_tier_guard(monkeypatch):
    """A fallback-tier arm never silently pairs with a TPU arm: the headline
    ratio is withheld on tier mismatch and the value carries value_tier."""
    bench = _load_bench(monkeypatch)
    out, status = {"value": 0.0, "vs_baseline": 0.0}, {}
    bench._merge(out, "baseline", True, {"baseline_imgs_per_sec": 100.0}, status)
    bench._merge(
        out, "flagship", True, {"flagship_imgs_per_sec": 400.0}, status,
        tier="cpu-smoke-fallback",
    )
    assert status["flagship"] == "ok [cpu-smoke-fallback]"
    assert out["value"] == 400.0
    assert out["value_tier"] == "cpu-smoke-fallback"  # self-describing headline
    assert out["vs_baseline"] == 0.0  # cross-tier ratio never computed


def test_midround_pointer_rejects_fallback_tiers(monkeypatch, tmp_path):
    """The BENCH_MIDROUND republish gate: flagship must be plain-ok TPU, and
    baseline-derived fields are dropped unless baseline was plain-ok too."""
    bench = _load_bench(monkeypatch)
    art_dir = tmp_path / "artifacts"
    art_dir.mkdir()
    mid = {
        "platform": "tpu", "device": "TPU v5 lite", "recorded_unix": 1,
        "flagship_imgs_per_sec": 22801.0, "baseline_imgs_per_sec": 40.0,
        "vs_baseline": 570.0, "mfu": 0.005,
        "phases": {"flagship": "ok", "baseline": "ok [cpu-smoke-fallback]"},
    }
    (art_dir / "BENCH_MIDROUND.json").write_text(json.dumps(mid))
    monkeypatch.setattr(bench, "HERE", str(tmp_path))
    out = {}
    bench._artifact_pointers(out)
    ptr = out["midround_chip_bench"]
    assert ptr["flagship_imgs_per_sec"] == 22801.0
    # the CPU-fallback baseline (and the ratio built on it) must NOT be
    # re-exported under the chip label
    assert "baseline_imgs_per_sec" not in ptr and "vs_baseline" not in ptr
    # and a fallback-tier flagship disqualifies the pointer entirely
    mid["phases"]["flagship"] = "ok [cpu-smoke-fallback]"
    (art_dir / "BENCH_MIDROUND.json").write_text(json.dumps(mid))
    out2 = {}
    bench._artifact_pointers(out2)
    assert "midround_chip_bench" not in out2


def test_run_with_deadline(monkeypatch):
    """The child-side phase deadline: a slow phase is abandoned with
    TimeoutError (no SIGKILL needed — the tunnel-wedge prevention), a fast
    one returns its data, and a crashing one relays its exception."""
    import time as _time

    import pytest

    bench = _load_bench(monkeypatch)
    assert bench._run_with_deadline("x", lambda: {"a": 1}, 5.0) == {"a": 1}
    with pytest.raises(TimeoutError, match="abandoned"):
        bench._run_with_deadline("slow", lambda: _time.sleep(30), 0.2)

    def boom():
        raise ValueError("inner")

    with pytest.raises(ValueError, match="inner"):
        bench._run_with_deadline("crash", boom, 5.0)


def test_merge_builds_value_and_ratio(monkeypatch):
    bench = _load_bench(monkeypatch)
    out, status = {"value": 0.0, "vs_baseline": 0.0}, {}
    bench._merge(out, "probe", True, {"device": "TPU v5e", "platform": "tpu",
                                      "n_devices": 4}, status)
    assert out["device"] == "TPU v5e" and status["probe"] == "ok"
    assert out["n_devices"] == 4  # the measured device count rides the line
    bench._merge(out, "flagship", True,
                 {"flagship_imgs_per_sec": 1000.0, "step_time_ms": 2.0}, status)
    assert out["value"] == 1000.0  # flagship IS the headline metric
    bench._merge(out, "baseline", True, {"baseline_imgs_per_sec": 250.0}, status)
    assert out["vs_baseline"] == 4.0
    bench._merge(out, "gpt", False, {"error": "boom"}, status)
    assert status["gpt"].startswith("error: boom")
    assert "gpt" not in out  # failed phases contribute no fields


class _FakeChild:
    """Scripted stand-in for bench._ChildProc: a list of events, where an
    event is a dict (phase line), None (EOF), or "hang" (queue.Empty —
    what a compile wedged in C++ looks like to the parent)."""

    spawns = []  # [(phases, script), ...] consumed in order
    killed = []
    timeouts = []  # budget passed to every next_event call, in order

    def __init__(self, phases):
        assert _FakeChild.spawns, f"unexpected spawn for phases={phases}"
        expect, self.script = _FakeChild.spawns.pop(0)
        assert list(phases) == expect, (phases, expect)

    def next_event(self, timeout_s):
        _FakeChild.timeouts.append(round(timeout_s))
        ev = self.script.pop(0)
        if ev == "hang":
            raise queue.Empty()
        return ev

    def kill(self):
        _FakeChild.killed.append(True)


def _ok(phase, **data):
    return {"phase": phase, "ok": True, "data": data}


def _run_orchestrator(bench, tmp_path, spawns):
    lines = []
    _FakeChild.spawns = spawns
    _FakeChild.killed = []
    _FakeChild.timeouts = []
    bench._ChildProc = _FakeChild
    bench._emit = lambda payload: lines.append(json.loads(json.dumps(payload)))
    # a successful fake TPU run self-persists artifacts/BENCH_MIDROUND.json
    # (_persist_midround) — point HERE at pytest's managed tmp dir so
    # orchestrator tests can never overwrite the repo's committed record
    bench.HERE = str(tmp_path)
    assert bench.orchestrate() == 0
    assert not _FakeChild.spawns, "orchestrator under-spawned"
    return lines


def test_orchestrator_happy_path(monkeypatch, tmp_path):
    """One child serves every phase; a cumulative line lands after each;
    the full record is final (partial=False) and the very last line is the
    bounded summary digest of it."""
    bench = _load_bench(monkeypatch)
    all_phases = list(bench.PHASES)
    lines = _run_orchestrator(bench, tmp_path, [(all_phases, [
        _ok("probe", device="TPU v5e", platform="tpu", n_devices=1),
        _ok("flagship", flagship_imgs_per_sec=1000.0, step_time_ms=2.56,
            mfu=0.41, preset="full"),
        _ok("baseline", baseline_imgs_per_sec=100.0),
        _ok("gpt", gpt={"step_time_ms": 50.0, "mfu": 0.35}),
        _ok("fp32arm", fp32_scanned_imgs_per_sec=300.0),
        _ok("overlap", overlap={"combiner_merged": True}),
        _ok("loader", loader_samples_per_s=200000.0, data_load_share=0.03),
        _ok("serving", serving_tokens_per_s_per_chip=800.0,
            kv_capacity_ratio=4.0, p99_decode_ms_per_token=2.0),
        None,
    ])])
    # first line precedes any backend touch and is already valid
    assert lines[0]["partial"] is True and lines[0]["value"] == 0.0
    tail = lines[-2]  # the authoritative full record
    assert tail["partial"] is False
    assert tail["value"] == 1000.0 and tail["vs_baseline"] == 10.0
    assert tail["device"] == "TPU v5e"
    assert tail["gpt"]["mfu"] == 0.35
    assert all(tail["phases"][p] == "ok" for p in bench.PHASES)
    # the LAST line is the bounded summary: same headline numbers, always
    # small enough for a fixed-size stdout tail
    summary = lines[-1]
    assert summary["summary"] is True
    assert summary["value"] == 1000.0 and summary["vs_baseline"] == 10.0
    assert summary["phases"]["flagship"] == "ok"
    assert len(json.dumps(summary)) <= bench._SUMMARY_LIMIT
    # per-phase cumulative lines + first line + full record + summary
    assert len(lines) == 3 + len(bench.PHASES)


def test_orchestrator_survives_hang_and_respawns(monkeypatch, tmp_path):
    """A child wedged mid-flagship (the round-3 killer) costs exactly that
    phase: the parent kills it, respawns for the remainder, and the tail
    line still carries everything else."""
    bench = _load_bench(monkeypatch)
    lines = _run_orchestrator(bench, tmp_path, [
        (list(bench.PHASES), [
            _ok("probe", device="TPU v5e", platform="tpu", n_devices=1),
            "hang",  # flagship compile wedged in C++
        ]),
        (["baseline", "gpt", "fp32arm", "overlap", "loader", "serving"], [
            _ok("baseline", baseline_imgs_per_sec=100.0),
            _ok("gpt", gpt={"step_time_ms": 50.0}),
            _ok("fp32arm", fp32_scanned_imgs_per_sec=300.0),
            _ok("overlap", overlap={"combiner_merged": True}),
            _ok("loader", loader_samples_per_s=200000.0),
            _ok("serving", serving_tokens_per_s_per_chip=800.0),
            None,
        ]),
    ])
    tail = lines[-1]
    assert tail["phases"]["flagship"].startswith("timeout")
    assert tail["phases"]["baseline"] == "ok"
    assert tail["phases"]["overlap"] == "ok"
    assert tail["value"] == 0.0  # flagship lost → headline honestly absent
    assert _FakeChild.killed  # the wedged child was hard-killed


def test_orchestrator_cpu_fallback_after_two_init_failures(monkeypatch, tmp_path):
    """Two consecutive init failures degrade to the clearly-labeled CPU
    smoke tier; the TPU error stays on the line."""
    bench = _load_bench(monkeypatch)
    init_fail = [{"phase": "__init__", "ok": False,
                  "data": {"error": "TimeoutError: init exceeded 240s"}}]
    all_phases = list(bench.PHASES)
    lines = _run_orchestrator(bench, tmp_path, [
        (all_phases, list(init_fail)),
        (all_phases, list(init_fail)),
        (all_phases, [  # post-fallback child, now on cpu
            _ok("probe", device="cpu", platform="cpu", n_devices=8),
            _ok("flagship", flagship_imgs_per_sec=50.0, preset="small"),
            _ok("baseline", baseline_imgs_per_sec=25.0),
            _ok("gpt", gpt={"step_time_ms": 400.0}),
            _ok("overlap", overlap={"combiner_merged": True}),
            _ok("loader", loader_samples_per_s=100000.0),
            _ok("serving", serving_tokens_per_s_per_chip=80.0),
            None,
        ]),
    ])
    tail = lines[-1]
    assert os.environ.get("BENCH_PLATFORM") == "cpu"  # set for the fallback
    assert tail["tpu_error"].startswith("TimeoutError")
    assert tail["device"] == "cpu" and tail["value"] == 50.0
    os.environ.pop("BENCH_PLATFORM", None)  # orchestrate mutated real env


def test_orchestrator_no_cpu_fallback_env(monkeypatch, tmp_path):
    """BENCH_NO_CPU_FALLBACK=1 restores fail-hard: two init failures end
    the run with the error on the line and every phase unresolved."""
    bench = _load_bench(monkeypatch, BENCH_NO_CPU_FALLBACK="1")
    init_fail = [{"phase": "__init__", "ok": False,
                  "data": {"error": "RuntimeError: UNAVAILABLE"}}]
    all_phases = list(bench.PHASES)
    lines = _run_orchestrator(bench, tmp_path, [
        (all_phases, list(init_fail)),
        (all_phases, list(init_fail)),
    ])
    tail = lines[-1]
    assert tail["value"] == 0.0
    assert tail["tpu_error"].startswith("RuntimeError")
    assert all(tail["phases"][p].startswith("skipped") for p in bench.PHASES)
    assert os.environ.get("BENCH_PLATFORM") is None


def test_orchestrator_counts_silent_child_death_as_init_failure(monkeypatch, tmp_path):
    """A child that dies before emitting ANY marker line (native crash in
    the PJRT client during backend init — no Python exception, no __init__
    report) must count toward the init-failure fallback policy instead of
    burning one phase per crash."""
    bench = _load_bench(monkeypatch)
    all_phases = list(bench.PHASES)
    lines = _run_orchestrator(bench, tmp_path, [
        (all_phases, [None]),  # EOF with zero events
        (all_phases, [None]),  # again → 2 init failures → CPU fallback
        (all_phases, [
            _ok("probe", device="cpu", platform="cpu", n_devices=8),
            _ok("flagship", flagship_imgs_per_sec=50.0, preset="small"),
            _ok("baseline", baseline_imgs_per_sec=25.0),
            _ok("gpt", gpt={"step_time_ms": 400.0}),
            _ok("overlap", overlap={"combiner_merged": True}),
            _ok("loader", loader_samples_per_s=100000.0),
            _ok("serving", serving_tokens_per_s_per_chip=80.0),
            None,
        ]),
    ])
    tail = lines[-1]
    assert tail["tpu_error"] == "child process died during backend init"
    # phases measured AFTER the degrade carry the tier tag so a mixed line
    # can't read as all-TPU
    assert tail["value"] == 50.0
    assert tail["phases"]["probe"] == "ok [cpu-smoke-fallback]"
    os.environ.pop("BENCH_PLATFORM", None)  # orchestrate mutated real env


def test_first_event_budget_includes_init_grace(monkeypatch, tmp_path):
    """A child's FIRST event window covers process start + jax import + the
    backend-init watchdog; later phases in the same child get the bare
    phase budget. A respawned child's first phase gets the grace again —
    otherwise an init hang after a mid-run kill would be misclassified as
    a per-phase timeout and never count toward the CPU fallback."""
    bench = _load_bench(monkeypatch)
    lines = _run_orchestrator(bench, tmp_path, [
        (list(bench.PHASES), [
            _ok("probe", device="TPU v5e", platform="tpu", n_devices=1),
            "hang",  # flagship wedged -> kill -> respawn
        ]),
        (["baseline", "gpt", "fp32arm", "overlap", "loader", "serving"], [
            _ok("baseline", baseline_imgs_per_sec=100.0),
            _ok("gpt", gpt={}),
            _ok("fp32arm", fp32_scanned_imgs_per_sec=300.0),
            _ok("overlap", overlap={}),
            _ok("loader", loader_samples_per_s=200000.0),
            _ok("serving", serving_tokens_per_s_per_chip=800.0),
            None,
        ]),
    ])
    t = _FakeChild.timeouts
    g = bench.INIT_GRACE_S
    assert t[0] == bench.PHASE_BUDGET_S["probe"] + g     # child 1, first event
    assert t[1] == bench.PHASE_BUDGET_S["flagship"]      # same child, no grace
    assert t[2] == bench.PHASE_BUDGET_S["baseline"] + g  # respawn, grace again
    assert t[3] == bench.PHASE_BUDGET_S["gpt"]
    assert lines[-1]["phases"]["baseline"] == "ok"


def test_cpu_fallback_gets_fresh_init_failure_budget(monkeypatch, tmp_path):
    """After the fallback engages, init_failures is reset: one CPU-child
    hiccup (timeout/early exit) must trigger a respawn, not abort the whole
    orchestration."""
    bench = _load_bench(monkeypatch)
    init_fail = [{"phase": "__init__", "ok": False,
                  "data": {"error": "TimeoutError: init exceeded 240s"}}]
    all_phases = list(bench.PHASES)
    lines = _run_orchestrator(bench, tmp_path, [
        (all_phases, list(init_fail)),
        (all_phases, list(init_fail)),       # -> CPU fallback
        (all_phases, [
            _ok("probe", device="cpu", platform="cpu", n_devices=8),
            "hang",                           # CPU child wedges on flagship
        ]),
        (["baseline", "gpt", "fp32arm", "overlap", "loader", "serving"], [
            # respawned
            _ok("baseline", baseline_imgs_per_sec=25.0),
            _ok("gpt", gpt={}),
            _ok("fp32arm", fp32_scanned_imgs_per_sec=30.0),
            _ok("overlap", overlap={}),
            _ok("loader", loader_samples_per_s=100000.0),
            _ok("serving", serving_tokens_per_s_per_chip=80.0),
            None,
        ]),
    ])
    tail = lines[-1]
    assert tail["phases"]["flagship"].startswith("timeout")
    assert tail["phases"]["overlap"] == "ok [cpu-smoke-fallback]"
    os.environ.pop("BENCH_PLATFORM", None)  # orchestrate mutated real env


def test_orchestrator_waits_for_abandoned_drain(monkeypatch, tmp_path):
    """After the last phase reports, the parent must NOT kill the child
    immediately: an abandoned phase's daemon thread may still be inside a
    remote compile, and killing the process mid-request wedges the
    tunnel's remote side for hours (the 03:37 r4 run). The parent waits
    for the child's __drain__ report + EOF; the kill is a no-op backstop."""
    bench = _load_bench(monkeypatch)
    all_phases = list(bench.PHASES)
    lines = _run_orchestrator(bench, tmp_path, [(all_phases, [
        _ok("probe", device="TPU v5e", platform="tpu", n_devices=1),
        _ok("flagship", flagship_imgs_per_sec=1000.0, step_time_ms=2.56,
            preset="full"),
        _ok("baseline", baseline_imgs_per_sec=100.0),
        {"phase": "gpt", "ok": False,
         "data": {"error": "_PhaseAbandoned: phase gpt exceeded ..."}},
        _ok("overlap", overlap={"combiner_merged": True}),
        _ok("loader", loader_samples_per_s=200000.0),
        _ok("serving", serving_tokens_per_s_per_chip=800.0),
        {"phase": "__drain__", "ok": True,
         "data": {"drained": ["gpt"], "still_alive": []}},
        None,  # child exits on its own AFTER draining
    ])])
    full = lines[-2]  # abandoned_drain is full-record detail, not summary
    assert full["abandoned_drain"] == {"drained": ["gpt"], "still_alive": []}
    assert full["phases"]["gpt"].startswith("error")
    assert _FakeChild.killed == [True]  # backstop fired once, after EOF


def test_orchestrator_kills_immediately_on_giveup(monkeypatch, tmp_path):
    """A parent-side timeout means the child is WEDGED — the kill backstop
    must fire without a drain wait (waiting on a wedged child would burn
    the remaining window for nothing)."""
    bench = _load_bench(monkeypatch)
    lines = _run_orchestrator(bench, tmp_path, [
        (list(bench.PHASES), [
            _ok("probe", device="TPU v5e", platform="tpu", n_devices=1),
            _ok("flagship", flagship_imgs_per_sec=1000.0, preset="full"),
            _ok("baseline", baseline_imgs_per_sec=100.0),
            _ok("gpt", gpt={"step_time_ms": 50.0}),
            _ok("fp32arm", fp32_scanned_imgs_per_sec=300.0),
            _ok("loader", loader_samples_per_s=200000.0),
            _ok("serving", serving_tokens_per_s_per_chip=800.0),
            "hang",  # overlap wedged — the LAST pending phase
        ]),
    ])
    tail = lines[-1]
    assert tail["phases"]["overlap"].startswith("timeout")
    assert _FakeChild.killed == [True]


def test_run_with_deadline_registers_abandoned_thread(monkeypatch):
    """An abandoned phase's thread lands in _ABANDONED_THREADS so the
    child's end-of-run drain can join it before process exit."""
    import threading as _threading

    bench = _load_bench(monkeypatch)
    bench._ABANDONED_THREADS.clear()
    release = _threading.Event()

    def slow():
        release.wait(10.0)
        return {}

    try:
        bench._run_with_deadline("gpt", slow, 0.05)
    except bench._PhaseAbandoned:
        pass
    else:  # pragma: no cover - the deadline must fire
        raise AssertionError("expected _PhaseAbandoned")
    t = bench._ABANDONED_THREADS.get("gpt")
    assert t is not None and t.is_alive()
    release.set()  # the "compile" finishes; the drain join must succeed
    t.join(5.0)
    assert not t.is_alive()


def test_midround_self_persists_on_full_tpu_run(monkeypatch, tmp_path):
    """A fully-successful TPU run writes artifacts/BENCH_MIDROUND.json
    (in the scratch HERE) so later bench lines can point at it; a
    CPU-tier or partial run must NOT (same bar as the pointer guard)."""
    bench = _load_bench(monkeypatch)
    all_phases = list(bench.PHASES)
    _run_orchestrator(bench, tmp_path, [(all_phases, [
        _ok("probe", device="TPU v5e", platform="tpu", n_devices=1),
        _ok("flagship", flagship_imgs_per_sec=1000.0, step_time_ms=2.56,
            preset="full"),
        _ok("baseline", baseline_imgs_per_sec=100.0),
        _ok("gpt", gpt={"step_time_ms": 50.0}),
        _ok("overlap", overlap={"combiner_merged": True}),
        _ok("loader", loader_samples_per_s=200000.0),
        _ok("serving", serving_tokens_per_s_per_chip=800.0),
        None,
    ])])
    path = os.path.join(bench.HERE, "artifacts", "BENCH_MIDROUND.json")
    with open(path) as f:
        rec = json.load(f)
    assert rec["flagship_imgs_per_sec"] == 1000.0
    assert rec["platform"] == "tpu" and rec["phases"]["baseline"] == "ok"
    assert "midround_chip_bench" not in rec  # no self-reference chains

    # CPU probe (smoke tier): nothing persisted
    bench2 = _load_bench(monkeypatch)
    cpu_dir = tmp_path / "cpu-run"
    cpu_dir.mkdir()
    _run_orchestrator(bench2, cpu_dir, [(all_phases, [
        _ok("probe", device="cpu", platform="cpu", n_devices=8),
        _ok("flagship", flagship_imgs_per_sec=60.0, preset="small"),
        _ok("baseline", baseline_imgs_per_sec=30.0),
        _ok("gpt", gpt={"step_time_ms": 50.0}),
        _ok("overlap", overlap={"combiner_merged": True}),
        _ok("loader", loader_samples_per_s=100000.0),
        _ok("serving", serving_tokens_per_s_per_chip=80.0),
        None,
    ])])
    assert not os.path.exists(
        os.path.join(str(cpu_dir), "artifacts", "BENCH_MIDROUND.json")
    )


def test_init_hang_retries_once_then_engages_fallback(monkeypatch, tmp_path):
    """An init HANG (_InitTimeout) gets exactly ONE retry probe — transient
    tunnel contention clears about half of them — and the second hang
    exhausts the two-strike budget and engages the CPU fallback. The retry
    is published as ``init_retries`` in the summary."""
    bench = _load_bench(monkeypatch)
    hang = [{"phase": "__init__", "ok": False,
             "data": {"error": "_InitTimeout: jax backend init exceeded 240s"}}]
    all_phases = list(bench.PHASES)
    lines = _run_orchestrator(bench, tmp_path, [
        (all_phases, list(hang)),  # strike one -> one retry probe follows
        (all_phases, list(hang)),  # strike two -> budget spent, CPU fallback
        (all_phases, [
            _ok("probe", device="cpu", platform="cpu", n_devices=8),
            _ok("flagship", flagship_imgs_per_sec=50.0, preset="small"),
            _ok("baseline", baseline_imgs_per_sec=25.0),
            _ok("gpt", gpt={"step_time_ms": 400.0}),
            _ok("fp32arm", fp32_scanned_imgs_per_sec=30.0),
            _ok("overlap", overlap={"combiner_merged": True}),
            _ok("serving", serving_tokens_per_s_per_chip=80.0),
            None,
        ]),
    ])
    tail = lines[-1]
    assert tail["tpu_error"].startswith("_InitTimeout")
    assert tail["init_retries"] == 1
    assert tail["device"] == "cpu" and tail["value"] == 50.0
    os.environ.pop("BENCH_PLATFORM", None)  # orchestrate mutated real env


def test_flops_band_disjoint_windows_unchanged(monkeypatch):
    """At the production CHUNK (>= 8) the two ±2x windows are disjoint and
    the helper reproduces the old classification exactly."""
    bench = _load_bench(monkeypatch)
    assert bench._flops_band(50.0, 50) == "trip"
    assert bench._flops_band(25.0, 50) == "trip"   # lower window edge
    assert bench._flops_band(100.0, 50) == "trip"  # upper window edge
    assert bench._flops_band(1.0, 50) == "once"
    assert bench._flops_band(0.5, 50) == "once"
    assert bench._flops_band(2.0, 50) == "once"
    assert bench._flops_band(7.0, 50) is None      # between the windows
    assert bench._flops_band(0.4, 50) is None      # below both
    assert bench._flops_band(101.0, 50) is None    # above both
    assert bench._flops_band(0.0, 50) is None      # degenerate input


def test_flops_band_small_chunk_overlap_resolved(monkeypatch):
    """The bug: for CHUNK <= 4 the windows [chunk/2, 2*chunk] and [0.5, 2]
    OVERLAP, and the old ``if`` ordering classified every overlap ratio as
    trip-multiplied — silently dividing a count-once flops figure by
    chunk. The helper resolves the overlap by nearest band center in log
    space."""
    bench = _load_bench(monkeypatch)
    # chunk=2: 1.2 is nearer 1 than 2 (the old code called it "trip")
    assert bench._flops_band(1.2, 2) == "once"
    assert bench._flops_band(1.5, 2) == "trip"  # nearer 2 in log space
    assert bench._flops_band(1.9, 2) == "trip"
    # chunk=4: the geometric midpoint of the bands is 2.0 — ties go trip
    assert bench._flops_band(1.9, 4) == "once"
    assert bench._flops_band(2.0, 4) == "trip"
    assert bench._flops_band(2.1, 4) == "trip"
    # chunk=1: bands coincide; either label divides by 1 — same number
    assert bench._flops_band(1.0, 1) == "trip"


def _worst_case_record(bench):
    """A cumulative record padded to every observed maximum at once: long
    error strings at their truncation caps, full per-dispatch time lists,
    every artifact pointer, six error-status phases."""
    out = {
        "metric": "cifar10_resnet50_train_imgs_per_sec",
        "value": 123456.78, "unit": "imgs/sec", "vs_baseline": 1234.567,
        "partial": False, "wall_s": 869.9,
        "device": "TPU v5 litepod-256 " + "d" * 100,
        "platform": "tpu", "n_devices": 256, "preset": "full",
        "value_tier": "cpu-smoke-fallback",
        "flagship_imgs_per_sec": 35000.12, "step_time_ms": 7.3142,
        "flagship_reps": 64,
        "flagship_imgs_per_sec_min": 22800.01,
        "flagship_imgs_per_sec_max": 35000.12,
        "dispatch_times_ms": [round(7.31 + i / 100, 2) for i in range(64)],
        "baseline_imgs_per_sec": 40.25, "baseline_step_time_ms": 6360.2484,
        "baseline_imgs_per_sec_min": 38.11, "baseline_imgs_per_sec_max": 44.92,
        "baseline_passes": [round(38.0 + i / 10, 2) for i in range(16)],
        "mfu": 0.4123, "flops_per_step": 1.039e10,
        "flops_chunk_ratio": 49.97,
        "flops_method": ("hlo scan-trip-multiplied (cross-check "
                         "unavailable: " + "e" * 160)[:160],
        "fp32_scanned_imgs_per_sec": 9000.5,
        "fp32_dispatch_times_ms": [round(28.0 + i, 2) for i in range(16)],
        "tpu_error": "E" * 400,  # the child-side truncation cap
        "abandoned_drain": {"drained": ["gpt", "flagship_crosscheck"],
                            "still_alive": ["overlap"]},
        "concurrent_abandoned": ["gpt"],
        "gpt": {"model": "gpt2-small-124m", "seq_len": 1024, "batch": 8,
                "vocab": 50257, "mfu": 0.3512, "tokens_per_sec": 123456.7,
                "step_time_ms": 66.4, "flops_per_step": 8.76e12,
                "flops_method": "f" * 160},
        "overlap": {"n_async_collectives": 0, "n_overlapped": 0,
                    "compiled_collectives": 3, "combiner_merged": True},
        "tpu_evidence": {"device": "TPU v5 lite", "recorded_unix": 1754000000,
                         "phases_ok": ["allreduce", "flagship", "gpt",
                                       "overlap", "powersgd", "probe"]},
        "accuracy_study": {
            t: {"accuracy_delta_pts": -0.42, "gradient_bytes_ratio": 122.8}
            for t in ("cifar", "imdb", "imdb_wide")
        },
        "midround_chip_bench": {
            "device": "TPU v5 lite", "recorded_unix": 1754000000,
            "flagship_imgs_per_sec": 35000.12, "mfu": 0.41,
            "baseline_imgs_per_sec": 40.25, "vs_baseline": 869.5,
            "baseline_passes": [38.1, 40.25, 44.9],
            "gpt": {"model": "gpt2-small-124m", "seq_len": 1024,
                    "mfu": 0.35, "tokens_per_sec": 123456.7},
        },
    }
    status = {p: ("error: " + "y" * 200)[:206] for p in bench.PHASES}
    return out, status


def test_compact_summary_bounded_on_worst_case(monkeypatch):
    """The summary line serializes under _SUMMARY_LIMIT even when every
    field of the record is at its maximum size, and still leads with the
    headline numbers."""
    bench = _load_bench(monkeypatch)
    out, status = _worst_case_record(bench)
    summary = bench._compact_summary(out, status)
    line = json.dumps(summary)
    assert len(line) <= bench._SUMMARY_LIMIT, len(line)
    assert summary["summary"] is True
    assert summary["metric"] == out["metric"]
    assert summary["value"] == out["value"]
    assert summary["vs_baseline"] == out["vs_baseline"]
    # unbounded payloads must never ride the summary
    for k in ("dispatch_times_ms", "baseline_passes", "abandoned_drain",
              "midround_chip_bench", "accuracy_study"):
        assert k not in summary


def test_compact_summary_parses_from_2000_char_tail(monkeypatch):
    """The driver's failure mode this line exists for: the full record has
    outgrown a 2,000-char stdout tail, so the tail's last COMPLETE line
    must be the summary and must round-trip json.loads."""
    bench = _load_bench(monkeypatch)
    out, status = _worst_case_record(bench)
    full_line = json.dumps(out)
    assert len(full_line) > 2000  # the premise: the record alone overflows
    summary = bench._compact_summary(out, status)
    stream = full_line + "\n" + json.dumps(summary) + "\n"
    tail = stream[-2000:]
    complete = [ln for ln in tail.split("\n") if ln]
    # the first tail entry is the truncated full record — unparseable —
    # but the LAST complete line is the whole summary
    rec = json.loads(complete[-1])
    assert rec == summary
    assert rec["summary"] is True and rec["value"] == out["value"]


def _load_gate():
    spec = importlib.util.spec_from_file_location(
        "gate_under_test", os.path.join(REPO, "scripts", "gate.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_summary_round_trips_through_gate_tail_parser(monkeypatch):
    """The contract the summary line exists for, proved against the REAL
    consumer: a >1,200-char full record plus the bounded summary, cut to a
    2,000-char tail, must still yield the summary — with its headline
    metrics intact — through gate.py's backwards tail scan (the same parser
    the driver's ``parsed`` field and baseline fallback rely on)."""
    bench = _load_bench(monkeypatch)
    gate = _load_gate()
    out, status = _worst_case_record(bench)
    summary = bench._compact_summary(out, status)
    full_line = json.dumps(out)
    assert len(full_line) > bench._SUMMARY_LIMIT  # premise: record overflows
    tail = (full_line + "\n" + json.dumps(summary) + "\n")[-2000:]
    doc = gate._summary_from_lines(tail.split("\n"))
    assert doc == summary  # byte-exact round trip through the tail
    metrics = gate.extract_metrics(doc)
    assert metrics["value"] == out["value"]
    assert metrics["flagship_imgs_per_sec"] == out["flagship_imgs_per_sec"]
    assert metrics["mfu"] == out["mfu"]  # the gate's MFU baseline rides it


def test_orchestrator_emits_summary_on_crash(monkeypatch, tmp_path):
    """An orchestrator-level exception (round 5's "parsed": null: the tail
    ended in a front-truncated full record, no summary) must not skip the
    final emissions: the full record lands with partial=True and the error
    on it, the bounded summary is still the very last line, and the
    exception re-raises so the exit code stays honest."""
    bench = _load_bench(monkeypatch)
    lines = []

    class _Boom:
        def __init__(self, phases):
            raise RuntimeError("injected orchestrator crash")

    bench._ChildProc = _Boom
    bench._emit = lambda payload: lines.append(json.loads(json.dumps(payload)))
    bench.HERE = str(tmp_path)
    with pytest.raises(RuntimeError, match="injected"):
        bench.orchestrate()
    full, summary = lines[-2], lines[-1]
    assert full["partial"] is True  # the crashed round never claims finality
    assert full["orchestrator_error"].startswith("RuntimeError")
    assert all(
        str(v).startswith("skipped: orchestrator error")
        for v in full["phases"].values()
    )
    assert summary["summary"] is True
    assert summary["orchestrator_error"].startswith("RuntimeError")
    assert len(json.dumps(summary)) <= bench._SUMMARY_LIMIT


def test_gate_baseline_records_mfu(monkeypatch, tmp_path):
    """A plain-ok flagship round with a derived MFU records it in
    artifacts/GATE_BASELINE.json so gate.py can compare a run report's
    mfu_headline like-for-like; a round without one omits the key."""
    bench = _load_bench(monkeypatch)
    bench.HERE = str(tmp_path)
    out = {"platform": "cpu", "preset": "small", "value": 50.0,
           "flagship_imgs_per_sec": 50.0, "vs_baseline": 2.0, "mfu": 0.41}
    bench._record_gate_baseline(out, {"flagship": "ok"})
    path = os.path.join(str(tmp_path), "artifacts", "GATE_BASELINE.json")
    with open(path) as f:
        rec = json.load(f)
    assert rec["mfu"] == 0.41 and rec["flagship_imgs_per_sec"] == 50.0
    out.pop("mfu")
    bench._record_gate_baseline(out, {"flagship": "ok"})
    with open(path) as f:
        assert "mfu" not in json.load(f)


def test_gate_baseline_records_mfu_target(monkeypatch, tmp_path):
    """The per-tier MFU floor (bench.MFU_TARGETS / BENCH_MFU_TARGET) is
    published by the flagship phase and recorded into GATE_BASELINE.json
    even when mfu itself was withheld — the target is policy, not
    measurement, and gate.py gates the mfu metric against it."""
    bench = _load_bench(monkeypatch)
    bench.HERE = str(tmp_path)
    assert bench._mfu_target("full") == bench.MFU_TARGETS["full"]
    monkeypatch.setenv("BENCH_MFU_TARGET", "0.33")
    assert bench._mfu_target("small") == 0.33
    monkeypatch.delenv("BENCH_MFU_TARGET")
    out = {"platform": "tpu", "preset": "full", "value": 100.0,
           "flagship_imgs_per_sec": 100.0, "vs_baseline": 2.0,
           "mfu_target": bench._mfu_target("full")}  # no "mfu": withheld
    bench._record_gate_baseline(out, {"flagship": "ok"})
    path = os.path.join(str(tmp_path), "artifacts", "GATE_BASELINE.json")
    with open(path) as f:
        rec = json.load(f)
    assert rec["mfu_target"] == bench.MFU_TARGETS["full"]
    assert "mfu" not in rec


@pytest.mark.slow
def test_child_phases_real_jax_smoke(tmp_path):
    """The real measurement child (subprocess, real jax on CPU, tiny chunk):
    the flagship publishes median + spread + per-dispatch times, the fp32arm
    mirrors the protocol with its preset label — the phase INTERNALS the
    scripted-children orchestrator tests can't see."""
    import subprocess
    import sys as _sys

    env = {k: v for k, v in os.environ.items() if not k.startswith("BENCH_")}
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel here
    # the harness exports an 8-virtual-device XLA_FLAGS (conftest); the
    # child must compile for ONE device or two cold 8-way shard_map
    # compiles serialize on the 1-core host and blow the timeout
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.update(
        BENCH_PLATFORM="cpu", BENCH_CHUNK="2", BENCH_FLAGSHIP_REPS="2",
        BENCH_FP32ARM_REPS="1",
    )
    proc = subprocess.run(
        [_sys.executable, os.path.join(REPO, "bench.py"),
         "--phases", "probe,flagship,fp32arm"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO,
    )
    # phase/init errors ride stdout as @BENCH@ JSON lines, not stderr
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    events = {}
    for line in proc.stdout.splitlines():
        if line.startswith("@BENCH@ "):
            ev = json.loads(line[len("@BENCH@ "):])
            events[ev["phase"]] = ev
    flag = events["flagship"]
    assert flag["ok"], flag
    d = flag["data"]
    assert d["flagship_reps"] == 2
    assert len(d["dispatch_times_ms"]) == 2
    assert (
        d["flagship_imgs_per_sec_min"]
        <= d["flagship_imgs_per_sec"]
        <= d["flagship_imgs_per_sec_max"]
    )
    arm = events["fp32arm"]["data"]
    assert arm["preset"] == d["preset"] == "small"
    assert arm["fp32_scanned_imgs_per_sec"] > 0


def test_run_perf_gate_strictness_follows_platform(monkeypatch, tmp_path):
    """The round-end perf gate: skipped without a report/baseline pair,
    chip-strict on TPU (--strict-device), advisory on CPU, and a nonzero
    gate exit rides the status without failing the bench."""
    bench = _load_bench(monkeypatch)
    monkeypatch.setattr(bench, "HERE", str(tmp_path))

    out, status = {"platform": "tpu"}, {}
    bench._run_perf_gate(out, status)
    assert status["gate"].startswith("skipped")
    assert "gate_strict_device" not in out

    art = tmp_path / "artifacts"
    art.mkdir()
    (art / "run_report.json").write_text("{}")
    (art / "GATE_BASELINE.json").write_text("{}")

    calls = []

    def _fake_run(argv, timeout):
        calls.append(list(argv))

        class _R:
            returncode = 0

        return _R()

    monkeypatch.setattr(bench.subprocess, "run", _fake_run)
    bench._run_perf_gate(out, status)
    assert status["gate"] == "ok" and out["gate_strict_device"] is True
    assert "--strict-device" in calls[-1] and "--advisory" not in calls[-1]

    out_cpu, status_cpu = {"platform": "cpu"}, {}
    bench._run_perf_gate(out_cpu, status_cpu)
    assert "--advisory" in calls[-1]
    assert out_cpu["gate_strict_device"] is False

    def _regressed(argv, timeout):
        class _R:
            returncode = 3

        return _R()

    monkeypatch.setattr(bench.subprocess, "run", _regressed)
    status_bad = {}
    bench._run_perf_gate({"platform": "tpu"}, status_bad)
    assert status_bad["gate"] == "regressed (exit 3)"
