"""bench.py's pure helpers — no backend needed: the peak-FLOPs device map,
the escalating init-timeout ladder, and the artifact pointers that ride the
one JSON line."""

import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench(monkeypatch, attempt=None):
    if attempt is not None:
        monkeypatch.setenv("BENCH_ATTEMPT", str(attempt))
    else:
        monkeypatch.delenv("BENCH_ATTEMPT", raising=False)
    monkeypatch.delenv("BENCH_INIT_TIMEOUT_S", raising=False)
    # bench.py stamps BENCH_START_TS at import (ladder wall budget). Pin it
    # via monkeypatch so teardown REMOVES it — a bare setdefault from the
    # import would otherwise leak a stale stamp into later tests'
    # subprocesses (which would then skip straight to the CPU fallback).
    monkeypatch.setenv("BENCH_START_TS", "0")
    spec = importlib.util.spec_from_file_location(
        f"bench_under_test_{attempt}", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _FakeDevice:
    def __init__(self, platform, kind):
        self.platform = platform
        self.device_kind = kind


def test_peak_flops_device_map(monkeypatch):
    bench = _load_bench(monkeypatch)
    assert bench._peak_flops(_FakeDevice("tpu", "TPU v5 lite")) == 197e12
    assert bench._peak_flops(_FakeDevice("tpu", "TPU v5p")) == 459e12
    assert bench._peak_flops(_FakeDevice("tpu", "TPU v6e")) == 918e12
    # longest-match: "v5 lite" must not resolve via the bare "v5" entry
    assert bench._peak_flops(_FakeDevice("tpu", "tpu v5 litepod-8")) == 197e12
    assert bench._peak_flops(_FakeDevice("cpu", "cpu")) == 0.0  # smoke tier
    assert bench._peak_flops(_FakeDevice("tpu", "TPU v99")) == 0.0  # unknown


def test_init_timeout_ladder_escalates(monkeypatch):
    assert _load_bench(monkeypatch, attempt=1).INIT_TIMEOUT_S == 180
    assert _load_bench(monkeypatch, attempt=2).INIT_TIMEOUT_S == 300
    assert _load_bench(monkeypatch, attempt=3).INIT_TIMEOUT_S == 600
    assert _load_bench(monkeypatch, attempt=9).INIT_TIMEOUT_S == 600  # clamped
    monkeypatch.setenv("BENCH_INIT_TIMEOUT_S", "42")  # explicit pin wins
    spec = importlib.util.spec_from_file_location(
        "bench_pinned", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.INIT_TIMEOUT_S == 42


def test_artifact_pointers_ride_the_line(monkeypatch):
    """The committed evidence artifacts surface as compact pointers in the
    bench payload (device + phase list + freshness, study deltas)."""
    bench = _load_bench(monkeypatch)
    out = {}
    bench._artifact_pointers(out)
    # ACCURACY_STUDY.json is committed this round — pointers must decode it
    assert "accuracy_study" in out
    assert out["accuracy_study"]["cifar"]["gradient_bytes_ratio"] > 10
    assert "tpu_evidence" in out
    assert isinstance(out["tpu_evidence"]["phases_ok"], list)
    json.dumps(out)  # the line must stay serializable
