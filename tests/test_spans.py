"""Span-based performance attribution, unit to end-to-end.

Units: the nested host span API (``observe.spans``), the per-phase
MFU/roofline accounting (``observe.mfu``), the ``cost_analysis`` compat
shim (``_jax_compat.compiled_cost``), and report.py's span aggregation +
Chrome-trace export — all jax-free.

End-to-end: ``scripts/run_probe.py`` spawns the REAL 2-rank supervised toy
run, and the test asserts the full pipeline: a well-formed Perfetto trace
with nested spans from both ranks and collective instants, a run report
with per-phase MFU + roofline verdict, and ``scripts/gate.py`` exiting
nonzero on an injected MFU regression.
"""

import importlib.util
import json
import os
import sys
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from network_distributed_pytorch_tpu._jax_compat import compiled_cost  # noqa: E402
from network_distributed_pytorch_tpu.observe import mfu, spans  # noqa: E402
from network_distributed_pytorch_tpu.observe.sinks import MemorySink  # noqa: E402
from network_distributed_pytorch_tpu.observe.telemetry import Telemetry  # noqa: E402


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        f"_spans_test_{name}", os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[f"_spans_test_{name}"] = mod
    spec.loader.exec_module(mod)
    return mod


def _mem_telemetry():
    sink = MemorySink()
    return Telemetry([sink]), sink


# ---------------------------------------------------------------------------
# observe.spans: the nested host span API


def test_span_nesting_parent_links_and_order():
    telemetry, sink = _mem_telemetry()
    with spans.span("outer", telemetry=telemetry, step=7):
        with spans.span("inner", telemetry=telemetry, step=7):
            pass
    recs = sink.of_kind("span")
    # a span emits at CLOSE, so the inner record lands first
    assert [r["name"] for r in recs] == ["inner", "outer"]
    inner, outer = recs
    assert inner["parent_id"] == outer["span_id"]
    assert inner["depth"] == 1 and outer["depth"] == 0
    assert outer["parent_id"] is None
    assert inner["step"] == 7
    assert inner["dur_s"] >= 0 and outer["dur_s"] >= inner["dur_s"]
    # emit-time stamps rode along (ts marks the close)
    assert "ts" in inner and "ts_mono" in inner


def test_span_without_recorder_is_safe_and_keeps_nesting():
    # no telemetry anywhere: spans must cost nothing and still nest, so a
    # library span deep in the loader never cares whether a run recorder
    # is ambient
    assert spans.current_span_id() is None
    with spans.span("quiet"):
        outer_id = spans.current_span_id()
        assert outer_id is not None
        with spans.span("quiet/inner"):
            assert spans.current_span_id() != outer_id
        assert spans.current_span_id() == outer_id
    assert spans.current_span_id() is None


def test_recording_makes_telemetry_ambient():
    telemetry, sink = _mem_telemetry()
    with spans.recording(telemetry):
        with spans.span("ambient"):
            pass
    assert [r["name"] for r in sink.of_kind("span")] == ["ambient"]
    # the ambient recorder is restored on exit
    with spans.span("after"):
        pass
    assert len(sink.of_kind("span")) == 1


def test_span_rank_defaults_from_env(monkeypatch):
    telemetry, sink = _mem_telemetry()
    monkeypatch.setenv("RESILIENCE_RANK", "3")
    with spans.span("ranked", telemetry=telemetry):
        pass
    assert sink.of_kind("span")[0]["rank"] == 3
    monkeypatch.delenv("RESILIENCE_RANK")
    with spans.span("unranked", telemetry=telemetry):
        pass
    assert sink.of_kind("span")[1]["rank"] is None


def test_span_stacks_are_thread_local():
    telemetry, sink = _mem_telemetry()
    ready = threading.Event()

    def other():
        with spans.span("thread_b", telemetry=telemetry):
            ready.wait(5.0)

    with spans.recording(telemetry):
        t = threading.Thread(target=other)
        with spans.span("thread_a"):
            t.start()
            ready.set()
            t.join(5.0)
    by_name = {r["name"]: r for r in sink.of_kind("span")}
    # concurrent spans in another thread must NOT parent under thread_a
    assert by_name["thread_b"]["parent_id"] is None
    assert by_name["thread_b"]["depth"] == 0
    assert by_name["thread_a"]["parent_id"] is None


def test_span_emits_even_when_body_raises():
    telemetry, sink = _mem_telemetry()
    with pytest.raises(ValueError, match="boom"):
        with spans.span("doomed", telemetry=telemetry):
            raise ValueError("boom")
    recs = sink.of_kind("span")
    assert [r["name"] for r in recs] == ["doomed"]
    assert spans.current_span_id() is None  # the stack unwound


# ---------------------------------------------------------------------------
# observe.mfu: peak tables, roofline classification, event construction


def test_peak_flops_table_lookup():
    assert mfu.peak_flops("TPU v5 lite") == 197e12
    assert mfu.peak_flops("TPU v5p") == 459e12
    # longest-match: "v5 lite" must not resolve via the bare "v5" entry
    assert mfu.peak_flops("tpu v5 litepod-8") == 197e12
    assert mfu.peak_flops("TPU v99") == 0.0  # unknown kind
    assert mfu.peak_flops("cpu", platform="cpu") == 0.0  # non-TPU platform
    assert mfu.hbm_bandwidth("TPU v4") == 1228e9


def test_classify_roofline_all_bounds():
    # unknown: no peak to compare against
    assert mfu.classify_roofline(1e12, 1e9, 0.0, 1e12)["bound"] == "unknown"
    # comm-exposed wins over everything once the exposed fraction crosses
    # the threshold — no point tuning kernels when the wire is the wall
    v = mfu.classify_roofline(
        1e12, 1e9, 2e14, 1e12, exposed_comm_fraction=0.7
    )
    assert v["bound"] == "comm-exposed"
    # hbm: arithmetic intensity below the ridge
    v = mfu.classify_roofline(1e9, 1e9, 2e14, 1e12)
    assert v["bound"] == "hbm"
    assert v["arithmetic_intensity"] == pytest.approx(1.0)
    assert v["ridge_flops_per_byte"] == pytest.approx(200.0)
    # compute: intensity above the ridge
    assert mfu.classify_roofline(1e13, 1e9, 2e14, 1e12)["bound"] == "compute"


def test_mfu_event_numbers():
    ev = mfu.mfu_event(
        label="toy", step_time_s=0.01, flops_per_step=2.0e9,
        peak_flops_per_s=1e12, exposed_comm_fraction=1.0,
    )
    assert ev.mfu == pytest.approx(0.2)
    assert ev.bound == "comm-exposed"
    rec = ev.record()
    assert rec["event"] == "mfu" and rec["label"] == "toy"
    assert "mfu" in ev.banner()


def test_mfu_from_compile_records_joins_and_dedupes():
    recs = [
        {"label": "toy", "flops_per_step": 2.0e9, "flops_source": "analytic",
         "device_kind": "toy-sim", "peak_flops_per_s": 1e12},
        {"label": "toy", "flops_per_step": 9.9e9},  # duplicate label: dropped
        {"label": "no-cost"},  # no flops: skipped
    ]
    out = mfu.mfu_from_compile_records(recs, step_time_s=0.01, n_steps=5)
    assert [e.label for e in out] == ["toy"]
    assert out[0].mfu == pytest.approx(0.2)
    assert out[0].n_steps == 5
    # invalid step time: nothing to join against
    assert mfu.mfu_from_compile_records(recs, step_time_s=0.0) == []


# ---------------------------------------------------------------------------
# _jax_compat.compiled_cost: the cost_analysis shim


class _FakeCompiled:
    def __init__(self, result=None, raises=False):
        self._result = result
        self._raises = raises

    def cost_analysis(self):
        if self._raises:
            raise NotImplementedError("unsupported backend")
        return self._result


def test_compiled_cost_normalizes_both_jaxlib_shapes():
    cost = {"flops": 123.0, "bytes accessed": 456.0, "utilization": "n/a"}
    # jaxlib <= 0.4.x returns [dict]; newer returns the dict directly
    assert compiled_cost(_FakeCompiled([dict(cost)])) == {
        "flops": 123.0, "bytes accessed": 456.0
    }
    assert compiled_cost(_FakeCompiled(dict(cost)))["flops"] == 123.0


def test_compiled_cost_graceful_none():
    assert compiled_cost(_FakeCompiled(raises=True)) is None
    assert compiled_cost(_FakeCompiled([])) is None
    assert compiled_cost(_FakeCompiled(None)) is None
    # a cost dict with no flops is useless for MFU: normalized to None
    assert compiled_cost(_FakeCompiled({"bytes accessed": 9.0})) is None


# ---------------------------------------------------------------------------
# report.py: span aggregation + Chrome-trace export (unit level)


def _span_rec(name, rank, close, dur, depth=0, span_id=1, parent=None):
    return {
        "event": "span", "name": name, "rank": rank, "t_run": close,
        "dur_s": dur, "depth": depth, "span_id": span_id,
        "parent_id": parent,
    }


def test_span_summary_shares_and_idle():
    report = _load_script("report")
    events = [
        _span_rec("step", 0, 2.0, 1.0),          # covers [1, 2]
        _span_rec("step", 0, 4.0, 1.0),          # covers [3, 4]
        {"event": "step", "rank": 0, "t_run": 5.0, "step_time_s": 1.0},
    ]
    s = report.span_summary(events)
    # rank 0 wall = [2.0, 5.0] from event stamps -> 3 s; idle = wall not
    # covered by depth-0 spans (clamped): [2,2]+[3,4] covered -> 2 s idle
    assert s["total_wall_s"] == pytest.approx(3.0)
    assert s["by_name"]["step"]["count"] == 2
    assert s["by_name"]["step"]["total_s"] == pytest.approx(2.0)
    assert s["by_name"]["step"]["share"] == pytest.approx(2.0 / 3.0)
    assert s["idle_by_rank"]["0"]["idle_s"] == pytest.approx(2.0)
    assert report.span_summary([{"event": "step", "t_run": 1.0}]) is None


def test_chrome_trace_backdates_spans_and_names_processes():
    report = _load_script("report")
    events = [
        _span_rec("outer", 0, 11.0, 2.0, depth=0, span_id=1),
        _span_rec("inner", 0, 10.5, 1.0, depth=1, span_id=2, parent=1),
        {"event": "collective", "rank": 1, "t_run": 10.0, "tag": "g",
         "op": "all-reduce", "payload_bytes": 8, "layer": "reducer"},
        {"event": "failure", "rank": None, "t_run": 12.0, "kind": "crash",
         "message": "boom"},
    ]
    doc = report.chrome_trace(events)
    evs = doc["traceEvents"]
    slices = {e["name"]: e for e in evs if e.get("ph") == "X"}
    # t0 is the earliest span START (11.0 - 2.0 = 9.0), not earliest stamp
    assert slices["outer"]["ts"] == pytest.approx(0.0)
    assert slices["outer"]["dur"] == pytest.approx(2e6)
    assert slices["inner"]["ts"] == pytest.approx(0.5e6)
    assert slices["inner"]["args"]["parent_id"] == 1
    instants = [e for e in evs if e.get("ph") == "i"]
    assert {e["cat"] for e in instants} == {"collective", "failure"}
    # supervisor events land on pid -1; metadata names every process
    assert [e for e in instants if e["cat"] == "failure"][0]["pid"] == -1
    names = {
        e["pid"]: e["args"]["name"] for e in evs
        if e.get("ph") == "M" and e["name"] == "process_name"
    }
    assert names == {-1: "supervisor", 0: "rank 0", 1: "rank 1"}
    assert report.chrome_trace([])["traceEvents"] == []


# ---------------------------------------------------------------------------
# end-to-end: 2-rank probe -> trace + MFU report -> gate regression


@pytest.fixture(scope="module")
def probe_artifacts(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("probe")
    run_probe = _load_script("run_probe")
    json_out = str(tmp / "run_report.json")
    trace_out = str(tmp / "toy_trace.json")
    rc = run_probe.main([
        "--out-dir", str(tmp / "toy_run"), "--json-out", json_out,
        "--trace-out", trace_out, "--steps", "4",
    ])
    assert rc == 0
    return json_out, trace_out


def test_probe_trace_is_wellformed_with_nested_spans(probe_artifacts):
    _json_out, trace_out = probe_artifacts
    with open(trace_out) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    assert evs, "empty trace"
    span_slices = [e for e in evs if e.get("ph") == "X" and e["cat"] == "span"]
    # spans from BOTH worker ranks
    assert {e["pid"] for e in span_slices} == {0, 1}
    # nesting survived the merge: step/compute parents under step
    children = [
        e for e in span_slices
        if e["name"] == "step/compute" and e["args"].get("parent_id")
    ]
    assert children
    parents = {
        (e["pid"], e["args"]["span_id"]): e["name"] for e in span_slices
    }
    for c in children:
        assert parents[(c["pid"], c["args"]["parent_id"])] == "step"
    # the toy all-reduce shows up as collective instants
    assert any(
        e.get("cat") == "collective" and e.get("ph") == "i" for e in evs
    )


def test_probe_report_carries_mfu_and_roofline(probe_artifacts):
    json_out, _trace_out = probe_artifacts
    with open(json_out) as f:
        report = json.load(f)
    recs = report["mfu"]
    assert len(recs) == 1 and recs[0]["label"] == "toy"
    # 2 GF/step at >= 10 ms/step against the 1 TF/s toy peak: mfu lands
    # just under the ideal 0.2 (step time includes checkpoint overhead)
    assert 0.05 < recs[0]["mfu"] <= 0.2
    assert recs[0]["flops_source"] == "analytic"
    # the toy's single all-reduce is fully exposed -> comm-bound verdict
    assert recs[0]["bound"] == "comm-exposed"
    assert recs[0]["exposed_comm_fraction"] == pytest.approx(1.0)
    assert report["mfu_headline"] == pytest.approx(recs[0]["mfu"])
    assert report["spans"]["by_name"]["step"]["count"] == 8  # 2 ranks x 4


def test_gate_fails_on_injected_mfu_regression(probe_artifacts, tmp_path):
    json_out, _trace_out = probe_artifacts
    gate = _load_script("gate")
    with open(json_out) as f:
        report = json.load(f)
    current = report["mfu_headline"]
    # baseline claims 3x the measured MFU — far past the 20% tolerance
    baseline = str(tmp_path / "baseline.json")
    with open(baseline, "w") as f:
        json.dump({"mfu": current * 3.0}, f)
    rc = gate.main([
        "--report", json_out, "--baseline", baseline, "--root", str(tmp_path)
    ])
    assert rc == 1
    # control: gating against an equal baseline passes
    with open(baseline, "w") as f:
        json.dump({"mfu": current}, f)
    assert gate.main([
        "--report", json_out, "--baseline", baseline, "--root", str(tmp_path)
    ]) == 0
    # and a span-share blowup alone fails the gate (absolute tolerance)
    shrunk = dict(report)
    shrunk["spans"] = json.loads(json.dumps(report["spans"]))
    shrunk["spans"]["by_name"]["step"]["share"] = (
        report["spans"]["by_name"]["step"]["share"] - 0.2
    )
    with open(baseline, "w") as f:
        json.dump(shrunk, f)
    assert gate.main([
        "--report", json_out, "--baseline", baseline, "--root", str(tmp_path)
    ]) == 1
