"""Worker for the true multi-process rendezvous test (run as a subprocess).

Each of N OS processes rendezvouses via ``jax.distributed.initialize`` on
CPU (1 local device each — the reference's one-rank-per-process world,
``ddp_guide/run_script.py:4-23``), builds the global ``data`` mesh, assembles
its local batch shard into the global batch with
``multihost.global_batch_from_local``, and runs ExactReducer training steps.
Prints the per-step global losses and the first parameter element so the
parent can assert equality with a single-process run.

Usage: python multiprocess_worker.py <coordinator_port> <process_id> <num_processes>
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# must happen before jax import: 1 CPU device per process, no TPU plugin
from network_distributed_pytorch_tpu.hostenv import force_cpu_devices  # noqa: E402

force_cpu_devices(n=None, drop_tpu_tunnel=True)

import jax  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from network_distributed_pytorch_tpu.data.multihost import (  # noqa: E402
    global_batch_from_local,
    global_state_from_host,
)
from network_distributed_pytorch_tpu.parallel import (  # noqa: E402
    ExactReducer,
    PowerSGDReducer,
)
from network_distributed_pytorch_tpu.parallel.mesh import (  # noqa: E402
    DistributedConfig,
    initialize_distributed,
    make_mesh,
    shutdown_distributed,
)
from network_distributed_pytorch_tpu.parallel.trainer import (  # noqa: E402
    TrainState,
    make_train_step,
    stateless_loss,
)


def main() -> int:
    port, pid, nproc = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    initialize_distributed(
        DistributedConfig(
            num_processes=nproc,
            process_id=pid,
            coordinator_address=f"localhost:{port}",
            timeout_seconds=60,
        )
    )
    assert jax.process_count() == nproc, jax.process_count()
    assert len(jax.local_devices()) == 1
    assert jax.device_count() == nproc
    mesh = make_mesh()

    # deterministic toy regression, same on every process (shared seed — the
    # reference's DataPartitioner seed-1234 convention)
    rng = np.random.RandomState(1234)
    w_true = rng.randn(16, 4).astype(np.float32)
    x = rng.randn(8 * nproc, 16).astype(np.float32)
    y = x @ w_true
    params = {"w": np.zeros((16, 4), np.float32), "b": np.zeros((4,), np.float32)}

    def loss(p, batch):
        xb, yb = batch
        import jax.numpy as jnp

        return jnp.mean((xb @ p["w"] + p["b"] - yb) ** 2)

    # THIS process's shard of the batch (rank-partitioned, like
    # DataPartitioner.use(rank))
    lo, hi = 8 * pid, 8 * (pid + 1)
    batch = global_batch_from_local((x[lo:hi], y[lo:hi]), mesh)

    results = {}
    for name, reducer, algo in (
        ("exact", ExactReducer(), "sgd"),
        # the flagship compressed path: EF chain + warm-start Q across
        # REAL process boundaries
        ("powersgd", PowerSGDReducer(
            random_seed=1234, compression_rank=2, matricize="last"
        ), "ef_momentum"),
    ):
        step = make_train_step(
            stateless_loss(loss), reducer, params, learning_rate=0.05,
            momentum=0.9, algorithm=algo, mesh=mesh, donate_state=False,
        )
        state = step.init_state(params)
        state = global_state_from_host(
            state,
            TrainState(
                params=P(), momenta=P(), memories=P("data"),
                reducer_state=P(), model_state=P("data"),
            ),
            mesh,
        )
        losses = []
        for _ in range(3):
            state, l = step(state, batch)
            losses.append(float(l))
        w0 = float(np.asarray(jax.device_get(state.params["w"]))[0, 0])
        results[name] = (losses, w0)

    # DiLoCo round across REAL process boundaries: per-worker inner state,
    # PowerSGD-compressed outer deltas, one compiled shard_map round
    from network_distributed_pytorch_tpu.parallel import make_diloco_train_fn
    from network_distributed_pytorch_tpu.parallel.localsgd import DiLoCoState

    diloco = make_diloco_train_fn(
        stateless_loss(loss), params, inner_learning_rate=0.05,
        sync_every=2, inner_algorithm="sgd_plain", mesh=mesh,
        donate_state=False,
        reducer=PowerSGDReducer(
            random_seed=1234, compression_rank=2, matricize="last"
        ),
    )
    dstate = global_state_from_host(
        diloco.init_state(params),
        DiLoCoState(
            params=P(), outer_momenta=P(), inner_opt=P("data"),
            memories=P("data"), reducer_state=P(), model_state=P("data"),
        ),
        mesh,
    )
    # two DISTINCT inner-step batches (reversed rows for step 2) so the
    # sync_every scan is falsifiable — identical steps would mask a batch-
    # threading regression
    stacked = tuple(
        np.stack([a, a[::-1]]) for a in (x, y)
    )
    dbatches = global_state_from_host(
        stacked, (P(None, "data"), P(None, "data")), mesh
    )
    dlosses = []
    for _ in range(2):
        dstate, dl = diloco(dstate, dbatches)
        dlosses.extend(float(v) for v in np.asarray(jax.device_get(dl)))
    dw0 = float(np.asarray(jax.device_get(dstate.params["w"]))[0, 0])
    results["diloco"] = (dlosses, dw0)

    for name, (losses, w0) in results.items():
        print(
            f"RESULT kind={name} pid={pid} "
            f"losses={','.join(f'{v:.8f}' for v in losses)} w00={w0:.8f}",
            flush=True,
        )
    shutdown_distributed()
    return 0


if __name__ == "__main__":
    sys.exit(main())
