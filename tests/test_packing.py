"""TensorPacker round-trip + bits arithmetic (reference ``tensor_buffer.py``)."""

import jax
import jax.numpy as jnp
import numpy as np

from network_distributed_pytorch_tpu.parallel import TensorPacker
from network_distributed_pytorch_tpu.parallel.comm import n_bits


def _arrays():
    k = jax.random.PRNGKey(0)
    ks = jax.random.split(k, 3)
    return [
        jax.random.normal(ks[0], (4, 5)),
        jax.random.normal(ks[1], (7,)),
        jax.random.normal(ks[2], (2, 3, 2)),
    ]


def test_pack_unpack_roundtrip():
    arrays = _arrays()
    packer = TensorPacker.for_arrays(arrays)
    flat = packer.pack(arrays)
    assert flat.shape == (4 * 5 + 7 + 2 * 3 * 2,)
    out = packer.unpack(flat)
    for a, b in zip(arrays, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pack_unpack_under_jit():
    arrays = _arrays()
    packer = TensorPacker.for_arrays(arrays)

    @jax.jit
    def roundtrip(xs):
        return packer.unpack(packer.pack(xs))

    out = roundtrip(arrays)
    for a, b in zip(arrays, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bits():
    # 8 * nelement * element_size (tensor_buffer.py:44-45)
    packer = TensorPacker([(4, 5), (7,)], dtype=jnp.float32)
    assert packer.bits() == 8 * 27 * 4
    assert n_bits(jnp.zeros((4, 5), jnp.float32)) == 8 * 20 * 4
    assert n_bits(jnp.zeros((3,), jnp.bfloat16)) == 8 * 3 * 2
    assert n_bits(jax.ShapeDtypeStruct((10, 10), jnp.float32)) == 8 * 100 * 4


def test_empty():
    packer = TensorPacker([])
    assert packer.pack([]).shape == (0,)
    assert packer.unpack(jnp.zeros((0,))) == []
    assert packer.bits() == 0
