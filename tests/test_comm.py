"""Collective wrappers over the real shard_map/psum path on 8 virtual devices
(the reference's collectives are NCCL calls it could only test on a lab
cluster; SURVEY §4 'distributed-without-a-cluster')."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from network_distributed_pytorch_tpu.parallel import (
    DATA_AXIS,
    all_gather,
    all_reduce_mean,
    all_reduce_sum,
    make_mesh,
)
from network_distributed_pytorch_tpu.parallel.comm import axis_index, axis_size


def test_all_reduce_sum_and_mean(devices):
    mesh = make_mesh()
    x = jnp.arange(8.0).reshape(8, 1)  # one row per device

    def f(xs):
        return all_reduce_sum(xs, DATA_AXIS), all_reduce_mean(xs, DATA_AXIS)

    s, m = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=(P(DATA_AXIS), P(DATA_AXIS)))
    )(x)
    np.testing.assert_allclose(np.asarray(s), np.full((8, 1), 28.0))
    np.testing.assert_allclose(np.asarray(m), np.full((8, 1), 3.5))


def test_all_gather(devices):
    mesh = make_mesh()
    x = jnp.arange(8.0).reshape(8, 1)

    def f(xs):
        g = all_gather(xs, DATA_AXIS)  # (8, 1, 1) on each device
        return g.reshape(1, -1)

    g = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS)))(x)
    np.testing.assert_allclose(np.asarray(g), np.tile(np.arange(8.0), (8, 1)))


def test_axis_helpers(devices):
    mesh = make_mesh()

    def f(xs):
        return xs * 0 + axis_size(DATA_AXIS), xs * 0 + axis_index(DATA_AXIS)

    size, idx = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=(P(DATA_AXIS), P(DATA_AXIS)))
    )(jnp.zeros((8, 1)))
    np.testing.assert_allclose(np.asarray(size), np.full((8, 1), 8.0))
    np.testing.assert_allclose(np.asarray(idx)[:, 0], np.arange(8.0))


def test_single_process_fallbacks():
    # axis_name=None -> identity / stack-of-one (reducer.py:193-195, tensor_buffer.py:64-69)
    x = jnp.arange(4.0)
    np.testing.assert_array_equal(np.asarray(all_reduce_sum(x, None)), np.asarray(x))
    np.testing.assert_array_equal(np.asarray(all_reduce_mean(x, None)), np.asarray(x))
    assert all_gather(x, None).shape == (1, 4)
    assert axis_size(None) == 1
    assert axis_index(None) == 0


def test_mesh_shape_validation():
    import pytest

    with pytest.raises(ValueError):
        make_mesh(axis_sizes=(3,), axis_names=("data",))
