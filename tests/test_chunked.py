"""Round-6 chunked, software-pipelined reduction (``parallel.comm``):
chunked-vs-monolithic BIT-exactness for both reducers, ledger byte
invariance, the explicit ppermute ring, chunked FSDP gathers, and the
compiled collective structure (K chunks must survive XLA as K collectives
whose payloads reconcile byte-exactly with the wire ledger)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from network_distributed_pytorch_tpu.parallel import (
    DATA_AXIS,
    ExactReducer,
    PowerSGDReducer,
    make_mesh,
)
from network_distributed_pytorch_tpu.parallel.comm import (
    chunk_bounds,
    chunked_all_reduce_mean,
    fence,
    ring_all_reduce_mean,
)
from network_distributed_pytorch_tpu.parallel.reducers import PowerSGDState

W = 8
CHUNK_COUNTS = (1, 2, 3, 7)  # 7 leaves a ragged last chunk on every payload


def _bits(x):
    """uint bit-pattern view — equality here is BITWISE, not allclose."""
    x = np.asarray(x)
    return x.view({2: np.uint16, 4: np.uint32, 8: np.uint64}[x.dtype.itemsize])


def _template_leaves(key):
    ks = jax.random.split(key, 5)
    return [
        jax.random.normal(ks[0], (8, 3, 3, 3)),
        jax.random.normal(ks[1], (16, 8)),
        jax.random.normal(ks[2], (16,)),
        jax.random.normal(ks[3], (10, 16)),
        jax.random.normal(ks[4], (10,)),
    ]


def _stacked_sends(seed):
    """One distinct template per worker, stacked along the device axis."""
    per_worker = [_template_leaves(jax.random.PRNGKey(seed + w)) for w in range(W)]
    return [jnp.stack([pw[i] for pw in per_worker]) for i in range(5)]


# ---- chunk_bounds / fence units -------------------------------------------


def test_chunk_bounds_partition_and_balance():
    for total in (1, 7, 8, 530, 1000):
        for k in (1, 2, 3, 7, 16):
            bounds = chunk_bounds(total, k)
            assert len(bounds) == min(k, total)
            # contiguous partition of [0, total)
            assert bounds[0][0] == 0 and bounds[-1][1] == total
            for (_, e0), (s1, _) in zip(bounds, bounds[1:]):
                assert e0 == s1
            sizes = [e - s for s, e in bounds]
            # balanced: sizes differ by at most 1, larger chunks first
            assert max(sizes) - min(sizes) <= 1
            assert sizes == sorted(sizes, reverse=True)


def test_chunk_bounds_edge_cases():
    assert chunk_bounds(0, 4) == []
    assert chunk_bounds(-3, 4) == []
    assert chunk_bounds(3, 10) == [(0, 1), (1, 2), (2, 3)]  # clamped to size
    assert chunk_bounds(5, 1) == [(0, 5)]
    assert chunk_bounds(5, 0) == [(0, 5)]  # k floors at 1


def test_fence_preserves_values():
    a, b = jnp.arange(4.0), jnp.ones((2, 3))
    fa = fence(a)
    np.testing.assert_array_equal(_bits(fa), _bits(a))
    fa, fb = fence(a, b)
    np.testing.assert_array_equal(_bits(fa), _bits(a))
    np.testing.assert_array_equal(_bits(fb), _bits(b))
    assert fence() == ()


def test_fence_is_transparent_to_grad():
    # the _jax_compat AD rules: chunked FSDP gathers differentiate through
    # the barrier, so grad(f ∘ fence) must equal grad(f)
    def f(x):
        return jnp.sum(fence(x) ** 2)

    x = jnp.arange(5.0)
    np.testing.assert_array_equal(
        _bits(jax.grad(f)(x)), _bits(jax.grad(lambda x: jnp.sum(x**2))(x))
    )


# ---- chunked flat all-reduce ----------------------------------------------


def _run_flat(fn, flat_per_device):
    mesh = make_mesh()

    def body(xs):
        return fn(xs[0])[None]

    return jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS))
    )(flat_per_device)


@pytest.mark.parametrize("k", CHUNK_COUNTS)
def test_chunked_flat_allreduce_bitwise(devices, k):
    # 531 elements: ragged under every K in CHUNK_COUNTS except 1
    flat = jax.random.normal(jax.random.PRNGKey(0), (W, 531))
    mono = _run_flat(lambda x: chunked_all_reduce_mean(x, DATA_AXIS, 1), flat)
    chunked = _run_flat(lambda x: chunked_all_reduce_mean(x, DATA_AXIS, k), flat)
    np.testing.assert_array_equal(_bits(chunked), _bits(mono))


def test_chunked_flat_allreduce_single_process():
    # axis None falls through to the per-chunk identity fallback
    x = jnp.arange(11.0)
    out = chunked_all_reduce_mean(x, None, 3)
    np.testing.assert_array_equal(_bits(out), _bits(x))


# ---- explicit ppermute ring -----------------------------------------------


def test_ring_allreduce_close_to_pmean(devices):
    flat = jax.random.normal(jax.random.PRNGKey(1), (W, 530))
    mean = _run_flat(lambda x: jax.lax.pmean(x, DATA_AXIS), flat)
    ring = _run_flat(lambda x: ring_all_reduce_mean(x, DATA_AXIS), flat)
    # the ring REASSOCIATES (each shard sums in a different rank rotation):
    # deterministic and ~1-ulp close, but not bitwise pmean — DESIGN.md R6
    np.testing.assert_allclose(np.asarray(ring), np.asarray(mean), rtol=1e-5, atol=1e-7)


def test_ring_allreduce_exact_on_dyadic(devices):
    # sums of small integers over W=8 divide exactly in binary floating
    # point, so reassociation cannot change the result: bitwise equal
    flat = jnp.asarray(
        np.random.RandomState(2).randint(-8, 8, size=(W, 37)), jnp.float32
    )
    mean = _run_flat(lambda x: jax.lax.pmean(x, DATA_AXIS), flat)
    ring = _run_flat(lambda x: ring_all_reduce_mean(x, DATA_AXIS), flat)
    np.testing.assert_array_equal(_bits(ring), _bits(mean))


def test_ring_allreduce_ragged_and_shape(devices):
    # 13 !% 8: the ring pads to 16, reduces, slices back
    flat = jax.random.normal(jax.random.PRNGKey(3), (W, 13))
    ring = _run_flat(lambda x: ring_all_reduce_mean(x, DATA_AXIS), flat)
    mean = _run_flat(lambda x: jax.lax.pmean(x, DATA_AXIS), flat)
    assert ring.shape == flat.shape
    np.testing.assert_allclose(np.asarray(ring), np.asarray(mean), rtol=1e-5, atol=1e-7)


def test_ring_allreduce_single_process_fallbacks():
    x = jnp.arange(6.0)
    np.testing.assert_array_equal(_bits(ring_all_reduce_mean(x, None)), _bits(x))


@pytest.mark.parametrize("k", (2, 3))
def test_chunked_ring_strategy_close(devices, k):
    flat = jax.random.normal(jax.random.PRNGKey(4), (W, 201))
    mean = _run_flat(lambda x: jax.lax.pmean(x, DATA_AXIS), flat)
    ring = _run_flat(
        lambda x: chunked_all_reduce_mean(x, DATA_AXIS, k, strategy="ring"), flat
    )
    np.testing.assert_allclose(np.asarray(ring), np.asarray(mean), rtol=1e-5, atol=1e-7)


# ---- reducers: chunked == monolithic, bitwise -----------------------------


def _run_exact(reducer, stacked):
    mesh = make_mesh()

    def f(*send):
        send = [s[0] for s in send]
        _, out, _, _ = reducer.reduce({}, send, DATA_AXIS)
        return tuple(o[None] for o in out)

    return jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P(DATA_AXIS),) * 5, out_specs=(P(DATA_AXIS),) * 5
        )
    )(*stacked)


@pytest.mark.parametrize("k", CHUNK_COUNTS)
def test_exact_chunked_bitwise_equals_monolithic(devices, k):
    stacked = _stacked_sends(50)
    mono = _run_exact(ExactReducer(), stacked)
    chunked = _run_exact(ExactReducer(comm_chunks=k), stacked)
    for a, b in zip(chunked, mono):
        np.testing.assert_array_equal(_bits(a), _bits(b))


def _run_powersgd(reducer, template, stacked):
    mesh = make_mesh()
    state = reducer.init(template)

    def f(q_memory, key, *send):
        send = [s[0] for s in send]
        st, out, mem, _ = reducer.reduce(PowerSGDState(q_memory, key), send, DATA_AXIS)
        return (
            st.q_memory,
            st.key,
            tuple(o[None] for o in out),
            tuple(m[None] for m in mem),
        )

    return jax.jit(
        jax.shard_map(
            f,
            mesh=mesh,
            in_specs=(P(), P()) + (P(DATA_AXIS),) * 5,
            out_specs=(P(), P(), (P(DATA_AXIS),) * 5, (P(DATA_AXIS),) * 5),
        )
    )(state.q_memory, state.key, *stacked)


@pytest.mark.parametrize("k", CHUNK_COUNTS)
def test_powersgd_chunked_bitwise_equals_monolithic(devices, k):
    template = [jnp.zeros_like(l) for l in _template_leaves(jax.random.PRNGKey(0))]
    stacked = _stacked_sends(80)
    kwargs = dict(random_seed=11, compression_rank=2, matricize="last")
    q_m, key_m, out_m, mem_m = _run_powersgd(
        PowerSGDReducer(**kwargs), template, stacked
    )
    q_c, key_c, out_c, mem_c = _run_powersgd(
        PowerSGDReducer(comm_chunks=k, **kwargs), template, stacked
    )
    np.testing.assert_array_equal(_bits(q_c), _bits(q_m))
    for a, b in zip(out_c + mem_c, out_m + mem_m):
        np.testing.assert_array_equal(_bits(a), _bits(b))


# ---- ledger: byte-invariant under K, counts itemize the chunks ------------


@pytest.mark.parametrize("k", CHUNK_COUNTS)
def test_exact_ledger_bytes_invariant_counts_chunked(k):
    template = _template_leaves(jax.random.PRNGKey(0))
    mono = ExactReducer()
    chunked = ExactReducer(comm_chunks=k)
    base = mono.ledger_entries(template, axis=DATA_AXIS)
    entries = chunked.ledger_entries(template, axis=DATA_AXIS)
    # same bytes (the chunks PARTITION the flat buffer), count = chunks
    assert sum(e.payload_bytes for e in entries) == sum(
        e.payload_bytes for e in base
    )
    assert sum(e.count for e in entries) == chunked.n_collectives(template) == k
    # and the ledger still sums exactly to the analytic bits model
    _, _, _, bits = mono.reduce({}, template, None)
    assert 8 * sum(e.payload_bytes for e in entries) == bits


@pytest.mark.parametrize("k", CHUNK_COUNTS)
def test_powersgd_ledger_bytes_invariant_counts_chunked(k):
    template = _template_leaves(jax.random.PRNGKey(0))
    kwargs = dict(random_seed=11, compression_rank=2, matricize="last")
    mono = PowerSGDReducer(**kwargs)
    chunked = PowerSGDReducer(comm_chunks=k, **kwargs)
    base = mono.ledger_entries(template, axis=DATA_AXIS)
    entries = chunked.ledger_entries(template, axis=DATA_AXIS)
    assert sum(e.payload_bytes for e in entries) == sum(
        e.payload_bytes for e in base
    )
    assert 8 * sum(e.payload_bytes for e in entries) == mono.bits_per_step(template)
    # each payload (P, Q, rank1) chunks independently — clamped by its size
    from network_distributed_pytorch_tpu.parallel.reducers import (
        _n_chunk_collectives,
    )

    metas = chunked._metas(template)
    p_packer, q_packer, r1_packer = chunked._packers(template, metas)
    by_tag = {e.tag: e.count for e in entries}
    assert by_tag["powersgd.P"] == _n_chunk_collectives(p_packer.total_size, k)
    assert by_tag["powersgd.Q"] == _n_chunk_collectives(q_packer.total_size, k)
    assert by_tag["powersgd.rank1"] == _n_chunk_collectives(r1_packer.total_size, k)


def test_comm_chunks_requires_packed():
    with pytest.raises(AssertionError):
        ExactReducer(packed=False, comm_chunks=2)
    with pytest.raises(AssertionError):
        ExactReducer(comm_strategy="bogus")


# ---- trainer end-to-end: chunked step == unchunked step, bitwise ----------


def test_train_step_chunked_bitwise(devices):
    from network_distributed_pytorch_tpu.models import SmallCNN
    from network_distributed_pytorch_tpu.parallel.trainer import (
        make_train_step,
        stateless_loss,
    )
    from network_distributed_pytorch_tpu.utils import cross_entropy_loss

    img = (8, 8, 3)
    model = SmallCNN(width=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *img)))["params"]

    def loss_fn(params, batch):
        x, y = batch
        return cross_entropy_loss(model.apply({"params": params}, x), y)

    loss_fn = stateless_loss(loss_fn)
    mesh = make_mesh()

    def run(reducer):
        step = make_train_step(
            loss_fn, reducer, params, learning_rate=0.05, momentum=0.9,
            algorithm="sgd", mesh=mesh, donate_state=False,
        )
        state = step.init_state(params)
        for i in range(3):
            ky, kx = jax.random.split(jax.random.PRNGKey(i))
            y = jax.random.randint(ky, (64,), 0, 10)
            x = jax.random.normal(kx, (64, *img))
            state, loss = step(state, (x, y))
        return state, step

    s_mono, _ = run(ExactReducer())
    s_chunk, step_chunk = run(ExactReducer(comm_chunks=3))
    for a, b in zip(
        jax.tree_util.tree_leaves(s_chunk.params),
        jax.tree_util.tree_leaves(s_mono.params),
    ):
        np.testing.assert_array_equal(_bits(a), _bits(b))
    # the step's compile-time ledger itemizes the chunks and still sums to
    # bits_per_step (step_ledger's construction-time assert also ran)
    assert step_chunk.ledger.total_bits() == step_chunk.bits_per_step


# ---- FSDP: chunked gathers == monolithic, bitwise -------------------------


def test_fsdp_chunked_bitwise(devices):
    from network_distributed_pytorch_tpu.models import SmallCNN
    from network_distributed_pytorch_tpu.parallel.fsdp import make_fsdp_train_step
    from network_distributed_pytorch_tpu.parallel.trainer import stateless_loss
    from network_distributed_pytorch_tpu.utils import cross_entropy_loss

    img = (8, 8, 3)
    model = SmallCNN(width=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *img)))["params"]

    def loss_fn(params, batch):
        x, y = batch
        return cross_entropy_loss(model.apply({"params": params}, x), y)

    loss_fn = stateless_loss(loss_fn)
    mesh = make_mesh()

    def run(comm_chunks):
        step = make_fsdp_train_step(
            loss_fn, params, learning_rate=0.05, momentum=0.9, algorithm="sgd",
            mesh=mesh, donate_state=False, comm_chunks=comm_chunks,
        )
        state = step.init_state(params)
        for i in range(2):
            ky, kx = jax.random.split(jax.random.PRNGKey(i))
            y = jax.random.randint(ky, (64,), 0, 10)
            x = jax.random.normal(kx, (64, *img))
            state, _ = step(state, (x, y))
        return step.unshard(state)

    mono = run(None)
    chunked = run(2)
    for a, b in zip(
        jax.tree_util.tree_leaves(chunked), jax.tree_util.tree_leaves(mono)
    ):
        np.testing.assert_array_equal(_bits(a), _bits(b))


# ---- compiled structure: K chunks survive XLA as K collectives ------------


@pytest.mark.parametrize("k", (3, 7))
def test_compiled_chunk_collectives_survive_and_reconcile(devices, k):
    """The pipeline's whole point: the barrier-fenced chunks must NOT be
    re-fused by XLA — the compiled step carries exactly the ledger's
    collective count, and the HLO payload bytes equal the ledger's."""
    from network_distributed_pytorch_tpu.observe.ledger import WireLedger
    from network_distributed_pytorch_tpu.utils.hlo_audit import (
        collective_summary,
        hlo_text_of_compiled,
    )

    mesh = make_mesh()
    reducer = ExactReducer(comm_chunks=k)
    template = _template_leaves(jax.random.PRNGKey(0))
    stacked = tuple(jnp.stack([l] * W) for l in template)

    def f(*send):
        send = [s[0] for s in send]
        _, out, _, _ = reducer.reduce({}, send, DATA_AXIS)
        return tuple(o[None] for o in out)

    jitted = jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=(P(DATA_AXIS),) * 5, out_specs=(P(DATA_AXIS),) * 5
        )
    )
    hlo = hlo_text_of_compiled(jitted.lower(*stacked).compile())
    summary = collective_summary(hlo)
    entries = reducer.ledger_entries(template, axis=DATA_AXIS)
    assert summary["count"] == sum(e.count for e in entries) == k
    rec = WireLedger(entries).reconcile(hlo)
    assert rec["exact"], rec
