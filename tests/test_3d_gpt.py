"""The full 3-D composition on the REAL model: GPT trained with data ×
pipeline × tensor parallelism in ONE compiled step — 1F1B over 'pipe',
Megatron head-sharded blocks over 'model', batch sharded over 'data' —
with loss and every gradient pinned against the single-device model."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from network_distributed_pytorch_tpu.models import next_token_loss
from network_distributed_pytorch_tpu.models.gpt import (
    GPTConfig,
    GPTLM,
    make_gpt_pipeline_train_fn,
    make_gpt_tp_stage_fn,
    split_gpt_params,
)
from network_distributed_pytorch_tpu.parallel.mesh import make_mesh
from network_distributed_pytorch_tpu.parallel.pipeline import (
    stacked_stage_params,
)

_TINY = dict(
    vocab_size=64, max_position_embeddings=16, dim=16, n_layers=2,
    n_heads=2, hidden_dim=32, dropout=0.0,
)


def _stage_specs(n_model_dims_ok=True):
    """Per-leaf specs for stacked stage params (pipe, layers, *block dims)
    with the block dims sharded per gpt_tp_param_specs' block entry."""
    col = {"kernel": P("pipe", None, None, "model"), "bias": P("pipe", None, "model")}
    row = {"kernel": P("pipe", None, "model", None), "bias": P("pipe", None)}
    ln = {"scale": P("pipe", None), "bias": P("pipe", None)}
    return {
        "layers": {
            "ln_1": ln,
            "attn": {"q_proj": col, "k_proj": col, "v_proj": col, "out_proj": row},
            "ln_2": ln,
            "mlp_fc": col,
            "mlp_proj": row,
        }
    }


def test_3d_gpt_matches_single_device(devices):
    """(2 data, 2 pipe, 2 model) mesh: the 3-D step's loss and EVERY
    gradient — embed/wpe (replicated), model-sharded stage leaves, final LN
    — match the plain single-device GPTLM gradients."""
    cfg = GPTConfig(**_TINY)
    model = GPTLM(cfg)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 64, (8, 16)))
    labels = jnp.asarray(rng.randint(0, 64, (8, 16)))
    params = model.init(jax.random.PRNGKey(0), ids[:1])["params"]

    ref_loss, ref_g = jax.value_and_grad(
        lambda p: next_token_loss(model.apply({"params": p}, ids), labels)
    )(params)

    n_stages = 2
    embed, stages, final = split_gpt_params(params, n_stages)
    stacked = stacked_stage_params(stages)
    mesh = make_mesh(
        axis_sizes=(2, 2, 2), axis_names=("data", "pipe", "model"),
        devices=devices,
    )
    train = make_gpt_pipeline_train_fn(
        cfg, layers_per_stage=1, num_microbatches=2,
        params_varying_over=("data",),
        stage_fn=make_gpt_tp_stage_fn(cfg, layers_per_stage=1),
    )

    def step(e, st, f, x, y):
        loss, (ge, gs, gf) = train(e, st, f, x, y)
        pm = lambda t: jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, "data"), t
        )
        return jax.lax.pmean(loss, "data"), pm(ge), pm(gs), pm(gf)

    sspecs = _stage_specs()
    loss3, ge, gs, gf = jax.jit(
        jax.shard_map(
            step, mesh=mesh,
            in_specs=(P(), sspecs, P(), P("data"), P("data")),
            out_specs=(P(), P(), sspecs, P()),
        )
    )(embed, stacked, final, ids, labels)

    np.testing.assert_allclose(float(loss3), float(ref_loss), rtol=1e-5)
    gmax = max(
        float(jnp.max(jnp.abs(l))) for l in jax.tree_util.tree_leaves(ref_g)
    )

    def close(a, b, what):
        d = float(jnp.max(jnp.abs(jnp.asarray(a) - jnp.asarray(b)))) / gmax
        assert d < 5e-5, (what, d)

    close(ge["wte"]["embedding"], ref_g["wte"]["embedding"], "wte")
    close(ge["wpe"]["embedding"], ref_g["wpe"]["embedding"], "wpe")
    close(gf["ln_f"]["scale"], ref_g["ln_f"]["scale"], "ln_f")
    # stage grads: (pipe, layers=1, ...) — stage i layer 0 == h_i
    for i in range(n_stages):
        blk = ref_g[f"h_{i}"]
        got = jax.tree_util.tree_map(lambda t: t[i, 0], gs["layers"])
        for (kp, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(blk),
            jax.tree_util.tree_leaves_with_path(got),
        ):
            close(b, a, f"h_{i}{jax.tree_util.keystr(kp)}")
