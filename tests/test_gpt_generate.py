"""KV-cache decoding: single-token decode steps match the full forward, and
greedy generate matches the naive (re-run-the-whole-prefix) loop."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from network_distributed_pytorch_tpu.models.gpt import (
    generate,
    gpt_decode_step,
    gpt_tiny,
    init_gpt_cache,
)

B, T = 2, 12


def _setup():
    model = gpt_tiny(max_position_embeddings=64)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (B, T)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    return model, params, ids


def test_decode_steps_match_full_forward(devices):
    model, params, ids = _setup()
    ref = model.apply({"params": params}, ids)  # (B, T, V)

    cache = init_gpt_cache(model.config, B, T)
    for i in range(T):
        logits, cache = gpt_decode_step(
            model.config, params, cache, ids[:, i], i
        )
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(ref[:, i]), rtol=2e-4, atol=2e-4
        )


@pytest.mark.slow
def test_greedy_generate_matches_naive_loop(devices):
    model, params, ids = _setup()
    new = 8

    # naive reference: re-run the full forward on the growing prefix
    cur = ids
    naive = []
    for _ in range(new):
        logits = model.apply({"params": params}, cur)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        naive.append(nxt)
        cur = jnp.concatenate([cur, nxt[:, None]], axis=1)
    naive = jnp.stack(naive, axis=1)

    out = jax.jit(
        lambda p, i: generate(model.config, p, i, max_new_tokens=new)
    )(params, ids)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(naive))


def test_temperature_sampling_shape_and_validity(devices):
    model, params, ids = _setup()
    out = generate(
        model.config, params, ids, max_new_tokens=5, temperature=0.8,
        key=jax.random.PRNGKey(42),
    )
    assert out.shape == (B, 5)
    assert bool(jnp.all((out >= 0) & (out < 128)))


def test_generate_zero_tokens_is_empty(devices):
    model, params, ids = _setup()
    out = generate(model.config, params, ids, max_new_tokens=0)
    assert out.shape == (B, 0)


def test_eos_early_stop_prefix_matches_full_run(devices):
    """EOS stop under static shapes: a row that samples EOS pads the rest
    of its row with the EOS id, and every token BEFORE the stop is
    bitwise-identical to the run without a stop condition."""
    model, params, ids = _setup()
    new = 8
    full = np.asarray(
        generate(model.config, params, ids, max_new_tokens=new)
    )
    # pick an id the run actually emits mid-sequence, so at least one row
    # genuinely stops early
    eos = int(full[0, new // 2])
    out = np.asarray(
        generate(
            model.config, params, ids, max_new_tokens=new, eos_token_id=eos
        )
    )
    assert out.shape == full.shape
    stopped_early = False
    for r in range(B):
        hits = np.where(full[r] == eos)[0]
        if hits.size == 0:
            np.testing.assert_array_equal(out[r], full[r])
            continue
        j = int(hits[0])
        stopped_early = stopped_early or j + 1 < new
        np.testing.assert_array_equal(out[r, : j + 1], full[r, : j + 1])
        assert (out[r, j + 1:] == eos).all()
    assert stopped_early


def test_eos_prompt_never_suppresses_first_token(devices):
    """A prompt that happens to END with the EOS id still generates: the
    stop condition watches SAMPLED tokens, and the first sampled token is
    only padded when it itself is EOS."""
    model, params, ids = _setup()
    full = np.asarray(generate(model.config, params, ids, max_new_tokens=4))
    eos = int(ids[0, -1])
    if int(full[0, 0]) == eos:  # degenerate draw; nothing to distinguish
        return
    out = np.asarray(
        generate(
            model.config, params, ids, max_new_tokens=4, eos_token_id=eos
        )
    )
    assert int(out[0, 0]) == int(full[0, 0])


def test_decode_step_does_not_mutate_input_cache(devices):
    model, params, ids = _setup()
    cache = init_gpt_cache(model.config, B, T)
    before = np.asarray(cache[0]["k"]).copy()
    _, cache2 = gpt_decode_step(model.config, params, cache, ids[:, 0], 0)
    np.testing.assert_array_equal(np.asarray(cache[0]["k"]), before)
    assert float(np.abs(np.asarray(cache2[0]["k"])).max()) > 0
