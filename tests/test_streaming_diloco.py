"""Streaming DiLoCo: K=1 degenerates to plain DiLoCo exactly, fragment-wise
sync trains with K× lower peak bytes, compressed fragments carry EF state,
and the per-phase wire cost reconciles with the compiled collectives."""

import jax
import jax.numpy as jnp
import numpy as np

from network_distributed_pytorch_tpu.parallel import (
    PowerSGDReducer,
    make_diloco_train_fn,
    make_mesh,
    make_streaming_diloco_train_fn,
)
from network_distributed_pytorch_tpu.parallel.trainer import (
    LOSS_SYNC_BITS,
    stateless_loss,
)

W = 8


def _problem():
    rng = np.random.RandomState(0)
    w_true = rng.randn(16, 4).astype(np.float32)
    x = rng.randn(64, 16).astype(np.float32)
    y = x @ w_true
    params = {"w": jnp.zeros((16, 4)), "b": jnp.zeros((4,))}

    def loss(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] + p["b"] - yb) ** 2)

    return params, stateless_loss(loss), (jnp.asarray(x), jnp.asarray(y))


def _stack(batch, h):
    return tuple(jnp.broadcast_to(b[None], (h,) + b.shape) for b in batch)


def test_k1_equals_plain_diloco(devices):
    """One fragment == plain DiLoCo, phase-for-round, params bit-close."""
    params, loss_fn, batch = _problem()
    mesh = make_mesh()
    h = 4
    stream = make_streaming_diloco_train_fn(
        loss_fn, params, inner_learning_rate=0.05, num_fragments=1,
        sync_every=h, mesh=mesh,
    )
    plain = make_diloco_train_fn(
        loss_fn, params, inner_learning_rate=0.05, sync_every=h,
        mesh=mesh, donate_state=False,
    )
    sstate, pstate = stream.init_state(params), plain.init_state(params)
    for r in range(4):
        sstate, slosses = stream(sstate, _stack(batch, h), r)
        pstate, plosses = plain(pstate, _stack(batch, h))
        np.testing.assert_allclose(
            np.asarray(slosses), np.asarray(plosses), rtol=1e-6
        )
    np.testing.assert_allclose(
        np.asarray(stream.eval_params(sstate)["w"]),
        np.asarray(plain.eval_params(pstate)["w"]),
        rtol=1e-5, atol=1e-7,
    )


def test_fragments_train_and_cut_peak_bytes(devices):
    """K=2 round-robin fragments: loss descends, every fragment's anchor
    eventually moves, and the peak per-sync bytes are well below a full
    parameter sync."""
    params, loss_fn, batch = _problem()
    mesh = make_mesh()
    h = 4
    stream = make_streaming_diloco_train_fn(
        loss_fn, params, inner_learning_rate=0.05, num_fragments=2,
        sync_every=h, inner_algorithm="sgd_plain", mesh=mesh,
    )
    full = make_diloco_train_fn(
        loss_fn, params, inner_learning_rate=0.05, sync_every=h,
        mesh=mesh, donate_state=False,
    )
    state = stream.init_state(params)
    first = last = None
    for _ in range(12):
        # no round_index: the phase counter rides in the carry, so a
        # checkpointed state resumes on the correct fragment schedule
        state, losses = stream(state, _stack(batch, h))
        if first is None:
            first = float(losses[0])
        last = float(losses[-1])
    assert last < 0.2 * first, (first, last)
    assert int(state.phase) == 12
    # both fragments synced: both anchors moved off the zero init
    assert float(jnp.max(jnp.abs(state.anchors["w"]))) > 0.0
    assert float(jnp.max(jnp.abs(state.anchors["b"]))) > 0.0
    assert stream.peak_sync_bits < full.bits_per_round
    # time-average matches plain DiLoCo at the same period
    np.testing.assert_allclose(
        stream.bits_per_step * stream.sync_every * stream.num_fragments,
        sum(stream.bits_per_phase),
    )


def test_compressed_fragments_with_ef(devices):
    """PowerSGD per fragment: trains, and the fragment EF memory holds the
    residual for the compressed leaf."""
    params, loss_fn, batch = _problem()
    mesh = make_mesh()
    h = 4
    stream = make_streaming_diloco_train_fn(
        loss_fn, params, inner_learning_rate=0.05, num_fragments=2,
        sync_every=h, inner_algorithm="sgd_plain", mesh=mesh,
        reducer=PowerSGDReducer(random_seed=7, compression_rank=2, matricize="last"),
    )
    state = stream.init_state(params)
    first = last = None
    for r in range(16):
        state, losses = stream(state, _stack(batch, h), r)
        if first is None:
            first = float(losses[0])
        last = float(losses[-1])
    assert last < 0.5 * first, (first, last)
    assert float(jnp.max(jnp.abs(state.memories["w"]))) > 0.0


def test_phase_wire_audit(devices):
    """Each compiled phase's collective payload reconciles with its analytic
    bits (scan-body loss pmean adjustment, as for local SGD/DiLoCo)."""
    from network_distributed_pytorch_tpu.utils.hlo_audit import (
        collective_summary,
        compiled_hlo_text,
    )

    params, loss_fn, batch = _problem()
    mesh = make_mesh()
    h = 4
    stream = make_streaming_diloco_train_fn(
        loss_fn, params, inner_learning_rate=0.05, num_fragments=2,
        sync_every=h, mesh=mesh,
    )
    state = stream.init_state(params)
    for k in range(2):
        hlo = compiled_hlo_text(
            stream.fns[k], state, _stack(batch, h), jnp.ones((h,), jnp.float32)
        )
        audit = collective_summary(hlo)
        audited = 8 * audit["total_payload_bytes"] + (h - 1) * LOSS_SYNC_BITS
        assert audited == stream.bits_per_phase[k], (k, audit)


def test_fragments_are_size_balanced(devices):
    """Greedy assignment keeps the PEAK phase bytes near total/K even with
    one dominant leaf — a round-robin split would leave the peak at the
    dominant leaf's full size plus whatever shared its bin."""
    big = {
        "emb": jnp.zeros((128, 16)),   # dominant
        "a": jnp.zeros((16, 16)), "b": jnp.zeros((16, 16)),
        "c": jnp.zeros((16, 16)), "d": jnp.zeros((16, 16)),
        "e": jnp.zeros((16, 16)), "f": jnp.zeros((16, 16)),
        "g": jnp.zeros((16, 16)), "h": jnp.zeros((16, 16)),
    }
    loss = stateless_loss(
        lambda p, batch: sum(
            jnp.sum(l ** 2) for l in jax.tree_util.tree_leaves(p)
        )
        + 0.0 * jnp.sum(batch[0])
    )
    stream = make_streaming_diloco_train_fn(
        loss, big, inner_learning_rate=0.01, num_fragments=2,
        sync_every=2, mesh=make_mesh(),
    )
    total = sum(stream.bits_per_phase)
    # dominant leaf (2048 elems) + balance of small leaves: peak should sit
    # well under 75% of total (round-robin with emb first would give ~64%+
    # of the PARAM bytes to one phase; greedy gives ~54%)
    assert stream.peak_sync_bits < 0.6 * total, (
        stream.bits_per_phase, total
    )
