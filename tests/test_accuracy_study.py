"""run_to_plateau semantics (scripts/accuracy_study.py) with faked
train_loop/step/evaluate — no devices: best-accuracy is tracked
unconditionally while the patience mark only moves on meaningful jumps, and
the plateaued flag reflects the break, not the curve length."""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_study(monkeypatch):
    # a non-"cpu" platform value skips the script's module-level env setup
    # (force_cpu_devices + rendezvous-deadline XLA_FLAGS), which would
    # otherwise leak into every later test's subprocesses
    monkeypatch.setenv("ACCURACY_STUDY_PLATFORM", "preset-by-conftest")
    spec = importlib.util.spec_from_file_location(
        "accuracy_study", os.path.join(REPO, "scripts", "accuracy_study.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _FakeLogger:
    def summary(self):
        return {"steps": 4}


class _FakeStep:
    bits_per_step = 800


def _run(mod, accs, max_epochs=30, patience=3, monkeypatch=None):
    import network_distributed_pytorch_tpu.experiments.common as common

    calls = {"n": 0}

    def fake_train_loop(step, state, batches, epochs, log_every=0, prefetch=0):
        return state, _FakeLogger()

    monkeypatch.setattr(common, "train_loop", fake_train_loop)

    def evaluate(step, state):
        i = min(calls["n"], len(accs) - 1)
        calls["n"] += 1
        return accs[i]

    return mod.run_to_plateau(
        "t", _FakeStep(), None, lambda e: iter(()), evaluate,
        max_epochs, patience,
    )


def test_best_tracks_small_gains(monkeypatch):
    """Steady sub-min_delta improvement: the patience mark stays put (the
    arm plateaus) but best_accuracy reports the true maximum, not epoch 0."""
    mod = _load_study(monkeypatch)
    accs = [0.90, 0.901, 0.9012, 0.9013, 0.9014, 0.9015]
    rec = _run(mod, accs, patience=3, monkeypatch=monkeypatch)
    assert rec["plateaued"] is True
    assert rec["epochs_run"] == 4  # mark at epoch 0, +patience
    assert rec["best_accuracy"] == 0.9013  # max seen, not the mark

def test_plateaued_true_when_break_on_last_epoch(monkeypatch):
    """Patience met exactly on the final allowed epoch still records
    plateaued=True (previously inferred — wrongly — from curve length)."""
    mod = _load_study(monkeypatch)
    accs = [0.5, 0.9, 0.9, 0.9, 0.9]
    rec = _run(mod, accs, max_epochs=5, patience=3, monkeypatch=monkeypatch)
    assert rec["epochs_run"] == 5
    assert rec["plateaued"] is True


def test_budget_capped_run_not_plateaued(monkeypatch):
    """Accuracy still climbing past min_delta each epoch when max_epochs
    runs out: plateaued=False."""
    mod = _load_study(monkeypatch)
    accs = [0.1, 0.2, 0.3, 0.4, 0.5, 0.6]
    rec = _run(mod, accs, max_epochs=4, patience=3, monkeypatch=monkeypatch)
    assert rec["epochs_run"] == 4
    assert rec["plateaued"] is False
    assert rec["best_accuracy"] == 0.4
    assert rec["total_mb_on_wire"] == round(800 * 16 / 8e6, 2)
