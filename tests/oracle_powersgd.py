"""Pure-NumPy oracle of the reference PowerSGD reduction (``reducer.py:43-170``),
implemented literally from the reference's math for golden-value parity tests.

The oracle simulates W workers in one process: it takes each worker's send
buffers, a shared initial Q, and returns what every worker's (identical)
decompressed output, per-worker error memories, next Q, and bit count must be.
"""

from typing import List, Sequence, Tuple

import numpy as np


def orthogonalize_np(matrix: np.ndarray, eps: float = 1e-8) -> np.ndarray:
    """Sequential-column Gram-Schmidt, the reference recurrence
    (``reducer.py:183-191``)."""
    matrix = matrix.copy()
    n, m = matrix.shape
    for i in range(m):
        col = matrix[:, i : i + 1]
        col /= np.sqrt(np.sum(col**2)) + eps
        if i + 1 < m:
            rest = matrix[:, i + 1 :]
            rest -= np.sum(col * rest, axis=0) * col
    return matrix


def matricize(t: np.ndarray, mode: str = "first") -> np.ndarray:
    if mode == "first":
        return t.reshape(t.shape[0], -1)
    return t.reshape(-1, t.shape[-1])


def powersgd_reduce_np(
    sends_per_worker: Sequence[List[np.ndarray]],
    qs: List[np.ndarray],
    compression_rank: int,
    matricize_mode: str = "first",
    n_power_iterations: int = 0,
) -> Tuple[List[np.ndarray], List[List[np.ndarray]], List[np.ndarray], int]:
    """One reduction step over W simulated workers.

    Returns (out, memories_per_worker, next_qs, bits). ``qs`` must be the
    current warm-start Qs for the high-rank tensors in leaf order.
    ``n_power_iterations`` adds extra P/Q subspace rounds (the framework's
    beyond-parity extension; 0 = the reference's single fused round).
    """
    n_workers = len(sends_per_worker)
    template = sends_per_worker[0]
    rank1_idx = [i for i, t in enumerate(template) if t.ndim <= 1]
    high_idx = [i for i, t in enumerate(template) if t.ndim > 1]

    bits = 0
    out = [None] * len(template)

    # rank-1 tensors: uncompressed allreduce-mean (reducer.py:130-133)
    for i in rank1_idx:
        stacked = np.stack([w[i] for w in sends_per_worker])
        out[i] = stacked.mean(axis=0)
        bits += 32 * template[i].size

    next_qs = list(qs)
    p_hats = [None] * len(high_idx)
    for _round in range(1 + n_power_iterations):
        # P = mean_w(M_w Q); bits count the packed P buffer (reducer.py:120-128)
        p_hats = []
        for j, i in enumerate(high_idx):
            mats = [matricize(w[i], matricize_mode) for w in sends_per_worker]
            p = np.mean([m @ next_qs[j] for m in mats], axis=0)
            bits += 32 * p.size
            p_hats.append(orthogonalize_np(p))

        # Q = mean_w(M_w^T P_hat) (reducer.py:139-147)
        next_qs = []
        for j, i in enumerate(high_idx):
            mats = [matricize(w[i], matricize_mode) for w in sends_per_worker]
            q = np.mean([m.T @ p_hats[j] for m in mats], axis=0)
            bits += 32 * q.size
            next_qs.append(q)

    # decompress P_hat Q^T (reducer.py:157-163)
    for j, i in enumerate(high_idx):
        out[i] = (p_hats[j] @ next_qs[j].T).reshape(template[i].shape)

    memories = []
    for w in sends_per_worker:
        mem = [np.zeros_like(t) for t in template]
        for i in high_idx:
            mem[i] = w[i] - out[i]
        memories.append(mem)

    return out, memories, next_qs, bits
