"""Streaming EWMA health detectors (jax-free, fast).

Every detector is clock-free and O(1) per observation, so each behavior
the live plane relies on is pinned exactly: EWMA warmup, spike severity
bands (warn vs the NaN-precursor critical), baseline freezing (a spike
or a drift must not poison the envelope it is judged against),
sustain-before-fire, cooldown heartbeats, and the monitor's per-rank
detector isolation.
"""

import math

import pytest

from network_distributed_pytorch_tpu.observe.health import (
    BandwidthCollapseDetector,
    DetectorConfig,
    Ewma,
    GradNormSpikeDetector,
    HealthMonitor,
    LossPlateauDetector,
    SloBurnRateDetector,
    StepTimeDriftDetector,
)


# ---------------------------------------------------------------------------
# the primitive
# ---------------------------------------------------------------------------


def test_ewma_mean_and_std():
    e = Ewma(alpha=0.5)
    assert e.mean is None and e.std == 0.0
    e.update(1.0)
    assert e.mean == 1.0
    assert e.std == 0.0  # a single sample has no spread
    e.update(3.0)
    assert e.mean == pytest.approx(2.0)
    assert e.std > 0.0
    for _ in range(50):
        e.update(2.0)
    assert e.mean == pytest.approx(2.0, rel=1e-3)
    assert e.std == pytest.approx(0.0, abs=0.1)


# ---------------------------------------------------------------------------
# grad-norm spike
# ---------------------------------------------------------------------------


def test_grad_spike_needs_warmup():
    det = GradNormSpikeDetector(DetectorConfig())
    # fewer than 3 observations: even a huge value cannot fire (the EWMA
    # has no envelope yet)
    assert det.observe(1.0) is None
    assert det.observe(1e6) is None


def test_grad_spike_warn_and_critical_bands():
    cfg = DetectorConfig(cooldown=0)
    det = GradNormSpikeDetector(cfg)
    for _ in range(10):
        assert det.observe(1.0) is None
    warn = det.observe(5.0)  # > 3x mean but < 50x mean
    assert warn is not None and warn.severity == "warn"
    critical = det.observe(100.0)  # > nan_factor x mean
    assert critical is not None and critical.severity == "critical"
    assert "NaN precursor" in critical.message


def test_grad_spike_non_finite_is_critical():
    det = GradNormSpikeDetector(DetectorConfig())
    a = det.observe(float("nan"))
    assert a is not None and a.severity == "critical"
    assert a.value == float("inf")  # JSON-safe


def test_grad_spike_does_not_poison_baseline():
    det = GradNormSpikeDetector(DetectorConfig(cooldown=0))
    for _ in range(10):
        det.observe(1.0)
    assert det.observe(1000.0) is not None
    # the spike was NOT folded into the EWMA: normal values stay quiet and
    # an identical second spike still fires
    assert det.observe(1.0) is None
    assert det.observe(1000.0) is not None


def test_grad_spike_cooldown_silences_repeats():
    det = GradNormSpikeDetector(DetectorConfig(cooldown=5))
    for _ in range(10):
        det.observe(1.0)
    assert det.observe(1000.0) is not None
    # within the cooldown window: sick but silent
    assert det.observe(1000.0) is None
    assert det.fired == 1


# ---------------------------------------------------------------------------
# loss plateau
# ---------------------------------------------------------------------------


def test_loss_plateau_quiet_while_improving():
    cfg = DetectorConfig(plateau_sustain=3, plateau_min_obs=5)
    det = LossPlateauDetector(cfg)
    loss = 10.0
    for _ in range(50):
        assert det.observe(loss) is None
        loss *= 0.9  # healthy steady improvement


def test_loss_plateau_fires_on_flat_loss():
    cfg = DetectorConfig(plateau_sustain=3, plateau_min_obs=5, cooldown=0)
    det = LossPlateauDetector(cfg)
    fired = [det.observe(1.0) for _ in range(40)]
    alerts = [a for a in fired if a is not None]
    assert alerts and alerts[0].alert == "loss_plateau"
    assert alerts[0].severity == "warn"


# ---------------------------------------------------------------------------
# step-time drift
# ---------------------------------------------------------------------------


def test_step_time_drift_fires_and_freezes_baseline():
    cfg = DetectorConfig(drift_sustain=3, drift_min_obs=5, cooldown=0)
    det = StepTimeDriftDetector(cfg)
    for _ in range(20):
        assert det.observe(0.010) is None
    fired = []
    for _ in range(30):
        a = det.observe(0.030)  # 3x the baseline
        if a is not None:
            fired.append(a)
    assert fired and fired[0].alert == "step_time_drift"
    # the baseline froze while drifted: it still reads ~10 ms, so the
    # detector keeps firing (a heartbeat) instead of self-silencing
    assert len(fired) >= 2
    assert det._slow.mean == pytest.approx(0.010, rel=0.05)


def test_step_time_drift_ignores_nonpositive():
    det = StepTimeDriftDetector(DetectorConfig())
    for v in (0.0, -1.0, float("nan")):
        assert det.observe(v) is None


# ---------------------------------------------------------------------------
# bandwidth collapse
# ---------------------------------------------------------------------------


def test_bandwidth_collapse_fires_after_sustain():
    cfg = DetectorConfig(collapse_sustain=3, collapse_min_obs=5, cooldown=0)
    det = BandwidthCollapseDetector(cfg)
    for _ in range(10):
        assert det.observe(100e6) is None
    results = [det.observe(10e6) for _ in range(5)]  # 0.1x baseline
    fired = [a for a in results if a is not None]
    # sustain=3: the first two collapsed windows accumulate, the third fires
    assert results[0] is None and results[1] is None
    assert fired and fired[0].alert == "bandwidth_collapse"


def test_bandwidth_collapse_sustain_resets_on_recovery():
    cfg = DetectorConfig(collapse_sustain=3, collapse_min_obs=5, cooldown=0)
    det = BandwidthCollapseDetector(cfg)
    for _ in range(10):
        det.observe(100e6)
    assert det.observe(10e6) is None
    assert det.observe(10e6) is None
    assert det.observe(100e6) is None  # recovery resets the streak
    assert det.observe(10e6) is None
    assert det.observe(10e6) is None
    assert det.fired == 0


# ---------------------------------------------------------------------------
# serving SLO burn
# ---------------------------------------------------------------------------


def test_slo_burn_fires_over_target():
    cfg = DetectorConfig(slo_target_s=1.0, slo_sustain=2, cooldown=0)
    det = SloBurnRateDetector(cfg)
    assert det.observe(0.5) is None
    assert det.observe(1.5) is None  # sustain=2: first breach accumulates
    a = det.observe(1.5)
    assert a is not None and a.alert == "slo_burn"
    assert a.threshold == 1.0


# ---------------------------------------------------------------------------
# the monitor
# ---------------------------------------------------------------------------


def test_monitor_per_rank_grad_detectors_are_isolated():
    mon = HealthMonitor(DetectorConfig(cooldown=0))
    # rank 0 learns a 1.0 baseline; rank 1 a 100.0 baseline
    for _ in range(10):
        assert mon.observe_grad_norm(1.0, rank=0) == []
        assert mon.observe_grad_norm(100.0, rank=1) == []
    # 100.0 is a spike for rank 0 but baseline for rank 1
    fired = mon.observe_grad_norm(100.0, rank=0, step=7)
    assert len(fired) == 1
    assert fired[0].rank == 0 and fired[0].step == 7
    assert mon.observe_grad_norm(100.0, rank=1) == []


def test_monitor_collects_and_counts_by_kind():
    mon = HealthMonitor(DetectorConfig(slo_target_s=1.0, slo_sustain=1,
                                       cooldown=0))
    mon.observe_serving_p99(2.0)
    mon.observe_serving_p99(3.0)
    assert len(mon.alerts) == 2
    assert mon.fired_by_kind() == {"slo_burn": 2}


def test_monitor_alert_records_are_json_safe():
    mon = HealthMonitor(DetectorConfig())
    mon.observe_grad_norm(float("inf"), rank=0)
    for a in mon.alerts:
        rec = a.record()
        assert rec["event"] == "alert"
        assert not any(
            isinstance(v, float) and math.isnan(v) for v in rec.values()
        )
