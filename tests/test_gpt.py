"""GPT decoder LM: causality, sequence-parallel exactness (ring + Ulysses
causal), and DDP training on a synthetic language task."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from network_distributed_pytorch_tpu.models.gpt import (
    GPTConfig,
    GPTLM,
    gpt_tiny,
    next_token_loss,
)
from network_distributed_pytorch_tpu.parallel import ExactReducer, make_mesh
from network_distributed_pytorch_tpu.parallel.trainer import (
    make_train_step,
    stateless_loss,
)

N = 8
T = 8 * N  # global sequence length (8 tokens per shard)


def _tokens(seed, b=2, t=T, vocab=128):
    return jnp.asarray(
        np.random.RandomState(seed).randint(0, vocab, (b, t)), jnp.int32
    )


def test_scan_layers_matches_unrolled(devices):
    """scan_layers runs the SAME math as the unrolled loop: with the
    unrolled params stacked into the scan layout, logits match; the round
    trip through unstack restores the original tree exactly; and a train
    step under scan_layers learns (grads flow through the scan)."""
    from network_distributed_pytorch_tpu.models.gpt import (
        stack_gpt_layer_params,
        unstack_gpt_layer_params,
    )

    cfg = dict(vocab_size=128, max_position_embeddings=64, dim=32,
               n_layers=3, n_heads=2, hidden_dim=64, dropout=0.0)
    unrolled = GPTLM(GPTConfig(**cfg))
    scanned = GPTLM(GPTConfig(scan_layers=True, **cfg))
    ids = _tokens(3, b=2, t=16)
    params_u = unrolled.init(jax.random.PRNGKey(0), ids)["params"]
    params_s = stack_gpt_layer_params(params_u, 3)
    # the stacked tree is what scanned.init would produce, shape-wise
    shapes_s = jax.eval_shape(
        lambda: scanned.init(jax.random.PRNGKey(0), ids)
    )["params"]
    assert jax.tree_util.tree_structure(params_s) == jax.tree_util.tree_structure(
        jax.tree_util.tree_map(lambda x: 0, shapes_s)
    )
    out_u = unrolled.apply({"params": params_u}, ids)
    out_s = scanned.apply({"params": params_s}, ids)
    np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_s), atol=2e-5)
    # understating n_layers must raise, not silently truncate the model
    with pytest.raises(ValueError, match="block keys"):
        stack_gpt_layer_params(params_u, 2)
    # round trip restores the unrolled tree bit-for-bit
    back = unstack_gpt_layer_params(params_s)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params_u, back,
    )
    # training step under scan_layers: loss descends on a repeat task
    mesh = make_mesh()
    toks = jnp.broadcast_to(
        jnp.arange(33, dtype=jnp.int32)[None, :] % 128, (16, 33)
    )
    batch = (toks[:, :-1], toks[:, 1:])

    def loss_fn(p, b):
        return next_token_loss(scanned.apply({"params": p}, b[0]), b[1])

    step = make_train_step(
        stateless_loss(loss_fn), ExactReducer(), params_s,
        learning_rate=0.1, momentum=0.9, algorithm="sgd", mesh=mesh,
    )
    state = step.init_state(params_s)
    first = last = None
    for _ in range(12):
        state, loss = step(state, batch)
        first = float(loss) if first is None else first
        last = float(loss)
    assert last < first * 0.7, (first, last)


def test_causality(devices):
    """Changing future tokens must not change past logits."""
    model = gpt_tiny()
    ids = _tokens(0, b=1, t=16)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    out1 = model.apply({"params": params}, ids)
    ids2 = ids.at[:, 10:].set((ids[:, 10:] + 7) % 128)
    out2 = model.apply({"params": params}, ids2)
    np.testing.assert_allclose(
        np.asarray(out1[:, :10]), np.asarray(out2[:, :10]), atol=1e-5
    )
    assert float(jnp.abs(out1[:, 10:] - out2[:, 10:]).max()) > 1e-3


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
@pytest.mark.slow
def test_seq_parallel_forward_matches_single_device(devices, impl):
    overrides = dict(max_position_embeddings=T)
    if impl == "ulysses":
        overrides.update(n_heads=N, dim=2 * N, hidden_dim=4 * N)
    base = gpt_tiny(**overrides)
    ids = _tokens(1)
    params = base.init(jax.random.PRNGKey(0), ids[:, :8])["params"]
    ref = base.apply({"params": params}, ids)

    mesh = make_mesh(axis_sizes=(N,), axis_names=("seq",))
    sharded_model = gpt_tiny(seq_axis="seq", seq_impl=impl, **overrides)
    out = jax.jit(
        jax.shard_map(
            lambda p, i: sharded_model.apply({"params": p}, i),
            mesh=mesh,
            in_specs=(P(), P(None, "seq")),
            out_specs=P(None, "seq"),
        )
    )(params, ids)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


@pytest.mark.slow
def test_gpt_ddp_training_learns(devices):
    """Exact-DDP training on a deterministic next-token task (cyclic
    sequences => the next token is fully predictable)."""
    model = gpt_tiny(vocab_size=16, max_position_embeddings=32)
    rng = np.random.RandomState(0)

    def batch(seed, b=16, t=32):
        start = np.random.RandomState(seed).randint(0, 16, (b, 1))
        toks = (start + np.arange(t + 1)[None, :]) % 16
        toks = jnp.asarray(toks, jnp.int32)
        return toks[:, :-1], toks[:, 1:]

    ids, _ = batch(0)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    def loss_fn(params, b):
        x, y = b
        return next_token_loss(model.apply({"params": params}, x), y)

    mesh = make_mesh()
    step = make_train_step(
        stateless_loss(loss_fn), ExactReducer(), params, learning_rate=0.1,
        momentum=0.9, algorithm="sgd", mesh=mesh, donate_state=False,
    )
    state = step.init_state(params)
    losses = []
    for i in range(30):
        state, loss = step(state, batch(i % 4))
        losses.append(float(loss))
    assert losses[-1] < 0.3 * losses[0], losses[::6]
