"""Supervisor: spawn, watch, restart, degrade — against real subprocesses.

The fast tests drive the jax-free ``toy_supervised_worker`` (millisecond
restarts) through every supervisor code path: crash → restart → resume,
hang → heartbeat-kill → restart, restart exhaustion → degraded world
shrink, and ``allow_degraded=False`` → run declared dead. The slow test is
the ISSUE's acceptance bar: a REAL training rank (SmallCNN + PowerSGD EF)
SIGKILLed mid-epoch by its chaos plan, restarted by the supervisor, resumed
from the committed checkpoint — and the final params/EF-memory digests are
bit-identical to an uninterrupted run.
"""

import json
import os
import subprocess
import sys

import pytest

from network_distributed_pytorch_tpu.launch import worker_argv_base
from network_distributed_pytorch_tpu.observe import MemorySink, Telemetry
from network_distributed_pytorch_tpu.resilience import (
    CKPT_UNWRITABLE_EXIT_CODE,
    PREEMPT_EXIT_CODE,
    ChaosPlan,
    FaultSpec,
    Supervisor,
    SupervisorConfig,
    mesh_from_env,
    plan_mesh,
)
from network_distributed_pytorch_tpu.resilience.supervisor import ENV_MESH

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
TOY = os.path.join(TESTS_DIR, "toy_supervised_worker.py")
JAXWORKER = os.path.join(TESTS_DIR, "supervised_worker.py")


def _telemetry():
    sink = MemorySink()
    return Telemetry([sink]), sink


def _kinds(sink):
    return [r.get("kind") for r in sink.records if r.get("event") == "failure"]


def _toy_argv(tmp_path, steps=6, plan_path=None, heartbeat=False,
              step_seconds=0.01, graceful_term=False):
    def argv_for_rank(rank, world, incarnation):
        argv = [
            sys.executable, TOY,
            "--rank", str(rank), "--world", str(world),
            "--steps", str(steps),
            "--state-dir", str(tmp_path / "state"),
            "--result-dir", str(tmp_path / "results"),
            "--step-seconds", str(step_seconds),
        ]
        if plan_path:
            argv += ["--chaos-plan", plan_path]
        if heartbeat:
            argv += ["--heartbeat-dir", str(tmp_path / "hb")]
        if graceful_term:
            argv += ["--graceful-term"]
        return argv

    return argv_for_rank


def _result(tmp_path, rank):
    with open(tmp_path / "results" / f"rank{rank}.json") as f:
        return json.load(f)


def test_toy_crash_restart_resume(tmp_path):
    """Rank 1 exits non-zero at step 2; the supervisor restarts it and the
    restarted life resumes from the persisted accumulator — total progress
    is exactly steps * world, not recomputed from zero."""
    plan_path = str(tmp_path / "plan.json")
    ChaosPlan([FaultSpec(kind="proc_exit", step=2, rank=1)]).save(plan_path)
    telemetry, sink = _telemetry()
    result = Supervisor(
        _toy_argv(tmp_path, steps=6, plan_path=plan_path),
        world_size=2,
        config=SupervisorConfig(
            max_restarts=2, backoff_base_s=0.01, poll_interval_s=0.02,
        ),
        telemetry=telemetry,
    ).run()
    assert result.success
    assert result.total_restarts == 1
    assert not result.degraded
    assert result.world_size == 2
    r0, r1 = _result(tmp_path, 0), _result(tmp_path, 1)
    assert r0["value"] == r1["value"] == 6 * 2  # resumed, not restarted
    assert r0["incarnation"] == 0
    assert r1["incarnation"] == 1  # finished in its second life
    kinds = _kinds(sink)
    assert "worker_exit" in kinds
    assert "worker_restart" in kinds
    assert "run_complete" in kinds


def test_toy_hang_detected_by_heartbeat(tmp_path):
    """Rank 0 stops beating (sleeps forever); the supervisor notices the
    stale heartbeat, kills it, and the restarted incarnation finishes."""
    plan_path = str(tmp_path / "plan.json")
    ChaosPlan(
        [FaultSpec(kind="proc_hang", step=2, rank=0,
                   payload={"hang_seconds": 60.0})]
    ).save(plan_path)
    telemetry, sink = _telemetry()
    result = Supervisor(
        _toy_argv(tmp_path, steps=5, plan_path=plan_path, heartbeat=True),
        world_size=1,
        config=SupervisorConfig(
            max_restarts=2, backoff_base_s=0.01, poll_interval_s=0.05,
            heartbeat_dir=str(tmp_path / "hb"),
            heartbeat_timeout_s=0.5, startup_grace_s=5.0,
            deadline_s=30.0,
        ),
        telemetry=telemetry,
    ).run()
    assert result.success, result.reason
    assert result.total_restarts == 1
    assert _result(tmp_path, 0)["value"] == 5
    kinds = _kinds(sink)
    assert "worker_hang" in kinds
    assert "worker_restart" in kinds


def test_toy_degraded_world_shrink(tmp_path):
    """Rank 1 crashes in EVERY life (incarnation=None): once its restart
    budget is gone the supervisor relaunches the survivors on a shrunk
    world instead of declaring the run dead."""
    plan_path = str(tmp_path / "plan.json")
    ChaosPlan(
        [FaultSpec(kind="proc_exit", step=1, rank=1, incarnation=None)]
    ).save(plan_path)
    telemetry, sink = _telemetry()
    result = Supervisor(
        _toy_argv(tmp_path, steps=5, plan_path=plan_path),
        world_size=2,
        config=SupervisorConfig(
            max_restarts=1, backoff_base_s=0.01, poll_interval_s=0.02,
            deadline_s=60.0,
        ),
        telemetry=telemetry,
    ).run()
    assert result.success, result.reason
    assert result.degraded
    assert result.world_size == 1
    assert "degraded_restart" in _kinds(sink)
    # the surviving rank finished on the shrunk world; its later steps
    # accumulated world=1 increments (the accounting was recomputed)
    r0 = _result(tmp_path, 0)
    assert r0["world"] == 1
    assert r0["step"] == 5
    # rank 1 never completed
    assert not os.path.exists(tmp_path / "results" / "rank1.json")


def test_toy_no_degraded_declares_dead(tmp_path):
    plan_path = str(tmp_path / "plan.json")
    ChaosPlan(
        [FaultSpec(kind="proc_exit", step=1, rank=1, incarnation=None)]
    ).save(plan_path)
    telemetry, sink = _telemetry()
    result = Supervisor(
        _toy_argv(tmp_path, steps=5, plan_path=plan_path),
        world_size=2,
        config=SupervisorConfig(
            max_restarts=1, backoff_base_s=0.01, poll_interval_s=0.02,
            allow_degraded=False, deadline_s=60.0,
        ),
        telemetry=telemetry,
    ).run()
    assert not result.success
    assert "max_restarts" in result.reason
    assert "run_failed" in _kinds(sink)


def test_toy_sigkill_shows_negative_returncode(tmp_path):
    """A SIGKILLed worker (no cleanup, no atexit) is restarted like any
    crash; the recorded exit code is the signal's negative returncode."""
    plan_path = str(tmp_path / "plan.json")
    ChaosPlan([FaultSpec(kind="proc_kill", step=1, rank=0)]).save(plan_path)
    telemetry, sink = _telemetry()
    result = Supervisor(
        _toy_argv(tmp_path, steps=4, plan_path=plan_path),
        world_size=1,
        config=SupervisorConfig(
            max_restarts=2, backoff_base_s=0.01, poll_interval_s=0.02,
        ),
        telemetry=telemetry,
    ).run()
    assert result.success
    assert result.total_restarts == 1
    exits = [
        r for r in sink.records
        if r.get("event") == "failure" and r.get("kind") == "worker_exit"
    ]
    assert any("exit code -9" in e.get("message", "") for e in exits)


def test_toy_graceful_vs_hard_death_classification(tmp_path):
    """Rank 0 gets a preemption notice (self-SIGTERM, honored: state saved,
    exit ``PREEMPT_EXIT_CODE``); rank 1 is SIGKILLed. Both are restarted
    and finish, but the supervisor's worker_exit events classify the two
    deaths differently — graceful vs hard — which is what the report
    timeline's death tally reads."""
    plan_path = str(tmp_path / "plan.json")
    ChaosPlan(
        [
            FaultSpec(kind="proc_preempt", step=2, rank=0),
            FaultSpec(kind="proc_kill", step=1, rank=1),
        ]
    ).save(plan_path)
    telemetry, sink = _telemetry()
    result = Supervisor(
        _toy_argv(tmp_path, steps=4, plan_path=plan_path, graceful_term=True),
        world_size=2,
        config=SupervisorConfig(
            max_restarts=2, backoff_base_s=0.01, poll_interval_s=0.02,
        ),
        telemetry=telemetry,
    ).run()
    assert result.success
    assert result.total_restarts == 2
    # the preempted rank saved at the SIGTERM, so no progress was lost
    r0, r1 = _result(tmp_path, 0), _result(tmp_path, 1)
    assert r0["value"] == r1["value"] == 4 * 2
    msgs = [
        r.get("message", "") for r in sink.records
        if r.get("event") == "failure" and r.get("kind") == "worker_exit"
    ]
    assert any(
        f"exit code {PREEMPT_EXIT_CODE} (graceful death)" in m for m in msgs
    )
    assert any("exit code -9 (hard death)" in m for m in msgs)


def test_plan_mesh_policy_table():
    """The quorum planner maximizes world, then trades TENSOR for DATA
    (smallest tensor wins the tie, then smallest fsdp), keeps model axes
    at divisors of their old degree, and returns None below the floor."""
    old = {"data": 2, "fsdp": 1, "tensor": 2}
    assert plan_mesh(old, 2) == {"data": 2, "fsdp": 1, "tensor": 1}
    assert plan_mesh(old, 3) == {"data": 3, "fsdp": 1, "tensor": 1}
    assert plan_mesh(old, 4) == {"data": 4, "fsdp": 1, "tensor": 1}
    assert plan_mesh(old, 1) == {"data": 1, "fsdp": 1, "tensor": 1}
    assert plan_mesh(old, 1, min_world=2) is None
    assert plan_mesh(old, 0) is None
    assert plan_mesh({"data": 2, "fsdp": 2, "tensor": 2}, 4) == {
        "data": 4, "fsdp": 1, "tensor": 1
    }
    # a pure-DP mesh just shrinks/grows along data
    assert plan_mesh({"data": 4}, 3) == {"data": 3, "fsdp": 1, "tensor": 1}


def test_mesh_from_env_roundtrip(monkeypatch):
    monkeypatch.delenv(ENV_MESH, raising=False)
    assert mesh_from_env() is None
    monkeypatch.setenv(ENV_MESH, json.dumps({"data": 2, "tensor": 2}))
    assert mesh_from_env() == {"data": 2, "tensor": 2}
    monkeypatch.setenv(ENV_MESH, "not json")
    assert mesh_from_env() is None


def test_toy_quorum_replan_on_zone_outage(tmp_path):
    """Tentpole: a correlated 2-rank zone outage on a 2(data) x 2(tensor)
    world is ONE incident — the supervisor replans the survivors to the
    largest viable mesh (2x1x1, tensor traded for data), emits a typed
    ReshapeEvent, and the run completes degraded instead of burning both
    ranks' restart budgets independently."""
    plan_path = str(tmp_path / "plan.json")
    ChaosPlan(
        [FaultSpec(kind="zone_outage", step=2, payload={"ranks": [2, 3]})]
    ).save(plan_path)
    telemetry, sink = _telemetry()
    result = Supervisor(
        _toy_argv(tmp_path, steps=6, plan_path=plan_path),
        world_size=4,
        config=SupervisorConfig(
            max_restarts=2, backoff_base_s=0.01, poll_interval_s=0.02,
            allow_degraded=True, min_world_size=2, term_grace_s=0.5,
            mesh_axes={"data": 2, "tensor": 2}, correlation_window_s=5.0,
            deadline_s=60.0,
        ),
        telemetry=telemetry,
    ).run()
    assert result.success, result.reason
    assert result.degraded
    assert result.world_size == 2
    assert result.final_mesh == {"data": 2, "fsdp": 1, "tensor": 1}
    reshapes = [r for r in sink.records if r.get("event") == "reshape"]
    assert len(reshapes) == 1
    assert reshapes[0]["correlated"] is True
    assert reshapes[0]["dead_ranks"] == [2, 3]
    assert reshapes[0]["old_mesh"] == {"data": 2, "fsdp": 1, "tensor": 2}
    assert reshapes[0]["new_mesh"] == {"data": 2, "fsdp": 1, "tensor": 1}
    degraded = [
        r.get("message", "") for r in sink.records
        if r.get("kind") == "degraded_restart"
    ]
    assert any("correlated death of ranks [2, 3]" in m for m in degraded)
    # the survivors finished the run on the replanned world
    for rank in (0, 1):
        res = _result(tmp_path, rank)
        assert res["step"] == 6
        assert res["world"] == 2


def test_toy_host_flap_stays_independent(tmp_path):
    """A single flapping host (hard death in each of its first two lives)
    burns its own restart budget — same-rank deaths inside the window are
    NOT a correlated incident, so no replan happens."""
    plan_path = str(tmp_path / "plan.json")
    ChaosPlan(
        [FaultSpec(kind="host_flap", step=1, rank=1, incarnation=None,
                   payload={"flaps": 2})]
    ).save(plan_path)
    telemetry, sink = _telemetry()
    result = Supervisor(
        _toy_argv(tmp_path, steps=4, plan_path=plan_path),
        world_size=2,
        config=SupervisorConfig(
            max_restarts=3, backoff_base_s=0.01, poll_interval_s=0.02,
            mesh_axes={"data": 2}, correlation_window_s=5.0, deadline_s=60.0,
        ),
        telemetry=telemetry,
    ).run()
    assert result.success, result.reason
    assert not result.degraded
    assert result.world_size == 2
    assert result.total_restarts == 2  # the flap's two hard deaths
    assert result.final_mesh == {"data": 2, "fsdp": 1, "tensor": 1}
    assert not [r for r in sink.records if r.get("event") == "reshape"]
    r1 = _result(tmp_path, 1)
    assert r1["step"] == 4 and r1["incarnation"] == 2  # third life finished


def test_toy_ckpt_unwritable_fails_fast(tmp_path):
    """Satellite: a worker that exits with the CKPT_UNWRITABLE sentinel
    (its state path is persistently unwritable — here the atomic-write tmp
    path is occupied by a directory, which defeats even root) stops the
    run IMMEDIATELY: no restart storm against a broken checkpoint dir."""
    state_dir = tmp_path / "state"
    state_dir.mkdir()
    (state_dir / "rank0.json.tmp").mkdir()  # open(tmp, "w") -> EISDIR
    telemetry, sink = _telemetry()
    result = Supervisor(
        _toy_argv(tmp_path, steps=4),
        world_size=1,
        config=SupervisorConfig(
            max_restarts=3, backoff_base_s=0.01, poll_interval_s=0.02,
            deadline_s=60.0,
        ),
        telemetry=telemetry,
    ).run()
    assert not result.success
    assert result.total_restarts == 0  # fail-fast, not a restart storm
    assert "unwritable" in result.reason
    assert result.exit_codes.get(0) == CKPT_UNWRITABLE_EXIT_CODE
    kinds = _kinds(sink)
    assert "run_failed" in kinds and "worker_restart" not in kinds


def test_worker_argv_base_strips_supervisor_flags():
    argv = [
        "--experiment", "exact", "--supervise", "--max-restarts", "5",
        "--heartbeat-timeout=30", "--no-degraded",
        "--process-id", "3", "--num-processes", "8",
        "--chaos-plan", "plan.json", "--epochs", "2",
    ]
    assert worker_argv_base(argv) == [
        "--experiment", "exact", "--chaos-plan", "plan.json", "--epochs", "2",
    ]


# ---------------------------------------------------------------------------
# the acceptance bar: kill-and-resume determinism on a REAL training rank
# ---------------------------------------------------------------------------

def _run_jax_worker_supervised(tmp_path, name, plan_path=None, epochs=3):
    ckpt = str(tmp_path / name / "ckpt")
    result_path = str(tmp_path / name / "result.json")
    event_log = str(tmp_path / name / "events.jsonl")
    os.makedirs(str(tmp_path / name), exist_ok=True)

    def argv_for_rank(rank, world, incarnation):
        argv = [
            sys.executable, JAXWORKER,
            "--rank", str(rank), "--world", str(world),
            "--epochs", str(epochs),
            "--ckpt-dir", ckpt, "--result", result_path,
            "--event-log", event_log,
        ]
        if plan_path:
            argv += ["--chaos-plan", plan_path]
        return argv

    telemetry, sink = _telemetry()
    result = Supervisor(
        argv_for_rank, world_size=1,
        config=SupervisorConfig(
            max_restarts=2, backoff_base_s=0.05, poll_interval_s=0.1,
            deadline_s=540.0,
        ),
        telemetry=telemetry,
        log_dir=str(tmp_path / name / "logs"),
    ).run()
    assert result.success, (result.reason, result.exit_codes)
    with open(result_path) as f:
        digests = json.load(f)
    events = []
    with open(event_log) as f:
        for line in f:
            try:
                events.append(json.loads(line))
            except ValueError:
                pass
    return result, digests, events, sink


@pytest.mark.slow
def test_kill_and_resume_matches_uninterrupted(devices, tmp_path):
    """SIGKILL a rank mid-epoch (chaos proc_kill at step 6 of a 3-epoch x
    4-step run), let the supervisor restart it, and assert the resumed
    run's final params and EF memories are bit-identical to an
    uninterrupted run — the EF chain continued from the committed
    checkpoint, not from scratch."""
    _, ref, _, _ = _run_jax_worker_supervised(tmp_path, "ref")

    plan_path = str(tmp_path / "plan.json")
    ChaosPlan(
        [FaultSpec(kind="proc_kill", step=6, rank=0)]  # epoch 1, mid-epoch
    ).save(plan_path)
    result, killed, events, sink = _run_jax_worker_supervised(
        tmp_path, "killed", plan_path=plan_path
    )

    assert result.total_restarts == 1
    assert killed["incarnation"] == 1  # finished in its second life
    assert killed["start_epoch"] == 1  # resumed from the epoch-0 checkpoint
    assert killed["params_digest"] == ref["params_digest"]
    assert killed["memories_digest"] == ref["memories_digest"]

    worker_kinds = [
        e.get("kind") for e in events if e.get("event") == "failure"
    ]
    assert "chaos_injected" in worker_kinds  # the kill, from life 0
    assert "resumed" in worker_kinds  # the restart, from life 1
    parent_kinds = _kinds(sink)
    assert "worker_exit" in parent_kinds
    assert "worker_restart" in parent_kinds
    assert "worker_complete" in parent_kinds
