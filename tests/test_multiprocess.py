"""TRUE multi-process rendezvous (round-1 verdict: L1 was the only layer with
zero execution evidence). Spawns 2 OS processes that rendezvous through
``jax.distributed.initialize`` over a localhost coordinator — the reference's
operating unit (one rank per process, ``ddp_guide/run_script.py:4-23``,
``tcp://`` rendezvous ``ddp_guide_cifar10/ddp_init.py:91``) — runs ExactReducer
training steps through ``multihost.global_batch_from_local``, and asserts the
losses equal a single-process run of the same problem."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "multiprocess_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _single_process_reference(nproc: int, kind: str = "exact"):
    """The same toy problem in ONE process on an nproc-device virtual mesh
    (the same collective code path, no OS-process boundary)."""
    import jax
    import jax.numpy as jnp

    from network_distributed_pytorch_tpu.parallel import (
        ExactReducer,
        PowerSGDReducer,
        make_mesh,
    )
    from network_distributed_pytorch_tpu.parallel.trainer import (
        make_train_step,
        stateless_loss,
    )

    rng = np.random.RandomState(1234)
    w_true = rng.randn(16, 4).astype(np.float32)
    x = rng.randn(8 * nproc, 16).astype(np.float32)
    y = x @ w_true
    params = {"w": jnp.zeros((16, 4)), "b": jnp.zeros((4,))}

    def loss(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] + p["b"] - yb) ** 2)

    if kind == "diloco":
        from network_distributed_pytorch_tpu.parallel import (
            make_diloco_train_fn,
        )

        diloco = make_diloco_train_fn(
            stateless_loss(loss), params, inner_learning_rate=0.05,
            sync_every=2, inner_algorithm="sgd_plain",
            mesh=make_mesh(devices=jax.devices()[:nproc]), donate_state=False,
            reducer=PowerSGDReducer(
                random_seed=1234, compression_rank=2, matricize="last"
            ),
        )
        dstate = diloco.init_state(params)
        stacked = tuple(
            jnp.stack([jnp.asarray(a), jnp.asarray(a[::-1].copy())])
            for a in (x, y)
        )
        losses = []
        for _ in range(2):
            dstate, dl = diloco(dstate, stacked)
            losses.extend(float(v) for v in np.asarray(dl))
        return losses, float(np.asarray(diloco.eval_params(dstate)["w"])[0, 0])
    if kind == "powersgd":
        reducer, algo = PowerSGDReducer(
            random_seed=1234, compression_rank=2, matricize="last"
        ), "ef_momentum"
        mesh = make_mesh(devices=jax.devices()[:nproc])
    else:
        # exact DDP == single-device large batch (equal shards)
        reducer, algo, mesh = ExactReducer(), "sgd", None
    step = make_train_step(
        stateless_loss(loss), reducer, params, learning_rate=0.05,
        momentum=0.9, algorithm=algo, mesh=mesh, donate_state=False,
    )
    state = step.init_state(params)
    batch = (jnp.asarray(x), jnp.asarray(y))
    losses = []
    for _ in range(3):
        state, l = step(state, batch)
        losses.append(float(l))
    return losses, float(np.asarray(state.params["w"])[0, 0])


@pytest.mark.slow
def test_two_process_rendezvous_matches_single_process(devices):
    nproc = 2
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(pid), str(nproc)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        for pid in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("multi-process rendezvous timed out in this environment")
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}"

    results = {}
    for out in outs:
        for line in out.splitlines():
            if not line.startswith("RESULT"):
                continue
            fields = dict(kv.split("=") for kv in line.split()[1:])
            results[(fields["kind"], int(fields["pid"]))] = (
                [float(v) for v in fields["losses"].split(",")],
                float(fields["w00"]),
            )
    for kind in ("exact", "powersgd", "diloco"):
        assert (kind, 0) in results and (kind, 1) in results, results.keys()
        # both ranks report the same (pmean'd) losses and identical params
        assert results[(kind, 0)] == results[(kind, 1)]
        ref_losses, ref_w00 = _single_process_reference(nproc, kind)
        # exact: 2-process DDP == single-device full batch; powersgd: the
        # EF/warm-start chain over REAL process boundaries == the same chain
        # on a single-process 2-device mesh
        np.testing.assert_allclose(results[(kind, 0)][0], ref_losses, rtol=1e-6)
        np.testing.assert_allclose(results[(kind, 0)][1], ref_w00, rtol=1e-6)
