"""Chaos matrix + checkpoint hardening.

The fault-injection half of the resilience story: every recoverable fault
kind in ``resilience.chaos`` is injected into a real (small) training run
and the run must complete with the documented recovery — and, for the
state-preserving faults, land on EXACTLY the parameters of a clean run.
The checkpoint tests prove the commit protocol: a torn directory is never
selected, a bit-flip is caught by checksums at restore, and ``restore_latest``
falls back to the previous good step with a telemetry trail.
"""

import json
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from network_distributed_pytorch_tpu.experiments.common import (
    resilient_train_loop,
)
from network_distributed_pytorch_tpu.models import SmallCNN
from network_distributed_pytorch_tpu.observe import MemorySink, Telemetry
from network_distributed_pytorch_tpu.parallel import PowerSGDReducer, make_mesh
from network_distributed_pytorch_tpu.parallel.trainer import (
    make_train_step,
    stateless_loss,
)
from network_distributed_pytorch_tpu.resilience import (
    PROCESS_FAULTS,
    ChaosPlan,
    ChaosStep,
    ChaosTransientError,
    FaultSpec,
    GuardedStep,
    NonFiniteLossError,
    PreemptionGuard,
    chaos_batches,
    guarded_batches,
)
from network_distributed_pytorch_tpu.resilience.chaos import (
    bitflip_checkpoint,
    tear_checkpoint,
)
from network_distributed_pytorch_tpu.utils import cross_entropy_loss
from network_distributed_pytorch_tpu.utils.checkpoint import (
    COMMITTED_MARKER,
    CHECKSUM_MANIFEST,
    committed_step_paths,
    gc_checkpoints,
    is_committed,
    latest_step_path,
    read_topology,
    restore_latest,
    save_checkpoint,
    verify_checkpoint,
)

IMG = (8, 8, 3)
EPOCHS = 2
BATCH = 32


def _setup():
    model = SmallCNN(width=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *IMG)))["params"]

    def lf(p, b):
        x, y = b
        return cross_entropy_loss(model.apply({"params": p}, x), y)

    mesh = make_mesh()
    step = make_train_step(
        stateless_loss(lf),
        PowerSGDReducer(random_seed=7, compression_rank=2, matricize="last"),
        params, learning_rate=0.05, momentum=0.9, algorithm="ef_momentum",
        mesh=mesh, donate_state=False,
    )
    return step, params


def _batches(epoch, steps=3):
    rng = np.random.RandomState(1000 + epoch)
    means = np.random.RandomState(999).randn(10, *IMG)
    for _ in range(steps):
        y = rng.randint(0, 10, BATCH)
        x = means[y] + 0.5 * rng.randn(BATCH, *IMG)
        yield jnp.asarray(x, jnp.float32), jnp.asarray(y)


def _telemetry():
    sink = MemorySink()
    return Telemetry([sink]), sink


def _kinds(sink):
    return [r.get("kind") for r in sink.records if r.get("event") == "failure"]


def _run(tmp_path, name, plan=None, **kw):
    step, params = _setup()
    telemetry, sink = _telemetry()
    state, _, _ = resilient_train_loop(
        step, step.init_state(params), _batches, EPOCHS,
        checkpoint_dir=str(tmp_path / name), telemetry=telemetry,
        run_name=name, chaos_plan=plan, **kw,
    )
    return state, sink


def _assert_params_equal(a, b):
    for x, y in zip(
        jax.tree_util.tree_leaves(a.params), jax.tree_util.tree_leaves(b.params)
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# chaos matrix: every recoverable fault kind x its documented recovery
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize(
    "kind", ["loader_bad_batch", "loader_short_batch"]
)
def test_chaos_matrix_loader_faults_dropped(devices, tmp_path, kind):
    """A poisoned/short batch is injected, detected, and dropped; the run
    completes on the remaining batches."""
    plan = ChaosPlan([FaultSpec(kind=kind, step=1)], seed=3)
    state, sink = _run(
        tmp_path, f"chaos-{kind}", plan=plan,
        guard_batches=True, expected_batch=BATCH,
    )
    kinds = _kinds(sink)
    assert "chaos_injected" in kinds
    assert "bad_batch_dropped" in kinds
    assert all(
        np.isfinite(np.asarray(l)).all()
        for l in jax.tree_util.tree_leaves(state.params)
    )


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["step_transient", "step_nan"])
def test_chaos_matrix_step_faults_retried_bit_exact(devices, tmp_path, kind):
    """A transient step error / NaN loss is retried without advancing state,
    so the final parameters are BIT-IDENTICAL to a clean run."""
    clean, _ = _run(tmp_path, "clean")
    plan = ChaosPlan([FaultSpec(kind=kind, step=2)], seed=3)
    state, sink = _run(
        tmp_path, f"chaos-{kind}", plan=plan, step_retries=2,
    )
    kinds = _kinds(sink)
    assert "chaos_injected" in kinds
    assert "retry" in kinds
    _assert_params_equal(state, clean)
    for a, b in zip(
        jax.tree_util.tree_leaves(state.memories),
        jax.tree_util.tree_leaves(clean.memories),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["ckpt_torn", "ckpt_bitflip"])
def test_chaos_matrix_checkpoint_faults_fall_back(devices, tmp_path, kind):
    """A corrupted newest checkpoint is skipped at resume; the run restarts
    from the previous good epoch and still finishes all epochs."""
    plan = ChaosPlan([FaultSpec(kind=kind, step=1)], seed=3)
    # run 2 epochs, corrupting the epoch-1 checkpoint after it lands
    _run(tmp_path, "chaos-ckpt", plan=plan)
    root = str(tmp_path / "chaos-ckpt")
    if kind == "ckpt_torn":
        # torn: no marker -> not even listed as committed
        assert latest_step_path(root) == os.path.join(root, "step_0")
    else:
        # bitflip: still committed, only checksums can catch it
        assert latest_step_path(root) == os.path.join(root, "step_1")
        ok, reason = verify_checkpoint(os.path.join(root, "step_1"))
        assert not ok and "checksum mismatch" in reason

    # resume: falls back to step_0, re-trains epoch 1, emits the fallback
    step, params = _setup()
    telemetry, sink = _telemetry()
    state, _, start_epoch = resilient_train_loop(
        step, step.init_state(params), _batches, EPOCHS,
        checkpoint_dir=root, telemetry=telemetry, run_name="resume",
    )
    assert start_epoch == 1
    if kind == "ckpt_bitflip":
        assert "checkpoint_fallback" in _kinds(sink)
    # the re-save replaced the corrupt step_1 with a good one
    ok, reason = verify_checkpoint(os.path.join(root, "step_1"))
    assert ok, reason


@pytest.mark.slow
def test_chaos_full_matrix_combined(devices, tmp_path):
    """All recoverable fault kinds in ONE run — recoveries compose."""
    plan = ChaosPlan(
        [
            FaultSpec(kind="loader_bad_batch", step=0),
            FaultSpec(kind="loader_short_batch", step=3),
            FaultSpec(kind="step_transient", step=1),
            FaultSpec(kind="step_nan", step=2),
        ],
        seed=5,
    )
    state, sink = _run(
        tmp_path, "combined", plan=plan, step_retries=2,
        guard_batches=True, expected_batch=BATCH,
    )
    kinds = _kinds(sink)
    assert kinds.count("chaos_injected") == 4
    assert "bad_batch_dropped" in kinds and "retry" in kinds


# ---------------------------------------------------------------------------
# preemption grace: SIGTERM -> emergency checkpoint -> mid-epoch resume
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_preempt_grace_checkpoint_and_midepoch_resume(devices, tmp_path):
    """A ``proc_preempt`` fault SIGTERMs the process mid-epoch; the
    installed guard turns it into an emergency COMMITTED checkpoint at the
    next step boundary (epoch cursor recorded), the loop stops early, and
    the resumed run re-enters the SAME epoch at the right step — landing
    bit-identical to an uninterrupted run."""
    clean, _ = _run(tmp_path, "preempt-clean")

    plan = ChaosPlan([FaultSpec(kind="proc_preempt", step=1)], seed=3)
    step, params = _setup()
    telemetry, sink = _telemetry()
    root = str(tmp_path / "preempt")
    with PreemptionGuard(telemetry=telemetry) as guard:
        resilient_train_loop(
            step, step.init_state(params), _batches, EPOCHS,
            checkpoint_dir=root, telemetry=telemetry, run_name="preempt",
            chaos_plan=plan, preemption_guard=guard,
        )
    assert guard.checkpoint_saved
    kinds = _kinds(sink)
    assert "chaos_injected" in kinds
    assert "preempt_notice" in kinds
    assert "preempt_checkpoint" in kinds
    # the emergency save carries the mid-epoch cursor: 2 of 3 steps done
    cursor = read_topology(os.path.join(root, "step_0"))["epoch_cursor"]
    assert cursor == {"epoch": 0, "batches_done": 2}

    step2, params2 = _setup()
    telemetry2, sink2 = _telemetry()
    resumed, _, start_epoch = resilient_train_loop(
        step2, step2.init_state(params2), _batches, EPOCHS,
        checkpoint_dir=root, telemetry=telemetry2, run_name="resume",
    )
    assert start_epoch == 0  # the preempted epoch, not the next one
    msg = next(
        r["message"] for r in sink2.records if r.get("kind") == "resumed"
    )
    assert "+2 steps" in msg
    _assert_params_equal(resumed, clean)
    for a, b in zip(
        jax.tree_util.tree_leaves(resumed.memories),
        jax.tree_util.tree_leaves(clean.memories),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# chaos primitives (fast, no training loop)
# ---------------------------------------------------------------------------

def test_fault_kinds_include_proc_preempt():
    assert "proc_preempt" in PROCESS_FAULTS
    FaultSpec(kind="proc_preempt", step=0)  # accepted, not "unknown kind"


def test_preemption_guard_turns_sigterm_into_flag():
    prev = signal.getsignal(signal.SIGTERM)
    telemetry, sink = _telemetry()
    with PreemptionGuard(telemetry=telemetry, rank=1) as guard:
        assert not guard.requested
        os.kill(os.getpid(), signal.SIGTERM)  # the process survives this
        assert guard.requested
    assert signal.getsignal(signal.SIGTERM) == prev  # disposition restored
    notices = [r for r in sink.records if r.get("kind") == "preempt_notice"]
    assert len(notices) == 1
    assert notices[0]["rank"] == 1

def test_chaos_plan_roundtrip_and_once_semantics(tmp_path):
    plan = ChaosPlan(
        [
            FaultSpec(kind="proc_kill", step=2, rank=1),
            FaultSpec(kind="step_nan", step=2, rank=None, incarnation=None),
        ],
        seed=9,
    )
    path = plan.save(str(tmp_path / "plan.json"))
    loaded = ChaosPlan.load(path)
    assert loaded.seed == 9
    assert [f.kind for f in loaded.faults] == ["proc_kill", "step_nan"]

    # rank filter: rank 0 at step 2 only matches the any-rank spec
    spec = loaded.pop(("step_nan",), 2, rank=0, incarnation=5)
    assert spec is not None and spec.kind == "step_nan"
    # once-per-spec: the same trigger never fires twice
    assert loaded.pop(("step_nan",), 2, rank=0, incarnation=5) is None
    # incarnation filter: the default-0 proc_kill won't fire in life 1
    assert loaded.pop(("proc_kill",), 2, rank=1, incarnation=1) is None
    assert loaded.pop(("proc_kill",), 2, rank=1, incarnation=0) is not None


def test_fault_spec_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec(kind="meteor_strike", step=0)


def test_chaos_step_transient_and_nan(devices):
    calls = []

    class FakeStep:
        bits_per_step = 123

        def __call__(self, state, batch):
            calls.append(batch)
            return state + 1, 0.5

    plan = ChaosPlan(
        [
            FaultSpec(kind="step_transient", step=0),
            FaultSpec(kind="step_nan", step=1),
        ]
    )
    telemetry, sink = _telemetry()
    wrapped = ChaosStep(FakeStep(), plan, telemetry=telemetry)
    assert wrapped.bits_per_step == 123  # delegation
    with pytest.raises(ChaosTransientError):
        wrapped(0, "b0")
    # step_nan: state NOT advanced, loss non-finite, inner never called
    state, loss = wrapped(0, "b1")
    assert state == 0 and np.isnan(loss)
    assert calls == []
    # past the schedule, the real step runs
    state, loss = wrapped(0, "b2")
    assert state == 1 and calls == ["b2"]
    assert _kinds(sink).count("chaos_injected") == 2


def test_guarded_step_retries_nan_without_advancing(devices):
    attempts = []

    class FlakyStep:
        def __call__(self, state, batch):
            attempts.append(state)
            if len(attempts) == 1:
                return state + 100, jnp.float32(float("nan"))
            return state + 1, jnp.float32(0.25)

    telemetry, sink = _telemetry()
    guarded = GuardedStep(
        FlakyStep(), retries=2, backoff_seconds=0.0, telemetry=telemetry
    )
    state, loss = guarded(0, None)
    assert state == 1  # poisoned +100 update was discarded
    assert attempts == [0, 0]  # same inputs replayed
    assert "retry" in _kinds(sink)


def test_guarded_step_exhausted_raises(devices):
    class AlwaysNaN:
        def __call__(self, state, batch):
            return state, jnp.float32(float("nan"))

    telemetry, _ = _telemetry()
    guarded = GuardedStep(
        AlwaysNaN(), retries=1, backoff_seconds=0.0, telemetry=telemetry
    )
    with pytest.raises(NonFiniteLossError):
        guarded(0, None)


def test_chaos_batches_poison_and_short(devices):
    def src(epoch):
        for _ in range(2):
            yield (np.zeros((8, 4), np.float32), np.zeros((8,), np.int32))

    plan = ChaosPlan(
        [
            FaultSpec(kind="loader_bad_batch", step=0),
            FaultSpec(kind="loader_short_batch", step=1),
        ],
        seed=2,
    )
    telemetry, sink = _telemetry()
    out = list(chaos_batches(src, plan, telemetry=telemetry)(0))
    assert np.isnan(np.asarray(out[0][0])).any()
    assert np.asarray(out[1][0]).shape[0] == 4  # halved leading dim
    assert np.asarray(out[1][1]).shape[0] == 4

    # guarded_batches drops exactly the two poisoned ones
    plan2 = ChaosPlan(
        [
            FaultSpec(kind="loader_bad_batch", step=0),
            FaultSpec(kind="loader_short_batch", step=1),
        ],
        seed=2,
    )
    poisoned = chaos_batches(src, plan2, telemetry=telemetry)
    guarded = guarded_batches(poisoned, expected_batch=8, telemetry=telemetry)
    assert list(guarded(0)) == []
    assert _kinds(sink).count("bad_batch_dropped") == 2


# ---------------------------------------------------------------------------
# checkpoint hardening: the commit protocol
# ---------------------------------------------------------------------------

def _tree(v: float):
    return {
        "w": np.full((16, 8), v, np.float32),
        "b": np.arange(8, dtype=np.float32) * v,
    }


def test_commit_protocol_artifacts(devices, tmp_path):
    root = str(tmp_path / "ck")
    final = save_checkpoint(root, _tree(1.0), step=0)
    assert final == os.path.join(os.path.abspath(root), "step_0")
    assert is_committed(final)
    assert os.path.isfile(os.path.join(final, CHECKSUM_MANIFEST))
    with open(os.path.join(final, COMMITTED_MARKER)) as f:
        assert json.load(f)["step"] == 0
    ok, reason = verify_checkpoint(final)
    assert ok, reason
    # no leftover tmp dirs
    assert not [n for n in os.listdir(root) if n.startswith("_tmp.")]


def test_abort_before_commit_leaves_only_tmp(devices, tmp_path):
    """The mid-save crash seam: data written, commit never ran — readers
    must see NO checkpoint at all."""
    root = str(tmp_path / "ck")
    tmp = save_checkpoint(root, _tree(1.0), step=0, _abort_before_commit=True)
    assert os.path.basename(tmp).startswith("_tmp.")
    assert os.path.isdir(tmp)
    assert not os.path.isdir(os.path.join(root, "step_0"))
    assert latest_step_path(root) is None
    assert restore_latest(root, _tree(0.0)) is None


def test_torn_checkpoint_never_selected(devices, tmp_path):
    root = str(tmp_path / "ck")
    save_checkpoint(root, _tree(1.0), step=0)
    save_checkpoint(root, _tree(2.0), step=1)
    tear_checkpoint(os.path.join(root, "step_1"))
    assert latest_step_path(root) == os.path.join(
        os.path.abspath(root), "step_0"
    )
    restored = restore_latest(root, _tree(0.0))
    assert restored is not None
    state, step = restored
    assert step == 0
    np.testing.assert_array_equal(state["w"], _tree(1.0)["w"])


def test_bitflip_caught_by_checksums_with_fallback_event(devices, tmp_path):
    root = str(tmp_path / "ck")
    save_checkpoint(root, _tree(1.0), step=0)
    save_checkpoint(root, _tree(2.0), step=1)
    bitflip_checkpoint(os.path.join(root, "step_1"), seed=4)
    # still committed — only verification can tell
    assert latest_step_path(root) == os.path.join(
        os.path.abspath(root), "step_1"
    )
    telemetry, sink = _telemetry()
    restored = restore_latest(root, _tree(0.0), telemetry=telemetry, label="t")
    assert restored is not None
    state, step = restored
    assert step == 0
    np.testing.assert_array_equal(state["w"], _tree(1.0)["w"])
    fallbacks = [
        r for r in sink.records
        if r.get("event") == "failure" and r.get("kind") == "checkpoint_fallback"
    ]
    assert len(fallbacks) == 1
    assert "checksum mismatch" in fallbacks[0]["message"]


def test_manifest_catches_extra_and_missing_files(devices, tmp_path):
    root = str(tmp_path / "ck")
    final = save_checkpoint(root, _tree(1.0), step=0)
    with open(os.path.join(final, "smuggled.bin"), "wb") as f:
        f.write(b"x")
    ok, reason = verify_checkpoint(final)
    assert not ok and "unmanifested" in reason
    os.remove(os.path.join(final, "smuggled.bin"))
    with open(os.path.join(final, CHECKSUM_MANIFEST)) as f:
        victim = sorted(json.load(f))[0]
    os.remove(os.path.join(final, victim))
    ok, reason = verify_checkpoint(final)
    assert not ok and "missing file" in reason


def test_gc_keep_last(devices, tmp_path):
    root = str(tmp_path / "ck")
    for s in range(4):
        save_checkpoint(root, _tree(float(s)), step=s)
    # a foreign abandoned tmp dir gets collected too
    os.makedirs(os.path.join(root, "_tmp.step_9.99999"))
    deleted = gc_checkpoints(root, keep_last=2)
    kept = [s for s, _ in committed_step_paths(root)]
    assert kept == [3, 2]
    assert any("_tmp.step_9" in d for d in deleted)

    # keep_last threaded through save_checkpoint
    save_checkpoint(root, _tree(9.0), step=4, keep_last=2)
    assert [s for s, _ in committed_step_paths(root)] == [4, 3]
    with pytest.raises(ValueError):
        gc_checkpoints(root, keep_last=0)


def test_restore_latest_empty_root(devices, tmp_path):
    assert restore_latest(str(tmp_path / "nope"), _tree(0.0)) is None
