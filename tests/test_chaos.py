"""Chaos matrix + checkpoint hardening.

The fault-injection half of the resilience story: every recoverable fault
kind in ``resilience.chaos`` is injected into a real (small) training run
and the run must complete with the documented recovery — and, for the
state-preserving faults, land on EXACTLY the parameters of a clean run.
The checkpoint tests prove the commit protocol: a torn directory is never
selected, a bit-flip is caught by checksums at restore, and ``restore_latest``
falls back to the previous good step with a telemetry trail.
"""

import json
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from network_distributed_pytorch_tpu.experiments.common import (
    resilient_train_loop,
)
from network_distributed_pytorch_tpu.models import SmallCNN
from network_distributed_pytorch_tpu.observe import MemorySink, Telemetry
from network_distributed_pytorch_tpu.parallel import PowerSGDReducer, make_mesh
from network_distributed_pytorch_tpu.parallel.trainer import (
    make_train_step,
    stateless_loss,
)
from network_distributed_pytorch_tpu.resilience import (
    COMM_FAULTS,
    CORRELATED_FAULTS,
    FAULT_KINDS,
    INJECTION_SITES,
    PROCESS_FAULTS,
    ChaosPlan,
    ChaosStep,
    ChaosTransientError,
    CheckpointUnwritableError,
    CollectiveWatchdog,
    CommDeadlineGuard,
    CommEscalationError,
    CommFaultInjector,
    FallbackController,
    FaultSpec,
    GuardedStep,
    NonFiniteLossError,
    PreemptionGuard,
    Rung,
    chaos_batches,
    check_fault_registry,
    guarded_batches,
)
from network_distributed_pytorch_tpu.resilience.chaos import (
    bitflip_checkpoint,
    make_checkpoint_unwritable,
    restore_checkpoint_writable,
    tear_checkpoint,
)
from network_distributed_pytorch_tpu.utils import cross_entropy_loss
from network_distributed_pytorch_tpu.utils.checkpoint import (
    COMMITTED_MARKER,
    CHECKSUM_MANIFEST,
    committed_step_paths,
    gc_checkpoints,
    is_committed,
    latest_step_path,
    read_topology,
    restore_latest,
    save_checkpoint,
    verify_checkpoint,
)

IMG = (8, 8, 3)
EPOCHS = 2
BATCH = 32


def _setup():
    model = SmallCNN(width=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *IMG)))["params"]

    def lf(p, b):
        x, y = b
        return cross_entropy_loss(model.apply({"params": p}, x), y)

    mesh = make_mesh()
    step = make_train_step(
        stateless_loss(lf),
        PowerSGDReducer(random_seed=7, compression_rank=2, matricize="last"),
        params, learning_rate=0.05, momentum=0.9, algorithm="ef_momentum",
        mesh=mesh, donate_state=False,
    )
    return step, params


def _batches(epoch, steps=3):
    rng = np.random.RandomState(1000 + epoch)
    means = np.random.RandomState(999).randn(10, *IMG)
    for _ in range(steps):
        y = rng.randint(0, 10, BATCH)
        x = means[y] + 0.5 * rng.randn(BATCH, *IMG)
        yield jnp.asarray(x, jnp.float32), jnp.asarray(y)


def _telemetry():
    sink = MemorySink()
    return Telemetry([sink]), sink


def _kinds(sink):
    return [r.get("kind") for r in sink.records if r.get("event") == "failure"]


def _run(tmp_path, name, plan=None, **kw):
    step, params = _setup()
    telemetry, sink = _telemetry()
    state, _, _ = resilient_train_loop(
        step, step.init_state(params), _batches, EPOCHS,
        checkpoint_dir=str(tmp_path / name), telemetry=telemetry,
        run_name=name, chaos_plan=plan, **kw,
    )
    return state, sink


def _assert_params_equal(a, b):
    for x, y in zip(
        jax.tree_util.tree_leaves(a.params), jax.tree_util.tree_leaves(b.params)
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# chaos matrix: every recoverable fault kind x its documented recovery
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize(
    "kind", ["loader_bad_batch", "loader_short_batch"]
)
def test_chaos_matrix_loader_faults_dropped(devices, tmp_path, kind):
    """A poisoned/short batch is injected, detected, and dropped; the run
    completes on the remaining batches."""
    plan = ChaosPlan([FaultSpec(kind=kind, step=1)], seed=3)
    state, sink = _run(
        tmp_path, f"chaos-{kind}", plan=plan,
        guard_batches=True, expected_batch=BATCH,
    )
    kinds = _kinds(sink)
    assert "chaos_injected" in kinds
    assert "bad_batch_dropped" in kinds
    assert all(
        np.isfinite(np.asarray(l)).all()
        for l in jax.tree_util.tree_leaves(state.params)
    )


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["step_transient", "step_nan"])
def test_chaos_matrix_step_faults_retried_bit_exact(devices, tmp_path, kind):
    """A transient step error / NaN loss is retried without advancing state,
    so the final parameters are BIT-IDENTICAL to a clean run."""
    clean, _ = _run(tmp_path, "clean")
    plan = ChaosPlan([FaultSpec(kind=kind, step=2)], seed=3)
    state, sink = _run(
        tmp_path, f"chaos-{kind}", plan=plan, step_retries=2,
    )
    kinds = _kinds(sink)
    assert "chaos_injected" in kinds
    assert "retry" in kinds
    _assert_params_equal(state, clean)
    for a, b in zip(
        jax.tree_util.tree_leaves(state.memories),
        jax.tree_util.tree_leaves(clean.memories),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["ckpt_torn", "ckpt_bitflip"])
def test_chaos_matrix_checkpoint_faults_fall_back(devices, tmp_path, kind):
    """A corrupted newest checkpoint is skipped at resume; the run restarts
    from the previous good epoch and still finishes all epochs."""
    plan = ChaosPlan([FaultSpec(kind=kind, step=1)], seed=3)
    # run 2 epochs, corrupting the epoch-1 checkpoint after it lands
    _run(tmp_path, "chaos-ckpt", plan=plan)
    root = str(tmp_path / "chaos-ckpt")
    if kind == "ckpt_torn":
        # torn: no marker -> not even listed as committed
        assert latest_step_path(root) == os.path.join(root, "step_0")
    else:
        # bitflip: still committed, only checksums can catch it
        assert latest_step_path(root) == os.path.join(root, "step_1")
        ok, reason = verify_checkpoint(os.path.join(root, "step_1"))
        assert not ok and "checksum mismatch" in reason

    # resume: falls back to step_0, re-trains epoch 1, emits the fallback
    step, params = _setup()
    telemetry, sink = _telemetry()
    state, _, start_epoch = resilient_train_loop(
        step, step.init_state(params), _batches, EPOCHS,
        checkpoint_dir=root, telemetry=telemetry, run_name="resume",
    )
    assert start_epoch == 1
    if kind == "ckpt_bitflip":
        assert "checkpoint_fallback" in _kinds(sink)
    # the re-save replaced the corrupt step_1 with a good one
    ok, reason = verify_checkpoint(os.path.join(root, "step_1"))
    assert ok, reason


@pytest.mark.slow
def test_chaos_full_matrix_combined(devices, tmp_path):
    """All recoverable fault kinds in ONE run — recoveries compose."""
    plan = ChaosPlan(
        [
            FaultSpec(kind="loader_bad_batch", step=0),
            FaultSpec(kind="loader_short_batch", step=3),
            FaultSpec(kind="step_transient", step=1),
            FaultSpec(kind="step_nan", step=2),
        ],
        seed=5,
    )
    state, sink = _run(
        tmp_path, "combined", plan=plan, step_retries=2,
        guard_batches=True, expected_batch=BATCH,
    )
    kinds = _kinds(sink)
    assert kinds.count("chaos_injected") == 4
    assert "bad_batch_dropped" in kinds and "retry" in kinds


# ---------------------------------------------------------------------------
# preemption grace: SIGTERM -> emergency checkpoint -> mid-epoch resume
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_preempt_grace_checkpoint_and_midepoch_resume(devices, tmp_path):
    """A ``proc_preempt`` fault SIGTERMs the process mid-epoch; the
    installed guard turns it into an emergency COMMITTED checkpoint at the
    next step boundary (epoch cursor recorded), the loop stops early, and
    the resumed run re-enters the SAME epoch at the right step — landing
    bit-identical to an uninterrupted run."""
    clean, _ = _run(tmp_path, "preempt-clean")

    plan = ChaosPlan([FaultSpec(kind="proc_preempt", step=1)], seed=3)
    step, params = _setup()
    telemetry, sink = _telemetry()
    root = str(tmp_path / "preempt")
    with PreemptionGuard(telemetry=telemetry) as guard:
        resilient_train_loop(
            step, step.init_state(params), _batches, EPOCHS,
            checkpoint_dir=root, telemetry=telemetry, run_name="preempt",
            chaos_plan=plan, preemption_guard=guard,
        )
    assert guard.checkpoint_saved
    kinds = _kinds(sink)
    assert "chaos_injected" in kinds
    assert "preempt_notice" in kinds
    assert "preempt_checkpoint" in kinds
    # the emergency save carries the mid-epoch cursor: 2 of 3 steps done
    cursor = read_topology(os.path.join(root, "step_0"))["epoch_cursor"]
    assert cursor == {"epoch": 0, "batches_done": 2}

    step2, params2 = _setup()
    telemetry2, sink2 = _telemetry()
    resumed, _, start_epoch = resilient_train_loop(
        step2, step2.init_state(params2), _batches, EPOCHS,
        checkpoint_dir=root, telemetry=telemetry2, run_name="resume",
    )
    assert start_epoch == 0  # the preempted epoch, not the next one
    msg = next(
        r["message"] for r in sink2.records if r.get("kind") == "resumed"
    )
    assert "+2 steps" in msg
    _assert_params_equal(resumed, clean)
    for a, b in zip(
        jax.tree_util.tree_leaves(resumed.memories),
        jax.tree_util.tree_leaves(clean.memories),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# chaos primitives (fast, no training loop)
# ---------------------------------------------------------------------------

def test_fault_kinds_include_proc_preempt():
    assert "proc_preempt" in PROCESS_FAULTS
    FaultSpec(kind="proc_preempt", step=0)  # accepted, not "unknown kind"


def test_preemption_guard_turns_sigterm_into_flag():
    prev = signal.getsignal(signal.SIGTERM)
    telemetry, sink = _telemetry()
    with PreemptionGuard(telemetry=telemetry, rank=1) as guard:
        assert not guard.requested
        os.kill(os.getpid(), signal.SIGTERM)  # the process survives this
        assert guard.requested
    assert signal.getsignal(signal.SIGTERM) == prev  # disposition restored
    notices = [r for r in sink.records if r.get("kind") == "preempt_notice"]
    assert len(notices) == 1
    assert notices[0]["rank"] == 1

def test_chaos_plan_roundtrip_and_once_semantics(tmp_path):
    plan = ChaosPlan(
        [
            FaultSpec(kind="proc_kill", step=2, rank=1),
            FaultSpec(kind="step_nan", step=2, rank=None, incarnation=None),
        ],
        seed=9,
    )
    path = plan.save(str(tmp_path / "plan.json"))
    loaded = ChaosPlan.load(path)
    assert loaded.seed == 9
    assert [f.kind for f in loaded.faults] == ["proc_kill", "step_nan"]

    # rank filter: rank 0 at step 2 only matches the any-rank spec
    spec = loaded.pop(("step_nan",), 2, rank=0, incarnation=5)
    assert spec is not None and spec.kind == "step_nan"
    # once-per-spec: the same trigger never fires twice
    assert loaded.pop(("step_nan",), 2, rank=0, incarnation=5) is None
    # incarnation filter: the default-0 proc_kill won't fire in life 1
    assert loaded.pop(("proc_kill",), 2, rank=1, incarnation=1) is None
    assert loaded.pop(("proc_kill",), 2, rank=1, incarnation=0) is not None


def test_fault_spec_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec(kind="meteor_strike", step=0)


def test_chaos_plan_load_time_validation(tmp_path):
    """Satellite: a malformed plan refuses at LOAD time, naming the
    offending entry index — not a crash hours later at injection time."""
    with pytest.raises(ValueError, match=r"fault\[1\] must be an object"):
        ChaosPlan.from_json(
            {"faults": [{"kind": "proc_kill", "step": 0}, "zap"]}
        )
    with pytest.raises(
        ValueError, match=r"fault\[0\] invalid: unknown fault kind"
    ):
        ChaosPlan.from_json({"faults": [{"kind": "meteor", "step": 0}]})
    with pytest.raises(ValueError, match=r"fault\[0\] invalid"):
        ChaosPlan.from_json(
            {"faults": [{"kind": "proc_kill", "step": 0, "at_rank": 1}]}
        )
    with pytest.raises(
        ValueError, match=r"fault\[2\] invalid: step must be an int"
    ):
        ChaosPlan.from_json({"faults": [
            {"kind": "proc_kill", "step": 0},
            {"kind": "step_nan", "step": 1},
            {"kind": "proc_exit", "step": "soon"},
        ]})
    with pytest.raises(
        ValueError, match=r"fault\[0\] invalid: payload\['ranks'\]"
    ):
        ChaosPlan.from_json({"faults": [
            {"kind": "zone_outage", "step": 0, "payload": {"ranks": []}}
        ]})
    # ChaosPlan.load routes files through the same validation
    path = tmp_path / "bad_plan.json"
    path.write_text(json.dumps({"faults": [{"kind": "meteor", "step": 0}]}))
    with pytest.raises(ValueError, match=r"fault\[0\]"):
        ChaosPlan.load(str(path))


def test_correlated_faults_registered_and_zone_matching():
    assert set(CORRELATED_FAULTS) == {"zone_outage", "host_flap"}
    for kind in CORRELATED_FAULTS:
        assert INJECTION_SITES[kind] == "process"
    assert INJECTION_SITES["ckpt_unwritable"] == "checkpoint"
    # payload["ranks"] overrides the rank field: every zone member matches
    spec = FaultSpec(kind="zone_outage", step=3, payload={"ranks": [2, 3]})
    assert spec.matches(3, 2, 0) and spec.matches(3, 3, 0)
    assert not spec.matches(3, 0, 0)
    assert not spec.matches(2, 2, 0)  # wrong step
    # host_flap matches every incarnation; the worker's flaps cap decides
    # which lives actually die
    flap = FaultSpec(kind="host_flap", step=1, rank=0, incarnation=None)
    assert flap.matches(1, 0, 0) and flap.matches(1, 0, 5)


def test_chaos_step_transient_and_nan(devices):
    calls = []

    class FakeStep:
        bits_per_step = 123

        def __call__(self, state, batch):
            calls.append(batch)
            return state + 1, 0.5

    plan = ChaosPlan(
        [
            FaultSpec(kind="step_transient", step=0),
            FaultSpec(kind="step_nan", step=1),
        ]
    )
    telemetry, sink = _telemetry()
    wrapped = ChaosStep(FakeStep(), plan, telemetry=telemetry)
    assert wrapped.bits_per_step == 123  # delegation
    with pytest.raises(ChaosTransientError):
        wrapped(0, "b0")
    # step_nan: state NOT advanced, loss non-finite, inner never called
    state, loss = wrapped(0, "b1")
    assert state == 0 and np.isnan(loss)
    assert calls == []
    # past the schedule, the real step runs
    state, loss = wrapped(0, "b2")
    assert state == 1 and calls == ["b2"]
    assert _kinds(sink).count("chaos_injected") == 2


def test_guarded_step_retries_nan_without_advancing(devices):
    attempts = []

    class FlakyStep:
        def __call__(self, state, batch):
            attempts.append(state)
            if len(attempts) == 1:
                return state + 100, jnp.float32(float("nan"))
            return state + 1, jnp.float32(0.25)

    telemetry, sink = _telemetry()
    guarded = GuardedStep(
        FlakyStep(), retries=2, backoff_seconds=0.0, telemetry=telemetry
    )
    state, loss = guarded(0, None)
    assert state == 1  # poisoned +100 update was discarded
    assert attempts == [0, 0]  # same inputs replayed
    assert "retry" in _kinds(sink)


def test_guarded_step_exhausted_raises(devices):
    class AlwaysNaN:
        def __call__(self, state, batch):
            return state, jnp.float32(float("nan"))

    telemetry, _ = _telemetry()
    guarded = GuardedStep(
        AlwaysNaN(), retries=1, backoff_seconds=0.0, telemetry=telemetry
    )
    with pytest.raises(NonFiniteLossError):
        guarded(0, None)


def test_chaos_batches_poison_and_short(devices):
    def src(epoch):
        for _ in range(2):
            yield (np.zeros((8, 4), np.float32), np.zeros((8,), np.int32))

    plan = ChaosPlan(
        [
            FaultSpec(kind="loader_bad_batch", step=0),
            FaultSpec(kind="loader_short_batch", step=1),
        ],
        seed=2,
    )
    telemetry, sink = _telemetry()
    out = list(chaos_batches(src, plan, telemetry=telemetry)(0))
    assert np.isnan(np.asarray(out[0][0])).any()
    assert np.asarray(out[1][0]).shape[0] == 4  # halved leading dim
    assert np.asarray(out[1][1]).shape[0] == 4

    # guarded_batches drops exactly the two poisoned ones
    plan2 = ChaosPlan(
        [
            FaultSpec(kind="loader_bad_batch", step=0),
            FaultSpec(kind="loader_short_batch", step=1),
        ],
        seed=2,
    )
    poisoned = chaos_batches(src, plan2, telemetry=telemetry)
    guarded = guarded_batches(poisoned, expected_batch=8, telemetry=telemetry)
    assert list(guarded(0)) == []
    assert _kinds(sink).count("bad_batch_dropped") == 2


# ---------------------------------------------------------------------------
# checkpoint hardening: the commit protocol
# ---------------------------------------------------------------------------

def _tree(v: float):
    return {
        "w": np.full((16, 8), v, np.float32),
        "b": np.arange(8, dtype=np.float32) * v,
    }


def test_commit_protocol_artifacts(devices, tmp_path):
    root = str(tmp_path / "ck")
    final = save_checkpoint(root, _tree(1.0), step=0)
    assert final == os.path.join(os.path.abspath(root), "step_0")
    assert is_committed(final)
    assert os.path.isfile(os.path.join(final, CHECKSUM_MANIFEST))
    with open(os.path.join(final, COMMITTED_MARKER)) as f:
        assert json.load(f)["step"] == 0
    ok, reason = verify_checkpoint(final)
    assert ok, reason
    # no leftover tmp dirs
    assert not [n for n in os.listdir(root) if n.startswith("_tmp.")]


def test_save_checkpoint_unwritable_raises_typed(devices, tmp_path):
    """Satellite: a persistently unwritable checkpoint root raises the
    TYPED ``CheckpointUnwritableError`` from ``save_checkpoint`` — the
    fail-fast signal the supervisor turns into a hard stop instead of a
    restart storm. The blocker here is a parent path that is a file
    (errno ENOTDIR), which fails even for root — chmod tricks do not."""
    blocker = tmp_path / "ckroot"
    blocker.write_text("not a directory")
    with pytest.raises(CheckpointUnwritableError, match="unwritable"):
        save_checkpoint(str(blocker / "ck"), _tree(1.0), step=0)
    # OSError so orbax/IO handlers see it, NOT RuntimeError so the
    # transient-retry wrappers (GuardedStep) can never swallow it
    assert issubclass(CheckpointUnwritableError, OSError)
    assert not issubclass(CheckpointUnwritableError, RuntimeError)


def test_make_checkpoint_unwritable_roundtrip(tmp_path):
    root = tmp_path / "ck"
    root.mkdir()
    make_checkpoint_unwritable(str(root))
    assert (os.stat(root).st_mode & 0o777) == 0o500
    restore_checkpoint_writable(str(root))
    assert (os.stat(root).st_mode & 0o777) == 0o700


def test_abort_before_commit_leaves_only_tmp(devices, tmp_path):
    """The mid-save crash seam: data written, commit never ran — readers
    must see NO checkpoint at all."""
    root = str(tmp_path / "ck")
    tmp = save_checkpoint(root, _tree(1.0), step=0, _abort_before_commit=True)
    assert os.path.basename(tmp).startswith("_tmp.")
    assert os.path.isdir(tmp)
    assert not os.path.isdir(os.path.join(root, "step_0"))
    assert latest_step_path(root) is None
    assert restore_latest(root, _tree(0.0)) is None


def test_torn_checkpoint_never_selected(devices, tmp_path):
    root = str(tmp_path / "ck")
    save_checkpoint(root, _tree(1.0), step=0)
    save_checkpoint(root, _tree(2.0), step=1)
    tear_checkpoint(os.path.join(root, "step_1"))
    assert latest_step_path(root) == os.path.join(
        os.path.abspath(root), "step_0"
    )
    restored = restore_latest(root, _tree(0.0))
    assert restored is not None
    state, step = restored
    assert step == 0
    np.testing.assert_array_equal(state["w"], _tree(1.0)["w"])


def test_bitflip_caught_by_checksums_with_fallback_event(devices, tmp_path):
    root = str(tmp_path / "ck")
    save_checkpoint(root, _tree(1.0), step=0)
    save_checkpoint(root, _tree(2.0), step=1)
    bitflip_checkpoint(os.path.join(root, "step_1"), seed=4)
    # still committed — only verification can tell
    assert latest_step_path(root) == os.path.join(
        os.path.abspath(root), "step_1"
    )
    telemetry, sink = _telemetry()
    restored = restore_latest(root, _tree(0.0), telemetry=telemetry, label="t")
    assert restored is not None
    state, step = restored
    assert step == 0
    np.testing.assert_array_equal(state["w"], _tree(1.0)["w"])
    fallbacks = [
        r for r in sink.records
        if r.get("event") == "failure" and r.get("kind") == "checkpoint_fallback"
    ]
    assert len(fallbacks) == 1
    assert "checksum mismatch" in fallbacks[0]["message"]


def test_manifest_catches_extra_and_missing_files(devices, tmp_path):
    root = str(tmp_path / "ck")
    final = save_checkpoint(root, _tree(1.0), step=0)
    with open(os.path.join(final, "smuggled.bin"), "wb") as f:
        f.write(b"x")
    ok, reason = verify_checkpoint(final)
    assert not ok and "unmanifested" in reason
    os.remove(os.path.join(final, "smuggled.bin"))
    with open(os.path.join(final, CHECKSUM_MANIFEST)) as f:
        victim = sorted(json.load(f))[0]
    os.remove(os.path.join(final, victim))
    ok, reason = verify_checkpoint(final)
    assert not ok and "missing file" in reason


def test_gc_keep_last(devices, tmp_path):
    root = str(tmp_path / "ck")
    for s in range(4):
        save_checkpoint(root, _tree(float(s)), step=s)
    # a foreign abandoned tmp dir gets collected too
    os.makedirs(os.path.join(root, "_tmp.step_9.99999"))
    deleted = gc_checkpoints(root, keep_last=2)
    kept = [s for s, _ in committed_step_paths(root)]
    assert kept == [3, 2]
    assert any("_tmp.step_9" in d for d in deleted)

    # keep_last threaded through save_checkpoint
    save_checkpoint(root, _tree(9.0), step=4, keep_last=2)
    assert [s for s, _ in committed_step_paths(root)] == [4, 3]
    with pytest.raises(ValueError):
        gc_checkpoints(root, keep_last=0)


def test_restore_latest_empty_root(devices, tmp_path):
    assert restore_latest(str(tmp_path / "nope"), _tree(0.0)) is None


# ---------------------------------------------------------------------------
# degraded-fabric survival: comm-layer faults, watchdogs, fallback ladder
# ---------------------------------------------------------------------------


def _info(phase="launch", chunk=0, n_chunks=1, payload=4096, device=0,
          tag="grads"):
    return {
        "tag": tag, "chunk": chunk, "n_chunks": n_chunks,
        "payload_bytes": payload, "phase": phase, "device_index": device,
    }


def test_comm_fault_registry_bijection():
    assert set(COMM_FAULTS) == {
        "comm_throttle", "comm_stall", "comm_flap", "comm_slow_edge",
        "comm_partition", "comm_heal",
    }
    for kind in COMM_FAULTS:
        assert kind in FAULT_KINDS
        assert INJECTION_SITES[kind] == "comm-hook"
        FaultSpec(kind=kind, step=0)  # accepted, not "unknown kind"
    # every kind has a site and every site names a kind — both directions
    check_fault_registry()
    assert set(INJECTION_SITES) == set(FAULT_KINDS)


def test_comm_fault_injector_throttle_lifecycle():
    plan = ChaosPlan([
        FaultSpec(kind="comm_throttle", step=1, payload={
            "bytes_per_s": 1e6, "max_sleep_s": 0.04, "duration_steps": 2,
        }),
    ])
    telemetry, sink = _telemetry()
    inj = CommFaultInjector(plan, rank=0, telemetry=telemetry)
    inj.advance(0)
    assert not inj.throttled
    inj.advance(1)
    assert inj.throttled
    assert "chaos_injected" in _kinds(sink)
    # wrong device / retire phase: filtered, no sleep
    import time as _t
    t0 = _t.monotonic()
    inj(_info(device=1))
    inj(_info(phase="retire"))
    assert _t.monotonic() - t0 < 0.02
    # matching launch: sleeps min(payload/rate, max_sleep) = the clamp
    t0 = _t.monotonic()
    inj(_info(payload=10_000_000))
    assert _t.monotonic() - t0 >= 0.03
    # expires at step 1 + duration_steps
    inj.advance(2)
    assert inj.throttled
    inj.advance(3)
    assert not inj.throttled
    assert "comm_fault_cleared" in _kinds(sink)


def test_comm_fault_injector_stall_fires_once():
    plan = ChaosPlan([
        FaultSpec(kind="comm_stall", step=0, payload={
            "stall_seconds": 0.05, "chunk": 1,
        }),
    ])
    inj = CommFaultInjector(plan, rank=0)
    inj.advance(0)
    assert inj.stall_pending
    import time as _t
    t0 = _t.monotonic()
    inj(_info(chunk=0))  # wrong chunk: no stall
    assert _t.monotonic() - t0 < 0.02
    t0 = _t.monotonic()
    inj(_info(chunk=1))
    assert _t.monotonic() - t0 >= 0.04
    assert not inj.stall_pending  # one collective hangs, ONCE
    t0 = _t.monotonic()
    inj(_info(chunk=1))
    assert _t.monotonic() - t0 < 0.02


def test_comm_flap_defaults_to_clearing():
    plan = ChaosPlan([FaultSpec(kind="comm_flap", step=2)])
    inj = CommFaultInjector(plan, rank=0)
    inj.advance(2)
    assert inj.throttled
    inj.advance(4)
    assert inj.throttled
    inj.advance(5)  # default clears_after=3
    assert not inj.throttled


def test_collective_watchdog_expiry_and_epoch_counters():
    import time as _t

    telemetry, sink = _telemetry()
    with CollectiveWatchdog(
        n_workers=8, slack=1.0, floor_s=0.05, escalate_after=2,
        telemetry=telemetry, rank=0, label="t",
    ) as wd:
        # clean window: launch then retire inside the budget
        wd.begin_attempt()
        wd(_info(phase="launch"))
        wd(_info(phase="retire"))
        assert not wd.expired_this_attempt
        wd.note_step(False)
        # blown window: the retire never comes before the deadline
        wd.begin_attempt()
        wd(_info(phase="launch", chunk=2, n_chunks=4))
        _t.sleep(0.15)
        assert wd.expired_this_attempt
        assert wd.fired and wd.fired[-1]["chunk"] == 2
        # hooks from other devices never arm rank 0's timer
        wd.begin_attempt()
        wd(_info(phase="launch", device=3))
        _t.sleep(0.08)
        assert not wd.expired_this_attempt
        # escalation streak: K consecutive degraded steps
        wd.note_step(True)
        assert not wd.should_escalate()
        wd.note_step(True)
        assert wd.should_escalate()
        counters = wd.take_epoch()
        assert counters == {"deadline_expiries": 1, "degraded_steps": 2}
        # epoch counters reset; the consecutive streak survives the epoch
        assert wd.take_epoch() == {"deadline_expiries": 0, "degraded_steps": 0}
        assert wd.should_escalate()
    deadline_events = [
        r for r in sink.records if r.get("kind") == "comm_deadline"
    ]
    assert len(deadline_events) == 1
    assert "grads[2/4]" in deadline_events[0]["label"]


class _ScriptedWatchdog:
    """CommDeadlineGuard contract double: expiry verdicts per attempt."""

    escalate_after = 3

    def __init__(self, verdicts):
        self._verdicts = list(verdicts)
        self._current = False
        self.noted = []

    def begin_attempt(self):
        self._current = self._verdicts.pop(0) if self._verdicts else False

    @property
    def expired_this_attempt(self):
        return self._current

    def note_step(self, degraded):
        self.noted.append(degraded)

    def should_escalate(self):
        return self.noted[-3:] == [True, True, True]


def test_comm_deadline_guard_retry_then_degrade():
    calls = []

    class Step:
        bits_per_step = 64

        def __call__(self, state, batch):
            calls.append(state)
            return state + 1, 0.5

    telemetry, sink = _telemetry()
    wd = _ScriptedWatchdog([False, True, False, True, True])
    guard = CommDeadlineGuard(Step(), wd, telemetry=telemetry, label="t")
    assert guard.bits_per_step == 64  # delegation
    # attempt 1 clean: one call, not degraded
    state, _ = guard(0, None)
    assert state == 1 and calls == [0]
    # attempt expired -> retried IN PLACE on the same inputs -> clean
    state, _ = guard(state, None)
    assert state == 2 and calls == [0, 1, 1]
    kinds = _kinds(sink)
    assert kinds.count("comm_step_retry") == 1
    assert "comm_degraded" not in kinds
    # expired twice: the (late but valid) state is kept, step marked degraded
    state, _ = guard(state, None)
    assert state == 3
    assert "comm_degraded" in _kinds(sink)
    assert wd.noted == [False, False, True]


def test_comm_deadline_guard_escalates_past_runtime_error_handlers():
    class Step:
        def __call__(self, state, batch):
            return state + 1, 0.5

    wd = _ScriptedWatchdog([True, True] * 6)  # every attempt expires
    guard = CommDeadlineGuard(Step(), wd)
    guard(0, None)
    guard(0, None)
    with pytest.raises(CommEscalationError):
        guard(0, None)
    # an escalation must pass through GuardedStep/retry_transient, which
    # catch RuntimeError — so it must not BE one
    assert not issubclass(CommEscalationError, RuntimeError)


def test_fence_hooks_preserve_bits_and_see_every_chunk(devices):
    from jax.sharding import PartitionSpec as P

    from network_distributed_pytorch_tpu.parallel import DATA_AXIS
    from network_distributed_pytorch_tpu.parallel import comm
    from network_distributed_pytorch_tpu.parallel.comm import (
        chunked_all_reduce_mean,
    )

    mesh = make_mesh()
    flat = jax.random.normal(jax.random.PRNGKey(0), (8, 531))

    def run(k):
        def body(xs):
            return chunked_all_reduce_mean(xs[0], DATA_AXIS, k, tag="t")[None]

        return jax.jit(
            jax.shard_map(
                body, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS)
            )
        )(flat)

    baseline = np.asarray(run(3))
    seen = []
    comm.add_fence_hook(seen.append)
    try:
        assert comm.fence_hooks_active()
        hooked = np.asarray(run(3))
    finally:
        comm.remove_fence_hook(seen.append)
    assert not comm.fence_hooks_active()
    # the callback is outside the math: bitwise identical results
    np.testing.assert_array_equal(
        baseline.view(np.uint32), hooked.view(np.uint32)
    )
    mine = [i for i in seen if i["device_index"] == 0]
    launches = [i for i in mine if i["phase"] == "launch"]
    retires = [i for i in mine if i["phase"] == "retire"]
    # 3 chunk launches + the final retire, once per logical collective
    assert [i["chunk"] for i in launches] == [0, 1, 2]
    assert len(retires) == 1
    itemsize = np.dtype(np.float32).itemsize
    assert sum(i["payload_bytes"] for i in launches) == 531 * itemsize
    assert retires[0]["payload_bytes"] == 531 * itemsize
    assert all(i["tag"] == "t" and i["n_chunks"] == 3 for i in launches)


# -- the e2e matrix: fault -> watchdog/controller -> documented recovery ----


def _adaptive_setup():
    model = SmallCNN(width=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *IMG)))["params"]

    def lf(p, b):
        x, y = b
        return cross_entropy_loss(model.apply({"params": p}, x), y)

    mesh = make_mesh()

    def step_factory(overrides):
        if overrides.get("reducer") == "powersgd":
            reducer = PowerSGDReducer(
                random_seed=7,
                compression_rank=overrides.get("reducer_rank", 2),
                matricize="last",
                comm_chunks=overrides.get("comm_chunks"),
                comm_strategy=overrides.get("comm_strategy", "interleave"),
            )
        else:
            from network_distributed_pytorch_tpu.parallel import ExactReducer

            reducer = ExactReducer(
                comm_chunks=overrides.get("comm_chunks"),
                comm_strategy=overrides.get("comm_strategy", "interleave"),
            )
        return make_train_step(
            stateless_loss(lf), reducer, params, learning_rate=0.05,
            momentum=0.9, algorithm="ef_momentum", mesh=mesh,
            donate_state=False,
        )

    return step_factory, params


def _policy_records(sink):
    return [r for r in sink.records if r.get("event") == "policy"]


def _step_losses(sink):
    return [r["loss"] for r in sink.records if r.get("event") == "step"]


def _bits_deltas(sink):
    bits = [
        r["bits_cumulative"] for r in sink.records
        if r.get("event") == "step" and "bits_cumulative" in r
    ]
    return [b - a for a, b in zip(bits, bits[1:])]


@pytest.mark.slow
def test_comm_throttle_walks_ladder_down_and_back(devices):
    """The tentpole e2e: a mid-run throttle degrades achieved bandwidth ->
    the controller descends to the compressed rung (reducer actually
    switched, wire bytes/step measurably reduced per the ledger) with a
    typed PolicyEvent; the fault clears -> the ladder walks back up; the
    loss stays finite and nothing restarts."""
    from network_distributed_pytorch_tpu.experiments.common import (
        adaptive_train_loop,
    )

    step_factory, params = _adaptive_setup()
    telemetry, sink = _telemetry()
    plan = ChaosPlan([
        FaultSpec(kind="comm_throttle", step=6, payload={
            "bytes_per_s": 2e4, "max_sleep_s": 0.15, "duration_steps": 6,
        }),
    ])
    injector = CommFaultInjector(plan, rank=0, telemetry=telemetry)
    controller = FallbackController(
        ladder=[
            Rung("exact", {}),
            Rung("powersgd", {"reducer": "powersgd", "reducer_rank": 2}),
        ],
        descend_after=1, recover_after=2, telemetry=telemetry,
    )
    state, logger, controller = adaptive_train_loop(
        step_factory, params, None, _batches, 10, controller,
        injector=injector, telemetry=telemetry,
        # the throttle's per-chunk sleep (0.15s) must degrade bandwidth
        # WITHOUT tripping the deadline watchdog — that's the stall test
        deadline_floor_s=0.5,
    )
    policies = _policy_records(sink)
    descents = [p for p in policies if p["action"] == "descend"]
    ascents = [p for p in policies if p["action"] == "ascend"]
    assert descents and ascents
    assert descents[0]["rung_after"] == "powersgd"
    assert "achieved_bytes_per_s" in descents[0]["trigger"]
    # the descent's byte claim: the compressed rung sheds real ledger bytes
    assert (
        descents[0]["predicted_bytes_per_step"]
        < descents[0]["realized_bytes_per_step"]
    )
    # ...and the ledger the logger charged agrees: compressed steps cost
    # measurably fewer wire bits than exact steps
    deltas = set(_bits_deltas(sink))
    assert len(deltas) == 2 and min(deltas) < max(deltas)
    kinds = _kinds(sink)
    assert "chaos_injected" in kinds
    assert "comm_fault_cleared" in kinds
    assert "worker_restart" not in kinds  # recovery happened in-place
    assert controller.index == 0  # recovered all the way back to exact
    losses = _step_losses(sink)
    assert losses and np.isfinite(losses).all()
    assert all(
        np.isfinite(np.asarray(l)).all()
        for l in jax.tree_util.tree_leaves(state.params)
    )


@pytest.mark.slow
def test_comm_flap_recovers_in_place_without_escalation(devices):
    """A transient flap throttles a few steps then self-clears; the run
    absorbs it with no deadline expiry, no escalation, no restart — the
    flap lifecycle is visible as injected -> cleared telemetry."""
    from network_distributed_pytorch_tpu.experiments.common import (
        adaptive_train_loop,
    )

    step_factory, params = _adaptive_setup()
    telemetry, sink = _telemetry()
    plan = ChaosPlan([
        FaultSpec(kind="comm_flap", step=4, payload={
            "bytes_per_s": 2e4, "max_sleep_s": 0.1, "clears_after": 3,
        }),
    ])
    injector = CommFaultInjector(plan, rank=0, telemetry=telemetry)
    controller = FallbackController(
        ladder=[Rung("exact", {})], telemetry=telemetry,
    )
    state, logger, _ = adaptive_train_loop(
        step_factory, params, None, _batches, 4, controller,
        injector=injector, telemetry=telemetry, deadline_floor_s=0.5,
    )
    kinds = _kinds(sink)
    assert "chaos_injected" in kinds
    assert "comm_fault_cleared" in kinds
    assert "comm_deadline" not in kinds  # under the deadline floor
    assert "worker_restart" not in kinds
    losses = _step_losses(sink)
    assert len(losses) == 12  # every step of every epoch completed
    assert np.isfinite(losses).all()


@pytest.mark.slow
def test_comm_stall_trips_deadline_step_retried_ledger_unchanged(devices):
    """One collective hangs past its deadline: the watchdog fires
    ``comm_deadline``, the guard retries the step in place (the stall is
    once-only, so the retry is clean), no escalation — and the wire ledger
    is bit-identical to a clean run's, because injection lives in a host
    callback, not in the graph."""
    from network_distributed_pytorch_tpu.experiments.common import (
        adaptive_train_loop,
    )

    step_factory, params = _adaptive_setup()
    telemetry, sink = _telemetry()
    plan = ChaosPlan([
        FaultSpec(kind="comm_stall", step=4, payload={
            "stall_seconds": 1.0, "chunk": 0,
        }),
    ])
    injector = CommFaultInjector(plan, rank=0, telemetry=telemetry)
    # single-rung ladder: the stalled epoch may NOT descend anywhere, so
    # every step must charge the exact reducer's ledger
    controller = FallbackController(
        ladder=[Rung("exact", {})], telemetry=telemetry,
    )
    state, logger, _ = adaptive_train_loop(
        step_factory, params, None, _batches, 3, controller,
        injector=injector, telemetry=telemetry,
        deadline_floor_s=0.2, deadline_slack=1.0, escalate_after=3,
    )
    kinds = _kinds(sink)
    assert "comm_deadline" in kinds
    assert "comm_step_retry" in kinds
    # the once-only stall clears on the retry: degraded never accumulates
    assert not any(k == "comm_degraded" for k in kinds)
    losses = _step_losses(sink)
    assert len(losses) == 9 and np.isfinite(losses).all()
    # ledger invariance: every step charged the same exact-reducer bits
    assert len(set(_bits_deltas(sink))) == 1
