"""Expert-parallel Switch-MoE GPT: end-to-end training with all-to-all
token dispatch, compressed-DP composition, and routing diagnostics."""

import numpy as np


def test_gpt_moe_trains_exact(devices):
    from network_distributed_pytorch_tpu.experiments import gpt_moe

    out = gpt_moe.run(steps_per_epoch=8, reducer="exact")
    assert out["final_loss"] < out["first_loss"] * 0.9, out
    assert out["n_experts"] == 8
    # token dispatch is physical: all_to_all hops in the compiled step
    assert out["hlo_collectives"].get("all-to-all", 0) >= 2
    assert 0.0 <= out["final_dropped_fraction"] < 1.0
    assert np.isfinite(out["final_aux_loss"])


def test_gpt_moe_powersgd_multi_expert(devices):
    """Compressed DP on the replicated params composed with 2 experts per
    device (16 routed experts)."""
    from network_distributed_pytorch_tpu.experiments import gpt_moe

    out = gpt_moe.run(
        steps_per_epoch=8, reducer="powersgd", experts_per_device=2
    )
    assert out["final_loss"] < out["first_loss"] * 0.95, out
    assert out["n_experts"] == 16
    assert out["reducer"] == "powersgd"

