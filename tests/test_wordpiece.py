"""First-party WordPiece vs HF ``DistilBertTokenizerFast`` — token-for-token
parity on a shared ``vocab.txt`` (round-2 verdict #5: with this proven, real
``distilbert-base-uncased`` tokenization needs only the vocab file on disk,
no ``transformers`` at runtime). The HF fast tokenizer is constructed from
the SAME local vocab file (no download), configured exactly as the reference
uses it (``ddp_powersgd_distillBERT_IMDb/ddp_init.py:74-77``: uncased,
truncation+padding)."""

import numpy as np
import pytest

from network_distributed_pytorch_tpu.data import WordPieceTokenizer, prepare_imdb
from network_distributed_pytorch_tpu.data.wordpiece import load_vocab

transformers = pytest.importorskip("transformers")

# [PAD]/[UNK]/[CLS]/[SEP]/[MASK] first (ids 0-4), then whole words and
# ##-continuations exercising every matcher path: multi-piece words, greedy
# longest-match ties, punctuation, digits, accent-folded forms, CJK.
VOCAB = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
    "the", "movie", "was", "great", "terrible", "un", "##believ", "##able",
    "##believable", "unbeliev", "act", "##ing", "!", ",", ".", "?", "'",
    "##s", "it", "good", "bad", "really", "re", "##ally", "café", "cafe",
    "##fe", "ca", "2", "##0", "##2", "##4", "in", "20", "##24", "watch",
    "##ed", "watched", "-", "co", "##-", "##op", "电", "影", "a", "an",
    "##n", "hyphen", "##ated",
]

TEXTS = [
    "The movie was great!",
    "Unbelievable acting, really.",
    "It was TERRIBLE?",
    "café cafe CAFÉ",                      # accent stripping + casing
    "watched in 2024",                     # digit pieces
    "co-op hyphenated-words, it's good",   # punctuation splitting
    "电影 was good",                        # CJK spacing
    "zzzzqqqq unknownword the",            # whole-word [UNK]
    "",                                    # empty text → [CLS] [SEP] only
    "the " * 300,                          # truncation past max_len
]


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    p = tmp_path_factory.mktemp("wp") / "vocab.txt"
    p.write_text("\n".join(VOCAB) + "\n", encoding="utf-8")
    return str(p)


@pytest.fixture(scope="module")
def hf_tok(vocab_file):
    return transformers.DistilBertTokenizerFast(
        vocab_file=vocab_file, do_lower_case=True
    )


def test_vocab_roundtrip(vocab_file):
    vocab = load_vocab(vocab_file)
    assert vocab["[PAD]"] == 0 and vocab["[CLS]"] == 2
    assert len(vocab) == len(VOCAB)


@pytest.mark.parametrize("max_len", [16, 64])
def test_parity_with_hf_fast(vocab_file, hf_tok, max_len):
    ours = WordPieceTokenizer(vocab_file, max_len=max_len)
    enc = ours(TEXTS)
    ref = hf_tok(
        TEXTS, truncation=True, padding="max_length", max_length=max_len
    )
    np.testing.assert_array_equal(
        enc["input_ids"], np.asarray(ref["input_ids"], np.int32)
    )
    np.testing.assert_array_equal(
        enc["attention_mask"], np.asarray(ref["attention_mask"], np.int32)
    )


def test_piece_level_parity(vocab_file, hf_tok):
    ours = WordPieceTokenizer(vocab_file)
    for text in TEXTS:
        assert ours.tokenize(text) == hf_tok.tokenize(text), text


def test_greedy_longest_match(vocab_file):
    tok = WordPieceTokenizer(vocab_file)
    # "unbelievable" must take the LONGEST first piece ("unbeliev", not "un")
    assert tok.wordpiece("unbelievable") == ["unbeliev", "##able"]
    # single char falls through to [UNK] when absent
    assert tok.wordpiece("q") == ["[UNK]"]
    assert tok.wordpiece("x" * 200) == ["[UNK]"]  # over the 100-char cap


def test_static_shapes_and_specials(vocab_file):
    tok = WordPieceTokenizer(vocab_file, max_len=8)
    enc = tok(["", "the movie was great ! ! ! ! ! !"])
    assert enc["input_ids"].shape == (2, 8)
    assert enc["input_ids"][0, 0] == tok.cls_id
    assert enc["input_ids"][0, 1] == tok.sep_id
    assert enc["input_ids"][0, 2] == tok.pad_id
    assert enc["input_ids"][1, -1] == tok.sep_id  # truncated row still ends [SEP]
    assert enc["attention_mask"].sum() == 2 + 8


def test_prepare_imdb_picks_up_vocab_txt(tmp_path):
    """A vocab.txt beside the dataset dir selects WordPiece as the default
    tokenizer (the drop-files-on-disk parity path, data/imdb.py)."""
    (tmp_path / "vocab.txt").write_text("\n".join(VOCAB) + "\n", encoding="utf-8")
    train, val, is_real = prepare_imdb(
        data_dir=str(tmp_path), max_len=32, synthetic_n=8
    )
    assert not is_real  # synthetic texts (no train/ dir) but real WordPiece ids
    assert train["input_ids"].shape[1] == 32
    # every row starts with [CLS]=2 — proves the WordPiece path was taken
    assert (train["input_ids"][:, 0] == 2).all()
