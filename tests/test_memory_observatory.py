"""Memory observatory: footprint shim, sampler, headroom, OOM, gate.

Unit coverage for the device-memory plane (``observe/memory.py`` and its
shims): the ``compiled_memory`` normalization across the result shapes
different jaxlibs return (attrs / dict / list / raising), the
MemorySampler's one-way CPU no-op (probe once, disable forever, zero log
lines), the EWMA headroom detector's warn/critical ladder and its
silent-drop of limitless samples, the live plane's memory gauges, the
guarded step's OOM trap (detect by message, never retry, ranked
post-mortem on disk), the chaos ``oom`` fault, the report's
always-present ``memory`` section with its labeled ``hbm_peak_bytes``
gate scalar, and ``gate.py``'s lower-is-better regression +
device-provenance verdicts. Everything here is CPU-only; the fake
"devices" are plain objects with a ``memory_stats`` method.
"""

import importlib.util
import json
import os
import sys

import pytest

from network_distributed_pytorch_tpu._jax_compat import compiled_memory
from network_distributed_pytorch_tpu.observe import MemoryEvent, Telemetry
from network_distributed_pytorch_tpu.observe.health import (
    DetectorConfig,
    HealthMonitor,
)
from network_distributed_pytorch_tpu.observe.live import (
    MetricRegistry,
    ingest_record,
)
from network_distributed_pytorch_tpu.observe.memory import (
    MemorySampler,
    build_oom_report,
    device_memory_stats,
    memory_footprint_fields,
    tree_bytes,
    write_oom_report,
)
from network_distributed_pytorch_tpu.resilience import (
    MEMORY_FAULTS,
    ChaosOutOfMemoryError,
    ChaosPlan,
    ChaosStep,
    FaultSpec,
    GuardedStep,
    OutOfMemoryError,
    is_oom_error,
)
from network_distributed_pytorch_tpu.resilience.chaos import INJECTION_SITES

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS_DIR)


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        f"_memtest_{name}", os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[f"_memtest_{name}"] = mod
    spec.loader.exec_module(mod)
    return mod


class _Sink:
    def __init__(self):
        self.events = []

    def emit(self, event, record):
        self.events.append(event)

    def close(self):
        pass


def _telemetry():
    sink = _Sink()
    return Telemetry(sinks=[sink]), sink


# ---------------------------------------------------------------------------
# compiled_memory: one shim over every result shape jaxlib has shipped
# ---------------------------------------------------------------------------


class _AttrsAnalysis:
    argument_size_in_bytes = 100
    output_size_in_bytes = 20
    temp_size_in_bytes = 50
    generated_code_size_in_bytes = 5


def _compiled(result):
    class _Compiled:
        def memory_analysis(self):
            if isinstance(result, Exception):
                raise result
            return result

    return _Compiled()


def test_compiled_memory_attrs_shape():
    out = compiled_memory(_compiled(_AttrsAnalysis()))
    assert out == {
        "argument_bytes": 100.0,
        "output_bytes": 20.0,
        "temp_bytes": 50.0,
        "generated_code_bytes": 5.0,
    }


def test_compiled_memory_dict_shapes_with_and_without_suffix():
    long = compiled_memory(
        _compiled({"argument_size_in_bytes": 7, "temp_size_in_bytes": 3})
    )
    short = compiled_memory(_compiled({"argument_bytes": 7, "temp_bytes": 3}))
    assert long == short == {"argument_bytes": 7.0, "temp_bytes": 3.0}


def test_compiled_memory_list_shape_takes_first_element():
    out = compiled_memory(_compiled([_AttrsAnalysis(), _AttrsAnalysis()]))
    assert out["argument_bytes"] == 100.0
    assert compiled_memory(_compiled([])) is None


def test_compiled_memory_raising_backend_is_none_not_crash():
    assert compiled_memory(_compiled(RuntimeError("no stats here"))) is None
    assert compiled_memory(object()) is None  # no memory_analysis at all
    # numeric garbage / unknown keys yield None, not a partial dict
    assert compiled_memory(_compiled({"argument_bytes": "big"})) is None
    assert compiled_memory(_compiled({"unrelated": 1.0})) is None


def test_footprint_fields_sum_to_peak_and_splat_safely():
    fields = memory_footprint_fields(_compiled(_AttrsAnalysis()))
    assert fields["peak_hbm_bytes"] == 175.0
    assert set(fields) == {
        "argument_bytes", "output_bytes", "temp_bytes",
        "generated_code_bytes", "peak_hbm_bytes",
    }
    # degraded backends give {} (never None) so callers can always **
    assert memory_footprint_fields(None) == {}
    assert memory_footprint_fields(_compiled(RuntimeError("x"))) == {}


def test_real_compiled_step_footprint_matches_shim():
    """On a real jitted function the ledger-facing helper and the raw shim
    must agree — and on backends that do report, the split sums to the
    published peak."""
    import jax
    import jax.numpy as jnp

    compiled = jax.jit(lambda x: (x * 2.0).sum()).lower(
        jnp.zeros((8, 8), jnp.float32)
    ).compile()
    fields = memory_footprint_fields(compiled)
    raw = compiled_memory(compiled)
    if raw is None:
        assert fields == {}
    else:
        assert fields["peak_hbm_bytes"] == sum(
            v for k, v in fields.items() if k != "peak_hbm_bytes"
        )


# ---------------------------------------------------------------------------
# live sampler: emits typed events; CPU degrades to a one-way no-op
# ---------------------------------------------------------------------------


class _FakeDevice:
    device_kind = "fake-hbm"

    def __init__(self, stats):
        self.stats = stats
        self.calls = 0

    def memory_stats(self):
        self.calls += 1
        if isinstance(self.stats, Exception):
            raise self.stats
        return self.stats


def test_sampler_emits_memory_events():
    telemetry, sink = _telemetry()
    dev = _FakeDevice(
        {"bytes_in_use": 10.0, "peak_bytes_in_use": 12.0,
         "bytes_limit": 100.0}
    )
    sampler = MemorySampler(telemetry, label="t", rank=3, device=dev)
    event = sampler.sample(5)
    assert isinstance(event, MemoryEvent)
    assert sampler.enabled and sampler.last is event
    rec = sink.events[-1].record()
    assert rec["event"] == "memory"
    assert rec["bytes_in_use"] == 10.0
    assert rec["bytes_limit"] == 100.0
    assert rec["rank"] == 3 and rec["device_kind"] == "fake-hbm"


@pytest.mark.parametrize(
    "stats", [None, {}, NotImplementedError("no stats"), {"other": 1}]
)
def test_sampler_statless_backend_is_one_way_noop(stats):
    """The CPU contract: probe exactly once, disable forever, emit nothing
    — no per-step spam from a backend that will never answer."""
    telemetry, sink = _telemetry()
    dev = _FakeDevice(stats)
    sampler = MemorySampler(telemetry, device=dev)
    assert sampler.sample(0) is None
    assert not sampler.enabled and dev.calls == 1
    for step in range(1, 4):
        assert sampler.sample(step) is None
    assert dev.calls == 1  # never probed again
    assert sink.events == []


def test_device_memory_stats_normalizes_and_filters():
    stats = device_memory_stats(
        _FakeDevice({"bytes_in_use": 5, "bytes_limit": "lots", "junk": 1})
    )
    assert stats == {"bytes_in_use": 5.0}
    assert device_memory_stats(_FakeDevice(RuntimeError("x"))) is None


def test_tree_bytes_counts_array_leaves_only():
    import numpy as np

    tree = {"a": np.zeros((4, 4), np.float32), "b": [np.zeros(2, np.int8)],
            "c": "not an array", "d": None}
    assert tree_bytes(tree) == 4 * 4 * 4 + 2
    assert tree_bytes(None) == 0


# ---------------------------------------------------------------------------
# headroom detector: the OOM precursor
# ---------------------------------------------------------------------------


def test_headroom_ladder_warn_then_critical():
    monitor = HealthMonitor(DetectorConfig(cooldown=0))
    limit = 100.0
    fired = []
    # ramp the occupancy: the EWMA crosses warn well before critical
    for step, frac in enumerate([0.5, 0.7, 0.9, 0.97] + [0.97] * 20):
        fired += monitor.observe_hbm(frac * limit, limit, rank=0, step=step)
    kinds = [(a.alert, a.severity) for a in fired]
    assert ("hbm_headroom", "warn") in kinds
    assert ("hbm_headroom", "critical") in kinds
    assert kinds.index(("hbm_headroom", "warn")) < kinds.index(
        ("hbm_headroom", "critical")
    )


def test_headroom_limitless_samples_dropped_silently():
    """CPU backends report no limit; a fake occupancy of in_use/0 must
    never teach the detector anything."""
    monitor = HealthMonitor(DetectorConfig())
    for limit in (0.0, -1.0, None, float("nan")):
        assert monitor.observe_hbm(50.0, limit, rank=0, step=0) == []


def test_headroom_is_per_rank():
    monitor = HealthMonitor(DetectorConfig(cooldown=0))
    fired = []
    for step in range(8):
        fired += monitor.observe_hbm(97.0, 100.0, rank=1, step=step)
        fired += monitor.observe_hbm(10.0, 100.0, rank=0, step=step)
    assert fired and {a.rank for a in fired} == {1}


def test_live_ingest_memory_gauges():
    reg = MetricRegistry()
    ingest_record(
        reg,
        {"event": "memory", "bytes_in_use": 80.0, "peak_bytes_in_use": 90.0,
         "bytes_limit": 100.0, "rank": 2},
    )
    assert reg.get_gauge("live_hbm_bytes", rank="2") == 80.0
    assert reg.get_gauge("live_hbm_peak_bytes", rank="2") == 90.0
    assert reg.get_gauge("live_hbm_limit_bytes", rank="2") == 100.0


# ---------------------------------------------------------------------------
# OOM forensics: detection, report, the guarded step's trap
# ---------------------------------------------------------------------------


def test_is_oom_error_matches_allocator_messages_only():
    assert is_oom_error(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating 1073741824 bytes"
    ))
    assert is_oom_error(ValueError("xla: Out of memory while running"))
    assert not is_oom_error(RuntimeError("collective timed out"))


def test_oom_error_is_not_a_runtimeerror():
    """The class trick that keeps retry_transient(exceptions=(RuntimeError,))
    from replaying a deterministic OOM (CheckpointUnwritableError
    precedent)."""
    assert not issubclass(OutOfMemoryError, RuntimeError)


def test_build_oom_report_ranks_buffers_and_names_top():
    report = build_oom_report(
        error="E" * 5000, label="t", rank=1, step=7,
        last_memory={"bytes_in_use": 9.0},
        footprint={"temp_bytes": 4.0},
        buffers={"params": 10.0, "ef_memory": 30.0, "bad": float("-1"),
                 "skipped": None},
    )
    assert report["top_buffer"] == "ef_memory"
    names = [b["name"] for b in report["buffers"]]
    assert names == ["ef_memory", "params"]  # desc, negatives/None dropped
    assert len(report["error"]) == 2000  # clipped
    assert report["last_memory"] == {"bytes_in_use": 9.0}
    assert report["step"] == 7


def test_write_oom_report_creates_parent_atomically(tmp_path):
    path = str(tmp_path / "deep" / "oom_report.json")
    out = write_oom_report(build_oom_report(error="x"), path)
    assert out == path
    with open(path) as f:
        assert json.load(f)["kind"] == "oom"
    assert not os.path.exists(path + ".tmp")


def test_guarded_step_traps_oom_never_retries(tmp_path):
    calls = {"n": 0}

    def inner(state, batch):
        calls["n"] += 1
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 1048576 bytes"
        )

    telemetry, sink = _telemetry()
    oom_path = str(tmp_path / "artifacts" / "oom_report.json")
    guard = GuardedStep(
        inner, retries=5, backoff_seconds=0.0, telemetry=telemetry,
        label="train", rank=2,
        footprint={"temp_bytes": 4.0, "peak_hbm_bytes": 4.0},
        buffers_fn=lambda: {"params": 100.0, "activations": 25.0},
        oom_report_path=oom_path,
    )
    with pytest.raises(OutOfMemoryError) as err:
        guard(None, None)
    assert calls["n"] == 1  # an OOM is deterministic: no retry, ever
    assert "forensics" in str(err.value)
    with open(oom_path) as f:
        report = json.load(f)
    assert report["top_buffer"] == "params"
    assert report["rank"] == 2 and report["step"] == 0
    assert report["footprint"]["peak_hbm_bytes"] == 4.0
    assert "RESOURCE_EXHAUSTED" in report["error"]
    failures = [
        e.record() for e in sink.events
        if e.record().get("event") == "failure"
    ]
    assert any(
        f["kind"] == "oom" and "params" in f["message"] for f in failures
    )


def test_guarded_step_oom_minimal_without_hooks(tmp_path):
    """No sampler / footprint / buffers_fn: the guard still detects the
    OOM and writes a (sparse) post-mortem instead of crashing on None."""

    def inner(state, batch):
        raise RuntimeError("Out of memory")

    path = str(tmp_path / "oom.json")
    guard = GuardedStep(inner, retries=1, oom_report_path=path)
    with pytest.raises(OutOfMemoryError):
        guard(None, None)
    with open(path) as f:
        report = json.load(f)
    assert report["top_buffer"] is None
    assert report["buffers"] == [] and report["footprint"] is None


def test_guarded_step_still_retries_transient_runtimeerrors():
    calls = {"n": 0}

    def inner(state, batch):
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("transient fabric hiccup")
        return None, 0.5

    guard = GuardedStep(inner, retries=3, backoff_seconds=0.0)
    assert guard(None, None) == (None, 0.5)
    assert calls["n"] == 3


# ---------------------------------------------------------------------------
# chaos: the injectable allocator death
# ---------------------------------------------------------------------------


def test_oom_fault_registered_as_step_site_memory_group():
    assert MEMORY_FAULTS == ("oom",)
    assert INJECTION_SITES["oom"] == "step"


def test_chaos_step_injects_allocator_shaped_oom():
    step = ChaosStep(
        lambda *a: 0.0,
        ChaosPlan([FaultSpec(kind="oom", step=1, rank=0,
                             payload={"bytes": 2048})]),
        rank=0,
    )
    assert step(None, None) == 0.0  # step 0: clean
    with pytest.raises(ChaosOutOfMemoryError) as err:
        step(None, None)
    # injected == real to every layer above: a RuntimeError whose message
    # carries the allocator marker, so the guard's trap treats it the same
    assert isinstance(err.value, RuntimeError)
    assert is_oom_error(err.value)
    assert "2048" in str(err.value)
    assert step(None, None) == 0.0  # fires exactly once


def test_chaos_oom_through_guarded_step(tmp_path):
    """The game-day wiring in miniature: ChaosStep inside GuardedStep —
    the injected fault surfaces as OutOfMemoryError with forensics, not
    as a retried transient."""
    inner = ChaosStep(
        lambda *a: (None, 0.1),
        ChaosPlan([FaultSpec(kind="oom", step=0, rank=0)]),
        rank=0,
    )
    path = str(tmp_path / "oom.json")
    guard = GuardedStep(inner, retries=4, backoff_seconds=0.0,
                        oom_report_path=path)
    with pytest.raises(OutOfMemoryError):
        guard(None, None)
    assert os.path.exists(path)


# ---------------------------------------------------------------------------
# report: the always-present memory section
# ---------------------------------------------------------------------------


def test_memory_summary_cpu_graceful_predicted_only():
    report = _load_script("report")
    out = report.memory_summary(
        [{"event": "compile", "argument_bytes": 10.0, "temp_bytes": 5.0,
          "peak_hbm_bytes": 15.0}],
        [],
    )
    assert out["measured_available"] is False and out["measured"] is None
    assert out["hbm_peak_bytes"] == 15.0
    assert out["hbm_peak_source"] == "predicted"
    # ...and even with NOTHING the section exists (never vanishes)
    empty = report.memory_summary([], [])
    assert empty == {
        "predicted": None, "measured": None, "measured_available": False,
        "hbm_peak_bytes": None, "hbm_peak_source": None,
    }
    assert report.render_memory_section(empty)  # renders, says unavailable


def test_memory_summary_measured_peak_wins_across_ranks():
    report = _load_script("report")
    out = report.memory_summary(
        [{"event": "compile", "peak_hbm_bytes": 15.0}],
        [
            {"event": "memory", "rank": 0, "bytes_in_use": 40.0,
             "peak_bytes_in_use": 50.0, "bytes_limit": 100.0},
            {"event": "memory", "rank": 1, "bytes_in_use": 70.0,
             "peak_bytes_in_use": 80.0, "bytes_limit": 100.0,
             "device_kind": "toy"},
        ],
    )
    assert out["hbm_peak_source"] == "measured"
    assert out["hbm_peak_bytes"] == 80.0  # max across ranks, not sum
    assert out["measured"]["headroom_frac"] == pytest.approx(0.2)
    assert out["measured"]["per_rank"]["1"]["device_kind"] == "toy"


def test_chrome_trace_memory_counter_track():
    report = _load_script("report")
    doc = report.chrome_trace([
        {"event": "step", "rank": 0, "step": 0, "step_time_s": 0.01,
         "t_run": 1.0},
        {"event": "memory", "rank": 0, "step": 0, "bytes_in_use": 42.0,
         "bytes_limit": 100.0, "t_run": 1.01},
    ])
    counters = [
        e for e in doc["traceEvents"]
        if e.get("ph") == "C" and e.get("cat") == "memory"
    ]
    assert len(counters) == 1
    c = counters[0]
    assert c["name"] == "HBM bytes" and c["pid"] == 0
    assert c["args"]["bytes_in_use"] == 42.0


# ---------------------------------------------------------------------------
# gate: lower-is-better hbm_peak_bytes + device provenance
# ---------------------------------------------------------------------------


def test_gate_extracts_hbm_peak_nested_and_flat():
    gate = _load_script("gate")
    nested = gate.extract_metrics({"memory": {"hbm_peak_bytes": 5.0}})
    flat = gate.extract_metrics({"hbm_peak_bytes": 5.0})
    assert nested["hbm_peak_bytes"] == flat["hbm_peak_bytes"] == 5.0
    # a degraded section (None / 0) contributes nothing
    assert "hbm_peak_bytes" not in gate.extract_metrics(
        {"memory": {"hbm_peak_bytes": None}}
    )


def test_gate_fails_doubled_footprint():
    gate = _load_script("gate")
    verdicts = gate.compare(
        {"hbm_peak_bytes": 2e9}, {"hbm_peak_bytes": 1e9}, tolerance=0.2
    )
    (v,) = verdicts
    assert v["metric"] == "hbm_peak_bytes"
    assert v["regressed"] and v["ratio"] == pytest.approx(2.0)
    # shrinking the footprint is an improvement, not a regression
    ok = gate.compare(
        {"hbm_peak_bytes": 5e8}, {"hbm_peak_bytes": 1e9}, tolerance=0.2
    )
    assert not ok[0]["regressed"]


def test_gate_device_mismatch_advisory_vs_strict():
    gate = _load_script("gate")
    report = {"platform": "cpu"}
    baseline = {"platform": "tpu"}
    (advisory,) = gate.device_mismatch_verdict(report, baseline, strict=False)
    assert advisory["device_mismatch"] and not advisory["regressed"]
    (strict,) = gate.device_mismatch_verdict(report, baseline, strict=True)
    assert strict["regressed"]
    # matching or unattested sides stay silent — no noise verdicts
    assert gate.device_mismatch_verdict(
        {"platform": "TPU "}, {"platform": "tpu"}, strict=True
    ) == []
    assert gate.device_mismatch_verdict({}, baseline, strict=True) == []


def test_gate_platform_falls_back_to_mfu_device_kind():
    gate = _load_script("gate")
    assert gate._platform_of(
        {"mfu": [{"device_kind": "TPU v5e"}]}
    ) == "tpu v5e"
    assert gate._platform_of({"platform": "cpu", "mfu": []}) == "cpu"
    assert gate._platform_of({}) is None


def test_gate_main_device_mismatch_exit_codes(tmp_path):
    gate = _load_script("gate")
    rep = str(tmp_path / "r.json")
    base = str(tmp_path / "b.json")
    with open(rep, "w") as f:
        json.dump({"memory": {"hbm_peak_bytes": 1e9}, "platform": "cpu"}, f)
    with open(base, "w") as f:
        json.dump({"hbm_peak_bytes": 1e9, "platform": "tpu"}, f)
    assert gate.main(["--report", rep, "--baseline", base]) == 0
    assert gate.main(
        ["--report", rep, "--baseline", base, "--strict-device"]
    ) == 1
