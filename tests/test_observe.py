"""The observe subsystem: typed events, sinks, the telemetry registry, the
wire ledger, the metrics logger's event emission, and scripts/report.py.

Most tests here are jax-free on purpose — the bench parent orchestrator
imports observe before (and without) any jax backend init, and the one
subprocess test pins that property.
"""

import io
import json
import os
import subprocess
import sys

import pytest

from network_distributed_pytorch_tpu.observe import (
    CollectiveEvent,
    CompileEvent,
    EpochEvent,
    FailureEvent,
    JsonlSink,
    LedgerEntry,
    MemorySink,
    NoteEvent,
    RawEvent,
    StdoutSink,
    StepEvent,
    StreamJsonSink,
    Telemetry,
    WireLedger,
    audit_from_config,
    telemetry_for_run,
)
from network_distributed_pytorch_tpu.observe.ledger import (
    ledger_from_hlo_summary,
    loss_sync_entry,
    step_ledger,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# events
# ---------------------------------------------------------------------------


def test_step_event_record_excludes_presentation_fields():
    ev = StepEvent(
        step=3, epoch=0, loss=1.5, step_time_s=0.25, bits_cumulative=800,
        valid=True, verbose=True,
    )
    rec = ev.record()
    assert rec["event"] == "step"
    assert rec["valid"] is True
    assert "verbose" not in rec  # presentation-only
    assert "0.2" not in ev.banner() or "250.0 ms" in ev.banner()


def test_step_event_banner_gated_on_verbose_and_valid():
    quiet = StepEvent(0, 0, 1.0, 0.1, 8, valid=True, verbose=False)
    assert quiet.banner() is None
    untimed = StepEvent(0, 0, 1.0, 0.0, 8, valid=False, verbose=True)
    assert "untimed" in untimed.banner()


def test_epoch_event_banner_reference_format():
    ev = EpochEvent(epoch=2, rank=1, mean_loss=0.75, bits_cumulative=16_000_000)
    assert ev.banner() == (
        ">>>>> Rank 1, epoch 2: mean loss 0.7500, 2.00 MB communicated"
    )


def test_raw_event_record_is_verbatim_payload():
    payload = {"metric": "imgs/sec", "value": 42}
    rec = RawEvent(payload).record()
    assert rec == payload
    assert "event" not in rec


def test_failure_event_banner_is_json():
    ev = FailureEvent(kind="watchdog_timeout", label="step 9")
    parsed = json.loads(ev.banner())
    assert parsed["event"] == "failure"
    assert parsed["kind"] == "watchdog_timeout"


# ---------------------------------------------------------------------------
# telemetry + sinks
# ---------------------------------------------------------------------------


def test_telemetry_stamps_ts_except_raw():
    mem = MemorySink()
    t = Telemetry([mem])
    t.emit(NoteEvent("hello"))
    t.emit(RawEvent({"value": 1}))
    assert "ts" in mem.records[0]
    assert "ts" not in mem.records[1]  # verbatim driver contract


def test_telemetry_fans_out_to_all_sinks():
    a, b = MemorySink(), MemorySink()
    Telemetry([a, b]).emit(NoteEvent("x"))
    assert len(a.records) == len(b.records) == 1
    assert a.of_kind("note") and b.of_kind("note")


def test_stdout_sink_prints_only_banners(capsys):
    t = Telemetry([StdoutSink()])
    t.emit(NoteEvent("visible"))
    t.emit(StepEvent(0, 0, 1.0, 0.1, 8, verbose=False))  # banner() is None
    out = capsys.readouterr().out
    assert out == "visible\n"


def test_stream_json_sink_prefix():
    buf = io.StringIO()
    Telemetry([StreamJsonSink(buf, prefix="@BENCH@ ")]).emit(
        RawEvent({"phase": "probe", "ok": True})
    )
    line = buf.getvalue()
    assert line.startswith("@BENCH@ {")
    assert json.loads(line[len("@BENCH@ "):]) == {"phase": "probe", "ok": True}


def test_jsonl_sink_creates_parent_and_appends(tmp_path):
    path = str(tmp_path / "deep" / "nested" / "run.jsonl")
    with telemetry_for_run(event_log=path, stdout=False) as t:
        t.emit(NoteEvent("first"))
    with telemetry_for_run(event_log=path, stdout=False) as t:
        t.emit(NoteEvent("second"))  # append mode: the default
    lines = [json.loads(l) for l in open(path)]
    assert [l["message"] for l in lines] == ["first", "second"]


def test_jsonl_sink_write_mode_truncates(tmp_path):
    path = str(tmp_path / "run.jsonl")
    for msg in ("old", "new"):
        sink = JsonlSink(path, append=False)
        with Telemetry([sink]) as t:
            t.emit(NoteEvent(msg))
    lines = [json.loads(l) for l in open(path)]
    assert [l["message"] for l in lines] == ["new"]


def test_audit_from_config_defaults_to_event_log():
    class Cfg:
        event_log = None
        audit_wire = None

    c = Cfg()
    assert audit_from_config(c) is False
    c.event_log = "runs/x.jsonl"
    assert audit_from_config(c) is True
    c.audit_wire = False  # explicit override wins
    assert audit_from_config(c) is False
    c.event_log = None
    c.audit_wire = True
    assert audit_from_config(c) is True


def test_observe_package_is_jax_free():
    """The bench parent imports observe with NO jax backend init — importing
    the package must not pull jax into the process."""
    code = (
        "import sys\n"
        "import network_distributed_pytorch_tpu.observe\n"
        "assert 'jax' not in sys.modules, 'observe imported jax'\n"
    )
    subprocess.run([sys.executable, "-c", code], check=True, cwd=REPO)


# ---------------------------------------------------------------------------
# wire ledger
# ---------------------------------------------------------------------------


def _ledger():
    return WireLedger(
        [
            LedgerEntry("powersgd.P", "reducer", "all-reduce", "data", "float32", 64),
            LedgerEntry("powersgd.Q", "reducer", "all-reduce", "data", "float32", 32),
            loss_sync_entry("data"),
        ],
        dense_grad_bits=8 * 960,
    )


def test_wire_ledger_totals_and_grouping():
    led = _ledger()
    assert led.total_bytes() == 100
    assert led.total_bits() == 800
    assert led.by_tag() == {"powersgd.P": 64, "powersgd.Q": 32, "loss-sync": 4}
    assert led.by_layer() == {"reducer": 96, "trainer": 4}
    # compression ratio divides by REDUCER bytes only (loss-sync is overhead)
    assert led.compression_ratio() == pytest.approx(960 / 96)


def test_wire_ledger_collective_events_carry_label():
    evs = _ledger().collective_events("unit_test")
    assert len(evs) == 3
    assert all(e.label == "unit_test" for e in evs)
    assert {e.tag for e in evs} == {"powersgd.P", "powersgd.Q", "loss-sync"}


def test_wire_ledger_reconcile_reports_signed_delta():
    led = _ledger()  # 100 analytic bytes
    exact_hlo = (
        "  %ar = (f32[24]{0}, f32[]) all-reduce(%a, %b), "
        "replica_groups={{0,1}}, to_apply=%add\n"
    )  # 4*24 + 4 = 100 bytes
    rec = led.reconcile(exact_hlo)
    assert rec["exact"] and rec["delta_bytes"] == 0
    assert rec["hlo_by_kind"] == {"all-reduce": 1}
    short_hlo = "  %ar = f32[20]{0} all-reduce(%a), to_apply=%add\n"
    rec = led.reconcile(short_hlo)
    assert not rec["exact"]
    assert rec["delta_bytes"] == 80 - 100  # signed, never hidden


def test_step_ledger_asserts_itemization_matches_model(devices):
    import jax.numpy as jnp

    from network_distributed_pytorch_tpu.parallel import ExactReducer

    params = {"w": jnp.zeros((4, 3)), "b": jnp.zeros((3,))}
    # exact DDP moves every gradient byte once, plus the 4-byte loss pmean
    bits = 8 * 4 * (4 * 3 + 3) + 32
    led = step_ledger(ExactReducer(), params, "data", 2, expected_bits=bits)
    assert led.total_bits() == bits
    with pytest.raises(AssertionError, match="itemizes"):
        step_ledger(ExactReducer(), params, "data", 2, expected_bits=bits + 8)


def test_powersgd_ledger_itemizes_bits_per_step(devices):
    import jax.numpy as jnp

    from network_distributed_pytorch_tpu.parallel import PowerSGDReducer

    params = {"w": jnp.zeros((8, 4)), "b": jnp.zeros((4,))}
    red = PowerSGDReducer(compression_rank=2, matricize="last")
    led = step_ledger(
        red, params, "data", 2,
        expected_bits=red.bits_per_step(params, n_workers=2) + 32,
    )
    tags = led.by_tag()
    assert "powersgd.P" in tags and "powersgd.Q" in tags
    assert "loss-sync" in tags
    assert led.compression_ratio() is not None and led.compression_ratio() > 1.0


def test_ledger_from_hlo_summary_reconciles_exactly():
    from network_distributed_pytorch_tpu.utils.hlo_audit import collective_summary

    hlo = (
        "  %ar = f32[100]{0} all-reduce(%a), replica_groups={{0,1}}, to_apply=%add\n"
        "  %ag = f32[50]{0} all-gather(%b), dimensions={0}\n"
    )
    summary = collective_summary(hlo)
    led = ledger_from_hlo_summary(summary, layer="pipeline", axis="pipe")
    assert led.total_bytes() == summary["total_payload_bytes"]
    rec = led.reconcile(hlo)
    assert rec["exact"]  # exact by construction


def test_compiled_step_carries_matching_ledger(devices):
    """Trainer integration: every CompiledStep's ledger itemizes exactly its
    own bits_per_step (the construction-time invariant, end to end)."""
    import jax.numpy as jnp

    from network_distributed_pytorch_tpu.parallel import ExactReducer, make_mesh
    from network_distributed_pytorch_tpu.parallel.trainer import (
        make_train_step,
        stateless_loss,
    )

    params = {"w": jnp.zeros((8, 4))}
    loss = stateless_loss(lambda p, b: jnp.mean((b[0] @ p["w"] - b[1]) ** 2))
    step = make_train_step(
        loss, ExactReducer(), params, 0.05, mesh=make_mesh(), donate_state=False
    )
    assert step.ledger is not None
    assert step.ledger.total_bits() == step.bits_per_step
    assert "loss-sync" in step.ledger.by_tag()


# ---------------------------------------------------------------------------
# metrics logger -> events
# ---------------------------------------------------------------------------


def test_metrics_end_step_without_start_is_invalid_not_zero():
    from network_distributed_pytorch_tpu.utils.metrics import MetricsLogger

    mem = MemorySink()
    logger = MetricsLogger(bits_per_step=80, telemetry=Telemetry([mem]))
    logger.end_step(0, loss=1.0)  # no start_step: no timing origin
    logger.start_step()
    logger.end_step(0, loss=0.9)
    recs = mem.of_kind("step")
    assert recs[0]["valid"] is False
    assert recs[1]["valid"] is True
    # the invalid record is excluded from the steady-state mean, not
    # averaged in as a bogus ~0 s sample
    assert logger.records[0].valid is False
    assert logger.summary()["bits_communicated"] == 160


def test_metrics_second_end_step_does_not_reuse_timing_origin():
    from network_distributed_pytorch_tpu.utils.metrics import MetricsLogger

    logger = MetricsLogger(telemetry=Telemetry([]))
    logger.start_step()
    first = logger.end_step(0, loss=1.0)
    second = logger.end_step(0, loss=0.9)  # no new start_step
    assert first.valid and not second.valid


def test_metrics_dump_jsonl_creates_parent_and_appends(tmp_path):
    from network_distributed_pytorch_tpu.utils.metrics import MetricsLogger

    logger = MetricsLogger(bits_per_step=8, telemetry=Telemetry([]))
    logger.start_step()
    logger.end_step(0, loss=1.0)
    path = str(tmp_path / "not" / "yet" / "steps.jsonl")
    logger.dump_jsonl(path)  # parent dirs created
    logger.dump_jsonl(path, append=True)
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 2
    assert all(l["valid"] for l in lines)


def test_metrics_epoch_event_banner(capsys):
    from network_distributed_pytorch_tpu.utils.metrics import MetricsLogger

    logger = MetricsLogger(bits_per_step=8_000_000, telemetry=Telemetry([StdoutSink()]))
    logger.start_step()
    logger.end_step(0, loss=0.5)
    logger.end_epoch(0, rank=3)
    out = capsys.readouterr().out
    assert ">>>>> Rank 3, epoch 0: mean loss 0.5000, 1.00 MB communicated" in out


# ---------------------------------------------------------------------------
# scripts/report.py
# ---------------------------------------------------------------------------


def _load_report_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "report", os.path.join(REPO, "scripts", "report.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_report_renders_all_sections(tmp_path):
    report = _load_report_module()
    path = str(tmp_path / "run.jsonl")
    with telemetry_for_run(event_log=path, stdout=False) as t:
        for i in range(4):
            t.emit(StepEvent(i, 0, 1.0 - i * 0.1, 0.05 + i * 0.01, 96 * (i + 1)))
        t.emit(
            CollectiveEvent(
                label="t", tag="grads", layer="reducer", op="all-reduce",
                axis="data", dtype="float32", payload_bytes=92,
            )
        )
        t.emit(
            CompileEvent(
                label="t", analytic_bytes=96, hlo_bytes=96, delta_bytes=0,
                exact=True, hlo_collective_count=1,
                hlo_by_kind={"all-reduce": 1},
                overlap={"scheduled": True, "n_async_collectives": 0,
                         "n_overlapped": 0, "n_async_copy_windows": 2,
                         "n_copy_windows_with_compute": 1},
            )
        )
        t.emit(EpochEvent(epoch=0, rank=0, mean_loss=0.85, bits_cumulative=384))
        t.emit(FailureEvent(kind="watchdog_timeout", label="step 3"))
    events = report.load_events(path)
    text = report.render_report(events, name="unit")
    assert "steps" in text and "4 steps recorded" in text
    assert "wire ledger" in text and "grads" in text
    assert "compile audit" in text and "byte-exact" in text
    assert "all-reduce x1" in text
    assert "epochs" in text and "failures" in text
    assert "watchdog_timeout" in text


def test_report_failure_timeline(tmp_path):
    """The failures section orders the fault lifecycle by timestamp and
    reports injected -> detected -> recovered latencies per chaos fault."""
    report = _load_report_module()
    base = 1000.0
    failures = [
        {"event": "failure", "kind": "chaos_injected", "label": "proc_kill",
         "rank": 1, "step": 6, "ts": base},
        {"event": "failure", "kind": "worker_exit", "rank": 1,
         "message": "exit code -9", "ts": base + 0.4},
        {"event": "failure", "kind": "worker_restart", "rank": 1,
         "incarnation": 1, "ts": base + 1.0},
        {"event": "failure", "kind": "resumed", "rank": 1, "step": 0,
         "incarnation": 1, "ts": base + 2.5},
    ]
    lines = report.render_failure_timeline(failures)
    text = "\n".join(lines)
    assert "failures" in text  # section header contract with render_report
    assert "t+   0.000s" in text and "chaos_injected" in text
    assert "rank 1" in text and "@step 6" in text
    assert "inc 1" in text
    # the latency span: detection and recovery measured from the injection
    assert "proc_kill: detected +0.400s, worker_restart +1.000s" in text

    # events without a ts (foreign/legacy records) still render, at the end
    lines = report.render_failure_timeline(
        [{"event": "failure", "kind": "watchdog_timeout", "label": "step 3"}]
    )
    assert any("watchdog_timeout" in ln for ln in lines)


def test_report_death_tally_graceful_vs_hard():
    """The timeline tallies supervisor-observed deaths by the graceful/hard
    classification carried in worker_exit/worker_term messages — other
    kinds never count, even if their message mentions the words."""
    report = _load_report_module()
    failures = [
        {"event": "failure", "kind": "worker_exit", "rank": 0,
         "message": "exit code 75 (graceful death)", "ts": 1.0},
        {"event": "failure", "kind": "worker_exit", "rank": 1,
         "message": "exit code -9 (hard death)", "ts": 2.0},
        {"event": "failure", "kind": "worker_term", "rank": 2,
         "message": "graceful shutdown for world shrink", "ts": 3.0},
        {"event": "failure", "kind": "resumed", "rank": 0,
         "message": "a graceful restart that must NOT count", "ts": 4.0},
    ]
    lines = report.render_failure_timeline(failures)
    tally = [ln for ln in lines if "deaths:" in ln]
    assert len(tally) == 1
    assert "2 graceful" in tally[0] and "1 hard" in tally[0]
    # no deaths, no tally line
    assert not any(
        "deaths:" in ln
        for ln in report.render_failure_timeline(
            [{"event": "failure", "kind": "resumed", "ts": 1.0}]
        )
    )


def test_report_percentiles_and_delta(tmp_path):
    report = _load_report_module()
    assert report.percentile([1.0, 2.0, 3.0, 4.0], 50) == pytest.approx(3.0)
    assert report.percentile([5.0], 95) == 5.0
    path = str(tmp_path / "run.jsonl")
    with telemetry_for_run(event_log=path, stdout=False) as t:
        t.emit(
            CompileEvent(
                label="powersgd", analytic_bytes=100, hlo_bytes=92,
                delta_bytes=-8, exact=False, hlo_collective_count=2,
                compression_ratio=10.0, dense_grad_bytes=960,
                overlap={"scheduled": False},
            )
        )
    text = report.render_report(report.load_events(path))
    assert "delta -8 B" in text  # reported, not hidden
    assert "compression 10.0x" in text
    assert "HLO not scheduled" in text


def test_report_skips_foreign_lines(tmp_path):
    report = _load_report_module()
    path = str(tmp_path / "run.jsonl")
    with open(path, "w") as f:
        f.write("not json at all\n")
        f.write(json.dumps({"event": "note", "message": "ok"}) + "\n")
        f.write("[1, 2, 3]\n")  # JSON but not an object
    events = report.load_events(path)
    assert len(events) == 1 and events[0]["event"] == "note"


def test_report_cli_json_mode(tmp_path, capsys):
    report = _load_report_module()
    path = str(tmp_path / "run.jsonl")
    with telemetry_for_run(event_log=path, stdout=False) as t:
        t.emit(NoteEvent("x"))
        t.emit(NoteEvent("y"))
    assert report.main([path, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["events"] == {"note": 2}


# ---------------------------------------------------------------------------
# satellite coverage: ts_mono stamping, sink edges, torn-tail tolerance
# ---------------------------------------------------------------------------


def test_emit_stamps_monotonic_twin():
    """Every stamped record carries (ts, ts_mono); RawEvent's verbatim
    driver contract stays a ts-free pass-through."""
    sink = MemorySink()
    t = Telemetry([sink])
    t.emit(NoteEvent("hello"))
    rec = sink.records[-1]
    assert isinstance(rec["ts"], float) and isinstance(rec["ts_mono"], float)

    t.emit(RawEvent({"summary": True, "metric": "x"}))
    raw = sink.records[-1]
    assert "ts" not in raw and "ts_mono" not in raw

    # caller-provided stamps win over emit-time stamping
    class _Pinned(NoteEvent):
        def record(self):
            rec = super().record()
            rec["ts"] = 123.0
            rec["ts_mono"] = 4.0
            return rec

    t.emit(_Pinned("pinned"))
    assert sink.records[-1]["ts"] == 123.0
    assert sink.records[-1]["ts_mono"] == 4.0


def test_stream_json_sink_prefix_round_trips():
    """Prefixed lines (the @BENCH@ child protocol) must parse back to the
    exact record after the prefix is stripped — across multiple lines."""
    buf = io.StringIO()
    t = Telemetry([StreamJsonSink(buf, prefix="@BENCH@")])
    t.emit(NoteEvent("one"))
    t.emit(NoteEvent("two"))
    lines = buf.getvalue().splitlines()
    assert len(lines) == 2
    msgs = []
    for line in lines:
        assert line.startswith("@BENCH@")
        rec = json.loads(line[len("@BENCH@"):])
        msgs.append(rec["message"])
    assert msgs == ["one", "two"]


def test_jsonl_sink_append_vs_truncate(tmp_path):
    path = str(tmp_path / "log.jsonl")
    with telemetry_for_run(event_log=path, stdout=False) as t:
        t.emit(NoteEvent("first"))
    with telemetry_for_run(event_log=path, stdout=False) as t:
        t.emit(NoteEvent("second"))  # append=True default: extends
    with open(path) as f:
        assert len(f.read().splitlines()) == 2
    with telemetry_for_run(event_log=path, stdout=False, append=False) as t:
        t.emit(NoteEvent("fresh"))  # truncate: restarts the log
    with open(path) as f:
        lines = f.read().splitlines()
    assert len(lines) == 1 and json.loads(lines[0])["message"] == "fresh"


def test_memory_sink_of_kind_filters():
    sink = MemorySink()
    t = Telemetry([sink])
    t.emit(NoteEvent("a"))
    t.emit(StepEvent(step=0, epoch=0, loss=1.0, step_time_s=0.1,
                     bits_cumulative=8))
    t.emit(NoteEvent("b"))
    assert [r["message"] for r in sink.of_kind("note")] == ["a", "b"]
    assert len(sink.of_kind("step")) == 1
    assert sink.of_kind("failure") == []


def test_telemetry_close_is_idempotent(tmp_path):
    path = str(tmp_path / "log.jsonl")
    t = telemetry_for_run(event_log=path, stdout=False)
    t.emit(NoteEvent("x"))
    t.close()
    t.close()  # second close must not raise on the closed stream
    jsonl = next(s for s in t.sinks if isinstance(s, JsonlSink))
    assert jsonl.stream.closed


def test_report_counts_torn_tail_line(tmp_path):
    """A SIGKILLed rank's half-written final line is skipped and COUNTED —
    the report warns instead of raising or silently dropping it."""
    report = _load_report_module()
    path = str(tmp_path / "run.jsonl")
    with telemetry_for_run(event_log=path, stdout=False) as t:
        t.emit(NoteEvent("whole"))
    with open(path, "a") as f:
        f.write('{"event": "step", "step": 7, "ts": 1.0, "step_ti')
    events, skipped = report.load_events_counted(path)
    assert len(events) == 1 and skipped == 1
    text = report.render_report(events, skipped_lines=skipped)
    assert "1 unparseable/torn line(s) skipped" in text
    # and the zero case emits no warning line
    assert "torn" not in report.render_report(events, skipped_lines=0)
