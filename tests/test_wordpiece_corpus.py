"""Corpus-side WordPiece machinery (no HF dependency): contiguous rank
sharding reassembles to the monolithic encode, the corpus-built vocab
covers its own corpus, and the on-disk vocab cache builds exactly once
per (corpus, params) fingerprint."""

import numpy as np
import pytest

from network_distributed_pytorch_tpu.data import (
    WordPieceTokenizer,
    build_vocab,
    cached_vocab_file,
    merge_tokenized_shards,
    shard_rows,
)
from network_distributed_pytorch_tpu.data import wordpiece as wp

CORPUS = [
    "The movie was great, really great!",
    "Terrible acting. Unbelievable?",
    "It was good -- co-op mode was bad.",
    "Watched it in 2024, at the cafe.",
    "a really REALLY long review " * 8,
    "short",
    "punctuation!!! everywhere... and digits 123 456",
]


def _tok(tmp_path, max_len=32):
    path = cached_vocab_file(CORPUS, str(tmp_path / "vocab_cache"))
    return WordPieceTokenizer(path, max_len=max_len)


def test_shard_rows_partition_exact():
    """Every (n, W): shards are contiguous, balanced within one row, and
    their rank-order concatenation is exactly range(n) — including W > n
    (some shards empty) and non-divisible splits."""
    for n in (0, 1, 5, 7, 64):
        for w in (1, 2, 3, 5, 9):
            spans = [shard_rows(n, w, r) for r in range(w)]
            rows = [i for a, b in spans for i in range(a, b)]
            assert rows == list(range(n)), (n, w, spans)
            sizes = [b - a for a, b in spans]
            assert max(sizes) - min(sizes) <= 1, (n, w, sizes)
    with pytest.raises(ValueError):
        shard_rows(4, 2, 2)
    with pytest.raises(ValueError):
        shard_rows(4, 0, 0)


def test_encode_shard_merge_equals_monolithic(tmp_path):
    """Rank-sharded tokenization merged in rank order must be byte-equal
    to one process encoding the full corpus — for divisible and
    non-divisible world sizes."""
    tok = _tok(tmp_path)
    full = tok(CORPUS)
    for w in (1, 2, 3, 7):
        shards = [tok.encode_shard(CORPUS, w, r) for r in range(w)]
        merged = merge_tokenized_shards(shards)
        for k in ("input_ids", "attention_mask"):
            np.testing.assert_array_equal(merged[k], full[k])


def test_built_vocab_covers_corpus(tmp_path):
    """Character coverage in build_vocab: no word made of seen characters
    ever collapses to [UNK], and every corpus row encodes non-trivially."""
    tok = _tok(tmp_path)
    out = tok(CORPUS)
    assert not np.any(out["input_ids"] == tok.unk_id)
    # every row carries [CLS] + at least one real token + [SEP]
    assert np.all(out["attention_mask"].sum(axis=1) >= 3)


def test_vocab_build_is_deterministic():
    assert build_vocab(CORPUS) == build_vocab(list(CORPUS))
    # frequency-ranked words follow specials + chars; [PAD] stays id 0
    v = build_vocab(CORPUS)
    assert v[0] == "[PAD]" and v[1] == "[UNK]"


def test_vocab_cache_builds_once(tmp_path, monkeypatch):
    """Second call with the same corpus must return the cached file WITHOUT
    rebuilding (ranks re-tokenizing per incarnation was the startup cost);
    a changed corpus or changed params must miss the cache."""
    cache = str(tmp_path / "cache")
    p1 = cached_vocab_file(CORPUS, cache)

    def boom(*a, **k):  # any rebuild attempt is the regression
        raise AssertionError("vocab rebuilt despite cache hit")

    monkeypatch.setattr(wp, "build_vocab", boom)
    assert cached_vocab_file(CORPUS, cache) == p1
    monkeypatch.undo()
    p2 = cached_vocab_file(CORPUS + ["new document"], cache)
    p3 = cached_vocab_file(CORPUS, cache, max_size=4096)
    assert len({p1, p2, p3}) == 3
