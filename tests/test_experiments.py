"""Experiment entry points end-to-end on the 8-device mesh (small tier,
synthetic data): each reference guide's equivalent runs, reports metrics, and
the compressed path moves fewer bytes than the exact path."""

import numpy as np
import pytest

# every experiment drive compiles a full model + mesh step — the suite's slow
# tier (round-1 verdict: 12:41 wall with no fast tier; this module was ~9 min)
pytestmark = pytest.mark.slow

from network_distributed_pytorch_tpu.experiments import (
    bandwidth_study,
    bare_init,
    exact_cifar10,
    imdb_baseline,
    powersgd_cifar10,
    powersgd_imdb,
)
from network_distributed_pytorch_tpu.utils.config import ExperimentConfig


def _cfg(**kw):
    base = dict(training_epochs=1, log_every=0)
    base.update(kw)
    return ExperimentConfig(**base)


def test_bare_init(devices):
    out = bare_init.run(_cfg(training_epochs=0))
    assert out["num_devices"] == 8


def test_exact_cifar10(devices):
    out = exact_cifar10.run(
        _cfg(global_batch_size=64, learning_rate=0.001),
        preset="small",
        data_dir="/nonexistent",
        max_steps_per_epoch=3,
    )
    assert out["steps"] == 3
    assert np.isfinite(out["final_loss"])
    assert not out["real_data"]
    assert out["bits_communicated"] > 0


def test_powersgd_cifar10(devices):
    out = powersgd_cifar10.run(
        _cfg(global_batch_size=64, reducer_rank=2),
        preset="small",
        data_dir="/nonexistent",
        max_steps_per_epoch=3,
    )
    assert out["steps"] == 3 and np.isfinite(out["final_loss"])


def test_powersgd_beats_exact_on_wire(devices):
    kw = dict(preset="small", data_dir="/nonexistent", max_steps_per_epoch=2)
    exact = exact_cifar10.run(_cfg(global_batch_size=64), **kw)
    psgd = powersgd_cifar10.run(_cfg(global_batch_size=64, reducer_rank=2), **kw)
    assert psgd["bits_communicated"] < exact["bits_communicated"] / 10


def test_powersgd_imdb(devices):
    out = powersgd_imdb.run(
        _cfg(learning_rate=5e-5, reducer_rank=4, global_batch_size=32),
        preset="small",
        max_len=32,
        max_steps_per_epoch=2,
    )
    assert out["steps"] == 2 and np.isfinite(out["final_loss"])


def test_imdb_baseline_single_node(devices):
    out = imdb_baseline.run(
        _cfg(learning_rate=5e-5, global_batch_size=16),
        preset="small",
        max_len=32,
        max_steps_per_epoch=2,
    )
    assert out["steps"] == 2 and np.isfinite(out["final_loss"])


def test_bandwidth_study(devices):
    out = bandwidth_study.run(global_batch=64, reducer_ranks=(2,))
    res = out["results"]
    assert res["powersgd_r2"]["compression_ratio"] > 10
    for cfgname, r in res.items():
        # slower fabrics must cost more time
        p = r["projected_step_s"]
        assert p["1GbE"] > p["10GbE"] > p["100GbE"] > p["ICI(v5e)"]
        if "sync_every" in r:
            # avoidance rows reconcile at ROUND granularity: the in-scan
            # loss pmean appears once in HLO text but executes sync_every
            # times (see parallel.localsgd) — the study applies exactly
            # that adjustment, and it must land byte-exact
            assert r["audited_bits_per_round"] == r["bits_per_round"], (
                cfgname, r["hlo_collectives"]
            )
            continue
        # the projection is fed by the COMPILED step's collectives, and the
        # analytic wire model must reconcile with them byte-exactly
        assert r["audited_bits_per_step"] == r["bits_per_step"], (
            cfgname, r["hlo_collectives"]
        )
        assert sum(r["hlo_collectives"].values()) >= 1
    # communication avoidance: local SGD's amortized per-step bytes sit an
    # order below exact DDP (params/H vs full gradient)
    lsgd = res["local_sgd_h8"]
    assert lsgd["bits_per_step"] < res["exact"]["bits_per_step"] / 7
    # avoidance × compression: DiLoCo with PowerSGD-compressed outer deltas
    # undercuts even local SGD's amortized parameter allreduce
    assert (
        res["diloco_psgd_r4_h8"]["bits_per_step"] < lsgd["bits_per_step"] / 10
    )
    # fabric-aware hierarchy: the slow-fabric share is the compressed one,
    # classified per compiled replica group, and the split is exhaustive
    hier = res["hier_powersgd_r4"]
    assert hier["bits_slow_fabric"] < res["exact"]["bits_per_step"] / 10
    assert (
        hier["bits_fast_fabric"] + hier["bits_slow_fabric"]
        == hier["audited_bits_per_step"]
        == hier["bits_per_step"]
    )
    assert hier["slow_collectives"] >= 1


def test_launch_cli(devices):
    from network_distributed_pytorch_tpu.launch import main

    out = main(
        [
            "powersgd_cifar10",
            "--preset", "small",
            "--epochs", "1",
            "--global-batch", "64",
            "--reducer-rank", "2",
            "--max-steps-per-epoch", "2",
            "--data-dir", "/nonexistent",
            "--log-every", "0",
        ]
    )
    assert out["steps"] == 2


def test_imdb_baseline_adamw(devices):
    out = imdb_baseline.run(
        _cfg(learning_rate=5e-5, global_batch_size=16),
        preset="small",
        max_len=32,
        max_steps_per_epoch=2,
        optimizer_name="adamw",  # IMDb_dataset_distributer.py:55-66
    )
    assert out["steps"] == 2 and np.isfinite(out["final_loss"])
    assert out["optimizer"] == "adamw"


def test_powersgd_cifar10_eval_accuracy(devices):
    out = powersgd_cifar10.run(
        _cfg(global_batch_size=64, reducer_rank=2, training_epochs=2, learning_rate=0.02),
        preset="small",
        data_dir="/nonexistent",
        max_steps_per_epoch=20,
        eval_after=True,
    )
    # synthetic class blobs are very separable; training must beat chance
    assert out["eval_accuracy"] > 0.2, out


def test_powersgd_imdb_learns_synthetic_sentiment(devices):
    """SURVEY §4 integration tier: DistilBERT-shaped toy transformer, loss
    decreases on class-separable synthetic text."""
    out = powersgd_imdb.run(
        _cfg(
            learning_rate=2e-3, reducer_rank=4, global_batch_size=64,
            training_epochs=4,
        ),
        preset="small",
        max_len=32,
        max_steps_per_epoch=6,
    )
    rec = out
    assert np.isfinite(rec["final_loss"])
    assert rec["final_loss"] < 0.69, rec  # below ln(2) = chance for 2 classes


def test_gpt_lm_learns_with_powersgd(devices):
    """The decoder family under the reference's flagship algorithm: GPT +
    PowerSGD data parallelism learns the cyclic next-token task."""
    from network_distributed_pytorch_tpu.experiments import gpt_lm

    out = gpt_lm.run(
        _cfg(
            learning_rate=0.15, reducer_rank=4, global_batch_size=32,
            training_epochs=3,
        ),
        preset="small",
        seq_len=32,
        steps_per_epoch=15,
    )
    assert out["final_loss"] < 0.5, out
    assert out["bytes_communicated"] > 0


def test_powersgd_cifar10_real_data_path(devices, tmp_path):
    """End-to-end over the REAL on-disk data path (BASELINE.md: 'drop the
    dataset at ./data and the same commands run on real data'): write a
    cifar-10-batches-py directory in the torchvision pickle format, run the
    flagship experiment against it, and confirm it trained from DISK
    (real_data=True), not the synthetic fallback."""
    from test_data import _write_fake_cifar

    _write_fake_cifar(tmp_path)
    out = powersgd_cifar10.run(
        _cfg(global_batch_size=40, reducer_rank=2),
        preset="small",
        data_dir=str(tmp_path),
        max_steps_per_epoch=2,
    )
    assert out["real_data"] is True
    assert out["steps"] >= 2
    assert np.isfinite(out["final_loss"])


def test_gpt_pp_full_model_pipeline_learns(devices):
    """Pipeline parallelism as a user-facing experiment: 8 GPT stages over
    the 'pipe' mesh, 1F1B full-model training (embed/head included) learns
    the cyclic next-token task; wire bits come from the compiled HLO audit."""
    from network_distributed_pytorch_tpu.experiments import gpt_pp

    out = gpt_pp.run(
        _cfg(learning_rate=0.15, global_batch_size=16, training_epochs=3),
        preset="small",
        seq_len=32,
        steps_per_epoch=15,
    )
    assert out["final_loss"] < 0.5, out
    assert out["n_stages"] == 8
    assert out["bytes_communicated"] > 0
    assert sum(out["hlo_collectives"].values()) >= 1


def test_exact_cifar10_fsdp_strategy(devices):
    """ZeRO-3 as a launcher strategy: same exact-DDP workload with sharded
    params/grads/opt state, evaluated through unshard()."""
    out = exact_cifar10.run(
        _cfg(global_batch_size=64, learning_rate=0.02, training_epochs=1),
        preset="small",
        data_dir="/nonexistent",
        max_steps_per_epoch=4,
        strategy="fsdp",
        eval_after=True,
    )
    assert out["strategy"] == "fsdp"
    assert np.isfinite(out["final_loss"]) and out["steps"] == 4
    assert 0.0 <= out["eval_accuracy"] <= 1.0


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_gpt_sp_long_context_learns(devices, impl):
    """Sequence/context parallelism as a user-facing experiment: 8 seq
    shards (32 tokens/device of a 256-token context), exact ring or Ulysses
    attention, loss on the cyclic next-token task decreases."""
    from network_distributed_pytorch_tpu.experiments import gpt_sp

    out = gpt_sp.run(
        _cfg(learning_rate=0.15, global_batch_size=8, training_epochs=2),
        preset="small",
        seq_impl=impl,
        seq_len=256,
        steps_per_epoch=10,
    )
    assert out["n_seq_shards"] == 8 and out["tokens_per_device"] == 32
    assert out["final_loss"] < out["first_loss"] * 0.5, out
    assert out["bytes_communicated"] > 0


def test_gpt_pp_data_parallel_exact_matches_pipeline_only(devices):
    """DP x PP composition sanity: 2 data shards x 4 pipe stages with exact
    reduction must equal the same model trained pipeline-only on a 4-device
    mesh with the same microbatch partitioning (pmean of per-shard
    microbatch-mean grads == global microbatch-mean grads)."""
    import jax as _jax

    from network_distributed_pytorch_tpu.experiments import gpt_pp
    from network_distributed_pytorch_tpu.parallel import make_mesh

    cfg = lambda: _cfg(
        learning_rate=0.1, global_batch_size=16, training_epochs=1
    )
    ref = gpt_pp.run(
        cfg(),
        preset="small",
        mesh=make_mesh(
            axis_sizes=(4,), axis_names=("pipe",), devices=_jax.devices()[:4]
        ),
        steps_per_epoch=4,
        num_microbatches=4,
    )
    dp = gpt_pp.run(
        cfg(),
        preset="small",
        data_shards=2,
        mesh=make_mesh(
            axis_sizes=(2, 4), axis_names=("data", "pipe")
        ),
        steps_per_epoch=4,
        num_microbatches=2,  # 8-row shard / 2 = same 4-row microbatches
    )
    assert dp["data_shards"] == 2 and ref["data_shards"] == 1
    np.testing.assert_allclose(dp["final_loss"], ref["final_loss"], rtol=2e-5)
    np.testing.assert_allclose(dp["first_loss"], ref["first_loss"], rtol=2e-5)


def test_gpt_pp_data_parallel_powersgd_learns(devices):
    """Compressed data parallelism COMPOSED with pipeline parallelism — the
    reference's algorithm on a strategy it never had: 2 shards x 4 stages,
    PowerSGD EF chain across shards, loss decreases."""
    from network_distributed_pytorch_tpu.experiments import gpt_pp

    out = gpt_pp.run(
        _cfg(
            learning_rate=0.15, global_batch_size=16, training_epochs=3,
            reducer_rank=4,
        ),
        preset="small",
        data_shards=2,
        reducer="powersgd",
        steps_per_epoch=10,
        num_microbatches=2,
    )
    assert out["reducer"] == "powersgd"
    assert out["data_shards"] == 2
    assert out["final_loss"] < out["first_loss"] * 0.5, out


def test_eval_scores_every_example_even_below_batch_size(devices):
    """Regression: evaluation must not drop ragged tails — with fewer
    examples than batch_size the old drop-last path scored NOTHING and
    reported exactly 0.0."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from network_distributed_pytorch_tpu.experiments.common import (
        evaluate_image_classifier,
    )
    from network_distributed_pytorch_tpu.models import resnet18

    model = resnet18(num_classes=10, norm="batch", stem="cifar", width=8)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=True
    )
    x = np.random.RandomState(0).randn(10, 32, 32, 3).astype(np.float32)
    # an untrained model still predicts SOMETHING for all 10 rows; label
    # everything with its argmax so accuracy is exactly 1.0 — impossible
    # under the old tail-dropping bug (total would be 0 → 0.0)
    logits = model.apply(
        {"params": variables["params"], "batch_stats": variables["batch_stats"]},
        jnp.asarray(x), train=False,
    )
    y = np.asarray(jnp.argmax(logits, -1), np.int32)
    acc = evaluate_image_classifier(
        model, variables["params"], variables["batch_stats"], x, y,
        batch_size=256,  # larger than the dataset
    )
    assert acc == 1.0
    # ragged tail: 10 examples at batch 4 → 4+4+2, all scored
    acc = evaluate_image_classifier(
        model, variables["params"], variables["batch_stats"], x, y, batch_size=4
    )
    assert acc == 1.0
