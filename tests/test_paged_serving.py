"""Paged KV serving (PR 19): the block pool, prefix sharing, speculative
decoding, and the autoscaler's scheduler leases.

The load-bearing claims, in test form:

- **Allocator invariants** (jax-free): all-or-nothing allocation,
  refcounted link/release, double-free raises, and ``check_owners``
  catches every way the free-list and the owner chains can disagree.
- **Bit-identity at a fraction of the HBM**: the paged engine — including
  mid-flight admissions, prefix-shared admissions, and speculative
  rounds — produces EXACTLY the tokens of sequential
  ``models.gpt.generate`` calls and of the dense ``SlotEngine``. Paging,
  sharing, and speculation change the memory layout and the dispatch
  count, never the math.
- **Shared-prefix admission**: 8 identical prompts prefill the device
  ONCE; the other 7 admit from the prompt-hash index (zero forward
  passes), and copy-on-write isolates their divergent suffixes.
- **Exactly-once eviction + leak accounting**: every eviction path
  returns each block exactly once; the per-tick invariant
  ``free + Σ distinct chain entries == usable`` fails loudly when broken.
- **Backpressure**: a pool too small for the offered load defers
  admissions (strict FIFO) and still drains everything.
- **Scheduler leases**: the autoscaler's chip-lease API on
  ``resilience.scheduler.FleetScheduler`` grants from the free pool,
  respects reservations, and releases idempotently.
"""

import importlib.util
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from network_distributed_pytorch_tpu.models.gpt import generate, gpt_tiny
from network_distributed_pytorch_tpu.serving import Request
from network_distributed_pytorch_tpu.serving.blocks import (
    GARBAGE_BLOCK,
    BlockLeakError,
    BlockPool,
    OutOfBlocks,
    PrefixIndex,
    blocks_needed,
    prefix_key,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name: str):
    path = os.path.join(REPO, "scripts", f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"_paged_test_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[f"_paged_test_{name}"] = mod
    spec.loader.exec_module(mod)
    return mod


class _CaptureTelemetry:
    def __init__(self):
        self.events = []

    def emit(self, event):
        self.events.append(event)


# --- allocator units (jax-free) -------------------------------------------


def test_blocks_needed_and_prefix_key():
    assert blocks_needed(0, 4) == 0
    assert blocks_needed(1, 4) == 1
    assert blocks_needed(4, 4) == 1
    assert blocks_needed(5, 4) == 2
    # content-addressed: same tokens same key, regardless of container
    assert prefix_key([1, 2, 3]) == prefix_key((1, 2, 3))
    assert prefix_key([1, 2, 3]) != prefix_key([1, 2])


def test_block_pool_alloc_link_release_refcounts():
    pool = BlockPool(6, 4)  # 5 usable, block 0 is garbage
    assert pool.n_usable == 5 and pool.n_free == 5
    a = pool.alloc(2)
    assert a == [1, 2]  # deterministic ascending order
    assert all(pool.refcount(b) == 1 for b in a)
    # all-or-nothing: an uncoverable request takes NOTHING
    with pytest.raises(OutOfBlocks):
        pool.alloc(4)
    assert pool.n_free == 3
    pool.link(a)
    assert all(pool.refcount(b) == 2 for b in a)
    assert pool.release(a) == []  # survivors keep the blocks
    assert pool.release(a) == a  # last reference frees
    assert pool.n_free == 5
    with pytest.raises(BlockLeakError):
        pool.release([1])  # double free
    with pytest.raises(BlockLeakError):
        pool.link([1])  # linking an unallocated block
    # the garbage block is never a real reference
    assert pool.release([GARBAGE_BLOCK]) == []


def test_block_pool_check_owners_catches_discrepancies():
    pool = BlockPool(5, 4)
    chain = pool.alloc(2)
    pool.check_owners([chain])  # consistent
    with pytest.raises(BlockLeakError):
        pool.check_owners([])  # allocated but unowned
    with pytest.raises(BlockLeakError):
        pool.check_owners([chain, chain])  # multiplicity != refcount
    pool.link(chain)
    pool.check_owners([chain, chain])
    pool.release(chain)
    pool.release(chain)
    pool.check_owners([])


def test_prefix_index_register_lookup_evict_lru():
    pool = BlockPool(10, 4)
    prompt = [1, 2, 3, 4, 5, 6]  # one full block + a partial
    chain = pool.alloc(blocks_needed(len(prompt), 4))
    idx = PrefixIndex(pool)
    added = idx.register(prompt, chain, first_token=42)
    assert added == 2  # the 4-token block prefix + the exact prompt
    # exact hit replays the greedy first token; the index owns its refs
    hit = idx.lookup(prompt)
    assert hit["n_tokens"] == 6 and hit["first_token"] == 42
    assert pool.refcount(chain[0]) == 3  # slot + 2 index entries
    # a longer prompt sharing the first block matches at block granularity
    hit = idx.lookup([1, 2, 3, 4, 9, 9, 9])
    assert hit["n_tokens"] == 4 and hit["first_token"] is None
    assert idx.lookup([7, 7, 7]) is None
    pool.check_owners([chain] + idx.chains())
    # release the slot's own reference, then LRU-evict the index dry
    pool.release(chain)
    idx.evict_lru(pool.n_usable)
    assert len(idx) == 0 and pool.n_free == pool.n_usable
    pool.check_owners([])


def test_spec_accept_bitwise_semantics():
    from network_distributed_pytorch_tpu.serving.engine import spec_accept

    # greedy self-draft: every fed token matches the target's previous
    # output, so the whole round lands (K-1 drafts + the bonus token)
    assert spec_accept([5, 7, 8, 9], [7, 8, 9, 4], budget_left=10) == [
        7, 8, 9, 4,
    ]
    # adversarial draft: fed[2]=6 contradicts the target's outs[1]=8 —
    # the CORRECTED token 8 still lands, nothing after it does
    assert spec_accept([5, 7, 6, 9], [7, 8, 9, 4], budget_left=10) == [7, 8]
    # a first-proposal miss accepts exactly the one corrected token:
    # precisely what a target-only decode step would have emitted
    assert spec_accept([5, 1, 1, 1], [7, 8, 9, 4], budget_left=10) == [7]
    # request budget truncates a fully-matching round
    assert spec_accept([5, 7, 8, 9], [7, 8, 9, 4], budget_left=2) == [7, 8]
    # EOS stops the round even when the draft kept matching
    assert spec_accept(
        [5, 7, 8, 9], [7, 8, 9, 4], budget_left=10, eos_token_id=8
    ) == [7, 8]


# --- engine parity (device) -----------------------------------------------


def _serving_model(max_len=16, seed=0):
    model = gpt_tiny(vocab_size=64, max_position_embeddings=max_len)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, max_len), jnp.int32)
    )["params"]
    return model, params


def _mixed_requests(rng, n=5):
    reqs = []
    for i, budget in enumerate((4, 6, 3, 5, 4)[:n]):
        prompt = [int(t) for t in rng.randint(0, 64, rng.randint(2, 7))]
        reqs.append(
            Request(
                request_id=f"req-{i:04d}", prompt=prompt,
                max_new_tokens=budget,
            )
        )
    return reqs


def _assert_bitwise_vs_generate(model, params, reqs, max_len):
    for r in reqs:
        ref = generate(
            model.config, params, jnp.asarray([r.prompt], jnp.int32),
            r.max_new_tokens, cache_len=max_len,
        )
        np.testing.assert_array_equal(
            np.asarray(r.tokens, np.int32), np.asarray(ref[0])
        )


def test_paged_engine_bit_identical_to_dense_and_generate(devices):
    from network_distributed_pytorch_tpu.serving.engine import (
        PagedEngine,
        SlotEngine,
    )

    max_len = 16
    model, params = _serving_model(max_len)
    rng = np.random.RandomState(1)
    reqs = _mixed_requests(rng)
    engine = PagedEngine(
        model.config, params, n_slots=2, max_len=max_len, block_len=4,
    )
    # same mid-flight admission schedule as the dense engine's bit-identity
    # test: two admitted into slots freed by earlier completions
    for r in reqs[:3]:
        engine.submit(r)
    engine.step()
    engine.step()
    for r in reqs[3:]:
        engine.submit(r)
    finished = engine.run(max_steps=200)
    assert len(finished) == len(reqs)
    _assert_bitwise_vs_generate(model, params, reqs, max_len)
    # and bit-identical to the DENSE engine on the same workload
    dense_reqs = [
        Request(request_id=r.request_id, prompt=list(r.prompt),
                max_new_tokens=r.max_new_tokens)
        for r in reqs
    ]
    dense = SlotEngine(model.config, params, n_slots=2, max_len=max_len)
    for r in dense_reqs:
        dense.submit(r)
    dense.run(max_steps=200)
    assert {r.request_id: r.tokens for r in reqs} == {
        r.request_id: r.tokens for r in dense_reqs
    }
    # the pool drained clean: every block back on the free list
    assert engine.allocator.n_free == engine.allocator.n_usable or (
        engine.index is not None and len(engine.index) > 0
    )
    engine.allocator.check_owners(engine._owner_chains())


def test_spec_decoding_bitwise_self_draft_and_adversarial(devices):
    from network_distributed_pytorch_tpu.serving.engine import PagedEngine

    max_len = 16
    model, params = _serving_model(max_len)
    rng = np.random.RandomState(3)
    reqs = _mixed_requests(rng)

    def run_paged(spec_params):
        rs = [
            Request(request_id=r.request_id, prompt=list(r.prompt),
                    max_new_tokens=r.max_new_tokens)
            for r in reqs
        ]
        eng = PagedEngine(
            model.config, params, n_slots=2, max_len=max_len, block_len=4,
            draft_config=model.config if spec_params is not None else None,
            draft_params=spec_params, spec_k=4 if spec_params is not None else 0,
        )
        for r in rs:
            eng.submit(r)
        eng.run(max_steps=300)
        return eng, {r.request_id: r.tokens for r in rs}

    plain, want = run_paged(None)
    # self-draft: proposals are the target's own greedy tokens, so rounds
    # accept fully up to budget/EOS truncation — bitwise AND strictly
    # fewer target dispatches
    self_spec, got = run_paged(params)
    assert got == want
    assert self_spec.spec_proposed > 0
    assert self_spec.spec_accepted / self_spec.spec_proposed > 0.5
    assert self_spec.decode_steps < plain.decode_steps
    # adversarial draft (independently-initialized params): proposals are
    # near-noise, acceptance collapses to the corrected-token prefix —
    # and the emitted streams STILL match the target bitwise
    _, adv_params = _serving_model(max_len, seed=7)
    adv_spec, got = run_paged(adv_params)
    assert got == want
    assert adv_spec.spec_accepted < adv_spec.spec_proposed
    accept_rate = adv_spec.spec_accepted / adv_spec.spec_proposed
    assert accept_rate < 0.5  # a real draft would need distillation


def test_shared_prefix_eight_requests_prefill_once(devices):
    from network_distributed_pytorch_tpu.serving.engine import PagedEngine

    max_len = 16
    model, params = _serving_model(max_len)
    prompt = [3, 1, 4, 1, 5, 9]  # not block-aligned: COW territory
    cap = _CaptureTelemetry()
    engine = PagedEngine(
        model.config, params, n_slots=4, max_len=max_len, block_len=4,
        telemetry=cap, emit_pool_every=1,
    )
    reqs = [
        Request(request_id=f"s{i}", prompt=list(prompt), max_new_tokens=5)
        for i in range(8)
    ]
    for r in reqs:
        engine.submit(r)
    finished = engine.run(max_steps=200)
    assert len(finished) == 8
    # ONE device prefill; the other seven replayed from the prefix index
    assert engine.prefills == 1
    assert engine.prefix_hits == 7
    assert engine.prefill_tokens_saved == 7 * len(prompt)
    # identical prompts decode identical tokens — and match the reference
    _assert_bitwise_vs_generate(model, params, reqs, max_len)
    assert len({tuple(r.tokens) for r in reqs}) == 1
    # divergence isolation: the shared boundary block forced at least one
    # copy-on-write when a sharer first wrote into it
    assert engine.cow_copies >= 1
    # the ledger reached the live plane: kv_pool events carry the counters
    kv = [e.record() for e in cap.events if e.KIND == "kv_pool"]
    assert kv and kv[-1]["prefix_hits_total"] == 7
    assert kv[-1]["cow_copies_total"] == engine.cow_copies


def test_eviction_exactly_once_and_leak_assertion(devices):
    from network_distributed_pytorch_tpu.serving.engine import PagedEngine

    max_len = 16
    model, params = _serving_model(max_len)
    cap = _CaptureTelemetry()
    engine = PagedEngine(
        model.config, params, n_slots=2, max_len=max_len, block_len=4,
        telemetry=cap, check_leaks=True,
    )
    for i in range(3):
        engine.submit(
            Request(request_id=f"e{i}", prompt=[1, 2, i + 1],
                    max_new_tokens=8)
        )
    engine.step()  # two admitted + ticked, one still queued
    assert engine.allocator.n_free < engine.allocator.n_usable
    evicted = engine.evict_all(reason="shutdown")
    assert len(evicted) == 3 and engine.idle
    # exactly-once release: the pool is whole again (index cleared too)
    assert engine.allocator.n_free == engine.allocator.n_usable
    assert engine.evict_all() == []  # idempotent on an empty engine
    assert {e.record()["state"] for e in cap.events
            if e.KIND == "request"} == {"evicted"}

    # breaking the refcount ledger behind the engine's back trips the
    # per-tick invariant loudly instead of corrupting KV silently
    engine.submit(
        Request(request_id="leak", prompt=[9, 9], max_new_tokens=8)
    )
    engine.step()
    victim = next(s for s in engine.slots if s is not None)
    engine.allocator.release(victim.chain)
    with pytest.raises(BlockLeakError):
        engine.step()


def test_backpressure_defers_fifo_and_drains(devices):
    from network_distributed_pytorch_tpu.serving.engine import PagedEngine

    max_len = 16
    model, params = _serving_model(max_len)
    # 4 usable blocks; every request needs 3 (horizon 12 of block 4), so
    # the pool admits strictly one at a time regardless of the 2 slots
    engine = PagedEngine(
        model.config, params, n_slots=2, max_len=max_len, block_len=4,
        n_blocks=5, prefix_sharing=False,
    )
    reqs = [
        Request(request_id=f"b{i}", prompt=[1 + i, 2, 3], max_new_tokens=9)
        for i in range(4)
    ]
    for r in reqs:
        engine.submit(r)
    finished = engine.run(max_steps=400)
    assert len(finished) == 4
    assert engine.admissions_deferred > 0
    assert engine.peak_active == 1  # the pool, not the slot count, gated
    # FIFO under backpressure: completion order == submission order when
    # every request has the same decode budget and one runs at a time
    assert [r.request_id for r in finished] == [r.request_id for r in reqs]
    _assert_bitwise_vs_generate(model, params, reqs, max_len)
    assert engine.allocator.n_free == engine.allocator.n_usable


# --- scheduler leases (jax-free) ------------------------------------------


def test_fleet_scheduler_lease_grant_partial_and_release(tmp_path):
    from network_distributed_pytorch_tpu.resilience.scheduler import (
        FleetConfig,
        FleetScheduler,
        JobSpool,
    )

    cap = _CaptureTelemetry()
    sched = FleetScheduler(
        JobSpool(str(tmp_path / "jobs")),
        config=FleetConfig(n_devices=4),
        telemetry=cap,
    )
    got = sched.lease("serve-pool", 2, reason="scale_up")
    assert got == [0, 1] and sched.leased("serve-pool") == [0, 1]
    # partial grant: only what the free pool can cover
    assert sched.lease("serve-pool", 5) == [2, 3]
    assert sched.lease("serve-pool", 1) == []  # pool dry
    # release a subset, then the rest; releasing again is a no-op
    sched.lease_release("serve-pool", ranks=[1])
    assert sched.leased("serve-pool") == [0, 2, 3]
    sched.lease_release("serve-pool")
    assert sched.leased("serve-pool") == []
    sched.lease_release("serve-pool")
    assert sched.lease("other", 4) == [0, 1, 2, 3]
    grants = [
        e.record() for e in cap.events
        if e.KIND == "schedule" and e.record().get("planner") == "lease"
    ]
    assert any(g["world"] >= 1 for g in grants)
    assert any(g["world"] == 0 for g in grants)  # the release events


# --- live gauges + report + gate ------------------------------------------


def test_kv_pool_event_feeds_live_gauges():
    from network_distributed_pytorch_tpu.observe.events import KVPoolEvent
    from network_distributed_pytorch_tpu.observe.live import (
        MetricRegistry,
        ingest_record,
    )

    reg = MetricRegistry()
    ev = KVPoolEvent(
        label="t", rank=0, n_blocks=33, block_len=8, blocks_free=10,
        blocks_used=22, blocks_shared=6, pool_bytes=1 << 20,
        prefix_hits_total=7, prefill_tokens_saved_total=56,
        cow_copies_total=2, admissions_deferred_total=3,
    )
    ingest_record(reg, ev.record())
    assert reg.get_gauge("live_kv_blocks_free", rank="0") == 10
    assert reg.get_gauge("live_kv_prefix_hits_total", rank="0") == 7
    assert reg.get_gauge("live_kv_cow_copies_total", rank="0") == 2
    assert reg.get_gauge("live_kv_admissions_deferred_total", rank="0") == 3


def test_report_kv_section_and_gate_capacity_floor(tmp_path):
    report = _load_script("report")
    events = [
        {
            "event": "kv_pool", "rank": 0, "label": "serve", "n_blocks": 33,
            "block_len": 8, "blocks_free": 4, "blocks_used": 28,
            "blocks_shared": 7, "pool_bytes": 1 << 20,
            "prefix_hits_total": 5, "prefill_tokens_saved_total": 40,
            "cow_copies_total": 2, "admissions_deferred_total": 1,
            "t_wall": 100.0,
        },
        {
            "event": "kv_pool", "rank": 0, "label": "serve", "n_blocks": 33,
            "block_len": 8, "blocks_free": 32, "blocks_used": 0,
            "blocks_shared": 0, "pool_bytes": 1 << 20,
            "prefix_hits_total": 9, "prefill_tokens_saved_total": 72,
            "cow_copies_total": 3, "admissions_deferred_total": 1,
            "t_wall": 101.0,
        },
    ]
    kv = report.kv_pool_summary_from_events(events)
    # last snapshot wins for occupancy; min-free across the run gives peak
    assert kv["blocks_free_total"] == 32 and kv["prefix_hits_total"] == 9
    assert kv["engines"][0]["peak_blocks_used"] == 28
    text = report.render_report(events, name="kv-test")
    assert "serving KV memory" in text and "prefix-shared" in text

    gate = _load_script("gate")
    report_path = str(tmp_path / "report.json")
    base_path = str(tmp_path / "baseline.json")
    with open(base_path, "w") as f:
        json.dump({"kv_capacity_ratio": 4.0, "kv_capacity_ratio_target": 2.0}, f)
    # below the ABSOLUTE 2x floor -> regression even within tolerance math
    with open(report_path, "w") as f:
        json.dump({"kv_capacity_ratio": 1.5}, f)
    assert gate.main(
        ["--report", report_path, "--baseline", base_path,
         "--root", str(tmp_path)]
    ) == 1
    with open(report_path, "w") as f:
        json.dump({"kv_capacity_ratio": 4.1}, f)
    assert gate.main(
        ["--report", report_path, "--baseline", base_path,
         "--root", str(tmp_path)]
    ) == 0
