"""serving/: the continuous-batching engine and its elastic plumbing.

The load-bearing claims, in test form:

- **Bit-identity**: N requests decoded concurrently through the slot
  engine (including ones admitted mid-flight into freed slots) produce
  EXACTLY the tokens of N sequential ``models.gpt.generate`` calls with
  the cache capacity pinned to the engine's — continuous batching changes
  the schedule, never the math.
- **Fewer steps**: the engine's decode-tick count beats padded static
  batching on unequal-length workloads (``padded_static_decode_steps``
  is the foil).
- **Lifecycle + SLO**: the typed request state machine rejects illegal
  transitions, terminal requests emit one RequestEvent with the full
  latency split, and ``scripts/report.py``/``scripts/gate.py`` consume
  those events (SLO section; p99 decode-per-token regression fails the
  gate).
- **Elasticity**: the file spool's claim/complete/requeue protocol is
  idempotent and never steals a live claim; an abandoned (dead-rank)
  claim is re-queued and completed by a survivor; serving boots from a
  training checkpoint written at a different world size.
"""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from network_distributed_pytorch_tpu.models.gpt import generate, gpt_tiny
from network_distributed_pytorch_tpu.serving import (
    FINISHED,
    FileSpool,
    LifecycleError,
    Request,
    WorkloadConfig,
    poisson_workload,
    serve_from_spool,
    slo_summary,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS_DIR = os.path.join(REPO, "tests")


def _load_module(name: str, path: str):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_script(name: str):
    return _load_module(
        f"_serving_test_{name}", os.path.join(REPO, "scripts", f"{name}.py")
    )


class _CaptureTelemetry:
    def __init__(self):
        self.events = []

    def emit(self, event):
        self.events.append(event)


# --- request lifecycle (jax-free) ----------------------------------------


def test_request_lifecycle_latency_split_and_event():
    r = Request(request_id="a", prompt=[1, 2, 3], max_new_tokens=2)
    with pytest.raises(LifecycleError):
        r.mark_decoding(0.0)  # queued -> decoding skips prefill
    with pytest.raises(LifecycleError):
        r.event()  # non-terminal
    r.mark_enqueued(1.0)
    r.mark_prefilling(2.5)
    r.mark_decoding(3.0)
    r.add_token(5)
    assert not r.done
    r.add_token(6)
    assert r.done  # budget exhausted
    r.finish(4.0)
    assert r.state == FINISHED
    assert r.queue_s == 1.5 and r.prefill_s == 0.5
    assert r.decode_s == 1.0 and r.total_s == 3.0
    ev = r.event(label="t", rank=3)
    rec = ev.record()
    assert rec["event"] == "request" and rec["state"] == "finished"
    assert rec["tokens_generated"] == 2 and rec["rank"] == 3
    with pytest.raises(LifecycleError):
        r.add_token(7)  # terminal


def test_request_eos_stop_and_requeue_reset():
    r = Request(request_id="b", prompt=[1], max_new_tokens=8, eos_token_id=9)
    r.mark_enqueued(0.0)
    r.mark_prefilling(0.0)
    r.mark_decoding(0.0)
    r.add_token(4)
    r.add_token(9)
    assert r.done  # EOS, budget unspent
    fresh = r.reset_for_requeue()
    assert fresh.state == "queued" and fresh.tokens == []
    assert fresh.requeues == 1 and fresh.prompt == [1]
    # wire round-trip carries the description + requeues, not progress
    back = Request.loads(fresh.dumps())
    assert back.requeues == 1 and back.eos_token_id == 9
    assert back.tokens == [] and back.max_new_tokens == 8


# --- file spool (jax-free) ------------------------------------------------


def test_spool_ensure_claim_complete_idempotent(tmp_path):
    root = str(tmp_path / "spool")
    reqs = poisson_workload(WorkloadConfig(n_requests=3, rate_rps=0.0))
    producer = FileSpool(root)
    assert producer.ensure(reqs) == 3
    assert producer.ensure(reqs) == 0  # idempotent
    worker = FileSpool(root, rank=0, incarnation=0)
    got = worker.claim()
    assert got.request_id == reqs[0].request_id  # FIFO by id
    got.mark_enqueued(0.0)
    got.mark_prefilling(0.0)
    got.mark_decoding(0.0)
    got.add_token(1)
    got.finish(1.0)
    worker.complete(got)
    assert producer.ensure(reqs) == 0  # done requests never re-enqueue
    assert got.request_id in worker.done_ids()
    assert not worker.drained()  # two still queued
    # a duplicate queue file for a done id is dropped, not served twice
    with open(
        os.path.join(root, "queue", f"{got.request_id}.json"), "w"
    ) as f:
        json.dump(got.to_wire(), f)
    ids = {worker.claim().request_id, worker.claim().request_id}
    assert got.request_id not in ids and worker.claim() is None


def test_spool_requeue_orphans_never_steals_live_claims(tmp_path):
    root = str(tmp_path / "spool")
    reqs = poisson_workload(WorkloadConfig(n_requests=4, rate_rps=0.0))
    FileSpool(root).ensure(reqs)
    live = FileSpool(root, rank=0, incarnation=0)
    dead_peer = FileSpool(root, rank=1, incarnation=0)
    a = live.claim()
    b = dead_peer.claim()
    assert a is not None and b is not None
    # same world, everyone at their current incarnation: nothing is dead
    assert live.requeue_orphans(world=2) == 0
    # the world shrank past rank 1 AND rank 0 was restarted (incarnation
    # 1): both old claims are provably orphaned
    survivor = FileSpool(root, rank=0, incarnation=1)
    moved = survivor.requeue_orphans(world=1)
    assert moved == 2
    ids = {survivor.claim().request_id for _ in range(4)}
    assert {a.request_id, b.request_id} <= ids  # orphans are claimable again
    assert survivor.claim() is None  # queue fully drained into claims


def test_spool_requeue_skips_completed_orphans(tmp_path):
    root = str(tmp_path / "spool")
    reqs = poisson_workload(WorkloadConfig(n_requests=1, rate_rps=0.0))
    FileSpool(root).ensure(reqs)
    dying = FileSpool(root, rank=1, incarnation=0)
    r = dying.claim()
    r.mark_enqueued(0.0)
    r.mark_prefilling(0.0)
    r.mark_decoding(0.0)
    r.add_token(1)
    r.finish(1.0)
    # completion record landed but the claim-release unlink did not (crash
    # in between): the requeue must drop the claim, not duplicate the work
    doc = {
        "request_id": r.request_id, "state": r.state,
        "tokens": list(r.tokens), "tokens_generated": len(r.tokens),
        "requeues": 0, "rank": 1, "incarnation": 0,
    }
    with open(
        os.path.join(root, "done", f"{r.request_id}.json"), "w"
    ) as f:
        json.dump(doc, f)
    survivor = FileSpool(root, rank=0, incarnation=0)
    assert survivor.requeue_orphans(world=1) == 0
    assert survivor.claim() is None and survivor.drained()


# --- doc-primitive contention (the job spool rides on these) --------------


def test_spool_doc_contention_exactly_once(tmp_path):
    """N concurrent claimers (plus a scavenger hammering requeue_orphans
    with everyone alive) drain a doc workload with zero double-claims and
    zero lost entries. The claim path is one atomic os.rename per entry —
    this drives the actual race, not a serialized approximation, because
    the fleet scheduler's job admission rides on exactly these
    primitives."""
    root = str(tmp_path / "spool")
    n_docs, n_workers = 48, 8
    docs = {f"job-{i:03d}": {"doc_id": f"job-{i:03d}", "n": i}
            for i in range(n_docs)}
    assert FileSpool(root).ensure_docs(docs) == n_docs
    assert FileSpool(root).ensure_docs(docs) == 0  # idempotent

    claims = []  # (worker, entry_id) — append is atomic under the GIL
    stop = threading.Event()

    def claimer(idx):
        spool = FileSpool(root, rank=idx, incarnation=0)
        while not stop.is_set():
            got = spool.claim_doc()
            if got is None:
                # empty OR every rename race lost this pass — poll again
                # until the drain flag says the workload is done
                time.sleep(0.001)
                continue
            entry_id, doc = got
            claims.append((idx, entry_id))
            spool.complete_doc(entry_id, dict(doc, state="done", by=idx))

    def scavenger():
        # all ranks < world and at their live incarnation: every
        # requeue_orphans call must find nothing to steal, even racing
        # against in-flight renames
        spool = FileSpool(root, rank=0, incarnation=0)
        while not stop.is_set():
            assert spool.requeue_orphans(world=n_workers) == 0
            time.sleep(0.001)

    threads = [
        threading.Thread(target=claimer, args=(i,)) for i in range(n_workers)
    ] + [threading.Thread(target=scavenger)]
    for t in threads:
        t.start()
    check = FileSpool(root)
    deadline = time.monotonic() + 60.0
    while not check.drained():
        assert time.monotonic() < deadline, "spool failed to drain"
        time.sleep(0.005)
    stop.set()
    for t in threads:
        t.join(timeout=10.0)
        assert not t.is_alive()

    claimed_ids = [entry_id for _, entry_id in claims]
    assert len(claimed_ids) == n_docs, "an entry was claimed twice or lost"
    assert set(claimed_ids) == set(docs)
    done = check.done_records()
    assert set(done) == set(docs)
    # every completion names the worker whose claim produced it
    by_worker = {e: w for w, e in claims}
    for entry_id, doc in done.items():
        assert doc["by"] == by_worker[entry_id]


def test_spool_doc_release_reclaim_roundtrip(tmp_path):
    """release_doc parks a live claim back onto the queue with an updated
    document — the fleet scheduler's preempt/park path. The re-claimed doc
    carries the update, the manifest never changes, and drained() stays
    False until the entry actually completes."""
    root = str(tmp_path / "spool")
    docs = {"only": {"doc_id": "only", "steps_done": 0}}
    FileSpool(root).ensure_docs(docs)
    first = FileSpool(root, rank=0, incarnation=0)
    entry_id, doc = first.claim_doc()
    assert entry_id == "only"
    first.release_doc(entry_id, dict(doc, steps_done=7))  # park
    assert not first.drained()
    # parked entries are invisible to requeue_orphans (already queued)
    assert first.requeue_orphans(world=1) == 0
    second = FileSpool(root, rank=0, incarnation=1)
    entry_id2, doc2 = second.claim_doc()
    assert entry_id2 == "only" and doc2["steps_done"] == 7  # resume state
    assert first.manifest_ids() == ["only"]
    second.complete_doc(entry_id2, dict(doc2, state="done"))
    assert second.drained()


_STALLED_CLAIMER_SRC = """\
import os, sys, time
from network_distributed_pytorch_tpu.serving import FileSpool

root, trigger = sys.argv[1], sys.argv[2]
spool = FileSpool(root, rank=1, incarnation=0)
got = None
deadline = time.monotonic() + 30.0
while got is None and time.monotonic() < deadline:
    got = spool.claim_doc()
    time.sleep(0.005)
assert got is not None, "claimer never won the claim"
print("CLAIMED", flush=True)
while not os.path.exists(trigger):
    time.sleep(0.005)
entry_id, doc = got
spool.release_doc(entry_id, dict(doc, parked_by="stalled-claimer"))
print("RELEASED", flush=True)
"""


def test_spool_release_racing_requeue_sigstopped_claimer(tmp_path):
    """The partition-shaped race the fleet scheduler must survive: a
    claimer stalls (SIGSTOP — alive, not dead), the world shrinks past its
    rank, a survivor's ``requeue_orphans`` lawfully takes the claim, and
    the stalled worker then resumes and tries to ``release_doc`` a claim
    it no longer owns. The late release must no-op — exactly one live
    copy of the entry stays in circulation (no double-claim) and the
    requeue's bookkeeping (the incremented ``requeues`` count) survives
    instead of being overwritten by the staller's parked copy."""
    root = str(tmp_path / "spool")
    trigger = str(tmp_path / "release-now")
    FileSpool(root).ensure_docs({"only": {"doc_id": "only", "requeues": 0}})

    script = str(tmp_path / "stalled_claimer.py")
    with open(script, "w") as f:
        f.write(_STALLED_CLAIMER_SRC)
    env = dict(os.environ, PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, script, root, trigger],
        stdout=subprocess.PIPE, text=True, env=env,
    )
    try:
        assert proc.stdout.readline().strip() == "CLAIMED"
        os.kill(proc.pid, signal.SIGSTOP)

        survivor = FileSpool(root, rank=0, incarnation=0)
        # at world=2 the stalled rank 1 is a LIVE identity — untouchable
        assert survivor.requeue_orphans(world=2) == 0
        # the world shrank past it: the claim is provably orphaned
        assert survivor.requeue_orphans(world=1) == 1

        # resume the staller and let its release_doc race to the finish
        with open(trigger, "w") as f:
            f.write("go")
        os.kill(proc.pid, signal.SIGCONT)
        assert proc.stdout.readline().strip() == "RELEASED"
        assert proc.wait(timeout=30.0) == 0
    finally:
        try:
            os.kill(proc.pid, signal.SIGCONT)
        except OSError:
            pass
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10.0)

    # exactly one live copy: the requeued doc, its bookkeeping intact
    queued = sorted(os.listdir(os.path.join(root, "queue")))
    assert queued == ["only.json"]
    with open(os.path.join(root, "queue", "only.json")) as f:
        doc = json.load(f)
    assert doc["requeues"] == 1
    assert "parked_by" not in doc  # the stolen claim's release no-oped
    # no claim-side residue anywhere (including .releasing proof files)
    claimed_root = os.path.join(root, "claimed")
    residue = [
        os.path.join(d, n)
        for d in sorted(os.listdir(claimed_root))
        for n in os.listdir(os.path.join(claimed_root, d))
    ]
    assert residue == []
    # the entry is claimable exactly once, then the spool drains normally
    reclaimer = FileSpool(root, rank=0, incarnation=1)
    entry_id, doc2 = reclaimer.claim_doc()
    assert entry_id == "only" and doc2["requeues"] == 1
    assert reclaimer.claim_doc() is None
    reclaimer.complete_doc(entry_id, dict(doc2, state="done"))
    assert reclaimer.drained()


# --- toy-engine fail-over (jax-free, the probe's fast twin) ---------------


def test_toy_serving_failover_requeues_and_completes(tmp_path):
    toy = _load_module(
        "_toy_serving_under_test",
        os.path.join(TESTS_DIR, "toy_serving_worker.py"),
    )
    root = str(tmp_path / "spool")
    reqs = poisson_workload(
        WorkloadConfig(n_requests=6, rate_rps=0.0, max_new_tokens=(3, 6))
    )
    FileSpool(root).ensure(reqs)
    # rank 1 claims two requests and "dies" mid-decode: ticks once, never
    # completes, abandons its claims on the floor
    dying_spool = FileSpool(root, rank=1, incarnation=0)
    dying = toy.ToyEngine(2, rank=1)
    for _ in range(2):
        dying.submit(dying_spool.claim())
    dying.step()
    assert dying.n_active >= 1  # genuinely mid-decode
    # the supervisor degrades the world to 1; the survivor restarts at a
    # new incarnation and the serve loop re-queues the orphans
    cap = _CaptureTelemetry()
    spool = FileSpool(root, rank=0, incarnation=1)
    engine = toy.ToyEngine(2, telemetry=cap, rank=0)
    served = serve_from_spool(engine, spool, world=1, max_wall_s=30.0)
    assert served["completed"] == 6 and served["requeued_orphans"] == 2
    check = FileSpool(root)
    assert set(check.done_ids()) == set(check.manifest_ids())
    records = check.done_records()
    assert sum(r["requeues"] for r in records.values()) == 2
    # fail-over preserved determinism: every completion carries exactly
    # the token sequence the toy decoder defines for that request alone
    for req in reqs:
        want, probe = [], Request.from_wire(req.to_wire())
        probe.mark_enqueued(0.0)
        probe.mark_prefilling(0.0)
        probe.mark_decoding(0.0)
        while not probe.done:
            probe.add_token(toy.toy_token(probe))
        assert records[req.request_id]["tokens"] == probe.tokens
    # one terminal RequestEvent per completion went through telemetry
    recs = [e.record() for e in cap.events]
    assert len(recs) == 6
    assert all(r["event"] == "request" and r["state"] == "finished"
               for r in recs)
    slo = slo_summary(served["requests"])
    assert slo["n_finished"] == 6 and slo["total_tokens"] > 0


# --- the jax engine: bit-identity and step accounting ---------------------


def _serving_model(max_len=16):
    model = gpt_tiny(vocab_size=64, max_position_embeddings=max_len)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, max_len), jnp.int32)
    )["params"]
    return model, params


def test_engine_bit_identical_to_sequential_generate(devices):
    from network_distributed_pytorch_tpu.serving.engine import SlotEngine

    max_len = 16
    model, params = _serving_model(max_len)
    rng = np.random.RandomState(1)
    reqs = []
    for i, budget in enumerate((4, 6, 3, 5, 4)):
        prompt = [int(t) for t in rng.randint(0, 64, rng.randint(2, 7))]
        reqs.append(
            Request(
                request_id=f"req-{i:04d}", prompt=prompt,
                max_new_tokens=budget,
            )
        )
    engine = SlotEngine(model.config, params, n_slots=2, max_len=max_len)
    # three submitted up front; two more admitted MID-FLIGHT into slots
    # freed by earlier completions — the continuous-batching schedule
    for r in reqs[:3]:
        engine.submit(r)
    engine.step()
    engine.step()
    for r in reqs[3:]:
        engine.submit(r)
    finished = engine.run(max_steps=200)
    assert len(finished) == len(reqs)
    assert all(r.state == FINISHED for r in finished)
    for r in reqs:
        ref = generate(
            model.config, params, jnp.asarray([r.prompt], jnp.int32),
            r.max_new_tokens, cache_len=max_len,
        )
        np.testing.assert_array_equal(
            np.asarray(r.tokens, np.int32), np.asarray(ref[0])
        )


def test_continuous_batching_beats_padded_static(devices):
    from network_distributed_pytorch_tpu.serving.engine import (
        SlotEngine,
        padded_static_decode_steps,
    )

    model, params = _serving_model(16)
    budgets = [8, 2, 2, 2]
    cap = _CaptureTelemetry()
    engine = SlotEngine(
        model.config, params, n_slots=2, max_len=16, telemetry=cap, rank=0
    )
    for i, n in enumerate(budgets):
        engine.submit(
            Request(request_id=f"r{i}", prompt=[1 + i, 2, 3],
                    max_new_tokens=n)
        )
    finished = engine.run(max_steps=100)
    assert len(finished) == 4
    # padded static batching decodes each arrival-order pair in lockstep
    # to its longest member: (8,2) -> 7 ticks, (2,2) -> 1 tick
    static = padded_static_decode_steps(budgets, batch=2)
    assert static == 8
    # the engine backfills freed slots every tick, so the short requests
    # ride along with the long one instead of forcing extra groups
    assert engine.decode_steps == 7 < static
    assert engine.prefills == 4
    assert len(cap.events) == 4  # one terminal RequestEvent each


def test_padded_static_decode_steps_edge_cases():
    from network_distributed_pytorch_tpu.serving.engine import (
        padded_static_decode_steps,
    )

    assert padded_static_decode_steps([], 4) == 0
    assert padded_static_decode_steps([1, 1, 1], 2) == 0  # prefill-only
    assert padded_static_decode_steps([5], 1) == 4
    with pytest.raises(ValueError):
        padded_static_decode_steps([3], 0)


def test_engine_evict_all_emits_and_requeues(devices):
    from network_distributed_pytorch_tpu.serving.engine import SlotEngine

    model, params = _serving_model(16)
    cap = _CaptureTelemetry()
    engine = SlotEngine(
        model.config, params, n_slots=1, max_len=16, telemetry=cap
    )
    for i in range(2):
        engine.submit(
            Request(request_id=f"e{i}", prompt=[1, 2], max_new_tokens=6)
        )
    engine.step()  # one admitted + ticked, one still queued
    evicted = engine.evict_all(reason="shutdown")
    assert len(evicted) == 2 and engine.idle
    assert {e.record()["state"] for e in cap.events} == {"evicted"}
    fresh = [r.reset_for_requeue() for r in evicted]
    assert all(f.requeues == 1 and f.tokens == [] for f in fresh)


# --- checkpoint hot-load --------------------------------------------------


def test_restore_serving_params_across_world_sizes(devices, tmp_path):
    from network_distributed_pytorch_tpu.parallel.reducers import ExactReducer
    from network_distributed_pytorch_tpu.parallel.trainer import (
        init_train_state,
    )
    from network_distributed_pytorch_tpu.resilience.reshard import (
        make_topology,
    )
    from network_distributed_pytorch_tpu.serving.cache import (
        restore_serving_params,
    )
    from network_distributed_pytorch_tpu.utils.checkpoint import (
        save_checkpoint,
    )

    model, trained = _serving_model(16)
    root = str(tmp_path / "ckpt")
    assert restore_serving_params(root, trained) is None  # nothing yet
    # a 4-rank training fleet checkpoints its state (per-worker memories
    # carry the leading world axis) with the topology tag
    state = init_train_state(trained, ExactReducer(), num_devices=4)
    save_checkpoint(root, state, step=7, topology=make_topology(4))
    # serving boots single-process from different (fresh) params: the
    # widened template reads the 4-rank checkpoint, params come back
    # bit-identical to what training wrote
    fresh = jax.tree_util.tree_map(jnp.zeros_like, trained)
    restored = restore_serving_params(root, fresh)
    assert restored is not None
    params, step = restored
    assert step == 7
    for got, want in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(trained)
    ):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# --- the launcher entry + report/gate plumbing ----------------------------


def test_serve_gpt_entry_in_process(devices, tmp_path):
    from network_distributed_pytorch_tpu.experiments import serve_gpt

    out = serve_gpt.run(
        preset="small", slots=2, requests=4, request_rate=0.0,
        max_new_tokens=6,
    )
    assert out["experiment"] == "serve_gpt" and out["mode"] == "in_process"
    slo = out["slo"]
    assert slo["n_finished"] == 4 and slo["n_evicted"] == 0
    assert out["prefills"] == 4
    assert out["decode_steps"] <= out["padded_static_decode_steps"]
    assert slo["p99_decode_ms_per_token"] is None or (
        slo["p99_decode_ms_per_token"] > 0
    )


def test_serve_gpt_launch_flags_rejected_elsewhere():
    from network_distributed_pytorch_tpu.launch import main

    with pytest.raises(ValueError, match="--slots is not supported"):
        main(["gpt_generate", "--slots", "2"])
    with pytest.raises(ValueError, match="--spool-dir is not supported"):
        main(["gpt_lm", "--spool-dir", "/tmp/x"])


def test_report_renders_slo_section_and_gate_fails_on_regression(tmp_path):
    report = _load_script("report")
    events = []
    for i, decode_s in enumerate((0.010, 0.012, 0.200)):
        events.append({
            "event": "request", "request_id": f"req-{i:04d}",
            "state": "finished", "label": "t", "rank": 0,
            "prompt_tokens": 4, "tokens_generated": 11,
            "queue_s": 0.001, "prefill_s": 0.002, "decode_s": decode_s,
            "total_s": 0.003 + decode_s, "requeues": 1 if i == 2 else 0,
            "t_wall": 100.0 + i,
        })
    events.append({
        "event": "request", "request_id": "req-0099", "state": "evicted",
        "label": "t", "rank": 0, "prompt_tokens": 4, "tokens_generated": 2,
        "requeues": 0, "t_wall": 104.0,
    })
    slo = report.slo_summary_from_events(events)
    assert slo["n_requests"] == 4 and slo["n_finished"] == 3
    assert slo["n_evicted"] == 1 and slo["requeues"] == 1
    # nearest-rank p99 of 3 samples = the max; 10 decode ticks per request
    assert slo["p99_decode_ms_per_token"] == pytest.approx(20.0)
    text = report.render_report(events, name="slo-test")
    assert "serving SLO" in text and "requeue(s) survived" in text

    gate = _load_script("gate")
    report_path = str(tmp_path / "report.json")
    base_path = str(tmp_path / "baseline.json")
    with open(report_path, "w") as f:
        json.dump({"slo": slo}, f)
    with open(base_path, "w") as f:
        json.dump({"p99_decode_ms_per_token": 2.0}, f)  # flat baseline form
    # 20 ms/token vs baseline 2: way past tolerance -> exit 1
    rc = gate.main(
        ["--report", report_path, "--baseline", base_path,
         "--root", str(tmp_path)]
    )
    assert rc == 1
    # matching baseline passes
    with open(base_path, "w") as f:
        json.dump({"slo": {"p99_decode_ms_per_token": 19.0}}, f)
    rc = gate.main(
        ["--report", report_path, "--baseline", base_path,
         "--root", str(tmp_path)]
    )
    assert rc == 0
