"""Hierarchical (ICI-exact / DCN-compressed) reduction on a 2-D mesh:
equivalence with flat exact, oracle parity for the compressed outer phase,
byte-exact wire accounting vs the compiled HLO, and end-to-end training."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from network_distributed_pytorch_tpu.parallel import (
    ExactReducer,
    HierarchicalReducer,
    PowerSGDReducer,
    make_hierarchical_train_fn,
    make_mesh,
)
from network_distributed_pytorch_tpu.parallel.localsgd import (
    make_diloco_train_fn,
)
from network_distributed_pytorch_tpu.parallel.trainer import (
    LOSS_SYNC_BITS,
    make_train_step,
    stateless_loss,
)

N_DCN, N_ICI = 2, 4


def _mesh2d():
    return make_mesh(axis_sizes=(N_DCN, N_ICI), axis_names=("dcn", "ici"))


def _problem():
    rng = np.random.RandomState(0)
    w_true = rng.randn(16, 4).astype(np.float32)
    x = rng.randn(64, 16).astype(np.float32)
    y = x @ w_true
    params = {"w": jnp.zeros((16, 4)), "b": jnp.zeros((4,))}

    def loss(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] + p["b"] - yb) ** 2)

    return params, stateless_loss(loss), (jnp.asarray(x), jnp.asarray(y))


def _train(step, params, batch, steps=12):
    state = step.init_state(params)
    losses = []
    for _ in range(steps):
        state, l = step(state, batch)
        losses.append(float(l))
    return state, losses


def test_hierarchical_exact_equals_flat_exact(devices):
    """Exact-in-exact hierarchy == flat 8-worker exact DDP (mean of group
    means over equal groups is the global mean), loss-for-loss and
    param-for-param."""
    params, loss_fn, batch = _problem()
    mesh2d = _mesh2d()
    hier = make_train_step(
        loss_fn,
        HierarchicalReducer(ExactReducer(), mesh2d, "ici", "dcn"),
        params, 0.05, 0.9, "sgd", mesh=mesh2d, axis_name=("dcn", "ici"),
        donate_state=False,
    )
    flat = make_train_step(
        loss_fn, ExactReducer(), params, 0.05, 0.9, "sgd",
        mesh=make_mesh(), donate_state=False,
    )
    hs, hl = _train(hier, params, batch)
    fs, fl = _train(flat, params, batch)
    np.testing.assert_allclose(hl, fl, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(hs.params["w"]), np.asarray(fs.params["w"]), rtol=1e-6
    )


def test_hierarchical_powersgd_matches_group_mean_oracle(devices):
    """One hierarchical PowerSGD reduction == flat PowerSGD over N_DCN
    workers whose sends are the ICI-group means (computed host-side): the
    inner phase must be exactly an averaging preprocessor."""
    rng = np.random.RandomState(1)
    per_worker = [
        {"w": rng.randn(16, 4).astype(np.float32)} for _ in range(N_DCN * N_ICI)
    ]
    template = {"w": jnp.zeros((16, 4))}
    outer = PowerSGDReducer(compression_rank=2, matricize="last")
    mesh2d = _mesh2d()
    hier = HierarchicalReducer(outer, mesh2d, "ici", "dcn")

    stacked = {"w": jnp.asarray(np.stack([s["w"] for s in per_worker]))}

    def hier_reduce(send):
        st = hier.init(template)
        _, out, _, _ = hier.reduce(st, send, ("dcn", "ici"))
        return out

    out_h = jax.jit(
        jax.shard_map(
            lambda s: hier_reduce({"w": s["w"][0]})["w"][None],
            mesh=mesh2d,
            in_specs=(P(("dcn", "ici")),),
            out_specs=P(("dcn", "ici")),
        )
    )(stacked)

    # oracle: flat PowerSGD over N_DCN workers on the group means, using a
    # 2-device mesh (same code path, smaller world)
    means = np.stack([
        np.mean([per_worker[d * N_ICI + i]["w"] for i in range(N_ICI)], axis=0)
        for d in range(N_DCN)
    ])
    mesh1d = make_mesh(
        axis_sizes=(N_DCN,), axis_names=("dcn",), devices=jax.devices()[:N_DCN]
    )

    def flat_reduce(send):
        st = outer.init(template)
        _, out, _, _ = outer.reduce(st, send, "dcn")
        return out

    out_f = jax.jit(
        jax.shard_map(
            lambda s: flat_reduce({"w": s["w"][0]})["w"][None],
            mesh=mesh1d,
            in_specs=(P("dcn"),),
            out_specs=P("dcn"),
        )
    )({"w": jnp.asarray(means)})

    np.testing.assert_allclose(
        np.asarray(out_h)[0], np.asarray(out_f)[0], rtol=1e-5, atol=1e-6
    )


def test_hierarchical_bits_accounting_hlo_exact(devices):
    """Analytic bits (inner exact + outer compressed + loss sync) must equal
    the compiled 2-D-mesh step's collective payloads byte-exactly."""
    from network_distributed_pytorch_tpu.utils.hlo_audit import (
        collective_summary,
        compiled_hlo_text,
    )

    params, loss_fn, batch = _problem()
    mesh2d = _mesh2d()
    reducer = HierarchicalReducer(
        PowerSGDReducer(compression_rank=2, matricize="last"), mesh2d,
        "ici", "dcn",
    )
    step = make_train_step(
        loss_fn, reducer, params, 0.05, 0.9, "ef_momentum",
        mesh=mesh2d, axis_name=("dcn", "ici"), donate_state=False,
    )
    state = step.init_state(params)
    s = collective_summary(compiled_hlo_text(step.fn, state, batch))
    assert s["total_payload_bytes"] == step.bits_per_step // 8, s["by_kind"]
    by_fabric = reducer.bits_by_fabric(params)
    assert step.bits_per_step == (
        by_fabric["inner"] + by_fabric["outer"] + LOSS_SYNC_BITS
    )
    # the slow-fabric share is the compressed one (tiny test matrices give
    # modest ratios; real models reach the usual PowerSGD 10-100x)
    assert by_fabric["outer"] < by_fabric["inner"]


def test_hierarchical_powersgd_trains(devices):
    params, loss_fn, batch = _problem()
    mesh2d = _mesh2d()
    step = make_train_step(
        loss_fn,
        HierarchicalReducer(
            PowerSGDReducer(compression_rank=2, matricize="last"), mesh2d,
            "ici", "dcn",
        ),
        params, 0.05, 0.9, "ef_momentum", mesh=mesh2d,
        axis_name=("dcn", "ici"), donate_state=False,
    )
    _, losses = _train(step, params, batch, steps=30)
    assert losses[-1] < 0.2 * losses[0], losses


# ---------------------------------------------------------------------------
# the compiled two-level round (make_hierarchical_train_fn)
# ---------------------------------------------------------------------------


def _round_batches(batch, sync_every):
    return jax.tree_util.tree_map(
        lambda b: jnp.broadcast_to(b, (sync_every,) + b.shape), batch
    )


def _worker_copies(state):
    return np.asarray(state.params["w"])  # (n_workers, ...) per-worker view


def _hier(params, loss_fn, sync=4, **over):
    kw = dict(
        inner_learning_rate=0.05, outer_learning_rate=1.0,
        outer_momentum=0.0, outer_nesterov=False, sync_every=sync,
        inner_algorithm="sgd_plain", mesh=_mesh2d(), outer_async=False,
        donate_state=False,
    )
    kw.update(over)
    return make_hierarchical_train_fn(loss_fn, params, **kw)


def test_train_fn_sync_exact_is_site_averaging(devices):
    """outer_async=False + exact outer + outer lr 1 / momentum 0 IS
    hierarchical parameter averaging — the same trajectory as flat DiLoCo
    over 2 workers that each hold one SITE's data (a site reducing exactly
    every step behaves as one worker on the site-mean gradient). And sites
    never diverge at a sync point: every per-worker copy leaves the round
    equal to the new anchor."""
    params, loss_fn, batch = _problem()
    sync = 4
    step = _hier(params, loss_fn, sync=sync)
    batches = _round_batches(batch, sync)

    oracle = make_diloco_train_fn(
        loss_fn, params, inner_learning_rate=0.05, outer_learning_rate=1.0,
        outer_momentum=0.0, outer_nesterov=False, sync_every=sync,
        inner_algorithm="sgd_plain",
        mesh=make_mesh(
            axis_sizes=(N_DCN,), axis_names=("dcn",),
            devices=jax.devices()[:N_DCN],
        ),
        axis_name="dcn", donate_state=False,
    )

    state, ostate = step.init_state(params), oracle.init_state(params)
    for _ in range(3):
        state, site_losses = step(state, batches)
        ostate, o_losses = oracle(ostate, batches)
        np.testing.assert_allclose(
            np.asarray(site_losses).mean(axis=0), np.asarray(o_losses),
            rtol=1e-5, atol=1e-6,
        )
        copies = _worker_copies(state)
        for k in range(1, copies.shape[0]):  # no divergence at the sync point
            np.testing.assert_array_equal(copies[0], copies[k])
    np.testing.assert_allclose(
        np.asarray(step.eval_params(state)["w"]),
        np.asarray(oracle.eval_params(ostate)["w"]),
        rtol=1e-5, atol=1e-6,
    )


def test_train_fn_async_matches_sync_quality(devices):
    """The delayed-gradient recipe: one-round-stale outer updates converge
    at sync-mode quality (loss-level tolerance, NOT a bitwise trajectory
    claim — see DESIGN's guarantee classes)."""
    params, loss_fn, batch = _problem()
    recipe = dict(
        outer_learning_rate=0.5, outer_momentum=0.0, outer_nesterov=False
    )
    sync_step = _hier(params, loss_fn, **recipe)
    async_step = _hier(params, loss_fn, outer_async=True, **recipe)
    batches = _round_batches(batch, 4)

    finals = {}
    for name, step in (("sync", sync_step), ("async", async_step)):
        state = step.init_state(params)
        first = None
        for _ in range(12):
            state, losses = step(state, batches)
            if first is None:
                first = float(np.asarray(losses).mean())
        finals[name] = float(np.asarray(losses).mean())
        assert finals[name] < 0.4 * first, (name, first, finals[name])
    # one-round-stale updates cost at most one round of progress
    assert finals["async"] <= 1.1 * finals["sync"] + 1e-4, finals
    # async hides time, never traffic: same per-round wire bill
    assert async_step.bits_per_round == sync_step.bits_per_round
    assert async_step.outer_bits_per_step * async_step.sync_every == (
        async_step.outer_bits_per_round
    )


def test_train_fn_partition_local_rounds_and_rejoin(devices):
    """The game day in miniature: sync rounds, then a partition survived
    with local_round (sites step independently but stay EXACT within a
    site), then a healing sync whose EF catch-up lands the run within the
    divergence budget of a never-partitioned oracle — and re-synchronizes
    every copy bitwise."""
    params, loss_fn, batch = _problem()
    step = _hier(params, loss_fn)
    batches = _round_batches(batch, 4)

    oracle = step.init_state(params)
    for _ in range(6):
        oracle, o_losses = step(oracle, batches)

    state = step.init_state(params)
    for _ in range(2):
        state, _l = step(state, batches)
    for _ in range(2):  # the partition: no cross-site collective at all
        state, _l = step(state, batches, local=True)
        copies = _worker_copies(state)
        for site in range(N_DCN):  # within a site the inner path stays exact
            base = site * N_ICI
            for k in range(1, N_ICI):
                np.testing.assert_array_equal(copies[base], copies[base + k])
        assert np.any(copies[0] != copies[N_ICI]), (
            "sites did not diverge during the partition — the local round "
            "is not actually site-local (or the data is degenerate)"
        )
    for _ in range(2):  # heal: the first sync is the rejoin
        state, p_losses = step(state, batches)
    copies = _worker_copies(state)
    for k in range(1, copies.shape[0]):  # rejoin re-synchronizes bitwise
        np.testing.assert_array_equal(copies[0], copies[k])

    final_part = float(np.asarray(p_losses).mean())
    final_oracle = float(np.asarray(o_losses).mean())
    assert final_part <= 2.0 * final_oracle + 1e-3, (final_part, final_oracle)
