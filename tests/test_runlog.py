"""Run-level observability: manifest, shard merger, analytics, gate.

The unit tests pin the clock-alignment math on synthetic shards (a rank
whose wall clock is 3 s ahead must still interleave correctly in the merged
timeline) and the straggler/bandwidth analytics on hand-built events. The
end-to-end test is the ISSUE's acceptance bar: a 4-rank supervised toy run
with one SIGKILLed rank and one synthetically slow rank produces per-rank
shards plus a manifest; ``report.py --run-dir`` merges them into one
timeline with a straggler verdict and per-collective bandwidth utilization;
and ``gate.py`` passes the recorded run but fails a synthetically
regressed copy. Everything here is jax-free.
"""

import importlib.util
import json
import os
import sys

import pytest

from network_distributed_pytorch_tpu.observe import analytics, runlog
from network_distributed_pytorch_tpu.resilience import (
    ChaosPlan,
    FaultSpec,
    Supervisor,
    SupervisorConfig,
)
from network_distributed_pytorch_tpu.observe import telemetry_for_run

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS_DIR)
TOY = os.path.join(TESTS_DIR, "toy_supervised_worker.py")


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        f"_runlog_test_{name}", os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[f"_runlog_test_{name}"] = mod
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------


def test_manifest_roundtrip(tmp_path):
    m = runlog.new_manifest("runA", world_size=2)
    m.record_spawn(rank=0, incarnation=0, world_size=2, spawned_unix=100.0)
    m.record_spawn(rank=1, incarnation=0, world_size=2, spawned_unix=100.5)
    m.record_spawn(rank=1, incarnation=1, world_size=2, spawned_unix=103.0)
    m.save(str(tmp_path))

    back = runlog.RunManifest.load(str(tmp_path))
    assert back.run_id == "runA"
    assert back.world_size == 2
    # JSON forces string keys; load() must restore ints
    assert back.shards == {0: "events_rank0.jsonl", 1: "events_rank1.jsonl"}
    assert back.incarnations == {0: 1, 1: 2}
    assert back.spawn_time(1, 1) == 103.0
    assert back.spawn_time(1, 7) is None


def test_marker_and_shard_from_env(tmp_path):
    env = {
        runlog.ENV_RUN_DIR: str(tmp_path),
        runlog.ENV_RUN_ID: "runB",
        "RESILIENCE_RANK": "3",
        "RESILIENCE_WORLD": "4",
        "RESILIENCE_INCARNATION": "1",
    }
    marker = runlog.run_marker_from_env(env)
    assert marker is not None
    assert (marker.run_id, marker.rank, marker.world_size,
            marker.incarnation) == ("runB", 3, 4, 1)
    assert runlog.shard_event_log_from_env(env) == str(
        tmp_path / "events_rank3.jsonl"
    )
    # outside a managed run: no marker, no shard
    assert runlog.run_marker_from_env({}) is None
    assert runlog.shard_event_log_from_env({}) is None


# ---------------------------------------------------------------------------
# the merger
# ---------------------------------------------------------------------------


def _write_shard(path, events):
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")


def _synthetic_run(tmp_path, rank1_clock_offset=3.0):
    """Two ranks spawned simultaneously (parent clock 1000.0); rank 1's
    wall clock runs ``rank1_clock_offset`` seconds ahead. Steps genuinely
    interleave in real time: rank0 at +0.1/+0.3, rank1 at +0.2/+0.4."""
    m = runlog.new_manifest("sync", world_size=2)
    m.record_spawn(rank=0, incarnation=0, world_size=2, spawned_unix=1000.0)
    m.record_spawn(rank=1, incarnation=0, world_size=2, spawned_unix=1000.0)
    m.save(str(tmp_path))
    off = rank1_clock_offset
    _write_shard(
        runlog.shard_path(str(tmp_path), 0),
        [
            {"event": "marker", "kind": "run_start", "incarnation": 0,
             "ts": 1000.1, "ts_mono": 50.0},
            {"event": "step", "step": 0, "step_time_s": 0.1,
             "ts": 1000.2, "ts_mono": 50.1},
            {"event": "step", "step": 1, "step_time_s": 0.1,
             "ts": 1000.4, "ts_mono": 50.3},
        ],
    )
    _write_shard(
        runlog.shard_path(str(tmp_path), 1),
        [
            {"event": "marker", "kind": "run_start", "incarnation": 0,
             "ts": 1000.1 + off, "ts_mono": 80.0},
            {"event": "step", "step": 0, "step_time_s": 0.1,
             "ts": 1000.3 + off, "ts_mono": 80.2},
            {"event": "step", "step": 1, "step_time_s": 0.1,
             "ts": 1000.5 + off, "ts_mono": 80.4},
        ],
    )
    return m


def test_merge_corrects_skewed_clock(tmp_path):
    """Rank 1's wall clock is 3 s ahead; sorting by raw ``ts`` would dump
    all its events after rank 0's. The marker-anchored merge recovers the
    true interleaving and reports the offset."""
    _synthetic_run(tmp_path, rank1_clock_offset=3.0)
    merged = runlog.merge_run(str(tmp_path))

    steps = [e for e in merged.events if e.get("event") == "step"]
    assert [e["rank"] for e in steps] == [0, 1, 0, 1]
    # per-spawn deltas are [0.1, 3.1]; the median picks the honest rank's
    # startup, so rank 0 reads as offset 0 and rank 1 as +3 s
    assert merged.startup_s == pytest.approx(0.1)
    assert merged.per_rank[0]["clock_offset_s"] == pytest.approx(0.0)
    assert merged.per_rank[1]["clock_offset_s"] == pytest.approx(3.0)
    # aligned times are on the parent clock
    assert steps[0]["t_run"] == pytest.approx(1000.2)
    assert steps[1]["t_run"] == pytest.approx(1000.3)
    # raw-ts ordering really is wrong — the correction is load-bearing
    raw = sorted(steps, key=lambda e: e["ts"])
    assert [e["rank"] for e in raw] == [0, 0, 1, 1]


def test_merge_falls_back_to_offset_corrected_ts(tmp_path):
    """Events lacking ``ts_mono`` (pre-existing logs, STAMP_TS opt-outs
    with a manual ts) still land via ``ts - offset``."""
    _synthetic_run(tmp_path, rank1_clock_offset=3.0)
    # strip ts_mono from rank 1's step events only
    path = runlog.shard_path(str(tmp_path), 1)
    evs, _ = runlog.load_shard(path)
    for e in evs:
        if e["event"] == "step":
            e.pop("ts_mono")
    _write_shard(path, evs)

    merged = runlog.merge_run(str(tmp_path))
    steps = [e for e in merged.events if e.get("event") == "step"]
    assert [e["rank"] for e in steps] == [0, 1, 0, 1]
    assert steps[1]["t_run"] == pytest.approx(1000.3)


def test_merge_tolerates_torn_tail_and_missing_shard(tmp_path):
    m = _synthetic_run(tmp_path, rank1_clock_offset=0.0)
    # a SIGKILLed rank's half-written final line
    with open(runlog.shard_path(str(tmp_path), 0), "a") as f:
        f.write('{"event": "step", "step": 2, "ts": 1000.6, "step_t')
    # and a third rank whose shard never appeared
    m.record_spawn(rank=2, incarnation=0, world_size=3, spawned_unix=1000.0)
    m.save(str(tmp_path))

    merged = runlog.merge_run(str(tmp_path))
    assert merged.torn_lines == 1
    assert merged.per_rank[0]["torn_lines"] == 1
    assert merged.per_rank[2]["missing"] is True
    # the readable events all survived
    assert sum(1 for e in merged.events if e.get("event") == "step") == 4


# ---------------------------------------------------------------------------
# analytics
# ---------------------------------------------------------------------------


def _step(rank, i, dt):
    return {"event": "step", "rank": rank, "step": i, "step_time_s": dt}


def test_straggler_detection_flags_slow_rank():
    events = [
        _step(r, i, 0.08 if r == 2 else 0.01)
        for r in range(4) for i in range(6)
    ]
    stats = analytics.rank_step_stats(events)
    assert stats[0]["n"] == 5  # first timed step dropped (compile-ish)
    flags = analytics.detect_stragglers(stats, factor=1.5)
    assert [ev.rank for ev in flags] == [2]
    ev = flags[0]
    assert ev.factor == pytest.approx(8.0)
    assert "rank 2" in ev.banner() and "8.00x" in ev.banner()
    # the event round-trips through the telemetry record contract
    rec = ev.record()
    assert rec["event"] == "straggler" and rec["rank"] == 2


def test_straggler_detection_needs_quorum():
    # a single rank can't straggle relative to itself
    events = [_step(0, i, 0.08) for i in range(6)]
    stats = analytics.rank_step_stats(events)
    assert analytics.detect_stragglers(stats, factor=1.5) == []


def test_effective_bandwidth_dedupes_replicated_ledger():
    """Every rank (and every incarnation) re-emits the same wire ledger;
    the estimator must count each collective once, not world_size times."""
    coll = {
        "event": "collective", "label": "toy", "tag": "toy.grads",
        "op": "all-reduce", "dtype": "float32", "payload_bytes": 1 << 20,
        "count": 1,
    }
    out = analytics.effective_bandwidth(
        step_time_s=0.01,
        collectives=[dict(coll, rank=r) for r in range(4)],
        n_workers=4,
    )
    assert out["total"]["payload_bytes"] == 1 << 20
    assert out["total"]["achieved_bytes_per_s"] == pytest.approx((1 << 20) / 0.01)
    # utilization is achieved / line rate for every fabric in the table
    for fabric, rate in analytics.FABRICS_BYTES_PER_S.items():
        assert out["total"]["utilization"][fabric] == pytest.approx(
            (1 << 20) / 0.01 / rate
        )
    # overlap evidence shrinks the comm budget and raises achieved rate
    overlap = {
        "n_async_collectives": 1, "n_overlapped": 1,
        "n_sync_collectives": 1, "n_sync_gaps_with_compute": 0,
    }
    hidden = analytics.effective_bandwidth(
        step_time_s=0.01,
        collectives=[coll],
        n_workers=4,
        overlap=overlap,
    )
    assert hidden["comm_budget_s"] == pytest.approx(0.005)
    assert hidden["total"]["achieved_bytes_per_s"] == pytest.approx(
        2 * out["total"]["achieved_bytes_per_s"]
    )


def test_effective_bandwidth_degenerate_inputs():
    assert analytics.effective_bandwidth(0.01, [], 4) is None
    assert analytics.effective_bandwidth(0.0, [{"payload_bytes": 1}], 4) is None


# ---------------------------------------------------------------------------
# end to end: supervised run -> report -> gate
# ---------------------------------------------------------------------------


def test_supervised_run_report_and_gate(tmp_path, capsys):
    """4 ranks, rank 1 SIGKILLed at step 2 (restarted), rank 3 running 8x
    slow. The run dir must hold a manifest + per-rank shards; the merged
    report must flag rank 3 as the straggler and price the toy collective
    against every fabric; the gate must pass the recorded run and fail a
    synthetically regressed copy of it."""
    run_dir = str(tmp_path / "run")
    plan_path = str(tmp_path / "plan.json")
    ChaosPlan([FaultSpec(kind="proc_kill", step=2, rank=1)]).save(plan_path)

    def argv_for_rank(rank, world, incarnation):
        return [
            sys.executable, TOY,
            "--rank", str(rank), "--world", str(world),
            "--steps", "6",
            "--state-dir", str(tmp_path / "state"),
            "--result-dir", str(tmp_path / "results"),
            "--step-seconds", "0.08" if rank == 3 else "0.01",
            "--chaos-plan", plan_path,
        ]

    telemetry = telemetry_for_run(
        event_log=os.path.join(run_dir, runlog.SUPERVISOR_LOG), stdout=False
    )
    result = Supervisor(
        argv_for_rank,
        world_size=4,
        config=SupervisorConfig(
            max_restarts=2, backoff_base_s=0.01, poll_interval_s=0.02,
        ),
        telemetry=telemetry,
        run_dir=run_dir,
    ).run()
    telemetry.close()
    assert result.success and result.total_restarts == 1

    # manifest + one shard per rank, with rank 1 spawned twice
    manifest = runlog.RunManifest.load(run_dir)
    assert manifest.world_size == 4
    assert manifest.incarnations[1] == 2
    for rank in range(4):
        assert os.path.exists(runlog.shard_path(run_dir, rank))

    merged = runlog.merge_run(run_dir)
    assert merged.per_rank[1]["markers"] == 2  # one run_start per life
    kinds = {e.get("event") for e in merged.events}
    assert {"marker", "step", "collective", "failure"} <= kinds

    # report --run-dir: one timeline, straggler verdict, bandwidth table
    report = _load_script("report")
    json_out = str(tmp_path / "run_report.json")
    rc = report.main(["--run-dir", run_dir, "--json-out", json_out])
    assert rc == 0
    text = capsys.readouterr().out
    assert "per-rank step time" in text
    assert "straggler: rank 3" in text
    assert "effective bandwidth" in text and "1GbE" in text

    with open(json_out) as f:
        rep = json.load(f)
    assert rep["world_size"] == 4
    assert [s["rank"] for s in rep["stragglers"]] == [3]
    assert rep["bandwidth"]["total"]["achieved_bytes_per_s"] > 0
    assert set(rep["bandwidth"]["total"]["utilization"]) == set(
        analytics.FABRICS_BYTES_PER_S
    )
    assert rep["failures"]["restarts"] == 1

    # gate: identical run passes; a 2x-slower copy fails; advisory never fails
    gate = _load_script("gate")
    assert gate.main(["--report", json_out, "--baseline", json_out]) == 0
    regressed = dict(rep)
    regressed["step_p50_s"] = rep["step_p50_s"] * 2
    bad = str(tmp_path / "regressed.json")
    with open(bad, "w") as f:
        json.dump(regressed, f)
    assert gate.main(["--report", bad, "--baseline", json_out]) == 1
    assert gate.main(["--report", bad, "--baseline", json_out, "--advisory"]) == 0
    capsys.readouterr()  # drain the gate's stdout verdicts
