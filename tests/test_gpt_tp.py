"""Tensor-parallel GPT: forward parity with the flax model, end-to-end
training equivalence of the TP decomposition with single-device SGD, and
the composed DP×TP step with PowerSGD-compressed data-axis gradients."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from network_distributed_pytorch_tpu.models.gpt import (
    GPTConfig,
    GPTLM,
    gpt_tp_param_specs,
    tp_gpt_forward,
)
from network_distributed_pytorch_tpu.parallel.mesh import make_mesh

_TINY = dict(
    vocab_size=64, max_position_embeddings=16, dim=16, n_layers=2,
    n_heads=4, hidden_dim=32, dropout=0.0,
)


def test_tp_forward_matches_flax_model(devices):
    """Head-sharded attention + column/row MLP over 4 model shards computes
    the same logits as the unsharded GPTLM."""
    cfg = GPTConfig(**_TINY)
    model = GPTLM(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    ref = model.apply({"params": params}, ids)
    mesh = make_mesh(
        axis_sizes=(4,), axis_names=("model",), devices=devices[:4]
    )
    out = jax.jit(
        jax.shard_map(
            lambda p, i: tp_gpt_forward(cfg, p, i),
            mesh=mesh, in_specs=(gpt_tp_param_specs(cfg), P()), out_specs=P(),
        )
    )(params, ids)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_gpt_tp_exact_matches_single_device_sgd(devices):
    """The full experiment (2 data × 4 model, exact reduction) follows the
    same loss trajectory as plain single-device SGD on the same synthetic
    batches — TP + exact-DP decomposition changes nothing numerically."""
    from network_distributed_pytorch_tpu.experiments import gpt_tp
    from network_distributed_pytorch_tpu.experiments.gpt_lm import (
        synthetic_lm_batches,
    )
    from network_distributed_pytorch_tpu.models import next_token_loss
    from network_distributed_pytorch_tpu.parallel.trainer import (
        sgd_momentum_update,
    )
    from network_distributed_pytorch_tpu.utils.config import ExperimentConfig

    config = ExperimentConfig(
        training_epochs=1, global_batch_size=16, learning_rate=0.1, seed=714,
        log_every=0,
    )
    steps = 5
    out = gpt_tp.run(
        config=config, model_shards=4, reducer="exact", steps_per_epoch=steps
    )

    cfg = GPTConfig(
        vocab_size=64, max_position_embeddings=32, dim=32, n_layers=2,
        n_heads=8, hidden_dim=64, dropout=0.0,
    )
    model = GPTLM(cfg)
    params = model.init(
        jax.random.PRNGKey(714), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    vel = jax.tree_util.tree_map(jnp.zeros_like, params)

    @jax.jit
    def ref_step(params, vel, x, y):
        loss, grads = jax.value_and_grad(
            lambda p: next_token_loss(model.apply({"params": p}, x), y)
        )(params)
        params, vel = sgd_momentum_update(params, vel, grads, 0.1, 0.9)
        return params, vel, loss

    losses = []
    for x, y in synthetic_lm_batches(64, 16, 32, steps, 714):
        params, vel, loss = ref_step(params, vel, x, y)
        losses.append(float(loss))
    np.testing.assert_allclose(out["first_loss"], losses[0], rtol=1e-5)
    np.testing.assert_allclose(out["final_loss"], losses[-1], rtol=1e-4)


def test_gpt_tp_powersgd_dp_learns(devices):
    """Compressed data parallelism composed with tensor parallelism: the
    2×4 mesh trains with PowerSGD on the model-sharded kernels and exact
    reduction on the replicated leaves."""
    from network_distributed_pytorch_tpu.experiments import gpt_tp

    out = gpt_tp.run(model_shards=4, reducer="powersgd", steps_per_epoch=10)
    assert out["final_loss"] < out["first_loss"] * 0.85, out
    assert out["data_shards"] == 2 and out["model_shards"] == 4
    assert out["hlo_collectives"]["all-reduce"] >= 3


def test_gpt_tp_rejects_powersgd_without_data_axis(devices):
    from network_distributed_pytorch_tpu.experiments import gpt_tp

    try:
        gpt_tp.run(model_shards=8, reducer="powersgd", steps_per_epoch=1)
    except ValueError as e:
        assert "data axis" in str(e)
    else:
        raise AssertionError("expected ValueError")


def test_vocab_parallel_ce_matches_full(devices):
    """Vocab-sharded CE (no full-vocab row materialized) == next_token_loss
    on the assembled logits, value and gradient."""
    from network_distributed_pytorch_tpu.models import next_token_loss
    from network_distributed_pytorch_tpu.models.gpt import (
        vocab_parallel_next_token_loss,
    )

    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(2, 8, 64).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 64, (2, 8)))
    ref_loss, ref_g = jax.value_and_grad(
        lambda l: next_token_loss(l, labels)
    )(logits)
    mesh = make_mesh(
        axis_sizes=(4,), axis_names=("model",), devices=devices[:4]
    )
    loss, g = jax.jit(
        jax.shard_map(
            lambda l, y: jax.value_and_grad(
                lambda ls: vocab_parallel_next_token_loss(ls, y, "model")
            )(l),
            mesh=mesh,
            in_specs=(P(None, None, "model"), P()),
            out_specs=(P(), P(None, None, "model")),
        )
    )(logits, labels)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(ref_g), rtol=1e-5, atol=1e-8
    )


def test_gpt_tp_vocab_parallel_matches_single_device(devices):
    """The full experiment with the vocab-sharded head follows the same
    trajectory as plain single-device SGD (extends the exact-equivalence
    test to the vocab-parallel path)."""
    from network_distributed_pytorch_tpu.experiments import gpt_tp
    from network_distributed_pytorch_tpu.experiments.gpt_lm import (
        synthetic_lm_batches,
    )
    from network_distributed_pytorch_tpu.models import next_token_loss
    from network_distributed_pytorch_tpu.parallel.trainer import (
        sgd_momentum_update,
    )
    from network_distributed_pytorch_tpu.utils.config import ExperimentConfig

    config = ExperimentConfig(
        training_epochs=1, global_batch_size=16, learning_rate=0.1, seed=714,
        log_every=0,
    )
    steps = 4
    out = gpt_tp.run(
        config=config, model_shards=4, reducer="exact", vocab_parallel=True,
        steps_per_epoch=steps,
    )
    cfg = GPTConfig(
        vocab_size=64, max_position_embeddings=32, dim=32, n_layers=2,
        n_heads=8, hidden_dim=64, dropout=0.0,
    )
    model = GPTLM(cfg)
    params = model.init(
        jax.random.PRNGKey(714), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    vel = jax.tree_util.tree_map(jnp.zeros_like, params)

    @jax.jit
    def ref_step(params, vel, x, y):
        loss, grads = jax.value_and_grad(
            lambda p: next_token_loss(model.apply({"params": p}, x), y)
        )(params)
        params, vel = sgd_momentum_update(params, vel, grads, 0.1, 0.9)
        return params, vel, loss

    losses = []
    for x, y in synthetic_lm_batches(64, 16, 32, steps, 714):
        params, vel, loss = ref_step(params, vel, x, y)
        losses.append(float(loss))
    np.testing.assert_allclose(out["first_loss"], losses[0], rtol=1e-5)
    np.testing.assert_allclose(out["final_loss"], losses[-1], rtol=1e-4)
