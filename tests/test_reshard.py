"""Elastic world-size-safe recovery: resharding math + topology protocol.

Fast half: the ``resilience.reshard`` invariants as property tests — the
EF-memory fold preserves the sequential rank-order sum BIT-FOR-BIT, the
per-worker stat merge is the weighted average, the elastic re-split keeps
exactly-once dataset coverage, the accumulation rescale preserves the
global batch — plus the checkpoint topology protocol: a cross-world
restore refuses loudly (``TopologyMismatchError``) unless routed through
the resharder.

Slow half: the end-to-end proof. A 4-rank run is preempted mid-epoch
(``proc_preempt`` + ``PreemptionGuard`` → emergency committed checkpoint
with an epoch cursor), then restarted at world 3: the restore reshards,
the resumed run matches an uninterrupted world-3 run seeded with the same
resharded state, and the ``resumed``/``resharded`` events land in the
JSONL log.
"""

import json
import os
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from network_distributed_pytorch_tpu.data import elastic_assignments
from network_distributed_pytorch_tpu.experiments.common import (
    resilient_train_loop,
    train_loop,
)
from network_distributed_pytorch_tpu.models import SmallCNN
from network_distributed_pytorch_tpu.observe import (
    JsonlSink,
    MemorySink,
    Telemetry,
)
from network_distributed_pytorch_tpu.parallel import PowerSGDReducer, make_mesh
from network_distributed_pytorch_tpu.parallel.trainer import (
    make_train_step,
    stateless_loss,
)
from network_distributed_pytorch_tpu.resilience import (
    ChaosPlan,
    FaultSpec,
    PreemptionGuard,
)
from network_distributed_pytorch_tpu.resilience.reshard import (
    derive_rank_key,
    fold_groups,
    fold_memories,
    make_topology,
    memory_total,
    merge_model_state,
    merge_tp_leaf,
    mesh_world,
    normalize_mesh_axes,
    rescale_accum_steps,
    reshard_from_checkpoint,
    reshard_tp_params,
    reshard_train_state,
    split_tp_leaf,
    topology_mesh,
    widen_memories,
    widen_model_state,
    widen_template,
)
from network_distributed_pytorch_tpu.utils import cross_entropy_loss
from network_distributed_pytorch_tpu.utils.checkpoint import (
    TopologyMismatchError,
    read_topology,
    restore_checkpoint,
    restore_checkpoint_sharded,
    restore_latest,
    save_checkpoint,
)


class MiniState(NamedTuple):
    """Smallest TrainState-like carry the reshard/topology code accepts."""

    params: Any
    memories: Any
    model_state: Any


def _mini(world: int, seed: int = 0) -> MiniState:
    rng = np.random.RandomState(seed)
    return MiniState(
        params={"w": rng.randn(6, 4).astype(np.float32)},
        memories={
            "w": rng.randn(world, 6, 4).astype(np.float32),
            "b": rng.randn(world, 4).astype(np.float32),
        },
        model_state=None,
    )


def _bytes_of(tree) -> list:
    return [np.asarray(l).tobytes() for l in jax.tree_util.tree_leaves(tree)]


# ---------------------------------------------------------------------------
# fold geometry + the bit-for-bit sum invariant
# ---------------------------------------------------------------------------

def test_fold_groups_geometry():
    assert fold_groups(4, 3) == [[0, 1], [2], [3]]
    assert fold_groups(4, 1) == [[0, 1, 2, 3]]
    assert fold_groups(4, 4) == [[0], [1], [2], [3]]
    assert fold_groups(8, 5) == [[0, 1, 2, 3], [4], [5], [6], [7]]
    with pytest.raises(ValueError, match="only shrinks"):
        fold_groups(4, 5)
    with pytest.raises(ValueError, match=">= 1"):
        fold_groups(4, 0)


def test_fold_memories_sum_bit_for_bit():
    """The conserved quantity: the strict left-to-right rank-order sum of
    every memory leaf has IDENTICAL BYTES before and after any fold — the
    prefix grouping makes it the same chain of fp32 additions, not merely
    the same real number."""
    rng = np.random.RandomState(7)
    world = 8
    memories = {
        "conv": (100.0 * rng.randn(world, 3, 5)).astype(np.float32),
        "dense": {"k": rng.randn(world, 17).astype(np.float32)},
    }
    before = _bytes_of(memory_total(memories))
    for new_world in range(1, world + 1):
        folded = fold_memories(memories, new_world)
        for leaf in jax.tree_util.tree_leaves(folded):
            assert np.asarray(leaf).shape[0] == new_world
        assert _bytes_of(memory_total(folded)) == before


def test_fold_memories_identity_at_same_world():
    mem = {"m": np.arange(12, dtype=np.float32).reshape(4, 3)}
    out = fold_memories(mem, 4)
    np.testing.assert_array_equal(out["m"], mem["m"])


# ---------------------------------------------------------------------------
# widening: zero-pad rows, bit-exact by x + 0.0 == x
# ---------------------------------------------------------------------------

def test_widen_memories_zero_pad_bit_for_bit():
    """Widening appends zero EF rows; since x + 0.0 is exact for every
    finite fp32 x, the sequential rank-order sum keeps IDENTICAL BYTES —
    including the non-divisible pairs the fold geometry never sees."""
    rng = np.random.RandomState(3)
    for old, new in [(3, 5), (4, 6), (1, 4), (2, 2)]:
        mem = {
            "w": (50.0 * rng.randn(old, 5, 3)).astype(np.float32),
            "b": {"k": rng.randn(old, 9).astype(np.float32)},
        }
        before = _bytes_of(memory_total(mem))
        wide = widen_memories(mem, new)
        for leaf in jax.tree_util.tree_leaves(wide):
            arr = np.asarray(leaf)
            assert arr.shape[0] == new
            assert not arr[old:].any()  # new ranks start with zero error
        assert _bytes_of(memory_total(wide)) == before
    with pytest.raises(ValueError, match="only widens"):
        widen_memories({"m": np.zeros((4, 2), np.float32)}, 3)


def test_widen_model_state_replicates_rank0():
    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = widen_model_state({"mean": arr}, 4)["mean"]
    assert out.shape == (4, 3)
    np.testing.assert_array_equal(out[2], arr[0])
    np.testing.assert_array_equal(out[3], arr[0])
    assert widen_model_state(None, 4) is None
    with pytest.raises(ValueError, match="only widens"):
        widen_model_state({"m": np.zeros((4, 2), np.float32)}, 2)


def test_reshard_train_state_widens_non_divisible():
    """new_world > old_world routes through the widen path — including
    non-divisible pairs (3 -> 5, 4 -> 6) — with params untouched and the
    EF sum conserved bit-for-bit."""
    for old, new in [(3, 5), (4, 6)]:
        st = _mini(old)
        before = _bytes_of(memory_total(st.memories))
        out = reshard_train_state(st, new)
        for leaf in jax.tree_util.tree_leaves(out.memories):
            assert np.asarray(leaf).shape[0] == new
        assert _bytes_of(memory_total(out.memories)) == before
        assert _bytes_of(out.params) == _bytes_of(st.params)
        # ...and a later shrink folds the padded rows back losslessly
        back = reshard_train_state(out, old)
        assert _bytes_of(memory_total(back.memories)) == before


def test_widen_template_states_on_disk_shape():
    t = _mini(3)
    wide = widen_template(t, 5)
    for leaf in jax.tree_util.tree_leaves(wide.memories):
        arr = np.asarray(leaf)
        assert arr.shape[0] == 5 and not arr.any()
    # shrink direction too: the template just states the checkpoint shape
    narrow = widen_template(t, 2)
    for leaf in jax.tree_util.tree_leaves(narrow.memories):
        assert np.asarray(leaf).shape[0] == 2


def test_derive_rank_key_for_widened_ranks(devices):
    """New ranks born in a widening re-derive their PRNG keys from the
    same base-key lineage — distinct from every surviving rank's, and
    reproducible."""
    keys = {r: np.asarray(derive_rank_key(0, r, 1)).tobytes() for r in range(6)}
    assert len(set(keys.values())) == 6
    assert np.asarray(derive_rank_key(0, 5, 1)).tobytes() == keys[5]


# ---------------------------------------------------------------------------
# per-worker stat merge
# ---------------------------------------------------------------------------

def test_merge_model_state_weighted_average():
    arr = np.arange(8, dtype=np.float32).reshape(4, 2)
    samples = [10, 20, 30, 40]
    out = merge_model_state({"mean": arr}, 2, samples_per_rank=samples)["mean"]
    # groups [[0,1,2],[3]]: row 0 = weighted avg of rows 0..2, row 1 = row 3
    want0 = (10 * arr[0] + 20 * arr[1] + 30 * arr[2]) / 60.0
    np.testing.assert_allclose(out[0], want0, rtol=1e-6)
    np.testing.assert_array_equal(out[1], arr[3])
    assert out.shape == (2, 2) and out.dtype == np.float32


def test_merge_model_state_int_and_none():
    counts = np.array([[5], [6], [7], [8]], dtype=np.int32)
    out = merge_model_state({"n": counts}, 2)["n"]
    # non-float leaves keep the first source rank's value per group
    np.testing.assert_array_equal(out, np.array([[5], [8]], dtype=np.int32))
    assert merge_model_state(None, 2) is None
    with pytest.raises(ValueError, match="samples_per_rank"):
        merge_model_state(
            {"m": np.zeros((4, 2), np.float32)}, 2, samples_per_rank=[1, 2]
        )


# ---------------------------------------------------------------------------
# global-batch preservation + RNG lineage
# ---------------------------------------------------------------------------

def test_rescale_accum_steps_preserves_global_batch():
    assert rescale_accum_steps(24, 4, 3, 1) == 2
    assert rescale_accum_steps(24, 4, 4, 1) == 1  # no change, no rescale
    assert rescale_accum_steps(240, 8, 5, 2) == 4
    for gb, ow, nw, oa in [(24, 4, 3, 1), (240, 8, 5, 2), (64, 8, 4, 1)]:
        k = rescale_accum_steps(gb, ow, nw, oa)
        assert gb % k == 0 and (gb // k) % nw == 0  # trainer batch contract
        assert gb // k <= gb // oa  # microbatch never grows
    # infeasible (32 never splits over 3): fall back to the old accumulation
    assert rescale_accum_steps(32, 4, 3, 1) == 1
    with pytest.raises(ValueError, match="old_accum"):
        rescale_accum_steps(24, 4, 3, 0)


def test_derive_rank_key_distinct_and_deterministic(devices):
    keys = {}
    for rank in range(4):
        for inc in range(2):
            k = np.asarray(derive_rank_key(0, rank, inc))
            keys[(rank, inc)] = k.tobytes()
    assert len(set(keys.values())) == 8  # all (rank, incarnation) distinct
    again = np.asarray(derive_rank_key(0, 2, 1)).tobytes()
    assert again == keys[(2, 1)]


# ---------------------------------------------------------------------------
# elastic data re-split: exactly-once coverage at any world size
# ---------------------------------------------------------------------------

def test_elastic_assignments_cover_disjointly():
    n = 120
    full = set(range(n))
    for world in (4, 3):
        parts = elastic_assignments(n, world)
        assert len(parts) == world
        flat = [i for p in parts for i in p]
        assert len(flat) == len(set(flat))  # disjoint
        assert set(flat) == full  # exactly-once coverage
    # the W=4 and W'=3 splits cut the SAME permutation — no reshuffle
    perm4 = [i for p in elastic_assignments(n, 4) for i in p]
    perm3 = [i for p in elastic_assignments(n, 3) for i in p]
    assert perm4 == perm3


# ---------------------------------------------------------------------------
# topology protocol: tagged checkpoints refuse silent cross-world restores
# ---------------------------------------------------------------------------

def test_topology_record_roundtrip(devices, tmp_path):
    root = str(tmp_path / "ck")
    topo = make_topology(
        4, global_batch=24, accum_steps=1, bits_per_step=999, rng_seed=5,
        epoch_cursor={"epoch": 1, "batches_done": 3},
    )
    final = save_checkpoint(root, _mini(4), step=0, topology=topo)
    back = read_topology(final)
    assert back["world_size"] == 4
    assert back["global_batch"] == 24
    assert back["epoch_cursor"] == {"epoch": 1, "batches_done": 3}
    assert [s["rank"] for s in back["shard_layout"]] == [0, 1, 2, 3]
    # untagged directory: None, not an error
    assert read_topology(str(tmp_path / "nope")) is None


def test_cross_topology_restore_refuses(devices, tmp_path):
    """Satellite: a world-4 tagged checkpoint restored into a world-3
    template must raise a CLEAR topology-mismatch error from every restore
    entry point — never garbage, never a deep orbax failure."""
    root = str(tmp_path / "ck")
    final = save_checkpoint(root, _mini(4), step=0, topology=make_topology(4))
    t3 = _mini(3)
    for restore in (restore_checkpoint, restore_checkpoint_sharded):
        with pytest.raises(TopologyMismatchError, match="topology mismatch"):
            restore(final, t3)
    with pytest.raises(TopologyMismatchError, match="world size 4"):
        restore_latest(root, t3)
    # the matching world restores normally
    state = restore_checkpoint(final, _mini(4, seed=1))
    assert _bytes_of(state.memories) == _bytes_of(_mini(4).memories)


def test_restore_latest_routes_through_resharder(devices, tmp_path):
    root = str(tmp_path / "ck")
    state4 = _mini(4)
    save_checkpoint(root, state4, step=2, topology=make_topology(4))
    t3 = _mini(3, seed=1)

    def resharder(path, saved_topo):
        assert saved_topo["world_size"] == 4
        return reshard_from_checkpoint(path, t3, saved_topology=saved_topo)

    restored, step = restore_latest(root, t3, resharder=resharder)
    assert step == 2
    for leaf in jax.tree_util.tree_leaves(restored.memories):
        assert np.asarray(leaf).shape[0] == 3
    # replicated leaves pass through; the EF sum is conserved bit-for-bit
    assert _bytes_of(restored.params) == _bytes_of(state4.params)
    assert _bytes_of(memory_total(restored.memories)) == _bytes_of(
        memory_total(state4.memories)
    )


def test_reshard_from_checkpoint_requires_topology(devices, tmp_path):
    root = str(tmp_path / "ck")
    final = save_checkpoint(root, _mini(4), step=0)  # untagged
    with pytest.raises(ValueError, match="no topology record"):
        reshard_from_checkpoint(final, _mini(3))


# ---------------------------------------------------------------------------
# mesh geometry + TP shard movement
# ---------------------------------------------------------------------------

def test_normalize_mesh_axes_and_world():
    assert normalize_mesh_axes(None, 4) == {"data": 4, "fsdp": 1, "tensor": 1}
    axes = normalize_mesh_axes({"data": 2, "tensor": 2})
    assert axes == {"data": 2, "fsdp": 1, "tensor": 2}
    assert mesh_world(axes) == 4
    assert mesh_world({"data": 3}) == 3
    with pytest.raises(ValueError, match="unknown mesh axes"):
        normalize_mesh_axes({"data": 2, "pipeline": 2})
    with pytest.raises(ValueError, match=">= 1"):
        normalize_mesh_axes({"data": 0})
    with pytest.raises(ValueError, match="expected 8"):
        normalize_mesh_axes({"data": 2, "tensor": 2}, world_size=8)
    with pytest.raises(ValueError, match="axes or a world size"):
        normalize_mesh_axes(None)
    # pre-mesh topology records (no mesh_axes key) mean all-data
    assert topology_mesh({"world_size": 3}) == {
        "data": 3, "fsdp": 1, "tensor": 1
    }


def test_tp_leaf_split_merge_roundtrip_exact():
    rng = np.random.RandomState(11)
    full = rng.randn(6, 8).astype(np.float32)
    stacked = split_tp_leaf(full, 4, 1)
    assert stacked.shape == (4, 6, 2)
    assert merge_tp_leaf(stacked, 1).tobytes() == full.tobytes()
    # axis 0 too
    assert merge_tp_leaf(split_tp_leaf(full, 3, 0), 0).tobytes() == full.tobytes()
    with pytest.raises(ValueError, match="does not divide"):
        split_tp_leaf(full, 5, 1)
    with pytest.raises(ValueError, match=">= 1"):
        split_tp_leaf(full, 0, 1)
    with pytest.raises(ValueError, match="leading shard axis"):
        merge_tp_leaf(np.zeros(4, np.float32), 0)


def test_reshard_tp_params_moves_listed_leaves_only():
    rng = np.random.RandomState(12)
    full = rng.randn(6, 8).astype(np.float32)
    b = rng.randn(8).astype(np.float32)
    params = {"w": split_tp_leaf(full, 2, 1), "b": b}
    merged = reshard_tp_params(params, 2, 1, {"w": 1})
    assert merged["w"].shape == (1, 6, 8)
    assert merged["w"][0].tobytes() == full.tobytes()
    assert merged["b"].tobytes() == b.tobytes()  # unlisted: replicated
    # round-trip back to 2 shards is pure byte movement
    again = reshard_tp_params(merged, 1, 2, {"w": 1})
    assert _bytes_of(again) == _bytes_of(params)
    # equal degrees / empty table: identity
    assert reshard_tp_params(params, 2, 2, {"w": 1}) is params
    assert reshard_tp_params(params, 2, 1, {}) is params


def test_make_topology_records_mesh():
    topo = make_topology(
        4, mesh_axes={"data": 2, "tensor": 2}, tp_param_axes={"w": 1}
    )
    assert topo["mesh_axes"] == {"data": 2, "fsdp": 1, "tensor": 2}
    assert topo["tp_param_axes"] == {"w": 1}
    assert topology_mesh(topo) == {"data": 2, "fsdp": 1, "tensor": 2}
    # default: all-data, empty TP table — the pre-mesh meaning, recorded
    assert make_topology(4)["mesh_axes"] == {"data": 4, "fsdp": 1, "tensor": 1}
    assert make_topology(4)["tp_param_axes"] == {}
    with pytest.raises(ValueError, match="expected 4"):
        make_topology(4, mesh_axes={"data": 3})


class MeshState(NamedTuple):
    params: Any
    memories: Any
    model_state: Any


def _mesh_state(data: int, tp: int, seed: int = 0) -> MeshState:
    """A TrainState-like mini on a data x tp mesh: ``w`` is TP-stacked
    ``(tp,) + shard_shape`` (full dim 8 on axis 1), memories per-DATA-rank."""
    rng = np.random.RandomState(seed)
    full = rng.randn(6, 8).astype(np.float32)
    return MeshState(
        params={"w": split_tp_leaf(full, tp, 1), "b": rng.randn(8).astype(np.float32)},
        memories={"m": rng.randn(data, 6, 8).astype(np.float32)},
        model_state=None,
    )


def test_mesh_checkpoint_trades_tensor_for_data(devices, tmp_path):
    """Tentpole e2e: a 2(data) x 2(tensor) checkpoint boots a 2x1 mesh —
    TP shards merge by byte movement, the data axis is untouched."""
    root = str(tmp_path / "ck")
    st = _mesh_state(2, 2)
    topo = make_topology(
        4, mesh_axes={"data": 2, "tensor": 2}, tp_param_axes={"w": 1}
    )
    final = save_checkpoint(root, st, step=0, topology=topo)
    template = _mesh_state(2, 1, seed=9)
    out = reshard_from_checkpoint(
        final, template, mesh_axes={"data": 2, "tensor": 1}
    )
    full = merge_tp_leaf(st.params["w"], 1)
    assert out.params["w"].shape == (1, 6, 8)
    assert out.params["w"][0].tobytes() == full.tobytes()
    assert np.asarray(out.params["b"]).tobytes() == st.params["b"].tobytes()
    assert _bytes_of(out.memories) == _bytes_of(st.memories)


def test_mesh_checkpoint_folds_data_keeps_tensor(devices, tmp_path):
    """2(data) x 2(tensor) -> 1x2: the EF fold runs along the data axis
    (sum conserved bit-for-bit) while the TP stack passes through."""
    root = str(tmp_path / "ck")
    st = _mesh_state(2, 2)
    topo = make_topology(
        4, mesh_axes={"data": 2, "tensor": 2}, tp_param_axes={"w": 1}
    )
    final = save_checkpoint(root, st, step=0, topology=topo)
    template = _mesh_state(1, 2, seed=9)
    out = reshard_from_checkpoint(
        final, template, mesh_axes={"data": 1, "tensor": 2}
    )
    assert out.params["w"].shape == (2, 6, 4)
    assert _bytes_of(out.params["w"]) == _bytes_of(st.params["w"])
    assert np.asarray(out.memories["m"]).shape[0] == 1
    assert _bytes_of(memory_total(out.memories)) == _bytes_of(
        memory_total(st.memories)
    )


def test_mesh_checkpoint_full_collapse_2x2_to_1x1(devices, tmp_path):
    root = str(tmp_path / "ck")
    st = _mesh_state(2, 2)
    topo = make_topology(
        4, mesh_axes={"data": 2, "tensor": 2}, tp_param_axes={"w": 1}
    )
    final = save_checkpoint(root, st, step=0, topology=topo)
    out = reshard_from_checkpoint(
        final, _mesh_state(1, 1, seed=9),
        mesh_axes={"data": 1, "tensor": 1},
    )
    assert out.params["w"].shape == (1, 6, 8)
    assert out.params["w"][0].tobytes() == merge_tp_leaf(
        st.params["w"], 1
    ).tobytes()
    assert _bytes_of(memory_total(out.memories)) == _bytes_of(
        memory_total(st.memories)
    )


def test_check_topology_mesh_data_axis_mismatch(devices, tmp_path):
    """A mesh-tagged checkpoint compares the template rows against the
    recorded DATA degree: the same-mesh restore passes, a different data
    degree refuses loudly."""
    root = str(tmp_path / "ck")
    st = _mesh_state(2, 2)
    topo = make_topology(
        4, mesh_axes={"data": 2, "tensor": 2}, tp_param_axes={"w": 1}
    )
    final = save_checkpoint(root, st, step=0, topology=topo)
    # same mesh: restores fine despite world_size (4) != memory rows (2)
    back = restore_checkpoint(final, _mesh_state(2, 2, seed=9))
    assert _bytes_of(back.memories) == _bytes_of(st.memories)
    with pytest.raises(TopologyMismatchError, match="data degree 2"):
        restore_checkpoint(final, _mesh_state(3, 2, seed=9))


def test_reshard_from_checkpoint_rejects_mesh_template_conflict(
    devices, tmp_path
):
    root = str(tmp_path / "ck")
    final = save_checkpoint(
        root, _mini(4), step=0, topology=make_topology(4)
    )
    with pytest.raises(ValueError, match="per-rank rows"):
        reshard_from_checkpoint(
            final, _mini(3), mesh_axes={"data": 2, "tensor": 1}
        )


# ---------------------------------------------------------------------------
# end-to-end: preempted at W=4, resumed at W'=3
# ---------------------------------------------------------------------------

IMG = (8, 8, 3)
GB = 24  # global batch, preserved across the shrink
N_EX = 120  # dataset size: divides evenly at both W=4 and W'=3
STEPS_PER_EPOCH = N_EX // GB
EPOCHS = 2


def _global_batches(epoch: int):
    """Deterministic stream of GLOBAL batches — world-size independent, so
    the W=4 and W'=3 runs see byte-identical data."""
    rng = np.random.RandomState(500 + epoch)
    means = np.random.RandomState(999).randn(10, *IMG)
    for _ in range(STEPS_PER_EPOCH):
        y = rng.randint(0, 10, GB)
        x = (means[y] + 0.5 * rng.randn(GB, *IMG)).astype(np.float32)
        yield x, y


def _batches_fn(accum: int):
    def gen(epoch: int):
        for x, y in _global_batches(epoch):
            if accum > 1:
                x = x.reshape((accum, GB // accum) + x.shape[1:])
                y = y.reshape((accum, GB // accum))
            yield jnp.asarray(x, jnp.float32), jnp.asarray(y)

    return gen


def _make_step(mesh, accum: int):
    model = SmallCNN(width=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *IMG)))["params"]

    def lf(p, b):
        x, y = b
        return cross_entropy_loss(model.apply({"params": p}, x), y)

    step = make_train_step(
        stateless_loss(lf),
        PowerSGDReducer(random_seed=7, compression_rank=2, matricize="last"),
        params, learning_rate=0.05, momentum=0.9, algorithm="ef_momentum",
        mesh=mesh, accum_steps=accum, donate_state=False,
    )
    return step, params


@pytest.mark.slow
def test_elastic_shrink_4_to_3_end_to_end(devices, tmp_path):
    """A rank dies mid-epoch at W=4 (preemption notice → emergency
    committed checkpoint with an epoch cursor); the run restarts at W'=3
    from the W=4 checkpoint: exactly-once data coverage, the folded EF
    sum bit-for-bit, the resumed run equal to an uninterrupted W'=3 run
    seeded with the same resharded state, and ``resumed``/``resharded``
    in the JSONL event log."""
    from network_distributed_pytorch_tpu.experiments.common import (
        accum_batch_sharding,
    )

    ckpt = str(tmp_path / "elastic")
    log_path = str(tmp_path / "events.jsonl")

    # -- phase 1: W=4, preempted mid-epoch 0 --------------------------------
    mesh4 = make_mesh(devices=devices[:4])
    step4, params = _make_step(mesh4, accum=1)
    topo4 = make_topology(
        4, global_batch=GB, accum_steps=1,
        bits_per_step=step4.bits_per_step, rng_seed=0,
    )
    plan = ChaosPlan([FaultSpec(kind="proc_preempt", step=2)], seed=11)
    sink4 = MemorySink()
    tel4 = Telemetry([sink4, JsonlSink(log_path)])
    with PreemptionGuard(telemetry=tel4) as guard:
        stopped, _, _ = resilient_train_loop(
            step4, step4.init_state(params), _batches_fn(1), EPOCHS,
            checkpoint_dir=ckpt, telemetry=tel4, run_name="w4",
            chaos_plan=plan, topology=topo4, preemption_guard=guard,
        )
    assert guard.checkpoint_saved
    kinds4 = [r.get("kind") for r in sink4.records if r.get("event") == "failure"]
    assert "preempt_notice" in kinds4 and "preempt_checkpoint" in kinds4
    cursor = read_topology(os.path.join(ckpt, "step_0"))["epoch_cursor"]
    assert cursor == {"epoch": 0, "batches_done": 3}
    pre_total = memory_total(stopped.memories)

    # -- the survivors' data re-split covers the dataset exactly once -------
    parts3 = elastic_assignments(N_EX, 3)
    flat = [i for p in parts3 for i in p]
    assert sorted(flat) == list(range(N_EX)) and len(set(flat)) == N_EX

    # -- phase 2: reshard to W'=3, global batch preserved via accum ---------
    accum3 = rescale_accum_steps(GB, 4, 3, 1)
    assert accum3 == 2
    mesh3 = make_mesh(devices=devices[:3])
    step3, _ = _make_step(mesh3, accum=accum3)
    init3 = step3.init_state(params)
    shard3 = accum_batch_sharding(mesh3, accum3)

    # direct reshard: the folded EF sum is the W=4 sum, bit-for-bit
    resharded = reshard_from_checkpoint(os.path.join(ckpt, "step_0"), init3)
    assert _bytes_of(memory_total(resharded.memories)) == _bytes_of(pre_total)
    assert _bytes_of(resharded.params) == _bytes_of(stopped.params)

    topo3 = make_topology(
        3, global_batch=GB, accum_steps=accum3,
        bits_per_step=step3.bits_per_step, rng_seed=0, incarnation=1,
    )
    sink3 = MemorySink()
    tel3 = Telemetry([sink3, JsonlSink(log_path)])
    final, logger3, start_epoch = resilient_train_loop(
        step3, init3, _batches_fn(accum3), EPOCHS,
        checkpoint_dir=ckpt, telemetry=tel3, run_name="w3",
        topology=topo3, batch_sharding=shard3, incarnation=1,
    )
    assert start_epoch == 0  # re-entered the preempted epoch, mid-way
    kinds3 = [r.get("kind") for r in sink3.records if r.get("event") == "failure"]
    assert "resumed" in kinds3 and "resharded" in kinds3
    resumed_msg = next(
        r["message"] for r in sink3.records if r.get("kind") == "resumed"
    )
    assert "+3 steps" in resumed_msg
    final_loss = logger3.summary().get("final_loss")
    assert final_loss is not None and np.isfinite(final_loss)

    # -- oracle: an uninterrupted W'=3 run from the same resharded state ----
    def skipped_batches(epoch: int):
        it = _batches_fn(accum3)(epoch)
        if epoch == 0:
            for _ in range(cursor["batches_done"]):
                next(it)
        return it

    oracle, _ = train_loop(
        step3, resharded, skipped_batches, EPOCHS, start_epoch=0,
        batch_sharding=shard3, run_name="oracle",
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(final.params),
        jax.tree_util.tree_leaves(oracle.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(
        jax.tree_util.tree_leaves(final.memories),
        jax.tree_util.tree_leaves(oracle.memories),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # -- the JSONL log carries the whole story ------------------------------
    with open(log_path) as f:
        logged = [json.loads(l) for l in f if l.strip()]
    logged_kinds = {r.get("kind") for r in logged if r.get("event") == "failure"}
    assert {"preempt_notice", "preempt_checkpoint", "resumed", "resharded"} <= logged_kinds
