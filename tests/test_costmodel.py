"""Offline what-if cost model (observe.costmodel) and its observatory.

Unit-pins the calibration math on a synthetic run report, the per-config
prediction components (compression bytes, chunk pipeline depth, sync-period
amortization), the deterministic fabric flip the model exists to predict
(compression wins on a slow fabric, the dense baseline wins on ICI), the
plan document + PredictionEvent pipeline, the predicted-vs-realized join,
the plan-ordered fallback ladder, and the gate's costmodel_error /
missing_baseline plumbing. Also the analytics edge cases the planner
leans on (single-sample percentiles, zero-duration spans, ledgers without
overlap attribution). Everything here is jax-free.
"""

import importlib.util
import json
import math
import os
import sys

import pytest

from network_distributed_pytorch_tpu.observe import analytics, costmodel, runlog
from network_distributed_pytorch_tpu.observe.events import PredictionEvent
from network_distributed_pytorch_tpu.resilience import (
    DEFAULT_LADDER,
    ladder_from_plan,
)
from network_distributed_pytorch_tpu.utils import bandwidth

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS_DIR)


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        f"_costmodel_test_{name}", os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[f"_costmodel_test_{name}"] = mod
    spec.loader.exec_module(mod)
    return mod


MIB = 1 << 20


def _toy_report(**over):
    """A synthetic run report shaped like scripts/report.py's machine dict:
    10 ms of pure compute (the step/compute span), one fully-exposed 8 MiB
    all-reduce, 80 ms measured step — a comm-dominated 2-worker run."""
    doc = {
        "run_dir": "synthetic",
        "step_p50_s": 0.08,
        "world_size": 2,
        "bandwidth": {
            "total": {"payload_bytes": 8 * MIB, "count": 1},
            "attribution": {"exposed_fraction": 1.0, "n_collectives": 1},
        },
        "compile": {
            "analytic_bytes": 8 * MIB,
            "comm_config": {"reducer": "exactreducer"},
        },
        "mfu": [{"flops_per_step": 2.0e9, "peak_flops_per_s": 1.0e12}],
        "spans": {"by_name": {"step/compute": {"mean_s": 0.01}}},
    }
    doc.update(over)
    return doc


# ---------------------------------------------------------------------------
# canonical configs and join keys
# ---------------------------------------------------------------------------


def test_canonical_config_normalizes_knobs():
    c = costmodel.canonical_config(
        {"reducer": "PowerSGDReducer", "comm_chunks": None}, name="rung"
    )
    assert c["reducer"] == "powersgd"
    assert c["reducer_rank"] == 1  # powersgd without a rank is rank-1
    assert c["comm_chunks"] == 0 and c["bucket_bytes"] == 0
    assert c["sync_every"] == 1
    assert c["name"] == "rung"
    # exact is the default family, whatever the class name looked like
    assert costmodel.canonical_config({})["reducer"] == "exact"


def test_config_key_joins_on_knobs_not_names():
    a = {"name": "compress-low-rank", "reducer": "powersgd", "reducer_rank": 1}
    b = {"name": "toy", "reducer": "PowerSGDReducer", "reducer_rank": 1}
    assert costmodel.config_key(a) == costmodel.config_key(b)
    assert costmodel.config_key(a) != costmodel.config_key(
        {"reducer": "powersgd", "reducer_rank": 2}
    )


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------


def test_calibrate_reads_spans_bytes_and_flops():
    calib = costmodel.calibrate(_toy_report())
    assert calib.step_time_s == pytest.approx(0.08)
    assert calib.compute_s == pytest.approx(0.01)  # the step/compute mean
    assert calib.dense_bytes == 8 * MIB
    assert calib.n_workers == 2
    assert calib.exposed_fraction == 1.0
    assert calib.flops_per_step == 2.0e9
    # effective rate is MFU-scaled: measured FLOPs over measured compute
    assert calib.effective_flops_per_s == pytest.approx(2.0e9 / 0.01)
    assert calib.source_config["reducer"] == "exact"


def test_calibrate_requires_a_step_time():
    with pytest.raises(ValueError):
        costmodel.calibrate({"world_size": 2})


def test_calibrate_source_fabric_subtracts_modeled_comm():
    # a jitted step's collectives retire inside step/compute: with the
    # source fabric named, the modeled exposed comm comes OFF the compute
    # calibration (floored at MIN_COMPUTE_FRACTION of the step)
    report = _toy_report(
        spans={"by_name": {"step/compute": {"mean_s": 0.08}}}
    )
    plain = costmodel.calibrate(report)
    adjusted = costmodel.calibrate(report, source_fabric="1GbE")
    modeled = bandwidth.allreduce_time_s(8 * MIB, 2, "1GbE", n_collectives=1)
    assert plain.compute_s == pytest.approx(0.08)
    assert adjusted.compute_s == pytest.approx(
        max(0.08 - modeled, costmodel.MIN_COMPUTE_FRACTION * 0.08)
    )
    assert adjusted.compute_s < plain.compute_s


def test_calibrate_compressed_source_measures_bytes_fraction():
    # a source run that executed PowerSGD rank-2 moving 2 MiB of an 8 MiB
    # dense gradient calibrates bytes_fraction_per_rank = (2/8)/2
    report = _toy_report(
        bandwidth={
            "total": {"payload_bytes": 2 * MIB, "count": 1},
            "attribution": {"exposed_fraction": 1.0, "n_collectives": 1},
        },
        compile={
            "analytic_bytes": 2 * MIB,
            "dense_grad_bytes": 8 * MIB,
            "comm_config": {"reducer": "powersgd", "reducer_rank": 2},
        },
    )
    calib = costmodel.calibrate(report)
    assert calib.dense_bytes == 8 * MIB
    assert calib.bytes_fraction_per_rank == pytest.approx(0.125)


# ---------------------------------------------------------------------------
# prediction components
# ---------------------------------------------------------------------------


def test_predict_baseline_is_compute_plus_wire_and_latency():
    calib = costmodel.calibrate(_toy_report())
    p = costmodel.predict(calib, {"name": "baseline"}, "1GbE")
    wire = (2.0 * 1 / 2) * (8 * MIB / bandwidth.FABRICS_BYTES_PER_S["1GbE"])
    assert p["wire_s"] == pytest.approx(wire)
    assert p["predicted_step_s"] == pytest.approx(
        0.01 + wire + bandwidth.LATENCY_S["1GbE"]
    )
    assert p["predicted_bytes_per_step"] == 8 * MIB
    assert p["pipeline_depth"] == 1


def test_predict_compression_shrinks_bytes_and_prices_compute():
    calib = costmodel.calibrate(_toy_report())
    p = costmodel.predict(
        calib, {"reducer": "powersgd", "reducer_rank": 1}, "1GbE"
    )
    # rank-1 payload: dense/8 by the default per-rank fraction; P and Q
    # round trips double the per-collective latency
    assert p["predicted_bytes_per_step"] == pytest.approx(MIB)
    assert p["latency_s"] == pytest.approx(2 * bandwidth.LATENCY_S["1GbE"])
    expected_compress = (
        costmodel.POWERSGD_FLOPS_PER_ELEM_PER_RANK * (8 * MIB / 4.0)
    ) / calib.effective_flops_per_s
    assert p["compress_s"] == pytest.approx(expected_compress)


def test_predict_chunks_trade_exposure_for_latency():
    calib = costmodel.calibrate(_toy_report())
    mono = costmodel.predict(calib, {}, "1GbE")
    chunked = costmodel.predict(calib, {"comm_chunks": 4}, "1GbE")
    assert chunked["pipeline_depth"] == 4
    assert chunked["exposed_comm_s"] == pytest.approx(
        mono["exposed_comm_s"] / 4
    )
    assert chunked["latency_s"] == pytest.approx(mono["latency_s"] * 4)


def test_predict_bucket_bytes_sets_depth_and_caps():
    calib = costmodel.calibrate(_toy_report())
    p = costmodel.predict(calib, {"bucket_bytes": 2 * MIB}, "1GbE")
    assert p["pipeline_depth"] == 4  # ceil(8 MiB / 2 MiB)
    tiny = costmodel.predict(calib, {"bucket_bytes": 1}, "1GbE")
    assert tiny["pipeline_depth"] == costmodel.MAX_PIPELINE_DEPTH


def test_predict_sync_every_amortizes_the_round():
    calib = costmodel.calibrate(_toy_report())
    every = costmodel.predict(calib, {}, "1GbE")
    wide = costmodel.predict(calib, {"sync_every": 8}, "1GbE")
    comm_every = every["predicted_step_s"] - every["compute_s"]
    comm_wide = wide["predicted_step_s"] - wide["compute_s"]
    assert comm_wide == pytest.approx(comm_every / 8)
    assert wide["predicted_bytes_per_step"] == pytest.approx(8 * MIB / 8)


def test_predict_rejects_unknown_fabric():
    calib = costmodel.calibrate(_toy_report())
    with pytest.raises(ValueError):
        costmodel.predict(calib, {}, "carrier-pigeon")


def test_fabric_flip_compression_wins_slow_baseline_wins_ici():
    # THE prediction the planner exists for: on 1 GbE the dense 8 MiB wire
    # time (~67 ms) dwarfs the compression compute (~0.3 ms), on ICI the
    # ordering inverts — the same configs, ranked per fabric
    calib = costmodel.calibrate(_toy_report())
    configs = [
        {"name": "baseline"},
        {"name": "compress", "reducer": "powersgd", "reducer_rank": 1},
    ]
    ranked = costmodel.search(
        calib, fabrics=["1GbE", "ICI(v5e)"], configs=configs
    )
    assert ranked["1GbE"][0]["config"]["name"] == "compress"
    assert ranked["ICI(v5e)"][0]["config"]["name"] == "baseline"


# ---------------------------------------------------------------------------
# plan document, events, and the realized join
# ---------------------------------------------------------------------------


def test_build_plan_and_prediction_events():
    calib = costmodel.calibrate(_toy_report())
    plan = costmodel.build_plan(calib, fabrics=["1GbE", "ICI(v5e)"])
    assert plan["schema"] == costmodel.PLAN_SCHEMA
    assert set(plan["fabrics"]) == {"1GbE", "ICI(v5e)"}
    for slot in plan["fabrics"].values():
        ranked = slot["ranked"]
        assert slot["best"] == ranked[0]
        steps = [p["predicted_step_s"] for p in ranked]
        assert steps == sorted(steps)
    # every DEFAULT_LADDER rung is priced and named in the ladder ordering
    assert set(r.name for r in DEFAULT_LADDER) <= set(plan["ladder"]["1GbE"])
    events = costmodel.prediction_events(plan, rank=0)
    assert events and all(isinstance(e, PredictionEvent) for e in events)
    rec = events[0].record()
    assert rec["event"] == "prediction"
    assert rec["config_key"] and rec["predicted_step_s"] > 0


def test_join_realized_matches_on_the_compile_comm_config():
    calib = costmodel.calibrate(_toy_report())
    plan = costmodel.build_plan(calib, fabrics=["1GbE"])
    pred = next(
        p for p in plan["fabrics"]["1GbE"]["ranked"]
        if p["config"]["name"] == "compress-low-rank"
    )
    realized = pred["predicted_step_s"] * 1.10  # realized 10% slower
    report = _toy_report(
        step_p50_s=realized,
        compile={
            "comm_config": {"reducer": "powersgd", "reducer_rank": 1},
        },
    )
    joined = costmodel.join_realized(plan, "1GbE", report)
    assert joined["matched"] is True
    assert joined["config_key"] == pred["config_key"]
    assert joined["error"] == pytest.approx(0.10 / 1.10)
    assert joined["beats_default"] is True  # < the 80 ms source step
    # no such fabric in the plan, or no usable step time -> None
    assert costmodel.join_realized(plan, "10GbE", report) is None
    assert (
        costmodel.join_realized(plan, "1GbE", {"step_p50_s": None}) is None
    )


# ---------------------------------------------------------------------------
# the plan-ordered fallback ladder
# ---------------------------------------------------------------------------


def test_ladder_from_plan_reorders_prunes_and_survives_staleness():
    plan = {"ladder": {"1GbE": ["compress", "ghost-rung", "baseline"]}}
    ordered = ladder_from_plan(plan, "1GbE")
    names = [r.name for r in ordered]
    # plan-named rungs lead (unknown names ignored), the rest keep their
    # static order, nothing is lost
    assert names[:2] == ["compress", "baseline"]
    assert set(names) == set(r.name for r in DEFAULT_LADDER)
    pruned = ladder_from_plan(plan, "1GbE", max_rungs=2)
    assert [r.name for r in pruned] == ["compress", "baseline"]
    # a stale plan without this fabric leaves the ladder untouched
    same = ladder_from_plan(plan, "ICI(v5e)")
    assert [r.name for r in same] == [r.name for r in DEFAULT_LADDER]
    assert [r.name for r in ladder_from_plan({}, "1GbE")] == [
        r.name for r in DEFAULT_LADDER
    ]


# ---------------------------------------------------------------------------
# gate: costmodel_error extraction, the 25% ceiling, missing_baseline
# ---------------------------------------------------------------------------


def test_gate_extracts_costmodel_error_and_enforces_the_ceiling():
    gate = _load_script("gate")
    report = {"costmodel": {"error": 0.07}}
    metrics = gate.extract_metrics(report)
    assert metrics["costmodel_error"] == pytest.approx(0.07)
    ok = gate.costmodel_target_verdict(metrics, report, {})
    assert len(ok) == 1 and not ok[0]["regressed"]
    assert ok[0]["baseline"] == gate.DEFAULT_COSTMODEL_ERROR_TARGET
    bad = gate.costmodel_target_verdict(
        {"costmodel_error": 0.40}, {}, {}
    )
    assert bad[0]["regressed"]
    # a recorded per-round target overrides the default
    custom = gate.costmodel_target_verdict(
        {"costmodel_error": 0.40}, {}, {"costmodel_error_target": 0.5}
    )
    assert not custom[0]["regressed"]


def test_gate_missing_baseline_is_advisory_never_a_keyerror():
    gate = _load_script("gate")
    verdicts = gate.compare(
        {"costmodel_error": 0.1, "step_p50_s": 0.02},
        {"step_p50_s": 0.02},  # a stale baseline, recorded pre-planner
        tolerance=0.2,
    )
    by_metric = {v["metric"]: v for v in verdicts}
    missing = by_metric["costmodel_error"]
    assert missing["missing_baseline"] is True
    assert missing["regressed"] is False
    assert missing["baseline"] is None
    assert not by_metric["step_p50_s"].get("missing_baseline")
    # a metric only the baseline carries is skipped, not inverted
    assert "mfu" not in by_metric


# ---------------------------------------------------------------------------
# report: --compare over two synthetic run dirs
# ---------------------------------------------------------------------------


def _write_toy_run(run_dir, step_s, payload_bytes):
    os.makedirs(run_dir, exist_ok=True)
    m = runlog.new_manifest(os.path.basename(run_dir), world_size=1)
    m.record_spawn(rank=0, incarnation=0, world_size=1, spawned_unix=100.0)
    m.save(run_dir)
    events = [
        {"event": "marker", "kind": "run_start", "ts": 100.0, "ts_mono": 0.0},
        {
            "event": "collective", "label": "toy", "tag": "g", "op": "all-reduce",
            "dtype": "float32", "payload_bytes": payload_bytes, "count": 1,
            "ts": 100.0, "ts_mono": 0.0,
        },
    ]
    t = 0.0
    for i in range(4):
        t += step_s
        events.append({
            "event": "span", "name": "step/compute", "dur_s": step_s * 0.5,
            "depth": 0, "rank": 0, "step": i, "ts": 100.0 + t, "ts_mono": t,
        })
        events.append({
            "event": "step", "step": i, "epoch": 0, "loss": 1.0,
            "step_time_s": step_s, "rank": 0, "ts": 100.0 + t, "ts_mono": t,
        })
    with open(runlog.shard_path(run_dir, 0), "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")


def test_compare_runs_diffs_step_time_bytes_and_span_shares(tmp_path):
    report = _load_script("report")
    a, b = str(tmp_path / "runA"), str(tmp_path / "runB")
    _write_toy_run(a, step_s=0.02, payload_bytes=4 * MIB)
    _write_toy_run(b, step_s=0.01, payload_bytes=1 * MIB)
    text, doc = report.compare_runs(a, b)
    assert doc["schema"] == 1
    step = doc["metrics"]["step_p50_s"]
    assert step["ratio"] == pytest.approx(0.5, rel=0.05)
    assert doc["metrics"]["bandwidth.total.payload_bytes"]["ratio"] == (
        pytest.approx(0.25)
    )
    assert "step/compute" in doc["span_shares"]
    assert "run compare" in text and "B/A" in text


# ---------------------------------------------------------------------------
# analytics edge cases the planner leans on
# ---------------------------------------------------------------------------


def test_percentile_single_sample_and_empty():
    assert analytics.percentile([0.042], 50) == 0.042
    assert analytics.percentile([0.042], 95) == 0.042
    assert math.isnan(analytics.percentile([], 50))


def test_rank_step_stats_single_step_keeps_the_sample():
    stats = analytics.rank_step_stats(
        [{"event": "step", "rank": 0, "step_time_s": 0.5}]
    )
    # one timed step: drop_first must not divide by an empty window
    assert stats[0]["n"] == 1
    assert stats[0]["p50_s"] == 0.5
    assert stats[0]["mean_s"] == 0.5


def test_span_summary_zero_duration_spans_do_not_divide_by_zero():
    report = _load_script("report")
    spans = report.span_summary([
        {"event": "span", "name": "noop", "dur_s": 0.0, "rank": 0,
         "depth": 0, "ts": 1.0},
    ])
    slot = spans["by_name"]["noop"]
    assert slot["mean_s"] == 0.0 and slot["total_s"] == 0.0
    # a single instant gives zero wall-clock: share is None, not a crash
    assert slot["share"] is None


def test_effective_bandwidth_ledger_without_overlap_or_bytes():
    ledger = [{"tag": "g", "op": "all-reduce", "payload_bytes": 1000.0}]
    # no overlap extract: every byte charged exposed, still a full answer
    bw = analytics.effective_bandwidth(0.01, ledger, n_workers=2, overlap=None)
    assert bw["total"]["achieved_bytes_per_s"] == pytest.approx(1000.0 / 0.01)
    assert bw["attribution"]["n_collectives"] == 0
    # nothing priceable -> None, never a ZeroDivisionError
    assert analytics.effective_bandwidth(0.01, [], n_workers=2) is None
    assert analytics.effective_bandwidth(0.0, ledger, n_workers=2) is None
    assert (
        analytics.effective_bandwidth(
            0.01, [{"tag": "g", "payload_bytes": None}], n_workers=2
        )
        is None
    )
