"""The gpt_generate launcher entry point: KV-cache decode throughput with
greedy determinism."""


def test_gpt_generate_entry_point(devices):
    from network_distributed_pytorch_tpu.launch import main

    out = main(
        ["gpt_generate", "--preset", "small", "--max-new-tokens", "16"]
    )
    assert out["experiment"] == "gpt_generate"
    assert out["generate_tokens_per_sec"] > 0
    assert out["decode_ms_per_token"] > 0 and out["prefill_ms"] > 0
    assert len(out["sample_head"]) == 8
    # greedy decode is deterministic
    out2 = main(
        ["gpt_generate", "--preset", "small", "--max-new-tokens", "16"]
    )
    assert out["sample_head"] == out2["sample_head"]
