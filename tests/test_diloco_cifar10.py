"""DiLoCo CIFAR launcher entry point: training descends in rounds, the
streaming variant runs, and the logged wire total equals rounds x the
round's analytic cost."""

import numpy as np
import pytest


def _run(**kw):
    from network_distributed_pytorch_tpu.experiments import diloco_cifar10
    from network_distributed_pytorch_tpu.utils.config import ExperimentConfig

    cfg = ExperimentConfig(
        training_epochs=2, global_batch_size=64, reducer_rank=2, log_every=0,
    )
    return diloco_cifar10.run(
        config=cfg, preset="small", data_dir="/nonexistent",
        sync_every=4, inner_learning_rate=0.05, max_steps_per_epoch=8, **kw,
    )


@pytest.mark.slow
def test_diloco_cifar10_compressed_rounds(devices):
    out = _run(reducer="powersgd")
    assert out["final_loss"] < out["first_loss"], out
    # 2 epochs x 2 rounds, each round = one reducer pass over params
    assert out["steps"] == 4
    np.testing.assert_allclose(
        out["bits_communicated"], 4 * out["bits_per_round"]
    )


@pytest.mark.slow
def test_diloco_cifar10_streaming(devices):
    out = _run(reducer="powersgd", fragments=2)
    assert out["experiment"] == "diloco_cifar10"
    assert out["fragments"] == 2
    assert np.isfinite(out["final_loss"])


@pytest.mark.slow
def test_trailing_partial_round_pads_not_drops(devices, tmp_path, monkeypatch):
    """A dataset that exhausts mid-round must still train every sample: the
    trailing partial round is padded to sync_every with zero-weighted
    batches and synced, so the clean path's data-drop tally is exactly
    zero and the padded round is logged as a real step."""
    import json

    import numpy as np

    from network_distributed_pytorch_tpu.experiments import diloco_cifar10
    from network_distributed_pytorch_tpu.utils.config import ExperimentConfig

    rng = np.random.RandomState(0)
    x = rng.rand(448, 32, 32, 3).astype(np.float32)  # 7 batches of 64
    y = rng.randint(0, 10, size=(448,)).astype(np.int32)
    monkeypatch.setattr(
        diloco_cifar10, "load_cifar10_or_synthetic",
        lambda data_dir, train=True: (x, y, False),
    )
    log = tmp_path / "events.jsonl"
    cfg = ExperimentConfig(
        training_epochs=1, global_batch_size=64, log_every=0,
        event_log=str(log),
    )
    out = diloco_cifar10.run(
        config=cfg, preset="small", data_dir="/nonexistent",
        sync_every=4, inner_learning_rate=0.05,
    )
    # 7 batches at sync_every=4: one full round + one padded (3 real + 1
    # pad) round — both logged, nothing dropped
    assert out["steps"] == 2, out
    assert np.isfinite(out["final_loss"])
    events = [json.loads(l) for l in log.read_text().splitlines() if l.strip()]
    drops = [e for e in events if e.get("kind") == "data_drop"]
    assert drops == [], drops
