"""DiLoCo CIFAR launcher entry point: training descends in rounds, the
streaming variant runs, and the logged wire total equals rounds x the
round's analytic cost."""

import numpy as np
import pytest


def _run(**kw):
    from network_distributed_pytorch_tpu.experiments import diloco_cifar10
    from network_distributed_pytorch_tpu.utils.config import ExperimentConfig

    cfg = ExperimentConfig(
        training_epochs=2, global_batch_size=64, reducer_rank=2, log_every=0,
    )
    return diloco_cifar10.run(
        config=cfg, preset="small", data_dir="/nonexistent",
        sync_every=4, inner_learning_rate=0.05, max_steps_per_epoch=8, **kw,
    )


@pytest.mark.slow
def test_diloco_cifar10_compressed_rounds(devices):
    out = _run(reducer="powersgd")
    assert out["final_loss"] < out["first_loss"], out
    # 2 epochs x 2 rounds, each round = one reducer pass over params
    assert out["steps"] == 4
    np.testing.assert_allclose(
        out["bits_communicated"], 4 * out["bits_per_round"]
    )


@pytest.mark.slow
def test_diloco_cifar10_streaming(devices):
    out = _run(reducer="powersgd", fragments=2)
    assert out["experiment"] == "diloco_cifar10"
    assert out["fragments"] == 2
    assert np.isfinite(out["final_loss"])
