"""The dryrun golden-parity gate itself (``__graft_entry__._expect``):
tolerance math, drift rejection, the record mode, and the n_devices scoping
— pure-host checks, no mesh needed. The end-to-end use (every strategy
path's loss/checksum against ``_GOLDEN_8``) runs in the driver's
``dryrun_multichip(8)``."""

import importlib.util
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_entry():
    # import by path: __graft_entry__ lives at the repo root, not in the
    # package. The instance is shared module-scoped across these tests —
    # safe because _expect reads os.environ at call time, not import time.
    spec = importlib.util.spec_from_file_location(
        "graft_entry_under_test", os.path.join(REPO, "__graft_entry__.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def entry_mod():
    return _load_entry()


def test_expect_accepts_golden_within_tolerance(entry_mod, monkeypatch):
    monkeypatch.delenv("GRAFT_RECORD_GOLDEN", raising=False)
    name = next(iter(entry_mod._GOLDEN_8))
    want = entry_mod._GOLDEN_8[name]
    entry_mod._expect(name, want, 8)
    entry_mod._expect(name, want * (1 + 1e-6), 8)  # fp jitter passes


def test_expect_rejects_numeric_drift(entry_mod, monkeypatch):
    monkeypatch.delenv("GRAFT_RECORD_GOLDEN", raising=False)
    name = next(iter(entry_mod._GOLDEN_8))
    want = entry_mod._GOLDEN_8[name]
    with pytest.raises(AssertionError, match="numeric drift"):
        entry_mod._expect(name, want * 1.001, 8)  # 0.1% is real drift


def test_expect_rejects_nonfinite_everywhere(entry_mod, monkeypatch):
    monkeypatch.delenv("GRAFT_RECORD_GOLDEN", raising=False)
    with pytest.raises(AssertionError):
        entry_mod._expect("anything", float("nan"), 4)  # even off-golden n


def test_expect_scopes_goldens_to_eight_devices(entry_mod, monkeypatch):
    monkeypatch.delenv("GRAFT_RECORD_GOLDEN", raising=False)
    name = next(iter(entry_mod._GOLDEN_8))
    # wildly wrong value passes at n != 8: goldens are shape-specific
    entry_mod._expect(name, 1e9, 4)


def test_expect_record_mode_prints_instead_of_asserting(
    entry_mod, monkeypatch, capsys
):
    monkeypatch.setenv("GRAFT_RECORD_GOLDEN", "1")
    name = next(iter(entry_mod._GOLDEN_8))
    entry_mod._expect(name, 123.456, 8)  # would fail hard in assert mode
    assert f'"{name}": 123.456' in capsys.readouterr().out


def test_golden_table_is_well_formed(entry_mod):
    """Every golden is a finite float with a healthy magnitude: a value
    cancelling toward zero would make the relative tolerance meaningless
    (the abs-sum checksum convention exists to prevent exactly that)."""
    import math

    assert len(entry_mod._GOLDEN_8) >= 15
    for name, v in entry_mod._GOLDEN_8.items():
        assert math.isfinite(v), name
        assert abs(v) > 1e-3, f"{name}: near-cancelled golden {v}"
