"""utils/profiling smoke: the thin jax.profiler wrappers.

These were 0%-covered plumbing until the span work made them load-bearing
(``experiments.common.train_loop`` wraps every step in
``step_annotation``). The tests pin the contract the loop relies on:
``annotate``/``step_annotation`` enter and exit cleanly even OUTSIDE an
active trace (cheap no-ops — how they run on CPU CI every time), and
``trace`` really round-trips start/stop, leaving a capture on disk and
releasing the profiler even when the body raises.
"""

import os

import jax
import jax.numpy as jnp
import pytest

from network_distributed_pytorch_tpu.utils import profiling


def test_annotate_nests_outside_trace():
    # no active trace: TraceAnnotation must still be a safe no-op region
    with profiling.annotate("outer"):
        with profiling.annotate("inner"):
            x = jnp.ones(()) + 1
    assert float(x) == 2.0


def test_step_annotation_wraps_computation():
    with profiling.step_annotation("toy_run", step=3):
        y = jax.jit(lambda a: a * 2)(jnp.arange(4.0))
    assert float(y.sum()) == 12.0


def test_trace_writes_capture(tmp_path):
    log_dir = str(tmp_path / "trace")
    with profiling.trace(log_dir):
        with profiling.step_annotation("toy_run", step=0):
            jax.block_until_ready(jnp.ones((8,)) * 2)
    captured = []
    for _root, _dirs, files in os.walk(log_dir):
        captured.extend(files)
    assert captured, "start/stop produced no capture files"


def test_trace_stops_on_exception(tmp_path):
    """The finally-clause contract: a raising body must still stop the
    profiler, or every later trace() in the process fails with 'profiler
    already started'."""
    with pytest.raises(ValueError, match="boom"):
        with profiling.trace(str(tmp_path / "t1")):
            raise ValueError("boom")
    with profiling.trace(str(tmp_path / "t2")):  # proof the first stopped
        pass
