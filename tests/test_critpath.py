"""Cross-rank critical path + per-edge fabric matrix (jax-free, fast).

Pins the observability tentpole end to end on synthetic evidence: the
typed fabric-model accessor in ``utils.bandwidth`` (scalar tables vs a
measured per-edge matrix, slowest-edge ring semantics against a
hand-computed 3-rank oracle), the critical-path analyzer's blame
discipline (rank AND phase AND ring edge, excess-over-median so a
throttled link is blamed even when compute is absolutely larger), the
matrix measurement/persistence round-trip, the per-edge health-alert
naming, the live aggregator's edge rates, the report's Perfetto
collective-flow arrows and ``--watch`` dashboard rendering, and the
gate's ``critpath_comm_share`` extraction.
"""

import importlib.util
import json
import os
import sys

import pytest

from network_distributed_pytorch_tpu.observe import (
    CritPathEvent,
    critpath,
    fabric,
    runlog,
)
from network_distributed_pytorch_tpu.observe import costmodel
from network_distributed_pytorch_tpu.observe.health import (
    DetectorConfig,
    HealthMonitor,
)
from network_distributed_pytorch_tpu.observe.live import (
    LiveAggregator,
    MetricRegistry,
    ShardFollower,
)
from network_distributed_pytorch_tpu.utils import bandwidth

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS_DIR)

MIB = 1 << 20


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        f"_critpath_test_{name}", os.path.join(REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[f"_critpath_test_{name}"] = mod
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# the typed fabric-model accessor (utils.bandwidth)
# ---------------------------------------------------------------------------


def test_ring_neighbors():
    assert bandwidth.ring_neighbors(3) == [(0, 1), (1, 2), (2, 0)]
    assert bandwidth.ring_neighbors(2) == [(0, 1), (1, 0)]
    assert bandwidth.ring_neighbors(1) == []
    assert bandwidth.ring_neighbors(0) == []


def test_fabric_model_scalar_matches_tables():
    model = bandwidth.fabric_model()
    assert not model.per_edge
    assert model.bottleneck() is None
    for name, rate in bandwidth.FABRICS_BYTES_PER_S.items():
        assert model.ring_beta(name) == rate
        assert model.ring_latency_s(name) == bandwidth.LATENCY_S[name]
    # the model's allreduce matches the module-level closed form
    assert model.allreduce_time_s(MIB, 4, "10GbE") == pytest.approx(
        bandwidth.allreduce_time_s(MIB, 4, "10GbE")
    )


def test_fabric_model_matrix_slowest_edge_gates():
    matrix = {
        "edges": [
            {"src": 0, "dst": 1, "bytes_per_s": 2e9, "latency_s": 1e-4},
            {"src": 1, "dst": 2, "bytes_per_s": 0.5e9, "latency_s": 2e-4},
            {"src": 2, "dst": 0, "bytes_per_s": 1e9, "latency_s": 1e-4},
        ]
    }
    model = bandwidth.fabric_model(matrix)
    assert model.per_edge
    bn = model.bottleneck()
    assert (bn.src, bn.dst) == (1, 2)
    # the matrix overrides every named fabric's scalar: the worst link
    # gates the ring regardless of what the fabric claims
    assert model.ring_beta("100GbE") == 0.5e9
    assert model.ring_latency_s("100GbE") == 2e-4


def test_fabric_model_degrades_on_malformed_matrix():
    for bad in (None, "nope", {"edges": "x"},
                {"edges": [{"src": 0}]},
                {"edges": [{"src": 0, "dst": 1, "bytes_per_s": 0}]},
                {"edges": [{"src": 0, "dst": 1, "bytes_per_s": -2.0}]}):
        model = bandwidth.fabric_model(bad)
        assert not model.per_edge
        assert model.ring_beta("10GbE") == bandwidth.FABRICS_BYTES_PER_S[
            "10GbE"
        ]


def test_costmodel_slowest_edge_oracle_3_rank_ring():
    """Acceptance oracle: predict() with a measured 3-rank matrix must
    price the ring against the slowest edge, term by hand-computed term."""
    calib = costmodel.CostCalibration(
        step_time_s=0.05, compute_s=0.03, dense_bytes=float(4 * MIB),
        bytes_per_step=float(4 * MIB), n_workers=3, exposed_fraction=1.0,
        n_collectives=1,
    )
    worst_beta = 0.25e9
    worst_lat = 5e-4
    matrix = {
        "edges": [
            {"src": 0, "dst": 1, "bytes_per_s": 4e9, "latency_s": 1e-5},
            {"src": 1, "dst": 2, "bytes_per_s": worst_beta,
             "latency_s": worst_lat},
            {"src": 2, "dst": 0, "bytes_per_s": 2e9, "latency_s": 1e-5},
        ]
    }
    pred = costmodel.predict(calib, {"reducer": "exact"}, "100GbE",
                             matrix=matrix)
    # hand oracle: 2(W-1)/W * B / beta_worst + n_coll * lat_worst
    wire = (2.0 * 2 / 3) * (4 * MIB) / worst_beta
    assert pred["wire_s"] == pytest.approx(wire)
    assert pred["predicted_step_s"] == pytest.approx(
        0.03 + wire + worst_lat
    )
    assert pred["per_edge"] is True
    assert pred["bottleneck_edge"] == {"src": 1, "dst": 2}
    # without the matrix the same fabric prices off its (faster) scalar
    scalar = costmodel.predict(calib, {"reducer": "exact"}, "100GbE")
    assert scalar["per_edge"] is False
    assert scalar["bottleneck_edge"] is None
    assert scalar["predicted_step_s"] < pred["predicted_step_s"]


# ---------------------------------------------------------------------------
# the critical-path analyzer
# ---------------------------------------------------------------------------


def _span(step, rank, name, dur, span_id=None, parent_id=None):
    return {
        "event": "span", "name": name, "dur_s": dur, "step": step,
        "rank": rank, "span_id": span_id or f"s{step}r{rank}{name}",
        "parent_id": parent_id,
    }


def _rank_step(step, rank, data=0.0, compute=0.01, comm=0.0):
    """One rank-step's leaf spans under a container (the toy layout)."""
    container = f"c{step}r{rank}"
    spans = [
        {"event": "span", "name": "step", "dur_s": data + compute + comm,
         "step": step, "rank": rank, "span_id": container,
         "parent_id": None},
        _span(step, rank, "step/compute", compute, parent_id=container),
    ]
    if data > 0:
        spans.append(_span(step, rank, "data_load", data,
                           parent_id=container))
    if comm > 0:
        spans.append(_span(step, rank, "step/comm", comm,
                           parent_id=container))
    return spans


def test_phase_of_taxonomy():
    assert critpath.phase_of("data_load") == critpath.PHASE_DATA
    assert critpath.phase_of("step/comm") == critpath.PHASE_COMM
    assert critpath.phase_of("step/compute") == critpath.PHASE_COMPUTE
    assert critpath.phase_of("checkpoint/save") == critpath.PHASE_COMPUTE


def test_analyze_blames_rank_phase_and_edge():
    events = []
    for step in range(4):
        events += _rank_step(step, 0, compute=0.010, comm=0.002)
        slow = 0.002 if step == 0 else 0.050  # throttle lands at step 1
        events += _rank_step(step, 1, compute=0.010, comm=slow)
    crit = critpath.analyze(events, world_size=2)
    assert crit is not None
    assert crit["n_steps"] == 4
    late = [e for e in crit["events"] if e["step"] >= 1]
    assert all(e["rank"] == 1 for e in late)
    assert all(e["phase"] == critpath.PHASE_COMM for e in late)
    assert all(
        (e["edge_src"], e["edge_dst"]) == (1, 0) for e in late
    )
    assert crit["top_edge"] == {"src": 1, "dst": 0, "blamed_steps": 3}
    assert crit["blame_by_rank"]["1"] > 0.5
    assert crit["blame_by_phase"][critpath.PHASE_COMM] > 0.5
    assert 0 < crit["comm_share"] <= 1


def test_blame_is_excess_over_median_not_absolute():
    # compute (40 ms) is absolutely larger than comm everywhere, but only
    # rank 2's comm stands out vs the cross-rank median -> blame comm
    per_rank = {
        0: {"data_load": 0.0, "compute": 0.040, "collective-wait": 0.002},
        1: {"data_load": 0.0, "compute": 0.040, "collective-wait": 0.002},
        2: {"data_load": 0.0, "compute": 0.041, "collective-wait": 0.020},
    }
    ev = critpath.step_blame(per_rank, world_size=3, step=7)
    assert isinstance(ev, CritPathEvent)
    assert ev.rank == 2
    assert ev.phase == critpath.PHASE_COMM
    assert (ev.edge_src, ev.edge_dst) == (2, 0)
    assert ev.path_s == pytest.approx(0.061)


def test_step_blame_uniform_ranks_fall_back_to_absolute_phase():
    per_rank = {
        0: {"data_load": 0.0, "compute": 0.040, "collective-wait": 0.002},
        1: {"data_load": 0.0, "compute": 0.040, "collective-wait": 0.002},
    }
    ev = critpath.step_blame(per_rank, world_size=2, step=0)
    assert ev.phase == critpath.PHASE_COMPUTE
    assert ev.edge_src is None and ev.edge_dst is None


def test_analyze_none_without_ranked_spans():
    assert critpath.analyze([], world_size=2) is None
    # spans without step/rank (the single-log mode) carry no evidence
    assert critpath.analyze(
        [{"event": "span", "name": "step/compute", "dur_s": 0.01}], 2
    ) is None


def test_critpath_event_record_round_trip():
    ev = CritPathEvent(step=3, rank=1, phase="collective-wait",
                       path_s=0.05, edge_src=1, edge_dst=0,
                       comm_s=0.04, compute_s=0.01)
    rec = ev.record()
    assert rec["event"] == "critpath"
    assert (rec["step"], rec["rank"]) == (3, 1)
    assert rec["phase"] == "collective-wait"
    assert (rec["edge_src"], rec["edge_dst"]) == (1, 0)


# ---------------------------------------------------------------------------
# the measured fabric matrix
# ---------------------------------------------------------------------------


def _collective(rank, payload=MIB):
    return {
        "event": "collective", "label": "toy", "tag": "toy.grads",
        "layer": "reducer", "op": "all-reduce", "axis": "data",
        "dtype": "float32", "payload_bytes": payload, "rank": rank,
    }


def test_measure_fabric_matrix_rates_and_bottleneck():
    events = [_collective(0), _collective(1)]  # dedupes to ONE payload
    for step in range(5):
        events += _rank_step(step, 0, comm=0.010)
        events += _rank_step(step, 1, comm=0.100)
    matrix = fabric.measure_fabric_matrix(events, world_size=2)
    assert matrix is not None
    assert matrix["topology"] == "ring"
    assert matrix["per_step_bytes"] == pytest.approx(float(MIB))
    per_edge_bytes = 2.0 * 1 / 2 * MIB
    assert matrix["per_step_edge_bytes"] == pytest.approx(per_edge_bytes)
    rows = {(r["src"], r["dst"]): r for r in matrix["edges"]}
    # warmup: the first wait per rank is dropped, 4 samples remain
    assert rows[(0, 1)]["n_steps"] == 4
    assert rows[(0, 1)]["bytes_per_s"] == pytest.approx(
        per_edge_bytes / 0.010
    )
    assert rows[(1, 0)]["bytes_per_s"] == pytest.approx(
        per_edge_bytes / 0.100
    )
    assert matrix["bottleneck"] == {"src": 1, "dst": 0}
    # the utilization table prices each edge against every named fabric
    util = fabric.edge_utilization(matrix)
    u01 = next(r for r in util if (r["src"], r["dst"]) == (0, 1))
    assert u01["utilization"]["10GbE"] == pytest.approx(
        (per_edge_bytes / 0.010) / bandwidth.FABRICS_BYTES_PER_S["10GbE"]
    )


def test_measure_fabric_matrix_needs_evidence():
    assert fabric.measure_fabric_matrix([], 2) is None
    assert fabric.measure_fabric_matrix([_collective(0)], 1) is None
    # ledger but no comm spans
    assert fabric.measure_fabric_matrix([_collective(0)], 2) is None
    # comm spans but no ledger bytes
    events = _rank_step(0, 0, comm=0.01) + _rank_step(0, 1, comm=0.01)
    assert fabric.measure_fabric_matrix(events, 2) is None


def test_matrix_save_load_round_trip(tmp_path):
    events = [_collective(0)]
    for step in range(3):
        events += _rank_step(step, 0, comm=0.01)
        events += _rank_step(step, 1, comm=0.02)
    matrix = fabric.measure_fabric_matrix(events, 2)
    path = str(tmp_path / "fabric_matrix.json")
    fabric.save_matrix(matrix, path)
    loaded = fabric.load_matrix(path)
    assert loaded == json.loads(json.dumps(matrix))
    # and the loaded doc drives the typed accessor
    model = bandwidth.fabric_model(loaded)
    assert model.per_edge
    assert fabric.load_matrix(str(tmp_path / "absent.json")) is None
    (tmp_path / "bad.json").write_text("{not json")
    assert fabric.load_matrix(str(tmp_path / "bad.json")) is None
    (tmp_path / "empty.json").write_text('{"edges": []}')
    assert fabric.load_matrix(str(tmp_path / "empty.json")) is None


# ---------------------------------------------------------------------------
# per-edge health alerts + live edge rates
# ---------------------------------------------------------------------------


def test_health_monitor_per_edge_alert_names_edge():
    cfg = DetectorConfig(collapse_min_obs=3, collapse_sustain=1,
                         cooldown=100)
    mon = HealthMonitor(cfg)
    for _ in range(5):
        assert mon.observe_bytes_per_s(1e9, edge=(1, 0)) == []
        assert mon.observe_bytes_per_s(1e9) == []  # aggregate detector
    fired = mon.observe_bytes_per_s(1e7, edge=(1, 0))
    assert len(fired) == 1
    assert fired[0].alert == "bandwidth_collapse"
    assert fired[0].message.startswith("edge 1->0:")
    assert fired[0].rank == 1
    # the collapse on edge (1, 0) must not have touched edge (0, 1)
    assert mon.observe_bytes_per_s(1e9, edge=(0, 1)) == []


def _live_run_dir(tmp_path, comm_by_rank):
    run_dir = str(tmp_path)
    m = runlog.new_manifest("runC", world_size=2)
    for r in (0, 1):
        m.record_spawn(rank=r, incarnation=0, world_size=2,
                       spawned_unix=100.0)
    m.save(run_dir)
    for r, comm in comm_by_rank.items():
        shard = os.path.join(run_dir, runlog.shard_name(r))
        with open(shard, "a") as f:
            f.write(json.dumps({
                "event": "marker", "kind": "run_start", "run_id": "runC",
                "rank": r, "world_size": 2, "incarnation": 0,
                "ts": 100.5, "ts_mono": 50.0,
            }) + "\n")
            f.write(json.dumps(_collective(r)) + "\n")
            for step, dur in enumerate(comm):
                f.write(json.dumps(_span(step, r, "step/comm", dur)) + "\n")
    return run_dir


def test_aggregator_edge_rates_and_gauges(tmp_path):
    run_dir = _live_run_dir(
        tmp_path, {0: [0.01, 0.01, 0.01], 1: [0.05, 0.05, 0.05]}
    )
    agg = LiveAggregator(run_dir)
    agg.poll()
    rates = agg.edge_rates()
    per_edge_bytes = 2.0 * 1 / 2 * MIB
    assert rates[(0, 1)] == pytest.approx(per_edge_bytes / 0.01)
    assert rates[(1, 0)] == pytest.approx(per_edge_bytes / 0.05)
    assert agg.registry.get_gauge(
        "live_edge_bytes_per_s", edge="1->0"
    ) == pytest.approx(per_edge_bytes / 0.05)


# ---------------------------------------------------------------------------
# satellite: ShardFollower truncation/rotation round-trip
# ---------------------------------------------------------------------------


def test_follower_truncation_resets_cleanly(tmp_path):
    shard = str(tmp_path / "events_rank0.jsonl")
    with open(shard, "w") as f:
        for i in range(4):
            f.write(json.dumps({"event": "step", "step": i}) + "\n")
    follower = ShardFollower(shard)
    assert [e["step"] for e in follower.poll()] == [0, 1, 2, 3]
    saved = follower.offset
    # rotation: the file is truncated SHORTER than the persisted offset
    # and a new incarnation starts writing from scratch
    with open(shard, "w") as f:
        f.write(json.dumps({"event": "step", "step": 100}) + "\n")
    assert os.path.getsize(shard) < saved
    resumed = ShardFollower(shard, offset=saved)
    assert [e["step"] for e in resumed.poll()] == [100]  # reset, no raise
    with open(shard, "a") as f:
        f.write(json.dumps({"event": "step", "step": 101}) + "\n")
    assert [e["step"] for e in resumed.poll()] == [101]  # and keeps tailing


# ---------------------------------------------------------------------------
# report plumbing: watch dashboard, flow arrows, critpath section, gate
# ---------------------------------------------------------------------------


class _StubAgg:
    def __init__(self, registry=None, alerts=None, run_dir=""):
        self.registry = registry or MetricRegistry()
        self.alerts = alerts or []
        self.run_dir = run_dir


def test_render_watch_frame_never_raises_on_empty_or_partial(tmp_path):
    report = _load_script("report")
    # empty: a fresh registry with no samples at all
    frame = report.render_watch_frame(_StubAgg(run_dir=str(tmp_path)))
    assert "alerts fired: 0" in frame
    assert "steps" in frame
    # partial: some gauges present, others absent, odd label shapes
    reg = MetricRegistry()
    reg.counter("live_steps_total", 5, rank="0")
    reg.gauge("live_step_time_p50_seconds", 0.012)
    reg.gauge("live_comm_bytes_per_s", 1.5e8)
    reg.gauge("live_fabric_utilization", 0.4, fabric="10GbE")
    reg.gauge("live_edge_bytes_per_s", 2e7, edge="1->0")
    reg.gauge("live_torn_lines_total", 2)
    frame = report.render_watch_frame(_StubAgg(registry=reg))
    assert "p50" in frame and "10GbE" in frame
    assert "1->0" in frame  # the per-edge tile rides the dashboard
    assert "torn shard lines: 2" in frame
    # a real (but empty) aggregator over an empty run dir also renders
    agg = LiveAggregator(str(tmp_path))
    agg.poll()
    assert report.render_watch_frame(agg, run_dir=str(tmp_path))


def test_chrome_trace_emits_paired_flow_arrows():
    report = _load_script("report")
    events = []
    base = 100.0
    for step in range(2):
        for rank in (0, 1):
            t = base + step * 0.1 + 0.05
            events.append({
                "event": "span", "name": "step/comm", "dur_s": 0.02,
                "step": step, "rank": rank, "span_id": f"c{step}{rank}",
                "t_run": t,
            })
    doc = report.chrome_trace(events)
    flows = [e for e in doc["traceEvents"]
             if e.get("cat") == "collective-flow"]
    starts = [e for e in flows if e["ph"] == "s"]
    finishes = [e for e in flows if e["ph"] == "f"]
    # 2 steps x 2 ranks chained cyclically = 4 arrows, each s+f paired
    assert len(starts) == 4 and len(finishes) == 4
    assert {e["id"] for e in starts} == {e["id"] for e in finishes}
    for f in finishes:
        assert f["bp"] == "e"
    # every arrow crosses ranks: its s and f land on different pids
    by_id = {e["id"]: e for e in starts}
    for f in finishes:
        assert f["pid"] != by_id[f["id"]]["pid"]


def test_render_critpath_section_renders_matrix_table():
    report = _load_script("report")
    events = [_collective(0)]
    for step in range(3):
        events += _rank_step(step, 0, comm=0.01)
        events += _rank_step(step, 1, comm=0.05)
    crit = critpath.analyze(events, 2)
    matrix = fabric.measure_fabric_matrix(events, 2)
    lines = report.render_critpath_section(
        crit, matrix, clock_skew_bound_s=0.002
    )
    text = "\n".join(lines)
    assert "critical path (cross-rank)" in text
    assert "top gating edge 1 -> 0" in text
    assert "bottleneck edge: 1 -> 0" in text
    assert "+/- 2.0 ms" in text
    # and the empty case renders nothing rather than raising
    assert report.render_critpath_section(None, None) == []


def test_gate_extracts_critpath_comm_share():
    gate = _load_script("gate")
    assert gate.METRICS["critpath_comm_share"] == "lower"
    nested = gate.extract_metrics({"critpath": {"comm_share": 0.25}})
    assert nested["critpath_comm_share"] == 0.25
    flat = gate.extract_metrics({"critpath_comm_share": 0.0})
    assert flat["critpath_comm_share"] == 0.0  # zero is healthy, records
    # current-only metric vs a stale baseline: advisory, never a regression
    verdicts = gate.compare(
        {"critpath_comm_share": 0.3}, {"step_p50_s": 0.01}, tolerance=0.05
    )
    v = next(v for v in verdicts if v["metric"] == "critpath_comm_share")
    assert v.get("missing_baseline") is True
    assert v["regressed"] is False
