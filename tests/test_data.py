"""Data pipeline tests: CIFAR-10 binary format round-trip, IMDb directory
parsing (reference ``read_imdb_split`` semantics), tokenizer determinism,
batch iteration static shapes."""

import pickle

import numpy as np

from network_distributed_pytorch_tpu.data import (
    HashTokenizer,
    iterate_batches,
    load_cifar10,
    load_cifar10_or_synthetic,
    prepare_imdb,
    read_imdb_split,
    steps_per_epoch,
    synthetic_cifar10,
)


def _write_fake_cifar(tmp_path):
    base = tmp_path / "cifar-10-batches-py"
    base.mkdir(parents=True)
    rng = np.random.RandomState(0)
    for i in range(1, 6):
        entry = {
            "data": rng.randint(0, 256, (20, 3072), dtype=np.uint8),
            "labels": rng.randint(0, 10, 20).tolist(),
        }
        with open(base / f"data_batch_{i}", "wb") as f:
            pickle.dump(entry, f)
    entry = {
        "data": rng.randint(0, 256, (10, 3072), dtype=np.uint8),
        "labels": rng.randint(0, 10, 10).tolist(),
    }
    with open(base / "test_batch", "wb") as f:
        pickle.dump(entry, f)


def test_cifar10_binary_format(tmp_path):
    _write_fake_cifar(tmp_path)
    x, y = load_cifar10(str(tmp_path), train=True)
    assert x.shape == (100, 32, 32, 3) and x.dtype == np.float32
    assert y.shape == (100,) and y.dtype == np.int32
    # normalization: ((u8/255) - .5)/.5 in [-1, 1]
    assert -1.0 <= x.min() and x.max() <= 1.0
    xt, yt = load_cifar10(str(tmp_path), train=False)
    assert xt.shape == (10, 32, 32, 3)
    # channel unpacking: first 1024 bytes are the R plane
    with open(tmp_path / "cifar-10-batches-py" / "data_batch_1", "rb") as f:
        raw = pickle.load(f, encoding="latin1")["data"]
    np.testing.assert_allclose(
        x[0, 0, 0, 0], ((raw[0, 0] / 255.0) - 0.5) / 0.5, rtol=1e-6
    )


def test_cifar10_fallback(tmp_path):
    x, y, real = load_cifar10_or_synthetic(str(tmp_path / "nope"), synthetic_n=64)
    assert not real and x.shape == (64, 32, 32, 3)
    sx, sy = synthetic_cifar10(32, seed=1)
    sx2, sy2 = synthetic_cifar10(32, seed=1)
    np.testing.assert_array_equal(sx, sx2)  # deterministic


def test_read_imdb_split(tmp_path):
    for label in ["pos", "neg"]:
        d = tmp_path / "train" / label
        d.mkdir(parents=True)
        for i in range(3):
            (d / f"{i}.txt").write_text(f"{label} review {i}")
    texts, labels = read_imdb_split(str(tmp_path / "train"))
    assert len(texts) == 6
    # pos first (label 1), then neg (label 0) — reference iteration order
    assert labels == [1, 1, 1, 0, 0, 0]
    assert texts[0].startswith("pos")


def test_hash_tokenizer():
    tok = HashTokenizer(vocab_size=1000, max_len=16)
    out = tok(["hello world", "hello world hello"])
    assert out["input_ids"].shape == (2, 16)
    # [CLS] first, [SEP] terminated, deterministic ids, mask aligned
    assert out["input_ids"][0, 0] == 1
    assert out["input_ids"][0, 3] == 2
    assert out["attention_mask"][0].sum() == 4
    assert out["input_ids"][0, 1] == out["input_ids"][1, 1]  # same word, same id
    assert (out["input_ids"] < 1000).all()


def test_prepare_imdb_synthetic():
    train, val, real = prepare_imdb(max_len=32, vocab_size=512, synthetic_n=100)
    assert not real
    assert train["input_ids"].shape == (80, 32)
    assert val["input_ids"].shape == (20, 32)
    assert set(np.unique(train["labels"])) <= {0, 1}


def test_iterate_batches_static_shapes():
    x = np.arange(103)
    y = np.arange(103) * 2
    batches = list(iterate_batches([x, y], 10, seed=1, epoch=0))
    assert len(batches) == 10 == steps_per_epoch(103, 10)
    for bx, by in batches:
        assert bx.shape == (10,)
        np.testing.assert_array_equal(by, bx * 2)  # alignment preserved
    # different epoch -> different order; same epoch -> same order
    b0 = list(iterate_batches([x], 10, seed=1, epoch=0))
    b1 = list(iterate_batches([x], 10, seed=1, epoch=1))
    b0b = list(iterate_batches([x], 10, seed=1, epoch=0))
    assert not all(np.array_equal(a[0], b[0]) for a, b in zip(b0, b1))
    assert all(np.array_equal(a[0], b[0]) for a, b in zip(b0, b0b))


def test_device_prefetch_preserves_trajectory():
    """train_loop with async device prefetch must produce the IDENTICAL
    training trajectory as the unprefetched loop (staging is pure overlap,
    never reordering), on the real 8-device mesh step."""
    import jax.numpy as jnp

    from network_distributed_pytorch_tpu.experiments.common import train_loop
    from network_distributed_pytorch_tpu.parallel import ExactReducer, make_mesh
    from network_distributed_pytorch_tpu.parallel.trainer import (
        make_train_step,
        stateless_loss,
    )

    rng = np.random.RandomState(0)
    x = rng.randn(64, 8).astype(np.float32)
    y = (x @ rng.randn(8, 1).astype(np.float32))[:, 0]
    params = {"w": jnp.zeros((8,))}
    loss = stateless_loss(
        lambda p, b: ((b[0] @ p["w"] - b[1]) ** 2).mean()
    )
    step = make_train_step(
        loss, ExactReducer(), params, 0.05, mesh=make_mesh(),
        algorithm="sgd_plain", donate_state=False,
    )

    def batches(epoch):
        yield from iterate_batches([x, y], 16, seed=7, epoch=epoch)

    outs = []
    for prefetch in (0, 2):
        state = step.init_state(params)
        state, logger = train_loop(
            step, state, batches, epochs=2, log_every=0, prefetch=prefetch
        )
        outs.append((np.asarray(state.params["w"]), logger.summary()["final_loss"]))
    np.testing.assert_array_equal(outs[0][0], outs[1][0])
    assert outs[0][1] == outs[1][1]


def test_cifar10_bin_format_matches_pickle(tmp_path):
    """The SAME dataset written as cifar-10-batches-bin (native decoder) and
    cifar-10-batches-py (pickle) loads to identical arrays."""
    _write_fake_cifar(tmp_path)
    xp, yp = load_cifar10(str(tmp_path), train=True)
    xpt, ypt = load_cifar10(str(tmp_path), train=False)

    bin_root = tmp_path / "bin"
    base = bin_root / "cifar-10-batches-bin"
    base.mkdir(parents=True)

    def write_bin(pickle_name, bin_name):
        with open(tmp_path / "cifar-10-batches-py" / pickle_name, "rb") as f:
            entry = pickle.load(f, encoding="latin1")
        np.concatenate(
            [
                np.asarray(entry["labels"], np.uint8)[:, None],
                np.asarray(entry["data"], np.uint8),
            ],
            axis=1,
        ).tofile(base / bin_name)

    for i in range(1, 6):
        write_bin(f"data_batch_{i}", f"data_batch_{i}.bin")
    write_bin("test_batch", "test_batch.bin")

    xb, yb = load_cifar10(str(bin_root), train=True)
    xbt, ybt = load_cifar10(str(bin_root), train=False)
    np.testing.assert_array_equal(yb, yp)
    np.testing.assert_array_equal(ybt, ypt)
    np.testing.assert_allclose(xb, xp, rtol=0, atol=1e-6)
    np.testing.assert_allclose(xbt, xpt, rtol=0, atol=1e-6)


def test_cifar10_bin_rejects_truncated_file(tmp_path):
    base = tmp_path / "cifar-10-batches-bin"
    base.mkdir(parents=True)
    for i in range(1, 6):
        np.zeros(99, np.uint8).tofile(base / f"data_batch_{i}.bin")
    np.zeros(100, np.uint8).tofile(base / "test_batch.bin")  # not a record multiple
    import pytest

    with pytest.raises(ValueError, match="3073"):
        load_cifar10(str(tmp_path), train=False)
    with pytest.raises(ValueError, match="3073"):
        load_cifar10(str(tmp_path), train=True)


def test_cifar10_stale_empty_dir_does_not_shadow(tmp_path):
    """An empty cifar-10-batches-py dir (interrupted download) must not
    shadow a complete cifar-10-batches-bin dir; and a 0-byte bin file fails
    loudly instead of silently shrinking the dataset."""
    from network_distributed_pytorch_tpu.data.cifar10 import cifar10_on_disk

    (tmp_path / "cifar-10-batches-py").mkdir(parents=True)  # empty: unusable
    base = tmp_path / "cifar-10-batches-bin"
    base.mkdir()
    rng = np.random.RandomState(3)
    for i in range(1, 6):
        rec = np.concatenate(
            [
                rng.randint(0, 10, (4, 1), dtype=np.uint8),
                rng.randint(0, 256, (4, 3072), dtype=np.uint8),
            ],
            axis=1,
        )
        rec.tofile(base / f"data_batch_{i}.bin")
    assert cifar10_on_disk(str(tmp_path)) == str(base)
    x, y = load_cifar10(str(tmp_path), train=True)
    assert x.shape == (20, 32, 32, 3)

    # truncate one file to zero bytes: loud failure, not a 16-image epoch
    (base / "data_batch_2.bin").write_bytes(b"")
    import pytest

    with pytest.raises(ValueError, match="3073"):
        load_cifar10(str(tmp_path), train=True)


def test_cifar10_split_aware_format_fallthrough(tmp_path):
    """An eval-only pickle drop must not shadow a bin dir that HAS the
    training split: format selection is per requested split."""
    from network_distributed_pytorch_tpu.data.cifar10 import cifar10_on_disk

    py = tmp_path / "cifar-10-batches-py"
    py.mkdir(parents=True)
    entry = {
        "data": np.zeros((4, 3072), np.uint8),
        "labels": [0, 1, 2, 3],
    }
    with open(py / "test_batch", "wb") as f:
        pickle.dump(entry, f)  # eval-only drop
    bin_dir = tmp_path / "cifar-10-batches-bin"
    bin_dir.mkdir()
    rng = np.random.RandomState(5)
    for i in range(1, 6):
        np.concatenate(
            [
                rng.randint(0, 10, (4, 1), dtype=np.uint8),
                rng.randint(0, 256, (4, 3072), dtype=np.uint8),
            ],
            axis=1,
        ).tofile(bin_dir / f"data_batch_{i}.bin")
    assert cifar10_on_disk(str(tmp_path), train=True) == str(bin_dir)
    assert cifar10_on_disk(str(tmp_path), train=False) == str(py)
    x, _ = load_cifar10(str(tmp_path), train=True)   # bin format
    assert x.shape == (20, 32, 32, 3)
    xt, _ = load_cifar10(str(tmp_path), train=False)  # pickle format
    assert xt.shape == (4, 32, 32, 3)


def test_cifar10_partial_train_dir_falls_through(tmp_path):
    """A pickle dir holding only data_batch_1 (interrupted extraction) must
    not satisfy the train probe — load_cifar10 reads batches 1-5 and would
    crash with a raw FileNotFoundError from open(). The probe requires all
    five, so the complete bin dir wins (and with no alternative, the loader
    raises its own clear FileNotFoundError)."""
    from network_distributed_pytorch_tpu.data.cifar10 import cifar10_on_disk

    py = tmp_path / "cifar-10-batches-py"
    py.mkdir(parents=True)
    with open(py / "data_batch_1", "wb") as f:
        pickle.dump({"data": np.zeros((4, 3072), np.uint8),
                     "labels": [0, 1, 2, 3]}, f)
    # partial dir alone: train probe fails outright -> clear error path
    assert cifar10_on_disk(str(tmp_path), train=True) is None
    import pytest

    with pytest.raises(FileNotFoundError, match="CIFAR-10 not found"):
        load_cifar10(str(tmp_path), train=True)

    # ...and it must not shadow a COMPLETE bin dir
    bin_dir = tmp_path / "cifar-10-batches-bin"
    bin_dir.mkdir()
    rng = np.random.RandomState(7)
    for i in range(1, 6):
        np.concatenate(
            [rng.randint(0, 10, (4, 1), dtype=np.uint8),
             rng.randint(0, 256, (4, 3072), dtype=np.uint8)], axis=1,
        ).tofile(bin_dir / f"data_batch_{i}.bin")
    assert cifar10_on_disk(str(tmp_path), train=True) == str(bin_dir)
    x, _ = load_cifar10(str(tmp_path), train=True)
    assert x.shape == (20, 32, 32, 3)
