"""Ring attention over an 8-device seq mesh ≡ single-device full attention
(exact, up to fp reassociation), including padding masks and causal mode."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from network_distributed_pytorch_tpu.parallel import make_mesh
from network_distributed_pytorch_tpu.parallel.sequence import ring_attention

B, T, H, D = 2, 64, 4, 16  # T sharded 8 ways -> 8 per device


def _full_attention(q, k, v, mask=None, causal=False):
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(D)
    if mask is not None:
        scores = scores + mask[:, None, None, :]
    if causal:
        pos = jnp.arange(T)
        scores = jnp.where(pos[:, None] >= pos[None, :], scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def _qkv(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(ks[i], (B, T, H, D)) for i in range(3))


def _run_ring(q, k, v, mask, causal):
    mesh = make_mesh(axis_sizes=(8,), axis_names=("seq",))

    def body(q, k, v, mask):
        return ring_attention(q, k, v, "seq", mask=mask, causal=causal)

    specs = P(None, "seq")
    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(specs, specs, specs, specs),
            out_specs=specs,
        )
    )(q, k, v, mask)


def test_matches_full_attention(devices):
    q, k, v = _qkv(0)
    mask = jnp.zeros((B, T))
    out = _run_ring(q, k, v, mask, causal=False)
    ref = _full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_padding_mask(devices):
    q, k, v = _qkv(1)
    neg = jnp.asarray(-1e30)
    mask = jnp.zeros((B, T)).at[:, 48:].set(neg)  # last device's keys padded
    out = _run_ring(q, k, v, mask, causal=False)
    ref = _full_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_causal(devices):
    q, k, v = _qkv(2)
    mask = jnp.zeros((B, T))
    out = _run_ring(q, k, v, mask, causal=True)
    ref = _full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)
