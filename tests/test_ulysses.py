"""Ulysses (all-to-all) sequence parallelism over the 8-device seq mesh ≡
single-device full attention, agreement with ring attention, and the
sequence-parallel DistilBERT encoder with seq_impl='ulysses'."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from network_distributed_pytorch_tpu.parallel import make_mesh
from network_distributed_pytorch_tpu.parallel.sequence import (
    ring_attention,
    ulysses_attention,
)

B, T, H, D = 2, 64, 8, 16  # T and H both divide the 8-way shard


def _full_attention(q, k, v, mask=None, causal=False):
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(D)
    if mask is not None:
        scores = scores + mask[:, None, None, :]
    if causal:
        pos = jnp.arange(T)
        scores = jnp.where(pos[:, None] >= pos[None, :], scores, -jnp.inf)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def _qkv(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(ks[i], (B, T, H, D)) for i in range(3))


def _run_sharded(fn, q, k, v, mask, causal):
    mesh = make_mesh(axis_sizes=(8,), axis_names=("seq",))

    def body(q, k, v, mask):
        return fn(q, k, v, "seq", mask=mask, causal=causal)

    specs = P(None, "seq")
    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(specs, specs, specs, specs),
            out_specs=specs,
        )
    )(q, k, v, mask)


@pytest.mark.parametrize("causal", [False, True], ids=["bidir", "causal"])
@pytest.mark.slow
def test_ulysses_matches_full_attention(devices, causal):
    q, k, v = _qkv(1)
    mask = jnp.zeros((B, T)).at[1, 48:].set(-jnp.inf)  # pad tail of row 1
    ref = _full_attention(q, k, v, mask=mask, causal=causal)
    out = _run_sharded(ulysses_attention, q, k, v, mask, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6)


def test_ulysses_matches_ring(devices):
    q, k, v = _qkv(2)
    mask = jnp.zeros((B, T)).at[0, 56:].set(-jnp.inf)
    ring = _run_sharded(ring_attention, q, k, v, mask, False)
    uly = _run_sharded(ulysses_attention, q, k, v, mask, False)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(ring), rtol=2e-5, atol=2e-6)


def test_ulysses_rejects_indivisible_heads(devices):
    mesh = make_mesh(axis_sizes=(8,), axis_names=("seq",))
    q = jnp.zeros((B, T, 4, D))  # 4 heads over 8 shards

    def body(q):
        return ulysses_attention(q, q, q, "seq")

    with pytest.raises(AssertionError, match="must divide"):
        jax.jit(
            jax.shard_map(
                body, mesh=mesh, in_specs=P(None, "seq"), out_specs=P(None, "seq")
            )
        )(q)


@pytest.mark.slow
def test_ulysses_distilbert_encoder_matches_single_device(devices):
    from network_distributed_pytorch_tpu.models.distilbert import (
        DistilBertConfig,
        DistilBertEncoder,
    )

    cfg = dict(
        vocab_size=128, max_position_embeddings=64, dim=32, n_layers=2,
        n_heads=8, hidden_dim=64, dropout=0.0, attention_dropout=0.0,
    )
    base = DistilBertEncoder(DistilBertConfig(**cfg))
    uly = DistilBertEncoder(
        DistilBertConfig(**cfg, seq_axis="seq", seq_impl="ulysses")
    )

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 128, (B, 32)), jnp.int32)
    mask = jnp.ones((B, 32), jnp.int32).at[1, 24:].set(0)

    params = base.init(jax.random.PRNGKey(0), ids, mask)["params"]
    ref = base.apply({"params": params}, ids, mask, deterministic=True)

    mesh = make_mesh(axis_sizes=(8,), axis_names=("seq",))
    out = jax.jit(
        jax.shard_map(
            lambda p, i, m: uly.apply({"params": p}, i, m, deterministic=True),
            mesh=mesh,
            in_specs=(P(), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq"),
        )
    )(params, ids, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)
