"""HLO collective audit: the analytic bytes-on-wire model must equal what
XLA actually compiled (SURVEY §7's 'honest accounting' hard part), and the
audit exposes the combiner's collective-count reduction."""

import jax
import jax.numpy as jnp

from network_distributed_pytorch_tpu.models import SmallCNN
from network_distributed_pytorch_tpu.parallel import (
    ExactReducer,
    PowerSGDReducer,
    make_mesh,
)
from network_distributed_pytorch_tpu.parallel.trainer import (
    make_train_step,
    stateless_loss,
)
from network_distributed_pytorch_tpu.utils import cross_entropy_loss
from network_distributed_pytorch_tpu.utils.hlo_audit import (
    collective_summary,
    compiled_hlo_text,
)

IMG = (8, 8, 3)


def _setup():
    model = SmallCNN(width=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *IMG)))["params"]

    def lf(p, b):
        x, y = b
        return cross_entropy_loss(model.apply({"params": p}, x), y)

    batch = (jnp.zeros((64, *IMG)), jnp.zeros((64,), jnp.int32))
    return params, stateless_loss(lf), batch


def _summary(reducer, algo):
    params, loss_fn, batch = _setup()
    mesh = make_mesh()
    step = make_train_step(
        loss_fn, reducer, params, 0.05, 0.9, algo, mesh=mesh, donate_state=False
    )
    state = step.init_state(params)
    txt = compiled_hlo_text(step.fn, state, batch)
    return step, collective_summary(txt)


def test_exact_hlo_payload_matches_analytic(devices):
    step, s = _summary(ExactReducer(), "sgd")
    # compiled payload = packed gradient + the 4-byte loss pmean
    assert s["total_payload_bytes"] == step.bits_per_step // 8 + 4
    # combiner merges the gradient and loss all-reduces into ONE collective
    assert s["by_kind"] == {"all-reduce": 1}


def test_powersgd_hlo_payload_matches_analytic(devices):
    step, s = _summary(PowerSGDReducer(compression_rank=2, matricize="last"), "ef_momentum")
    assert s["total_payload_bytes"] == step.bits_per_step // 8 + 4
    # the P / rank-1 / Q / loss collectives compile to at most 3 (Q depends
    # on allreduced-P so it cannot merge with it; the rest may combine)
    assert 2 <= s["by_kind"]["all-reduce"] <= 3


def test_fsdp_hlo_payload_matches_analytic(devices):
    """ZeRO-3's compiled collectives: all-gather(params) + reduce-scatter
    (grads) payloads must equal the analytic 2x model (+ loss/model-state
    pmeans), with the grad reduce-scatter appearing as real reduce-scatter
    ops (psum_scatter from the AD transpose), not widened all-reduces."""
    from network_distributed_pytorch_tpu.parallel.fsdp import make_fsdp_train_step

    params, loss_fn, batch = _setup()
    mesh = make_mesh()
    step = make_fsdp_train_step(
        loss_fn, params, learning_rate=0.05, momentum=0.9, algorithm="sgd",
        mesh=mesh, donate_state=False,
    )
    state = step.init_state(params)
    txt = compiled_hlo_text(step.fn, state, batch)
    s = collective_summary(txt)

    assert s["by_kind"].get("reduce-scatter", 0) >= 1, s["by_kind"]
    assert s["by_kind"].get("all-gather", 0) >= 1, s["by_kind"]
    # analytic: gather + scatter of every padded leaf; compiled adds the
    # 4-byte loss pmean (model_state is {} here)
    assert s["total_payload_bytes"] == step.bits_per_step // 8 + 4
