"""HLO collective audit: the analytic bytes-on-wire model must equal what
XLA actually compiled (SURVEY §7's 'honest accounting' hard part), and the
audit exposes the combiner's collective-count reduction."""

import jax
import jax.numpy as jnp

from network_distributed_pytorch_tpu.models import SmallCNN
from network_distributed_pytorch_tpu.parallel import (
    ExactReducer,
    PowerSGDReducer,
    make_mesh,
)
from network_distributed_pytorch_tpu.parallel.trainer import (
    make_train_step,
    stateless_loss,
)
from network_distributed_pytorch_tpu.utils import cross_entropy_loss
from network_distributed_pytorch_tpu.utils.hlo_audit import (
    collective_summary,
    compiled_hlo_text,
)

IMG = (8, 8, 3)


def _setup():
    model = SmallCNN(width=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *IMG)))["params"]

    def lf(p, b):
        x, y = b
        return cross_entropy_loss(model.apply({"params": p}, x), y)

    batch = (jnp.zeros((64, *IMG)), jnp.zeros((64,), jnp.int32))
    return params, stateless_loss(lf), batch


def _summary(reducer, algo):
    params, loss_fn, batch = _setup()
    mesh = make_mesh()
    step = make_train_step(
        loss_fn, reducer, params, 0.05, 0.9, algo, mesh=mesh, donate_state=False
    )
    state = step.init_state(params)
    txt = compiled_hlo_text(step.fn, state, batch)
    return step, collective_summary(txt)


def test_exact_hlo_payload_matches_analytic(devices):
    step, s = _summary(ExactReducer(), "sgd")
    # bits_per_step is the WHOLE step's wire cost (reducer payload + the
    # 4-byte loss pmean, trainer.LOSS_SYNC_BITS) — byte-exact vs compiled HLO
    assert s["total_payload_bytes"] == step.bits_per_step // 8
    # only all-reduces, and at most 2 (the gradient + the loss pmean —
    # whether the combiner merges them into one is toolchain-dependent)
    assert set(s["by_kind"]) == {"all-reduce"}
    assert 1 <= s["by_kind"]["all-reduce"] <= 2


def test_powersgd_hlo_payload_matches_analytic(devices):
    step, s = _summary(PowerSGDReducer(compression_rank=2, matricize="last"), "ef_momentum")
    assert s["total_payload_bytes"] == step.bits_per_step // 8
    # the P / rank-1 / Q / loss logical collectives compile to at most 4;
    # Q depends on allreduced-P so at least 2 remain after the combiner
    # (how much the rest merge is toolchain-dependent)
    assert 2 <= s["by_kind"]["all-reduce"] <= 4


def test_full_step_with_batch_stats_no_unaccounted_collectives(devices):
    """Round-1 verdict item 4: the entire compiled train step — including a
    model WITH BatchNorm running stats in model_state — must contain no
    collective payload the analytic ``bits_per_step`` doesn't carry. BN stats
    stay per-worker (zero wire bytes, the reference's unsynced-BN torch-DDP
    semantics), so the only non-reducer collective is the scalar loss pmean."""
    from network_distributed_pytorch_tpu.experiments.common import (
        image_classifier_loss,
    )
    from network_distributed_pytorch_tpu.models import resnet18

    model = resnet18(num_classes=10, norm="batch", stem="cifar", width=8)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *IMG)), train=True)
    loss_fn = image_classifier_loss(model, has_batch_stats=True)
    batch = (jnp.zeros((16, *IMG)), jnp.zeros((16,), jnp.int32))
    mesh = make_mesh()
    for reducer, algo in (
        (ExactReducer(), "sgd"),
        (PowerSGDReducer(compression_rank=2, matricize="last"), "ef_momentum"),
    ):
        step = make_train_step(
            loss_fn, reducer, variables["params"], 0.05, 0.9, algo,
            mesh=mesh, donate_state=False,
        )
        state = step.init_state(
            variables["params"],
            model_state={"batch_stats": variables["batch_stats"]},
        )
        s = collective_summary(compiled_hlo_text(step.fn, state, batch))
        assert s["total_payload_bytes"] == step.bits_per_step // 8, (
            algo, s["by_kind"], s["total_payload_bytes"], step.bits_per_step // 8
        )


def test_fsdp_hlo_payload_matches_analytic(devices):
    """ZeRO-3's compiled collectives: all-gather(params) + reduce-scatter
    (grads) payloads must equal the analytic 2x model (+ loss/model-state
    pmeans), with the grad reduce-scatter appearing as real reduce-scatter
    ops (psum_scatter from the AD transpose), not widened all-reduces."""
    from network_distributed_pytorch_tpu.parallel.fsdp import make_fsdp_train_step

    params, loss_fn, batch = _setup()
    mesh = make_mesh()
    step = make_fsdp_train_step(
        loss_fn, params, learning_rate=0.05, momentum=0.9, algorithm="sgd",
        mesh=mesh, donate_state=False,
    )
    state = step.init_state(params)
    txt = compiled_hlo_text(step.fn, state, batch)
    s = collective_summary(txt)

    assert s["by_kind"].get("reduce-scatter", 0) >= 1, s["by_kind"]
    assert s["by_kind"].get("all-gather", 0) >= 1, s["by_kind"]
    # analytic: gather + scatter of every padded leaf + the loss pmean
    # (LOSS_SYNC_BITS); model_state is {} here
    assert s["total_payload_bytes"] == step.bits_per_step // 8


def test_audit_parses_tpu_layout_annotations():
    """TPU HLO shapes carry tiling/memory-space layout suffixes
    ("{0:T(1024)S(1)}") — the audit must parse them (a v5e-compiled module
    previously audited as ZERO collectives)."""
    from network_distributed_pytorch_tpu.utils.hlo_audit import audit_hlo

    hlo = (
        "  %psum.1 = f32[219724]{0:T(1024)S(1)} all-reduce(%c), "
        "replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add\n"
        "  %ar = (f32[53130]{0:T(1024)S(1)}, f32[106280]{0:T(1024)S(1)}, "
        "f32[]{:T(128)}) all-reduce(%a, %b, %c), "
        "replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add\n"
    )
    ops = audit_hlo(hlo)
    assert len(ops) == 2
    assert ops[0].payload_bytes == 4 * 219724
    assert ops[1].payload_bytes == 4 * (53130 + 106280 + 1)


def test_audit_tuple_result_combiner_merged_mixed_dtypes():
    """A combiner-merged collective is ONE tuple-result op whose payload
    sums its components at each component's OWN dtype width — a bf16 buffer
    merged with f32 buffers must not be billed at 4 bytes/elem."""
    from network_distributed_pytorch_tpu.utils.hlo_audit import audit_hlo

    hlo = (
        "  %merged = (f32[100]{0}, bf16[50]{0}, f32[]) "
        "all-reduce(%a, %b, %c), replica_groups={{0,1,2,3}}, to_apply=%add\n"
    )
    ops = audit_hlo(hlo)
    assert len(ops) == 1
    op = ops[0]
    assert op.kind == "all-reduce"
    assert op.payload_bytes == 4 * 100 + 2 * 50 + 4
    assert op.dtype == "f32+bf16+f32"
    assert op.shape == ((100,), (50,), ())
    assert op.group == (0, 1, 2, 3) and op.group_size == 4


def test_audit_tuple_result_reduce_scatter_scales_by_group():
    """A tuple-result (combiner-merged) reduce-scatter's result is 1/N of
    each reduced buffer — the audit scales the SUMMED components by the
    replica-group size so the payload stays in the same convention as
    all-reduce (the logical buffer moved)."""
    from network_distributed_pytorch_tpu.utils.hlo_audit import audit_hlo

    hlo = (
        "  %rs = (f32[16]{0}, f32[8]{0}) reduce-scatter(%a, %b), "
        "replica_groups={{0,1,2,3}}, dimensions={0}, to_apply=%add\n"
    )
    ops = audit_hlo(hlo)
    assert len(ops) == 1
    assert ops[0].payload_bytes == (4 * 16 + 4 * 8) * 4
    assert ops[0].group_size == 4


def test_audit_async_start_form_counted_once():
    """The async `-start` form of a collective is audited like the sync op
    (same result type), and its `-done` line — which repeats no collective
    keyword with a payload — adds nothing."""
    from network_distributed_pytorch_tpu.utils.hlo_audit import (
        audit_hlo,
        collective_summary,
    )

    hlo = (
        "  %ar = f32[96]{0} all-reduce-start(%x), "
        "replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add\n"
        "  %ard = f32[96]{0} all-reduce-done(%ar)\n"
    )
    ops = audit_hlo(hlo)
    assert len(ops) == 1
    assert ops[0].payload_bytes == 4 * 96
    assert collective_summary(hlo)["by_kind"] == {"all-reduce": 1}
