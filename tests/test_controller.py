"""Fallback-controller and deadline-derivation units (jax-free, fast).

The degraded-fabric policy layer (``resilience.controller``) is pure
host-side bookkeeping, so every behavior the e2e chaos tests rely on is
pinned here without a backend: the ladder's documented order, the
descend/ascend hysteresis (consecutive evidence; the indeterminate middle
band resets both streaks), the bandwidth-collapse trigger relative to the
per-rung learned best, PolicyEvent emission, and the collective-deadline
budget (modeled time vs measured p50 vs the floor).
"""

import pytest

from network_distributed_pytorch_tpu.observe import MemorySink, Telemetry
from network_distributed_pytorch_tpu.resilience import (
    DEFAULT_LADDER,
    EpochHealth,
    FallbackController,
    Rung,
    derive_collective_deadline,
)


def _health(epoch=0, achieved=0.0, expiries=0, degraded=0, stragglers=0):
    return EpochHealth(
        epoch=epoch, step_p50_s=0.01, achieved_bytes_per_s=achieved,
        deadline_expiries=expiries, degraded_steps=degraded,
        stragglers=stragglers,
    )


# ---- ladder shape ----------------------------------------------------------


def test_default_ladder_documented_order():
    names = [r.name for r in DEFAULT_LADDER]
    assert names == [
        "baseline", "chunked", "ring", "compress", "compress-low-rank",
        "localsgd", "hierarchical", "hierarchical-async",
    ]
    # baseline overrides nothing; each compression rung names the reducer;
    # the localsgd rung widens the sync period; the bottom two rungs go
    # two-level (and finally async) — the geo-resilient end of the ladder
    assert DEFAULT_LADDER[0].overrides == {}
    assert DEFAULT_LADDER[2].overrides["comm_strategy"] == "ring"
    for rung in DEFAULT_LADDER[3:6]:
        assert rung.overrides["reducer"] == "powersgd"
    assert DEFAULT_LADDER[4].overrides["reducer_rank"] < (
        DEFAULT_LADDER[3].overrides["reducer_rank"]
    )
    assert "sync_every" not in DEFAULT_LADDER[4].overrides
    assert DEFAULT_LADDER[5].overrides["sync_every"] > 1
    for rung in DEFAULT_LADDER[6:]:
        assert rung.overrides["reducer"] == "hierarchical"
    assert DEFAULT_LADDER[7].overrides.get("outer_async")
    assert (
        DEFAULT_LADDER[7].overrides["sync_every"]
        > DEFAULT_LADDER[6].overrides["sync_every"]
    )


def test_ladder_validation():
    with pytest.raises(ValueError, match="at least one rung"):
        FallbackController(ladder=[])
    with pytest.raises(ValueError, match="outside ladder"):
        FallbackController(start_index=len(DEFAULT_LADDER))


# ---- descend / ascend walking ----------------------------------------------


def test_descends_in_order_and_stops_at_bottom():
    c = FallbackController(descend_after=1)
    seen = []
    for epoch in range(len(DEFAULT_LADDER) + 2):
        d = c.observe(_health(epoch=epoch, expiries=1))
        if d is not None:
            assert d.action == "descend"
            assert d.rung_index_after == d.rung_index_before + 1
            assert d.overrides == DEFAULT_LADDER[d.rung_index_after].overrides
            seen.append((d.rung_before, d.rung_after))
    # walked every edge exactly once, then held at the bottom rung
    assert seen == [
        (a.name, b.name) for a, b in zip(DEFAULT_LADDER, DEFAULT_LADDER[1:])
    ]
    assert c.rung.name == "hierarchical-async"


def test_descend_requires_consecutive_degraded_epochs():
    c = FallbackController(descend_after=2)
    assert c.observe(_health(epoch=0, degraded=1)) is None
    # an indeterminate epoch (no faults, no bandwidth evidence) resets the
    # streak — a move needs CONSECUTIVE evidence
    assert c.observe(_health(epoch=1)) is None
    assert c.observe(_health(epoch=2, degraded=1)) is None
    d = c.observe(_health(epoch=3, degraded=1))
    assert d is not None and d.action == "descend"
    assert "degraded_steps" in d.trigger


def test_ascend_requires_consecutive_healthy_epochs():
    c = FallbackController(start_index=1, recover_after=2)
    # first healthy epoch seeds the rung's best and starts the streak
    assert c.observe(_health(epoch=0, achieved=100.0)) is None
    # indeterminate (achieved in the middle band) resets the streak
    assert c.observe(_health(epoch=1, achieved=60.0)) is None
    assert c.observe(_health(epoch=2, achieved=100.0)) is None
    d = c.observe(_health(epoch=3, achieved=95.0))
    assert d is not None and d.action == "ascend"
    assert d.rung_index_after == 0
    assert "recovered" in d.trigger
    # at the top rung, healthy epochs never ascend past the ladder
    c2 = FallbackController(recover_after=1)
    assert c2.observe(_health(epoch=0, achieved=10.0)) is None
    assert c2.observe(_health(epoch=1, achieved=10.0)) is None
    assert c2.index == 0


def test_bandwidth_collapse_is_a_degraded_trigger():
    c = FallbackController(descend_after=1, degrade_factor=0.5)
    assert c.observe(_health(epoch=0, achieved=100.0)) is None  # seeds best
    d = c.observe(_health(epoch=1, achieved=40.0))  # < 0.5 x best
    assert d is not None and d.action == "descend"
    assert "achieved_bytes_per_s" in d.trigger
    # per-rung best: the NEW rung has no history, so the same 40 B/s is
    # indeterminate there (seeds that rung's best instead of triggering)
    assert c.observe(_health(epoch=2, achieved=40.0)) is None
    assert c.index == 1


def test_every_fault_counter_triggers_degraded():
    for kw in ({"expiries": 1}, {"degraded": 2}, {"stragglers": 3}):
        c = FallbackController(descend_after=1)
        d = c.observe(_health(**kw))
        assert d is not None and d.action == "descend", kw


# ---- PolicyEvent emission --------------------------------------------------


def test_record_emits_policy_event_with_byte_claims():
    sink = MemorySink()
    c = FallbackController(
        descend_after=1, telemetry=Telemetry([sink]), rank=3
    )
    d = c.observe(_health(epoch=5, expiries=2))
    c.record(d, predicted_bytes_per_step=1348.0, realized_bytes_per_step=4428.0)
    events = [r for r in sink.records if r.get("event") == "policy"]
    assert len(events) == 1
    (e,) = events
    assert e["action"] == "descend"
    assert e["epoch"] == 5
    assert e["rung_before"] == "baseline" and e["rung_after"] == "chunked"
    assert e["overrides"] == {"comm_chunks": 4}
    assert e["predicted_bytes_per_step"] == 1348.0
    assert e["realized_bytes_per_step"] == 4428.0
    assert e["rank"] == 3
    assert "deadline_expiries" in e["trigger"]
    assert c.decisions == [d]


def test_custom_ladder_and_overrides_copying():
    ladder = [Rung("a", {}), Rung("b", {"comm_chunks": 2})]
    c = FallbackController(ladder=ladder, descend_after=1)
    d = c.observe(_health(expiries=1))
    d.overrides["comm_chunks"] = 999  # mutating the decision's copy...
    assert c.overrides == {"comm_chunks": 2}  # ...never reaches the rung


# ---- collective-deadline derivation ----------------------------------------


def test_deadline_floor_dominates_tiny_payloads():
    # a few bytes on ICI models out at microseconds; the floor holds
    assert derive_collective_deadline(16, 8, "ICI(v5e)", floor_s=0.25) == 0.25


def test_deadline_measured_p50_dominates_optimistic_model():
    # the model says microseconds; the fabric measurably delivers 100ms —
    # the deadline follows the measurement times the slack
    budget = derive_collective_deadline(
        16, 8, "ICI(v5e)", measured_p50_s=0.1, slack=4.0, floor_s=0.05
    )
    assert budget == pytest.approx(0.4)


def test_deadline_model_scales_with_payload_and_fabric():
    from network_distributed_pytorch_tpu.observe.analytics import (
        _load_utils_module,
    )

    bw = _load_utils_module("bandwidth")
    payload = 100 * (1 << 20)  # 100 MB on 1GbE: seconds, far above floor
    budget = derive_collective_deadline(
        payload, 8, "1GbE", slack=2.0, floor_s=0.05
    )
    assert budget == pytest.approx(
        bw.allreduce_time_s(payload, 8, "1GbE") * 2.0
    )
    # a faster fabric derives a tighter deadline for the same payload
    assert budget > derive_collective_deadline(
        payload, 8, "100GbE", slack=2.0, floor_s=0.05
    )


# ---- mid-epoch alert nudges (the live plane's entry point) -----------------


def test_nudge_critical_descends_immediately():
    c = FallbackController(descend_after=3)  # boundary would need 3 epochs
    d = c.nudge("grad_spike", epoch=2, severity="critical")
    assert d is not None and d.action == "descend"
    assert d.trigger == "alert:grad_spike:critical"
    assert d.epoch == 2
    assert c.index == 1
    assert c.nudged_epoch == 2


def test_nudge_comm_shaped_warn_descends_immediately():
    for alert in ("bandwidth_collapse", "step_time_drift"):
        c = FallbackController(descend_after=3)
        d = c.nudge(alert, epoch=0, severity="warn")
        assert d is not None and d.trigger == f"alert:{alert}:warn"


def test_nudge_other_warn_precharges_streak():
    c = FallbackController(descend_after=2)
    # a non-comm warn returns no decision but pre-charges the streak:
    # the next degraded boundary epoch descends one epoch sooner
    assert c.nudge("grad_spike", epoch=0, severity="warn") is None
    assert c.index == 0
    d = c.observe(_health(epoch=0, degraded=1))
    assert d is not None and d.action == "descend"


def test_nudge_at_most_one_descend_per_epoch():
    c = FallbackController()
    assert c.nudge("grad_spike", epoch=1, severity="critical") is not None
    # same epoch: the decision budget is spent (even for a comm alert)
    assert c.nudge("bandwidth_collapse", epoch=1, severity="warn") is None
    assert c.index == 1
    # a later epoch spends its own budget
    assert c.nudge("grad_spike", epoch=2, severity="critical") is not None
    assert c.index == 2


def test_nudged_epoch_boundary_observe_is_noop():
    c = FallbackController(descend_after=1)
    assert c.nudge("grad_spike", epoch=3, severity="critical") is not None
    # the SAME epoch's boundary verdict must not double-move on the same
    # evidence, no matter how degraded the numbers look
    assert c.observe(_health(epoch=3, expiries=5, degraded=9)) is None
    assert c.index == 1
    # the NEXT epoch's boundary owns its decision again
    d = c.observe(_health(epoch=4, degraded=1))
    assert d is not None and d.rung_index_after == 2


def test_nudge_at_bottom_rung_holds():
    c = FallbackController(start_index=len(DEFAULT_LADDER) - 1)
    assert c.nudge("grad_spike", epoch=0, severity="critical") is None
    assert c.index == len(DEFAULT_LADDER) - 1
    # the budget was NOT spent by the refused move
    assert c.nudged_epoch is None


def test_nudge_descend_emits_policy_event_with_alert_trigger():
    sink = MemorySink()
    telemetry = Telemetry([sink])
    c = FallbackController(telemetry=telemetry, rank=0)
    d = c.nudge("bandwidth_collapse", epoch=0, severity="critical")
    c.record(d, predicted_bytes_per_step=10.0, realized_bytes_per_step=100.0)
    telemetry.close()
    recs = [r for r in sink.records if r["event"] == "policy"]
    assert len(recs) == 1
    assert recs[0]["trigger"] == "alert:bandwidth_collapse:critical"
    assert recs[0]["action"] == "descend"
