"""DiLoCo (outer-optimizer local SGD): reduction to plain local SGD at the
identity outer step, a NumPy golden replica of the outer-Nesterov round,
compressed outer deltas with error-feedback telescoping, and byte-exact
wire accounting of the compressed round."""

import jax
import jax.numpy as jnp
import numpy as np

from network_distributed_pytorch_tpu.parallel import (
    PowerSGDReducer,
    make_diloco_train_fn,
    make_local_sgd_train_fn,
    make_mesh,
)
from network_distributed_pytorch_tpu.parallel.trainer import (
    LOSS_SYNC_BITS,
    stateless_loss,
)

W = 8


def _problem(seed=0):
    rng = np.random.RandomState(seed)
    w_true = rng.randn(16, 4).astype(np.float32)
    x = rng.randn(64, 16).astype(np.float32)
    y = x @ w_true
    params = {"w": jnp.zeros((16, 4)), "b": jnp.zeros((4,))}

    def loss(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] + p["b"] - yb) ** 2)

    return params, stateless_loss(loss), (jnp.asarray(x), jnp.asarray(y))


def _stack(batch, h):
    return tuple(jnp.broadcast_to(b[None], (h,) + b.shape) for b in batch)


def test_identity_outer_step_equals_local_sgd(devices):
    """outer_lr=1, outer_momentum=0, exact reducer ⇒ θ₀ − mean(θ₀−θ_w)
    = mean(θ_w): DiLoCo degenerates to local-SGD parameter averaging,
    round-for-round, with the same per-worker inner momenta."""
    params, loss_fn, batch = _problem()
    mesh = make_mesh()
    h = 4
    diloco = make_diloco_train_fn(
        loss_fn, params, inner_learning_rate=0.05, outer_learning_rate=1.0,
        outer_momentum=0.0, sync_every=h, mesh=mesh, donate_state=False,
    )
    local = make_local_sgd_train_fn(
        loss_fn, params, 0.05, sync_every=h, algorithm="sgd",
        mesh=mesh, donate_state=False,
    )
    dstate, lstate = diloco.init_state(params), local.init_state(params)
    for _ in range(3):
        dstate, dlosses = diloco(dstate, _stack(batch, h))
        lstate, llosses = local(lstate, _stack(batch, h))
        np.testing.assert_allclose(
            np.asarray(dlosses), np.asarray(llosses), rtol=1e-6
        )
    np.testing.assert_allclose(
        np.asarray(diloco.eval_params(dstate)["w"]),
        np.asarray(local.eval_params(lstate)["w"]),
        rtol=1e-5, atol=1e-7,
    )


def test_outer_nesterov_matches_numpy_golden(devices):
    """One full round vs a literal NumPy replica: H plain inner steps, Δ̄ =
    mean over workers, outer Nesterov m←μm+Δ̄, θ←θ₀−γ(Δ̄+μm).  The global
    batch is built as 8 identical per-worker shards, so every worker
    computes the same delta and the NumPy loop needs no per-worker axis —
    divergence mechanics are covered by the local-SGD equivalence test
    above."""
    rng = np.random.RandomState(3)
    w_true = rng.randn(16, 4).astype(np.float32)
    x_shard = rng.randn(8, 16).astype(np.float32)
    y_shard = x_shard @ w_true
    params = {"w": jnp.zeros((16, 4)), "b": jnp.zeros((4,))}
    loss_fn = stateless_loss(
        lambda p, b: jnp.mean((b[0] @ p["w"] + p["b"] - b[1]) ** 2)
    )
    batch = (
        jnp.asarray(np.tile(x_shard, (W, 1))),
        jnp.asarray(np.tile(y_shard, (W, 1))),
    )
    mesh = make_mesh()
    h, gamma, mu, ilr = 3, 0.7, 0.9, 0.05
    diloco = make_diloco_train_fn(
        loss_fn, params, inner_learning_rate=ilr, outer_learning_rate=gamma,
        outer_momentum=mu, outer_nesterov=True, sync_every=h,
        inner_algorithm="sgd_plain", mesh=mesh, donate_state=False,
    )
    state = diloco.init_state(params)

    x, y = x_shard, y_shard
    w = np.zeros((16, 4), np.float32)
    b = np.zeros((4,), np.float32)
    m_w = np.zeros_like(w)
    m_b = np.zeros_like(b)
    for _ in range(4):  # rounds
        state, _ = diloco(state, _stack(batch, h))
        w0, b0 = w.copy(), b.copy()
        for _ in range(h):  # inner plain-SGD steps
            r = x @ w + b - y
            gw = 2.0 * x.T @ r / r.size
            gb = 2.0 * r.sum(0) / r.size
            w, b = w - ilr * gw, b - ilr * gb
        dw, db = w0 - w, b0 - b  # every worker computes the same delta
        m_w, m_b = mu * m_w + dw, mu * m_b + db
        w = w0 - gamma * (dw + mu * m_w)
        b = b0 - gamma * (db + mu * m_b)
    np.testing.assert_allclose(
        np.asarray(diloco.eval_params(state)["w"]), w, rtol=1e-4, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(diloco.eval_params(state)["b"]), b, rtol=1e-4, atol=1e-6
    )


def test_compressed_deltas_train_with_error_feedback(devices):
    """PowerSGD-compressed outer deltas: loss descends across rounds and the
    EF memories hold the (nonzero) per-worker compression residual — the
    same telescoping the Algorithm-2 trainer applies per step."""
    params, loss_fn, batch = _problem()
    mesh = make_mesh()
    h = 4
    diloco = make_diloco_train_fn(
        loss_fn, params, inner_learning_rate=0.05,
        sync_every=h, inner_algorithm="sgd_plain", mesh=mesh, donate_state=False,
        reducer=PowerSGDReducer(random_seed=7, compression_rank=2, matricize="last"),
    )
    state = diloco.init_state(params)
    first = last = None
    for _ in range(16):
        state, losses = diloco(state, _stack(batch, h))
        if first is None:
            first = float(losses[0])
        last = float(losses[-1])
    assert last < 0.15 * first, (first, last)
    # rank-2 compression of a rank-4 delta must leave a residual
    assert float(jnp.max(jnp.abs(state.memories["w"]))) > 0.0


def test_adamw_inner_optimizer(devices):
    """The paper's recipe — optax AdamW inner, Nesterov outer — trains, and
    the per-worker inner optimizer state persists across rounds."""
    import optax

    params, loss_fn, batch = _problem()
    mesh = make_mesh()
    h = 4
    diloco = make_diloco_train_fn(
        loss_fn, params,  # no inner_learning_rate: the optax inner has its own
        sync_every=h, inner_algorithm="optax",
        inner_optimizer=optax.adamw(3e-2), mesh=mesh, donate_state=False,
    )
    state = diloco.init_state(params)
    first = last = None
    for _ in range(12):
        state, losses = diloco(state, _stack(batch, h))
        if first is None:
            first = float(losses[0])
        last = float(losses[-1])
    assert last < 0.5 * first, (first, last)
    counts = [
        l for l in jax.tree_util.tree_leaves(state.inner_opt)
        if l.ndim == 1 and l.shape == (W,) and l.dtype == jnp.int32
    ]
    assert counts and int(counts[0][0]) == 12 * h  # adam step count, per worker


def test_wire_accounting_hlo_exact(devices):
    """Compressed-DiLoCo bits_per_round (one PowerSGD pass over a
    param-shaped tree + H loss pmeans) must equal the compiled round's
    collective payload byte-exactly, and undercut local SGD's full
    parameter allreduce."""
    from network_distributed_pytorch_tpu.utils.hlo_audit import (
        collective_summary,
        compiled_hlo_text,
    )

    params, loss_fn, batch = _problem()
    mesh = make_mesh()
    h = 4
    reducer = PowerSGDReducer(random_seed=7, compression_rank=1, matricize="last")
    diloco = make_diloco_train_fn(
        loss_fn, params, inner_learning_rate=0.05, sync_every=h,
        reducer=reducer, mesh=mesh, donate_state=False,
    )
    state = diloco.init_state(params)
    batches = _stack(batch, h)
    hlo = compiled_hlo_text(
        diloco.fn, state, batches, jnp.ones((h,), jnp.float32)
    )
    audit = collective_summary(hlo)
    # the loss pmean sits inside the scan body: audited once, executed H
    # times (see CompiledLocalSGD.bits_per_round docstring)
    audited_round_bits = 8 * audit["total_payload_bytes"] + (h - 1) * LOSS_SYNC_BITS
    assert audited_round_bits == diloco.bits_per_round, (
        audit, diloco.bits_per_round
    )
    local = make_local_sgd_train_fn(
        loss_fn, params, 0.05, sync_every=h, mesh=mesh, donate_state=False
    )
    assert diloco.bits_per_round < local.bits_per_round


def test_padded_partial_round_equals_shorter_round(devices):
    """Pad-and-mask contract: a sync_every=4 round fed 3 real batches plus
    one zero-weighted pad slot must land on the SAME parameters as a
    sync_every=3 compiled round on the real batches alone — the mask turns
    the pad slot into a carry no-op, so no recompile and no dropped or
    phantom inner steps. Pad CONTENT must be irrelevant (zeros, garbage,
    even NaN — jnp.where is a select, not a blend)."""
    params, loss_fn, batch = _problem()
    mesh = make_mesh()
    reducer_args = dict(
        inner_learning_rate=0.05, mesh=mesh, donate_state=False,
    )
    padded = make_diloco_train_fn(
        loss_fn, params, sync_every=4, **reducer_args
    )
    short = make_diloco_train_fn(
        loss_fn, params, sync_every=3, **reducer_args
    )
    real = _stack(batch, 3)

    def pad_with(filler):
        return tuple(
            jnp.concatenate([r, filler(r[:1])], axis=0) for r in real
        )

    w = jnp.asarray([1.0, 1.0, 1.0, 0.0], jnp.float32)
    zero_state, zero_losses = padded(
        padded.init_state(params), pad_with(jnp.zeros_like), w
    )
    nan_state, nan_losses = padded(
        padded.init_state(params), pad_with(lambda r: jnp.full_like(r, jnp.nan)), w
    )
    short_state, short_losses = short(short.init_state(params), real)

    np.testing.assert_array_equal(
        np.asarray(zero_state.params["w"]), np.asarray(nan_state.params["w"])
    )
    assert np.all(np.isfinite(np.asarray(nan_state.params["w"])))
    np.testing.assert_allclose(
        np.asarray(zero_state.params["w"]),
        np.asarray(short_state.params["w"]),
        rtol=1e-6, atol=1e-8,
    )
    # masked slot reports exactly 0.0 loss; real slots match the short run
    np.testing.assert_allclose(
        np.asarray(zero_losses[:3]), np.asarray(short_losses), rtol=1e-6
    )
    assert float(zero_losses[3]) == 0.0 and float(nan_losses[3]) == 0.0


def test_all_ones_weights_bitwise_legacy(devices):
    """The default all-ones mask must be bitwise-neutral: calling with and
    without explicit weights produces identical parameters (the select is
    the identity when every weight is 1)."""
    params, loss_fn, batch = _problem()
    mesh = make_mesh()
    h = 4
    diloco = make_diloco_train_fn(
        loss_fn, params, inner_learning_rate=0.05, sync_every=h, mesh=mesh,
        donate_state=False,
    )
    batches = _stack(batch, h)
    a, _ = diloco(diloco.init_state(params), batches)
    b, _ = diloco(
        diloco.init_state(params), batches, jnp.ones((h,), jnp.float32)
    )
    np.testing.assert_array_equal(
        np.asarray(a.params["w"]), np.asarray(b.params["w"])
    )
