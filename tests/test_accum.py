"""Gradient accumulation: k microbatches with a summed-grad scan carry must
match one big-batch step exactly (mean loss, equal microbatch sizes), keep
BN-style model_state threading, and leave the wire cost at ONE reduction per
step."""

import jax.numpy as jnp
import numpy as np

from network_distributed_pytorch_tpu.parallel import (
    ExactReducer,
    PowerSGDReducer,
    make_mesh,
)
from network_distributed_pytorch_tpu.parallel.trainer import (
    make_train_step,
    stateless_loss,
)

W = 8


def _problem():
    rng = np.random.RandomState(0)
    w_true = rng.randn(16, 4).astype(np.float32)
    x = rng.randn(128, 16).astype(np.float32)
    y = x @ w_true
    params = {"w": jnp.zeros((16, 4)), "b": jnp.zeros((4,))}

    def loss(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] + p["b"] - yb) ** 2)

    return params, stateless_loss(loss), (jnp.asarray(x), jnp.asarray(y))


def _split(batch, k):
    return tuple(t.reshape((k, t.shape[0] // k) + t.shape[1:]) for t in batch)


def test_accum_equals_big_batch_distributed(devices):
    """accum_steps=4 over quarter-size microbatches == one full-batch step,
    bit-close, for both the exact and the PowerSGD EF path (the compression
    sees the same mean gradient either way)."""
    params, loss_fn, batch = _problem()
    mesh = make_mesh()
    for make_red, algo in [
        (lambda: ExactReducer(), "sgd"),
        (
            lambda: PowerSGDReducer(
                random_seed=5, compression_rank=2, matricize="last"
            ),
            "ef_momentum",
        ),
    ]:
        big = make_train_step(
            loss_fn, make_red(), params, 0.05, algorithm=algo, mesh=mesh,
            donate_state=False,
        )
        acc = make_train_step(
            loss_fn, make_red(), params, 0.05, algorithm=algo, mesh=mesh,
            donate_state=False, accum_steps=4,
        )
        bstate, astate = big.init_state(params), acc.init_state(params)
        for _ in range(4):
            bstate, bloss = big(bstate, batch)
            astate, aloss = acc(astate, _split(batch, 4))
            np.testing.assert_allclose(
                float(aloss), float(bloss), rtol=1e-5, atol=1e-7
            )
        np.testing.assert_allclose(
            np.asarray(astate.params["w"]), np.asarray(bstate.params["w"]),
            rtol=1e-5, atol=1e-6,
        )


def test_accum_single_process_model_state_threads():
    """axis_name=None fallback: microbatch scan threads model_state through
    (counter-style aux state advances once per microbatch)."""
    params = {"w": jnp.ones((4, 2))}

    def loss_fn(p, model_state, batch):
        xb, yb = batch
        loss = jnp.mean((xb @ p["w"] - yb) ** 2)
        return loss, {"count": model_state["count"] + 1}

    step = make_train_step(
        loss_fn, ExactReducer(), params, 0.01, algorithm="sgd_plain",
        mesh=None, donate_state=False, accum_steps=3,
    )
    state = step.init_state(params, model_state={"count": jnp.zeros((), jnp.int32)})
    x = jnp.ones((3, 4, 4))
    y = jnp.zeros((3, 4, 2))
    state, loss = step(state, (x, y))
    assert int(state.model_state["count"]) == 3
    assert bool(jnp.isfinite(loss))


def test_accum_wire_cost_is_one_reduction(devices):
    """The reducer runs once per step regardless of accum_steps: compiled
    collective payload == the analytic single-reduction model byte-exactly."""
    from network_distributed_pytorch_tpu.utils.hlo_audit import (
        collective_summary,
        compiled_hlo_text,
    )

    params, loss_fn, batch = _problem()
    mesh = make_mesh()
    step = make_train_step(
        loss_fn,
        PowerSGDReducer(random_seed=5, compression_rank=2, matricize="last"),
        params, 0.05, algorithm="ef_momentum", mesh=mesh,
        donate_state=False, accum_steps=4,
    )
    state = step.init_state(params)
    audit = collective_summary(compiled_hlo_text(step.fn, state, _split(batch, 4)))
    assert 8 * audit["total_payload_bytes"] == step.bits_per_step, audit


def test_accum_scanned_train_fn(devices):
    """Scanned epoch runner composes with accumulation: (num_steps, accum,
    batch, ...) leaves, losses match the per-step accum path."""
    from network_distributed_pytorch_tpu.parallel.trainer import (
        make_scanned_train_fn,
    )

    params, loss_fn, batch = _problem()
    mesh = make_mesh()
    per_step = make_train_step(
        loss_fn, ExactReducer(), params, 0.05, algorithm="sgd", mesh=mesh,
        donate_state=False, accum_steps=4,
    )
    scanned = make_scanned_train_fn(
        loss_fn, ExactReducer(), params, 0.05, algorithm="sgd", mesh=mesh,
        donate_state=False, accum_steps=4,
    )
    mb = _split(batch, 4)
    stacked = tuple(jnp.broadcast_to(t[None], (3,) + t.shape) for t in mb)
    pstate, sstate = per_step.init_state(params), scanned.init_state(params)
    plosses = []
    for _ in range(3):
        pstate, l = per_step(pstate, mb)
        plosses.append(float(l))
    sstate, slosses = scanned(sstate, stacked)
    np.testing.assert_allclose(np.asarray(slosses), plosses, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(sstate.params["w"]), np.asarray(pstate.params["w"]),
        rtol=1e-6, atol=1e-7,
    )


def test_accum_through_launcher(devices):
    """--accum-steps flows launcher → config → experiment → trainer; the
    experiment trains and reports the same single-reduction wire model."""
    from network_distributed_pytorch_tpu.launch import main

    out = main(
        [
            "powersgd_cifar10",
            "--preset", "small",
            "--epochs", "1",
            "--global-batch", "64",
            "--reducer-rank", "2",
            "--accum-steps", "2",
            "--max-steps-per-epoch", "2",
            "--data-dir", "/nonexistent",
            "--log-every", "0",
        ]
    )
    assert out["experiment"] == "powersgd_cifar10"
    assert np.isfinite(out["final_loss"])
