"""utils.benchmarks — the shared GPT step-timing scaffold.

The MFU basis matters: XLA's ``cost_analysis`` counts a scanned decoder's
loop body ONCE regardless of trip count, so under ``GPTConfig.scan_layers``
the HLO flop count understates true work ~``n_layers``-fold. The scaffold
therefore reports MFU from the analytic PaLM-appendix accounting
(``gpt_analytic_train_flops``) and carries the raw HLO count alongside.
"""

import pytest

from network_distributed_pytorch_tpu.utils.benchmarks import (
    gpt_analytic_train_flops,
    time_gpt_train_step,
)


def test_analytic_flops_formula():
    # 6N per token + 12·L·d·s attention, times B·s tokens
    n, L, d, s, b = 1000.0, 3, 8, 16, 4
    expect = (6.0 * n + 12.0 * L * d * s) * b * s
    assert gpt_analytic_train_flops(n, L, d, s, b) == expect


def test_analytic_flops_gpt2_small_magnitude():
    # GPT-2-small full shape: ~124M params, L=12, d=768, s=1024, B=8
    # => ~7e12 flops/step (the published 6ND ballpark). Guard the basis
    # against unit slips (per-token vs per-step, fwd-only vs fwd+bwd).
    f = gpt_analytic_train_flops(124e6, 12, 768, 1024, 8)
    assert 5e12 < f < 9e12


@pytest.mark.parametrize("scan", [False, True])
def test_time_gpt_train_step_reports_analytic_basis(devices, scan):
    r = time_gpt_train_step(
        small=True, seq_len=32, batch=8, vocab=64, scan_layers=scan, reps=1
    )
    assert r["scan_layers"] is scan
    assert r["n_params"] > 0
    assert r["flops_method"].startswith("analytic")
    expect = gpt_analytic_train_flops(r["n_params"], 2, 32, 32, 8)
    assert r["flops_per_step"] == expect
    assert r["step_time_ms"] > 0 and r["tokens_per_sec"] > 0


def test_scanned_hlo_flops_undercount_is_real(devices):
    """The reason the analytic basis exists: the scanned program's HLO
    flop count must NOT be trusted to scale with depth. If XLA ever starts
    multiplying the body by the trip count, this starts failing and the
    basis choice deserves a second look."""
    flops = {}
    for scan in (False, True):
        r = time_gpt_train_step(
            small=True, seq_len=32, batch=8, vocab=64, scan_layers=scan,
            reps=1,
        )
        flops[scan] = r.get("flops_per_step_hlo")
    if flops[False] is None or flops[True] is None:
        pytest.skip("cost_analysis unavailable on this backend")
    assert flops[True] < flops[False]
