"""Trainer end-to-end on the 8-device mesh (SURVEY §4 integration tier):
exact-DDP ≡ single-device large-batch; PowerSGD trains; bits accounting."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from network_distributed_pytorch_tpu.models import SmallCNN, resnet18
from network_distributed_pytorch_tpu.parallel import (
    ExactReducer,
    PowerSGDReducer,
    make_mesh,
)
from network_distributed_pytorch_tpu.parallel.trainer import (
    make_train_step,
    stateless_loss,
)
from network_distributed_pytorch_tpu.utils import cross_entropy_loss

BATCH = 64
IMG = (8, 8, 3)


def _synthetic_batch(key, n=BATCH):
    """Learnable synthetic task: Gaussian class blobs (x = class mean + noise)."""
    ky, kx = jax.random.split(key)
    means = jax.random.normal(jax.random.PRNGKey(999), (10, *IMG))
    y = jax.random.randint(ky, (n,), 0, 10)
    x = means[y] + 0.5 * jax.random.normal(kx, (n, *IMG))
    return x, y


def _cnn_setup():
    model = SmallCNN(width=4)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *IMG)))["params"]

    def loss_fn(params, batch):
        x, y = batch
        return cross_entropy_loss(model.apply({"params": params}, x), y)

    return params, stateless_loss(loss_fn)


def test_exact_ddp_equals_single_device_large_batch(devices):
    params, loss_fn = _cnn_setup()
    mesh = make_mesh()

    dist_step = make_train_step(
        loss_fn, ExactReducer(), params, learning_rate=0.05, momentum=0.9,
        algorithm="sgd", mesh=mesh, donate_state=False,
    )
    single_step = make_train_step(
        loss_fn, ExactReducer(), params, learning_rate=0.05, momentum=0.9,
        algorithm="sgd", mesh=None, donate_state=False,
    )

    sd = dist_step.init_state(params)
    ss = single_step.init_state(params)
    for i in range(5):
        batch = _synthetic_batch(jax.random.PRNGKey(i))
        sd, loss_d = dist_step(sd, batch)
        ss, loss_s = single_step(ss, batch)
        np.testing.assert_allclose(float(loss_d), float(loss_s), rtol=1e-5)

    # identical parameters: pmean of per-shard grads == grad of global mean
    for a, b in zip(jax.tree_util.tree_leaves(sd.params), jax.tree_util.tree_leaves(ss.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_powersgd_training_reduces_loss(devices):
    params, loss_fn = _cnn_setup()
    mesh = make_mesh()
    reducer = PowerSGDReducer(random_seed=714, compression_rank=2, matricize="last")
    step = make_train_step(
        loss_fn, reducer, params, learning_rate=0.05, momentum=0.9,
        algorithm="ef_momentum", mesh=mesh,
    )
    state = step.init_state(params)
    losses = []
    for i in range(50):
        state, loss = step(state, _synthetic_batch(jax.random.PRNGKey(1000 + i)))
        losses.append(float(loss))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.8, losses


def test_bits_compressed_below_exact():
    params, loss_fn = _cnn_setup()
    exact = make_train_step(loss_fn, ExactReducer(), params, 0.01, mesh=None)
    psgd = make_train_step(
        loss_fn, PowerSGDReducer(compression_rank=2, matricize="last"), params, 0.01, mesh=None
    )
    assert 0 < psgd.bits_per_step < exact.bits_per_step
    total = sum(l.size for l in jax.tree_util.tree_leaves(params))
    assert exact.bits_per_step == 32 * total


@pytest.mark.slow
def test_resnet_batchnorm_distributed_step(devices):
    """ResNet-18 with BatchNorm: model_state (running stats) is carried
    per-worker (unsynced, like torch DDP); one distributed PowerSGD step
    runs and updates the stats."""
    model = resnet18(norm="batch", stem="cifar", width=8, num_classes=10)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *IMG)), train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def loss_fn(params, model_state, batch):
        x, y = batch
        logits, new_vars = model.apply(
            {"params": params, "batch_stats": model_state["batch_stats"]},
            x,
            train=True,
            mutable=["batch_stats"],
        )
        return cross_entropy_loss(logits, y), {"batch_stats": new_vars["batch_stats"]}

    reducer = PowerSGDReducer(compression_rank=2, matricize="last")
    mesh = make_mesh()
    step = make_train_step(
        loss_fn, reducer, params, 0.01, algorithm="ef_momentum", mesh=mesh, donate_state=False
    )
    state = step.init_state(params, model_state={"batch_stats": batch_stats})
    state2, loss = step(state, _synthetic_batch(jax.random.PRNGKey(3)))
    assert np.isfinite(float(loss))
    before = jax.tree_util.tree_leaves(state.model_state)
    after = jax.tree_util.tree_leaves(state2.model_state)
    assert any(not np.allclose(np.asarray(a), np.asarray(b)) for a, b in zip(before, after))


@pytest.mark.slow
def test_scanned_epoch_equals_stepwise(devices):
    """lax.scan multi-step runner must be numerically identical to the
    step-at-a-time loop (same collectives, same EF chain)."""
    from network_distributed_pytorch_tpu.parallel.trainer import make_scanned_train_fn

    params, loss_fn = _cnn_setup()
    mesh = make_mesh()
    reducer = PowerSGDReducer(random_seed=5, compression_rank=2, matricize="last")
    kw = dict(
        learning_rate=0.05, momentum=0.9, algorithm="ef_momentum",
        mesh=mesh, donate_state=False,
    )
    step = make_train_step(loss_fn, reducer, params, **kw)
    epoch = make_scanned_train_fn(loss_fn, reducer, params, **kw)

    batches = [_synthetic_batch(jax.random.PRNGKey(50 + i)) for i in range(4)]
    stacked = (
        jnp.stack([b[0] for b in batches]),
        jnp.stack([b[1] for b in batches]),
    )

    s1 = step.init_state(params)
    losses1 = []
    for b in batches:
        s1, l = step(s1, b)
        losses1.append(float(l))

    s2 = epoch.init_state(params)
    s2, losses2 = epoch(s2, stacked)
    np.testing.assert_allclose(np.asarray(losses2), losses1, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s1.params), jax.tree_util.tree_leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_max_grad_norm_clips_like_torch(devices):
    """max_grad_norm applies torch clip_grad_norm_ semantics to the reduced
    delta: the distributed clipped step equals a manually-clipped
    single-device step, and None leaves the trajectory unchanged."""
    import numpy as np

    from network_distributed_pytorch_tpu.parallel import ExactReducer, make_mesh

    rng = np.random.RandomState(0)
    w_true = 50.0 * rng.randn(16, 4).astype(np.float32)  # big grads
    x = rng.randn(64, 16).astype(np.float32)
    y = x @ w_true
    params = {"w": jnp.zeros((16, 4)), "b": jnp.zeros((4,))}
    loss_fn = stateless_loss(
        lambda p, b: jnp.mean((b[0] @ p["w"] + p["b"] - b[1]) ** 2)
    )
    batch = (jnp.asarray(x), jnp.asarray(y))
    mesh = make_mesh()
    max_norm = 1.0
    step = make_train_step(
        loss_fn, ExactReducer(), params, 0.05, algorithm="sgd_plain",
        mesh=mesh, donate_state=False, max_grad_norm=max_norm,
    )
    state = step.init_state(params)
    state, _ = step(state, batch)

    # manual replica: global-batch gradient, clipped, one plain-SGD step
    g = jax.grad(lambda p: loss_fn(p, {}, batch)[0])(params)
    norm = float(
        jnp.sqrt(sum(jnp.sum(l ** 2) for l in jax.tree_util.tree_leaves(g)))
    )
    assert norm > max_norm  # the clip must actually engage
    scale = max_norm / (norm + 1e-6)
    ref_w = np.asarray(params["w"]) - 0.05 * scale * np.asarray(g["w"])
    np.testing.assert_allclose(
        np.asarray(state.params["w"]), ref_w, rtol=1e-5, atol=1e-7
    )
    # update norm is capped at lr * max_norm
    upd = np.asarray(state.params["w"]).ravel().tolist() + np.asarray(
        state.params["b"]
    ).ravel().tolist()
    assert np.linalg.norm(np.asarray(upd)) <= 0.05 * max_norm * 1.001


def test_collapse_per_worker_is_host_side(devices):
    """The eval collapse must produce host (numpy) leaves from a
    device-sharded model_state WITHOUT compiling a fresh multi-device
    program — an eager cross-device reduction here deadlock-aborted whole
    processes on hosts with fewer cores than devices (see
    collapse_per_worker's docstring). Pins the semantics: "mean" averages
    the per-worker axis, "first" takes worker 0, both on host arrays."""
    from jax.sharding import NamedSharding, PartitionSpec

    from network_distributed_pytorch_tpu.parallel.trainer import (
        collapse_per_worker,
    )

    mesh = make_mesh()
    w = mesh.size
    stats = np.arange(w * 3, dtype=np.float32).reshape(w, 3)
    sharded = jax.device_put(
        stats, NamedSharding(mesh, PartitionSpec("data", None))
    )
    mean = collapse_per_worker({"bn": sharded}, "mean")
    first = collapse_per_worker({"bn": sharded}, "first")
    assert isinstance(mean["bn"], np.ndarray)
    assert isinstance(first["bn"], np.ndarray)
    np.testing.assert_allclose(mean["bn"], stats.mean(axis=0))
    np.testing.assert_allclose(first["bn"], stats[0])
