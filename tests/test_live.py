"""Live telemetry plane units (jax-free, fast).

Pins the streaming half of the observability stack: the metric registry
and its Prometheus text exposition, the event->metric derivation shared
by the in-process sink and the aggregator, resumable shard tailing (torn
tail mid-line, undecodable lines, restart markers, persisted offsets —
no event duplicated or dropped), the supervisor-side aggregator's gauge
math against the same analytics the post-hoc report uses, the /metrics
HTTP endpoint, and the alerts.jsonl feedback channel.
"""

import json
import os
import urllib.request

import pytest

from network_distributed_pytorch_tpu.observe import (
    CollectiveEvent,
    MemorySink,
    StepEvent,
    Telemetry,
    TrainHealthEvent,
    analytics,
    runlog,
)
from network_distributed_pytorch_tpu.observe.health import DetectorConfig
from network_distributed_pytorch_tpu.observe.live import (
    AlertFeed,
    LiveAggregator,
    MetricRegistry,
    MetricSink,
    MetricsHTTPServer,
    ShardFollower,
    append_alert,
    ingest_record,
    read_port_file,
)


# ---------------------------------------------------------------------------
# registry + exposition
# ---------------------------------------------------------------------------


def test_registry_counter_gauge_histogram():
    reg = MetricRegistry()
    reg.counter("c_total", rank="0")
    reg.counter("c_total", 2.0, rank="0")
    reg.counter("c_total", rank="1")
    reg.gauge("g", 1.5)
    reg.gauge("g", 2.5)  # last write wins
    for v in (1.0, 2.0, 3.0, 4.0):
        reg.observe("h_seconds", v)
    assert reg.get_counter("c_total", rank="0") == 3.0
    assert reg.get_counter("c_total", rank="1") == 1.0
    assert reg.get_counter("c_total", rank="9") == 0.0
    assert reg.get_gauge("g") == 2.5
    assert reg.get_gauge("missing") is None
    h = reg.get_histogram("h_seconds")
    assert h.count == 4 and h.total == 10.0
    # analytics.percentile is nearest-rank, like the report's
    assert h.percentile(50) == pytest.approx(3.0)


def test_registry_histogram_window_rolls():
    reg = MetricRegistry()
    for v in range(10):
        reg.observe("h", float(v), window=4)
    h = reg.get_histogram("h")
    # cumulative count/sum, but percentiles over the last 4 only (6..9)
    assert h.count == 10
    assert h.percentile(50) == pytest.approx(8.0)
    assert h.percentile(0) == pytest.approx(6.0)


def test_registry_snapshot_shape():
    reg = MetricRegistry()
    reg.counter("live_steps_total", rank="0")
    reg.gauge("live_loss", 0.5, rank="0")
    reg.observe("live_step_time_seconds", 0.01, rank="0")
    snap = reg.snapshot()
    assert snap["live_steps_total"]['{rank="0"}'] == 1.0
    assert snap["live_loss"]['{rank="0"}'] == 0.5
    hist = snap["live_step_time_seconds"]['{rank="0"}']
    assert hist["count"] == 1 and hist["p50"] == pytest.approx(0.01)


def test_prometheus_exposition_format():
    reg = MetricRegistry()
    reg.counter("x_total", help="things", rank="0")
    reg.gauge("y", float("inf"))
    reg.observe("z_seconds", 0.25)
    text = reg.render_prometheus()
    assert "# HELP x_total things" in text
    assert "# TYPE x_total counter" in text
    assert 'x_total{rank="0"} 1.0' in text
    assert "y +Inf" in text
    assert "# TYPE z_seconds summary" in text
    assert 'z_seconds{quantile="0.5"} 0.25' in text
    assert "z_seconds_count 1" in text
    assert "z_seconds_sum 0.25" in text
    # scrape freshness: the module's one sanctioned wall-clock read
    assert "live_scrape_unix_time" in text


# ---------------------------------------------------------------------------
# event -> metric derivation
# ---------------------------------------------------------------------------


def test_ingest_step_and_collective_and_health():
    reg = MetricRegistry()
    ingest_record(
        reg, {"event": "step", "step_time_s": 0.02, "loss": 0.7}, rank=1
    )
    ingest_record(
        reg, {"event": "step", "step_time_s": 0.04, "loss": 0.6,
              "valid": False}, rank=1
    )
    ingest_record(
        reg,
        {"event": "collective", "tag": "grads", "payload_bytes": 1024},
    )
    ingest_record(
        reg,
        {"event": "train_health", "grad_norm": 2.0, "ef_memory_norm": 0.5,
         "powersgd_rel_error": 0.1, "rank": 0},
    )
    assert reg.get_counter("live_steps_total", rank="1") == 2.0
    # the invalid step counts but its time is not observed
    assert reg.get_histogram("live_step_time_seconds", rank="1").count == 1
    assert reg.get_gauge("live_loss", rank="1") == 0.6
    assert reg.get_counter("live_comm_bytes_total", tag="grads") == 1024.0
    assert reg.get_gauge("live_grad_norm", rank="0") == 2.0
    assert reg.get_gauge("live_ef_memory_norm", rank="0") == 0.5
    assert reg.get_gauge("live_powersgd_rel_error", rank="0") == 0.1


def test_ingest_serving_request_split():
    reg = MetricRegistry()
    ingest_record(
        reg,
        {"event": "request", "state": "finished", "total_s": 1.0,
         "queue_s": 0.2, "decode_s": 0.5, "tokens_generated": 10},
    )
    ingest_record(reg, {"event": "request", "state": "failed"})
    assert reg.get_counter("live_serving_requests_total", state="finished") == 1
    assert reg.get_counter("live_serving_requests_total", state="failed") == 1
    assert reg.get_histogram("live_serving_total_seconds").count == 1
    ms = reg.get_histogram("live_serving_decode_ms_per_token")
    assert ms.percentile(50) == pytest.approx(50.0)


def test_metric_sink_rides_telemetry():
    sink = MetricSink()
    telemetry = Telemetry([sink])
    # StepEvent carries no rank; the in-process sink labels it "?"
    telemetry.emit(
        StepEvent(step=0, epoch=0, loss=1.0, step_time_s=0.01,
                  bits_cumulative=0)
    )
    telemetry.emit(
        CollectiveEvent(label="l", tag="t", layer="r", op="all-reduce",
                        axis="data", dtype="float32", payload_bytes=64)
    )
    telemetry.close()
    assert sink.registry.get_counter("live_steps_total", rank="?") == 1.0
    assert sink.registry.get_counter("live_comm_bytes_total", tag="t") == 64.0


# ---------------------------------------------------------------------------
# resumable shard tailing
# ---------------------------------------------------------------------------


def _writeln(path, obj, newline=True):
    with open(path, "a") as f:
        f.write(json.dumps(obj) + ("\n" if newline else ""))


def test_follower_torn_tail_not_consumed(tmp_path):
    shard = str(tmp_path / "events_rank0.jsonl")
    _writeln(shard, {"event": "step", "step": 0})
    _writeln(shard, {"event": "step", "step": 1}, newline=False)  # torn tail
    f = ShardFollower(shard)
    first = f.poll()
    assert [e["step"] for e in first] == [0]
    assert f.torn == 0  # a half-written tail is pending, not torn
    # the writer finishes the line and appends one more
    with open(shard, "a") as fh:
        fh.write("\n")
    _writeln(shard, {"event": "step", "step": 2})
    second = f.poll()
    assert [e["step"] for e in second] == [1, 2]  # no dup, no drop
    assert f.poll() == []


def test_follower_counts_undecodable_complete_lines(tmp_path):
    shard = str(tmp_path / "events_rank0.jsonl")
    _writeln(shard, {"event": "step", "step": 0})
    with open(shard, "a") as fh:
        fh.write("{this is not json}\n")
    _writeln(shard, {"event": "step", "step": 1})
    f = ShardFollower(shard)
    assert [e["step"] for e in f.poll()] == [0, 1]
    assert f.torn == 1


def test_follower_resumes_from_persisted_offset(tmp_path):
    shard = str(tmp_path / "events_rank0.jsonl")
    for i in range(3):
        _writeln(shard, {"event": "step", "step": i})
    f = ShardFollower(shard)
    assert len(f.poll()) == 3
    saved = f.offset
    for i in range(3, 6):
        _writeln(shard, {"event": "step", "step": i})
    resumed = ShardFollower(shard, offset=saved)
    assert [e["step"] for e in resumed.poll()] == [3, 4, 5]


def test_follower_missing_file_is_quiet(tmp_path):
    f = ShardFollower(str(tmp_path / "absent.jsonl"))
    assert f.poll() == []
    assert f.offset == 0


# ---------------------------------------------------------------------------
# the aggregator
# ---------------------------------------------------------------------------


def _marker(rank, incarnation, ts, ts_mono):
    return {
        "event": "marker", "kind": "run_start", "run_id": "runL",
        "rank": rank, "world_size": 2, "incarnation": incarnation,
        "ts": ts, "ts_mono": ts_mono,
    }


def _step(rank, step, dt, ts, ts_mono, loss=None):
    rec = {
        "event": "step", "step": step, "epoch": 0, "step_time_s": dt,
        "rank": rank, "ts": ts, "ts_mono": ts_mono,
    }
    if loss is not None:
        rec["loss"] = loss
    return rec


def _toy_run(tmp_path, times_by_rank, payload=1 << 20):
    """A two-rank run dir with a manifest, markers, one wire-ledger
    collective per rank (deduped by the aggregator), and steady steps."""
    run_dir = str(tmp_path)
    m = runlog.new_manifest("runL", world_size=2)
    for r in (0, 1):
        m.record_spawn(rank=r, incarnation=0, world_size=2,
                       spawned_unix=100.0)
    m.save(run_dir)
    for r, times in times_by_rank.items():
        shard = os.path.join(run_dir, runlog.shard_name(r))
        _writeln(shard, _marker(r, 0, 100.5, 50.0))
        _writeln(shard, {
            "event": "collective", "label": "toy", "tag": "toy.grads",
            "layer": "reducer", "op": "all-reduce", "axis": "data",
            "dtype": "float32", "payload_bytes": payload, "rank": r,
            "ts": 100.5, "ts_mono": 50.0,
        })
        t = 101.0
        mono = 51.0
        for i, dt in enumerate(times):
            t += dt
            mono += dt
            _writeln(shard, _step(r, i, dt, t, mono))
    return run_dir


def test_aggregator_gauges_match_report_statistics(tmp_path):
    # first timed step per incarnation pays compile and must be dropped
    times = {0: [0.5, 0.01, 0.02, 0.03], 1: [0.5, 0.02, 0.02, 0.04]}
    run_dir = _toy_run(tmp_path, times)
    agg = LiveAggregator(run_dir)
    agg.poll()
    expected_p50 = analytics.percentile(
        [analytics.percentile(times[0][1:], 50),
         analytics.percentile(times[1][1:], 50)], 50,
    )
    assert agg.step_p50_s() == pytest.approx(expected_p50)
    assert agg.registry.get_gauge(
        "live_step_time_p50_seconds"
    ) == pytest.approx(expected_p50)
    # bytes/s: same effective_bandwidth call the report makes, over the
    # deduped ledger (two ranks emitted the same collective once)
    bw = agg.bandwidth()
    expected = analytics.effective_bandwidth(
        expected_p50,
        [{"label": "toy", "tag": "toy.grads", "op": "all-reduce",
          "dtype": "float32", "payload_bytes": 1 << 20}],
        2,
    )
    assert bw["total"]["achieved_bytes_per_s"] == pytest.approx(
        expected["total"]["achieved_bytes_per_s"]
    )
    assert agg.registry.get_gauge("live_comm_bytes_per_s") == pytest.approx(
        expected["total"]["achieved_bytes_per_s"]
    )
    assert agg.registry.get_counter("live_steps_total", rank="0") == 4.0


def test_aggregator_restart_marker_drops_new_first_step(tmp_path):
    run_dir = _toy_run(tmp_path, {0: [0.5, 0.01, 0.01], 1: [0.5, 0.01, 0.01]})
    agg = LiveAggregator(run_dir)
    agg.poll()
    # rank 1 restarts: new incarnation marker, then its own compile-paying
    # first step (slow) and steady steps — the slow step must NOT land in
    # the steady-state stats
    shard = os.path.join(run_dir, runlog.shard_name(1))
    _writeln(shard, _marker(1, 1, 110.0, 10.0))
    _writeln(shard, _step(1, 3, 0.9, 110.9, 10.9))
    _writeln(shard, _step(1, 4, 0.01, 110.91, 10.91))
    agg.poll()
    assert 0.9 not in agg._steady[1]
    assert agg._steady[1].count(0.01) >= 2


def test_aggregator_offsets_roundtrip_no_double_count(tmp_path):
    run_dir = _toy_run(tmp_path, {0: [0.5, 0.01], 1: [0.5, 0.01]})
    agg = LiveAggregator(run_dir)
    agg.poll()
    offsets = os.path.join(run_dir, "offsets.json")
    agg.save_offsets(offsets)

    shard = os.path.join(run_dir, runlog.shard_name(0))
    _writeln(shard, _step(0, 2, 0.02, 102.0, 52.0))
    follower = LiveAggregator(run_dir)
    follower.load_offsets(offsets)
    follower.poll()
    # the resumed aggregator sees ONLY the new step
    assert follower.registry.get_counter("live_steps_total", rank="0") == 1.0
    assert follower.registry.get_counter("live_steps_total", rank="1") == 0.0


def test_aggregator_fires_grad_spike_alert(tmp_path):
    run_dir = _toy_run(tmp_path, {0: [0.5, 0.01], 1: [0.5, 0.01]})
    shard = os.path.join(run_dir, runlog.shard_name(0))
    t = 103.0
    for i in range(4):
        _writeln(shard, {
            "event": "train_health", "step": i, "grad_norm": 1.0,
            "rank": 0, "ts": t + i, "ts_mono": 53.0 + i,
        })
    _writeln(shard, {
        "event": "train_health", "step": 4, "grad_norm": 1000.0,
        "rank": 0, "ts": t + 4, "ts_mono": 57.0,
    })
    agg = LiveAggregator(run_dir)
    fired = agg.poll()
    spikes = [a for a in fired if a.alert == "grad_spike"]
    assert len(spikes) == 1
    assert spikes[0].severity == "critical"
    assert spikes[0].rank == 0
    assert agg.registry.get_counter(
        "live_alerts_fired_total", alert="grad_spike", severity="critical"
    ) == 1.0
    # idle polls fire nothing new
    assert agg.poll() == []


def test_aggregator_counts_torn_lines(tmp_path):
    run_dir = _toy_run(tmp_path, {0: [0.5, 0.01], 1: [0.5, 0.01]})
    shard = os.path.join(run_dir, runlog.shard_name(0))
    with open(shard, "a") as fh:
        fh.write("not json at all\n")
    _writeln(shard, _step(0, 2, 0.02, 102.0, 52.0))
    agg = LiveAggregator(run_dir)
    agg.poll()
    assert agg.registry.get_gauge("live_torn_lines_total") == 1.0


def test_aggregator_detector_config_threading(tmp_path):
    run_dir = _toy_run(tmp_path, {0: [0.5, 0.01], 1: [0.5, 0.01]})
    cfg = DetectorConfig(spike_sigma=2.0, nan_factor=5.0)
    agg = LiveAggregator(run_dir, detector_config=cfg)
    assert agg.monitor.config.nan_factor == 5.0


# ---------------------------------------------------------------------------
# /metrics exposition server
# ---------------------------------------------------------------------------


def test_metrics_http_server_scrape_and_port_file(tmp_path):
    reg = MetricRegistry()
    reg.counter("live_steps_total", 7.0, rank="0")
    server = MetricsHTTPServer(reg, port=0).start()
    try:
        assert server.port > 0
        server.write_port_file(str(tmp_path))
        assert read_port_file(str(tmp_path)) == server.port
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(f"{base}/metrics", timeout=5.0) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode()
        assert 'live_steps_total{rank="0"} 7.0' in body
        with urllib.request.urlopen(f"{base}/healthz", timeout=5.0) as resp:
            assert resp.status == 200
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/nope", timeout=5.0)
    finally:
        server.close()


def test_read_port_file_absent(tmp_path):
    assert read_port_file(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# the alerts.jsonl feedback channel
# ---------------------------------------------------------------------------


def test_alert_feed_roundtrip(tmp_path):
    run_dir = str(tmp_path)
    feed = AlertFeed(run_dir)
    assert feed.poll() == []  # channel not created yet
    append_alert(run_dir, {"event": "alert", "alert": "grad_spike",
                           "severity": "critical"})
    append_alert(run_dir, {"event": "marker", "kind": "noise"})
    got = feed.poll()
    assert len(got) == 1 and got[0]["alert"] == "grad_spike"
    # incremental: nothing new, nothing returned
    assert feed.poll() == []
    append_alert(run_dir, {"event": "alert", "alert": "slo_burn",
                           "severity": "warn"})
    assert [r["alert"] for r in feed.poll()] == ["slo_burn"]


def test_memory_sink_records_train_health_event():
    sink = MemorySink()
    telemetry = Telemetry([sink])
    telemetry.emit(TrainHealthEvent(step=3, epoch=1, grad_norm=1.5,
                                    ef_memory_norm=0.2,
                                    powersgd_rel_error=0.05, rank=0))
    telemetry.close()
    recs = [r for r in sink.records if r["event"] == "train_health"]
    assert len(recs) == 1
    assert recs[0]["grad_norm"] == 1.5
    assert recs[0]["powersgd_rel_error"] == 0.05
