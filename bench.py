"""Benchmark — one JSON line for the driver.

Flagship: CIFAR-10 training step (entry point A/B's model family) on real
TPU. Two configurations run back-to-back:

- **baseline emulation**: the reference's exact-DDP configuration translated
  literally — ResNet-50, fp32, exact allreduce-mean, SGD momentum
  (``ddp_guide_cifar10/ddp_init.py:108-125``).
- **flagship**: the same model trained the TPU-first way — bfloat16 compute
  on the MXU + PowerSGD rank-4 compressed reduction (the reference's
  flagship algorithm, ``ddp_powersgd_guide_cifar10``).

metric  = flagship images/sec (global batch 256, one training step)
vs_baseline = flagship imgs/sec / baseline-emulation imgs/sec — i.e. how much
faster the TPU-native design trains the reference's own workload than a
literal translation of the reference's config. The reference itself publishes
no numbers to compare against (BASELINE.md).
"""

import json
import time

import jax
import jax.numpy as jnp


def _measure(step, state, batch, iters=10):
    state, loss = step(state, batch)  # compile + warmup
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = step(state, batch)
    jax.block_until_ready(loss)
    return (time.perf_counter() - t0) / iters


def main():
    from network_distributed_pytorch_tpu.data import synthetic_cifar10
    from network_distributed_pytorch_tpu.experiments.common import image_classifier_loss
    from network_distributed_pytorch_tpu.models import resnet50
    from network_distributed_pytorch_tpu.parallel import (
        ExactReducer,
        PowerSGDReducer,
        make_mesh,
    )
    from network_distributed_pytorch_tpu.parallel.trainer import make_train_step

    batch_size = 256  # reference global batch — ddp_guide_cifar10/ddp_init.py:49
    mesh = make_mesh()
    images, labels = synthetic_cifar10(batch_size, seed=0)
    batch = (jnp.asarray(images), jnp.asarray(labels))

    results = {}
    for name, dtype, reducer, algo in [
        ("baseline_fp32_exact", jnp.float32, ExactReducer(), "sgd"),
        (
            "flagship_bf16_powersgd",
            jnp.bfloat16,
            PowerSGDReducer(random_seed=714, compression_rank=4, matricize="last"),
            "ef_momentum",
        ),
    ]:
        model = resnet50(num_classes=10, norm="batch", stem="imagenet", dtype=dtype)
        variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=True)
        loss_fn = image_classifier_loss(model, has_batch_stats=True)
        step = make_train_step(
            loss_fn, reducer, variables["params"], learning_rate=0.001, momentum=0.9,
            algorithm=algo, mesh=mesh, donate_state=False,
        )
        state = step.init_state(
            variables["params"], model_state={"batch_stats": variables["batch_stats"]}
        )
        t = _measure(step, state, batch)
        results[name] = batch_size / t

    value = results["flagship_bf16_powersgd"]
    vs = value / results["baseline_fp32_exact"]
    print(
        json.dumps(
            {
                "metric": "cifar10_resnet50_train_imgs_per_sec",
                "value": round(value, 2),
                "unit": "imgs/sec",
                "vs_baseline": round(vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
