"""Benchmark — one JSON line for the driver.

Flagship: CIFAR-10 ResNet-50 training (the reference's entry point A/B model
family) on real TPU. Two configurations run back-to-back:

- **baseline emulation**: the reference's configuration translated literally
  — ResNet-50, fp32, exact allreduce-mean, SGD momentum, one host dispatch
  per step (the reference's Python loop,
  ``ddp_guide_cifar10/ddp_init.py:108-125``).
- **flagship**: the same workload the TPU-first way — bfloat16 compute on
  the MXU and the ``lax.scan`` epoch runner (whole step chunks compiled into
  ONE dispatch, ``make_scanned_train_fn``), donated carries.

On a single chip there is no wire, so gradient-sync flavor is irrelevant to
wall time here; the compressed-vs-exact wire story is measured by the
bandwidth study harness (``experiments/bandwidth_study.py``) and the HLO
collective audit instead. metric = flagship imgs/sec; vs_baseline =
flagship / baseline — how much faster the TPU-native design trains the
reference's own workload than a literal translation of it. The reference
itself publishes no numbers (BASELINE.md).

Also reported: **MFU** — the compiled program's FLOPs (XLA cost analysis on
the exact executable that ran) ÷ measured step time ÷ the chip's peak bf16
FLOP/s, detected from ``device_kind``.

Resilience (round-1 postmortem: ``BENCH_r01.json`` rc=1, one transient
``UNAVAILABLE`` at backend init threw away the round's only hardware run):
this process performs the session's FIRST jax backend init, guarded by a
SIGALRM watchdog (the TPU tunnel's failure mode is an indefinite hang) and
in-process retries; if init still fails, the whole interpreter re-execs
itself (backend-init failures are cached per-process in jax) up to
``MAX_ATTEMPTS``. Every exit path prints exactly one parseable JSON line.
"""

import json
import os
import sys
import time

CHUNK = int(os.environ.get("BENCH_CHUNK", "10"))  # steps per scanned dispatch
ATTEMPT_ENV = "BENCH_ATTEMPT"
MAX_ATTEMPTS = int(os.environ.get("BENCH_MAX_ATTEMPTS", "4"))
# escalating per-attempt init deadline (round-2 postmortem: three flat 120 s
# timeouts lost the round's only driver-run TPU window — a cold tunnel can
# legitimately need several minutes for its first backend init); an explicit
# BENCH_INIT_TIMEOUT_S pins every attempt instead
_INIT_TIMEOUT_LADDER = (180, 300, 600, 600)
INIT_TIMEOUT_S = int(
    os.environ.get("BENCH_INIT_TIMEOUT_S", "0")
) or _INIT_TIMEOUT_LADDER[
    min(int(os.environ.get(ATTEMPT_ENV, "1")) - 1, len(_INIT_TIMEOUT_LADDER) - 1)
]
# total wall budget across the whole re-exec ladder: the driver must get
# its one JSON line before ITS patience runs out, so once the ladder has
# burned this much the next failure skips straight to the CPU fallback
# instead of another long TPU attempt. First exec stamps the start time.
TOTAL_DEADLINE_S = int(os.environ.get("BENCH_TOTAL_DEADLINE_S", "1500"))
_START_ENV = "BENCH_START_TS"
os.environ.setdefault(_START_ENV, str(int(time.time())))


def _ladder_elapsed_s() -> float:
    return time.time() - float(os.environ[_START_ENV])

# Peak dense bf16 FLOP/s per chip by device_kind substring (public spec
# sheets). Longest match wins ("v5 lite" before "v5").
_PEAK_BF16_FLOPS = {
    "v2": 45e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5litepod": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v5": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
    "v6": 918e12,
}


def _emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def _peak_flops(device) -> float:
    """Peak bf16 FLOP/s for ``device``, or 0.0 when unknown (CPU smoke tier)."""
    kind = (getattr(device, "device_kind", "") or "").lower()
    if device.platform != "tpu":
        return 0.0
    for key in sorted(_PEAK_BF16_FLOPS, key=len, reverse=True):
        if key in kind:
            return _PEAK_BF16_FLOPS[key]
    return 0.0


class _InitTimeout(BaseException):
    """Backend init hang (probe thread still blocked after the deadline).
    BaseException-derived so ``retry_transient`` (which retries ``Exception``)
    never waits out a second in-process hang — a hang goes straight to the
    re-exec ladder, which catches it explicitly."""


def _init_backend():
    """The session's first jax backend touch, with watchdog + retry.

    ``jax.devices()`` against the one-shot TPU tunnel either works quickly,
    fails with a transient UNAVAILABLE, or hangs forever. The hang blocks
    inside the PJRT C++ client, where no Python signal handler can run — so
    the probe runs in a daemon worker thread and the main thread joins with
    a deadline; a blown deadline escalates to the fresh-interpreter re-exec
    ladder in ``main`` (the hung thread is destroyed by ``execv``).
    Transient *exceptions* get one cheap in-process ``retry_transient``
    pass first (cheap because jax caches a failed init per-process: if the
    failure is sticky the retry re-raises instantly and the ladder takes
    over with a truly fresh process).
    """
    import threading

    import jax

    from network_distributed_pytorch_tpu.utils.failure import retry_transient

    # the environment may pin an accelerator platform by config (the axon
    # sitecustomize sets jax_platforms itself, so the env var alone is not
    # enough); BENCH_PLATFORM=cpu is the CI/smoke override
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    def _enable_tpu_cache(devices) -> None:
        # persistent compilation cache — enabled only once the PROBED
        # platform is TPU: big-model compiles through the TPU tunnel are
        # minutes-slow and the tunnel is flaky, so caching the serialized
        # executable on disk makes every retry (including this process's
        # own re-exec ladder) resume instead of re-pay. Never enabled for
        # XLA:CPU: its AOT entries can carry stricter machine features than
        # runtime detection reports (observed '+prefer-no-scatter … could
        # lead to SIGILL' warnings).
        if devices[0].platform != "tpu":
            return
        try:
            cache_dir = os.environ.get(
                "BENCH_XLA_CACHE",
                os.path.join(os.path.dirname(os.path.abspath(__file__)), ".xla_cache"),
            )
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception as e:  # noqa: BLE001
            print(f"# bench: compilation cache unavailable: {e}", file=sys.stderr)

    def _probe():
        box = {}

        def worker():
            try:
                box["devices"] = jax.devices()
            except BaseException as e:  # noqa: BLE001 — relayed to main thread
                box["error"] = e

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        t.join(INIT_TIMEOUT_S)
        if t.is_alive():
            raise _InitTimeout(f"jax backend init exceeded {INIT_TIMEOUT_S}s")
        if "error" in box:
            raise box["error"]
        return box["devices"]

    devices = retry_transient(
        _probe, retries=1, backoff_seconds=1.0,
        exceptions=(Exception,), on_retry=lambda i, e: print(
            f"# bench: backend init retry {i}: {type(e).__name__}: {e}",
            file=sys.stderr, flush=True,
        ),
    )
    _enable_tpu_cache(devices)
    return devices


def _measure(results: dict) -> dict:
    import jax
    import jax.numpy as jnp

    from network_distributed_pytorch_tpu.data import synthetic_cifar10
    from network_distributed_pytorch_tpu.experiments.common import image_classifier_loss
    from network_distributed_pytorch_tpu.models import resnet18, resnet50
    from network_distributed_pytorch_tpu.parallel import ExactReducer, make_mesh
    from network_distributed_pytorch_tpu.parallel.trainer import (
        make_scanned_train_fn,
        make_train_step,
    )

    # BENCH_PRESET=small: CPU-feasible smoke tier (CI / harness validation);
    # default is the reference's full config on the real chip. A non-TPU
    # platform auto-selects the small tier (the full ResNet-50/batch-256
    # config takes >10 min/step-chunk on CPU — useless as a smoke signal)
    # unless BENCH_PRESET=full explicitly forces it.
    preset_env = os.environ.get("BENCH_PRESET", "").lower()
    small = preset_env == "small" or (
        preset_env != "full" and jax.devices()[0].platform != "tpu"
    )
    results["preset"] = "small" if small else "full"
    make_model = (
        (lambda dtype: resnet18(num_classes=10, norm="batch", stem="cifar", width=8, dtype=dtype))
        if small
        else (lambda dtype: resnet50(num_classes=10, norm="batch", stem="imagenet", dtype=dtype))
    )
    # reference global batch — ddp_guide_cifar10/ddp_init.py:49
    batch_size = 32 if small else 256
    mesh = make_mesh()
    results["device"] = getattr(jax.devices()[0], "device_kind", jax.devices()[0].platform)
    images, labels = synthetic_cifar10(batch_size, seed=0)
    batch = (jnp.asarray(images), jnp.asarray(labels))

    # --- baseline emulation: fp32, stepwise host loop ---------------------
    model = make_model(jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=True)
    loss_fn = image_classifier_loss(model, has_batch_stats=True)
    step = make_train_step(
        loss_fn, ExactReducer(), variables["params"], learning_rate=0.001,
        momentum=0.9, algorithm="sgd", mesh=mesh, donate_state=True,
    )
    state = step.init_state(
        variables["params"], model_state={"batch_stats": variables["batch_stats"]}
    )
    from network_distributed_pytorch_tpu.utils.timing import wait_result

    state, loss = step(state, batch)  # compile + warmup
    wait_result(loss)
    t0 = time.perf_counter()
    for _ in range(CHUNK):
        state, loss = step(state, batch)
    wait_result(loss)  # fetch-to-observe-completion, utils.timing
    results["baseline_imgs_per_sec"] = batch_size * CHUNK / (time.perf_counter() - t0)

    # --- flagship: bf16 MXU compute + scanned epoch runner ----------------
    model = make_model(jnp.bfloat16)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=True)
    loss_fn = image_classifier_loss(model, has_batch_stats=True)
    scanned = make_scanned_train_fn(
        loss_fn, ExactReducer(), variables["params"], learning_rate=0.001,
        momentum=0.9, algorithm="sgd", mesh=mesh, donate_state=True,
    )
    state = scanned.init_state(
        variables["params"], model_state={"batch_stats": variables["batch_stats"]}
    )
    chunk_batch = (
        jnp.broadcast_to(batch[0][None], (CHUNK,) + batch[0].shape),
        jnp.broadcast_to(batch[1][None], (CHUNK,) + batch[1].shape),
    )
    # AOT-compile so the MFU numerator is the cost analysis of the EXACT
    # executable being timed (no second trace/compile).
    compiled = scanned.fn.lower(state, chunk_batch).compile()
    flops_chunk = 0.0
    try:
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        flops_chunk = float(ca.get("flops", 0.0))
    except Exception:  # cost analysis is best-effort; MFU just goes unreported
        pass
    state, losses = compiled(state, chunk_batch)  # warmup
    wait_result(losses)
    t0 = time.perf_counter()
    state, losses = compiled(state, chunk_batch)
    wait_result(losses)
    dt = time.perf_counter() - t0
    results["flagship_imgs_per_sec"] = batch_size * CHUNK / dt
    results["step_time_ms"] = 1000.0 * dt / CHUNK

    peak = _peak_flops(jax.devices()[0])
    if flops_chunk > 0 and peak > 0:
        results["mfu"] = flops_chunk / dt / peak
        results["flops_per_step"] = flops_chunk / CHUNK

    _overlap_evidence(results, make_model, mesh)
    _measure_gpt(results)
    return results


def _measure_gpt(results: dict) -> None:
    """GPT-2-small (124M) training-step throughput + MFU — the compute-dense
    workload where MFU is meaningful (CIFAR's 32×32 convs genuinely bound MXU
    utilization, so the flagship CIFAR MFU reads low by construction; a
    768-dim decoder at seq 1024 keeps the MXU fed and makes the number
    interpretable). The measurement itself lives in
    ``utils.benchmarks.time_gpt_train_step`` — the SAME scaffold
    ``scripts/tpu_evidence.py`` uses, so the driver metric and the committed
    hardware record share one methodology (AOT executable, cost analysis of
    the exact program timed, fetch-to-observe timing). Best-effort —
    failures are recorded, never fatal."""
    try:
        import jax

        from network_distributed_pytorch_tpu.utils.benchmarks import (
            time_gpt_train_step,
        )

        small = results.get("preset") == "small"
        gpt = time_gpt_train_step(
            small=small,
            seq_len=64 if small else 1024,
            batch=8,
            vocab=128 if small else 50257,
            reps=2 if small else 10,
        )
        flops = gpt.pop("flops_per_step", None)
        peak = _peak_flops(jax.devices()[0])
        if flops and peak > 0:
            gpt["mfu"] = round(flops / (gpt["step_time_ms"] / 1000.0) / peak, 4)
            gpt["flops_per_step"] = flops
        results["gpt"] = gpt
    except Exception as e:  # noqa: BLE001 — evidence is best-effort
        results["gpt"] = {"error": f"{type(e).__name__}: {e}"[:300]}


def _overlap_evidence(results: dict, make_model, mesh) -> None:
    """Comm/compute concurrency evidence for the PowerSGD step, from the
    scheduled v5e executable (SURVEY §5 set 'assert via profile' as the bar
    for replacing the reference's async-handle overlap, ``reducer.py:131-168``).

    Two findings are extracted from the post-optimization HLO and persisted
    as ``OVERLAP.json``: (a) any async ``*-start``/``*-done`` collective
    windows and the compute scheduled inside them (``utils.overlap``), and
    (b) what the all-reduce combiner did to the 4 logical collectives
    (P, rank-1, Q, loss) — on v5e it MERGES the rank-1 payload into the Q
    all-reduce, eliminating the separate collective the reference could only
    hide. Unless the bench is already running on a ≥2-chip TPU mesh, the
    step is compiled against an 8-chip v5e topology AOT — the schedule IS
    the evidence, no execution needed. Best-effort: failures are recorded,
    never fatal."""
    import jax
    import jax.numpy as jnp

    from network_distributed_pytorch_tpu.experiments.common import image_classifier_loss
    from network_distributed_pytorch_tpu.parallel import PowerSGDReducer, make_mesh
    from network_distributed_pytorch_tpu.parallel.trainer import make_train_step
    from network_distributed_pytorch_tpu.utils.hlo_audit import collective_summary
    from network_distributed_pytorch_tpu.utils.overlap import overlap_report

    try:
        target_mesh = mesh
        topology_note = "attached TPU devices"
        if mesh.size < 2 or jax.devices()[0].platform != "tpu":
            from jax.experimental import topologies

            topo = topologies.get_topology_desc(
                platform="tpu", topology_name="v5e:2x4"
            )
            target_mesh = make_mesh(devices=topo.devices)
            topology_note = "AOT v5e:2x4 topology (no execution)"

        model = make_model(jnp.bfloat16)
        variables = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=True
        )
        loss_fn = image_classifier_loss(model, has_batch_stats=True)
        step = make_train_step(
            loss_fn,
            PowerSGDReducer(random_seed=714, compression_rank=4, matricize="last"),
            variables["params"], learning_rate=0.001, momentum=0.9,
            algorithm="ef_momentum", mesh=target_mesh, donate_state=False,
        )
        state_abs = jax.eval_shape(
            lambda p, bs: step.init_state(p, model_state={"batch_stats": bs}),
            variables["params"], variables["batch_stats"],
        )
        batch_abs = (
            jax.ShapeDtypeStruct((8 * target_mesh.size, 32, 32, 3), jnp.float32),
            jax.ShapeDtypeStruct((8 * target_mesh.size,), jnp.int32),
        )
        # ask for ASYNC collectives + the latency-hiding scheduler so the
        # scheduled HLO exposes *-start/*-done windows with compute inside
        # them — the TPU equivalent of the reference's async handle overlap
        # (reducer.py:131-168), asserted from the schedule itself. Option
        # sets are tried most-specific first; an executable with no async
        # windows still yields the combiner-merge evidence.
        lowered = step.fn.lower(state_abs, batch_abs)
        compiled_exe, flags_used = None, None
        for opts in (
            {
                "xla_tpu_enable_latency_hiding_scheduler": "true",
                "xla_tpu_enable_async_collective_fusion": "true",
                "xla_tpu_enable_async_collective_fusion_fuse_all_reduce": "true",
            },
            {"xla_tpu_enable_latency_hiding_scheduler": "true"},
            None,
        ):
            try:
                compiled_exe = (
                    lowered.compile(compiler_options=opts)
                    if opts
                    else lowered.compile()
                )
                flags_used = sorted(opts) if opts else []
                break
            except Exception as opt_err:  # noqa: BLE001 — try the next set
                last_opt_err = opt_err
        if compiled_exe is None:
            raise last_opt_err
        from network_distributed_pytorch_tpu.utils.hlo_audit import (
            hlo_text_of_compiled,
        )

        hlo = hlo_text_of_compiled(compiled_exe)
        rep = overlap_report(hlo)
        rep["compiler_flags"] = flags_used
        aud = collective_summary(hlo)
        rep["compiled_collectives"] = {
            "count": aud["count"],
            "by_kind": aud["by_kind"],
            "ops": [
                {
                    "kind": o.kind,
                    "dtype": o.dtype,
                    "shapes": [list(s) for s in o.shape],
                    "payload_bytes": o.payload_bytes,
                }
                for o in aud["ops"]
            ],
        }
        # P, rank-1, Q, loss — reducer.py:126-147 + the loss pmean
        rep["logical_collectives"] = 4
        rep["combiner_merged"] = aud["count"] < 4
        rep["workload"] = "powersgd_r4_" + ("resnet18" if "small" == results.get("preset") else "resnet50")
        rep["compiled_for"] = topology_note
        # an AOT-topology schedule is attached-device-independent — say so
        # rather than stamping whatever chip happened to be attached
        rep["device"] = (
            "AOT (schedule is attached-device-independent)"
            if target_mesh is not mesh
            else results.get("device", "?")
        )
        # only the real-chip run owns OVERLAP.json — a CPU smoke run must
        # not clobber the committed TPU artifact (it once did)
        name = (
            "OVERLAP.json"
            if jax.devices()[0].platform == "tpu"
            else "OVERLAP_smoke.json"
        )
        with open(
            os.path.join(os.path.dirname(os.path.abspath(__file__)), name), "w"
        ) as f:
            json.dump(rep, f, indent=1)
        results["overlap"] = {
            "n_async_collectives": rep["n_async_collectives"],
            "n_overlapped": rep["n_overlapped"],
            "compiled_collectives": aud["count"],
            "combiner_merged": rep["combiner_merged"],
        }
    except Exception as e:  # noqa: BLE001 — evidence is best-effort
        results["overlap"] = {"error": f"{type(e).__name__}: {e}"[:300]}


def _artifact_pointers(out: dict) -> None:
    """Compact pointers to the round's committed hardware/accuracy evidence
    (artifacts/TPU_EVIDENCE.json, artifacts/ACCURACY_STUDY.json) so the one
    bench line names the fuller record even when the end-of-round tunnel is
    wedged and this process had to fall back to the CPU smoke tier."""
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        with open(os.path.join(here, "artifacts", "TPU_EVIDENCE.json")) as f:
            ev = json.load(f)
        out["tpu_evidence"] = {
            "device": ev.get("device"),
            "recorded_unix": ev.get("recorded_unix"),  # None = pre-round-3
            "phases_ok": sorted(
                k for k, v in ev.get("phases", {}).items() if v.get("ok")
            ),
        }
    except Exception:  # noqa: BLE001 — pointer only
        pass
    try:
        with open(os.path.join(here, "artifacts", "ACCURACY_STUDY.json")) as f:
            st = json.load(f)
        out["accuracy_study"] = {
            t: {
                "accuracy_delta_pts": st[t].get("accuracy_delta_pts"),
                "gradient_bytes_ratio": st[t].get("gradient_bytes_ratio"),
            }
            for t in ("cifar", "imdb")
            if t in st
        }
    except Exception:  # noqa: BLE001 — pointer only
        pass


def main() -> int:
    out = {
        "metric": "cifar10_resnet50_train_imgs_per_sec",
        "value": 0.0,
        "unit": "imgs/sec",
        "vs_baseline": 0.0,
    }
    _artifact_pointers(out)
    try:
        _init_backend()
    except (_InitTimeout, Exception) as e:
        attempt = int(os.environ.get(ATTEMPT_ENV, "1"))
        if attempt < MAX_ATTEMPTS and _ladder_elapsed_s() < TOTAL_DEADLINE_S:
            # backend-init failures are cached per-process: a fresh interpreter
            # is the only real retry
            print(
                f"# bench: attempt {attempt} failed at init "
                f"({type(e).__name__}: {e}); re-exec "
                f"({int(_ladder_elapsed_s())}s/{TOTAL_DEADLINE_S}s budget)",
                file=sys.stderr, flush=True,
            )
            os.environ[ATTEMPT_ENV] = str(attempt + 1)
            time.sleep(5.0 * attempt)
            os.execv(sys.executable, [sys.executable, os.path.abspath(__file__)] + sys.argv[1:])
        if not os.environ.get("BENCH_PLATFORM"):
            # TPU unreachable after every retry (e.g. a wedged tunnel):
            # degrade to the CPU smoke tier in one final fresh interpreter —
            # an honest, clearly-labeled ("device": "cpu", "preset":
            # "small") harness-works number plus the TPU error beats an
            # error-only line. BENCH_NO_CPU_FALLBACK=1 restores fail-hard.
            if os.environ.get("BENCH_NO_CPU_FALLBACK") != "1":
                print(
                    f"# bench: TPU init failed after {attempt} attempts; "
                    "falling back to CPU smoke tier",
                    file=sys.stderr, flush=True,
                )
                os.environ["BENCH_PLATFORM"] = "cpu"
                os.environ["BENCH_TPU_ERROR"] = (
                    f"{type(e).__name__}: {e}"[:300]
                )
                os.environ.pop("PALLAS_AXON_POOL_IPS", None)
                os.environ[ATTEMPT_ENV] = str(attempt + 1)
                os.execv(
                    sys.executable,
                    [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
                )
        out["error"] = f"backend init failed after {attempt} attempts: {type(e).__name__}: {e}"[:800]
        _emit(out)
        return 0

    results = {}
    try:
        _measure(results)
        out["value"] = round(results["flagship_imgs_per_sec"], 2)
        out["vs_baseline"] = round(
            results["flagship_imgs_per_sec"] / results["baseline_imgs_per_sec"], 3
        )
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"[:800]
    for k in ("mfu", "step_time_ms", "device", "preset", "overlap", "gpt"):
        if k in results:
            out[k] = round(results[k], 4) if isinstance(results[k], float) else results[k]
    if os.environ.get("BENCH_TPU_ERROR"):
        out["tpu_error"] = os.environ["BENCH_TPU_ERROR"]
    _emit(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
