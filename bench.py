"""Benchmark — incremental JSON lines for the driver (it parses the tail).

Flagship: CIFAR-10 ResNet-50 training (the reference's entry point A/B model
family, ``ddp_guide_cifar10/ddp_init.py:57-62``) on real TPU. Two arms:

- **baseline emulation**: the reference's configuration translated literally
  — ResNet-50, fp32, exact allreduce-mean, SGD momentum, one host dispatch
  per step (the reference's Python loop,
  ``ddp_guide_cifar10/ddp_init.py:108-125``).
- **flagship**: the same workload the TPU-first way — bfloat16 compute on
  the MXU and the ``lax.scan`` epoch runner (whole step chunks compiled into
  ONE dispatch, ``make_scanned_train_fn``), donated carries.

metric = flagship imgs/sec; vs_baseline = flagship / baseline. Also
reported: **MFU** (XLA cost analysis of the exact executable timed ÷ wall
time ÷ peak bf16 FLOP/s by device_kind) for both the flagship and a
full-shape GPT-2-small (124M, seq 1024, vocab 50257) training step — the
compute-dense workload where MFU is meaningful. All timing is
fetch-to-observe (``utils.timing.wait_result``): on this platform
``block_until_ready`` can return before execution completes.

Architecture (round-3 postmortem — ``BENCH_r03.json`` rc=124, *nothing*
printed: the old all-or-nothing process died inside a single monolithic
measurement pass, stuck in a C++ ``CompileAndLoad`` where no Python signal
handler can run): this file is now TWO programs.

- **Parent orchestrator** (default entry): imports no jax. Emits a valid
  JSON line immediately, then spawns one child at a time to run measurement
  phases in order (probe → flagship → baseline → gpt → overlap), each under
  a HARD per-phase deadline — a child wedged inside a compile is SIGKILLed,
  which no in-process watchdog can do. After every phase result it re-emits
  one cumulative, self-contained JSON line, so whenever the driver's
  patience runs out the tail of stdout is the richest complete snapshot.
  A global deadline (default 870 s < the driver's window) is enforced
  between phases; remaining phases are recorded as skipped. The very last
  line is a bounded (≤1,200-char) summary digest so a fixed-size stdout
  tail always ends in one complete, parseable record.
- **Child** (``--phases a,b,...``): performs the backend init (daemon-thread
  watchdog — the TPU tunnel's failure mode is an indefinite hang inside the
  PJRT client), then runs its phases, printing one marker-prefixed JSON
  line per phase. One child runs many phases (backend init is paid once);
  only after a kill does a fresh child re-pay init for the remainder.
  Each non-probe phase also self-deadlines in a daemon thread at its
  budget minus a margin (``_run_with_deadline``): an overlong compile is
  ABANDONED with an error marker instead of letting the parent SIGKILL the
  child — a SIGKILL mid-compile wedges the tunnel's remote side for a long
  time (observed >1 h), and abandoning keeps the initialized backend alive
  for the remaining phases.

If backend init fails twice in a row the parent degrades to the CPU smoke
tier in clearly-labeled form (``"device": "cpu"``, ``"preset": "small"``)
unless BENCH_NO_CPU_FALLBACK=1 — an honest harness-works number plus the
TPU error beats an error-only line. Children on TPU enable the persistent
compilation cache (``.xla_cache/``), so any run in the same machine image
(including a mid-round warmup) makes later runs compile warm.
"""

from __future__ import annotations

import glob
import json
import math
import os
import subprocess
import sys
import threading
import time

HERE = os.path.dirname(os.path.abspath(__file__))
# steps per scanned dispatch. The flagship times ONE dispatch
# fetch-to-observe, so the tunnel's host<->chip round trip is amortized
# over CHUNK steps — at 10, that overhead dominated the measurement
# (22.8k vs 35.0k imgs/sec across identical runs was tunnel-latency
# variance, artifacts/BENCH_R4_RUN2.json). 50 is still far below real
# usage (make_scanned_train_fn dispatches a ~195-step CIFAR epoch per
# call), so the amortization understates, not overstates, the runner.
CHUNK = int(os.environ.get("BENCH_CHUNK", "50"))
# the literal-translation baseline pays the host round trip EVERY step by
# design (that's the arm's whole point), so its eager-loop iteration count
# must stay decoupled from CHUNK: at the measured 3.4 s/step, CHUNK=50
# iterations would alone blow the 240 s phase budget
BASELINE_REPS = int(os.environ.get("BENCH_BASELINE_REPS", "8"))
# per-tier MFU floors for the flagship (published as ``mfu_target`` in the
# phase record, the summary, and GATE_BASELINE.json so scripts/gate.py can
# gate the mfu metric against an EXPLICIT target instead of only
# run-over-run drift). Anchored on recorded chip runs of the "full" preset
# (artifacts/BENCH_R4_RUN2.json mfu=0.0072, BENCH_MIDROUND.json 0.0047 —
# the spread is tunnel variance): 0.005 sits at the observed midpoint, and
# the "small" tier's shallow ResNet-18 carries proportionally less MXU
# work per byte. Override per-run with BENCH_MFU_TARGET.
MFU_TARGETS = {"small": 0.002, "full": 0.005}
# absolute ceiling for the data-plane span share at the flagship tier: the
# loader must cost under 5% of the overlapped step loop (ISSUE PR 12
# acceptance). gate.py reads the recorded value as a lower-is-better
# metric AND this target as an absolute bound, mirroring mfu_target.
DATA_LOAD_SHARE_TARGET = 0.05
# absolute floor for the paged KV cache's concurrency win at equal HBM:
# the block pool must admit >= 2x the requests a dense slot cache holds
# in the same device bytes (PR 19 acceptance). gate.py reads the recorded
# kv_capacity_ratio as higher-is-better AND this target as an absolute
# bound, mirroring data_load_share_target.
KV_CAPACITY_RATIO_TARGET = 2.0
# absolute ceiling for the offline cost model's predicted-vs-realized step
# time error (observe.costmodel; ISSUE PR 13 acceptance): the planner's
# predictions must stay within 25% of measured on executed configs.
# gate.py reads the recorded costmodel_error as a lower-is-better metric
# AND this target as an absolute bound, mirroring mfu_target.
COSTMODEL_ERROR_TARGET = 0.25
MARKER = "@BENCH@ "


def _mfu_target(preset: str) -> float:
    env = os.environ.get("BENCH_MFU_TARGET")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    return MFU_TARGETS.get(preset, 0.0)
# global wall budget for the whole orchestration — must undercut the
# driver's own patience (round 3 was killed at rc=124 with nothing printed;
# VERDICT r3 set the bar at <=900 s)
TOTAL_DEADLINE_S = int(os.environ.get("BENCH_TOTAL_DEADLINE_S", "870"))
# per-phase hard deadlines, measured from the previous stdout event. The
# first entry of a child also covers process start + backend init. Cold
# compiles through the TPU tunnel are minutes-slow; these budgets assume
# the persistent cache has at least the flagship entry warm (a mid-round
# run of this same file warms it) and degrade gracefully when not: a blown
# budget skips that one phase, never the round.
PHASE_BUDGET_S = {
    "probe": int(os.environ.get("BENCH_PROBE_BUDGET_S", "300")),
    "flagship": int(os.environ.get("BENCH_FLAGSHIP_BUDGET_S", "330")),
    "baseline": int(os.environ.get("BENCH_BASELINE_BUDGET_S", "240")),
    "gpt": int(os.environ.get("BENCH_GPT_BUDGET_S", "420")),
    "fp32arm": int(os.environ.get("BENCH_FP32ARM_BUDGET_S", "240")),
    "overlap": int(os.environ.get("BENCH_OVERLAP_BUDGET_S", "240")),
    "loader": int(os.environ.get("BENCH_LOADER_BUDGET_S", "150")),
    "serving": int(os.environ.get("BENCH_SERVING_BUDGET_S", "240")),
}
# priority order under the global deadline: the headline pair first, then
# the GPT MFU row (verdict item), then the decomposition arm, then the
# AOT-only overlap evidence, then the loader-isolation arm (host-only —
# cheap, but it must never displace a device measurement), then the
# serving arm (small-model inference — last because the training-path
# numbers are the round's headline)
PHASES = (
    "probe", "flagship", "baseline", "gpt", "fp32arm", "overlap", "loader",
    "serving",
)
# extra wait on a child's FIRST event only: process start + jax import +
# the backend-init watchdog (BENCH_INIT_TIMEOUT_S, default 240 s) all
# precede it. Without this, a respawned child that hangs at init would be
# misclassified as a per-phase timeout (its phase budget expires before
# the child's own init watchdog can report), and the 2-init-failure CPU
# fallback would engage late or never.
INIT_GRACE_S = int(os.environ.get("BENCH_INIT_GRACE_S", "300"))

# Driver-facing JSON lines flow through the observe sinks (the same event
# model the experiments log through). observe is jax-free by design, so the
# parent orchestrator still imports no jax. RawEvent keeps each payload
# verbatim — no "event" wrapper, no timestamp — so the driver's tail parser
# sees byte-identical lines.
from network_distributed_pytorch_tpu.observe import (  # noqa: E402
    RawEvent,
    StreamJsonSink,
    Telemetry,
)

_PARENT_TELEMETRY = Telemetry([StreamJsonSink(sys.stdout)])
_CHILD_TELEMETRY = Telemetry([StreamJsonSink(sys.stdout, prefix=MARKER)])


def _emit(payload: dict) -> None:
    _PARENT_TELEMETRY.emit(RawEvent(payload))


# ---------------------------------------------------------------------------
# child: backend init + measurement phases
# ---------------------------------------------------------------------------


def _child_emit(phase: str, ok: bool, data: dict) -> None:
    _CHILD_TELEMETRY.emit(RawEvent({"phase": phase, "ok": ok, "data": data}))


class _InitTimeout(BaseException):
    """Backend init hang (probe thread still blocked after the deadline).
    BaseException-derived so generic ``except Exception`` recovery paths
    never swallow it and wait out a SECOND in-process hang: a hang must
    reach the parent as an ``__init__`` failure within the probe budget
    (240 s < 300 s, matching ``backend_preflight``'s no-retry-on-timeout
    default), or the parent would misclassify it as a per-phase timeout
    and the 2-init-failure CPU fallback would never engage."""


def _init_backend():
    """The child's first jax backend touch, guarded by a deadline.

    ``jax.devices()`` against the one-shot TPU tunnel either works quickly,
    fails with a transient UNAVAILABLE, or hangs forever inside the PJRT
    C++ client where no signal handler runs. The probe itself lives in
    ``hostenv.backend_preflight`` (daemon worker thread joined with a
    deadline, bounded-backoff retry on raised errors, NO retry on a hang
    — a hang must reach the parent as an ``__init__`` failure within one
    probe budget, 240 s < 300 s, or the 2-init-failure CPU fallback never
    engages); this wrapper translates its verdict back into the exception
    taxonomy the parent's retry/fallback policy keys on.
    """
    import jax

    from network_distributed_pytorch_tpu import hostenv

    # the environment may pin an accelerator platform by config (the axon
    # sitecustomize sets jax_platforms itself, so the env var alone is not
    # enough); BENCH_PLATFORM=cpu is the CI/smoke + fallback override
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    timeout_s = int(os.environ.get("BENCH_INIT_TIMEOUT_S", "240"))
    verdict = hostenv.backend_preflight(
        timeout_s=timeout_s, attempts=2, backoff_s=1.0,
        force=True, retry_on_timeout=False,
    )
    if verdict["attempts"] > 1:
        print(
            f"# bench: backend init retried ({verdict['attempts']} attempts)",
            file=sys.stderr, flush=True,
        )
    if not verdict["ok"]:
        cause = str(verdict.get("cause") or "backend init failed")
        if cause.startswith("init_timeout"):
            raise _InitTimeout(cause)
        raise RuntimeError(cause)
    # the probe thread already paid backend init in THIS process, so this
    # second call returns the live client instantly
    devices = jax.devices()
    if devices[0].platform == "tpu":
        # persistent compilation cache — TPU only: big-model compiles
        # through the tunnel are minutes-slow, and a warmed cache turns the
        # driver's end-of-round run from cold-compile roulette into a
        # seconds-long replay. Never enabled for XLA:CPU: its AOT entries
        # can carry stricter machine features than runtime detection
        # reports (observed '+prefer-no-scatter … SIGILL' warnings).
        try:
            cache_dir = os.environ.get(
                "BENCH_XLA_CACHE", os.path.join(HERE, ".xla_cache")
            )
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception as e:  # noqa: BLE001
            print(f"# bench: compilation cache unavailable: {e}", file=sys.stderr)
    return devices


def _cache_dir_entries():
    """``(dir, n_entries)`` for the persistent compilation cache, or
    ``(None, 0)`` when no cache is configured (XLA:CPU — ``_init_backend``
    enables the cache on TPU only)."""
    import jax

    d = getattr(jax.config, "jax_compilation_cache_dir", None)
    if not d or not os.path.isdir(d):
        return None, 0
    return d, sum(1 for n in os.listdir(d) if not n.startswith("."))


class _CacheProbe:
    """Persistent-compilation-cache accounting around one phase.

    Construct before the phase's compiles, ``report()`` after: a compile
    served from the cache writes no new entry, so ``new_entries == 0``
    reads as "hit" and ``> 0`` as "miss" (fresh compiles persisted).
    ``disabled`` is the honest CPU answer — the cache is TPU-only
    (see ``_init_backend``), and a smoke run must not publish hit/miss
    fields that look like warm-cache evidence."""

    def __init__(self):
        self.dir, self.before = _cache_dir_entries()

    def report(self) -> dict:
        if self.dir is None:
            return {"status": "disabled"}
        _, after = _cache_dir_entries()
        new = after - self.before
        return {
            "status": "miss" if new > 0 else "hit",
            "new_entries": new,
            "entries_total": after,
        }


def _peak_flops(device) -> float:
    """Peak bf16 FLOP/s for ``device``, or 0.0 when unknown (CPU smoke
    tier). The table lives in ``observe.mfu`` — one provenance for the
    numbers both the bench MFU and the run report's roofline use."""
    from network_distributed_pytorch_tpu.observe.mfu import peak_flops

    return peak_flops(
        getattr(device, "device_kind", "") or "", device.platform
    )


def _small_preset() -> bool:
    """CPU-feasible smoke tier (CI / harness validation) unless on TPU;
    BENCH_PRESET pins either way. The full ResNet-50/batch-256 config takes
    >10 min/step-chunk on CPU — useless as a smoke signal."""
    import jax

    preset_env = os.environ.get("BENCH_PRESET", "").lower()
    return preset_env == "small" or (
        preset_env != "full" and jax.devices()[0].platform != "tpu"
    )


def _make_model(dtype, small: bool):
    from network_distributed_pytorch_tpu.models import resnet18, resnet50

    if small:
        return resnet18(num_classes=10, norm="batch", stem="cifar", width=8, dtype=dtype)
    return resnet50(num_classes=10, norm="batch", stem="imagenet", dtype=dtype)


def _cifar_batch(batch_size: int):
    import jax.numpy as jnp

    from network_distributed_pytorch_tpu.data import synthetic_cifar10

    images, labels = synthetic_cifar10(batch_size, seed=0)
    return (jnp.asarray(images), jnp.asarray(labels))


def _phase_probe() -> dict:
    import jax

    d = jax.devices()[0]
    # runtime attestation: jaxlib pins the compiled XLA the numbers came
    # from — a perf delta across rounds with different jaxlibs is a
    # toolchain change, not a repo regression (gate.py's device-provenance
    # guard reads the platform field; the version rides along for humans)
    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", None)
    except Exception:  # noqa: BLE001 — attestation is best-effort
        jaxlib_version = None
    return {
        "device": getattr(d, "device_kind", d.platform),
        "platform": d.platform,
        "n_devices": jax.device_count(),
        "jaxlib_version": jaxlib_version,
    }


def _median(xs):
    import statistics

    return statistics.median(xs)


def _scanned_cifar_setup(dtype):
    """Build + AOT-compile the CHUNK-scanned CIFAR train step — ONE scaffold
    shared by the flagship (bf16) and fp32 decomposition arms, so the pair
    differs in nothing but dtype and the comparison isolates exactly that.
    Returns ``(scanned, state, chunk_batch, compiled, batch_size, small,
    compile_stats)`` where ``compile_stats`` splits the AOT cost into its
    tracing (``lower_ms``) and XLA-compile (``compile_ms``) components —
    the compile component is what a warm persistent cache replays."""
    import jax
    import jax.numpy as jnp

    from network_distributed_pytorch_tpu.experiments.common import image_classifier_loss
    from network_distributed_pytorch_tpu.parallel import ExactReducer, make_mesh
    from network_distributed_pytorch_tpu.parallel.trainer import make_scanned_train_fn

    small = _small_preset()
    batch_size = 32 if small else 256  # reference global batch — ddp_init.py:49
    model = _make_model(dtype, small)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=True)
    loss_fn = image_classifier_loss(model, has_batch_stats=True)
    scanned = make_scanned_train_fn(
        loss_fn, ExactReducer(), variables["params"], learning_rate=0.001,
        momentum=0.9, algorithm="sgd", mesh=make_mesh(), donate_state=True,
    )
    state = scanned.init_state(
        variables["params"], model_state={"batch_stats": variables["batch_stats"]}
    )
    batch = _cifar_batch(batch_size)
    chunk_batch = (
        jnp.broadcast_to(batch[0][None], (CHUNK,) + batch[0].shape),
        jnp.broadcast_to(batch[1][None], (CHUNK,) + batch[1].shape),
    )
    t0 = time.perf_counter()
    lowered = scanned.fn.lower(state, chunk_batch)
    t1 = time.perf_counter()
    compiled = lowered.compile()
    t2 = time.perf_counter()
    compile_stats = {
        "lower_ms": round(1000.0 * (t1 - t0), 2),
        "compile_ms": round(1000.0 * (t2 - t1), 2),
    }
    return scanned, state, chunk_batch, compiled, batch_size, small, compile_stats


def _default_reps(env_var: str, tpu: str, cpu: str) -> int:
    """Rep count for a timing phase: chip runs get error bars; the CPU
    smoke tier gets the minimum that exercises the path — each CHUNK
    dispatch costs ~35 s there and the wedged-tunnel fallback must fit
    the driver's window."""
    import jax

    default = tpu if jax.devices()[0].platform == "tpu" else cpu
    return max(1, int(os.environ.get(env_var, default)))


def _timed_dispatches(compiled, state, chunk_batch, reps):
    """Warmup + ``reps`` fetch-to-observe timed CHUNK-step dispatches.
    Returns ``(state, times_s, first_execute_s)`` in MEASUREMENT order —
    ``first_execute_s`` is the warmup dispatch timed separately: against an
    AOT executable it contains NO compile (that is ``compile_stats``), only
    first-run costs (program load, donation setup, allocator warmup), so
    publishing it apart from the steady-state reps keeps both honest
    (round-4 verdict weak
    #1: one-shot timings through a contended tunnel showed a 54% spread
    across runs — 22.8k vs 35.0k imgs/sec; every published rate needs
    median + spread, and the published sequence must keep its time order so
    a drift across reps — tunnel warmup, a draining abandoned compile —
    stays visible; callers sort a local copy for min/median/max)."""
    from network_distributed_pytorch_tpu.utils.timing import wait_result

    t0 = time.perf_counter()
    state, losses = compiled(state, chunk_batch)  # warmup / first execute
    wait_result(losses)
    first_execute_s = time.perf_counter() - t0
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        state, losses = compiled(state, chunk_batch)
        wait_result(losses)  # fetch-to-observe-completion, utils.timing
        times.append(time.perf_counter() - t0)
    return state, times, first_execute_s


def _flops_band(ratio: float, chunk: int):
    """Classify the FLOPs cross-check ratio ``flops_chunk / flops_1`` as
    ``"trip"`` (trip-multiplied, ratio ~chunk), ``"once"`` (count-once,
    ratio ~1), or ``None`` (matches neither — caller withholds MFU).

    The original two ±2x windows — [chunk/2, 2*chunk] and [0.5, 2] —
    OVERLAP once chunk <= 4 (at chunk=2, ratio 1.5 sits in both, and the
    trip-multiplied branch won by ``if`` ordering, silently dividing a
    count-once flops figure by chunk). Inside the overlap the nearer band
    center in log space decides; outside it the windows are disjoint and
    the behavior is unchanged (identical to the old code for chunk >= 8).
    At chunk == 1 the bands coincide and the tie resolves to ``"trip"`` —
    harmless, since dividing by 1 equals counting once."""
    if ratio <= 0 or chunk < 1:
        return None
    in_trip = 0.5 * chunk <= ratio <= 2.0 * chunk
    in_once = 0.5 <= ratio <= 2.0
    if in_trip and in_once:
        return (
            "trip"
            if abs(math.log(ratio / chunk)) <= abs(math.log(ratio))
            else "once"
        )
    if in_trip:
        return "trip"
    if in_once:
        return "once"
    return None


def _phase_flagship() -> dict:
    """bf16 MXU compute + scanned epoch runner, AOT-compiled so the MFU
    numerator is the cost analysis of the EXACT executable timed."""
    import jax
    import jax.numpy as jnp

    t_phase0 = time.perf_counter()
    scanned, state, chunk_batch, compiled, batch_size, small, compile_stats = (
        _scanned_cifar_setup(jnp.bfloat16)
    )
    flops_chunk = 0.0
    try:
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        flops_chunk = float(ca.get("flops", 0.0))
    except Exception:  # cost analysis is best-effort; MFU just goes unreported
        pass
    reps = _default_reps("BENCH_FLAGSHIP_REPS", "5", "2")
    state, times, first_exec = _timed_dispatches(compiled, state, chunk_batch, reps)
    ranked = sorted(times)
    dt = _median(times)
    out = {
        "preset": "small" if small else "full",
        # the one-time costs, split: AOT trace + XLA compile (what the
        # persistent cache can replay) vs the first executable dispatch
        # (program load / donation setup — never cacheable). The old record
        # lumped all three into an invisible warmup.
        "lower_ms": compile_stats["lower_ms"],
        "compile_ms": compile_stats["compile_ms"],
        "first_execute_ms": round(1000.0 * first_exec, 2),
        "flagship_imgs_per_sec": round(batch_size * CHUNK / dt, 2),
        "step_time_ms": round(1000.0 * dt / CHUNK, 4),
        "flagship_reps": reps,
        # min dispatch time -> max rate and vice versa
        "flagship_imgs_per_sec_max": round(batch_size * CHUNK / ranked[0], 2),
        "flagship_imgs_per_sec_min": round(batch_size * CHUNK / ranked[-1], 2),
        # measurement order, NOT sorted: a monotone drift across reps (the
        # tunnel warming up, an abandoned compile draining) must stay
        # visible in the published sequence
        "dispatch_times_ms": [round(1000.0 * t, 2) for t in times],
    }
    # published floor for this tier, emitted even when mfu itself is
    # withheld (CPU smoke / failed cross-check) — the target is policy,
    # not measurement, and gate.py needs it either way
    out["mfu_target"] = _mfu_target(out["preset"])
    # flops_chunk ÷ CHUNK is only valid where the compiler's cost analysis
    # multiplies the scan body by its trip count. The TPU toolchain does
    # (measured: chip runs report flops_per_step = 10.39 GF for this
    # program at CHUNK=10 — exactly one step's conv work, so flops_chunk
    # was 10×); XLA:CPU counts the body ONCE regardless of trip count
    # (measured: identical flops at chunk 1/2/8). peak>0 restricts the
    # emission to TPU, where the division is right — the CPU smoke tier
    # must not publish a flops number known to be wrong by ~CHUNK×.
    peak = _peak_flops(jax.devices()[0])
    if flops_chunk > 0 and peak > 0:
        # advisor r4: don't trust the trip-count-multiplied semantic as a
        # toolchain invariant — cross-check against a chunk-1 lowering of
        # the SAME program each run (compile-only; cached after the first
        # run). Ratio ~CHUNK confirms multiplied semantics; ~1 means the
        # toolchain switched to count-once (then flops_chunk IS one step);
        # anything else withholds MFU rather than publishing a number
        # known to be wrong by up to CHUNK x.
        per_step = None
        # the cross-check costs one extra (cacheable) compile AFTER the
        # timing is already measured — it must never cost the phase its
        # headline number. Bound it by the REAL budget this phase has left
        # (same clock as child_main: static budget minus 45, capped by the
        # global deadline), run the compile in a daemon thread, and on
        # timeout abandon it into _ABANDONED_THREADS (the child drains
        # those before exit — an abandoned remote compile must never die
        # with the process, that's the tunnel-wedge failure mode) and
        # publish with the historically-validated division instead.
        elapsed = time.perf_counter() - t_phase0
        budget_left = PHASE_BUDGET_S.get("flagship", 330) - 45.0 - elapsed
        deadline_unix = float(os.environ.get("BENCH_DEADLINE_UNIX", "0"))
        if deadline_unix:
            budget_left = min(budget_left, deadline_unix - time.time() - 45.0)
        xcheck_s = min(
            budget_left - 20.0,
            float(os.environ.get("BENCH_CROSSCHECK_SOFT_S", "150")),
        )
        if xcheck_s < 20.0:
            per_step = flops_chunk / CHUNK
            out["flops_method"] = (
                "hlo scan-trip-multiplied (cross-check skipped: "
                f"{int(max(0, budget_left))}s of phase budget left)"
            )
            out["mfu"] = round(per_step / (dt / CHUNK) / peak, 4)
            out["flops_per_step"] = per_step
            return out
        try:
            one_batch = (
                chunk_batch[0][:1],
                chunk_batch[1][:1],
            )
            xbox: dict = {}

            def _xcheck():
                try:
                    ca1 = scanned.fn.lower(state, one_batch).compile()
                    xbox["ca"] = ca1.cost_analysis()
                except BaseException as e:  # noqa: BLE001 — relayed
                    xbox["error"] = e

            xt = threading.Thread(
                target=_xcheck, daemon=True, name="flagship-crosscheck"
            )
            xt.start()
            xt.join(xcheck_s)
            if xt.is_alive():
                _ABANDONED_THREADS["flagship_crosscheck"] = xt
                raise TimeoutError(f"chunk-1 compile exceeded {int(xcheck_s)}s")
            if "error" in xbox:
                raise xbox["error"]
            ca1 = xbox["ca"]
            ca1 = ca1[0] if isinstance(ca1, (list, tuple)) else ca1
            flops_1 = float(ca1.get("flops", 0.0))
            if flops_1 <= 0:
                # the chunk-1 analysis returned no flops — the cross-check
                # is UNAVAILABLE, not a mismatch (same best-effort caveat
                # as the except path below)
                raise ValueError("chunk-1 cost analysis returned no flops")
            ratio = flops_chunk / flops_1
            out["flops_chunk_ratio"] = round(ratio, 2)
            band = _flops_band(ratio, CHUNK)
            if band == "trip":
                per_step = flops_chunk / CHUNK
                out["flops_method"] = "hlo scan-trip-multiplied (chunk-1 cross-checked)"
            elif band == "once":
                per_step = flops_chunk
                out["flops_method"] = "hlo count-once (chunk-1 cross-checked)"
        except Exception as e:  # noqa: BLE001 — cross-check is best-effort;
            # an uncross-checked number keeps the historically-validated
            # division but says so
            per_step = flops_chunk / CHUNK
            out["flops_method"] = (
                "hlo scan-trip-multiplied (cross-check unavailable: "
                f"{type(e).__name__}: {e})"[:160]
            )
        if per_step is not None:
            out["mfu"] = round(per_step / (dt / CHUNK) / peak, 4)
            out["flops_per_step"] = per_step
        else:
            out["mfu_withheld"] = (
                f"flops_chunk/flops_1 ratio {out.get('flops_chunk_ratio')} "
                f"matches neither ~{CHUNK} (trip-multiplied) nor ~1 (count-once)"
            )
    return out


def _phase_baseline() -> dict:
    """The literal-translation arm: fp32, one host dispatch per step."""
    import jax
    import jax.numpy as jnp

    from network_distributed_pytorch_tpu.experiments.common import image_classifier_loss
    from network_distributed_pytorch_tpu.parallel import ExactReducer, make_mesh
    from network_distributed_pytorch_tpu.parallel.trainer import make_train_step
    from network_distributed_pytorch_tpu.utils.timing import wait_result

    small = _small_preset()
    batch_size = 32 if small else 256
    model = _make_model(jnp.float32, small)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=True)
    loss_fn = image_classifier_loss(model, has_batch_stats=True)
    step = make_train_step(
        loss_fn, ExactReducer(), variables["params"], learning_rate=0.001,
        momentum=0.9, algorithm="sgd", mesh=make_mesh(), donate_state=True,
    )
    state = step.init_state(
        variables["params"], model_state={"batch_stats": variables["batch_stats"]}
    )
    batch = _cifar_batch(batch_size)
    t0 = time.perf_counter()
    state, loss = step(state, batch)  # compile + warmup
    wait_result(loss)
    # jit path: trace, compile, and first execute are ONE opaque call —
    # unlike the AOT arms there is no seam to time them apart, so the
    # field says so instead of pretending to be a pure compile time
    first_call_ms = round(1000.0 * (time.perf_counter() - t0), 2)
    # three independent timed passes (round-4 verdict weak #5: vs_baseline
    # rested on a single unreplicated pair; with two passes the median IS
    # an endpoint, so three is the floor at which median and spread are
    # distinct); each pass pays the host round trip every step by design —
    # that is this arm's whole point
    passes = max(1, int(os.environ.get("BENCH_BASELINE_PASSES", "3")))
    rates = []
    for _ in range(passes):
        t0 = time.perf_counter()
        for _ in range(BASELINE_REPS):
            state, loss = step(state, batch)
        wait_result(loss)  # fetch-to-observe-completion, utils.timing
        rates.append(batch_size * BASELINE_REPS / (time.perf_counter() - t0))
    med = _median(rates)
    return {
        "baseline_imgs_per_sec": round(med, 2),
        "baseline_first_call_ms": first_call_ms,
        "baseline_first_call_note": "jit compile + first execute, unsplittable",
        "baseline_step_time_ms": round(1000.0 * batch_size / med, 4),
        # spread endpoints ride the record like the flagship's — the
        # vs_baseline ratio's denominator needs error bars too
        "baseline_imgs_per_sec_min": round(min(rates), 2),
        "baseline_imgs_per_sec_max": round(max(rates), 2),
        "baseline_passes": [round(r, 2) for r in sorted(rates)],
    }


def _phase_fp32arm() -> dict:
    """fp32 + scanned dispatch: the decomposition arm (round-4 verdict weak
    #5). The flagship/baseline pair differs in BOTH dtype (bf16 vs fp32) and
    dispatch structure (one scanned CHUNK-step dispatch vs one host dispatch
    per step); this arm holds the scanned dispatch fixed and swaps only the
    dtype, so  fp32arm/baseline  isolates dispatch amortization and
    flagship/fp32arm  isolates bf16-on-MXU. Identical protocol to the
    flagship by construction (``_scanned_cifar_setup``/``_timed_dispatches``
    are the same code)."""
    import jax.numpy as jnp

    _, state, chunk_batch, compiled, batch_size, small, compile_stats = (
        _scanned_cifar_setup(jnp.float32)
    )
    reps = _default_reps("BENCH_FP32ARM_REPS", "3", "1")
    state, times, first_exec = _timed_dispatches(compiled, state, chunk_batch, reps)
    ranked = sorted(times)
    dt = _median(times)
    return {
        # same tier-labeling contract as the flagship: a small-preset rate
        # must never be readable as the full ResNet-50/batch-256 number
        "preset": "small" if small else "full",
        # same one-time-cost split as the flagship's
        "fp32_lower_ms": compile_stats["lower_ms"],
        "fp32_compile_ms": compile_stats["compile_ms"],
        "fp32_first_execute_ms": round(1000.0 * first_exec, 2),
        "fp32_scanned_imgs_per_sec": round(batch_size * CHUNK / dt, 2),
        "fp32_scanned_step_time_ms": round(1000.0 * dt / CHUNK, 4),
        "fp32_scanned_reps": reps,
        "fp32_scanned_imgs_per_sec_max": round(batch_size * CHUNK / ranked[0], 2),
        "fp32_scanned_imgs_per_sec_min": round(batch_size * CHUNK / ranked[-1], 2),
        # measurement order — same contract as the flagship's
        # dispatch_times_ms
        "fp32_dispatch_times_ms": [round(1000.0 * t, 2) for t in times],
    }


def _phase_gpt() -> dict:
    """GPT-2-small (124M) training-step throughput + MFU — the compute-dense
    workload where MFU is meaningful (CIFAR's 32×32 convs genuinely bound
    MXU utilization, so the flagship CIFAR MFU reads low by construction).
    Full shape on TPU: seq 1024, vocab 50257, bf16 — measured by the SAME
    scaffold ``scripts/tpu_evidence.py`` uses (``utils.benchmarks``: AOT
    executable, cost analysis of the exact program timed, fetch-to-observe
    timing). The decoder stack runs scanned (``GPTConfig.scan_layers``):
    bit-identical math, ~5.6x smaller lowered HLO — the unrolled 124M step
    never finished compiling over the remote-compile link (>855 s abandoned
    mid-round r4; 300 s timeout r3), the scanned one must."""
    import jax

    from network_distributed_pytorch_tpu.utils.benchmarks import time_gpt_train_step

    small = _small_preset()
    gpt = time_gpt_train_step(
        small=small,
        seq_len=64 if small else 1024,
        batch=8,
        vocab=128 if small else 50257,
        scan_layers=True,
        reps=2 if small else 10,
    )
    # flops_per_step (and its flops_method label) stay on the record even
    # when MFU can't be derived — _peak_flops knows only TPU device kinds,
    # so the CPU smoke tier reports flops without an mfu field
    flops = gpt.get("flops_per_step")
    peak = _peak_flops(jax.devices()[0])
    if flops and peak > 0:
        gpt["mfu"] = round(flops / (gpt["step_time_ms"] / 1000.0) / peak, 4)
    return {"gpt": gpt}


def _phase_overlap() -> dict:
    """Comm/compute schedule evidence for the PowerSGD step, from the
    scheduled v5e executable (SURVEY §5 set 'assert via profile' as the bar
    for replacing the reference's async-handle overlap,
    ``reducer.py:131-168``). Two findings from the post-optimization HLO,
    persisted as ``OVERLAP.json``: (a) async ``*-start``/``*-done``
    collective windows and the compute scheduled inside them
    (``utils.overlap``); (b) what the all-reduce combiner did to the 4
    logical collectives (P, rank-1, Q, loss) — on v5e it MERGES the rank-1
    payload into the Q all-reduce, i.e. the separate collective the
    reference could only *hide* is eliminated outright. Claim discipline
    (VERDICT r3 #6): ``combiner_merged`` is the measured claim;
    ``n_async_collectives`` is reported as observed and has been 0 — we do
    NOT claim collectives overlap compute. Unless already on a ≥2-chip
    mesh, the step is compiled against an 8-chip v5e topology AOT — the
    schedule IS the evidence, no execution needed.

    A third finding (Round-6): the SAME workload compiled with
    ``comm_chunks=4`` — per-chunk collectives, their async windows or
    textual interleaving with compute fusions, and the byte-exact
    reconciliation of the per-chunk ledger against the compiled HLO —
    lands under the ``chunked`` key of ``OVERLAP.json``."""
    import jax
    import jax.numpy as jnp

    from network_distributed_pytorch_tpu.experiments.common import image_classifier_loss
    from network_distributed_pytorch_tpu.parallel import PowerSGDReducer, make_mesh
    from network_distributed_pytorch_tpu.parallel.trainer import make_train_step
    from network_distributed_pytorch_tpu.utils.hlo_audit import (
        collective_summary,
        hlo_text_of_compiled,
    )
    from network_distributed_pytorch_tpu.utils.overlap import overlap_report

    small = _small_preset()
    mesh = make_mesh()
    target_mesh = mesh
    topology_note = "attached TPU devices"
    if mesh.size < 2 or jax.devices()[0].platform != "tpu":
        from jax.experimental import topologies

        topo = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x4")
        target_mesh = make_mesh(devices=topo.devices)
        topology_note = "AOT v5e:2x4 topology (no execution)"

    model = _make_model(jnp.bfloat16, small)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=True)
    loss_fn = image_classifier_loss(model, has_batch_stats=True)
    step = make_train_step(
        loss_fn,
        PowerSGDReducer(random_seed=714, compression_rank=4, matricize="last"),
        variables["params"], learning_rate=0.001, momentum=0.9,
        algorithm="ef_momentum", mesh=target_mesh, donate_state=False,
    )
    state_abs = jax.eval_shape(
        lambda p, bs: step.init_state(p, model_state={"batch_stats": bs}),
        variables["params"], variables["batch_stats"],
    )
    batch_abs = (
        jax.ShapeDtypeStruct((8 * target_mesh.size, 32, 32, 3), jnp.float32),
        jax.ShapeDtypeStruct((8 * target_mesh.size,), jnp.int32),
    )
    # ask for ASYNC collectives + the latency-hiding scheduler so any
    # *-start/*-done windows the compiler is willing to open appear in the
    # scheduled HLO; option sets are tried most-specific first, and an
    # executable with no async windows still yields the combiner evidence
    lowered = step.fn.lower(state_abs, batch_abs)
    compiled_exe, flags_used, opts_used, last_opt_err = None, None, None, None
    for opts in (
        {
            "xla_tpu_enable_latency_hiding_scheduler": "true",
            "xla_tpu_enable_async_collective_fusion": "true",
            "xla_tpu_enable_async_collective_fusion_fuse_all_reduce": "true",
        },
        {"xla_tpu_enable_latency_hiding_scheduler": "true"},
        None,
    ):
        try:
            compiled_exe = (
                lowered.compile(compiler_options=opts) if opts else lowered.compile()
            )
            flags_used = sorted(opts) if opts else []
            opts_used = opts
            break
        except Exception as opt_err:  # noqa: BLE001 — try the next set
            last_opt_err = opt_err
    if compiled_exe is None:
        raise last_opt_err

    hlo = hlo_text_of_compiled(compiled_exe)
    rep = overlap_report(hlo)
    rep["compiler_flags"] = flags_used
    aud = collective_summary(hlo)
    rep["compiled_collectives"] = {
        "count": aud["count"],
        "by_kind": aud["by_kind"],
        "ops": [
            {
                "kind": o.kind,
                "dtype": o.dtype,
                "shapes": [list(s) for s in o.shape],
                "payload_bytes": o.payload_bytes,
            }
            for o in aud["ops"]
        ],
    }
    # P, rank-1, Q, loss — reducer.py:126-147 + the loss pmean
    rep["logical_collectives"] = 4
    rep["combiner_merged"] = aud["count"] < 4
    rep["workload"] = "powersgd_r4_" + ("resnet18" if small else "resnet50")
    rep["compiled_for"] = topology_note
    # Round-6 chunked-pipeline evidence (DESIGN.md): the SAME workload with
    # comm_chunks=4 — the schedule must show either async windows with
    # compute inside them or the chunk collectives textually interleaved
    # with compute fusions, and the per-chunk ledger must reconcile
    # byte-exactly against the compiled HLO. Best-effort: a failure here
    # must not cost the phase its monolithic evidence.
    try:
        chunks = max(2, int(os.environ.get("BENCH_COMM_CHUNKS", "4")))
        cstep = make_train_step(
            loss_fn,
            PowerSGDReducer(
                random_seed=714, compression_rank=4, matricize="last",
                comm_chunks=chunks,
            ),
            variables["params"], learning_rate=0.001, momentum=0.9,
            algorithm="ef_momentum", mesh=target_mesh, donate_state=False,
        )
        clowered = cstep.fn.lower(state_abs, batch_abs)
        cexe = (
            clowered.compile(compiler_options=opts_used)
            if opts_used else clowered.compile()
        )
        chlo = hlo_text_of_compiled(cexe)
        crep = overlap_report(chlo)
        rec = cstep.ledger.reconcile(chlo)
        rep["chunked"] = {
            "comm_chunks": chunks,
            "ledger_collectives": sum(e.count for e in cstep.ledger.entries),
            "ledger_bytes": cstep.ledger.total_bytes(),
            "hlo_collectives": rec["hlo_collective_count"],
            "hlo_bytes": rec["hlo_bytes"],
            "ledger_exact": rec["exact"],
            "n_async_collectives": crep["n_async_collectives"],
            "n_overlapped": crep["n_overlapped"],
            "collectives": crep["collectives"],
            "n_sync_collectives": crep["n_sync_collectives"],
            "n_sync_gaps_with_compute": crep["n_sync_gaps_with_compute"],
            "sync_interleaved": crep["sync_interleaved"],
            "sync_collectives": crep["sync_collectives"],
            "collective_emitters": crep["collective_emitters"],
        }
    except Exception as e:  # noqa: BLE001 — chunked evidence is additive
        rep["chunked"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    # an AOT-topology schedule is attached-device-independent — say so
    # rather than stamping whatever chip happened to be attached
    rep["device"] = (
        "AOT (schedule is attached-device-independent)"
        if target_mesh is not mesh
        else _phase_probe()["device"]
    )
    # only a real-chip run owns OVERLAP.json — a CPU smoke run must not
    # clobber the committed TPU artifact (it once did)
    name = "OVERLAP.json" if jax.devices()[0].platform == "tpu" else "OVERLAP_smoke.json"
    with open(os.path.join(HERE, name), "w") as f:
        json.dump(rep, f, indent=1)
    summary = {
        "n_async_collectives": rep["n_async_collectives"],
        "n_overlapped": rep["n_overlapped"],
        "compiled_collectives": aud["count"],
        "combiner_merged": rep["combiner_merged"],
    }
    if "error" not in rep["chunked"]:
        summary["chunked"] = {
            k: rep["chunked"][k]
            for k in (
                "comm_chunks", "hlo_collectives", "ledger_exact",
                "n_overlapped", "n_sync_gaps_with_compute", "sync_interleaved",
            )
        }
    else:
        summary["chunked"] = rep["chunked"]
    return {"overlap": summary}


def _phase_loader() -> dict:
    """Loader-isolation arm: host-side batch assembly throughput with the
    training step taken out of the loop, so a data-plane regression can't
    hide behind (or be blamed on) compute. Three numbers:

    - ``loader_python_samples_per_s``: the literal per-batch numpy
      assemble (gather + u8→f32 normalize), the pre-native hot path.
    - ``loader_samples_per_s``: ``NativeBatchLoader`` on the same dataset,
      order, and batch size — the fused multithreaded C++ pipeline
      (acceptance: ≥ 2× the Python arm where the native lib builds;
      falls back to the Python number, labeled, where it can't).
    - ``data_load_share``: fraction of a short overlapped train loop
      (double-buffered ``device_prefetch`` + a jitted reduction step)
      spent BLOCKED on data — the metric the flagship tier gates below
      5%. Measured here on a synthetic step, so it bounds the loader's
      own overhead, not any one model's arithmetic intensity."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from network_distributed_pytorch_tpu.data import device_prefetch
    from network_distributed_pytorch_tpu.native import NativeBatchLoader
    from network_distributed_pytorch_tpu.native.build import native_available

    small = _small_preset()
    n = 4096 if small else 16384
    batch = 64 if small else 256
    rng = np.random.RandomState(0)
    x = rng.randint(0, 256, size=(n, 32, 32, 3)).astype(np.uint8)
    y = rng.randint(0, 10, size=(n,)).astype(np.int32)
    loader = NativeBatchLoader(x, y, batch, seed=0)
    order = loader._order(0)

    def python_pass() -> int:
        cnt = 0
        for start in range(0, len(order), batch):
            sel = order[start : start + batch]
            _bx = ((x[sel].astype(np.float32) / 255.0) - 0.5) / 0.5
            _by = y[sel]
            cnt += len(sel)
        return cnt

    python_pass()  # warm caches so both arms measure steady state
    t0 = time.perf_counter()
    n_py = python_pass()
    py_rate = n_py / (time.perf_counter() - t0)

    out = {
        "loader_python_samples_per_s": round(py_rate, 1),
        "loader_native": bool(native_available()),
        "loader_dataset_n": n,
        "loader_batch": batch,
    }
    if out["loader_native"]:
        for _ in loader.epoch(0):  # warmup pass (thread spawn, faults)
            pass
        t0 = time.perf_counter()
        cnt = 0
        for bx, _by in loader.epoch(0):
            cnt += len(bx)
        native_rate = cnt / (time.perf_counter() - t0)
        out["loader_samples_per_s"] = round(native_rate, 1)
        out["loader_native_speedup"] = round(native_rate / py_rate, 2)
        out["loader_consumer_wait_s"] = round(
            loader.last_stats["consumer_wait_s"], 4
        )
    else:
        # the gate metric still exists on the fallback tier — it compares
        # like-for-like against a fallback-tier baseline (same contract as
        # the CPU smoke flagship)
        out["loader_samples_per_s"] = round(py_rate, 1)

    # the overlapped loop's step must carry REAL arithmetic — against a
    # trivial reduction nothing can hide and every loop reads ~100%
    # data-bound; two dense layers give the prefetcher a flagship-like
    # compute window to stage under
    feat = int(np.prod(x.shape[1:]))
    w1 = jnp.asarray(rng.randn(feat, 512).astype(np.float32) * 0.01)
    w2 = jnp.asarray(rng.randn(512, feat).astype(np.float32) * 0.01)

    @jax.jit
    def step(a, b, w1, w2):
        h = jnp.tanh(a.reshape(a.shape[0], -1) @ w1)
        return jnp.sum((h @ w2) ** 2) + jnp.sum(b)

    it = device_prefetch(loader.epoch(1), depth=2, label="bench_loader")
    wait_s = 0.0
    t_loop = time.perf_counter()
    steps = 0
    while True:
        t1 = time.perf_counter()
        try:
            bx, by = next(it)
        except StopIteration:
            break
        wait_s += time.perf_counter() - t1
        step(bx, by, w1, w2).block_until_ready()
        steps += 1
    total = time.perf_counter() - t_loop
    if steps and total > 0:
        out["data_load_share"] = round(wait_s / total, 4)
        out["data_load_share_target"] = DATA_LOAD_SHARE_TARGET
    return out


def _phase_serving() -> dict:
    """Paged-KV serving arm (PR 19): dense slot cache vs block-pool paged
    cache on the SAME model, workload, and KV device bytes. Three claims,
    each measured here rather than asserted:

    - ``kv_capacity_ratio``: peak concurrently-admitted requests, paged
      over dense, at equal KV HBM (the paged pool is sized to the dense
      cache's bytes plus one permanent garbage block). Requests are much
      shorter than ``max_len``, so the dense engine pins a full
      ``max_len`` row per request while the pool hands out only the
      blocks each request can actually reach — the acceptance bound is
      >= 2x (``KV_CAPACITY_RATIO_TARGET``).
    - ``serving_tokens_per_s_per_chip`` / ``p99_decode_ms_per_token``:
      throughput and tail latency of the PAGED arm — the engine the gate
      protects from here on.
    - ``serving_paged_bitwise_equal``: per-request token streams from the
      paged arm compared bit-for-bit against the dense arm's (the
      guarantee class that makes the capacity win free).

    A speculative arm (self-drafting target, ``spec_k=4``) rides along:
    same bitwise check, plus accept rate and target decode steps — on
    real hardware fewer target dispatches per token is the win; the
    accept accounting is what this tier can verify.
    """
    import jax

    from network_distributed_pytorch_tpu.models.gpt import gpt_tiny
    from network_distributed_pytorch_tpu.serving import (
        WorkloadConfig,
        poisson_workload,
        replay,
        slo_summary,
    )
    from network_distributed_pytorch_tpu.serving.engine import (
        PagedEngine,
        SlotEngine,
    )

    small = _small_preset()
    n_requests = 32 if small else 64
    dense_slots = 4
    max_len, block_len = 64, 8
    # budget <= 16 tokens/request -> <= 2 blocks of 8, against a dense
    # engine pinning all 64 positions per admission: the capacity gap the
    # ratio measures. rate_rps is effectively "all queued at t=0" so both
    # engines run at their admission ceiling, not the arrival rate's.
    workload = WorkloadConfig(
        n_requests=n_requests,
        rate_rps=2000.0,
        prompt_len=(4, 8),
        max_new_tokens=(2, 8),
        vocab=64,
        seed=0,
    )
    model = gpt_tiny(vocab_size=64, max_position_embeddings=max_len)
    params = model.init(
        jax.random.PRNGKey(0), jnp_zeros_tokens(max_len)
    )["params"]

    def arm(make_engine):
        eng = make_engine()
        t0 = time.perf_counter()
        finished = replay(eng, poisson_workload(workload), max_wall_s=120.0)
        wall = time.perf_counter() - t0
        tokens = {r.request_id: list(r.tokens) for r in finished}
        return eng, slo_summary(finished), tokens, wall

    dense, dense_slo, dense_tokens, dense_wall = arm(
        lambda: SlotEngine(
            model.config, params, n_slots=dense_slots, max_len=max_len
        )
    )
    # equal-HBM paged arm: pool = the dense cache's block-equivalents
    # (+ garbage block 0); n_slots raised so the BLOCK POOL is the
    # admission limit being measured, not the table count. Prefix sharing
    # off — random prompts never share, and a pinned index entry would
    # muddy the capacity count.
    n_blocks = dense_slots * (max_len // block_len) + 1
    paged, paged_slo, paged_tokens, paged_wall = arm(
        lambda: PagedEngine(
            model.config, params, n_slots=4 * dense_slots, max_len=max_len,
            block_len=block_len, n_blocks=n_blocks, prefix_sharing=False,
        )
    )
    spec, spec_slo, spec_tokens, spec_wall = arm(
        lambda: PagedEngine(
            model.config, params, n_slots=4 * dense_slots, max_len=max_len,
            block_len=block_len, n_blocks=n_blocks, prefix_sharing=False,
            draft_config=model.config, draft_params=params, spec_k=4,
        )
    )

    n_chips = 1  # single-device engines; the per-chip label is the contract
    ratio = (
        paged.peak_active / dense.peak_active if dense.peak_active else 0.0
    )
    total_tokens = sum(len(t) for t in paged_tokens.values())
    out = {
        "serving_requests": n_requests,
        "serving_dense_slots": dense_slots,
        "serving_block_len": block_len,
        "serving_n_blocks": n_blocks,
        # the equal-HBM attestation: pool bytes over dense cache bytes
        # (slightly > 1.0 — the garbage block is the only extra)
        "serving_hbm_parity": round(paged.pool_bytes / dense.cache_bytes, 4),
        "serving_dense_peak_active": dense.peak_active,
        "serving_paged_peak_active": paged.peak_active,
        "kv_capacity_ratio": round(ratio, 2),
        "kv_capacity_ratio_target": KV_CAPACITY_RATIO_TARGET,
        "serving_paged_bitwise_equal": paged_tokens == dense_tokens,
        "serving_spec_bitwise_equal": spec_tokens == dense_tokens,
        "serving_tokens_per_s_per_chip": round(
            total_tokens / paged_wall / n_chips, 2
        ),
        "serving_dense_tokens_per_s_per_chip": round(
            sum(len(t) for t in dense_tokens.values()) / dense_wall / n_chips,
            2,
        ),
        "p99_decode_ms_per_token": round(
            paged_slo["p99_decode_ms_per_token"], 3
        ),
        "serving_dense_p99_decode_ms_per_token": round(
            dense_slo["p99_decode_ms_per_token"], 3
        ),
        # speculative arm: accept accounting + the dispatch win (target
        # decode steps per generated token, lower is better — CPU wall
        # clock is draft-dominated at this model size, so the STEP ratio
        # is the portable evidence)
        "serving_spec_accept_rate": round(
            spec.stats().get("spec_accept_rate", 0.0), 4
        ),
        "serving_spec_decode_steps": spec.decode_steps,
        "serving_paged_decode_steps": paged.decode_steps,
        "serving_spec_p99_decode_ms_per_token": round(
            spec_slo["p99_decode_ms_per_token"], 3
        ),
        "serving_spec_wall_s": round(spec_wall, 3),
    }
    if not out["serving_paged_bitwise_equal"]:
        raise RuntimeError("paged serving arm diverged bitwise from dense")
    if not out["serving_spec_bitwise_equal"]:
        raise RuntimeError("speculative serving arm diverged bitwise from dense")
    return out


def jnp_zeros_tokens(max_len: int):
    """Tiny helper so _phase_serving's jax import stays phase-local."""
    import jax.numpy as jnp

    return jnp.zeros((1, max_len), jnp.int32)


_PHASE_FNS = {
    "probe": _phase_probe,
    "flagship": _phase_flagship,
    "baseline": _phase_baseline,
    "gpt": _phase_gpt,
    "fp32arm": _phase_fp32arm,
    "overlap": _phase_overlap,
    "loader": _phase_loader,
    "serving": _phase_serving,
}


class _PhaseAbandoned(TimeoutError):
    """A phase blew its child-side deadline; its daemon thread may still be
    draining on the device (relevant to later phases' timing honesty)."""


# threads of abandoned phases, by phase name — the child must try to DRAIN
# these before exiting: daemon threads die with the process, and dying
# inside an in-flight remote compile wedges the tunnel's remote side the
# same way a SIGKILL does (observed: the 03:37 run abandoned the GPT
# compile, finished its remaining phases, exited — and backend init hung
# for 8+ hours afterwards)
_ABANDONED_THREADS: dict = {}


def _run_with_deadline(name: str, fn, deadline_s: float) -> dict:
    """Run one phase in a daemon thread; on deadline, raise instead of
    letting the parent SIGKILL the child mid-compile.

    The distinction matters beyond this process: a SIGKILLed client wedges
    the one-shot TPU tunnel's remote side (observed: a kill inside the GPT
    compile left backend init hanging for over an hour afterwards), and the
    respawned child then re-pays — or fails — the wedge-prone init. A
    child-side timeout instead reports the phase as an error marker and
    keeps the SAME process (and its already-initialized backend) for the
    remaining phases. The abandoned thread stays alive as a daemon; jax
    dispatch is thread-safe, so the next phase can proceed while it drains.

    The parent's per-event budget remains the backstop for true C-level
    hangs that stall this thread's join return.
    """
    box: dict = {}

    def worker():
        try:
            box["out"] = fn()
        except BaseException as e:  # noqa: BLE001 — relayed to main thread
            box["error"] = e

    t = threading.Thread(target=worker, daemon=True, name=f"phase-{name}")
    t.start()
    t.join(deadline_s)
    if t.is_alive():
        _ABANDONED_THREADS[name] = t
        raise _PhaseAbandoned(
            f"phase {name} exceeded its child-side deadline of"
            f" {int(deadline_s)}s (abandoned, child continues)"
        )
    if "error" in box:
        e = box["error"]
        raise e if isinstance(e, Exception) else RuntimeError(repr(e))
    return box["out"]


def child_main(phase_list: list) -> int:
    try:
        _init_backend()
    except BaseException as e:  # noqa: BLE001 — parent owns retry policy
        _child_emit("__init__", False, {
            "error": f"{type(e).__name__}: {e}"[:400],
            # the preflight verdict's cause string, free of exception-type
            # prefix noise — the parent records it as init_timeout_cause
            "cause": str(e)[:400],
        })
        return 1
    # the parent's ABSOLUTE deadline (unix seconds): the child must finish —
    # or abandon — each phase before the parent's own budget math
    # (min(phase budget, global remaining)) would SIGKILL it mid-compile,
    # which wedges the tunnel. Static phase budgets alone are not enough:
    # near the end of the global window the parent's cap is the SMALLER
    # `left() - 15`, so the child's deadline must track the same clock.
    deadline_unix = float(os.environ.get("BENCH_DEADLINE_UNIX", "0")) or None
    for name in phase_list:
        try:
            budget = float(PHASE_BUDGET_S.get(name, 240)) - 45.0
            if deadline_unix is not None:
                budget = min(budget, deadline_unix - time.time() - 30.0)
            # under 30 s of real budget: skip rather than floor. A floor
            # (an earlier revision used max(30, budget)) can push the
            # child's self-deadline PAST the parent's `left() - 15` kill
            # time, re-introducing the SIGKILL-mid-compile tunnel wedge
            # the self-deadline exists to prevent. Applies to the probe
            # too: it runs unwrapped (near-instant after init), but not
            # when the global window is already spent.
            if budget <= (0 if name == "probe" else 30.0):
                raise TimeoutError(
                    f"phase {name} skipped: under 30s of budget left "
                    "(global deadline near, or a static BENCH_*_BUDGET_S "
                    "under 75s)"
                )
            # an earlier abandoned thread — a whole phase's, or an intra-
            # phase one like the flagship FLOPs cross-check compile — may
            # still be compiling/executing on the device while THIS phase
            # runs: its timed numbers shared the chip with that drain; say
            # so. _ABANDONED_THREADS (filtered to alive at phase START) is
            # the one registry both kinds land in; the liveness filter
            # keeps threads that finished draining before this phase — and
            # a phase's own late-abandoned helper, which never overlapped
            # its timing — off the label.
            live = sorted(
                n for n, t in _ABANDONED_THREADS.items() if t.is_alive()
            )
            if name == "probe":
                data = _PHASE_FNS[name]()
            else:
                # persistent-compilation-cache accounting brackets the
                # phase: zero new entries after its compiles = served from
                # cache ("hit"); CPU reports "disabled" (TPU-only cache)
                cache = _CacheProbe()
                data = _run_with_deadline(name, _PHASE_FNS[name], budget)
                data["compilation_cache"] = cache.report()
            if live:
                data["concurrent_abandoned"] = live
            _child_emit(name, True, data)
        except Exception as e:  # noqa: BLE001 — a phase crash must not
            # take down the phases behind it
            _child_emit(name, False, {"error": f"{type(e).__name__}: {e}"[:400]})
    if _ABANDONED_THREADS:
        # drain abandoned compiles before exiting: daemon threads die with
        # the process, and dying inside an in-flight remote compile wedges
        # the tunnel exactly like a SIGKILL (see _ABANDONED_THREADS). Spend
        # whatever remains of the global window on the join; report what
        # drained so the parent's line records the residual wedge risk.
        grace_until = (
            deadline_unix - 10.0
            if deadline_unix is not None
            else time.time() + float(os.environ.get("BENCH_DRAIN_GRACE_S", "120"))
        )
        drained, still_alive = [], []
        for name, t in _ABANDONED_THREADS.items():
            t.join(max(0.0, grace_until - time.time()))
            (still_alive if t.is_alive() else drained).append(name)
        _child_emit(
            "__drain__", True, {"drained": drained, "still_alive": still_alive}
        )
    return 0


# ---------------------------------------------------------------------------
# parent: orchestration
# ---------------------------------------------------------------------------


def _artifact_pointers(out: dict) -> None:
    """Compact pointers to the round's committed hardware/accuracy evidence
    so the bench line names the fuller record even when the end-of-round
    tunnel is wedged and every TPU phase fails."""
    try:
        with open(os.path.join(HERE, "artifacts", "TPU_EVIDENCE.json")) as f:
            ev = json.load(f)
        out["tpu_evidence"] = {
            "device": ev.get("device"),
            "recorded_unix": ev.get("recorded_unix"),
            "phases_ok": sorted(
                k for k, v in ev.get("phases", {}).items() if v.get("ok")
            ),
        }
    except Exception:  # noqa: BLE001 — pointer only
        pass
    try:
        with open(os.path.join(HERE, "artifacts", "ACCURACY_STUDY.json")) as f:
            st = json.load(f)
        out["accuracy_study"] = {
            t: {
                "accuracy_delta_pts": st[t].get("accuracy_delta_pts"),
                "gradient_bytes_ratio": st[t].get("gradient_bytes_ratio"),
            }
            for t in ("cifar", "imdb", "imdb_wide")
            if t in st
        }
    except Exception:  # noqa: BLE001 — pointer only
        pass
    try:
        with open(os.path.join(HERE, "artifacts", "BENCH_MIDROUND.json")) as f:
            mid = json.load(f)
        # require a PLAIN-ok flagship status: a line whose flagship was
        # re-run on the CPU-fallback tier (status "ok [cpu-smoke-fallback]")
        # must never be republished as a chip measurement
        if (
            mid.get("platform") == "tpu"
            and mid.get("flagship_imgs_per_sec")
            and mid.get("phases", {}).get("flagship") == "ok"
        ):
            keys = [
                "device", "recorded_unix", "flagship_imgs_per_sec", "mfu",
                "flagship_imgs_per_sec_min", "flagship_imgs_per_sec_max",
                "flagship_reps",
            ]
            if mid.get("phases", {}).get("baseline") == "ok":
                # baseline-derived fields only when THAT phase was also
                # plain-ok TPU — a fallback-tier baseline must not be
                # re-exported under the chip label either
                keys += [
                    "baseline_imgs_per_sec", "baseline_imgs_per_sec_min",
                    "baseline_imgs_per_sec_max", "baseline_passes",
                    "vs_baseline",
                ]
            if mid.get("phases", {}).get("fp32arm") == "ok":
                keys += ["fp32_scanned_imgs_per_sec"]
            rec = {k: mid.get(k) for k in keys if mid.get(k) is not None}
            if mid.get("phases", {}).get("gpt") == "ok" and isinstance(
                mid.get("gpt"), dict
            ):
                # re-export WITH the model/shape label: an unlabeled toy-
                # tier MFU under this key would read as the 124M chip MFU
                g = mid["gpt"]
                rec["gpt"] = {
                    k: g.get(k)
                    for k in ("model", "seq_len", "mfu", "tokens_per_sec")
                    if g.get(k) is not None
                }
            out["midround_chip_bench"] = rec
    except Exception:  # noqa: BLE001 — pointer only
        pass


class _ChildProc:
    """One measurement child with a line-streaming stdout reader."""

    def __init__(self, phases: list):
        import queue

        self.queue = queue.Queue()
        env = dict(os.environ)
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--phases", ",".join(phases)],
            stdout=subprocess.PIPE, stderr=None, env=env, text=True,
            cwd=HERE,
        )
        self._reader = threading.Thread(target=self._read, daemon=True)
        self._reader.start()

    def _read(self):
        for line in self.proc.stdout:
            if line.startswith(MARKER):
                try:
                    self.queue.put(json.loads(line[len(MARKER):]))
                except ValueError:
                    pass
        self.queue.put(None)  # EOF

    def next_event(self, timeout_s: float):
        """The next phase result, None on EOF, or raises queue.Empty."""
        return self.queue.get(timeout=max(0.1, timeout_s))

    def kill(self):
        try:
            self.proc.kill()
            self.proc.wait(timeout=10)
        except Exception:  # noqa: BLE001 — already gone
            pass


def _merge(
    out: dict, phase: str, ok: bool, data: dict, status: dict,
    tier: str = "",
) -> None:
    if not ok:
        status[phase] = "error: " + str(data.get("error", "?"))[:200]
        return
    # a phase re-run on the CPU-fallback tier AFTER earlier phases landed on
    # TPU must not read as a TPU measurement: the tier rides its status row
    # (the "device" field on the line reflects only the probe's backend)
    status[phase] = "ok" + (f" [{tier}]" if tier else "")
    if phase == "probe":
        out["device"] = data["device"]
        out["platform"] = data["platform"]
        out["n_devices"] = data["n_devices"]
        out["jaxlib_version"] = data.get("jaxlib_version")
    else:
        out.update(data)
    flag = out.get("flagship_imgs_per_sec")
    base = out.get("baseline_imgs_per_sec")
    if flag:
        out["value"] = flag
        if phase == "flagship" and tier:
            # the headline value came from a degraded tier: say so at top
            # level, not only in the nested status row — consumers that
            # read just {value, device} must not see a CPU number under a
            # TPU device label
            out["value_tier"] = tier
    # the headline ratio only makes sense when both arms ran on the SAME
    # tier: a TPU flagship over a CPU-fallback baseline (or vice versa)
    # would fabricate a cross-device speedup
    if flag and base and status.get("flagship") == status.get("baseline"):
        out["vs_baseline"] = round(flag / base, 3)


def _await_child_exit(child, out: dict, left) -> None:
    """After every phase has reported, wait (within the global window) for
    the child to drain abandoned compiles and exit by itself, recording its
    ``__drain__`` report if one arrives. See the caller's comment: killing
    a child mid-remote-compile is the tunnel-wedge failure mode."""
    import queue

    while True:
        budget = min(left() - 10.0, 300.0)
        if budget <= 0:
            return  # window truly spent — the backstop kill may fire
        try:
            ev = child.next_event(budget)
        except queue.Empty:  # a POLL timeout, not the window: keep waiting
            # until left() runs out (returning here would kill mid-drain
            # with window remaining — the wedge)
            continue
        except Exception:  # noqa: BLE001 — advisor r4: a persistent
            # non-Empty error (broken queue after reader-thread death)
            # means the child is effectively gone; looping on it would
            # burn the whole remaining window before the backstop kill
            return
        if ev is None:  # child exited cleanly
            return
        if ev.get("phase") == "__drain__":
            out["abandoned_drain"] = ev.get("data")
            _emit(out)


# serialized byte budget for the final summary line. The driver reads a
# fixed-size tail of stdout (~2,000 chars); 1,200 leaves headroom for the
# newline plus a partially-truncated previous line sharing the tail.
_SUMMARY_LIMIT = 1200
# headline keys in keep-priority order — when the serialized summary
# overflows _SUMMARY_LIMIT, keys drop from the BOTTOM of this list first
_SUMMARY_PRIORITY = (
    "metric", "value", "unit", "vs_baseline", "device", "platform",
    "n_devices", "jaxlib_version", "preset", "wall_s", "partial",
    "value_tier",
    "flagship_imgs_per_sec", "flagship_imgs_per_sec_min",
    "flagship_imgs_per_sec_max", "baseline_imgs_per_sec",
    "baseline_imgs_per_sec_min", "baseline_imgs_per_sec_max", "mfu",
    "mfu_target", "fp32_scanned_imgs_per_sec", "tpu_error", "init_retries",
    "init_timeout_cause", "orchestrator_error", "flops_chunk_ratio",
)


def _compact_summary(out: dict, status: dict) -> dict:
    """A bounded digest of the cumulative record, emitted as the round's
    VERY LAST stdout line: the driver parses a fixed-size tail, and the
    full record can outgrow it (per-dispatch time lists, artifact pointers,
    400-char error strings) — then the tail's only complete line would be
    truncated garbage. Serialized size is guaranteed <= _SUMMARY_LIMIT:
    every string is clipped, and whole keys drop in reverse priority order
    until the line fits."""

    def _clip(v):
        return v[:120] if isinstance(v, str) else v

    summary = {"summary": True}
    for k in _SUMMARY_PRIORITY:
        if out.get(k) is not None:
            summary[k] = _clip(out[k])
    # per-phase status strings, clipped hard: error statuses carry up to
    # 200 chars each and six phases of those would eat half the budget
    summary["phases"] = {k: _clip(str(v))[:60] for k, v in status.items()}
    gpt = out.get("gpt")
    if isinstance(gpt, dict):
        summary["gpt"] = {
            k: _clip(gpt[k])
            for k in ("model", "seq_len", "mfu", "tokens_per_sec")
            if gpt.get(k) is not None
        }
    while len(json.dumps(summary)) > _SUMMARY_LIMIT and len(summary) > 1:
        summary.pop(next(reversed(summary)))
    return summary


def orchestrate() -> int:
    t_start = time.time()
    # advisor r4: a statically configured BENCH_*_BUDGET_S below 75 s means
    # the child-side skip rule (budget - 45 <= 30) suppresses that phase on
    # EVERY run — surface the misconfiguration instead of letting it read
    # as a mysterious per-run timeout
    for _name, _b in PHASE_BUDGET_S.items():
        # child-side skip: budget-45 must EXCEED 30, so 75 itself skips
        if _name != "probe" and _b <= 75:
            print(
                f"# bench: WARNING: {_name} budget {_b}s <= 75s implies a "
                "permanent skip (child-side rule: budget-45 must exceed "
                "30s); raise BENCH_" + _name.upper() + "_BUDGET_S",
                file=sys.stderr, flush=True,
            )
    # children self-deadline against the SAME absolute clock the parent
    # kills by, so near the end of the window the child still reports (and
    # survives) before the parent's `left() - 15` cap would SIGKILL it
    # mid-compile — the tunnel-wedging outcome (_run_with_deadline)
    os.environ["BENCH_DEADLINE_UNIX"] = str(t_start + TOTAL_DEADLINE_S)

    def left() -> float:
        return TOTAL_DEADLINE_S - (time.time() - t_start)

    out = {
        "metric": "cifar10_resnet50_train_imgs_per_sec",
        "value": 0.0,
        "unit": "imgs/sec",
        "vs_baseline": 0.0,
        "partial": True,
    }
    _artifact_pointers(out)
    _emit(out)  # a valid line exists before the first backend touch

    status = {}
    out["phases"] = status
    pending = list(PHASES)
    init_failures = 0
    cpu_fallback = bool(os.environ.get("BENCH_PLATFORM"))  # pinned = no fallback
    fallback_engaged = False  # flipped only when we DEGRADE mid-run — a
    # deliberately pinned platform (BENCH_PLATFORM=cpu smoke) is not tagged
    crashed = None  # orchestrator-level exception, re-raised AFTER the
    # bounded summary line lands (satellite: a phase raising must never
    # leave the round's stdout tail without a valid standalone summary)
    try:
        while pending and left() > 45:
            child = _ChildProc(pending)
            child_events = 0
            gave_up = False  # parent-side timeout: the child is WEDGED — the
            # kill backstop must fire immediately, not after a drain wait
            window_spent = False  # global window ran out with phases pending:
            # the child may be mid-drain; give it the last few seconds
            try:
                while pending:
                    budget = min(
                        PHASE_BUDGET_S.get(pending[0], 240)
                        + (INIT_GRACE_S if child_events == 0 else 0),
                        left() - 15,
                    )
                    if budget <= 0:
                        window_spent = True
                        break
                    try:
                        ev = child.next_event(budget)
                    except Exception:  # queue.Empty — child wedged (compile hang)
                        status[pending[0]] = f"timeout after {int(budget)}s"
                        pending.pop(0)
                        gave_up = True
                        break
                    if ev is None:  # child exited
                        if child_events == 0:
                            # died before ANY marker line — a native crash
                            # inside backend init (segfault/OOM in the PJRT
                            # client emits no Python exception, so the child
                            # can't report __init__ itself). Count it as an
                            # init failure so the CPU fallback policy engages
                            # instead of burning one phase per crash.
                            init_failures += 1
                            if init_failures < 2:
                                out["init_retries"] = (
                                    out.get("init_retries", 0) + 1
                                )
                            out.setdefault(
                                "tpu_error", "child process died during backend init"
                            )
                        elif pending:
                            status.setdefault(pending[0], "child exited early")
                            pending.pop(0)
                        break
                    child_events += 1
                    if ev["phase"] == "__init__":
                        err = str(ev["data"].get("error", "?"))[:300]
                        # an init HANG (_InitTimeout after the 240 s watchdog)
                        # used to be decisive; pool-side evidence since shows
                        # roughly half the hangs were transient tunnel
                        # contention that a fresh probe clears. One retry is
                        # cheap against the window when it works and costs one
                        # 240 s probe when it doesn't, so hangs now share the
                        # two-strike budget with transient errors
                        # (UNAVAILABLE etc.) — every init failure gets exactly
                        # one more attempt before the CPU fallback verdict.
                        init_failures += 1
                        if init_failures < 2:
                            # another probe will follow (the while loop
                            # respawns for the still-pending phases) — make
                            # the retry visible in the published record
                            out["init_retries"] = out.get("init_retries", 0) + 1
                        out["tpu_error"] = err
                        # the preflight verdict's cause (hostenv
                        # .backend_preflight) rides into the bounded summary
                        # so the driver can tell a wedged runtime
                        # ("init_timeout: ...") from a missing one
                        # ("RuntimeError: ... UNAVAILABLE") without the logs
                        out["init_timeout_cause"] = str(
                            ev["data"].get("cause") or err
                        )[:200]
                        break
                    if ev["phase"] == "__drain__":
                        # the child's end-of-run report on abandoned-compile
                        # drains — informational, not a measurement phase
                        out["abandoned_drain"] = ev["data"]
                        _emit(out)
                        continue
                    init_failures = 0
                    if ev["phase"] in pending:
                        pending.remove(ev["phase"])
                    _merge(
                        out, ev["phase"], ev["ok"], ev["data"], status,
                        tier="cpu-smoke-fallback" if fallback_engaged else "",
                    )
                    _emit(out)
            finally:
                if (not pending and not gave_up) or window_spent:
                    # normal completion (or window exhaustion with the child
                    # possibly mid-drain): let the child drain + exit on its
                    # own. Killing it while an abandoned phase's daemon thread
                    # is mid-remote-compile wedges the tunnel for HOURS (the
                    # 03:37 run's GPT compile did exactly that) — the kill
                    # below must only ever be a no-op or a backstop. On
                    # window exhaustion _await_child_exit self-bounds to the
                    # last ~left()-10 seconds.
                    _await_child_exit(child, out, left)
                child.kill()
            if init_failures >= 2 and not cpu_fallback:
                if os.environ.get("BENCH_NO_CPU_FALLBACK") == "1":
                    break
                # TPU init budget spent — one decisive hang, or two transient
                # failures: degrade to the CPU smoke tier, clearly labeled;
                # the TPU error stays on the line
                print(
                    "# bench: TPU init failure budget exhausted (two strikes; "
                    "every failure, hangs included, got one retry); falling "
                    "back to CPU smoke tier",
                    file=sys.stderr, flush=True,
                )
                os.environ["BENCH_PLATFORM"] = "cpu"
                os.environ.pop("PALLAS_AXON_POOL_IPS", None)
                cpu_fallback = True
                fallback_engaged = True
                init_failures = 0  # the CPU tier gets its own failure budget —
                # otherwise one early CPU hiccup would hit `>= 2` and abort
                pending = [
                    p for p in PHASES if not str(status.get(p, "")).startswith("ok")
                ]
            elif init_failures >= 2:
                break
    except BaseException as exc:  # noqa: B036 — even SystemExit must
        # not skip the summary emission; re-raised below
        crashed = exc
    reason = "skipped: out of budget" if crashed is None else (
        "skipped: orchestrator error"
    )
    for p in pending:
        status.setdefault(p, reason)
    out["partial"] = crashed is not None
    if crashed is not None:
        out["orchestrator_error"] = (
            f"{type(crashed).__name__}: {crashed}"[:300]
        )
    out["wall_s"] = round(time.time() - t_start, 1)
    if crashed is None:  # a crashed round has nothing worth gating
        _run_perf_gate(out, status)
    _persist_midround(out, status)
    _record_gate_baseline(out, status)
    _emit(out)
    # the full record above stays the authoritative line; the bounded
    # summary AFTER it is what a fixed-size tail is guaranteed to hold
    # — and it must land even on a crash: round 5's driver record ended
    # in a front-truncated full record and "parsed": null because the
    # exception path skipped this line entirely
    _emit(_compact_summary(out, status))
    if crashed is not None:
        raise crashed
    return 0


def _persist_midround(out: dict, status: dict) -> None:
    """A fully-successful TPU run self-persists as the midround artifact —
    the chip record every LATER bench line points at (_artifact_pointers),
    so a wedged end-of-round tunnel can't erase the round's measurement.
    Round 4's artifact was hand-assembled from stdout; this closes that
    manual step. The bar mirrors the pointer's republication gate: TPU
    platform, FULL preset (a small-preset chip smoke must not overwrite
    the flagship record), plain-ok flagship; baseline may have failed —
    the pointer already withholds baseline-derived fields in that case —
    but a flagship-only record never overwrites an existing record that
    has BOTH arms plain-ok (no downgrading richer evidence)."""
    if (
        out.get("platform") != "tpu"
        or out.get("preset") != "full"
        or status.get("flagship") != "ok"
        or not out.get("flagship_imgs_per_sec")
    ):
        return
    path = os.path.join(HERE, "artifacts", "BENCH_MIDROUND.json")
    if status.get("baseline") != "ok":
        try:
            with open(path) as f:
                prev = json.load(f)
            if (
                prev.get("phases", {}).get("flagship") == "ok"
                and prev.get("phases", {}).get("baseline") == "ok"
            ):
                return  # keep the two-arm record over a flagship-only one
        except (OSError, ValueError):
            pass  # nothing readable to preserve — persist what we have
    rec = dict(out)
    rec.pop("midround_chip_bench", None)  # no self-reference chains
    rec["recorded_unix"] = int(time.time())
    rec["note"] = (
        "Self-persisted by bench.py after a fully-successful TPU run "
        "(plain-ok flagship+baseline); later bench lines carry this as "
        "midround_chip_bench so a wedged tunnel cannot erase it."
    )
    try:
        os.makedirs(os.path.join(HERE, "artifacts"), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f, indent=1)
        os.replace(tmp, path)
    except OSError:  # persistence is best-effort; the line already printed
        pass


def _run_perf_gate(out: dict, status: dict) -> None:
    """Gate the round's freshest run report against the PREVIOUS round's
    recorded baseline, before ``_record_gate_baseline`` overwrites it.

    The chip tier runs ``scripts/gate.py --strict-device`` (ROADMAP item 4
    leftover): a ``device=cpu`` fallback record must FAIL against a chip
    baseline instead of silently satisfying it — cross-hardware ratios are
    not regressions, they are provenance errors. The CPU smoke tier stays
    advisory: shared CI boxes gate like-for-like drift informationally and
    never block on hardware they do not have. The verdict rides the
    published record (``gate`` in phases, ``gate_strict_device`` on the
    line) either way."""
    report_path = os.path.join(HERE, "artifacts", "run_report.json")
    baseline_path = os.path.join(HERE, "artifacts", "GATE_BASELINE.json")
    if not (os.path.exists(report_path) and os.path.exists(baseline_path)):
        status["gate"] = "skipped: no report/baseline pair"
        return
    chip_tier = out.get("platform") == "tpu"
    argv = [
        sys.executable, os.path.join(HERE, "scripts", "gate.py"),
        "--report", report_path, "--root", HERE,
        "--strict-device" if chip_tier else "--advisory",
    ]
    try:
        rc = subprocess.run(argv, timeout=120).returncode
    except (OSError, subprocess.TimeoutExpired) as exc:
        status["gate"] = f"error: {type(exc).__name__}"[:60]
        return
    out["gate_strict_device"] = chip_tier
    status["gate"] = "ok" if rc == 0 else f"regressed (exit {rc})"


def _record_gate_baseline(out: dict, status: dict) -> None:
    """Record the round's headline throughput as the perf-gate baseline
    (artifacts/GATE_BASELINE.json, read by scripts/gate.py). Any round
    with a plain-ok flagship qualifies — unlike the midround artifact the
    gate compares like-for-like on whatever hardware CI runs, so a CPU
    smoke baseline is still a valid regression reference for CPU CI."""
    if status.get("flagship") != "ok" or not out.get("flagship_imgs_per_sec"):
        return
    rec = {
        "schema": 1,
        "source": "bench.py",
        "recorded_unix": int(time.time()),
        # runtime attestation, so gate.py's device-provenance guard (and a
        # human reading the baseline) knows exactly what produced these
        # numbers: a CPU report gating against this on a chip baseline is
        # flagged, not silently compared
        "platform": out.get("platform"),
        "jaxlib_version": out.get("jaxlib_version"),
        "n_devices": out.get("n_devices"),
        "init_retries": int(out.get("init_retries", 0) or 0),
        "preset": out.get("preset"),
        "value_tier": out.get("value_tier"),
        "flagship_imgs_per_sec": out.get("flagship_imgs_per_sec"),
        "value": out.get("value"),
        "vs_baseline": out.get("vs_baseline"),
        "phases": {k: str(v)[:60] for k, v in status.items()},
    }
    # flagship MFU (when the round derived one) rides along so gate.py can
    # compare a run report's mfu_headline like-for-like (ROADMAP item 2:
    # gate on MFU, not just imgs/sec)
    mfu = out.get("mfu")
    if isinstance(mfu, (int, float)) and mfu > 0:
        rec["mfu"] = float(mfu)
    # the tier's published MFU floor rides along unconditionally: gate.py
    # uses it as an ABSOLUTE target for the mfu metric (drift alone can
    # ratchet a slow regression past a relative-only gate)
    mfu_target = out.get("mfu_target")
    if isinstance(mfu_target, (int, float)) and mfu_target > 0:
        rec["mfu_target"] = float(mfu_target)
    # live-plane alert count from the newest probe run report (when one
    # exists): rides along so gate.py's lower-is-better alerts_fired
    # metric has a recorded reference. Zero is the healthy value and is
    # recorded as such — a later round that starts firing MORE alerts than
    # this baseline regresses the health envelope
    try:
        with open(os.path.join(HERE, "artifacts", "run_report.json")) as f:
            doc = json.load(f)
        fired = (doc.get("alerts") or {}).get("fired")
        if isinstance(fired, (int, float)) and fired >= 0:
            rec["alerts_fired"] = float(fired)
        # cross-rank critical-path comm share (observe.critpath) rides
        # along from the same report: zero (compute-bound path) is the
        # healthy value and records as such, so a later round whose steps
        # start gating on collective-wait regresses against it
        share = (doc.get("critpath") or {}).get("comm_share")
        if isinstance(share, (int, float)) and share >= 0:
            rec["critpath_comm_share"] = float(share)
        # peak device memory from the memory observatory: measured when
        # the sampler ran, else the compile-time predicted peak
        # (memory_summary picks and labels the source). Lower-is-better
        # in gate.py — a model/step change that doubles the footprint
        # regresses against this baseline before it OOMs in production
        hbm = (doc.get("memory") or {}).get("hbm_peak_bytes")
        if isinstance(hbm, (int, float)) and hbm > 0:
            rec["hbm_peak_bytes"] = float(hbm)
        # gradient-fidelity scalar (observe.fidelity via report.py): the
        # worst shape-group's mean relative compression error. Zero
        # (exact reducers) is the healthy value and records as such, so
        # a later round whose compressed wire quietly degrades what it
        # delivers regresses against this reference
        fid = (doc.get("fidelity") or {}).get("rel_error")
        if isinstance(fid, (int, float)) and fid >= 0:
            rec["fidelity_rel_error"] = float(fid)
    except (OSError, ValueError):
        pass
    # loader-isolation arm (PR 12): native assembly samples/s is a
    # higher-is-better gate metric, data_load_share a lower-is-better one
    # with an absolute ceiling (DATA_LOAD_SHARE_TARGET), mirroring the
    # mfu/mfu_target pair. Only recorded when the loader phase ran ok —
    # a skipped phase must not erase the previous baseline's fields.
    if str(status.get("loader", "")).startswith("ok"):
        for key in ("loader_samples_per_s", "data_load_share"):
            v = out.get(key)
            if isinstance(v, (int, float)) and v >= 0:
                rec[key] = float(v)
        if "data_load_share" in rec:
            rec["data_load_share_target"] = DATA_LOAD_SHARE_TARGET
    # paged-serving arm (PR 19): throughput and tail latency of the paged
    # engine are relative gate metrics; the capacity ratio also carries its
    # absolute >= 2x floor, same contract as data_load_share's ceiling.
    # Phase-gated like the loader's so a skipped arm keeps the previous
    # baseline's serving fields alive.
    if str(status.get("serving", "")).startswith("ok"):
        for key in (
            "serving_tokens_per_s_per_chip",
            "p99_decode_ms_per_token",
            "kv_capacity_ratio",
        ):
            v = out.get(key)
            if isinstance(v, (int, float)) and v > 0:
                rec[key] = float(v)
        if "kv_capacity_ratio" in rec:
            rec["kv_capacity_ratio_target"] = KV_CAPACITY_RATIO_TARGET
    # disaster-recovery MTTR from the newest game-day report (run_probe
    # phase 5 — the plain probe report has no replans): rides along so
    # gate.py's lower-is-better recovery_time_s metric has a recorded
    # reference for the quorum-replan game day
    try:
        with open(os.path.join(HERE, "artifacts", "gameday_report.json")) as f:
            mttr = json.load(f).get("recovery_time_s")
        if isinstance(mttr, (int, float)) and mttr > 0:
            rec["recovery_time_s"] = float(mttr)
    except (OSError, ValueError):
        pass
    # fleet goodput from the newest multi-job game day (run_probe
    # phase 10): higher-is-better weighted work per chip-second, so a
    # later round whose scheduler burns more chips for the same work —
    # or strands jobs unfinished — regresses against this reference
    try:
        with open(os.path.join(HERE, "artifacts", "fleet_report.json")) as f:
            goodput = json.load(f).get("fleet_goodput")
        if isinstance(goodput, (int, float)) and goodput > 0:
            rec["fleet_goodput"] = float(goodput)
    except (OSError, ValueError):
        pass
    # cost-model observatory (run_probe phase 7): the planner replay
    # reports carry predicted-vs-realized step time; record the WORST
    # fabric's error (the bound the model must hold everywhere) plus the
    # matching ms pair, so gate.py's lower-is-better costmodel_error and
    # its absolute 25% ceiling both have a recorded reference
    worst = None
    for name in sorted(glob.glob(
        os.path.join(HERE, "artifacts", "plan_replay_*_report.json")
    )):
        try:
            with open(name) as f:
                cm = json.load(f).get("costmodel") or {}
        except (OSError, ValueError):
            continue
        err = cm.get("error")
        if isinstance(err, (int, float)) and err >= 0 and (
            worst is None or err > worst.get("error", -1.0)
        ):
            worst = cm
    if worst is not None:
        rec["costmodel_error"] = float(worst["error"])
        rec["costmodel_error_target"] = COSTMODEL_ERROR_TARGET
        for src, dst in (
            ("predicted_step_s", "predicted_step_ms"),
            ("realized_step_s", "realized_step_ms"),
        ):
            v = worst.get(src)
            if isinstance(v, (int, float)) and v > 0:
                rec[dst] = float(v) * 1e3
    path = os.path.join(HERE, "artifacts", "GATE_BASELINE.json")
    try:
        os.makedirs(os.path.join(HERE, "artifacts"), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f, indent=1)
        os.replace(tmp, path)
    except OSError:  # best-effort, like the midround artifact
        pass


def main() -> int:
    if "--phases" in sys.argv:
        phases = sys.argv[sys.argv.index("--phases") + 1].split(",")
        return child_main([p for p in phases if p])
    return orchestrate()


if __name__ == "__main__":
    sys.exit(main())
