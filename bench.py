"""Benchmark — one JSON line for the driver.

Flagship: CIFAR-10 ResNet-50 training (the reference's entry point A/B model
family) on real TPU. Two configurations run back-to-back:

- **baseline emulation**: the reference's configuration translated literally
  — ResNet-50, fp32, exact allreduce-mean, SGD momentum, one host dispatch
  per step (the reference's Python loop,
  ``ddp_guide_cifar10/ddp_init.py:108-125``).
- **flagship**: the same workload the TPU-first way — bfloat16 compute on
  the MXU and the ``lax.scan`` epoch runner (whole step chunks compiled into
  ONE dispatch, ``make_scanned_train_fn``), donated carries.

On a single chip there is no wire, so gradient-sync flavor is irrelevant to
wall time here; the compressed-vs-exact wire story is measured by the
bandwidth study harness (``experiments/bandwidth_study.py``) and the HLO
collective audit instead. metric = flagship imgs/sec; vs_baseline =
flagship / baseline — how much faster the TPU-native design trains the
reference's own workload than a literal translation of it. The reference
itself publishes no numbers (BASELINE.md).
"""

import json
import os
import time

import jax
import jax.numpy as jnp

CHUNK = int(os.environ.get("BENCH_CHUNK", "10"))  # steps per scanned dispatch


def main():
    from network_distributed_pytorch_tpu.data import synthetic_cifar10
    from network_distributed_pytorch_tpu.experiments.common import image_classifier_loss
    from network_distributed_pytorch_tpu.models import resnet18, resnet50
    from network_distributed_pytorch_tpu.parallel import ExactReducer, make_mesh
    from network_distributed_pytorch_tpu.parallel.trainer import (
        make_scanned_train_fn,
        make_train_step,
    )

    # BENCH_PRESET=small: CPU-feasible smoke tier (CI / harness validation);
    # default is the reference's full config on the real chip.
    small = os.environ.get("BENCH_PRESET") == "small"
    make_model = (
        (lambda dtype: resnet18(num_classes=10, norm="batch", stem="cifar", width=8, dtype=dtype))
        if small
        else (lambda dtype: resnet50(num_classes=10, norm="batch", stem="imagenet", dtype=dtype))
    )
    # reference global batch — ddp_guide_cifar10/ddp_init.py:49
    batch_size = 32 if small else 256
    mesh = make_mesh()
    images, labels = synthetic_cifar10(batch_size, seed=0)
    batch = (jnp.asarray(images), jnp.asarray(labels))

    results = {}

    # --- baseline emulation: fp32, stepwise host loop ---------------------
    model = make_model(jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=True)
    loss_fn = image_classifier_loss(model, has_batch_stats=True)
    step = make_train_step(
        loss_fn, ExactReducer(), variables["params"], learning_rate=0.001,
        momentum=0.9, algorithm="sgd", mesh=mesh, donate_state=True,
    )
    state = step.init_state(
        variables["params"], model_state={"batch_stats": variables["batch_stats"]}
    )
    state, loss = step(state, batch)  # compile + warmup
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(CHUNK):
        state, loss = step(state, batch)
    jax.block_until_ready(loss)
    results["baseline_fp32_stepwise"] = batch_size * CHUNK / (time.perf_counter() - t0)

    # --- flagship: bf16 MXU compute + scanned epoch runner ----------------
    model = make_model(jnp.bfloat16)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=True)
    loss_fn = image_classifier_loss(model, has_batch_stats=True)
    scanned = make_scanned_train_fn(
        loss_fn, ExactReducer(), variables["params"], learning_rate=0.001,
        momentum=0.9, algorithm="sgd", mesh=mesh, donate_state=True,
    )
    state = scanned.init_state(
        variables["params"], model_state={"batch_stats": variables["batch_stats"]}
    )
    chunk_batch = (
        jnp.broadcast_to(batch[0][None], (CHUNK,) + batch[0].shape),
        jnp.broadcast_to(batch[1][None], (CHUNK,) + batch[1].shape),
    )
    state, losses = scanned(state, chunk_batch)  # compile + warmup
    jax.block_until_ready(losses)
    t0 = time.perf_counter()
    state, losses = scanned(state, chunk_batch)
    jax.block_until_ready(losses)
    results["flagship_bf16_scanned"] = batch_size * CHUNK / (time.perf_counter() - t0)

    value = results["flagship_bf16_scanned"]
    vs = value / results["baseline_fp32_stepwise"]
    print(
        json.dumps(
            {
                "metric": "cifar10_resnet50_train_imgs_per_sec",
                "value": round(value, 2),
                "unit": "imgs/sec",
                "vs_baseline": round(vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
