"""network_distributed_pytorch_tpu — a TPU-native (JAX/XLA) rebuild of
`jaeyong-song/network_distributed_pytorch`.

The reference is a bandwidth-study framework for data-parallel training over
slow networks: exact per-parameter allreduce DDP and PowerSGD rank-r
gradient-compressed DDP (error-feedback SGD with momentum), with
bytes-on-wire accounting at every collective.

This package provides the same capabilities, designed TPU-first:

- ``parallel.mesh``     — process-group / rendezvous layer (L1): ``jax.distributed``
  coordination over DCN + a ``jax.sharding.Mesh`` over ICI
  (reference: ``ddp_guide/ddp_init.py:37-45``).
- ``parallel.comm``     — communication primitives (L2): psum/pmean/all_gather
  wrappers with bits-on-wire accounting
  (reference: ``tensor_buffer.py``, ``reducer.py:193-198``).
- ``parallel.packing``  — flat-buffer packing of many tensors into one
  collective payload (reference: ``tensor_buffer.py:4-57``).
- ``parallel.reducers`` — gradient reduction (L3): ``ExactReducer`` and
  ``PowerSGDReducer`` as pure, jit-compatible functions
  (reference: ``reducer.py:43-170``).
- ``parallel.trainer``  — trainer (L4): error-feedback SGD with momentum
  (PowerSGD Algorithm 2) as a single jitted ``shard_map`` step
  (reference: ``ddp_powersgd_guide_cifar10/ddp_init.py:125-181``).
- ``data``              — deterministic cross-rank dataset partitioning and the
  CIFAR-10 / IMDb pipelines (reference: ``partition_helper.py``,
  ``ddp_powersgd_distillBERT_IMDb/ddp_init.py:43-94``).
- ``models``            — first-party flax models: MLP, CNN, ResNet-18/50/152,
  DistilBERT (the reference pulls these from torchvision / HuggingFace).
- ``ops``               — TPU kernels: Gram-Schmidt orthogonalization
  (fori_loop + Pallas variants; reference: ``reducer.py:180-191``).
- ``utils``             — config, metrics (finishing the reference's unfinished
  ``bits_communicated`` reporting), bandwidth model.
- ``experiments``       — the four reference "guides" as library entry points.
"""

__version__ = "0.1.0"
