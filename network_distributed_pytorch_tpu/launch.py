"""L5 — launcher CLI.

Mirrors the reference's per-rank ``run_script.py`` launchers: ``-rank``
(``ddp_guide/run_script.py:27-28``), ``-world_size`` / ``-init_method``
(``ddp_powersgd_distillBERT_IMDb/run_script.py:27-31``), which mutate the
config and call the experiment lifecycle. One launcher serves every
experiment (the reference copies the script four times); the ``cuda_rnak``
typo and hard-coded lab IPs are not reproduced (SURVEY §7).

Usage::

    python -m network_distributed_pytorch_tpu.launch powersgd_cifar10 \
        --process-id 0 --num-processes 1 --preset small --epochs 1
"""

from __future__ import annotations

import argparse
import sys

from .experiments import (
    bandwidth_study,
    bare_init,
    diloco_cifar10,
    exact_cifar10,
    gpt_generate,
    gpt_lm,
    gpt_moe,
    gpt_pp,
    gpt_sp,
    gpt_tp,
    imdb_baseline,
    powersgd_cifar10,
    powersgd_imdb,
    serve_gpt,
)
from .observe import RawEvent, StreamJsonSink, Telemetry
from .parallel.mesh import DistributedConfig, initialize_distributed
from .utils.config import ExperimentConfig

EXPERIMENTS = {
    "bare_init": bare_init.run,
    "exact_cifar10": exact_cifar10.run,
    "diloco_cifar10": diloco_cifar10.run,
    "powersgd_cifar10": powersgd_cifar10.run,
    "powersgd_imdb": powersgd_imdb.run,
    "imdb_baseline": imdb_baseline.run,
    "bandwidth_study": bandwidth_study.run,
    "gpt_lm": gpt_lm.run,
    "gpt_pp": gpt_pp.run,
    "gpt_sp": gpt_sp.run,
    "gpt_tp": gpt_tp.run,
    "gpt_moe": gpt_moe.run,
    "gpt_generate": gpt_generate.run,
    "serve_gpt": serve_gpt.run,
}


def build_parser() -> argparse.ArgumentParser:
    import os

    # mpirun-style launch (the reference documents the same env-var path,
    # ``ddp_guide/run_script.py:8-22``): OMPI_COMM_WORLD_RANK/SIZE become the
    # flag defaults, so `mpirun -np N python -m ...launch exp` just works.
    env_rank = int(os.environ.get("OMPI_COMM_WORLD_RANK", 0))
    env_size = int(os.environ.get("OMPI_COMM_WORLD_SIZE", 1))

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("experiment", choices=sorted(EXPERIMENTS))
    # the reference's -rank / -world_size / -init_method flags
    p.add_argument("--process-id", type=int, default=env_rank, help="rank of this host process")
    p.add_argument("--num-processes", type=int, default=env_size, help="world size (host processes)")
    p.add_argument("--coordinator", type=str, default=None, help="host:port rendezvous")
    p.add_argument("--seed", type=int, default=714)
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--global-batch", type=int, default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--momentum", type=float, default=None)
    p.add_argument("--reducer-rank", type=int, default=None)
    p.add_argument(
        "--accum-steps", type=int, default=None,
        help="gradient-accumulation microbatches per step"
             " (cifar and imdb experiments)",
    )
    p.add_argument(
        "--max-grad-norm", type=float, default=None,
        help="clip the reduced update to this global norm"
             " (cifar/imdb experiments)",
    )
    p.add_argument(
        "--comm-chunks", type=int, default=None,
        help="split each packed reduction payload into K fenced, software-"
             "pipelined collectives (cifar experiments; DESIGN.md Round-6)",
    )
    p.add_argument(
        "--comm-strategy", choices=["interleave", "ring"], default=None,
        help="chunk reduction engine: 'interleave' (per-chunk pmean, bitwise"
             " == monolithic) or 'ring' (explicit ppermute ring schedule,"
             " deterministic but reassociated)",
    )
    p.add_argument(
        "--bucket-bytes", type=int, default=None,
        help="bucketed backward overlap (cifar exact-DDP experiments): pack"
             " gradients into ~B-byte buckets in backward production order,"
             " one fenced collective each, so early buckets' wire time"
             " overlaps the rest of the backward (DESIGN.md: raw speed)",
    )
    p.add_argument(
        "--compress-impl", choices=["xla", "pallas"], default=None,
        help="PowerSGD compress pipeline: 'pallas' runs the fused kernels"
             " (EF add + P=MQ; Gram-Schmidt + Q=M^T P; decompress +"
             " residual — one HBM round-trip each per shape bucket);"
             " interpret mode off-TPU",
    )
    p.add_argument(
        "--orthogonalize-impl", choices=["auto", "xla", "pallas"],
        default=None,
        help="PowerSGD Gram-Schmidt engine ('auto': the Pallas VMEM kernel"
             " on TPU, the XLA fori_loop elsewhere)",
    )
    p.add_argument(
        "--attn-impl", choices=["auto", "einsum", "flash"], default=None,
        help="attention engine override for the transformer experiments"
             " ('auto': flash on TPU, einsum elsewhere; unset = each"
             " model's own default, which is also 'auto')",
    )
    p.add_argument(
        "--remat", action="store_true",
        help="rematerialize transformer blocks in the backward pass"
             " (gpt_lm, powersgd_imdb)",
    )
    p.add_argument(
        "--scan-layers", action="store_true",
        help="gpt_lm only: run decoder blocks as one lax.scan with stacked"
             " params — ~n_layers× smaller HLO and compile time, same math",
    )
    p.add_argument(
        "--health-every", type=int, default=None,
        help="emit a TrainHealthEvent (grad norm, EF memory norm, PowerSGD"
             " relative compression error) every N steps via the separately"
             " jitted health probe — the live plane's NaN-precursor feed"
             " (cifar experiments; 0/unset = never, zero overhead)",
    )
    p.add_argument("--preset", choices=["small", "full"], default="small")
    p.add_argument("--data-dir", type=str, default="./data")
    p.add_argument("--dtype", choices=["float32", "bfloat16"], default="float32")
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--max-steps-per-epoch", type=int, default=None)
    p.add_argument(
        "--strategy", choices=["ddp", "fsdp"], default="ddp",
        help="exact_cifar10 only: replicated DDP or ZeRO-3 fully-sharded",
    )
    p.add_argument(
        "--data-shards", type=int, default=1,
        help="gpt_pp only: compose data parallelism over the pipeline "
             "(mesh ('data','pipe'))",
    )
    p.add_argument(
        "--pp-reducer", choices=["exact", "powersgd"], default="exact",
        help="gpt_pp only: cross-shard gradient reduction when "
             "--data-shards > 1",
    )
    p.add_argument(
        "--model-shards", type=int, default=4,
        help="gpt_tp only: tensor-parallel shards (mesh ('data','model'))",
    )
    p.add_argument(
        "--tp-reducer", choices=["exact", "powersgd"], default="exact",
        help="gpt_tp only: data-axis gradient reduction when devices >"
             " --model-shards",
    )
    p.add_argument(
        "--sync-every", type=int, default=8,
        help="diloco_cifar10 only: local steps per outer sync round",
    )
    p.add_argument(
        "--fragments", type=int, default=1,
        help="diloco_cifar10 only: >1 switches to streaming DiLoCo"
             " (round-robin fragment sync)",
    )
    p.add_argument(
        "--diloco-reducer", choices=["exact", "powersgd"], default="exact",
        help="diloco_cifar10 only: compression of the outer parameter delta",
    )
    p.add_argument(
        "--experts-per-device", type=int, default=1,
        help="gpt_moe only: local experts per device (total = devices x this)",
    )
    p.add_argument(
        "--moe-reducer", choices=["exact", "powersgd"], default="exact",
        help="gpt_moe only: reduction for the replicated (non-expert) params",
    )
    p.add_argument(
        "--moe-top-k", type=int, default=1,
        help="gpt_moe only: experts per token (1=Switch, 2=GShard-style)",
    )
    p.add_argument(
        "--vocab-parallel", action="store_true",
        help="gpt_tp only: shard the tied token table over vocab rows and"
             " compute the CE without materializing full-vocab logits",
    )
    p.add_argument(
        "--checkpoint-dir", type=str, default=None,
        help="gpt_pp/gpt_sp: save the carry per epoch and resume the newest;"
             " exact_cifar10 (ddp): run through resilient_train_loop —"
             " committed per-epoch checkpoints, verified resume, and the"
             " --chaos-plan injection point; serve_gpt: hot-load model"
             " params from the newest committed training checkpoint",
    )
    p.add_argument(
        "--max-new-tokens", type=int, default=64,
        help="gpt_generate: decode length; serve_gpt: per-request decode"
             " budget cap (uniform in [2, this])",
    )
    p.add_argument(
        "--temperature", type=float, default=0.0,
        help="gpt_generate only: 0 = greedy",
    )
    # --- serve_gpt (serving/ continuous-batching engine) ------------------
    p.add_argument(
        "--slots", type=int, default=None,
        help="serve_gpt only: static batch slots of the continuous-batching"
             " engine (default 4)",
    )
    p.add_argument(
        "--requests", type=int, default=None,
        help="serve_gpt only: simulated requests in the Poisson workload"
             " (default 16)",
    )
    p.add_argument(
        "--request-rate", type=float, default=None,
        help="serve_gpt only: Poisson arrival rate in requests/s"
             " (default 64)",
    )
    p.add_argument(
        "--spool-dir", type=str, default=None,
        help="serve_gpt only: shared file-spool request queue — the elastic"
             " fleet mode; combine with --supervise --num-processes N for"
             " mid-decode fail-over (dead ranks' in-flight requests are"
             " re-queued on the survivors)",
    )
    p.add_argument(
        "--engine", type=str, default=None, choices=("slot", "paged"),
        help="serve_gpt only: KV cache engine — 'slot' (dense per-slot"
             " cache) or 'paged' (block-pool cache with copy-on-write"
             " prefix sharing; default slot)",
    )
    p.add_argument(
        "--block-len", type=int, default=None,
        help="serve_gpt only (--engine paged): tokens per KV block"
             " (default 16)",
    )
    p.add_argument(
        "--n-blocks", type=int, default=None,
        help="serve_gpt only (--engine paged): KV pool size in blocks"
             " (default: dense-equivalent bytes, slots * max_len/block_len"
             " + 1)",
    )
    p.add_argument(
        "--no-prefix-sharing", action="store_true",
        help="serve_gpt only (--engine paged): disable copy-on-write"
             " prompt-prefix sharing",
    )
    p.add_argument(
        "--spec-k", type=int, default=None,
        help="serve_gpt only (--engine paged): speculative decoding window"
             " — draft proposes K-1 tokens, target verifies all K in one"
             " batched step (default off)",
    )
    p.add_argument("--json", action="store_true", help="print the summary as JSON")
    p.add_argument(
        "--chaos-plan", type=str, default=None,
        help="JSON fault schedule (resilience.chaos.ChaosPlan) injected into"
             " experiments that run through resilient_train_loop; forwarded"
             " to workers under --supervise",
    )
    p.add_argument(
        "--adaptive-comm", action="store_true",
        help="exact_cifar10 (ddp) only: degraded-fabric survival — collective"
             " deadline watchdogs around every fenced chunk plus the"
             " closed-loop reducer fallback ladder (resilience.controller);"
             " --chaos-plan then drives comm-layer faults in-process, no"
             " checkpoint_dir needed",
    )
    p.add_argument(
        "--comm-fabric", type=str, default=None,
        choices=("1GbE", "10GbE", "100GbE", "ICI(v5e)"),
        help="--adaptive-comm: fabric whose modeled line rate"
             " (utils.bandwidth.FABRICS_BYTES_PER_S) budgets the collective"
             " deadlines (default ICI(v5e)); --plan: the fabric whose"
             " tuned best pick is applied",
    )
    p.add_argument(
        "--plan", type=str, default=None,
        help="tuned per-fabric plan file from scripts/plan.py (the offline"
             " what-if cost model): apply its predicted-best comm knobs for"
             " --comm-fabric (explicit CLI knobs still win), and under"
             " --adaptive-comm reorder the fallback ladder predicted-best-"
             "first (cifar experiments)",
    )
    # --- supervised elastic launch (resilience.supervisor) ---------------
    # these flags configure the PARENT only and are stripped from the
    # worker command lines (_SUPERVISOR_FLAGS below)
    p.add_argument(
        "--supervise", action="store_true",
        help="run as the supervising parent: spawn --num-processes copies of"
             " this command (one per rank), restart crashed/hung ranks with"
             " bounded backoff, degrade to a shrunk world when a rank is"
             " permanently gone",
    )
    p.add_argument(
        "--max-restarts", type=int, default=3,
        help="supervise: restarts per rank before it is declared dead",
    )
    p.add_argument(
        "--restart-backoff", type=float, default=0.25,
        help="supervise: base seconds of the bounded exponential backoff",
    )
    p.add_argument(
        "--heartbeat-dir", type=str, default=None,
        help="supervise: shared heartbeat directory for hang detection"
             " (workers must beat it, e.g. via resilient_train_loop)",
    )
    p.add_argument(
        "--heartbeat-timeout", type=float, default=None,
        help="supervise: seconds without a beat before a rank is killed"
             " and restarted",
    )
    p.add_argument(
        "--term-grace", type=float, default=5.0,
        help="supervise: seconds between SIGTERM and SIGKILL on every"
             " supervisor-initiated kill — the window a worker's"
             " PreemptionGuard has to commit an emergency checkpoint",
    )
    p.add_argument(
        "--min-world-size", type=int, default=1,
        help="supervise: smallest world a degraded restart may shrink to"
             " (the quorum planner's --min-world floor)",
    )
    p.add_argument(
        "--mesh-shape", type=str, default=None,
        help="supervise: the world's mesh shape as DATAxFSDPxTENSOR (e.g."
             " 2x1x2; product must equal --num-processes). Degraded"
             " restarts then go through the quorum planner — trade TP"
             " degree for DP first — instead of only shrinking the data"
             " axis; workers read the shape from RESILIENCE_MESH",
    )
    p.add_argument(
        "--correlation-window", type=float, default=2.0,
        help="supervise: hard deaths of >= 2 distinct ranks within this"
             " many seconds are classified as one correlated incident"
             " (zone outage) and replanned as a whole",
    )
    p.add_argument(
        "--no-degraded", action="store_true",
        help="supervise: declare the run dead instead of shrinking the"
             " world when a rank exhausts its restarts",
    )
    p.add_argument(
        "--worker-log-dir", type=str, default=None,
        help="supervise: per-rank-per-incarnation worker stdout logs",
    )
    p.add_argument(
        "--metrics-port", type=int, default=None,
        help="supervise + --run-dir: serve the live telemetry plane's"
             " Prometheus-text /metrics endpoint on this port (0 ="
             " ephemeral; the bound port is advertised in"
             " <run-dir>/metrics_port). Unset = live plane off",
    )
    p.add_argument(
        "--alert-restart-after", type=int, default=0,
        help="supervise live plane: restart a rank after this many"
             " sustained CRITICAL alerts attributed to it (the NaN-"
             "precursor path; restarts spend the ordinary restart budget;"
             " 0 = log-only)",
    )
    p.add_argument(
        "--event-log", type=str, default=None,
        help="append structured JSONL telemetry (steps, wire ledger, compile"
             " audits) to this path; read it back with scripts/report.py",
    )
    p.add_argument(
        "--run-dir", type=str, default=None,
        help="run-level observability directory: the supervising parent"
             " writes the run manifest (observe.runlog) and its own event"
             " shard there, each worker appends events_rank<R>.jsonl; merge"
             " with scripts/report.py --run-dir (use a FRESH directory per"
             " run)",
    )
    p.add_argument(
        "--trace-dir", type=str, default=None,
        help="capture a jax.profiler trace of the run under this directory",
    )
    p.add_argument(
        "--audit-wire", action="store_true", default=None,
        help="force the compile-time analytic-vs-HLO wire audit (default:"
             " on whenever --event-log is set)",
    )
    return p


def apply_plan(cfg: ExperimentConfig, args) -> None:
    """Apply a scripts/plan.py plan file's predicted-best comm knobs for
    the launch fabric onto ``cfg``. Explicit CLI knobs win over the plan;
    the plan wins over the dataclass defaults. A plan naming a different
    reducer family than the launched experiment only warns — the
    experiment choice stays the user's (under --adaptive-comm the
    reordered fallback ladder can still walk to the compressed rung)."""
    import json

    from .observe import costmodel

    with open(args.plan, "r", encoding="utf-8") as fh:
        plan = json.load(fh)
    fabric = args.comm_fabric or cfg.comm_fabric
    slot = (plan.get("fabrics") or {}).get(fabric)
    if not isinstance(slot, dict):
        sys.stderr.write(
            f"# launch: plan {args.plan} has no fabric {fabric!r};"
            " knobs unchanged\n"
        )
        cfg.plan_path = args.plan
        return
    best = costmodel.canonical_config((slot.get("best") or {}).get("config"))
    if args.comm_chunks is None and best["comm_chunks"]:
        cfg.comm_chunks = best["comm_chunks"]
    if args.comm_strategy is None:
        cfg.comm_strategy = best["comm_strategy"]
    if args.bucket_bytes is None and best["bucket_bytes"]:
        cfg.bucket_bytes = best["bucket_bytes"]
    if args.reducer_rank is None and best["reducer_rank"]:
        cfg.reducer_rank = best["reducer_rank"]
    plan_reducer = best["reducer"]
    exp_reducer = (
        "powersgd" if "powersgd" in args.experiment else "exact"
    )
    if plan_reducer != exp_reducer:
        sys.stderr.write(
            f"# launch: plan's best pick for {fabric} uses the"
            f" {plan_reducer!r} reducer but {args.experiment!r} runs"
            f" {exp_reducer!r} — comm knobs applied, reducer unchanged\n"
        )
    cfg.plan_path = args.plan


def config_from_args(args) -> ExperimentConfig:
    cfg = ExperimentConfig(
        seed=args.seed,
        process_id=args.process_id,
        num_processes=args.num_processes,
        coordinator_address=args.coordinator,
        compute_dtype=args.dtype,
        log_every=args.log_every,
    )
    if args.epochs is not None:
        cfg.training_epochs = args.epochs
    if args.global_batch is not None:
        cfg.global_batch_size = args.global_batch
    if args.lr is not None:
        cfg.learning_rate = args.lr
    if args.momentum is not None:
        cfg.momentum = args.momentum
    if args.reducer_rank is not None:
        cfg.reducer_rank = args.reducer_rank
    if args.accum_steps is not None:
        cfg.accum_steps = args.accum_steps
    if args.max_grad_norm is not None:
        cfg.max_grad_norm = args.max_grad_norm
    if args.comm_chunks is not None:
        cfg.comm_chunks = args.comm_chunks
    if args.comm_strategy is not None:
        cfg.comm_strategy = args.comm_strategy
    if args.bucket_bytes is not None:
        cfg.bucket_bytes = args.bucket_bytes
    if args.compress_impl is not None:
        cfg.compress_impl = args.compress_impl
    if args.orthogonalize_impl is not None:
        cfg.orthogonalize_impl = args.orthogonalize_impl
    if args.attn_impl is not None:
        cfg.attn_impl = args.attn_impl
    cfg.event_log = args.event_log
    cfg.trace_dir = args.trace_dir
    cfg.audit_wire = args.audit_wire
    cfg.chaos_plan = args.chaos_plan
    cfg.adaptive_comm = args.adaptive_comm
    if args.comm_fabric is not None:
        cfg.comm_fabric = args.comm_fabric
    if args.health_every is not None:
        cfg.health_every = args.health_every
    return cfg


# supervisor-parent-only flags, stripped from worker command lines
# (value-taking unless marked boolean)
_SUPERVISOR_FLAGS = {
    "--supervise": False,
    "--max-restarts": True,
    "--restart-backoff": True,
    "--heartbeat-timeout": True,
    "--term-grace": True,
    "--min-world-size": True,
    "--mesh-shape": True,
    "--correlation-window": True,
    "--no-degraded": False,
    "--worker-log-dir": True,
    "--metrics-port": True,
    "--alert-restart-after": True,
    # re-appended per worker with the supervisor's own numbering
    "--process-id": True,
    "--num-processes": True,
}


def parse_mesh_shape(spec: str) -> dict:
    """``DATAxFSDPxTENSOR`` (or the two-axis shorthand ``DATAxTENSOR``)
    into a mesh-axes dict for :class:`SupervisorConfig`."""
    try:
        degrees = [int(p) for p in spec.lower().replace("×", "x").split("x")]
    except ValueError:
        degrees = []
    if len(degrees) == 2:
        data, fsdp, tensor = degrees[0], 1, degrees[1]
    elif len(degrees) == 3:
        data, fsdp, tensor = degrees
    else:
        raise ValueError(
            f"--mesh-shape must look like DATAxFSDPxTENSOR (e.g. 2x1x2) or"
            f" DATAxTENSOR (e.g. 2x2), got {spec!r}"
        )
    return {"data": data, "fsdp": fsdp, "tensor": tensor}


def worker_argv_base(argv) -> list:
    """The launch argv with supervisor-only flags (and any explicit rank/
    world-size) removed — what every worker command line starts from."""
    out, skip = [], False
    for a in argv:
        if skip:
            skip = False
            continue
        flag = a.split("=", 1)[0]
        if flag in _SUPERVISOR_FLAGS:
            skip = _SUPERVISOR_FLAGS[flag] and "=" not in a
            continue
        out.append(a)
    return out


def _supervise(args, argv) -> dict:
    """Run as the supervising parent: every worker is this same CLI with
    ``--process-id``/``--num-processes`` rewritten per (rank, world)."""
    import os

    from .observe import MarkerEvent, telemetry_for_run
    from .observe import runlog as _runlog
    from .resilience.supervisor import Supervisor, SupervisorConfig

    base = worker_argv_base(argv)

    def argv_for_rank(rank: int, world: int, incarnation: int) -> list:
        return [
            sys.executable, "-m", "network_distributed_pytorch_tpu.launch",
            *base, "--process-id", str(rank), "--num-processes", str(world),
        ]

    # with a run dir, the parent's own events land in the conventional
    # supervisor shard so the merged timeline includes the failure domain
    event_log = args.event_log
    if args.run_dir and not event_log:
        event_log = os.path.join(args.run_dir, _runlog.SUPERVISOR_LOG)
    telemetry = telemetry_for_run(event_log=event_log)
    with telemetry:
        supervisor = Supervisor(
            argv_for_rank,
            world_size=args.num_processes,
            config=SupervisorConfig(
                max_restarts=args.max_restarts,
                backoff_base_s=args.restart_backoff,
                heartbeat_dir=args.heartbeat_dir,
                heartbeat_timeout_s=args.heartbeat_timeout,
                term_grace_s=args.term_grace,
                allow_degraded=not args.no_degraded,
                min_world_size=args.min_world_size,
                seed=args.seed,
                metrics_port=args.metrics_port,
                alert_restart_after=args.alert_restart_after,
                mesh_axes=(
                    parse_mesh_shape(args.mesh_shape)
                    if args.mesh_shape else None
                ),
                correlation_window_s=args.correlation_window,
            ),
            telemetry=telemetry,
            log_dir=args.worker_log_dir,
            run_dir=args.run_dir,
        )
        if args.run_dir:
            telemetry.emit(
                MarkerEvent(
                    kind="run_start", run_id=supervisor.run_id or "",
                    world_size=args.num_processes,
                )
            )
        result = supervisor.run()
    summary = {
        "supervised": True,
        "experiment": args.experiment,
        "success": result.success,
        "world_size": result.world_size,
        "total_restarts": result.total_restarts,
        "degraded": result.degraded,
        "reason": result.reason,
        "final_mesh": result.final_mesh,
    }
    if args.run_dir:
        summary["run_dir"] = args.run_dir
        summary["run_id"] = supervisor.run_id
    if args.json:
        Telemetry([StreamJsonSink(sys.stdout)]).emit(RawEvent(summary))
    if not result.success:
        raise SystemExit(3)
    return summary


def main(argv=None) -> dict:
    raw = argv if argv is not None else sys.argv[1:]
    if raw and raw[0] == "fleet":
        # the fleet control plane: gang-schedule spooled job manifests
        # over a fixed chip inventory (resilience.scheduler owns the CLI;
        # jax-free, so intercept BEFORE the experiment parser and its
        # choices= validation)
        from .resilience import scheduler as _scheduler

        return {"fleet_rc": _scheduler.main(raw[1:])}
    args = build_parser().parse_args(argv)
    if args.metrics_port is not None and not (args.supervise and args.run_dir):
        raise ValueError("--metrics-port requires --supervise and --run-dir")
    if args.alert_restart_after and not args.supervise:
        raise ValueError("--alert-restart-after requires --supervise")
    if args.mesh_shape and not args.supervise:
        raise ValueError("--mesh-shape requires --supervise")
    if args.supervise:
        return _supervise(args, argv if argv is not None else sys.argv[1:])
    if args.run_dir:
        # a worker rank of a run-dir launch: derive this rank's event shard,
        # and make sure the run env is present so telemetry_for_run leads
        # the shard with the run_start marker (supervised workers inherit
        # the env from the parent — setdefault keeps the parent's run id)
        import os

        from .observe import runlog as _runlog

        os.environ.setdefault(_runlog.ENV_RUN_DIR, args.run_dir)
        os.environ.setdefault(
            _runlog.ENV_RUN_ID, _runlog.default_run_id(args.run_dir)
        )
        os.environ.setdefault("RESILIENCE_RANK", str(args.process_id))
        os.environ.setdefault("RESILIENCE_WORLD", str(args.num_processes))
        if not args.event_log:
            args.event_log = _runlog.shard_path(args.run_dir, args.process_id)
    cfg = config_from_args(args)
    if args.plan is not None:
        if args.experiment not in ("exact_cifar10", "powersgd_cifar10"):
            raise ValueError(
                f"--plan is not supported by {args.experiment!r}"
                " (supported: exact_cifar10, powersgd_cifar10)"
            )
        apply_plan(cfg, args)

    # reject silently-ignored flags BEFORE any rendezvous: a pure-CLI error
    # must not burn a multi-host allocation on a doomed jax.distributed join
    _ACCUM_OK = ("exact_cifar10", "powersgd_cifar10", "powersgd_imdb", "imdb_baseline")
    _REMAT_OK = ("gpt_lm", "powersgd_imdb")
    if cfg.accum_steps > 1 and args.experiment not in _ACCUM_OK:
        raise ValueError(
            f"--accum-steps is not supported by {args.experiment!r}"
            f" (supported: {', '.join(_ACCUM_OK)})"
        )
    if cfg.max_grad_norm is not None and args.experiment not in _ACCUM_OK:
        raise ValueError(
            f"--max-grad-norm is not supported by {args.experiment!r}"
            f" (supported: {', '.join(_ACCUM_OK)})"
        )
    _CHUNKS_OK = ("exact_cifar10", "powersgd_cifar10")
    if cfg.comm_chunks is not None and args.experiment not in _CHUNKS_OK:
        raise ValueError(
            f"--comm-chunks is not supported by {args.experiment!r}"
            f" (supported: {', '.join(_CHUNKS_OK)})"
        )
    if cfg.comm_strategy != "interleave" and args.experiment not in _CHUNKS_OK:
        raise ValueError(
            f"--comm-strategy is not supported by {args.experiment!r}"
            f" (supported: {', '.join(_CHUNKS_OK)})"
        )
    if cfg.adaptive_comm and args.experiment != "exact_cifar10":
        raise ValueError(
            f"--adaptive-comm is not supported by {args.experiment!r}"
            " (supported: exact_cifar10)"
        )
    if (
        args.comm_fabric is not None
        and not cfg.adaptive_comm
        and args.plan is None
    ):
        raise ValueError("--comm-fabric requires --adaptive-comm or --plan")
    if args.remat and args.experiment not in _REMAT_OK:
        raise ValueError(
            f"--remat is not supported by {args.experiment!r}"
            f" (supported: {', '.join(_REMAT_OK)})"
        )
    if args.scan_layers and args.experiment != "gpt_lm":
        raise ValueError(
            f"--scan-layers is not supported by {args.experiment!r}"
            " (supported: gpt_lm)"
        )
    for flag, val in (
        ("--slots", args.slots), ("--requests", args.requests),
        ("--request-rate", args.request_rate),
        ("--spool-dir", args.spool_dir),
        ("--engine", args.engine), ("--block-len", args.block_len),
        ("--n-blocks", args.n_blocks),
        ("--no-prefix-sharing", args.no_prefix_sharing or None),
        ("--spec-k", args.spec_k),
    ):
        if val is not None and args.experiment != "serve_gpt":
            raise ValueError(
                f"{flag} is not supported by {args.experiment!r}"
                " (supported: serve_gpt)"
            )

    # multi-host rendezvous before any experiment touches devices
    # (the reference's setup() does the same before run_task()).
    # serve_gpt ranks share only the file spool — no collectives, and a
    # rendezvous would couple the fleet's fate to its slowest/dead rank,
    # exactly what the elastic spool exists to avoid
    if args.num_processes > 1 and args.experiment not in (
        "bare_init", "serve_gpt"
    ):
        initialize_distributed(
            DistributedConfig(
                process_id=cfg.process_id,
                num_processes=cfg.num_processes,
                coordinator_address=cfg.coordinator_address,
                timeout_seconds=cfg.timeout_seconds,
            )
        )

    fn = EXPERIMENTS[args.experiment]
    kwargs = {"config": cfg}
    if args.experiment == "diloco_cifar10":
        kwargs.update(preset=args.preset, data_dir=args.data_dir,
                      max_steps_per_epoch=args.max_steps_per_epoch,
                      sync_every=args.sync_every, fragments=args.fragments,
                      reducer=args.diloco_reducer)
        if args.lr is not None:
            # --lr names the INNER rate here (see diloco_cifar10.run)
            kwargs.update(inner_learning_rate=args.lr)
    elif args.experiment in ("exact_cifar10", "powersgd_cifar10"):
        kwargs.update(preset=args.preset, data_dir=args.data_dir,
                      max_steps_per_epoch=args.max_steps_per_epoch)
        if args.experiment == "exact_cifar10":
            kwargs.update(strategy=args.strategy,
                          checkpoint_dir=args.checkpoint_dir)
    elif args.experiment in ("powersgd_imdb", "imdb_baseline"):
        kwargs.update(preset=args.preset,
                      data_dir=None if args.data_dir == "./data" else args.data_dir,
                      max_steps_per_epoch=args.max_steps_per_epoch)
        if args.experiment == "powersgd_imdb":
            kwargs.update(remat=args.remat)
    elif args.experiment == "gpt_generate":
        kwargs.update(preset=args.preset, max_new_tokens=args.max_new_tokens,
                      temperature=args.temperature)
    elif args.experiment == "serve_gpt":
        kwargs.update(preset=args.preset,
                      slots=args.slots if args.slots is not None else 4,
                      requests=args.requests
                      if args.requests is not None else 16,
                      request_rate=args.request_rate
                      if args.request_rate is not None else 64.0,
                      max_new_tokens=args.max_new_tokens,
                      checkpoint_dir=args.checkpoint_dir,
                      spool_dir=args.spool_dir,
                      engine=args.engine if args.engine is not None
                      else "slot",
                      block_len=args.block_len
                      if args.block_len is not None else 16,
                      n_blocks=args.n_blocks,
                      prefix_sharing=not args.no_prefix_sharing,
                      spec_k=args.spec_k if args.spec_k is not None else 0)
    elif args.experiment == "bandwidth_study":
        kwargs.update(preset=args.preset)
    elif args.experiment in ("gpt_lm", "gpt_pp", "gpt_sp", "gpt_tp", "gpt_moe"):
        kwargs.update(preset=args.preset, max_steps_per_epoch=args.max_steps_per_epoch)
        if args.experiment == "gpt_lm":
            kwargs.update(remat=args.remat, scan_layers=args.scan_layers)
        if args.experiment == "gpt_pp":
            kwargs.update(data_shards=args.data_shards, reducer=args.pp_reducer)
        if args.experiment == "gpt_tp":
            kwargs.update(model_shards=args.model_shards, reducer=args.tp_reducer,
                          vocab_parallel=args.vocab_parallel)
        if args.experiment == "gpt_moe":
            kwargs.update(experts_per_device=args.experts_per_device,
                          reducer=args.moe_reducer, top_k=args.moe_top_k)
        if args.experiment in ("gpt_pp", "gpt_sp"):
            kwargs.update(checkpoint_dir=args.checkpoint_dir)

    result = fn(**kwargs)
    if args.json:
        # driver-facing contract: RawEvent keeps the payload verbatim, so the
        # line is byte-identical to the historical print(json.dumps(...))
        Telemetry([StreamJsonSink(sys.stdout)]).emit(RawEvent(result))
    return result


if __name__ == "__main__":
    main()
