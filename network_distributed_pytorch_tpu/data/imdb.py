"""IMDb sentiment pipeline.

Reference behavior (``ddp_powersgd_distillBERT_IMDb/ddp_init.py:43-94``):
``read_imdb_split`` walks ``aclImdb/{train,test}/{pos,neg}/*.txt``
(``:56-65``), an 80/20 train/val split via sklearn ``train_test_split``
(``:72``), ``DistilBertTokenizerFast`` with ``truncation=True, padding=True``
(``:74-77``), and per-rank partitioning with per-worker batch 16 (``:85-94``).
The reference hard-codes a lab path ``/home/seonbinara/aclImdb`` (``:69-70``)
— a defect SURVEY §7 says not to replicate; here the path is a parameter.

TPU-first: tokenization pads to a FIXED ``max_len`` (static shapes; the
reference pads to the longest sequence in the dataset, which on TPU would
recompile per length). A deterministic hash tokenizer stands in when no HF
tokenizer cache is on disk (no egress); any HF-style callable can be passed
instead. Synthetic class-separable text keeps the pipeline runnable with no
dataset on disk.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np


def read_imdb_split(split_dir: str) -> Tuple[List[str], List[int]]:
    """Parity port of ``read_imdb_split`` (``ddp_init.py:56-65``): texts and
    0/1 labels from ``{split_dir}/{pos,neg}/*.txt`` (note: the reference
    compares with ``label_dir is "neg"`` — an identity-comparison bug SURVEY
    flags; here it's a correct equality test)."""
    split = Path(split_dir)
    texts: List[str] = []
    labels: List[int] = []
    for label_dir in ["pos", "neg"]:
        for text_file in sorted((split / label_dir).iterdir()):
            texts.append(text_file.read_text(encoding="utf-8"))
            labels.append(0 if label_dir == "neg" else 1)
    return texts, labels


def train_val_split(
    texts: Sequence[str], labels: Sequence[int], test_size: float = 0.2, seed: int = 714
) -> Tuple[List[str], List[str], List[int], List[int]]:
    """Deterministic shuffle-split (the reference's sklearn
    ``train_test_split(test_size=.2)``, ``ddp_init.py:72``)."""
    n = len(texts)
    idx = np.arange(n)
    np.random.RandomState(seed).shuffle(idx)
    n_val = int(n * test_size)
    val, train = idx[:n_val], idx[n_val:]
    return (
        [texts[i] for i in train],
        [texts[i] for i in val],
        [labels[i] for i in train],
        [labels[i] for i in val],
    )


class HashTokenizer:
    """Deterministic whitespace + hashing tokenizer with HF-style output
    (``input_ids``, ``attention_mask``), fixed-length padded/truncated.
    id 0 = [PAD], 1 = [CLS], 2 = [SEP]; words hash into [3, vocab)."""

    def __init__(self, vocab_size: int = 30522, max_len: int = 256):
        from ..native.loader import _check_max_len

        _check_max_len(max_len)  # [CLS] + [SEP] alone need 2 slots
        self.vocab_size = vocab_size
        self.max_len = max_len

    def _word_id(self, word: str) -> int:
        h = 2166136261
        for ch in word.encode("utf-8"):  # FNV-1a: stable across runs/hosts
            h = ((h ^ ch) * 16777619) & 0xFFFFFFFF
        return 3 + h % (self.vocab_size - 3)

    def __call__(self, texts: Sequence[str]) -> dict:
        # hot loop runs in the native data runtime when available (parity
        # asserted in tests/test_native_loader.py); Python loop otherwise
        from ..native.loader import tokenize_hash

        out = tokenize_hash(texts, self.vocab_size, self.max_len)
        if out is not None:
            return out
        return self.python_call(texts)

    def python_call(self, texts: Sequence[str]) -> dict:
        """The reference Python implementation (also the native-parity oracle)."""
        ids = np.zeros((len(texts), self.max_len), dtype=np.int32)
        mask = np.zeros((len(texts), self.max_len), dtype=np.int32)
        for row, text in enumerate(texts):
            words = text.lower().split()[: self.max_len - 2]
            toks = [1] + [self._word_id(w) for w in words] + [2]
            ids[row, : len(toks)] = toks
            mask[row, : len(toks)] = 1
        return {"input_ids": ids, "attention_mask": mask}


def synthetic_imdb(
    n: int = 2048,
    seed: int = 0,
    num_words: int = 40,
    class_word_rate: float = 0.4,
    label_noise: float = 0.0,
) -> Tuple[List[str], List[int]]:
    """Class-separable synthetic reviews: each class draws words from a
    distinct vocabulary region, so real models can learn sentiment from it.

    ``class_word_rate`` is the probability each word carries class signal
    (the rest come from a shared vocabulary); ``label_noise`` symmetrically
    flips that fraction of labels AFTER text generation — flipped reviews
    keep the original class's words, so no classifier can exceed
    ``1 - label_noise`` on a split carrying the same noise (the knob that
    makes accuracy studies falsifiable, round-3 verdict #3). Defaults
    reproduce the historical draws bit-for-bit."""
    rng = np.random.RandomState(seed)
    pos_vocab = [f"good{i}" for i in range(50)] + ["great", "excellent", "wonderful"]
    neg_vocab = [f"bad{i}" for i in range(50)] + ["awful", "terrible", "boring"]
    common = [f"word{i}" for i in range(100)]
    texts, labels = [], []
    for _ in range(n):
        label = int(rng.randint(0, 2))
        vocab = pos_vocab if label else neg_vocab
        words = [
            vocab[rng.randint(len(vocab))]
            if rng.rand() < class_word_rate
            else common[rng.randint(len(common))]
            for _ in range(num_words)
        ]
        texts.append(" ".join(words))
        labels.append(label)
    if label_noise > 0.0:
        flips = rng.rand(n) < label_noise
        labels = [1 - y if f else y for y, f in zip(labels, flips)]
    return texts, labels


def prepare_imdb(
    data_dir: Optional[str] = None,
    tokenizer: Optional[Callable] = None,
    max_len: int = 256,
    vocab_size: int = 30522,
    synthetic_n: int = 2048,
    seed: int = 714,
    synthetic_kwargs: Optional[dict] = None,
) -> Tuple[dict, dict, bool]:
    """The ``prepare_IMDb`` equivalent (``ddp_init.py:68-83``): returns
    (train, val, is_real) where each split is
    ``{'input_ids', 'attention_mask', 'labels'}`` as fixed-shape numpy arrays.

    Default tokenizer resolution when none is passed: a ``vocab.txt`` next to
    the dataset (``{data_dir}/vocab.txt``) selects the first-party
    :class:`~.wordpiece.WordPieceTokenizer` — drop the file
    ``distilbert-base-uncased`` ships and tokenization matches
    ``DistilBertTokenizerFast`` token-for-token with no HF runtime
    (``tests/test_wordpiece.py``); otherwise the deterministic
    :class:`HashTokenizer` stands in (no-files-on-disk fallback).
    ``synthetic_kwargs`` forwards to :func:`synthetic_imdb` (hardness knobs
    for the accuracy study; ignored when real data is on disk).
    """
    if data_dir is not None and os.path.isdir(os.path.join(data_dir, "train")):
        texts, labels = read_imdb_split(os.path.join(data_dir, "train"))
        is_real = True
    else:
        texts, labels = synthetic_imdb(
            synthetic_n, seed=seed, **(synthetic_kwargs or {})
        )
        is_real = False
    train_texts, val_texts, train_labels, val_labels = train_val_split(
        texts, labels, test_size=0.2, seed=seed
    )
    if tokenizer is None:
        vocab_file = (
            os.path.join(data_dir, "vocab.txt") if data_dir is not None else ""
        )
        if vocab_file and os.path.isfile(vocab_file):
            from .wordpiece import WordPieceTokenizer

            tokenizer = WordPieceTokenizer(vocab_file, max_len=max_len)
            # max id + 1, not len(): blank/duplicate vocab lines make ids
            # sparse (load_vocab assigns by line number)
            vocab_span = max(tokenizer.vocab.values()) + 1
            if vocab_span > vocab_size:
                # ids past the embedding table would be silently clamped by
                # nn.Embed's take under jit (garbage inputs, no error) —
                # fail loudly instead: the model must be built with the
                # on-disk vocab's size
                raise ValueError(
                    f"{vocab_file} spans token ids up to {vocab_span - 1} but "
                    f"the model vocab_size is {vocab_size}; pass vocab_size="
                    f"{vocab_span} (and size the model to match) or pass an "
                    "explicit tokenizer"
                )
        else:
            tokenizer = HashTokenizer(vocab_size=vocab_size, max_len=max_len)

    def encode(ts, ls):
        enc = tokenizer(ts)
        return {
            "input_ids": np.asarray(enc["input_ids"], dtype=np.int32),
            "attention_mask": np.asarray(enc["attention_mask"], dtype=np.int32),
            "labels": np.asarray(ls, dtype=np.int32),
        }

    return encode(train_texts, train_labels), encode(val_texts, val_labels), is_real
