"""Deterministic cross-rank dataset partitioning.

Semantic parity with the reference's ``partition_helper.py`` (canonical copy
``ddp_guide_cifar10/partition_helper.py:1-35``, byte-identical in two other
dirs): shuffle all indices with a **fixed local RNG (default seed 1234 — NOT
the global config seed)** so every rank computes the same permutation with
zero communication, cut into fractional chunks, and expose an index-remapped
view per rank.

This matters on TPU pods for the same reason it matters on the reference's
GbE cluster: each host shards the dataset locally and identically, so no
coordination traffic is spent on data placement.
"""

from __future__ import annotations

import random
from typing import List, Optional, Sequence


class Partition:
    """Index-remapped view of a dataset (reference ``partition_helper.py:4-15``)."""

    def __init__(self, data, index: Sequence[int]):
        self.data = data
        self.index = list(index)

    def __len__(self) -> int:
        return len(self.index)

    def __getitem__(self, i: int):
        return self.data[self.index[i]]


def split_indices(
    data_len: int, sizes: Sequence[float], seed: int = 1234
) -> List[List[int]]:
    """The partitioner's index math without a data object: shuffle
    ``range(data_len)`` with the fixed local RNG, cut into ``int(frac *
    data_len)``-truncated chunks. The permutation depends only on ``seed``
    and ``data_len`` — NOT on ``sizes`` — which is what makes elastic
    re-splits (below) coverage-preserving."""
    rng = random.Random()
    rng.seed(seed)
    indexes = list(range(data_len))
    rng.shuffle(indexes)
    partitions: List[List[int]] = []
    for frac in sizes:
        part_len = int(frac * data_len)
        partitions.append(indexes[:part_len])
        indexes = indexes[part_len:]
    return partitions


class DataPartitioner:
    """Shuffle-once, cut-into-fractions partitioner
    (reference ``partition_helper.py:18-35``, including the fixed default
    ``seed=1234`` and ``int(frac * len)`` truncation semantics)."""

    def __init__(self, data, sizes: Sequence[float] = (0.7, 0.2, 0.1), seed: int = 1234):
        self.data = data
        self.partitions = split_indices(len(data), sizes, seed=seed)

    def use(self, partition: int) -> Partition:
        return Partition(self.data, self.partitions[partition])


def partition_dataset(data, world_size: int, rank: int, seed: int = 1234) -> Partition:
    """The trainers' equal-split convenience: ``sizes=[1/W]*W`` then
    ``use(rank)`` (reference ``ddp_guide_cifar10/ddp_init.py:49-52``)."""
    sizes = [1.0 / world_size for _ in range(world_size)]
    return DataPartitioner(data, sizes, seed=seed).use(rank)


def elastic_assignments(
    data_len: int, world_size: int, seed: int = 1234
) -> List[List[int]]:
    """Per-rank index assignments for the equal split at ANY world size,
    all cut from the same seed-``seed`` permutation — the elastic-recovery
    re-split. When the supervisor shrinks W → W', the W' survivors call
    this with the new world and, with no reshuffle and no coordination,
    cover the same ``world_size * (data_len // world_size)`` permutation
    prefix disjointly (the whole dataset when ``world_size`` divides
    ``data_len``)."""
    return split_indices(
        data_len, [1.0 / world_size] * world_size, seed=seed
    )


def per_worker_batch_size(global_batch: int, world_size: int) -> int:
    """``bsz = int(global / float(world))`` (``ddp_guide_cifar10/ddp_init.py:49``)."""
    return int(global_batch / float(world_size))
