"""Deterministic cross-rank dataset partitioning.

Semantic parity with the reference's ``partition_helper.py`` (canonical copy
``ddp_guide_cifar10/partition_helper.py:1-35``, byte-identical in two other
dirs): shuffle all indices with a **fixed local RNG (default seed 1234 — NOT
the global config seed)** so every rank computes the same permutation with
zero communication, cut into fractional chunks, and expose an index-remapped
view per rank.

This matters on TPU pods for the same reason it matters on the reference's
GbE cluster: each host shards the dataset locally and identically, so no
coordination traffic is spent on data placement.
"""

from __future__ import annotations

import hashlib
import random
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


class Partition:
    """Index-remapped view of a dataset (reference ``partition_helper.py:4-15``)."""

    def __init__(self, data, index: Sequence[int]):
        self.data = data
        self.index = list(index)

    def __len__(self) -> int:
        return len(self.index)

    def __getitem__(self, i: int):
        return self.data[self.index[i]]


def split_indices(
    data_len: int, sizes: Sequence[float], seed: int = 1234
) -> List[List[int]]:
    """The partitioner's index math without a data object: shuffle
    ``range(data_len)`` with the fixed local RNG, cut into ``int(frac *
    data_len)``-truncated chunks. The permutation depends only on ``seed``
    and ``data_len`` — NOT on ``sizes`` — which is what makes elastic
    re-splits (below) coverage-preserving."""
    rng = random.Random()
    rng.seed(seed)
    indexes = list(range(data_len))
    rng.shuffle(indexes)
    partitions: List[List[int]] = []
    for frac in sizes:
        part_len = int(frac * data_len)
        partitions.append(indexes[:part_len])
        indexes = indexes[part_len:]
    return partitions


class DataPartitioner:
    """Shuffle-once, cut-into-fractions partitioner
    (reference ``partition_helper.py:18-35``, including the fixed default
    ``seed=1234`` and ``int(frac * len)`` truncation semantics)."""

    def __init__(self, data, sizes: Sequence[float] = (0.7, 0.2, 0.1), seed: int = 1234):
        self.data = data
        self.partitions = split_indices(len(data), sizes, seed=seed)

    def use(self, partition: int) -> Partition:
        return Partition(self.data, self.partitions[partition])


def partition_dataset(data, world_size: int, rank: int, seed: int = 1234) -> Partition:
    """The trainers' equal-split convenience: ``sizes=[1/W]*W`` then
    ``use(rank)`` (reference ``ddp_guide_cifar10/ddp_init.py:49-52``)."""
    sizes = [1.0 / world_size for _ in range(world_size)]
    return DataPartitioner(data, sizes, seed=seed).use(rank)


def elastic_assignments(
    data_len: int, world_size: int, seed: int = 1234
) -> List[List[int]]:
    """Per-rank index assignments for the equal split at ANY world size,
    all cut from the same seed-``seed`` permutation — the elastic-recovery
    re-split. When the supervisor shrinks W → W', the W' survivors call
    this with the new world and, with no reshuffle and no coordination,
    cover the same ``world_size * (data_len // world_size)`` permutation
    prefix disjointly (the whole dataset when ``world_size`` divides
    ``data_len``)."""
    return split_indices(
        data_len, [1.0 / world_size] * world_size, seed=seed
    )


def per_worker_batch_size(global_batch: int, world_size: int) -> int:
    """``bsz = int(global / float(world))`` (``ddp_guide_cifar10/ddp_init.py:49``)."""
    return int(global_batch / float(world_size))


# ---------------------------------------------------------------------------
# The STREAMED elastic index (PR 12).
#
# ``elastic_assignments`` materializes the full Fisher-Yates permutation —
# O(data_len) memory per rank per call, fine for CIFAR, absurd for a
# billion-sample corpus. The stream form below replaces the materialized
# list with an O(1)-memory *cursor-addressable* bijection: any window of the
# shuffled index sequence is computed on demand, so a rank can resume
# mid-shard from a checkpointed cursor without replaying (or storing) the
# prefix.
#
# Guarantee class (see DESIGN.md): the streamed order is deterministic in
# (seed, data_len, epoch) and identical at every world size — but it is NOT
# bitwise-equal to the seed-1234 ``random.Random`` shuffle that
# ``split_indices`` materializes (a lazily-invertible permutation cannot be
# produced by Fisher-Yates without materializing it). Streamed runs are in
# the merge-tolerance class: sample *sets* per epoch are identical, visit
# order differs from the materialized path.
# ---------------------------------------------------------------------------

_SPLITMIX_C1 = np.uint64(0xBF58476D1CE4E5B9)
_SPLITMIX_C2 = np.uint64(0x94D049BB133111EB)


class StreamedPermutation:
    """A keyed bijection on ``[0, data_len)`` with O(1) random access.

    4-round Feistel network over the smallest even-bit-width domain
    covering ``data_len``, cycle-walked back into range (Black & Rogaway's
    format-preserving trick: out-of-range outputs are re-permuted until
    they land in range — a bijection composed with itself restricted to a
    subset is still a bijection on that subset). The domain is at most
    ``4 * data_len`` so the walk takes < 4 expected rounds; round keys are
    derived from (seed, data_len) via SHA-256 so the order is stable
    across platforms and process incarnations.

    Both directions are exposed: :meth:`apply` (position -> dataset index)
    drives the loader, :meth:`invert` (index -> position) is what lets the
    zero-drop property test verify bijectivity over a billion-element
    domain without materializing it.
    """

    ROUNDS = 4  # 4-round Feistel: PRP-strength keyed mixing (Luby-Rackoff)

    def __init__(self, data_len: int, seed: int = 1234):
        if data_len <= 0:
            raise ValueError(f"data_len must be positive, got {data_len}")
        self.data_len = int(data_len)
        self.seed = int(seed)
        bits = max((self.data_len - 1).bit_length(), 2)
        if bits % 2:
            bits += 1
        self.bits = bits
        self._hb = np.uint64(bits // 2)
        self._mask = np.uint64((1 << (bits // 2)) - 1)
        self.domain = 1 << bits
        digest = hashlib.sha256(
            f"ndp-stream-perm:{self.seed}:{self.data_len}".encode()
        ).digest()
        self._keys: Tuple[np.uint64, ...] = tuple(
            np.uint64(int.from_bytes(digest[8 * r: 8 * r + 8], "little"))
            for r in range(self.ROUNDS)
        )

    @staticmethod
    def _mix(v: np.ndarray) -> np.ndarray:
        # splitmix64 finalizer; uint64 arithmetic wraps mod 2^64 by design
        v = (v ^ (v >> np.uint64(30))) * _SPLITMIX_C1
        v = (v ^ (v >> np.uint64(27))) * _SPLITMIX_C2
        return v ^ (v >> np.uint64(31))

    def _permute(self, v: np.ndarray) -> np.ndarray:
        left, right = v >> self._hb, v & self._mask
        for key in self._keys:
            f = self._mix(right ^ key) & self._mask
            left, right = right, left ^ f
        return (left << self._hb) | right

    def _unpermute(self, v: np.ndarray) -> np.ndarray:
        left, right = v >> self._hb, v & self._mask
        for key in reversed(self._keys):
            f = self._mix(left ^ key) & self._mask
            left, right = right ^ f, left
        return (left << self._hb) | right

    def _walk(self, v: np.ndarray, step) -> np.ndarray:
        n = np.uint64(self.data_len)
        out = step(v)
        bad = out >= n
        while bad.any():
            out[bad] = step(out[bad])
            bad = out >= n
        return out

    def apply(self, offsets: np.ndarray) -> np.ndarray:
        """Dataset indices for epoch offsets (each in ``[0, data_len)``)."""
        offsets = np.asarray(offsets)
        if offsets.size and (
            offsets.min() < 0 or int(offsets.max()) >= self.data_len
        ):
            raise ValueError("offset out of range")
        with np.errstate(over="ignore"):
            return self._walk(
                offsets.astype(np.uint64), self._permute
            ).astype(np.int64)

    def invert(self, indices: np.ndarray) -> np.ndarray:
        """Epoch offsets that :meth:`apply` maps to ``indices``."""
        indices = np.asarray(indices)
        if indices.size and (
            indices.min() < 0 or int(indices.max()) >= self.data_len
        ):
            raise ValueError("index out of range")
        with np.errstate(over="ignore"):
            return self._walk(
                indices.astype(np.uint64), self._unpermute
            ).astype(np.int64)

    def window(self, start: int, stop: int) -> np.ndarray:
        """``apply`` over the contiguous offset range ``[start, stop)``."""
        return self.apply(np.arange(start, stop, dtype=np.int64))


class ElasticIndexStream:
    """The cursor-addressable stream form of :func:`elastic_assignments`.

    One global, world-size-independent stream of dataset indices: position
    ``p`` of the stream maps to epoch ``p // data_len`` shuffled with a
    per-epoch :class:`StreamedPermutation` (re-keyed with ``seed + epoch``,
    mirroring ``data.loader.epoch_order``'s reshuffle convention). A world
    of size W owns the stream by residue — position ``p`` belongs to rank
    ``p % W`` — and the only mutable coordinate is the single global
    ``cursor`` (= number of stream positions consumed by committed steps).

    That residue ownership is the whole zero-drop/zero-dup argument: for
    any cursor c and any window [c, c+G), the union of the W per-rank
    position sets is EXACTLY [c, c+G), disjointly — for every W. So a
    reshape W -> W' mid-shard needs no migration protocol at all: the
    survivors re-derive ownership from (cursor, W') and the stream
    continues with the exact sample multiset an uninterrupted run would
    have consumed (proven in ``tests/test_stream_index.py``). The cursor
    is checkpointed next to ``_TOPOLOGY.json`` as ``_LOADER_STATE.json``
    (:func:`utils.checkpoint.save_checkpoint`'s ``loader_state`` tag).
    """

    STATE_SCHEMA = 1
    STATE_KIND = "elastic_index_stream"

    def __init__(self, data_len: int, seed: int = 1234):
        if data_len <= 0:
            raise ValueError(f"data_len must be positive, got {data_len}")
        self.data_len = int(data_len)
        self.seed = int(seed)
        self._perms: Dict[int, StreamedPermutation] = {}

    def _perm(self, epoch: int) -> StreamedPermutation:
        perm = self._perms.get(epoch)
        if perm is None:
            if len(self._perms) > 8:  # a stream only ever straddles 2
                self._perms.clear()
            perm = self._perms[epoch] = StreamedPermutation(
                self.data_len, seed=self.seed + epoch
            )
        return perm

    def indices_at(self, positions: np.ndarray) -> np.ndarray:
        """Dataset indices at absolute stream positions (epoch-wrapping)."""
        positions = np.asarray(positions, dtype=np.int64)
        if positions.size and positions.min() < 0:
            raise ValueError("stream positions are non-negative")
        epochs = positions // self.data_len
        offsets = positions % self.data_len
        out = np.empty(positions.shape, dtype=np.int64)
        for e in np.unique(epochs):
            m = epochs == e
            out[m] = self._perm(int(e)).apply(offsets[m])
        return out

    def shard_positions(
        self, cursor: int, world_size: int, rank: int, count: int
    ) -> np.ndarray:
        """The next ``count`` stream positions rank ``rank`` owns at or
        after ``cursor`` in a world of ``world_size`` (``p % W == rank``)."""
        if not 0 <= rank < world_size:
            raise ValueError(f"rank {rank} outside world {world_size}")
        if cursor < 0:
            raise ValueError("cursor is non-negative")
        first = cursor + ((rank - cursor) % world_size)
        return first + world_size * np.arange(count, dtype=np.int64)

    def shard_indices(
        self, cursor: int, world_size: int, rank: int, count: int
    ) -> np.ndarray:
        """Dataset indices for :meth:`shard_positions` — the per-rank read."""
        return self.indices_at(
            self.shard_positions(cursor, world_size, rank, count)
        )

    # ---- checkpointable loader state ------------------------------------

    def state(self, cursor: int) -> Dict[str, Any]:
        """The ``_LOADER_STATE.json`` payload: everything a restarted (or
        resharded) world needs to resume this stream mid-shard."""
        return {
            "schema": self.STATE_SCHEMA,
            "kind": self.STATE_KIND,
            "data_len": self.data_len,
            "seed": self.seed,
            "cursor": int(cursor),
        }

    @classmethod
    def from_state(cls, state: Dict[str, Any]) -> Tuple["ElasticIndexStream", int]:
        """Rebuild (stream, cursor) from a :meth:`state` payload."""
        if state.get("kind") != cls.STATE_KIND:
            raise ValueError(f"not an index-stream state: {state.get('kind')!r}")
        if int(state.get("schema", 0)) > cls.STATE_SCHEMA:
            raise ValueError(f"loader state schema {state['schema']} too new")
        stream = cls(int(state["data_len"]), seed=int(state["seed"]))
        return stream, int(state["cursor"])


def streamed_elastic_assignments(
    data_len: int,
    world_size: int,
    seed: int = 1234,
    cursor: int = 0,
    count: Optional[int] = None,
) -> List[np.ndarray]:
    """``elastic_assignments``'s signature, stream semantics: the next
    ``count`` dataset indices per rank starting at global stream
    ``cursor`` (default: one epoch-equal share each, the materialized
    split's shape). Unlike the materialized form this is O(count) in both
    memory and time regardless of ``data_len``, and is resumable at any
    cursor — including one recorded under a *different* world size."""
    stream = ElasticIndexStream(data_len, seed=seed)
    if count is None:
        count = data_len // world_size
    return [
        stream.shard_indices(cursor, world_size, rank, count)
        for rank in range(world_size)
    ]
