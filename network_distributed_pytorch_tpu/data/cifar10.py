"""CIFAR-10 pipeline.

The reference loads CIFAR-10 via
``torchvision.datasets.CIFAR10("./data", train=True, download=True,
transform=[ToTensor, Normalize((.5,.5,.5),(.5,.5,.5))])``
(``ddp_guide_cifar10/ddp_init.py:42-47``). This module reads BOTH on-disk
forms directly — the ``cifar-10-batches-py`` pickle batches torchvision
downloads (Python) and the ``cifar-10-batches-bin`` binary records (the
native C++ decoder) — no torch in the loop — applies the same
normalization, and emits **NHWC** float32 (TPU-native layout; the
reference's NCHW is a GPU-ism).

When the dataset is not on disk (this build environment has no egress), a
deterministic synthetic stand-in with identical shapes/dtypes/semantics keeps
every pipeline and test runnable; real data is a drop-in swap.
"""

from __future__ import annotations

import os
import pickle
from typing import Optional, Tuple

import numpy as np

_MEAN = 0.5  # transforms.Normalize((0.5,0.5,0.5),(0.5,0.5,0.5)) — ddp_init.py:44
_STD = 0.5


def _normalize(images_u8: np.ndarray) -> np.ndarray:
    return ((images_u8.astype(np.float32) / 255.0) - _MEAN) / _STD


def cifar10_on_disk(
    data_dir: str = "./data", train: Optional[bool] = None
) -> Optional[str]:
    """Path of a USABLE extracted CIFAR-10 directory: the torchvision pickle
    form (``cifar-10-batches-py``) or the binary form
    (``cifar-10-batches-bin``, decoded by the native runtime).

    ``train`` selects which split must actually be present (None = either):
    a stale/partial directory — an interrupted download, an eval-only drop —
    must not shadow a directory in the OTHER format that has the split the
    caller needs. The train probe requires ALL FIVE data_batch files —
    ``load_cifar10`` reads batches 1-5, so a directory holding only batch 1
    (interrupted extraction) would pass a single-file probe and then crash
    in ``open()`` instead of falling through to the other format."""
    for name, suffix in (
        ("cifar-10-batches-py", ""),
        ("cifar-10-batches-bin", ".bin"),
    ):
        p = os.path.join(data_dir, name)
        train_files = [f"data_batch_{i}{suffix}" for i in range(1, 6)]
        test_files = [f"test_batch{suffix}"]
        candidates = (
            [train_files, test_files]
            if train is None
            else [train_files if train else test_files]
        )
        if any(
            all(os.path.isfile(os.path.join(p, f)) for f in files)
            for files in candidates
        ):
            return p
    return None


def _load_pickle_batches(base: str, names) -> Tuple[np.ndarray, np.ndarray]:
    xs, ys = [], []
    for name in names:
        with open(os.path.join(base, name), "rb") as f:
            entry = pickle.load(f, encoding="latin1")
        xs.append(np.asarray(entry["data"], dtype=np.uint8))
        ys.append(np.asarray(entry["labels"], dtype=np.int32))
    data = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)  # NCHW→NHWC
    return _normalize(data), np.concatenate(ys)


def _load_bin_batches(base: str, names) -> Tuple[np.ndarray, np.ndarray]:
    # cifar-10-batches-bin record = [label u8][3072 CHW bytes]; decoded
    # (and normalized, identically to _normalize) by the multithreaded C++
    # runtime, numpy fallback inside. One preallocated output; each file
    # decodes IN PLACE into its slice (outer-dim slices of a C-contiguous
    # array are contiguous) — no concatenate copy, no per-file f32 temp.
    from ..native import decode_cifar10_bin

    raws = []
    for name in names:
        raw = np.fromfile(os.path.join(base, name), dtype=np.uint8)
        if raw.size == 0 or raw.size % 3073 != 0:
            raise ValueError(
                f"{name}: {raw.size} bytes is not a positive whole number "
                "of 3073-byte CIFAR-10 records"
            )
        raws.append(raw.reshape(-1, 3073))
    total = sum(r.shape[0] for r in raws)
    images = np.empty((total, 32, 32, 3), np.float32)
    labels = np.empty((total,), np.int32)
    at = 0
    for raw in raws:
        n = raw.shape[0]
        decode_cifar10_bin(
            raw, mean=_MEAN, std=_STD,
            out_images=images[at : at + n], out_labels=labels[at : at + n],
        )
        at += n
    return images, labels


def load_cifar10(
    data_dir: str = "./data", train: bool = True
) -> Tuple[np.ndarray, np.ndarray]:
    """(images NHWC float32 normalized, labels int32). Raises if absent —
    use ``load_cifar10_or_synthetic`` for the gated fallback. Reads either
    on-disk form (pickle via Python, binary via the native decoder); both
    yield identical arrays (``tests/test_data.py``)."""
    base = cifar10_on_disk(data_dir, train=train)
    if base is None:
        raise FileNotFoundError(
            f"CIFAR-10 not found under {data_dir!r} (expected cifar-10-batches-py/ "
            "or cifar-10-batches-bin/; the reference downloads the former via "
            "torchvision, ddp_guide_cifar10/ddp_init.py:45)"
        )
    if base.endswith("-bin"):
        names = (
            [f"data_batch_{i}.bin" for i in range(1, 6)]
            if train
            else ["test_batch.bin"]
        )
        return _load_bin_batches(base, names)
    names = [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
    return _load_pickle_batches(base, names)


def synthetic_cifar10(
    n: int = 50000,
    seed: int = 0,
    num_classes: int = 10,
    class_sep: float = 0.5,
    noise: float = 0.25,
    label_noise: float = 0.0,
    return_means: bool = False,
):
    """Deterministic CIFAR-shaped class-blob data (32×32×3, normalized range),
    learnable by the real models — the test/no-egress stand-in.

    ``class_sep`` scales the class means against ``noise``'s per-pixel std:
    the defaults are near-perfectly separable (smoke tests need fast
    convergence), while e.g. ``class_sep=0.012`` puts the nearest-mean
    (Bayes-optimal) accuracy near 0.85 — a task accuracy studies can FAIL
    (round-3 verdict #3: both arms saturating at 1.0 proves nothing).
    ``label_noise`` symmetrically resamples that fraction of labels AFTER
    the images are drawn (the pixels keep the original class's blob).
    ``return_means=True`` appends the TRUE class means to the return (the
    Bayes-oracle inputs — an accuracy study must score its ceiling against
    the generator's means, never means re-fit on the scored points, where
    the self-term makes any task look solvable). Defaults reproduce the
    historical draws bit-for-bit."""
    rng = np.random.RandomState(seed)
    means = rng.randn(num_classes, 32, 32, 3).astype(np.float32) * class_sep
    labels = rng.randint(0, num_classes, size=n).astype(np.int32)
    images = means[labels] + noise * rng.randn(n, 32, 32, 3).astype(np.float32)
    if label_noise > 0.0:
        flip = rng.rand(n) < label_noise
        labels = np.where(
            flip, rng.randint(0, num_classes, size=n).astype(np.int32), labels
        )
    images = np.clip(images, -1.0, 1.0)
    if return_means:
        return images, labels, means
    return images, labels


def load_cifar10_or_synthetic(
    data_dir: str = "./data", train: bool = True, synthetic_n: int = 4096, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray, bool]:
    """(images, labels, is_real). Real data when on disk, synthetic otherwise."""
    try:
        x, y = load_cifar10(data_dir, train)
        return x, y, True
    except FileNotFoundError:
        x, y = synthetic_cifar10(synthetic_n, seed=seed)
        return x, y, False
