"""Batch iteration over in-memory numpy datasets.

Replaces the reference's ``DataLoader(partition, batch_size=bsz,
shuffle=True)`` (``ddp_guide_cifar10/ddp_init.py:52-54``). TPU-first
differences:

- batches are **static-shape**: the trailing partial batch is dropped by
  default (a torch DataLoader yields it; a ragged last batch would force an
  XLA recompile every epoch — the classic TPU anti-pattern).
- shuffling is seeded and epoch-keyed, so every run (and every host in a
  multi-host setup feeding the same partition logic) is reproducible.
"""

from __future__ import annotations

from typing import Iterator, Sequence, Tuple

import numpy as np

from ..observe.spans import span


def epoch_order(
    n: int,
    batch_size: int,
    seed: int = 0,
    epoch: int = 0,
    shuffle: bool = True,
    drop_last: bool = True,
) -> np.ndarray:
    """The epoch's example order: seeded epoch-keyed shuffle, truncated to
    whole batches when ``drop_last``. The single source of the framework's
    batch-order semantics — both the Python iterator below and the native
    (C++) prefetch loader consume it."""
    idx = np.arange(n)
    if shuffle:
        np.random.RandomState(seed + epoch).shuffle(idx)
    end = (n // batch_size) * batch_size if drop_last else n
    return idx[:end]


def iterate_batches(
    arrays: Sequence[np.ndarray],
    batch_size: int,
    seed: int = 0,
    epoch: int = 0,
    shuffle: bool = True,
    drop_last: bool = True,
) -> Iterator[Tuple[np.ndarray, ...]]:
    """Yield aligned minibatch tuples from equal-length arrays."""
    n = len(arrays[0])
    for a in arrays:
        assert len(a) == n, "batch arrays must be aligned"
    idx = epoch_order(n, batch_size, seed, epoch, shuffle, drop_last)
    for start in range(0, len(idx), batch_size):
        sel = idx[start : start + batch_size]
        # ambient span: gather cost of assembling one batch on the host
        # (runs inside the consumer's next(), so it nests under the
        # training loop's data_load span)
        with span("data_load/assemble"):
            batch = tuple(a[sel] for a in arrays)
        yield batch


def steps_per_epoch(n: int, batch_size: int, drop_last: bool = True) -> int:
    return n // batch_size if drop_last else -(-n // batch_size)


def device_prefetch(batches, sharding=None, depth: int = 2):
    """Asynchronously stage up to ``depth`` upcoming batches on device.

    ``jax.device_put`` dispatches the host→device copy without blocking, so
    staging batch N+1 (and N+2) while the jitted step runs batch N overlaps
    the transfer with compute — the input-pipeline overlap torch DataLoader
    gets from pinned-memory prefetch, done the JAX way. ``sharding`` should
    be the step's batch sharding (e.g. ``mesh_lib.data_sharding(mesh)``) so
    the copy lands directly in the right layout; None = default device
    (single-process path).
    """
    from collections import deque

    import jax

    def stage(batch):
        # dispatch only — the copy itself overlaps compute; a long span
        # here means device_put is blocking (e.g. committed-layout reshard)
        with span("data_load/stage"):
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(a, sharding), batch
            )

    queue = deque()
    for batch in batches:
        queue.append(stage(batch))
        if len(queue) > depth:
            yield queue.popleft()
    while queue:
        yield queue.popleft()
