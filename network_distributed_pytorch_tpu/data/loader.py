"""Batch iteration over in-memory numpy datasets.

Replaces the reference's ``DataLoader(partition, batch_size=bsz,
shuffle=True)`` (``ddp_guide_cifar10/ddp_init.py:52-54``). TPU-first
differences:

- batches are **static-shape**: the trailing partial batch is dropped by
  default (a torch DataLoader yields it; a ragged last batch would force an
  XLA recompile every epoch — the classic TPU anti-pattern).
- shuffling is seeded and epoch-keyed, so every run (and every host in a
  multi-host setup feeding the same partition logic) is reproducible.
"""

from __future__ import annotations

from typing import Iterator, Sequence, Tuple

import numpy as np

from ..observe.spans import span


def epoch_order(
    n: int,
    batch_size: int,
    seed: int = 0,
    epoch: int = 0,
    shuffle: bool = True,
    drop_last: bool = True,
) -> np.ndarray:
    """The epoch's example order: seeded epoch-keyed shuffle, truncated to
    whole batches when ``drop_last``. The single source of the framework's
    batch-order semantics — both the Python iterator below and the native
    (C++) prefetch loader consume it."""
    idx = np.arange(n)
    if shuffle:
        np.random.RandomState(seed + epoch).shuffle(idx)
    end = (n // batch_size) * batch_size if drop_last else n
    return idx[:end]


def iterate_batches(
    arrays: Sequence[np.ndarray],
    batch_size: int,
    seed: int = 0,
    epoch: int = 0,
    shuffle: bool = True,
    drop_last: bool = True,
) -> Iterator[Tuple[np.ndarray, ...]]:
    """Yield aligned minibatch tuples from equal-length arrays."""
    n = len(arrays[0])
    for a in arrays:
        assert len(a) == n, "batch arrays must be aligned"
    idx = epoch_order(n, batch_size, seed, epoch, shuffle, drop_last)
    for start in range(0, len(idx), batch_size):
        sel = idx[start : start + batch_size]
        # ambient span: gather cost of assembling one batch on the host
        # (runs inside the consumer's next(), so it nests under the
        # training loop's data_load span)
        with span("data_load/assemble"):
            batch = tuple(a[sel] for a in arrays)
        yield batch


def steps_per_epoch(n: int, batch_size: int, drop_last: bool = True) -> int:
    return n // batch_size if drop_last else -(-n // batch_size)


def _slots_match(host, leaves) -> bool:
    if len(host) != len(leaves):
        return False
    for buf, a in zip(host, leaves):
        if isinstance(a, np.ndarray) != isinstance(buf, np.ndarray):
            return False
        if isinstance(a, np.ndarray) and (
            buf.shape != a.shape or buf.dtype != a.dtype
        ):
            return False
    return True


def device_prefetch(batches, sharding=None, depth: int = 2, label: str = "train"):
    """Double-buffered host→device staging, up to ``depth`` batches ahead.

    Each incoming batch is copied into one of ``depth + 1`` PREALLOCATED
    host staging buffers (``np.copyto`` into stable, page-warm allocations
    — the host-runtime analogue of pinned staging memory: no per-batch
    malloc, no allocator churn under the transfer engine), then
    ``jax.device_put`` dispatches the host→device copy without blocking, so
    staging batch N+1 (and N+2) while the jitted step runs batch N overlaps
    the transfer with compute — the input-pipeline overlap torch DataLoader
    gets from pinned-memory prefetch, done the JAX way. A staging slot is
    only rewritten after ``jax.block_until_ready`` on the device array it
    last fed, so an in-flight transfer can never read a torn buffer.

    ``depth`` is overridable per run via the ``NDP_PREFETCH_DEPTH`` env var
    (0 = stage-and-yield, no lookahead). ``sharding`` should be the step's
    batch sharding (e.g. ``mesh_lib.data_sharding(mesh)``) so the copy
    lands directly in the right layout; None = default device
    (single-process path).

    On exhaustion emits one :class:`observe.events.LoaderEvent` through the
    ambient recorder — batch/sample counts, end-to-end samples/s, and the
    time spent *blocked on the upstream producer* (``wait_s``: the number
    that says whether decode/assemble, not staging, is the bottleneck).
    """
    import os
    import time
    from collections import deque

    import jax

    from ..observe.events import LoaderEvent
    from ..observe.spans import ambient

    env_depth = os.environ.get("NDP_PREFETCH_DEPTH")
    if env_depth:
        try:
            depth = int(env_depth)
        except ValueError:
            pass
    depth = max(int(depth), 0)
    n_slots = depth + 1
    slots = [None] * n_slots  # each live slot: [host_leaves, device_batch]

    def stage(batch, slot_i):
        # dispatch only — the copy itself overlaps compute; a long span
        # here means device_put is blocking (e.g. committed-layout reshard)
        with span("data_load/stage"):
            leaves, treedef = jax.tree_util.tree_flatten(batch)
            slot = slots[slot_i]
            if slot is not None:
                # the ring guarantee: the slot's previous transfer must have
                # landed before its host buffers are rewritten (a no-op wait
                # depth+1 batches later — the step consumed it long ago)
                jax.block_until_ready(slot[1])
            if slot is None or not _slots_match(slot[0], leaves):
                host = [
                    np.array(a, copy=True) if isinstance(a, np.ndarray) else a
                    for a in leaves
                ]
            else:
                host = slot[0]
                for j, a in enumerate(leaves):
                    if isinstance(host[j], np.ndarray):
                        np.copyto(host[j], a)
                    else:
                        host[j] = a
            device = jax.tree_util.tree_unflatten(
                treedef, [jax.device_put(b, sharding) for b in host]
            )
            slots[slot_i] = [host, device]
            return device, leaves

    queue = deque()
    it = iter(batches)
    slot_i = 0
    n_batches = 0
    n_samples = 0
    wait_s = 0.0
    t_start = time.monotonic()
    while True:
        t0 = time.monotonic()
        try:
            batch = next(it)
        except StopIteration:
            break
        wait_s += time.monotonic() - t0
        device, leaves = stage(batch, slot_i)
        slot_i = (slot_i + 1) % n_slots
        queue.append(device)
        n_batches += 1
        for a in leaves:
            if isinstance(a, np.ndarray):
                n_samples += len(a)
                break
        if len(queue) > depth:
            yield queue.popleft()
    while queue:
        yield queue.popleft()
    recorder = ambient()
    if recorder is not None and n_batches:
        elapsed = max(time.monotonic() - t_start, 1e-9)
        recorder.emit(
            LoaderEvent(
                label=label,
                batches=n_batches,
                samples=n_samples,
                samples_per_s=n_samples / elapsed,
                prefetch_depth=depth,
                wait_s=wait_s,
            )
        )
